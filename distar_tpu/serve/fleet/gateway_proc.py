"""Standalone mock gateway: ``python -m distar_tpu.serve.fleet.gateway_proc``.

The jax-free twin of ``bin/serve.py --mock`` (no model, no learner imports,
no health stack) — what the fleet capacity harness, the serve chaos drill
and the discovery tests spawn per gateway, so fleet members are real OS
processes (own GIL, real sockets) that start in well under a second.
Follows the ``replay.server`` fleet-process idiom: prints one parseable
``SERVE-GATEWAY <host> <tcp_port> <http_port>`` line once serving, then
runs until SIGTERM/SIGINT or stdin EOF (a dying parent reaps the fleet).

``--players MP0,MP1`` serves several mock models behind the one address
(``GatewayMux``); ``--coordinator host:port`` registers the data-plane
endpoint under ``serve_gateway`` with lease/heartbeat so routers and
opsctl discover it.
"""
from __future__ import annotations

import argparse
import signal
import sys
import threading
import time


def main(argv=None) -> int:
    from ..engine import MockModelEngine
    from ..gateway import InferenceGateway
    from ..http_frontend import ServeHTTPServer
    from ..mux import GatewayMux
    from ..tcp_frontend import ServeTCPServer
    from .discovery import register_gateway

    p = argparse.ArgumentParser(description="standalone mock serve gateway")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0, help="TCP data plane")
    p.add_argument("--http-port", type=int, default=0)
    p.add_argument("--slots", type=int, default=32)
    p.add_argument("--players", default="",
                   help="comma list -> multiplexed gateway (default: one "
                        "anonymous player)")
    p.add_argument("--version", default="v1", help="boot model version name")
    p.add_argument("--mock-delay-s", type=float, default=0.0)
    p.add_argument("--max-delay-ms", type=float, default=5.0)
    p.add_argument("--queue-capacity", type=int, default=1024)
    p.add_argument("--idle-ttl-s", type=float, default=300.0)
    p.add_argument("--coordinator", default="",
                   help="coordinator host:port to register under serve_gateway")
    p.add_argument("--lease-s", type=float, default=10.0)
    p.add_argument("--telemetry-interval-s", type=float, default=2.0,
                   help="cadence of registry-snapshot + tail-sampled-trace "
                        "shipping to the coordinator (requires "
                        "--coordinator; 0 disables)")
    p.add_argument("--no-trace", action="store_true",
                   help="disable request-span minting (the overhead A/B / "
                        "byte-identical-wire posture)")
    p.add_argument("--trace-keep-one-in", type=int, default=0,
                   help="override the tail sampler's random 1-in-N keep "
                        "rate (1 = retain every span — the drill/debug "
                        "posture; 0 = stock default)")
    p.add_argument("--drain-timeout-s", type=float, default=30.0,
                   help="graceful-retirement budget: after POST /drain, exit "
                        "once every resident session migrated off, or when "
                        "this many seconds passed — whichever comes first")
    p.add_argument("--transport", default="auto", choices=("auto", "shm", "tcp"),
                   help="TCP-frontend transport policy (auto/shm negotiate "
                        "shared-memory rings with colocated clients)")
    args = p.parse_args(argv)

    if args.no_trace:
        from ...obs import set_tracing

        set_tracing(False)
    if args.trace_keep_one_in > 0:
        from ...obs import TraceBuffer, set_trace_buffer

        set_trace_buffer(TraceBuffer(random_one_in=args.trace_keep_one_in))

    players = [s.strip() for s in args.players.split(",") if s.strip()]

    def build_gateway(player: str) -> InferenceGateway:
        params = {"version": args.version, "bias": 0.0, "player": player}
        gw = InferenceGateway(
            MockModelEngine(args.slots, params=params, delay_s=args.mock_delay_s),
            max_batch=args.slots,
            max_delay_s=args.max_delay_ms / 1000.0,
            queue_capacity=args.queue_capacity,
            idle_ttl_s=args.idle_ttl_s,
        )
        gw.load_version(args.version, params=params, activate=True)
        return gw

    if players:
        target = GatewayMux({pl: build_gateway(pl) for pl in players}).start()
    else:
        target = build_gateway("").start()

    tcp = ServeTCPServer(target, host=args.host, port=args.port,
                         transport=args.transport).start()
    http = ServeHTTPServer(target, host=args.host, port=args.http_port).start()

    beat = None
    shipper = None
    if args.coordinator:
        from ...comm.discovery import unregister_endpoint

        chost, _, cport = args.coordinator.rpartition(":")
        coord = (chost or "127.0.0.1", int(cport))
        beat = register_gateway(
            coord, tcp.host, tcp.port,
            meta={"players": players, "slots": args.slots,
                  "http_port": http.port, "version": args.version,
                  "mock": True},
            lease_s=args.lease_s,
        )

        def _deregister(beat=beat, coord=coord, host=tcp.host, port=tcp.port):
            beat.stop_event.set()
            unregister_endpoint(coord, host, port)

        # drain's step 1: leave discovery NOW, not a lease TTL later
        target.deregister = _deregister

        if args.telemetry_interval_s > 0:
            # telemetry + tail-sampled trace records + exemplars ship to the
            # broker: this gateway's server spans join client spans in the
            # coordinator trace store (GET /traces, opsctl trace)
            from ...obs import TelemetryShipper

            shipper = TelemetryShipper(
                source=f"gateway:{tcp.port}", coordinator_addr=coord,
                interval_s=args.telemetry_interval_s,
                endpoint=f"{tcp.host}:{tcp.port}",
            ).start()

    # CLI entrypoint output: the parseable serving line callers wait for
    print(f"SERVE-GATEWAY {tcp.host} {tcp.port} {http.port}",  # lint: allow-print
          flush=True)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    drain_deadline = [None]
    try:
        import select

        while not stop.is_set():
            ready, _, _ = select.select([sys.stdin], [], [], 0.5)
            if ready and not sys.stdin.buffer.read(1):
                break
            # graceful-retirement exit: once a POST /drain (or TCP drain op)
            # flipped us to draining, run until every resident session has
            # migrated off (the router ends them here as it re-pins), then
            # leave — bounded by --drain-timeout-s so a client that never
            # migrates can't pin a retiring process forever
            if getattr(target, "draining", False):
                if drain_deadline[0] is None:
                    drain_deadline[0] = time.monotonic() + args.drain_timeout_s
                if (target.resident_sessions() == 0
                        or time.monotonic() > drain_deadline[0]):
                    break
    except (OSError, ValueError, KeyboardInterrupt):
        pass
    if shipper is not None:
        shipper.stop()
    if beat is not None:
        beat.stop_event.set()
    tcp.stop()
    http.stop()
    target.drain_and_stop(5.0)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
