"""Fleet-wide model rollout: atomic hot-swap + canary-percent rollout.

PR 2's hot-swap contract holds per gateway (activate at a flush boundary,
zero in-flight loss). This module lifts it fleet-wide, coordinator-driven:

  * ``FleetRollout.rollout(version, ...)`` — ALL-OR-NOTHING generation
    bump across every gateway. Phase 1 loads + warms the version on every
    gateway and collects acks; any NACK aborts with nothing activated
    anywhere (a loaded-but-inactive version is inert). Phase 2 activates
    gateway by gateway; a NACK mid-phase rolls every already-swapped
    gateway back to the version it was serving — the fleet never settles
    split-brained. Outcomes are counted in
    ``distar_fleet_rollouts_total{outcome}``.

  * canary: ``canary_start(version, canary_addrs, ...)`` activates the new
    generation on a SUBSET of gateways only, and directs ``pct``% of NEW
    sessions there (the deterministic hash split in ``FleetRouter``) — via
    a ``router=`` handle for in-process routers, and by publishing the
    config to the coordinator (``serve_canary`` token) for polling ones
    (the standalone proxy's refresh loop applies it; in-client routers can
    call ``fetch_canary`` on their own cadence). ``compare()`` reads both
    pools' request outcomes + latency tails off gateway ``status``;
    ``promote()`` is a normal atomic rollout plus clearing the canary
    config. Existing sessions never migrate for a canary — affinity wins.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ...obs import get_registry
from ..errors import ServeError
from .discovery import GatewayMap
from .router import TRANSPORT_ERRORS, _split_addr

#: coordinator token the live canary config is published under (a kv record,
#: not an endpoint: latest-timestamp record wins, pct=0 means no canary)
CANARY_TOKEN = "serve_canary"


def publish_canary(coordinator_addr: Tuple[str, int], addrs: Sequence[str],
                   pct: float, version: str = "") -> None:
    """Publish (or clear, with ``pct=0``) the fleet's canary config. Routers
    polling ``fetch_canary`` converge on it within their refresh cadence."""
    from ...comm.coordinator import coordinator_request

    host, port = coordinator_addr
    coordinator_request(host, port, "register", {
        "token": CANARY_TOKEN, "ip": "canary", "port": 0,
        "meta": {"addrs": list(addrs), "pct": float(pct), "version": version},
    })


def fetch_canary(coordinator_addr: Tuple[str, int]) -> Optional[dict]:
    """The latest published canary config (``{"addrs", "pct", "version"}``),
    or None when nothing was ever published."""
    from ...comm.discovery import discover_endpoints

    records = discover_endpoints(coordinator_addr, CANARY_TOKEN)
    if not records:
        return None
    latest = max(records, key=lambda r: r.get("ts", 0.0))
    return dict(latest.get("meta") or {})


class FleetRollout:
    """Rollout controller over a gateway map (discovered or static)."""

    def __init__(self, gateway_map: GatewayMap, timeout_s: float = 60.0,
                 client_factory: Optional[Callable[[str], Any]] = None,
                 coordinator_addr: Optional[Tuple[str, int]] = None):
        self.map = gateway_map
        self.timeout_s = float(timeout_s)
        self.coordinator_addr = coordinator_addr
        self._client_factory = client_factory
        self._clients: Dict[str, Any] = {}
        self._c_rollouts = {
            outcome: get_registry().counter(
                "distar_fleet_rollouts_total",
                "fleet-wide rollout attempts by outcome", outcome=outcome)
            for outcome in ("ok", "load_nack", "rolled_back",
                            "rollback_failed", "compare_gated")
        }

    # ------------------------------------------------------------------ plumbing
    def _client(self, addr: str):
        client = self._clients.get(addr)
        if client is None:
            if self._client_factory is not None:
                client = self._client_factory(addr)
            else:
                from ..tcp_frontend import ServeClient

                host, port = _split_addr(addr)
                client = ServeClient(host, port, timeout_s=self.timeout_s)
            self._clients[addr] = client
        return client

    def close(self) -> None:
        clients, self._clients = list(self._clients.values()), {}
        for c in clients:
            try:
                c.close()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass

    def fleet_status(self, addrs: Optional[Sequence[str]] = None) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for addr in addrs or self.map.addrs:
            try:
                out[addr] = self._client(addr).status()
            except (ServeError,) + TRANSPORT_ERRORS as e:
                out[addr] = {"error": repr(e)}
        return out

    # ------------------------------------------------------------------ rollout
    def rollout(self, version: str, source: Optional[str] = None, params=None,
                addrs: Optional[Sequence[str]] = None,
                player: Optional[str] = None) -> dict:
        """Atomic fleet-wide generation bump; see module docstring. Returns
        ``{"ok", "outcome", "acks", "generations"|"rollback"}`` — never
        raises for per-gateway NACKs (the verdict is the return value)."""
        targets = list(addrs or self.map.addrs)
        t0 = time.perf_counter()
        # what each gateway serves NOW — the rollback target
        prev: Dict[str, Optional[str]] = {}
        for addr in targets:
            st = self.fleet_status([addr])[addr]
            if "error" in st:
                self._c_rollouts["load_nack"].inc()
                return {"ok": False, "outcome": "load_nack", "phase": "status",
                        "acks": {addr: st["error"]}}
            # the rollback target must be what THIS player serves: on a
            # multiplexed gateway the top-level registry is the default
            # player's (e.g. the teacher's), not the player being rolled
            if player is not None and (st.get("players") or {}).get(player):
                st = st["players"][player]
            prev[addr] = (st.get("registry") or {}).get("current")

        # phase 1: load + warm everywhere; a loaded version is inert until
        # activated, so any NACK aborts with the fleet untouched
        acks: Dict[str, Any] = {}
        nack = False
        for addr in targets:
            try:
                acks[addr] = self._client(addr).load(
                    version, source=source, params=params, activate=False,
                    player=player)
            except (ServeError,) + TRANSPORT_ERRORS as e:
                acks[addr] = {"error": repr(e)}
                nack = True
        if nack:
            self._c_rollouts["load_nack"].inc()
            return {"ok": False, "outcome": "load_nack", "phase": "load",
                    "acks": acks}

        # phase 2: activate gateway by gateway; NACK -> roll the already-
        # swapped prefix back to what it was serving
        generations: Dict[str, int] = {}
        swapped: List[str] = []
        for addr in targets:
            try:
                generations[addr] = self._client(addr).swap(version, player=player)
                swapped.append(addr)
            except (ServeError,) + TRANSPORT_ERRORS as e:
                rollback: Dict[str, Any] = {}
                failed = False
                for done in swapped:
                    target = prev[done]
                    try:
                        if target is None:
                            raise ServeError(
                                "no previous version to roll back to")
                        rollback[done] = self._client(done).swap(
                            target, player=player)
                    except (ServeError,) + TRANSPORT_ERRORS as re:
                        rollback[done] = {"error": repr(re)}
                        failed = True
                outcome = "rollback_failed" if failed else "rolled_back"
                self._c_rollouts[outcome].inc()
                return {"ok": False, "outcome": outcome, "phase": "swap",
                        "failed_gateway": addr, "error": repr(e),
                        "acks": acks, "rollback": rollback}
        self._c_rollouts["ok"].inc()
        return {"ok": True, "outcome": "ok", "acks": acks,
                "generations": generations,
                "elapsed_s": round(time.perf_counter() - t0, 4)}

    # ------------------------------------------------------------------- canary
    def canary_start(self, version: str, canary_addrs: Sequence[str],
                     pct: float, source: Optional[str] = None, params=None,
                     router=None, player: Optional[str] = None) -> dict:
        """Activate ``version`` on the canary gateways only (atomic within
        the subset) and direct ``pct``% of NEW sessions there — via the
        given in-process ``router`` and/or the coordinator-published config
        every polling router converges on."""
        canary_addrs = [a for a in canary_addrs if a in self.map.meta]
        if not canary_addrs:
            raise ValueError("canary_start: no valid canary gateway addresses")
        verdict = self.rollout(version, source=source, params=params,
                               addrs=canary_addrs, player=player)
        if not verdict["ok"]:
            return verdict
        if router is not None:
            router.set_canary(canary_addrs, pct)
        if self.coordinator_addr is not None:
            publish_canary(self.coordinator_addr, canary_addrs, pct, version)
        return {**verdict, "canary": {"addrs": canary_addrs, "pct": pct,
                                      "version": version}}

    def _fetch_divergence(self, window_s: float = 600.0) -> Optional[float]:
        """Freshest ``distar_distill_kl`` value from the coordinator's TSDB
        (the distill learner ships it with the rest of its telemetry) —
        the divergence-vs-teacher leg of the canary compare. None when no
        coordinator is configured or no distill learner ever shipped."""
        if self.coordinator_addr is None:
            return None
        import json
        import urllib.request

        host, port = self.coordinator_addr
        url = (f"http://{host}:{port}/timeseries"
               f"?name=distar_distill_kl&window_s={window_s:g}")
        try:
            with urllib.request.urlopen(url, timeout=5.0) as resp:
                body = json.loads(resp.read())
        except (OSError, ValueError):
            return None
        best = None
        for st in (body.get("stats") or {}).values():
            last = (st or {}).get("last")
            if isinstance(last, (int, float)):
                ts = (st or {}).get("last_ts", 0.0)
                if best is None or ts > best[0]:
                    best = (ts, float(last))
        return best[1] if best else None

    def compare(self, canary_addrs: Sequence[str],
                baseline: Optional[dict] = None,
                divergence: Optional[float] = None,
                max_divergence: Optional[float] = None,
                min_fps_ratio: float = 0.9,
                shed_slack: float = 0.01,
                latency_ratio: float = 1.5,
                win_rate: Optional[dict] = None,
                win_rate_fn: Optional[Callable[[], dict]] = None,
                min_win_rate: Optional[float] = None) -> dict:
        """Canary vs stable, from each gateway's own request accounting:
        cumulative outcome counters, shed rate and latency tails per pool,
        plus the two distillation-tier axes — **frames/s-per-slot** (ok
        requests per second per session slot, the serve-side throughput a
        cheaper student must not lose; measurable when ``baseline`` is a
        previous ``compare()`` snapshot to diff the lifetime counters
        against) and **divergence-vs-teacher** (``divergence=`` explicit,
        else the freshest ``distar_distill_kl`` from the coordinator TSDB).

        The third distillation axis is **win_rate**: head-to-head episodes
        of the canary (home) vs the stable policy over a fixed PRNG-keyed
        jaxenv scenario set (``envs.jaxenv.head_to_head``). Pass a
        ready-made summary via ``win_rate=`` or a zero-arg callable via
        ``win_rate_fn=`` (evaluated here, so the episode cost lands inside
        the compare step that reports it); ``min_win_rate`` turns the
        column into a gate — a canary that loses the head-to-head cannot
        promote.

        The returned ``verdict`` block is the promote/abort evidence the
        gated :meth:`promote` consumes: ``promote`` is True only when every
        measurable check passes; each failure lands in ``reasons``."""
        canary_set = set(canary_addrs)
        pools: Dict[str, dict] = {
            "stable": {"gateways": 0, "requests": {}, "shed_rate": 0.0,
                       "latency_p99_s": 0.0, "slots": 0},
            "canary": {"gateways": 0, "requests": {}, "shed_rate": 0.0,
                       "latency_p99_s": 0.0, "slots": 0},
        }
        for addr, st in self.fleet_status().items():
            pool = pools["canary" if addr in canary_set else "stable"]
            if "error" in st:
                pool.setdefault("unreachable", []).append(addr)
                continue
            pool["gateways"] += 1
            pool["slots"] += (st.get("sessions") or {}).get("num_slots", 0)
            for k, v in (st.get("requests") or {}).items():
                pool["requests"][k] = pool["requests"].get(k, 0.0) + v
            pool["shed_rate"] += st.get("shed_rate", 0.0)
            pool["latency_p99_s"] = max(
                pool["latency_p99_s"], (st.get("latency_s") or {}).get("p99", 0.0))
        for pool in pools.values():
            if pool["gateways"]:
                pool["shed_rate"] = round(pool["shed_rate"] / pool["gateways"], 6)
        out: Dict[str, Any] = dict(pools)
        out["ts"] = time.time()
        if baseline is not None and baseline.get("ts"):
            elapsed = max(out["ts"] - baseline["ts"], 1e-9)
            for name, pool in pools.items():
                prev = (baseline.get(name) or {}).get("requests") or {}
                ok_delta = pool["requests"].get("ok", 0.0) - prev.get("ok", 0.0)
                if pool["slots"]:
                    pool["fps_per_slot"] = round(
                        ok_delta / elapsed / pool["slots"], 6)
        if divergence is None:
            divergence = self._fetch_divergence()
        if divergence is not None:
            out["divergence"] = divergence
        if win_rate is None and win_rate_fn is not None:
            win_rate = win_rate_fn()
        if win_rate is not None:
            out["win_rate"] = dict(win_rate)

        reasons = []
        canary, stable = pools["canary"], pools["stable"]
        if canary.get("unreachable"):
            reasons.append(f"canary gateways unreachable: {canary['unreachable']}")
        if not canary["gateways"]:
            reasons.append("no reachable canary gateway")
        if canary["shed_rate"] > stable["shed_rate"] + shed_slack:
            reasons.append(
                f"canary shed_rate {canary['shed_rate']} > stable "
                f"{stable['shed_rate']} + {shed_slack}")
        if (canary["latency_p99_s"] and stable["latency_p99_s"]
                and canary["latency_p99_s"] > latency_ratio * stable["latency_p99_s"]):
            reasons.append(
                f"canary p99 {canary['latency_p99_s']:.4f}s > "
                f"{latency_ratio}x stable {stable['latency_p99_s']:.4f}s")
        c_fps, s_fps = canary.get("fps_per_slot"), stable.get("fps_per_slot")
        if c_fps is not None and s_fps and c_fps < min_fps_ratio * s_fps:
            reasons.append(
                f"canary fps_per_slot {c_fps} < {min_fps_ratio}x stable {s_fps}")
        if (max_divergence is not None and divergence is not None
                and divergence > max_divergence):
            reasons.append(
                f"divergence vs teacher {divergence:.4f} > "
                f"max_divergence {max_divergence}")
        if min_win_rate is not None:
            wr = (win_rate or {}).get("win_rate")
            if wr is None:
                reasons.append(
                    f"win_rate gate requested (min {min_win_rate}) but no "
                    "head-to-head result supplied")
            elif wr < min_win_rate:
                reasons.append(
                    f"canary win_rate {wr:.3f} < min_win_rate {min_win_rate} "
                    f"({win_rate.get('wins')}W/{win_rate.get('losses')}L/"
                    f"{win_rate.get('draws')}D over "
                    f"{win_rate.get('episodes')} episodes)")
        out["verdict"] = {"promote": not reasons, "reasons": reasons}
        return out

    def promote(self, version: str, source: Optional[str] = None, params=None,
                router=None, player: Optional[str] = None,
                verdict: Optional[dict] = None) -> dict:
        """The canary graduated: atomic fleet-wide rollout of ``version``,
        then clear the canary split (pins stay — sessions already on canary
        gateways are now on the fleet generation anyway). Pass a
        :meth:`compare` result (or its ``verdict`` block) as ``verdict`` to
        GATE the promotion on the compare evidence: a failing verdict
        refuses with ``outcome="compare_gated"`` and touches nothing — the
        canary keeps serving its split until an operator decides."""
        if verdict is not None:
            v = verdict.get("verdict", verdict)
            if not v.get("promote", True):
                self._c_rollouts["compare_gated"].inc()
                return {"ok": False, "outcome": "compare_gated",
                        "reasons": list(v.get("reasons", []))}
        verdict = self.rollout(version, source=source, params=params,
                               player=player)
        if verdict["ok"]:
            if router is not None:
                router.clear_canary()
            if self.coordinator_addr is not None:
                publish_canary(self.coordinator_addr, [], 0.0, version)
        return verdict


def main(argv=None) -> int:
    """Operator CLI: ``python -m distar_tpu.serve.fleet.rollout <cmd>``.

    ``status`` prints per-gateway serving state; ``rollout`` drives the
    atomic fleet-wide swap; ``canary`` activates a subset + publishes the
    routing split to the coordinator; ``promote`` graduates it. Exit 0 only
    when the fleet converged (rollback leaves exit 1 with the verdict
    printed as JSON)."""
    import argparse
    import json

    p = argparse.ArgumentParser(description="serve-fleet rollout controller")
    p.add_argument("command", choices=("status", "rollout", "canary", "promote"))
    p.add_argument("--gateways", default="", help="static 'h1:p1,h2:p2' list")
    p.add_argument("--discover", default="",
                   help="coordinator host:port to discover gateways from")
    p.add_argument("--version", default="", help="registry version name")
    p.add_argument("--source", default="", help="checkpoint storage URL")
    p.add_argument("--canary-addrs", default="",
                   help="canary: comma list of gateway addrs to canary; "
                        "promote: gate on compare() over these addrs "
                        "(shed/latency/divergence — a failing verdict "
                        "refuses with outcome=compare_gated)")
    p.add_argument("--canary-pct", type=float, default=10.0)
    p.add_argument("--max-divergence", type=float, default=None,
                   help="promote gating: refuse when the freshest "
                        "distar_distill_kl in the coordinator TSDB exceeds "
                        "this (the student drifted too far from the teacher)")
    p.add_argument("--player", default="", help="multiplexed gateways: player id")
    p.add_argument("--timeout-s", type=float, default=60.0)
    args = p.parse_args(argv)
    if bool(args.gateways) == bool(args.discover):
        p.error("exactly one of --gateways / --discover")
    coordinator = None
    if args.discover:
        host, _, port = args.discover.rpartition(":")
        coordinator = (host or "127.0.0.1", int(port))
        gateway_map = GatewayMap.discover(coordinator)
    else:
        gateway_map = GatewayMap.parse(args.gateways)
    ctl = FleetRollout(gateway_map, timeout_s=args.timeout_s,
                       coordinator_addr=coordinator)
    player = args.player or None
    try:
        if args.command == "status":
            print(json.dumps(ctl.fleet_status(),  # lint: allow-print
                             default=str, indent=1))
            return 0
        if not args.version or not args.source:
            p.error(f"{args.command} requires --version and --source")
        if args.command == "rollout":
            verdict = ctl.rollout(args.version, source=args.source, player=player)
        elif args.command == "canary":
            addrs = [a for a in args.canary_addrs.split(",") if a.strip()]
            verdict = ctl.canary_start(args.version, addrs, args.canary_pct,
                                       source=args.source, player=player)
        else:  # promote
            gate = None
            addrs = [a for a in args.canary_addrs.split(",") if a.strip()]
            if addrs:
                gate = ctl.compare(addrs, max_divergence=args.max_divergence)
            verdict = ctl.promote(args.version, source=args.source,
                                  player=player, verdict=gate)
        print(json.dumps(verdict, default=str))  # lint: allow-print
        return 0 if verdict.get("ok") else 1
    finally:
        ctl.close()


if __name__ == "__main__":
    raise SystemExit(main())
