"""Multi-gateway serving fleet: discovery, session-affinity routing, rollout.

The horizontal scale-out of ``distar_tpu/serve/`` (ROADMAP item 4): many
``bin/serve.py`` gateways register with the coordinator under the
``serve_gateway`` token (PR 4 lease/heartbeat + PR 9 ``peers`` discovery),
a routing tier pins sticky-carry sessions to gateways over the replay
fleet's consistent-hash ring — usable as an in-client library
(``FleetClient``, the rollout plane's ``--plane-addr discover`` backend)
or a thin standalone proxy (``python -m distar_tpu.serve.fleet.router``) —
and ``FleetRollout`` drives atomic fleet-wide model hot-swaps with
per-gateway ack/rollback plus canary-percent rollout.

Failure model in one line: a dead gateway's sessions re-route to survivors
within one retry budget and re-materialize from a zero carry, counted in
``distar_fleet_session_migrations_total`` (docs/serving.md, fleet section).
"""
from .discovery import GATEWAY_TOKEN, GatewayMap, register_gateway
from .rollout import CANARY_TOKEN, FleetRollout, fetch_canary, publish_canary
from .router import FleetClient, FleetRouter, RouterGatewayAdapter

__all__ = [
    "CANARY_TOKEN",
    "FleetClient",
    "FleetRollout",
    "FleetRouter",
    "GATEWAY_TOKEN",
    "GatewayMap",
    "RouterGatewayAdapter",
    "fetch_canary",
    "publish_canary",
    "register_gateway",
]
