"""Session-affinity routing/LB tier over the gateway fleet.

Sticky LSTM carries live server-side (PR 2's ``SessionTable``), so load
balancing cannot be per-request: a session pinned to gateway A must keep
hitting A or its carry restarts from zero. ``FleetRouter`` pins every
session to a gateway via the replay fleet's consistent-hash ring
(``replay.sharding.HashRing`` — stable md5 hashing, identical across
processes, N -> N+1 gateway growth remaps ~1/(N+1) of fresh sessions).

On gateway death the pin moves to a survivor (``distar_fleet_reroutes_
total``) and the session re-materializes from a zero carry on the new
gateway — detected exactly the PR 8 way: the per-episode ``session_step``
counter in every answer runs backwards, counted in
``distar_fleet_session_migrations_total``. The episode keeps rolling; the
migration cost is a visible number, never a silent quality loss.

Canary rollout support: ``set_canary(addrs, pct)`` carves the fleet into a
stable pool and a canary pool; ``pct``% of NEW sessions (chosen by a
deterministic hash split, so every router instance agrees) pin to canary
gateways. Existing sessions never move — affinity outranks canary.

Two deployment shapes, same code:

  * in-client library — ``FleetClient`` speaks the full ``ServeClient``
    surface (the rollout plane's ``remote`` backend mounts it directly via
    ``--plane-addr discover``), routing client-side like the replay
    fleet's sharded clients: no proxy hop on the data path.
  * thin standalone process — ``python -m distar_tpu.serve.fleet.router``
    fronts the fleet behind one address (``RouterGatewayAdapter`` behind
    the stock ``ServeTCPServer``/``ServeHTTPServer``), for callers that
    can't link the library.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ...obs import (
    annotate,
    finish_trace,
    get_registry,
    is_trace,
    join_trace,
    start_trace,
    tracing_enabled,
)
from ...replay.sharding import HashRing, stable_hash
from ...resilience import CircuitOpenError, RetryableError, RetryPolicy
from ..errors import CapacityError, DrainingError, ServeError
from .discovery import GatewayMap

#: exceptions that mean "this gateway is unreachable", never an application
#: answer — the router marks the gateway down and re-routes; typed
#: ``ServeError`` answers (sheds, unknown version...) pass through untouched
TRANSPORT_ERRORS = (ConnectionError, OSError, CircuitOpenError, RetryableError,
                    ValueError)


def _split_addr(addr: str) -> Tuple[str, int]:
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


class FleetRouter:
    """Pure routing state — gateway membership, session pins, down-list,
    canary split. No sockets: ``FleetClient`` (or any other transport) asks
    it where a session lives and reports gateway failures back."""

    def __init__(self, gateway_map: GatewayMap, vnodes: int = 128,
                 down_ttl_s: float = 10.0):
        self.map = gateway_map
        self.vnodes = int(vnodes)
        self.down_ttl_s = float(down_ttl_s)
        self._pins: Dict[str, str] = {}
        self._steps: Dict[str, int] = {}  # last seen session_step per session
        self._down: Dict[str, float] = {}  # addr -> retry-after monotonic ts
        self._canary_addrs: List[str] = []
        self._canary_pct: float = 0.0
        self._rings: Dict[frozenset, HashRing] = {}
        self._lock = threading.RLock()
        reg = get_registry()
        self._c_migrations = reg.counter(
            "distar_fleet_session_migrations_total",
            "sessions whose server-side carry re-materialized from zero "
            "(session_step ran backwards after a re-route or gateway restart)",
        )
        self._c_reroutes = reg.counter(
            "distar_fleet_reroutes_total",
            "session pins moved off an unreachable gateway to a survivor",
        )
        self._c_routed = {
            pool: reg.counter(
                "distar_fleet_routed_sessions_total",
                "new sessions pinned to a gateway, by routing pool", pool=pool)
            for pool in ("stable", "canary")
        }
        self._g_live = reg.gauge(
            "distar_fleet_gateways_live", "gateways currently routable")
        self._g_pinned = reg.gauge(
            "distar_fleet_sessions_pinned", "sessions holding a gateway pin")
        self._g_canary = reg.gauge(
            "distar_fleet_canary_pct",
            "percent of new sessions routed to the canary pool")
        self._g_live.set(len(self.map))

    # ------------------------------------------------------------- membership
    def live_addrs(self) -> List[str]:
        with self._lock:
            now = time.monotonic()
            live = [a for a in self.map.addrs if self._down.get(a, 0.0) <= now]
            self._g_live.set(len(live))
            return live

    def mark_down(self, addr: str, ttl_s: Optional[float] = None) -> None:
        """A transport failure was observed against ``addr``: keep new work
        off it for ``ttl_s`` (it is re-offered after — a restarted gateway
        on the same address rejoins automatically)."""
        with self._lock:
            self._down[addr] = time.monotonic() + (
                self.down_ttl_s if ttl_s is None else float(ttl_s))
        get_registry().counter(
            "distar_fleet_gateway_failures_total",
            "transport failures that marked a gateway down", gateway=addr,
        ).inc()

    def note_ok(self, addr: str) -> None:
        """A call against ``addr`` succeeded — clear any down mark early."""
        with self._lock:
            self._down.pop(addr, None)

    def mark_draining(self, addr: str, ttl_s: float = 60.0) -> None:
        """The gateway answered ``DrainingError``: it is retiring
        gracefully. Route new work AND existing pins off it (the re-pin is
        the migration; the retiring gateway finishes its in-flight work
        itself). A long TTL, not a permanent mark: the next membership
        refresh drops the address entirely, and a re-offer against a
        still-draining gateway just observes the drain again — harmless."""
        with self._lock:
            self._down[addr] = time.monotonic() + float(ttl_s)
        get_registry().counter(
            "distar_fleet_drains_observed_total",
            "DrainingError answers that moved routing off a retiring gateway",
            gateway=addr,
        ).inc()

    def refresh(self, gateway_map: GatewayMap) -> None:
        """Install a freshly discovered map (lease-evicted gateways are
        gone from it). Pins to departed gateways re-route on next use."""
        with self._lock:
            self.map = gateway_map
            self._rings.clear()
            self._down = {a: t for a, t in self._down.items()
                          if a in gateway_map.meta}
            self._canary_addrs = [a for a in self._canary_addrs
                                  if a in gateway_map.meta]

    # ----------------------------------------------------------------- canary
    def set_canary(self, addrs: Sequence[str], pct: float) -> None:
        """Route ``pct``% of NEW sessions to the canary gateways. Existing
        pins never move (affinity outranks canary). The split is a
        deterministic hash of the session id, so every router instance in
        the fleet sends the same sessions to the same pool."""
        with self._lock:
            self._canary_addrs = [a for a in addrs if a in self.map.meta]
            self._canary_pct = max(0.0, min(100.0, float(pct)))
            if not self._canary_addrs:
                self._canary_pct = 0.0
            self._g_canary.set(self._canary_pct)

    def clear_canary(self) -> None:
        self.set_canary([], 0.0)

    def canary_config(self) -> Tuple[List[str], float]:
        with self._lock:
            return list(self._canary_addrs), self._canary_pct

    def is_canary_session(self, session_id: str) -> bool:
        """Deterministic canary membership (cross-process stable — md5, not
        ``hash()``), evaluated against the CURRENT percent."""
        with self._lock:
            pct = self._canary_pct
        if pct <= 0.0:
            return False
        return (stable_hash(f"canary/{session_id}") % 10000) < pct * 100.0

    # ---------------------------------------------------------------- routing
    def _ring(self, addrs: List[str]) -> HashRing:
        key = frozenset(addrs)
        ring = self._rings.get(key)
        if ring is None:
            ring = self._rings[key] = HashRing(sorted(addrs), vnodes=self.vnodes)
        return ring

    def gateway_for(self, session_id: str) -> str:
        """The gateway this session lives on: its pin when that gateway is
        routable, else a survivor (counted re-route), else — for a fresh
        session — a ring pick from its pool (the canary split applies only
        here, to NEW sessions)."""
        with self._lock:
            now = time.monotonic()
            live = [a for a in self.map.addrs if self._down.get(a, 0.0) <= now]
            if not live:
                raise ServeError(
                    f"no routable gateway (fleet of {len(self.map)}, all down)")
            pinned = self._pins.get(session_id)
            if pinned is not None:
                if pinned in live and pinned in self.map.meta:
                    return pinned
                # pinned gateway unreachable: move to a survivor — the
                # session's carry re-materializes from zero over there
                addr = self._pick(session_id, live)
                self._pins[session_id] = addr
                self._c_reroutes.inc()
                return addr
            addr = self._pick(session_id, live)
            self._pins[session_id] = addr
            self._g_pinned.set(len(self._pins))
            pool = ("canary" if self._canary_pct > 0.0
                    and addr in self._canary_addrs else "stable")
            self._c_routed[pool].inc()
            return addr

    def _pick(self, session_id: str, live: List[str]) -> str:
        """Ring pick within the session's pool (caller holds the lock)."""
        canary_live = [a for a in self._canary_addrs if a in live]
        if canary_live and self.is_canary_session(session_id):
            return self._ring(canary_live).lookup(session_id)
        stable = [a for a in live if a not in self._canary_addrs] or live
        return self._ring(stable).lookup(session_id)

    def spill_over(self, session_id: str, addr: str) -> bool:
        """A FRESH session (no server-side carry yet) was capacity-shed at
        its ring-picked gateway: move its pin to the next live gateway so
        the fleet's free slots absorb it — arrival admission becomes a
        fleet-wide property, not a per-gateway accident of the hash split
        (and a just-joined gateway actually receives the overflow that
        triggered the scale-up). Sessions with a materialized carry NEVER
        move this way — affinity outranks capacity. Returns False when
        there is nowhere else to try (the shed then passes through)."""
        now = time.monotonic()
        with self._lock:
            if self._steps.get(session_id, 0) > 0:
                return False  # carry materialized: affinity wins
            live = [a for a in self.map.addrs if self._down.get(a, 0.0) <= now]
            if len(live) <= 1:
                return False
            cur = self._pins.get(session_id, addr)
            i = live.index(cur) if cur in live else -1
            nxt = live[(i + 1) % len(live)]
            if nxt == cur:
                return False
            self._pins[session_id] = nxt
        get_registry().counter(
            "distar_fleet_capacity_spillovers_total",
            "fresh sessions re-pinned past a capacity-full gateway to the "
            "next live one",
        ).inc()
        return True

    def note_step(self, session_id: str, step: Optional[int]) -> None:
        """Feed every answer's ``session_step`` back: when it runs backwards
        the server-side carry restarted from zero — one migration."""
        if step is None:
            return
        with self._lock:
            last = self._steps.get(session_id, 0)
            if last > 0 and int(step) <= last:
                self._c_migrations.inc()
            self._steps[session_id] = int(step)

    def reset_steps(self, session_id: str) -> None:
        """Episode boundary: the server restarts the counter with the carry
        — a step of 1 after this is NOT a migration."""
        with self._lock:
            self._steps.pop(session_id, None)

    def unpin(self, session_id: str) -> None:
        with self._lock:
            self._pins.pop(session_id, None)
            self._steps.pop(session_id, None)
            self._g_pinned.set(len(self._pins))

    def pins_on(self, addr: str) -> List[str]:
        with self._lock:
            return [sid for sid, a in self._pins.items() if a == addr]

    def stats(self) -> dict:
        with self._lock:
            now = time.monotonic()
            per_gateway: Dict[str, int] = {a: 0 for a in self.map.addrs}
            for a in self._pins.values():
                per_gateway[a] = per_gateway.get(a, 0) + 1
            return {
                "gateways": list(self.map.addrs),
                "down": sorted(a for a, t in self._down.items() if t > now),
                "pinned_sessions": len(self._pins),
                "pins_per_gateway": per_gateway,
                "canary": {"addrs": list(self._canary_addrs),
                           "pct": self._canary_pct},
            }


class FleetClient:
    """The whole fleet behind the ``ServeClient`` surface.

    Per-gateway ``ServeClient``s are dialed lazily, each under a SHORT
    retry policy — the rotation is the real retry: when a gateway's budget
    is exhausted the router marks it down, re-pins the affected sessions to
    survivors and the call is re-issued there, all inside the caller's
    timeout. Typed ``ServeError`` answers pass through untouched (sheds are
    application backpressure, not gateway death).

    ``player`` stamps every request for multiplexed gateways
    (``serve.mux.GatewayMux``); a single-model gateway ignores the field,
    so the same client speaks to both generations of server.
    """

    def __init__(self, gateway_map: Optional[GatewayMap] = None,
                 router: Optional[FleetRouter] = None,
                 coordinator_addr: Optional[Tuple[str, int]] = None,
                 timeout_s: float = 30.0, player: Optional[str] = None,
                 retry_policy: Optional[RetryPolicy] = None,
                 client_factory: Optional[Callable[[str], Any]] = None,
                 down_ttl_s: float = 10.0, transport: str = "auto",
                 refresh_s: float = 10.0):
        self.transport = transport
        if router is None:
            if gateway_map is None:
                if coordinator_addr is None:
                    raise ValueError(
                        "FleetClient needs a gateway_map, a router, or a "
                        "coordinator_addr to discover one")
                gateway_map = GatewayMap.discover(coordinator_addr)
            router = FleetRouter(gateway_map, down_ttl_s=down_ttl_s)
        self.router = router
        self.timeout_s = float(timeout_s)
        self.player = player
        # fail FAST per gateway: the router's re-route is the patience
        self._policy = retry_policy or RetryPolicy(
            max_attempts=2, backoff_base_s=0.1, backoff_max_s=0.5,
            deadline_s=max(5.0, timeout_s / 2.0))
        self._client_factory = client_factory
        self._clients: Dict[str, Any] = {}
        self._lock = threading.Lock()
        # live membership: with a coordinator in hand, re-discover the fleet
        # every refresh_s so joins (autoscaler scale-ups) and drains become
        # visible WITHOUT a restart — the comm.discovery refresh idiom
        self._refresher = None
        if coordinator_addr is not None and refresh_s > 0:
            from ...comm.discovery import start_refresh
            from .discovery import GATEWAY_TOKEN

            self._refresher = start_refresh(
                coordinator_addr, GATEWAY_TOKEN, self._apply_records,
                interval_s=refresh_s)

    def _apply_records(self, records) -> None:
        """Fold a freshly discovered fleet into the live router. An empty
        read is kept OUT (indistinguishable from a restarting broker that
        lost its records — a stale map beats an empty one). A departed
        address that still holds session pins gets the drain handoff:
        those sessions are ENDED there best-effort (a draining gateway
        still answers ``end``, so its residency actually reaches zero and
        it can exit; a crashed one ignores us harmlessly) before their
        next step re-pins them to a survivor. Clients held against
        departed gateways are then closed."""
        meta = {f"{r['ip']}:{r['port']}": dict(r.get("meta") or {})
                for r in records}
        if not meta:
            return
        departed = [a for a in self.router.map.addrs if a not in meta]
        pinned = {a: self.router.pins_on(a) for a in departed}
        self.router.refresh(GatewayMap(sorted(meta), meta=meta))
        for addr in departed:
            with self._lock:
                client = self._clients.get(addr)
            if client is not None and pinned.get(addr):
                self._drain_handoff(addr, client, pinned[addr],
                                    mark=False)
        with self._lock:
            dead = [a for a in self._clients if a not in meta]
            closed = [self._clients.pop(a) for a in dead]
        for c in closed:
            try:
                c.close()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass

    # ------------------------------------------------------------ connections
    def _dial(self, addr: str):
        if self._client_factory is not None:
            return self._client_factory(addr)
        from ..tcp_frontend import ServeClient

        host, port = _split_addr(addr)
        # transport negotiates per gateway: colocated members of a mixed
        # fleet ride shm, remote ones fall out to framed TCP naturally
        return ServeClient(host, port, timeout_s=self.timeout_s,
                           retry_policy=self._policy, transport=self.transport)

    def _client_for(self, addr: str):
        with self._lock:
            client = self._clients.get(addr)
        if client is not None:
            return client
        client = self._dial(addr)  # TRANSPORT_ERRORS propagate to the caller
        with self._lock:
            held = self._clients.setdefault(addr, client)
        if held is not client:
            client.close()
        return held

    def _gateway_failed(self, addr: str) -> None:
        with self._lock:
            client = self._clients.pop(addr, None)
        if client is not None:
            try:
                client.close()
            except Exception:  # noqa: BLE001 - already failed; best-effort
                pass
        self.router.mark_down(addr)

    def _player(self, player: Optional[str]) -> Optional[str]:
        return self.player if player is None else player

    # -------------------------------------------------------------- data path
    def act(self, session_id: str, obs, timeout_s: Optional[float] = None,
            want_teacher: bool = False, player: Optional[str] = None,
            trace: Optional[dict] = None) -> dict:
        """One agent step with affinity + failover: served by the session's
        pinned gateway, re-routed to a survivor when that gateway is
        unreachable (the carry re-materializes from zero over there —
        counted). Raises typed ``ServeError``s exactly like a direct
        ``ServeClient``. ``trace`` supplies a caller-minted span for the
        lane (re-route/retry time annotates it; finished here)."""
        req = {"session_id": session_id, "obs": obs, "want_teacher": want_teacher}
        if trace is not None:
            req["trace_ctx"] = trace
        out = self.act_many([req], timeout_s=timeout_s, player=player)[0]
        if isinstance(out, ServeError):
            raise out
        return out

    def act_many(self, requests, timeout_s: Optional[float] = None,
                 player: Optional[str] = None) -> list:
        """One cycle across the fleet: lanes group by their sessions'
        gateways, one ``act_many`` frame per gateway, per-lane results
        merged back in request order (dicts or typed ``ServeError``
        instances — the gateway contract). A gateway that fails mid-cycle
        is marked down, its lanes re-pin to survivors and re-issue; only
        when no routable gateway remains do those lanes come back as
        ``ServeError`` values."""
        requests = list(requests)
        player = self._player(player)
        # per-lane client spans, minted BEFORE routing so fleet-level work —
        # re-routes, drain handoffs, capacity spill-overs — is attributed
        # (``retry_s``) to the request that paid for it; the per-gateway
        # ServeClient stamps the compact wire field from the same context,
        # so the winning gateway's span joins under this lane's span
        if tracing_enabled():
            for r in requests:
                if r.get("trace_ctx") is None:
                    r["trace_ctx"] = start_trace(
                        "serve_client", session=r.get("session_id", "?"))
        results: List[Any] = [None] * len(requests)
        lanes = list(range(len(requests)))
        spills: Dict[int, int] = {}  # per-lane capacity spill-overs this call
        # every lane traverses at most the whole fleet once, plus one pick
        for _ in range(len(self.router.map) + 1):
            if not lanes:
                break
            round_t0 = time.monotonic()

            def _note_retry(idxs) -> None:
                # the re-route IS the retry: wall-clock this round burned
                # before the lane re-issues lands on its span as retry_s
                spent = time.monotonic() - round_t0
                for i in idxs:
                    annotate(requests[i].get("trace_ctx"), "retry_s", spent)

            by_addr: Dict[str, List[int]] = {}
            for i in lanes:
                try:
                    addr = self.router.gateway_for(requests[i]["session_id"])
                except ServeError as e:  # no routable gateway at all
                    results[i] = e
                    continue
                by_addr.setdefault(addr, []).append(i)
            retry: List[int] = []
            for addr, idxs in by_addr.items():
                try:
                    client = self._client_for(addr)
                    entries = client.act_many(
                        [requests[i] for i in idxs], timeout_s=timeout_s,
                        player=player)
                except TRANSPORT_ERRORS:
                    self._gateway_failed(addr)
                    _note_retry(idxs)
                    retry.extend(idxs)
                    continue
                self.router.note_ok(addr)
                handoff: List[int] = []
                for i, entry in zip(idxs, entries):
                    if isinstance(entry, DrainingError):
                        # graceful retirement, not backpressure: this lane's
                        # session migrates to a survivor (the PR 10 re-route
                        # path), it does NOT bounce back to the caller
                        handoff.append(i)
                        continue
                    if (isinstance(entry, CapacityError)
                            and spills.get(i, 0) < len(self.router.map) - 1
                            and self.router.spill_over(
                                requests[i]["session_id"], addr)):
                        # fresh session, full gateway, fleet not full:
                        # re-pinned to the next live gateway and re-issued
                        # (a fleet-wide-full session runs out of spills and
                        # sheds through typed, exactly as before)
                        spills[i] = spills.get(i, 0) + 1
                        _note_retry([i])
                        retry.append(i)
                        continue
                    results[i] = entry
                    if isinstance(entry, dict):
                        self.router.note_step(
                            requests[i]["session_id"], entry.get("session_step"))
                if handoff:
                    self._drain_handoff(
                        addr, client, [requests[i]["session_id"] for i in handoff])
                    _note_retry(handoff)
                    retry.extend(handoff)
            lanes = retry
        for i in lanes:  # passes exhausted with gateways still failing
            if results[i] is None:
                results[i] = ServeError("gateway fleet unreachable for lane")
        # lane spans resolve with the FINAL outcome (a shed that spilled to
        # a survivor and succeeded records ok, not the intermediate shed)
        for r, entry in zip(requests, results):
            ctx = r.get("trace_ctx")
            if not is_trace(ctx):
                continue
            if isinstance(entry, ServeError):
                entry.trace_id = ctx["trace_id"]
                finish_trace(ctx, "client_done",
                             outcome="shed" if entry.shed else "error")
            else:
                finish_trace(ctx, "client_done")
        return results

    def _drain_handoff(self, addr: str, client, session_ids,
                       mark: bool = True) -> None:
        """A gateway is retiring under these sessions: take routing off it
        (``mark=False`` when a membership refresh already removed it), then
        END each session there (freeing its slot, so the retiring process's
        ``resident_sessions`` actually drains to zero) before the caller's
        next step re-pins it to a survivor — where the carry
        re-materializes from zero and the migration is counted exactly
        (session_step runs backwards)."""
        if mark:
            self.router.mark_draining(addr)
        ended = 0
        for sid in session_ids:
            try:
                if client.end(sid, player=self.player):
                    ended += 1
            except Exception:  # noqa: BLE001 - the drain timeout frees it anyway
                pass
        if ended:
            get_registry().counter(
                "distar_fleet_drain_handoff_sessions_total",
                "sessions ended on a draining gateway before re-pinning to "
                "a survivor (exact-accounting half of a graceful migration)",
            ).inc(ended)

    # -------------------------------------------------------- session control
    def _routed_call(self, addr: str, opname: str, fn: Callable):
        """One control-plane call against a specific gateway; transport
        failure marks it down and surfaces typed (control ops don't blind-
        re-route: the caller re-issues and routing picks a survivor)."""
        try:
            client = self._client_for(addr)
            result = fn(client)
        except ServeError:
            raise  # typed application answer — the gateway is fine
        except TRANSPORT_ERRORS as e:
            self._gateway_failed(addr)
            raise ServeError(f"gateway {addr} unreachable for {opname}: {e!r}") from e
        self.router.note_ok(addr)
        return result

    def reserve(self, session_ids, player: Optional[str] = None) -> Dict[str, int]:
        """Bulk pre-allocation, grouped by each session's gateway. Exact
        capacity holds PER GATEWAY (each ``SessionTable.reserve`` is
        all-or-nothing); across gateways a later group's ``CapacityError``
        propagates with earlier groups already reserved — callers treat it
        as job-start failure exactly like the single-gateway contract."""
        player = self._player(player)
        out: Dict[str, int] = {}
        by_addr: Dict[str, List[str]] = {}
        for sid in session_ids:
            by_addr.setdefault(self.router.gateway_for(sid), []).append(sid)
        for addr, sids in by_addr.items():
            out.update(self._routed_call(
                addr, "reserve", lambda c, s=sids: c.reserve(s, player=player)))
        return out

    def hidden(self, session_id: str, player: Optional[str] = None):
        addr = self.router.gateway_for(session_id)
        return self._routed_call(
            addr, "hidden",
            lambda c: c.hidden(session_id, player=self._player(player)))

    def reset(self, session_id: str, player: Optional[str] = None) -> bool:
        addr = self.router.gateway_for(session_id)
        self.router.reset_steps(session_id)
        return self._routed_call(
            addr, "reset",
            lambda c: c.reset(session_id, player=self._player(player)))

    def end(self, session_id: str, player: Optional[str] = None) -> bool:
        try:
            addr = self.router.gateway_for(session_id)
            return self._routed_call(
                addr, "end",
                lambda c: c.end(session_id, player=self._player(player)))
        finally:
            self.router.unpin(session_id)

    # ------------------------------------------------------------ fleet admin
    def _broadcast(self, opname: str, fn: Callable) -> Dict[str, Any]:
        """Run a control op against every LIVE gateway; per-gateway results
        (``ServeError`` values for the unreachable) keyed by address."""
        out: Dict[str, Any] = {}
        for addr in self.router.live_addrs():
            try:
                out[addr] = self._routed_call(addr, opname, fn)
            except ServeError as e:
                out[addr] = e
        return out

    def set_teacher(self, params, player: Optional[str] = None) -> bool:
        p = self._player(player)
        replies = self._broadcast(
            "set_teacher", lambda c: c.set_teacher(params, player=p))
        return all(v is True for v in replies.values())

    def load(self, version: str, source: Optional[str] = None, params=None,
             activate: bool = False, player: Optional[str] = None) -> Dict[str, Any]:
        """Fleet-wide best-effort load (the rollout plane's weight-refresh
        path). For ATOMIC rollout with ack/rollback use ``fleet.rollout``."""
        p = self._player(player)
        return self._broadcast(
            "load", lambda c: c.load(version, source=source, params=params,
                                     activate=activate, player=p))

    def swap(self, version: str, player: Optional[str] = None) -> Dict[str, Any]:
        p = self._player(player)
        return self._broadcast("swap", lambda c: c.swap(version, player=p))

    def status(self) -> dict:
        per_gateway: Dict[str, Any] = {}
        for addr in self.router.map.addrs:
            try:
                per_gateway[addr] = self._routed_call(
                    addr, "status", lambda c: c.status())
            except ServeError as e:
                per_gateway[addr] = {"error": str(e)}
        return {"router": self.router.stats(), "gateways": per_gateway}

    def ping(self) -> bool:
        return all(not isinstance(v, ServeError)
                   for v in self._broadcast("ping", lambda c: c.ping()).values())

    def close(self) -> None:
        if self._refresher is not None:
            self._refresher.stop_event.set()
            self._refresher = None
        with self._lock:
            clients, self._clients = list(self._clients.values()), {}
        for c in clients:
            try:
                c.close()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class RouterGatewayAdapter:
    """``FleetClient`` behind the gateway method surface, so the stock
    ``ServeTCPServer``/``ServeHTTPServer`` can front a whole fleet as a
    thin standalone router process (for callers that can't mount the
    client library). ``resolve`` forwards each request's ``player`` field
    through to multiplexed gateways."""

    def __init__(self, fleet: FleetClient, player: Optional[str] = None):
        self.fleet = fleet
        self._player = player

    def resolve(self, player: Optional[str]) -> "RouterGatewayAdapter":
        if player is None or player == self._player:
            return self
        return RouterGatewayAdapter(self.fleet, player=player)

    def _join(self, wire):
        """A remote caller's wire trace field becomes this router process's
        own span (name ``router``) — the hop between client and gateway is
        then visible in the waterfall instead of folded into 'network'."""
        if wire is None or not tracing_enabled():
            return None
        return join_trace(wire, "router")

    def act(self, session_id: str, obs, timeout_s=None, want_teacher=False,
            trace=None):
        return self.fleet.act(session_id, obs, timeout_s=timeout_s,
                              want_teacher=want_teacher, player=self._player,
                              trace=self._join(trace))

    def act_many(self, requests, timeout_s=None):
        requests = list(requests)
        for r in requests:
            wire = r.get("trace")
            ctx = self._join(wire)
            if ctx is not None:
                r["trace_ctx"] = ctx
        return self.fleet.act_many(requests, timeout_s=timeout_s,
                                   player=self._player)

    def reserve_sessions(self, session_ids):
        return self.fleet.reserve(session_ids, player=self._player)

    def session_hidden(self, session_id: str):
        return self.fleet.hidden(session_id, player=self._player)

    def set_teacher(self, params):
        return self.fleet.set_teacher(params, player=self._player)

    def reset_session(self, session_id: str) -> bool:
        return self.fleet.reset(session_id, player=self._player)

    def end_session(self, session_id: str) -> bool:
        return self.fleet.end(session_id, player=self._player)

    def load_version(self, version, source=None, params=None, activate=False):
        replies = self.fleet.load(version, source=source, params=params,
                                  activate=activate, player=self._player)
        return {a: (v if not isinstance(v, ServeError) else {"error": str(v)})
                for a, v in replies.items()}

    def activate_version(self, version):
        replies = self.fleet.swap(version, player=self._player)
        errors = {a: str(v) for a, v in replies.items()
                  if isinstance(v, ServeError)}
        if errors:
            raise ServeError(f"swap failed on {sorted(errors)}: {errors}")
        return max((int(v) for v in replies.values()), default=0)

    def status(self) -> dict:
        return self.fleet.status()


def main(argv=None) -> int:
    """Standalone router: ``python -m distar_tpu.serve.fleet.router``.

    Fronts the gateway fleet (static ``--gateways`` list or coordinator
    ``--discover``) behind one TCP + one HTTP address. Prints a parseable
    ``SERVE-ROUTER <host> <tcp_port> <http_port>`` line once serving, then
    runs until SIGTERM/SIGINT or stdin EOF (the fleet-process idiom)."""
    import argparse
    import signal
    import sys

    from ..http_frontend import ServeHTTPServer
    from ..tcp_frontend import ServeTCPServer

    p = argparse.ArgumentParser(description="standalone serve-fleet router")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0, help="TCP data plane")
    p.add_argument("--http-port", type=int, default=0)
    p.add_argument("--gateways", default="",
                   help="static 'h1:p1,h2:p2' gateway list")
    p.add_argument("--discover", default="",
                   help="coordinator host:port to discover the fleet from")
    p.add_argument("--timeout-s", type=float, default=30.0)
    p.add_argument("--refresh-s", type=float, default=10.0,
                   help="re-discover cadence when using --discover")
    args = p.parse_args(argv)
    if bool(args.gateways) == bool(args.discover):
        p.error("exactly one of --gateways / --discover")

    coordinator = None
    if args.discover:
        host, _, port = args.discover.rpartition(":")
        coordinator = (host or "127.0.0.1", int(port))
        gateway_map = GatewayMap.discover(coordinator)
    else:
        gateway_map = GatewayMap.parse(args.gateways)
    fleet = FleetClient(gateway_map=gateway_map, timeout_s=args.timeout_s)
    adapter = RouterGatewayAdapter(fleet)
    tcp = ServeTCPServer(adapter, host=args.host, port=args.port).start()
    http = ServeHTTPServer(adapter, host=args.host, port=args.http_port).start()
    print(f"SERVE-ROUTER {tcp.host} {tcp.port} {http.port}",  # lint: allow-print
          flush=True)

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())

    if coordinator is not None:
        # live membership via the shared comm.discovery refresh idiom (the
        # same loop every FleetClient/sharded-replay client now runs), plus
        # convergence on the published canary split (rollout controller's
        # canary_start/promote publish it)
        from ...comm.discovery import start_refresh
        from .discovery import GATEWAY_TOKEN
        from .rollout import fetch_canary

        def apply_records(records):
            fleet._apply_records(records)
            cfg = fetch_canary(coordinator)
            if cfg is not None:
                fleet.router.set_canary(cfg.get("addrs") or [],
                                        float(cfg.get("pct") or 0.0))

        start_refresh(coordinator, GATEWAY_TOKEN, apply_records,
                      interval_s=args.refresh_s, stop_event=stop)
    try:
        import select

        while not stop.is_set():
            ready, _, _ = select.select([sys.stdin], [], [], 0.5)
            if ready and not sys.stdin.buffer.read(1):
                break
    except (OSError, ValueError, KeyboardInterrupt):
        pass
    tcp.stop()
    http.stop()
    fleet.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
