"""Engines: the fixed-shape batched forward the gateway flushes into.

An engine owns ``num_slots`` lanes of recurrent state and exposes exactly
the surface the batcher needs:

  * ``forward(prepared, active)`` — one batched step over all slots;
    inactive lanes are padding (their outputs are discarded and their
    hidden state must not advance)
  * ``reset_slot(idx)``           — zero one lane's carry (episode reset)
  * ``set_params(params)``        — install new weights (hot swap); must be
    shape-stable so the compiled forward is reused, not recompiled
  * ``teacher_forward(prepared, outputs, active)`` (optional, gated by
    ``has_teacher``) — teacher-forced logits for the freshly sampled
    actions, advancing per-slot teacher carries on ``active`` lanes only
  * ``hidden_for_slot(idx)`` (optional) — the lane's current policy carry
    (actors stamp it into trajectories as the learner's burn-in state)

``BatchedInferenceEngine`` adapts ``actor.inference.BatchedInference`` — the
serving path reuses the actor fleet's compiled ``sample_action`` verbatim.
``MockModelEngine`` is a CPU stand-in with observable per-slot dynamics for
tests, ``tools/loadgen.py`` and ``BENCH_MODE=rollout``.
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional

import numpy as np


class BatchedInferenceEngine:
    """Serve-side adapter over one ``BatchedInference`` (one player model)."""

    def __init__(self, infer):
        self._infer = infer

    @property
    def num_slots(self) -> int:
        return self._infer.num_slots

    @property
    def has_teacher(self) -> bool:
        return self._infer.teacher_params is not None

    def forward(self, prepared: List[dict], active: List[bool]) -> List[dict]:
        return self._infer.sample(prepared, active)

    def teacher_forward(self, prepared: List[dict], outputs: List[dict],
                        active: List[bool]) -> List[dict]:
        return self._infer.teacher_step(prepared, outputs, active)

    def reset_slot(self, idx: int) -> None:
        self._infer.reset_slot(idx)

    def set_params(self, params) -> None:
        self._infer.set_params(params)

    def set_teacher_params(self, params) -> None:
        self._infer.set_teacher_params(params)

    def hidden_for_slot(self, idx: int):
        return self._infer.hidden_for_slot(idx)

    def warmup(self, template_obs: dict, params=None) -> float:
        """Compile/execute the batched forward off the serving path: one
        throwaway step on zeroed scratch hidden state that touches neither
        the live params nor any slot's carry (safe concurrently with
        serving flushes). Returns wall seconds — dominated by XLA
        compilation the first time, ~one device step after."""
        t0 = time.perf_counter()
        self._infer.warmup(template_obs, params=params)
        return time.perf_counter() - t0


class MockModelEngine:
    """Deterministic mock with real engine semantics, no jax.

    Per-slot "hidden state" is a step counter that only advances on active
    lanes — sticky-session and reset bugs show up as wrong counters. Outputs
    echo the serving version (from params) so hot-swap tests can assert
    which weights served each request. ``delay_s`` models device time; the
    sleep releases the GIL like a real device dispatch, so concurrent
    submitters pile up behind it exactly as they would behind a TPU step.

    Two knobs model the one-device economics the rollout bench measures:
    ``per_slot_delay_s`` adds batch-size-dependent cost (sleep = delay_s +
    per_slot_delay_s * active lanes — a batched flush amortises the base
    cost), and ``device_lock`` — when several engine INSTANCES share one
    lock, their forwards serialise like N per-actor model replicas
    contending for the same physical chip.
    """

    def __init__(self, num_slots: int, params: Optional[dict] = None,
                 delay_s: float = 0.0, per_slot_delay_s: float = 0.0,
                 device_lock: Optional[threading.Lock] = None,
                 teacher_params: Optional[dict] = None):
        self.num_slots = num_slots
        self.params = dict(params or {"version": "v0", "bias": 0.0})
        self.delay_s = delay_s
        self.per_slot_delay_s = per_slot_delay_s
        self.device_lock = device_lock
        self.teacher_params = dict(teacher_params) if teacher_params else None
        self.steps = np.zeros(num_slots, dtype=np.int64)
        self.teacher_steps = np.zeros(num_slots, dtype=np.int64)
        self.forward_calls = 0
        self.teacher_calls = 0
        self.warmup_calls = 0
        self._lock = threading.Lock()

    @property
    def has_teacher(self) -> bool:
        return self.teacher_params is not None

    def warmup(self, template_obs: dict, params=None) -> float:
        if self.delay_s:
            time.sleep(self.delay_s)
        with self._lock:
            self.warmup_calls += 1
        return self.delay_s

    def set_params(self, params) -> None:
        with self._lock:
            self.params = dict(params)

    def set_teacher_params(self, params) -> None:
        with self._lock:
            self.teacher_params = dict(params)

    def reset_slot(self, idx: int) -> None:
        with self._lock:
            self.steps[idx] = 0
            self.teacher_steps[idx] = 0

    def hidden_for_slot(self, idx: int):
        with self._lock:
            return {"step": int(self.steps[idx])}

    def _device_time(self, n_active: int) -> None:
        d = self.delay_s + self.per_slot_delay_s * n_active
        if d <= 0:
            return
        if self.device_lock is not None:
            with self.device_lock:  # one chip: replica forwards serialise
                # analysis: allow(lock-held-blocking) — the sleep IS the simulated chip: the bench's shared device lock models serial forward execution, so blocking under it is the point
                time.sleep(d)
        else:
            time.sleep(d)

    def forward(self, prepared: List[dict], active: List[bool]) -> List[dict]:
        assert len(prepared) == self.num_slots and len(active) == self.num_slots
        self._device_time(sum(bool(a) for a in active))
        with self._lock:
            self.forward_calls += 1
            params = dict(self.params)
            # inactive lanes are padding by contract (their outputs are
            # discarded and must not be consumed) — skip their work, so a
            # many-slot gateway's flush cost scales with ACTIVE lanes, not
            # table size (the 10k-session capacity harness regime)
            outs: List[dict] = [None] * self.num_slots  # type: ignore[list-item]
            for i in range(self.num_slots):
                if not active[i]:
                    continue
                self.steps[i] += 1
                x = prepared[i].get("x", 0.0)
                outs[i] = {
                    "action": np.asarray(np.sum(x) + params.get("bias", 0.0)),
                    "step": int(self.steps[i]),
                    "version": params.get("version"),
                }
            return outs

    def teacher_forward(self, prepared: List[dict], outputs: List[dict],
                        active: List[bool]) -> List[dict]:
        """Teacher-forced mock: advances the per-slot TEACHER counter on
        active lanes only and echoes the teacher version, so carry semantics
        (reset zeroes it, inactive lanes keep theirs) are assertable."""
        assert len(prepared) == self.num_slots and len(active) == self.num_slots
        if self.teacher_params is None:
            raise RuntimeError("teacher_forward: no teacher params installed")
        self._device_time(sum(bool(a) for a in active))
        with self._lock:
            self.teacher_calls += 1
            tparams = dict(self.teacher_params)
            outs: List[dict] = [None] * self.num_slots  # type: ignore[list-item]
            for i in range(self.num_slots):
                if not active[i]:
                    continue
                self.teacher_steps[i] += 1
                outs[i] = {
                    "teacher_step": int(self.teacher_steps[i]),
                    "teacher_version": tparams.get("version"),
                }
            return outs
