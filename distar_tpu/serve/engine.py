"""Engines: the fixed-shape batched forward the gateway flushes into.

An engine owns ``num_slots`` lanes of recurrent state and exposes exactly
the surface the batcher needs:

  * ``forward(prepared, active)`` — one batched step over all slots;
    inactive lanes are padding (their outputs are discarded and their
    hidden state must not advance)
  * ``reset_slot(idx)``           — zero one lane's carry (episode reset)
  * ``set_params(params)``        — install new weights (hot swap); must be
    shape-stable so the compiled forward is reused, not recompiled

``BatchedInferenceEngine`` adapts ``actor.inference.BatchedInference`` — the
serving path reuses the actor fleet's compiled ``sample_action`` verbatim.
``MockModelEngine`` is a CPU stand-in with observable per-slot dynamics for
tests and ``tools/loadgen.py``.
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional

import numpy as np


class BatchedInferenceEngine:
    """Serve-side adapter over one ``BatchedInference`` (one player model)."""

    def __init__(self, infer):
        self._infer = infer

    @property
    def num_slots(self) -> int:
        return self._infer.num_slots

    def forward(self, prepared: List[dict], active: List[bool]) -> List[dict]:
        return self._infer.sample(prepared, active)

    def reset_slot(self, idx: int) -> None:
        self._infer.reset_slot(idx)

    def set_params(self, params) -> None:
        self._infer.set_params(params)

    def warmup(self, template_obs: dict, params=None) -> float:
        """Compile/execute the batched forward off the serving path: one
        throwaway step on zeroed scratch hidden state that touches neither
        the live params nor any slot's carry (safe concurrently with
        serving flushes). Returns wall seconds — dominated by XLA
        compilation the first time, ~one device step after."""
        t0 = time.perf_counter()
        self._infer.warmup(template_obs, params=params)
        return time.perf_counter() - t0


class MockModelEngine:
    """Deterministic mock with real engine semantics, no jax.

    Per-slot "hidden state" is a step counter that only advances on active
    lanes — sticky-session and reset bugs show up as wrong counters. Outputs
    echo the serving version (from params) so hot-swap tests can assert
    which weights served each request. ``delay_s`` models device time; the
    sleep releases the GIL like a real device dispatch, so concurrent
    submitters pile up behind it exactly as they would behind a TPU step.
    """

    def __init__(self, num_slots: int, params: Optional[dict] = None, delay_s: float = 0.0):
        self.num_slots = num_slots
        self.params = dict(params or {"version": "v0", "bias": 0.0})
        self.delay_s = delay_s
        self.steps = np.zeros(num_slots, dtype=np.int64)
        self.forward_calls = 0
        self.warmup_calls = 0
        self._lock = threading.Lock()

    def warmup(self, template_obs: dict, params=None) -> float:
        if self.delay_s:
            time.sleep(self.delay_s)
        with self._lock:
            self.warmup_calls += 1
        return self.delay_s

    def set_params(self, params) -> None:
        with self._lock:
            self.params = dict(params)

    def reset_slot(self, idx: int) -> None:
        with self._lock:
            self.steps[idx] = 0

    def forward(self, prepared: List[dict], active: List[bool]) -> List[dict]:
        assert len(prepared) == self.num_slots and len(active) == self.num_slots
        if self.delay_s:
            time.sleep(self.delay_s)
        with self._lock:
            self.forward_calls += 1
            params = dict(self.params)
            outs = []
            for i in range(self.num_slots):
                if active[i]:
                    self.steps[i] += 1
                x = prepared[i].get("x", 0.0)
                outs.append(
                    {
                        "action": np.asarray(np.sum(x) + params.get("bias", 0.0)),
                        "step": int(self.steps[i]),
                        "version": params.get("version"),
                    }
                )
            return outs
