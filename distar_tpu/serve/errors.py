"""Typed serve-plane errors.

Admission control and deadline enforcement answer with errors a caller can
dispatch on (retry-with-backoff for sheds, re-handshake for unknown
sessions) instead of blocking or returning ambiguous empties. Every error
maps to a wire dict (``to_wire``/``error_from_wire``) so both frontends —
JSON over HTTP and pickled frames over TCP — carry the same taxonomy.
"""
from __future__ import annotations


class ServeError(Exception):
    """Base serve failure. ``code`` is the stable wire identifier."""

    code = "serve_error"
    shed = False  # True for load-shed responses a client should retry later

    def to_wire(self) -> dict:
        return {"code": self.code, "error": str(self), "shed": self.shed}


class ShedError(ServeError):
    """Load shed: the server refused work it could not serve in time.
    Retryable by construction — no request state was created."""

    code = "shed"
    shed = True


class QueueFullError(ShedError):
    """Admission control: the bounded request queue is at capacity."""

    code = "shed_queue_full"


class DeadlineExceededError(ShedError):
    """The request's deadline passed before (or while) being served."""

    code = "shed_deadline"


class CapacityError(ShedError):
    """No session slot free and nothing idle enough to evict."""

    code = "shed_capacity"


class DrainingError(ShedError):
    """The gateway is draining for shutdown; no new admissions."""

    code = "draining"


class BadFrameError(ServeError):
    """The peer sent an unparseable frame (garbage header/codec): the framed
    stream can no longer be trusted and the connection closes after the
    reply."""

    code = "bad_frame"


class BadRequestError(ServeError):
    """The request was not a well-formed op dict, or named an op/surface
    this server does not have. Not retryable: re-sending the same request
    cannot fix it."""

    code = "bad_request"


class RingServiceError(ServeError):
    """The shm ring pump answered for a dispatch bug (comm/shm_ring.py
    ``RingService``): the request reached the server but its handler raised
    something untyped."""

    code = "shm_error"


class UnknownVersionError(ServeError):
    """Registry operation referenced a version that was never loaded."""

    code = "unknown_version"


class UnknownPlayerError(ServeError):
    """Request named a player this multiplexed gateway does not serve."""

    code = "unknown_player"


_WIRE_CODES = {
    cls.code: cls
    for cls in (
        ServeError,
        ShedError,
        QueueFullError,
        DeadlineExceededError,
        CapacityError,
        DrainingError,
        BadFrameError,
        BadRequestError,
        RingServiceError,
        UnknownVersionError,
        UnknownPlayerError,
    )
}


def error_from_wire(payload: dict) -> ServeError:
    """Rehydrate a typed error from its wire dict (unknown codes degrade to
    the base ``ServeError`` so old clients survive new server codes)."""
    cls = _WIRE_CODES.get(payload.get("code"), ServeError)
    return cls(payload.get("error", ""))
