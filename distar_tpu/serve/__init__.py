"""Dynamic-batching TPU inference gateway with a versioned hot-swap registry.

The standalone serving subsystem the trained league faces traffic through
(ROADMAP north star): ad-hoc ``sample_action`` requests from play services,
ladder bots, eval farms and human showmatches are coalesced into the same
fixed-shape jitted batch the actor fleet uses (``actor.inference.
BatchedInference`` — one compiled forward, pad-to-bucket), instead of the
actor's lockstep trajectory loop. The shape follows Podracer's Sebulba
split (arxiv 2104.06272: a central batched inference server decoupled from
its callers) with RLAX-style versioned weight swaps (arxiv 2512.06392).

Pieces:
  * ``MicroBatcher``     — deadline-aware request coalescing (flush on
                           batch-full or oldest-request deadline; per-request
                           timeouts shed with typed errors)
  * ``SessionTable``     — sticky sessions: server-side LSTM carry slots
                           with idle eviction
  * ``ModelRegistry``    — versioned params, warm-up off the serving path,
                           atomic zero-downtime swap
  * ``InferenceGateway`` — ties the above around an engine; admission
                           control, drain-then-stop shutdown
  * ``ServeHTTPServer``  — stdlib HTTP/JSON control + light data plane
  * ``ServeTCPServer`` / ``ServeClient`` — framed-TCP data plane on the
                           comm.serializer wire format (actor-grade callers)

Everything publishes into the process ``obs`` registry
(``distar_serve_*`` — see docs/serving.md for the full metric table).
"""
from .errors import (
    CapacityError,
    DeadlineExceededError,
    DrainingError,
    QueueFullError,
    ServeError,
    ShedError,
    UnknownPlayerError,
    UnknownVersionError,
    error_from_wire,
)
from .engine import BatchedInferenceEngine, MockModelEngine
from .batcher import MicroBatcher, PendingRequest
from .sessions import SessionTable
from .registry import ModelRegistry
from .gateway import InferenceGateway
from .mux import STUDENT_TIER, TEACHER_TIER, GatewayMux, tier_player
from .http_frontend import ServeHTTPServer
from .tcp_frontend import ServeClient, ServeTCPServer

__all__ = [
    "BatchedInferenceEngine",
    "CapacityError",
    "DeadlineExceededError",
    "DrainingError",
    "GatewayMux",
    "STUDENT_TIER",
    "TEACHER_TIER",
    "tier_player",
    "InferenceGateway",
    "MicroBatcher",
    "MockModelEngine",
    "ModelRegistry",
    "PendingRequest",
    "QueueFullError",
    "ServeClient",
    "ServeError",
    "ServeHTTPServer",
    "ServeTCPServer",
    "SessionTable",
    "ShedError",
    "UnknownPlayerError",
    "UnknownVersionError",
    "error_from_wire",
]
