"""The inference gateway: sessions + batcher + registry around one engine.

Request path (caller thread): resolve/allocate the sticky session slot,
mint a trace context, enqueue into the micro-batcher, block on the
rendezvous with the caller's timeout. Flush path (batcher thread): apply
any pending version swap at the flush boundary (in-flight forwards finish
on the old params — the zero-downtime half of the hot-swap protocol), pad
the fixed-shape batch with the zero template on inactive lanes, run ONE
engine forward, decollate and deliver per-request.

Shutdown is drain-then-stop: admissions shed with ``DrainingError`` while
everything already admitted flushes and completes.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..obs import (
    Span,
    annotate,
    finish_trace,
    get_registry,
    join_trace,
    note_exemplar,
    tracing_enabled,
)
from .batcher import MicroBatcher, PendingRequest
from .errors import DrainingError, ServeError, ShedError
from .registry import ModelRegistry
from .sessions import SessionTable


def _zeros_like_tree(t):
    """Pure-host zero template with the request's exact structure/dtypes
    (no jax: the gateway never touches the device outside the engine)."""
    if isinstance(t, dict):
        return {k: _zeros_like_tree(v) for k, v in t.items()}
    if isinstance(t, (list, tuple)):
        return type(t)(_zeros_like_tree(v) for v in t)
    return np.zeros_like(np.asarray(t))


class InferenceGateway:
    def __init__(
        self,
        engine,
        registry: Optional[ModelRegistry] = None,
        max_batch: Optional[int] = None,
        max_delay_s: float = 0.005,
        queue_capacity: int = 256,
        idle_ttl_s: float = 300.0,
        default_timeout_s: float = 10.0,
    ):
        self.engine = engine
        self.registry = registry if registry is not None else ModelRegistry(
            warmup_fn=self._warmup
        )
        self.sessions = SessionTable(
            engine.num_slots, idle_ttl_s=idle_ttl_s, on_alloc=engine.reset_slot
        )
        self.batcher = MicroBatcher(
            self._flush,
            max_batch=min(max_batch or engine.num_slots, engine.num_slots),
            max_delay_s=max_delay_s,
            capacity=queue_capacity,
        )
        self.default_timeout_s = default_timeout_s
        self._template = None
        self._template_lock = threading.Lock()
        self._applied_generation = 0
        self._served_version: Optional[str] = None
        self._draining = False
        #: entrypoints that registered this gateway with a coordinator set
        #: this to a callable that stops the heartbeat AND unregisters the
        #: lease; drain invokes it FIRST (a draining gateway must leave
        #: discovery before it starts shedding, or routers keep pinning new
        #: sessions to it until the lease dies)
        self.deregister = None
        self._deregistered = False
        self._drain_lock = threading.Lock()
        reg = get_registry()
        self._c_req = {
            outcome: reg.counter(
                "distar_serve_requests_total", "requests by outcome", outcome=outcome
            )
            for outcome in ("ok", "shed", "error", "timeout")
        }
        self._h_latency = reg.histogram(
            "distar_serve_request_latency_seconds", "submit-to-response latency"
        )
        self._g_inflight = reg.gauge(
            "distar_serve_inflight", "requests admitted and not yet completed"
        )

    def _warmup(self, params) -> None:
        """Default registry warm-up: one scratch forward, needs a template
        observation — skipped before the first request taught us the shape
        (cold start compiles on the first real flush instead)."""
        template = self._template
        if template is not None and hasattr(self.engine, "warmup"):
            self.engine.warmup(template, params=params)

    # ------------------------------------------------------------- lifecycle
    def start(self) -> "InferenceGateway":
        self.batcher.start()
        return self

    def _deregister_once(self) -> None:
        with self._drain_lock:
            if self._deregistered:
                return
            self._deregistered = True
            fn = self.deregister
        if fn is not None:
            try:
                fn()
            except Exception:  # noqa: BLE001 - best-effort; the lease still lapses
                pass

    def begin_drain(self) -> dict:
        """Enter graceful retirement — the drain state machine's first two
        steps, ordered deliberately:

          1. deregister the coordinator lease (leave discovery NOW, so
             routers stop pinning new sessions here);
          2. shed every NEW request with the typed ``DrainingError`` while
             requests already admitted flush and complete on the live
             batcher (this is ``start_draining``, not ``drain_and_stop``:
             the batcher thread keeps running).

        Resident sticky sessions then migrate client-side: a ``FleetClient``
        seeing ``DrainingError`` re-pins the session to a survivor and ends
        it here, so ``resident_sessions()`` drains to zero — the process
        exit condition the serving entrypoints poll. Idempotent."""
        self._deregister_once()
        if not self._draining:
            self._draining = True
            get_registry().counter(
                "distar_serve_drains_total",
                "graceful drains started on this gateway",
            ).inc()
        return {"draining": True, "resident": self.resident_sessions()}

    def resident_sessions(self) -> int:
        """Sessions still holding a slot — the number a drain waits on."""
        return self.sessions.stats()["active"]

    @property
    def draining(self) -> bool:
        return self._draining

    def drain_and_stop(self, timeout: Optional[float] = 30.0) -> None:
        """Stop admissions (deregistering the lease first), serve out the
        queue, stop the batcher thread."""
        self.begin_drain()
        self.batcher.drain_and_stop(timeout)

    # ----------------------------------------------------------- client API
    def act(self, session_id: str, obs: Dict[str, Any], timeout_s: Optional[float] = None,
            want_teacher: bool = False, trace=None):
        """One agent step: returns the engine's per-slot output dict plus
        ``model_version``. Raises a typed ``ServeError`` (``ShedError``
        subclasses are retryable load sheds). ``trace`` is the caller's
        compact wire trace-context field — the gateway's span joins it."""
        req = {"session_id": session_id, "obs": obs, "want_teacher": want_teacher}
        if trace is not None:
            req["trace"] = trace
        out = self.act_many([req], timeout_s=timeout_s)[0]
        if isinstance(out, ServeError):
            raise out
        return out

    def act_many(self, requests, timeout_s: Optional[float] = None):
        """Submit one cycle of requests — ``[{"session_id", "obs",
        "want_teacher"?}, ...]`` — and wait for all of them. Returns a
        per-request list whose entries are either the output dict or a
        typed ``ServeError`` INSTANCE (never raised: partial success must
        not lose the lanes that did complete — the rollout plane retries
        shed lanes individually). This is the actor-grade surface: a whole
        env fleet's cycle lands in the micro-batcher in one call, with no
        per-slot caller threads, and coalesces with every other caller's
        cycle into the same fixed-shape flush."""
        timeout_s = self.default_timeout_s if timeout_s is None else timeout_s
        t0 = time.perf_counter()
        if self._draining:
            # graceful retirement: NEW work sheds typed at the door (before
            # any session slot is touched) while already-admitted requests
            # finish on the live batcher; fleet clients treat this as the
            # migrate-my-session signal, not as backpressure
            self._c_req["shed"].inc(len(requests))
            err = DrainingError("gateway is draining; sessions are migrating")
            return [err for _ in requests]
        results: List[Any] = [None] * len(requests)
        pending: List[tuple] = []
        for i, r in enumerate(requests):
            session_id = r["session_id"]
            # server-side span: JOINS the caller's trace when the request
            # carries the compact wire field (client-minted span becomes the
            # parent — one trace_id across client/router/gateway), minted
            # fresh for untraced legacy callers
            ctx = None
            if tracing_enabled():
                ctx = join_trace(r.get("trace"), "serve_request",
                                 session=session_id)
            try:
                slot = self.sessions.acquire(session_id)
            except ShedError as e:  # CapacityError: no slot, nothing to evict
                self._c_req["shed"].inc()
                finish_trace(ctx, "shed", outcome="shed")
                results[i] = e
                continue
            with self._template_lock:
                if self._template is None:
                    self._template = _zeros_like_tree(r["obs"])
            req = PendingRequest(
                session_id, slot, r["obs"],
                deadline_ts=time.time() + timeout_s, ctx=ctx,
                want_teacher=bool(r.get("want_teacher", False)),
            )
            try:
                self.batcher.submit(req)  # QueueFull/Draining shed here
            except ShedError as e:
                self._c_req["shed"].inc()
                self.sessions.release(session_id)
                finish_trace(ctx, "shed", outcome="shed")
                results[i] = e
                continue
            self._g_inflight.inc()
            pending.append((i, session_id, req))
        wall_deadline = time.monotonic() + timeout_s + 0.25
        for i, session_id, req in pending:
            try:
                if not req.wait(max(0.0, wall_deadline - time.monotonic())):
                    # rendezvous never fired (flush wedged past the grace):
                    # abandon so a late delivery is discarded
                    if req.abandon():
                        self._c_req["timeout"].inc()
                        finish_trace(req.ctx, "timeout", outcome="error")
                        results[i] = ServeError(f"no response within {timeout_s}s")
                        continue
                if req.error is not None:
                    shed = req.error.shed
                    self._c_req["shed" if shed else "error"].inc()
                    finish_trace(req.ctx, "shed" if shed else "error",
                                 outcome="shed" if shed else "error")
                    results[i] = req.error
                    continue
                self._c_req["ok"].inc()
                latency = time.perf_counter() - t0
                self._h_latency.observe(latency)
                if req.ctx is not None:
                    # close the server span HERE (the waiter's thread) with
                    # the flush's queue/service attribution — the flush
                    # thread only stamped the cheap facts
                    annotate(req.ctx, "queue_s", req.queue_s)
                    annotate(req.ctx, "service_s", req.service_s)
                    finish_trace(req.ctx, "serve_done")
                    if req.ctx.get("_kept"):
                        # exemplar: the latency series names its last
                        # RETAINED witness (a dropped trace_id would 404 on
                        # retrieval) — a firing p99 SLO alert then names a
                        # retrievable trace
                        note_exemplar("distar_serve_request_latency_seconds",
                                      req.ctx.get("trace_id"), latency)
                results[i] = req.result
            finally:
                self._g_inflight.dec()
                self.sessions.release(session_id)
        return results

    def reserve_sessions(self, session_ids) -> Dict[str, int]:
        """Exact-capacity bulk admission: allocate (or confirm) a slot for
        every id atomically, shedding the WHOLE reservation typed
        (``CapacityError``) when the table can't host it — actors fail fast
        at job start instead of shedding mid-episode."""
        if self._draining:
            raise DrainingError("gateway is draining; no new reservations")
        return self.sessions.reserve(list(session_ids))

    def session_hidden(self, session_id: str):
        """The session's current policy carry (actors stamp it into
        trajectories as the learner's burn-in state). ``None`` when the
        session is unknown or the engine keeps no readable carry."""
        slot = self.sessions.slot_of(session_id)
        if slot is None or not hasattr(self.engine, "hidden_for_slot"):
            return None
        return self.engine.hidden_for_slot(slot)

    def set_teacher(self, params) -> bool:
        """Install frozen-teacher weights on the engine (the rollout
        plane's teacher-logits path batches through the same flushes)."""
        if not hasattr(self.engine, "set_teacher_params"):
            raise ServeError("engine has no teacher surface")
        self.engine.set_teacher_params(params)
        return True

    def reset_session(self, session_id: str) -> bool:
        """Episode boundary: zero the session's LSTM carry (policy AND
        teacher), restart its step counter, keep the slot."""
        slot = self.sessions.slot_of(session_id)
        if slot is None:
            return False
        self.engine.reset_slot(slot)
        self.sessions.reset_steps(session_id)
        return True

    def end_session(self, session_id: str) -> bool:
        return self.sessions.end(session_id)

    # ---------------------------------------------------------------- admin
    def load_version(self, version: str, source: Optional[str] = None, params=None,
                     activate: bool = False) -> dict:
        return self.registry.load(version, source=source, params=params, activate=activate)

    def activate_version(self, version: str) -> int:
        return self.registry.activate(version)

    def status(self) -> dict:
        requests = {name: c.value for name, c in self._c_req.items()}
        total = sum(requests.values())
        # live per-connection transport split (shm vs tcp), stamped on by
        # the TCP frontend when one is mounted — the opsctl serving
        # digest's "which leg is each connection on" answer
        transports = getattr(self, "_tcp_transports", None)
        return {
            **({"transports": transports()} if callable(transports) else {}),
            "draining": self._draining,
            "queue_depth": self.batcher.depth,
            "served_version": self._served_version,
            # the generation actually serving (applied at a flush boundary),
            # which trails registry.generation during an in-progress swap
            "generation": self._applied_generation,
            "sessions": self.sessions.stats(),
            "registry": self.registry.status(),
            # cumulative outcome counters + latency tails: what the fleet
            # rollout's canary-vs-stable compare and the opsctl serving
            # digest read per gateway
            "requests": requests,
            "shed_rate": round(requests.get("shed", 0.0) / total, 6) if total else 0.0,
            "latency_s": {"p50": self._h_latency.quantile(0.5),
                          "p99": self._h_latency.quantile(0.99)},
        }

    # ---------------------------------------------------------------- flush
    def _flush(self, batch: List[PendingRequest], reason: str) -> None:
        generation, version, params = self.registry.current()
        if params is not None and generation != self._applied_generation:
            # the swap boundary: the previous flush (and anything still
            # executing) used the old params reference; from here on the
            # engine serves the new generation
            self.engine.set_params(params)
            self._applied_generation = generation
            self._served_version = version
            self.registry.swap_applied(generation)
        template = self._template
        prepared: List[dict] = [template] * self.engine.num_slots
        active = [False] * self.engine.num_slots
        flush_ts = time.time()
        for r in batch:
            prepared[r.slot] = r.obs
            active[r.slot] = True
            if r.ctx is not None:
                # bare hop append, NO histogram: the flush thread is the
                # gateway's serial bottleneck, so per-request trace work
                # here costs throughput one-for-one — everything heavier
                # (service annotation, finish, exemplar) runs on the
                # waiter's thread, which overlaps the next forward
                r.ctx["hops"].append({"hop": "serve_flush", "ts": flush_ts})
        with Span("serve_forward") as fwd:
            outs = self.engine.forward(prepared, active)
        for r in batch:
            # service-time attribution: the whole batched forward serves
            # every lane of the flush (fixed-shape batching — a lane cannot
            # pay less than the flush it rode); annotated at completion
            r.service_s = fwd.elapsed
        # teacher logits piggyback on the same flush (one extra batched
        # forward serving every lane that asked, not one per caller); lanes
        # that didn't ask must not advance their teacher carry
        t_outs = None
        wanting = [r for r in batch if r.want_teacher]
        if wanting and getattr(self.engine, "has_teacher", False):
            t_active = [False] * self.engine.num_slots
            for r in wanting:
                t_active[r.slot] = True
            with Span("serve_teacher_forward"):
                t_outs = self.engine.teacher_forward(prepared, outs, t_active)
        for r in batch:
            out = dict(outs[r.slot])
            out["model_version"] = self._served_version
            if t_outs is not None and r.want_teacher:
                out["teacher_logit"] = t_outs[r.slot]
            # episode-local forward count: clients detect a server-side
            # carry reset (gateway restart, eviction) when it runs backwards
            out["session_step"] = self.sessions.note_step(r.session_id)
            if not r.complete(result=out):
                # waiter already abandoned (its timeout fired): nobody will
                # finish this span downstream — close it here so the trace
                # is retained with the truth
                finish_trace(r.ctx, "abandoned", outcome="error")
