"""Stdlib HTTP/JSON frontend: control plane + light data plane.

Follows the ``CoordinatorServer`` conventions (ThreadingHTTPServer, POST
routes answering ``{"code": 0, "info": ...}`` with HTTP 200, errors in the
``code`` field) so operators drive one curl-able surface across the stack.
Serve errors answer their typed wire dict (``{"code": "shed_queue_full",
"shed": true, ...}``). Observation arrays JSON-ify as nested lists — fine
for showmatch/eval callers; actor-grade traffic belongs on the framed-TCP
data plane (``tcp_frontend``), which carries real numpy.

Routes:
  POST /serve/act     {session_id, obs, timeout_s?}
  POST /serve/reset   {session_id}
  POST /serve/end     {session_id}
  POST /serve/load    {version, source, activate?}
  POST /serve/swap    {version}
  POST /serve/status  {}
  POST /serve/drain   {}   -> begin graceful retirement (idempotent)
  GET  /metrics       Prometheus scrape (shared obs helper)
  GET  /healthz /alerts /timeseries   fleet-health JSON (shared obs helper)

Drain contract (mirror of the TCP side): ``POST /drain`` deregisters the
coordinator lease and flips the gateway to shed-new/finish-in-flight; from
then on a shed NEW request answers HTTP **503** with the typed
``DrainingError`` wire body (every other typed serve error keeps the
legacy 200-with-wire-dict shape), while requests admitted before the drain
complete normally.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from .errors import DrainingError, ServeError


def jsonable(obj):
    """numpy trees -> plain JSON types (arrays to nested lists)."""
    if isinstance(obj, dict):
        return {k: jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [jsonable(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    return obj


def arrayify(obj):
    """JSON obs -> numpy trees (lists/scalars to arrays; dicts recurse)."""
    if isinstance(obj, dict):
        return {k: arrayify(v) for k, v in obj.items()}
    if isinstance(obj, (list, int, float)):
        return np.asarray(obj)
    return obj


class ServeHTTPServer:
    def __init__(self, gateway, host: str = "127.0.0.1", port: int = 0):
        root = gateway

        def routes(name: str, body: dict, trace=None):
            # multiplexed gateways resolve the optional ``player`` field
            # (absent = default player; single-model gateways ignore it)
            gw = root
            if hasattr(gw, "resolve"):
                gw = gw.resolve(body.get("player"))
            if name == "act":
                out = gw.act(
                    body["session_id"], arrayify(body["obs"]), body.get("timeout_s"),
                    trace=trace,
                )
                return jsonable(out)
            if name == "reset":
                return {"reset": gw.reset_session(body["session_id"])}
            if name == "end":
                return {"ended": gw.end_session(body["session_id"])}
            if name == "load":
                return gw.load_version(
                    body["version"], source=body["source"],
                    activate=bool(body.get("activate", False)),
                )
            if name == "swap":
                return {"generation": gw.activate_version(body["version"])}
            if name == "status":
                return gw.status()
            if name == "drain":
                # drain is ADDRESS-level (never per-player): begin graceful
                # retirement of the whole serving process
                if not hasattr(root, "begin_drain"):
                    raise ServeError("target has no drain surface")
                return root.begin_drain()
            return None

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def do_GET(self):
                from ..obs import handle_health_get, write_scrape_response

                if self.path.rstrip("/") == "/metrics":
                    write_scrape_response(self)
                    return
                if handle_health_get(self, self.path):
                    return
                self.send_response(404)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def do_POST(self):
                from ..obs import (
                    finish_trace,
                    format_traceparent,
                    join_trace,
                    parse_traceparent,
                    wire_ctx,
                )

                name = self.path.strip("/").split("/")[-1]
                length = int(self.headers.get("Content-Length", 0))
                status = 200
                # w3c traceparent propagation: a caller-supplied header joins
                # this frontend's span under the caller's trace_id, and the
                # gateway span joins under THAT — client-minted and
                # server-side spans assemble into one waterfall
                wire = parse_traceparent(self.headers.get("traceparent"))
                ctx = join_trace(wire, f"http_{name}") if wire is not None else None
                outcome = "ok"
                try:
                    body = json.loads(self.rfile.read(length) or b"{}")
                    info = routes(name, body,
                                  trace=wire_ctx(ctx) if ctx is not None else None)
                    payload = (
                        {"code": 404, "info": f"no route {name}"}
                        if info is None
                        else {"code": 0, "info": info}
                    )
                except DrainingError as e:
                    # the drain contract: shed-while-draining is visible at
                    # the HTTP layer too (load balancers and dumb probes key
                    # on the status line, not the body) — 503 + typed body
                    payload = e.to_wire()
                    status = 503
                    outcome = "shed"
                except ServeError as e:
                    payload = e.to_wire()
                    outcome = "shed" if e.shed else "error"
                except Exception as e:
                    payload = {"code": 1, "info": repr(e)}
                    outcome = "error"
                if ctx is not None and isinstance(payload, dict):
                    payload.setdefault("trace_id", ctx["trace_id"])
                data = json.dumps(payload, default=str).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                if ctx is not None:
                    # echo the joined context so HTTP callers can correlate
                    self.send_header("traceparent", format_traceparent(ctx))
                    finish_trace(ctx, "http_done", outcome=outcome)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ServeHTTPServer":
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        # reap the serve loop before closing its socket under it
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self._server.server_close()
