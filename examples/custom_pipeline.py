"""A runnable custom pipeline (role of the reference's agent template,
distar/agent/template/agent.py): copy this file anywhere on sys.path,
rename it, and select it by module name.

Try it (no game needed):

    # the learner: a subclass that logs through the standard stack
    PYTHONPATH=examples python -m distar_tpu.bin.sl_train \
        --platform cpu --iters 2 --pipeline custom_pipeline

    # the agent: plays side 1 of a league job (docs/agent_contract.md)
    #   league config:  pipeline: [custom_pipeline]
    #   or a job dict:  {"pipelines": ["default", "custom_pipeline"]}

Custom agents OWN their inference (distar_tpu/plugins.py): ``act`` may
run its own jitted model, a policy table, or a remote call — the Actor
gives it no inference slot, teacher, or trajectory assembly.
"""
from __future__ import annotations

import numpy as np

from distar_tpu.actor.scripted import ScriptedAgent
from distar_tpu.learner import RLLearner as _RLLearner
from distar_tpu.learner import SLLearner as _SLLearner
from distar_tpu.lib.actions import ACTIONS, TARGET_LOCATION_MASK


class Agent(ScriptedAgent):
    """Attack-move toward the map centre every few decisions, else no-op.

    Demonstrates the contract surface: read the feature-level obs, emit a
    structurally valid action dict (per-head applicability comes from the
    ACTIONS table).
    """

    HAS_MODEL = False

    _ATTACK = next(
        i for i, a in enumerate(ACTIONS) if a["name"] == "Attack_pt" and TARGET_LOCATION_MASK[i]
    )

    def act(self, obs: dict) -> dict:
        n = int(np.asarray(obs["entity_num"]))
        if self._steps % 4 == 0 and n > 0:
            return {
                "action_type": self._ATTACK,
                "delay": 8,
                "queued": 0,
                "selected_units": list(range(min(n, 8))),
                "target_unit": 0,
                "target_location": 76 * 160 + 80,  # map centre (y*W + x)
            }
        return self._noop()  # the base class's structurally valid no-op


class SLLearner(_SLLearner):
    """Example learner override: everything inherited; hook your own loss,
    dataloader, or logging here."""


class RLLearner(_RLLearner):
    """Same for RL — `rl_train --pipeline custom_pipeline` builds this."""
