#!/usr/bin/env python
"""perf_gate: performance regression gate + bench-trajectory aggregator.

Two commands, both consuming the JSON artifacts bench.py / obs.traceview
already emit (nothing here measures — this is the layer that finally READS
the `BENCH_*`/`MULTICHIP_*` files every round produces):

  check       compare a fresh artifact against a committed baseline with a
              noise tolerance. The impossible-timing recheck is a HARD
              precondition: a candidate whose own flop counts say its
              timing beats 1.1x the chip's datasheet peak — or that carries
              an in-band ``suspect``/``suspect_timing`` flag — fails the
              gate no matter how good the comparison looks (no number
              enters README/PERF without passing it; ROADMAP item 5).
              Exit 0 pass / 1 regression / 2 precondition failed.

  trajectory  aggregate the round-over-round artifacts (BENCH_r*.json,
              BENCH_LOCAL_*.json, MULTICHIP_r*.json, ROLLOUT_r*.json,
              artifacts/*_r*.json) into a markdown table, optionally
              rewritten in place between the PERF.md trajectory markers.

  scaling     sweep every committed artifact for forged scaling claims: an
              artifact may say ``scaling_valid: true`` ONLY with recorded
              ``host_cores >= 2`` AND the pinning provenance block the
              tools/pin.py harness writes (``pinning: {pinned: true, ...}``
              — each fleet process on its own core). Anything else —
              including a hand-forged single-core "true" — is refused exit
              2, the same hard-fail class as the impossible-timing recheck.
              The scaling gate is ALSO a ``check`` precondition.

Usage:
  python tools/perf_gate.py check --baseline artifacts/perf_baseline_cpu.json \\
         --candidate fresh.json [--tolerance 0.5]
  python tools/perf_gate.py trajectory [--write PERF.md]
  python tools/perf_gate.py scaling [--artifact one.json]
  python tools/perf_gate.py curve [--tolerance 0.10] [--json]
"""
from __future__ import annotations

import argparse
import glob
import json
import math
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from distar_tpu.obs.perf import peak_flops  # noqa: E402

TRAJ_BEGIN = "<!-- perf-trajectory:begin -->"
TRAJ_END = "<!-- perf-trajectory:end -->"


# ------------------------------------------------------------------- loading
def load_artifact(path: str) -> dict:
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except ValueError:
        # bench-driver JSON-LINES artifact (loadgen/trace_overhead
        # convention: one row per line, the LAST line is the summary) —
        # these used to be skipped silently, which kept e.g. FLEET_r12 out
        # of the trajectory and the scaling sweep
        docs = []
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            docs.append(json.loads(line))
        if not docs:
            raise
        doc = docs[-1]
    # driver wrapper format {n, cmd, rc, tail, parsed} -> the parsed result
    if isinstance(doc, dict) and "parsed" in doc and "tail" in doc:
        return {"_wrapper": doc, **(doc.get("parsed") or {})}
    return doc


def _points(artifact: dict) -> Dict[Tuple, dict]:
    """Comparable sweep points keyed by (kind, batch, unroll, cap, remat).
    Headline-only artifacts key a single ('headline',) point."""
    out: Dict[Tuple, dict] = {}
    for kind in ("sl", "rl", "sl_real"):
        for p in artifact.get(f"{kind}_sweep", []) or []:
            if "step_time_s" not in p and "frames_per_sec" not in p:
                continue  # errored sweep entry
            key = (kind, p.get("batch"), p.get("unroll"),
                   p.get("max_entities"), bool(p.get("remat")))
            out[key] = p
    if not out and isinstance(artifact.get("value"), (int, float)) \
            and artifact.get("value"):
        out[("headline", None, None, None, False)] = {
            "frames_per_sec": artifact["value"], "unit": artifact.get("unit"),
        }
    return out


# --------------------------------------------------------- the physics check
def impossible_timing(artifact: dict) -> List[str]:
    """Re-run bench.py's impossible-timing recheck over an artifact: any
    point whose max(flops_unoptimized, flops_optimized)/step_time exceeds
    1.1x the named device's datasheet peak is physically impossible. Points
    already flagged in-band (suspect / suspect_timing) count too. Returns
    the list of offences (empty = clean)."""
    offences: List[str] = []
    peak = peak_flops(str(artifact.get("device", "")))
    if artifact.get("suspect") or artifact.get("suspect_timing"):
        offences.append(
            f"artifact flags itself suspect: "
            f"{artifact.get('suspect_reason', 'suspect_timing set')!r}"
        )
    for key, p in _points(artifact).items():
        if p.get("suspect_timing"):
            offences.append(f"{key}: suspect_timing set by the bench recheck")
            continue
        step = p.get("step_time_s")
        flops = max(
            float(p.get("flops_unoptimized", 0.0) or 0.0),
            float(p.get("flops_optimized", 0.0) or 0.0),
            float(p.get("flops_per_step", 0.0) or 0.0),
        )
        if peak and step and flops and flops / step > 1.1 * peak:
            offences.append(
                f"{key}: {flops / step / 1e12:.1f} TFLOP/s implied > 1.1x "
                f"{peak / 1e12:.0f} TFLOP/s peak ({artifact.get('device')})"
            )
    return offences


# -------------------------------------------------------- the scaling check
def scaling_offences(artifact: dict) -> List[str]:
    """Forged-scaling-claim check: ``scaling_valid: true`` is a PHYSICAL
    claim — N fleet processes each held their own core — so it requires (a)
    recorded ``host_cores >= 2`` and (b) the pinning provenance block the
    tools/pin.py harness writes (``pinning.pinned == true`` with its own
    ``host_cores >= 2`` and per-process assignments). A single-core host, a
    refused plan, or a missing block all keep the honest default
    ``scaling_valid: false`` — claiming otherwise is an offence. Artifacts
    that don't claim scaling (false/absent) are always clean."""
    if not artifact.get("scaling_valid"):
        return []
    offences: List[str] = []
    cores = artifact.get("host_cores")
    if not isinstance(cores, int) or cores < 2:
        offences.append(
            f"scaling_valid: true with host_cores={cores!r} — a fleet "
            "cannot scale onto fewer than 2 cores")
    pin = artifact.get("pinning")
    if not isinstance(pin, dict):
        offences.append(
            "scaling_valid: true without a pinning provenance block "
            "(run the fleet under the tools/pin.py harness)")
        return offences
    if not pin.get("pinned"):
        offences.append(
            "scaling_valid: true but pinning.pinned is false "
            f"({pin.get('refused_reason', 'no reason recorded')!r})")
    pin_cores = pin.get("host_cores")
    if not isinstance(pin_cores, int) or pin_cores < 2:
        offences.append(
            f"scaling_valid: true but the pinning block saw "
            f"host_cores={pin_cores!r}")
    if pin.get("pinned") and not pin.get("assignments"):
        offences.append(
            "pinning.pinned is true but no per-process assignments were "
            "recorded")
    return offences


def scaling_sweep(repo: str = _REPO) -> List[Tuple[str, List[str]]]:
    """Every committed artifact with scaling offences: the tier-1 sweep
    (tests/test_perf_gate.py) keeps a forged row from ever landing."""
    paths = sorted(
        glob.glob(os.path.join(repo, "*_r*.json"))
        + glob.glob(os.path.join(repo, "artifacts", "*.json")))
    out: List[Tuple[str, List[str]]] = []
    for path in paths:
        try:
            doc = load_artifact(path)
        except (OSError, ValueError):
            continue
        offences = scaling_offences(doc)
        if offences:
            out.append((path, offences))
    return out


def cmd_scaling(args) -> int:
    if args.artifact:
        offences = scaling_offences(load_artifact(args.artifact))
        hits = [(args.artifact, offences)] if offences else []
        swept = 1
    else:
        hits = scaling_sweep()
        swept = len(glob.glob(os.path.join(_REPO, "*_r*.json"))
                    + glob.glob(os.path.join(_REPO, "artifacts", "*.json")))
    for path, offences in hits:
        for o in offences:
            print(f"FORGED SCALING CLAIM: {os.path.relpath(path, _REPO)}: {o}")
    if hits:
        print("perf_gate scaling: FAIL")
        return 2
    print(f"perf_gate scaling: PASS ({swept} artifacts swept)")
    return 0


# ------------------------------------------------------------------ checking
def compare(baseline: dict, candidate: dict, tolerance: float) -> Tuple[List[str], List[str]]:
    """(regressions, notes). A config regresses when its step time grew (or
    its throughput shrank) by more than ``tolerance`` (0.5 = 50%) over the
    baseline; configs missing from the candidate are notes, not failures
    (budget-truncated sweeps are normal)."""
    regressions: List[str] = []
    notes: List[str] = []
    base_pts, cand_pts = _points(baseline), _points(candidate)
    if not base_pts:
        notes.append("baseline has no comparable points")
    compared = 0
    for key, bp in sorted(base_pts.items(), key=str):
        cp = cand_pts.get(key)
        if cp is None:
            notes.append(f"{key}: missing from candidate (sweep truncated?)")
            continue
        compared += 1
        bs, cs = bp.get("step_time_s"), cp.get("step_time_s")
        if bs and cs and cs > bs * (1.0 + tolerance):
            regressions.append(
                f"{key}: step_time {cs:.4f}s vs baseline {bs:.4f}s "
                f"(+{(cs / bs - 1) * 100:.0f}% > {tolerance * 100:.0f}% tolerance)"
            )
            continue
        bf, cf = bp.get("frames_per_sec"), cp.get("frames_per_sec")
        if bf and cf and cf < bf / (1.0 + tolerance):
            regressions.append(
                f"{key}: {cf:.2f} frames/s vs baseline {bf:.2f} "
                f"(-{(1 - cf / bf) * 100:.0f}% > {tolerance * 100:.0f}% tolerance)"
            )
    # traceview reports compare on device step time
    b_step, c_step = (a.get("step_time_device_us") for a in (baseline, candidate))
    if b_step and c_step:
        compared += 1
        if c_step > b_step * (1.0 + tolerance):
            regressions.append(
                f"trace device step: {c_step:.0f}us vs baseline {b_step:.0f}us"
            )
    if not compared:
        regressions.append("no comparable points between baseline and candidate")
    return regressions, notes


def cmd_check(args) -> int:
    baseline = load_artifact(args.baseline)
    candidate = load_artifact(args.candidate)
    offences = impossible_timing(candidate)
    offences += [f"scaling: {o}" for o in scaling_offences(candidate)]
    if offences:
        for o in offences:
            print(f"PRECONDITION: {o}")
        print("perf_gate: FAIL (impossible-timing/scaling precondition)")
        return 2
    regressions, notes = compare(baseline, candidate, args.tolerance)
    for n in notes:
        print(f"note: {n}")
    for r in regressions:
        print(f"REGRESSION: {r}")
    if regressions:
        print("perf_gate: FAIL")
        return 1
    print(f"perf_gate: PASS (tolerance {args.tolerance * 100:.0f}%)")
    return 0


# ---------------------------------------------------------------- trajectory
def _round_of(path: str) -> str:
    m = re.search(r"_r(\d+)", os.path.basename(path))
    return m.group(1).lstrip("0") or "0" if m else "?"


def _status_of(artifact: dict) -> str:
    if artifact.get("suspect") or artifact.get("suspect_timing"):
        return "SUSPECT (in-band flag)"
    if impossible_timing(artifact):
        return "SUSPECT (impossible timing)"
    if scaling_offences(artifact):
        return "SUSPECT (unproven scaling claim)"
    if artifact.get("metric") is None:  # wrapper with no parsed result line
        return "no result"
    err = artifact.get("error")
    if err:
        return "no result"
    value = artifact.get("value")
    if isinstance(value, (int, float)) and value == 0.0:
        return "no result"
    vs = artifact.get("vs_baseline")
    if isinstance(vs, (int, float)) and vs > 20.0:
        # the b6x64 "109x" class: physically incoherent vs the reference
        # baseline but carrying no flop counts to prove it in-band
        return "SUSPECT (>20x baseline, unverifiable)"
    if artifact.get("device", "").lower().startswith("tpu"):
        return "ok (on-silicon)"
    return "ok (CPU-derived)"


def _multichip_row(path: str, doc: dict) -> Optional[dict]:
    if "multichip" in doc:  # executed-GSPMD scaling case (round 6+)
        return {
            "round": _round_of(path), "artifact": os.path.basename(path),
            "metric": "dp scaling efficiency", "value": doc.get("value"),
            "unit": doc.get("unit", ""), "status": _status_of(doc),
        }
    if "ok" in doc:  # dryrun wrapper format (rounds 1-5)
        return {
            "round": _round_of(path), "artifact": os.path.basename(path),
            "metric": "multichip dryrun", "value": 1.0 if doc.get("ok") else 0.0,
            "unit": f"ok @ {doc.get('n_devices', '?')} devices",
            "status": "ok (structural)" if doc.get("ok") else "no result",
        }
    return None


def collect_trajectory(repo: str = _REPO) -> List[dict]:
    rows: List[dict] = []
    for path in sorted(glob.glob(os.path.join(repo, "BENCH_r*.json"))
                       + glob.glob(os.path.join(repo, "BENCH_LOCAL_r*.json"))
                       + glob.glob(os.path.join(repo, "ROLLOUT_r*.json"))
                       + glob.glob(os.path.join(repo, "REPLAY_SHARD_r*.json"))
                       + glob.glob(os.path.join(repo, "FLEET_r*.json"))
                       + glob.glob(os.path.join(repo, "SHM_r*.json"))
                       + glob.glob(os.path.join(repo, "TRACE_r*.json"))
                       + glob.glob(os.path.join(repo, "DISTILL_r*.json"))
                       + glob.glob(os.path.join(repo, "DYNAMICS_r*.json"))
                       + glob.glob(os.path.join(repo, "ANAKIN_r*.json"))
                       + glob.glob(os.path.join(repo, "ARENA_r*.json"))
                       + glob.glob(os.path.join(repo, "artifacts", "perf_baseline*.json"))
                       + glob.glob(os.path.join(repo, "artifacts", "dynamics_*.json"))
                       + glob.glob(os.path.join(repo, "artifacts", "curves_r*.json"))
                       + glob.glob(os.path.join(repo, "artifacts", "rollout_*.json"))
                       + glob.glob(os.path.join(repo, "artifacts", "replay_*.json"))
                       + glob.glob(os.path.join(repo, "artifacts", "fleet_*.json"))
                       + glob.glob(os.path.join(repo, "artifacts", "shm_*.json"))
                       + glob.glob(os.path.join(repo, "artifacts", "trace_*.json"))
                       + glob.glob(os.path.join(repo, "artifacts", "distill_*.json"))
                       + glob.glob(os.path.join(repo, "artifacts", "anakin_*.json"))
                       + glob.glob(os.path.join(repo, "artifacts", "arena_*.json"))):
        try:
            doc = load_artifact(path)
        except (OSError, ValueError):
            continue
        rows.append({
            "round": _round_of(path), "artifact": os.path.basename(path),
            "metric": doc.get("metric", "?"), "value": doc.get("value"),
            "unit": doc.get("unit", ""), "status": _status_of(doc),
        })
        curve = doc.get("fleet_curve") or []
        if curve:
            # the serve-fleet artifact carries the capacity sweep in-band;
            # surface the at-capacity shed knee as its own trajectory row
            knee = max(curve, key=lambda r: r.get("level", 0))
            rows.append({
                "round": _round_of(path), "artifact": os.path.basename(path),
                "metric": (f"fleet session shed rate at "
                           f"{knee.get('level')} offered sessions "
                           f"({doc.get('gateways')} gateways)"),
                "value": knee.get("session_shed_rate"), "unit": "",
                "status": _status_of(doc),
            })
        if doc.get("shm_vs_tcp"):
            # the shm-transport artifact carries the three-way ratios
            # in-band; surface wall AND cpu ratios as trajectory rows
            rows.append({
                "round": _round_of(path), "artifact": os.path.basename(path),
                "metric": "shm ring vs framed-TCP loopback, real subprocesses "
                          "(wall clock)",
                "value": doc["shm_vs_tcp"], "unit": "x",
                "status": _status_of(doc),
            })
            if doc.get("shm_vs_tcp_cpu"):
                rows.append({
                    "round": _round_of(path),
                    "artifact": os.path.basename(path),
                    "metric": "shm ring vs framed-TCP loopback "
                              "(cpu-seconds per item, core-count independent)",
                    "value": doc["shm_vs_tcp_cpu"], "unit": "x",
                    "status": _status_of(doc),
                })
        if doc.get("envelope_pct") is not None:
            # a paired on/off overhead artifact (tracing r13, dynamics r16:
            # ab_label says which subsystem was A/B'd): surface the verdict
            # as its own row (the off arm is the comparison baseline)
            rows.append({
                "round": _round_of(path), "artifact": os.path.basename(path),
                "metric": f"{doc.get('ab_label', 'tracing')} on-vs-off "
                          "within the stated "
                          f"{doc.get('envelope_pct'):g}% envelope",
                "value": 1.0 if doc.get("within_envelope") else 0.0,
                "unit": "bool",
                "status": _status_of(doc),
            })
        for family, curve in sorted((doc.get("curves") or {}).items()):
            values = (curve or {}).get("values") or []
            if len(values) >= 2:
                # a committed learning-curve artifact: surface each family's
                # first->last descent; `perf_gate curve` gates it across
                # rounds
                rows.append({
                    "round": _round_of(path),
                    "artifact": os.path.basename(path),
                    "metric": (f"toy-run {family} {values[0]:g} -> "
                               f"{values[-1]:g} over {len(values)} points"),
                    "value": values[-1], "unit": "loss",
                    "status": _status_of(doc),
                })
        toy = (doc.get("distill") or {}).get("toy_run") or {}
        if toy.get("kl_first") is not None:
            # the distill artifact carries the toy-run KL curve in-band;
            # surface the convergence verdict as its own trajectory row
            rows.append({
                "round": _round_of(path), "artifact": os.path.basename(path),
                "metric": (f"distill toy-run KL {toy['kl_first']:g} -> "
                           f"{toy['kl_last']:g} over {toy.get('iters')} iters "
                           f"(monotone={bool(toy.get('monotone_decrease'))})"),
                "value": toy["kl_last"], "unit": "KL",
                "status": _status_of(doc),
            })
        anakin = doc.get("anakin") or {}
        if anakin.get("fused_vs_actor") or anakin.get("fused_vs_host"):
            # the anakin artifact carries both A/Bs in-band; headline the
            # real mock-env actor path (the ROADMAP baseline) and keep the
            # charitable tight-loop floor in the label
            baseline = ("mock-env actor path" if anakin.get("fused_vs_actor")
                        else "one-lane host loop")
            rows.append({
                "round": _round_of(path), "artifact": os.path.basename(path),
                "metric": (f"anakin fused scan vs {baseline}, same policy "
                           f"({anakin.get('batch_lanes')} lanes; "
                           f"tight-loop floor {anakin.get('fused_vs_host')}x; "
                           f"device_pure={bool(anakin.get('device_pure'))})"),
                "value": anakin.get("fused_vs_actor")
                or anakin["fused_vs_host"], "unit": "x",
                "status": _status_of(doc),
            })
        arena = doc.get("arena") or {}
        if arena.get("anchor_relative") is not None:
            # the arena artifact carries the skill ledger in-band; surface
            # the newest generation's anchor-relative rating as its own
            # trajectory row (`perf_gate skill` gates it across rounds)
            rows.append({
                "round": _round_of(path), "artifact": os.path.basename(path),
                "metric": (f"arena anchor-relative rating of newest "
                           f"generation ({arena.get('player', '?')}; "
                           f"{arena.get('matches', '?')} matches vs "
                           f"{arena.get('anchor', 'anchors')})"),
                "value": arena["anchor_relative"], "unit": "elo",
                "status": _status_of(doc),
            })
        fast = doc.get("replay_fast_path") or {}
        if fast.get("vs_tcp_loopback"):
            # the sharded-replay artifact carries the colocated fast-path
            # A/B in-band; surface it as its own trajectory row
            rows.append({
                "round": _round_of(path), "artifact": os.path.basename(path),
                "metric": "replay colocated fast path vs framed-TCP loopback",
                "value": fast["vs_tcp_loopback"], "unit": "x",
                "status": _status_of(doc),
            })
    for path in sorted(glob.glob(os.path.join(repo, "MULTICHIP_r*.json"))
                       + glob.glob(os.path.join(repo, "artifacts", "multichip_*.json"))):
        try:
            doc = load_artifact(path)
        except (OSError, ValueError):
            continue
        row = _multichip_row(path, doc)
        if row:
            rows.append(row)
    rows.sort(key=lambda r: (r["round"].zfill(3), r["artifact"]))
    return rows


def render_trajectory(rows: List[dict]) -> str:
    lines = [
        "| round | artifact | metric | value | unit | status |",
        "|---|---|---|---|---|---|",
    ]
    for r in rows:
        value = r["value"]
        value = f"{value:.3g}" if isinstance(value, (int, float)) else (value or "—")
        lines.append(
            f"| {r['round']} | `{r['artifact']}` | {r['metric']} "
            f"| {value} | {r['unit']} | {r['status']} |"
        )
    return "\n".join(lines)


def collect_curves(repo: str = _REPO) -> Dict[str, List[dict]]:
    """Committed toy-run learning curves by family, one entry per round:
    ``sl_total_loss``/``rl_total_loss`` (and anything else a round adds)
    from ``artifacts/curves_r*.json`` ``curves.<family>.values``, plus
    ``distill_kl`` from the DISTILL artifacts' in-band ``kl_curve``."""
    fams: Dict[str, List[dict]] = {}

    def add(family, path, values):
        values = [float(v) for v in values]
        if len(values) >= 2:
            fams.setdefault(family, []).append({
                "round": _round_of(path),
                "artifact": os.path.basename(path),
                "values": values,
            })

    for path in sorted(glob.glob(os.path.join(repo, "artifacts",
                                              "curves_r*.json"))):
        try:
            doc = load_artifact(path)
        except (OSError, ValueError):
            continue
        for family, curve in (doc.get("curves") or {}).items():
            add(family, path, (curve or {}).get("values") or [])
    for path in sorted(glob.glob(os.path.join(repo, "DISTILL_r*.json"))):
        try:
            doc = load_artifact(path)
        except (OSError, ValueError):
            continue
        toy = (doc.get("distill") or {}).get("toy_run") or {}
        add("distill_kl", path, toy.get("kl_curve") or [])
    for entries in fams.values():
        entries.sort(key=lambda e: (e["round"].zfill(3), e["artifact"]))
    return fams


def curve_verdicts(fams: Dict[str, List[dict]],
                   tolerance: float) -> Tuple[List[dict], List[str]]:
    """Per-family learning-curve gate. Each committed curve must be a real
    descent (finite, last < first); across rounds the NEWEST round's final
    value may not regress past the previous round's final value by more
    than ``tolerance`` (relative, sign-safe for negative RL losses). A
    family with a single round is its own baseline — PASS."""
    verdicts, failures = [], []
    for family, entries in sorted(fams.items()):
        for e in entries:
            values = e["values"]
            if not all(math.isfinite(v) for v in values):
                failures.append(f"{family}: non-finite values in "
                                f"{e['artifact']}")
            elif values[-1] >= values[0]:
                failures.append(
                    f"{family}: curve in {e['artifact']} does not descend "
                    f"({values[0]:g} -> {values[-1]:g})")
        verdict = {
            "family": family,
            "rounds": [e["round"] for e in entries],
            "first": entries[0]["values"][0],
            "last": entries[-1]["values"][-1],
        }
        if len(entries) >= 2:
            base, cand = entries[-2], entries[-1]
            base_last, cand_last = base["values"][-1], cand["values"][-1]
            allowed = base_last + tolerance * max(abs(base_last), 1e-9)
            verdict.update({
                "baseline_round": base["round"], "baseline_last": base_last,
                "candidate_round": cand["round"], "candidate_last": cand_last,
                "allowed": allowed,
                "regressed": cand_last > allowed,
            })
            if cand_last > allowed:
                failures.append(
                    f"{family}: round {cand['round']} final {cand_last:g} "
                    f"regressed past round {base['round']}'s {base_last:g} "
                    f"(allowed {allowed:g} at tolerance {tolerance:g})")
        else:
            verdict["regressed"] = False
            verdict["note"] = "single round: baseline PASS"
        verdicts.append(verdict)
    return verdicts, failures


def cmd_curve(args) -> int:
    fams = collect_curves()
    if not fams:
        print("no committed learning-curve artifacts "
              "(artifacts/curves_r*.json, DISTILL_r*.json)")
        return 1
    verdicts, failures = curve_verdicts(fams, args.tolerance)
    if args.json:
        print(json.dumps({"verdicts": verdicts, "failures": failures},
                         indent=1))
    else:
        for v in verdicts:
            if "candidate_last" in v:
                line = (f"{v['family']}: r{v['baseline_round']} "
                        f"{v['baseline_last']:g} -> r{v['candidate_round']} "
                        f"{v['candidate_last']:g} (allowed {v['allowed']:g})")
            else:
                line = (f"{v['family']}: {v['first']:g} -> {v['last']:g} "
                        f"({v.get('note', '')})")
            print(f"  {'REGRESSED' if v.get('regressed') else 'ok':<10} {line}")
        for f in failures:
            print(f"  FAIL: {f}")
    print("curve gate: PASS" if not failures
          else f"curve gate: FAIL ({len(failures)} offence(s))")
    return 0 if not failures else 1


def collect_skill(repo: str = _REPO) -> List[dict]:
    """Committed arena skill ledgers, one entry per round: the newest
    generation's anchor-relative ELO from ``ARENA_r*.json`` /
    ``artifacts/arena_*.json`` in-band ``arena`` blocks."""
    entries: List[dict] = []
    for path in sorted(glob.glob(os.path.join(repo, "ARENA_r*.json"))
                       + glob.glob(os.path.join(repo, "artifacts",
                                                "arena_*.json"))):
        try:
            doc = load_artifact(path)
        except (OSError, ValueError):
            continue
        arena = doc.get("arena") or {}
        value = arena.get("anchor_relative")
        if value is None:
            continue
        entries.append({
            "round": _round_of(path), "artifact": os.path.basename(path),
            "player": arena.get("player", "?"),
            "matches": arena.get("matches"),
            "value": float(value),
        })
    entries.sort(key=lambda e: (e["round"].zfill(3), e["artifact"]))
    return entries


def skill_verdicts(entries: List[dict],
                   tolerance: float) -> Tuple[List[dict], List[str]]:
    """The skill gate, round-over-round like ``curve``: the NEWEST round's
    anchor-relative rating may not fall more than ``tolerance`` ELO points
    below the previous round's. A single round is its own baseline — PASS.
    Non-finite ratings always fail."""
    failures: List[str] = []
    for e in entries:
        if not math.isfinite(e["value"]):
            failures.append(f"non-finite anchor-relative rating in "
                            f"{e['artifact']}")
    verdicts: List[dict] = []
    if entries:
        verdict = {
            "rounds": [e["round"] for e in entries],
            "first": entries[0]["value"],
            "last": entries[-1]["value"],
            "player": entries[-1]["player"],
        }
        if len(entries) >= 2:
            base, cand = entries[-2], entries[-1]
            allowed = base["value"] - tolerance
            verdict.update({
                "baseline_round": base["round"],
                "baseline_value": base["value"],
                "candidate_round": cand["round"],
                "candidate_value": cand["value"],
                "allowed": allowed,
                "regressed": cand["value"] < allowed,
            })
            if cand["value"] < allowed:
                failures.append(
                    f"skill: round {cand['round']} anchor-relative rating "
                    f"{cand['value']:g} regressed past round "
                    f"{base['round']}'s {base['value']:g} "
                    f"(allowed {allowed:g} at tolerance {tolerance:g} elo)")
        else:
            verdict["regressed"] = False
            verdict["note"] = "single round: baseline PASS"
        verdicts.append(verdict)
    return verdicts, failures


def cmd_skill(args) -> int:
    repo = getattr(args, "repo", "") or _REPO
    entries = collect_skill(repo)
    if not entries:
        print("no committed arena skill ledgers "
              "(ARENA_r*.json, artifacts/arena_*.json)")
        return 1
    verdicts, failures = skill_verdicts(entries, args.tolerance)
    if args.json:
        print(json.dumps({"entries": entries, "verdicts": verdicts,
                          "failures": failures}, indent=1))
    else:
        for e in entries:
            print(f"  r{e['round']:<4} {e['artifact']:<24} "
                  f"{e['player']:<16} anchor-relative={e['value']:g}")
        for v in verdicts:
            if "candidate_value" in v:
                print(f"  gate: r{v['baseline_round']} "
                      f"{v['baseline_value']:g} -> r{v['candidate_round']} "
                      f"{v['candidate_value']:g} (allowed {v['allowed']:g})"
                      f"{'  REGRESSED' if v['regressed'] else ''}")
            else:
                print(f"  gate: {v.get('note', '')}")
        for f in failures:
            print(f"  FAIL: {f}")
    print("skill gate: PASS" if not failures
          else f"skill gate: FAIL ({len(failures)} offence(s))")
    return 0 if not failures else 1


def cmd_trajectory(args) -> int:
    rows = collect_trajectory()
    table = render_trajectory(rows)
    if not args.write:
        print(table)
        return 0
    with open(args.write) as f:
        text = f.read()
    block = f"{TRAJ_BEGIN}\n{table}\n{TRAJ_END}"
    if TRAJ_BEGIN in text and TRAJ_END in text:
        pre, rest = text.split(TRAJ_BEGIN, 1)
        _, post = rest.split(TRAJ_END, 1)
        text = pre + block + post
    else:
        text = text.rstrip() + (
            "\n\n## Bench trajectory (artifact-derived, via tools/perf_gate.py)\n\n"
            f"{block}\n"
        )
    with open(args.write, "w") as f:
        f.write(text)
    print(f"wrote {len(rows)} trajectory rows into {args.write}")
    return 0


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = p.add_subparsers(dest="command", required=True)
    pc = sub.add_parser("check", help="gate a fresh artifact against a baseline")
    pc.add_argument("--baseline", required=True)
    pc.add_argument("--candidate", required=True)
    pc.add_argument("--tolerance", type=float, default=0.5,
                    help="allowed fractional slowdown before failing "
                         "(0.5 = step time may grow 50%%; CPU-noise sized)")
    pt = sub.add_parser("trajectory", help="round-over-round artifact table")
    pt.add_argument("--write", default="",
                    help="rewrite this file's trajectory block in place "
                         "(e.g. PERF.md); default prints to stdout")
    ps = sub.add_parser("scaling",
                        help="refuse forged scaling_valid claims (exit 2)")
    ps.add_argument("--artifact", default="",
                    help="check one artifact instead of sweeping the repo")
    pu = sub.add_parser("curve",
                        help="learning-curve gate: committed toy-run curves "
                             "must descend and not regress round-over-round")
    pu.add_argument("--tolerance", type=float, default=0.10,
                    help="allowed relative regression of the newest round's "
                         "final value vs the previous round's (default 10%%)")
    pu.add_argument("--json", action="store_true",
                    help="print verdicts as one JSON object")
    pk = sub.add_parser("skill",
                        help="arena skill gate: the newest generation's "
                             "anchor-relative rating must not regress "
                             "round-over-round")
    pk.add_argument("--tolerance", type=float, default=50.0,
                    help="allowed anchor-relative ELO drop vs the previous "
                         "round (default 50 points — jaxenv scenario noise)")
    pk.add_argument("--repo", default="",
                    help="sweep this tree instead of the repo root "
                         "(hermetic tests)")
    pk.add_argument("--json", action="store_true",
                    help="print entries/verdicts as one JSON object")
    args = p.parse_args()
    return {"check": cmd_check, "trajectory": cmd_trajectory,
            "scaling": cmd_scaling, "curve": cmd_curve,
            "skill": cmd_skill}[args.command](args)


if __name__ == "__main__":
    raise SystemExit(main())
