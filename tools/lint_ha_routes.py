"""HA-route lint: no coordinator route may silently become volatile.

The durability contract of the HA broker (distar_tpu/comm/ha.py) is a
classification: every route in ``CoordinatorServer.routes`` is either
**journaled** (``JOURNALED_ROUTES`` — written to the WAL before its reply,
replayed on restart, streamed to standbys) or **explicitly ephemeral**
(``EPHEMERAL_ROUTES`` — read-only or lossy-by-design, each with a reason).
This lint reads both sides with ``ast`` (no imports, same shim pattern as
lint_sockets/lint_metric_names) and fails when:

* a route exists in ``CoordinatorServer.routes`` but in neither set — the
  failure a future route (the league's matchmaker) would hit, forcing its
  author to decide durability instead of inheriting volatility;
* a route appears in both sets (contradictory classification);
* the ephemeral allowlist names a route that no longer exists — the list is
  SHRINK-ONLY: stale entries must be deleted, never accumulated;
* ``DURABLE_ROUTES`` isn't a subset of ``JOURNALED_ROUTES``;
* ``ask`` (a queue pop, the one non-idempotent route) ever appears in
  ``IDEMPOTENT_ROUTES`` — retrying a possibly-applied pop double-consumes.

Invoked from the test suite (tests/test_coordinator_ha.py) and runnable
standalone: ``python tools/lint_ha_routes.py``.
"""
from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Set

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

COORDINATOR_PY = os.path.join(_REPO, "distar_tpu", "comm", "coordinator.py")
HA_PY = os.path.join(_REPO, "distar_tpu", "comm", "ha.py")

_SET_NAMES = ("JOURNALED_ROUTES", "EPHEMERAL_ROUTES", "DURABLE_ROUTES",
              "IDEMPOTENT_ROUTES")


def server_routes(path: str = COORDINATOR_PY) -> Set[str]:
    """String keys of the ``routes = {...}`` dict in CoordinatorServer."""
    tree = ast.parse(open(path, encoding="utf-8").read(), filename=path)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "routes"
                and isinstance(node.value, ast.Dict)):
            continue
        keys = set()
        for k in node.value.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                keys.add(k.value)
        return keys
    raise AssertionError(f"no `routes = {{...}}` dict literal found in {path}")


def route_sets(path: str = HA_PY) -> Dict[str, Set[str]]:
    """The classification frozensets from ha.py, read as literals."""
    tree = ast.parse(open(path, encoding="utf-8").read(), filename=path)
    out: Dict[str, Set[str]] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id in _SET_NAMES):
            continue
        value = node.value
        if (isinstance(value, ast.Call) and isinstance(value.func, ast.Name)
                and value.func.id == "frozenset" and value.args):
            value = value.args[0]
        elts = getattr(value, "elts", None)
        if elts is None:
            raise AssertionError(
                f"{node.targets[0].id} in {path} is not a literal set — "
                "the lint (and reviewers) must be able to read it statically")
        out[node.targets[0].id] = {
            e.value for e in elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        }
    missing = [n for n in _SET_NAMES if n not in out]
    assert not missing, f"route sets missing from {path}: {missing}"
    return out


def lint() -> List[str]:
    problems: List[str] = []
    routes = server_routes()
    sets = route_sets()
    journaled, ephemeral = sets["JOURNALED_ROUTES"], sets["EPHEMERAL_ROUTES"]
    for route in sorted(routes - journaled - ephemeral):
        problems.append(
            f"route '{route}' in CoordinatorServer.routes is neither "
            "journaled (ha.JOURNALED_ROUTES) nor explicitly tagged ephemeral "
            "(ha.EPHEMERAL_ROUTES) — unclassified routes are volatile by "
            "accident; decide its durability")
    for route in sorted(journaled & ephemeral):
        problems.append(
            f"route '{route}' is in BOTH JOURNALED_ROUTES and "
            "EPHEMERAL_ROUTES — pick one")
    for route in sorted(ephemeral - routes):
        problems.append(
            f"EPHEMERAL_ROUTES names '{route}' which is not a server route — "
            "the allowlist is shrink-only; delete the stale entry")
    for route in sorted(journaled - routes):
        problems.append(
            f"JOURNALED_ROUTES names '{route}' which is not a server route")
    for route in sorted(sets["DURABLE_ROUTES"] - journaled):
        problems.append(
            f"DURABLE_ROUTES names '{route}' outside JOURNALED_ROUTES — "
            "only journaled records can be fsync'd/replicated")
    if "ask" in sets["IDEMPOTENT_ROUTES"]:
        problems.append(
            "'ask' is a queue POP and must never be in IDEMPOTENT_ROUTES — "
            "retrying a possibly-applied pop consumes a second record")
    return problems


def main() -> int:
    problems = lint()
    for p in problems:
        sys.stderr.write(p + "\n")
    if problems:
        sys.stderr.write(
            f"{len(problems)} offence(s); every coordinator route must be "
            "journaled or explicitly ephemeral (distar_tpu/comm/ha.py)\n")
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
