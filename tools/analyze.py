#!/usr/bin/env python
"""Project-invariant analyzer driver (distar_tpu/analysis framework).

Usage:
    python tools/analyze.py [paths...]           # analyze (default tree)
    python tools/analyze.py --changed            # only `git diff` files
    python tools/analyze.py report [paths...]    # ranked-markdown summary
    python tools/analyze.py --json out.json      # machine-readable report
    python tools/analyze.py --write-baseline     # regenerate the baseline

Default paths: ``distar_tpu tools bench.py``. Exit codes: 0 = clean,
1 = baselined-only (grandfathered debt, nothing new), 2 = new findings or
stale baseline entries (the baseline may only shrink). Tier-1 runs this via
tests/test_analysis.py::test_analysis_repo_clean; ``--changed`` is the fast
pre-commit mode. Rule catalog: docs/analysis.md.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from distar_tpu.analysis import (  # noqa: E402
    Analyzer, collect_files, load_baseline, render_markdown, save_baseline,
)

DEFAULT_PATHS = ("distar_tpu", "tools", "bench.py")
DEFAULT_BASELINE = os.path.join(_REPO, "tools", "analysis_baseline.json")


def _changed_files() -> list:
    """Python files touched per git (staged + unstaged + untracked)."""
    out = subprocess.run(
        ["git", "diff", "--name-only", "HEAD"],
        cwd=_REPO, capture_output=True, text=True, check=False,
    ).stdout
    untracked = subprocess.run(
        ["git", "ls-files", "--others", "--exclude-standard"],
        cwd=_REPO, capture_output=True, text=True, check=False,
    ).stdout
    files = []
    for line in (out + untracked).splitlines():
        line = line.strip()
        # scope --changed to the same tree the full run analyzes: tests and
        # docs change constantly and are not the analyzer's subject
        if not line.endswith(".py") or not os.path.exists(os.path.join(_REPO, line)):
            continue
        if not (line == "bench.py" or line.startswith(("distar_tpu/", "tools/"))):
            continue
        files.append(os.path.join(_REPO, line))
    return sorted(set(files))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("cmd_or_paths", nargs="*",
                        help="'report' or files/dirs to analyze "
                             f"(default: {' '.join(DEFAULT_PATHS)})")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="baseline JSON (default tools/analysis_baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline (every finding is new)")
    parser.add_argument("--changed", action="store_true",
                        help="analyze only files git reports changed (pre-commit mode)")
    parser.add_argument("--rules", default="",
                        help="comma-separated rule ids to restrict to")
    parser.add_argument("--json", dest="json_out", default="",
                        help="write the JSON report here ('-' = stdout)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="regenerate the baseline from current NEW findings "
                             "(then exits 0; review the diff before committing)")
    args = parser.parse_args(argv)

    paths = list(args.cmd_or_paths)
    report_mode = bool(paths) and paths[0] == "report"
    if report_mode:
        paths = paths[1:]
    if not paths:
        paths = list(DEFAULT_PATHS)

    analyzer = Analyzer(
        repo_root=_REPO,
        rules=[r.strip() for r in args.rules.split(",") if r.strip()] or None,
    )
    if args.changed:
        files = _changed_files()
        if not files:
            sys.stdout.write("analyze --changed: no changed python files\n")
            return 0
    else:
        files = collect_files(paths, repo_root=_REPO)
    baseline = [] if args.no_baseline else load_baseline(args.baseline)
    # --changed analyzes a subset, so baseline entries for files outside the
    # subset would all look stale; restrict the stale check to scanned files
    if args.changed and baseline:
        scanned = {os.path.relpath(f, _REPO).replace(os.sep, "/") for f in files}
        baseline = [e for e in baseline if e.get("path") in scanned]
    result = analyzer.run(files, baseline=baseline)

    if args.write_baseline:
        save_baseline(args.baseline, result.findings + result.baselined)
        sys.stdout.write(
            f"wrote {len(result.findings) + len(result.baselined)} entries to "
            f"{args.baseline}\n")
        return 0

    if args.json_out:
        payload = json.dumps(result.to_dict(), indent=1, sort_keys=True)
        if args.json_out == "-":
            sys.stdout.write(payload + "\n")
        else:
            with open(args.json_out, "w") as f:
                f.write(payload + "\n")
    if report_mode:
        sys.stdout.write(render_markdown(result))
    else:
        for f in result.findings:
            sys.stderr.write(str(f) + "\n")
        for e in result.stale_baseline:
            sys.stderr.write(
                f"STALE baseline entry (remove it — shrink-only): "
                f"{e['path']}: {e['rule']}: {e['ident']}\n")
        sys.stderr.write(
            f"analyze: {result.files} files · {len(result.findings)} new · "
            f"{len(result.baselined)} baselined · "
            f"{len(result.suppressed)} pragma-suppressed · "
            f"{len(result.stale_baseline)} stale baseline entries "
            f"(exit {result.exit_code})\n")
    return result.exit_code


if __name__ == "__main__":
    raise SystemExit(main())
