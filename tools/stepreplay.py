#!/usr/bin/env python
"""stepreplay: deterministically re-execute a black-box anomaly bundle.

The training-dynamics observatory (distar_tpu/obs/dynamics.py) answers
"what happened" with a forensic bundle: the offending batch, pre-step aux
(SL hidden carry / RL value-pretrain gate), PRNG seed, step index,
checkpoint pointer, config digest and the diagnostics tree that localized
the first non-finite module. This tool answers "can I hold it in my
hands": it reloads a bundle on any host — no experiment directory, no
replay fleet, no actor — rebuilds the exact learner from the bundle's own
config, restores the captured state, and re-executes that one train step
TWICE:

  python tools/stepreplay.py --bundle exp/blackbox/blackbox_000_step7_grad_nonfinite.bb
  python tools/stepreplay.py --bundle ... --platform cpu --json
  python tools/stepreplay.py --bundle ... --params init   # replay from a
        # fresh PRNG-seeded init instead of the captured state (triage:
        # batch-borne vs state-borne anomalies)

Verdict (exit 0 only when the bundle is a faithful reproduction):

  * ``nonfinite_reproduced`` — the replayed step is non-finite again
    (loss, grad norm, or any census total), required whenever the bundle's
    reasons include a non-finite class;
  * ``provenance_confirmed`` — the census family/module the ORIGINAL run
    blamed is non-finite in the replay too;
  * ``deterministic`` — the two replays are BIT-equal: every logged scalar
    and every post-step state leaf, NaN payloads included.

Honesty note carried from capture: donated buffers mean the bundled state
is one optimizer step PAST the anomaly. Batch-origin anomalies reproduce
regardless (the poison rides the batch); param-origin anomalies reproduce
because the post-step params are already poisoned by the NaN update.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _parse_args(argv=None):
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--bundle", required=True, help="path to a .bb bundle")
    p.add_argument("--platform", default="cpu",
                   help="JAX_PLATFORMS for the replay (default cpu: any "
                        "host can replay a fleet bundle)")
    p.add_argument("--params", choices=("bundle", "init"), default="bundle",
                   help="'bundle': restore the captured (post-anomaly) "
                        "state; 'init': fresh init from the recorded PRNG "
                        "seed — isolates batch-borne anomalies")
    p.add_argument("--runs", type=int, default=2,
                   help="replays to compare for bit-equality (>= 2)")
    p.add_argument("--workdir", default="",
                   help="scratch experiment dir (default: a tempdir)")
    p.add_argument("--json", action="store_true",
                   help="print the verdict as one JSON object")
    return p.parse_args(argv)


def _bits(x) -> bytes:
    """Bit-exact fingerprint of a host scalar/array (NaN payloads count)."""
    import numpy as np

    return np.asarray(x).tobytes()


def _tree_bits(tree) -> "list[tuple[str, bytes]]":
    import jax

    leaves = jax.tree_util.tree_leaves_with_path(tree)
    return [(jax.tree_util.keystr(path), _bits(leaf))
            for path, leaf in leaves if hasattr(leaf, "shape")]


def _nonfinite(log: dict) -> bool:
    import math

    for key in ("total_loss", "grad_norm", "dyn/grad_norm/total"):
        v = log.get(key)
        if v is not None and not math.isfinite(float(v)):
            return True
    for key in ("dyn/nonfinite_grads/total", "dyn/nonfinite_params/total",
                "dyn/nonfinite_batch/total"):
        if float(log.get(key, 0.0) or 0.0) > 0:
            return True
    return False


def replay(bundle: dict, params_from: str = "bundle", runs: int = 2) -> dict:
    """Rebuild the learner from the bundle's config, re-execute the
    captured step ``runs`` times from identical restored state, and return
    the verdict dict. Import-time side effects (jax) happen here, after
    the caller fixed JAX_PLATFORMS."""
    import jax
    import jax.numpy as jnp

    from distar_tpu.learner import DistillLearner, RLLearner, SLLearner
    from distar_tpu.obs.dynamics import config_digest, first_nonfinite

    classes = {"sllearner": SLLearner, "rllearner": RLLearner,
               "distilllearner": DistillLearner}
    cls = classes.get(bundle.get("learner", ""))
    if cls is None:
        raise SystemExit(f"unknown learner role {bundle.get('learner')!r} "
                         f"(know {sorted(classes)})")

    cfg = bundle["config"]
    digest_drift = config_digest(cfg) != bundle.get("config_digest")
    # redirect every filesystem side effect into the scratch dir and keep
    # the replay itself out of the anomaly business (no nested bundles)
    cfg.setdefault("common", {})["save_path"] = os.environ[
        "DISTAR_EXPERIMENTS_ROOT"]
    cfg.setdefault("learner", {}).setdefault("dynamics", {})["blackbox"] = False

    learner = cls(cfg)
    if int(bundle.get("prng_seed", 0)) != learner.init_prng_seed:
        learner.init_prng_seed = int(bundle["prng_seed"])
        learner._setup_state()
    init_state = learner._state

    def place(state):
        """Fresh XLA buffers per run — the step donates params/opt_state,
        so each replay needs its own placement (and device_put of host
        numpy can be zero-copy on CPU, unsafe under donation)."""
        if getattr(learner, "_shardings", None):
            return learner._place_state(state)
        pin = jax.jit(lambda t: jax.tree.map(
            lambda a: a + 0 if hasattr(a, "shape") else a, t))
        return pin(state)

    source = bundle.get("state") if params_from == "bundle" else None
    if params_from == "bundle" and source is None:
        raise SystemExit("bundle carries no state (blackbox_state was off); "
                         "rerun with --params init")
    aux = bundle.get("aux") or {}

    def arm():
        """Reset the learner to the bundle's captured pre-step conditions."""
        if source is not None:
            learner._state = place(source)
        else:
            learner._state = place(jax.device_get(init_state))
        if "hidden_state" in aux and hasattr(learner, "_hidden"):
            learner._hidden = jax.tree.map(jnp.asarray, aux["hidden_state"])
        if "only_update_value" in aux and \
                hasattr(learner, "_remaining_value_pretrain"):
            learner._remaining_value_pretrain = \
                1 if aux["only_update_value"] else 0

    batch = dict(bundle["batch"])
    batch.pop("_on_device", None)  # host copies must re-place on this host

    logs, states = [], []
    for _ in range(max(2, runs)):
        arm()
        log = learner._train(dict(batch))
        logs.append(log)
        states.append(_tree_bits(jax.device_get(  # analysis: allow(jax-device-get-in-loop) — loop is over replay arms (2-3 total), each needs its own post-step state snapshot for the bit-equality verdict
            learner._state)))

    deterministic = all(
        set(log) == set(logs[0])
        and all(_bits(log[k]) == _bits(logs[0][k]) for k in logs[0])
        for log in logs[1:]
    ) and all(s == states[0] for s in states[1:])

    reproduced = _nonfinite(logs[0])
    prov = bundle.get("provenance") or None
    prov_confirmed = None
    if prov:
        replay_prov = first_nonfinite(logs[0])
        key = f"dyn/nonfinite_{prov['origin']}/{prov['module']}"
        prov_confirmed = bool(
            float(logs[0].get(key, 0.0) or 0.0) > 0
            # post-step params are one NaN update past a batch/param poison,
            # so the replay may localize UPSTREAM of the original blame —
            # accept a same-or-narrower origin naming the same module
            or (replay_prov is not None
                and replay_prov["module"] == prov["module"])
        )

    expect_nonfinite = any(
        r in ("loss_nonfinite", "grad_nonfinite")
        for r in bundle.get("reasons", ())
    )
    ok = deterministic and (reproduced or not expect_nonfinite) and \
        prov_confirmed is not False
    return {
        "bundle_step": bundle.get("step"),
        "reasons": bundle.get("reasons"),
        "learner": bundle.get("learner"),
        "params_from": params_from,
        "runs": max(2, runs),
        "config_digest_drift": digest_drift,
        "nonfinite_reproduced": reproduced,
        "nonfinite_expected": expect_nonfinite,
        "provenance_recorded": prov,
        "provenance_confirmed": prov_confirmed,
        "deterministic": deterministic,
        "total_loss": float(logs[0].get("total_loss", float("nan"))),
        "ok": ok,
    }


def main(argv=None) -> int:
    args = _parse_args(argv)
    # fix the backend BEFORE jax import: a fleet bundle (TPU) must replay
    # on a laptop CPU; AOT perf tracing would only add noise to forensics
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
    os.environ.setdefault("DISTAR_PERF_AOT", "0")
    workdir = args.workdir or tempfile.mkdtemp(prefix="stepreplay_")
    os.environ["DISTAR_EXPERIMENTS_ROOT"] = workdir

    from distar_tpu.obs.dynamics import bundle_summary, load_bundle

    bundle = load_bundle(args.bundle)
    if not args.json:
        print(f"bundle: {json.dumps(bundle_summary(bundle), default=str)}")
    verdict = replay(bundle, params_from=args.params, runs=args.runs)
    if args.json:
        print(json.dumps(verdict, default=str))
    else:
        for k, v in verdict.items():
            print(f"  {k}: {v}")
        print("verdict: anomaly reproduced deterministically from the "
              "bundle alone" if verdict["ok"] else "verdict: REPLAY FAILED")
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
