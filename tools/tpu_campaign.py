"""On-chip measurement campaign: everything the round needs from ONE
successful chip claim, in priority order.

  1. bench.py sweep (SL/RL/sl_real)     -> BENCH_LOCAL_r05.json (repo root)
  2. kernel microbench (pallas vs XLA)  -> artifacts/pallas_microbench_tpu.json
  3. full-step attention A/B            -> artifacts/fullstep_ab_tpu.json
  4. jax.profiler trace of the SL step  -> experiments/profile_sl/

Each stage is its own subprocess (a crash in one never loses the others'
results) and everything is skipped if its artifact already exists, so the
campaign is resumable: run it in a loop until the relay frees up.

Usage:  python tools/tpu_campaign.py [--deadline 14400]

Kill-switch: ``touch /tmp/tpu_campaign_stop`` makes the campaign exit 0
immediately (and between chip-holding stages), so ``... && break`` retry
loops stop re-claiming the chip — e.g. before the driver's own bench
window. The file is intentionally persistent: remove it to re-arm.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(cmd, env_extra=None, timeout=3600, log_name="stage"):
    """Run a stage subprocess, polling the stop file so the kill-switch
    halts even a mid-flight chip-holding child within seconds."""
    env = dict(os.environ, **(env_extra or {}))
    print(f"[campaign] {log_name}: {' '.join(cmd)} (timeout {timeout}s)", flush=True)
    t0 = time.time()
    proc = subprocess.Popen(
        cmd, env=env, cwd=REPO, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    # drain pipes on threads: a chatty child must not deadlock the poll loop
    import threading

    chunks = {"out": [], "err": []}

    def _drain(stream, key):
        for line in iter(stream.readline, ""):
            chunks[key].append(line)
        stream.close()

    drains = [
        threading.Thread(target=_drain, args=(proc.stdout, "out"), daemon=True),
        threading.Thread(target=_drain, args=(proc.stderr, "err"), daemon=True),
    ]
    for d in drains:
        d.start()
    stopped = timed_out = False
    while proc.poll() is None:
        if time.time() - t0 > timeout:
            timed_out = True
            proc.kill()
            proc.wait()
            print(f"[campaign] {log_name}: TIMEOUT after {time.time() - t0:.0f}s",
                  flush=True)
            break
        if os.path.exists(STOP_FILE):
            stopped = True
            proc.terminate()  # frees the chip claim; bench traps nothing
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
            print(f"[campaign] {log_name}: stop file present, child terminated",
                  flush=True)
            break
        time.sleep(10)
    for d in drains:
        d.join(timeout=5)
    stdout, stderr = "".join(chunks["out"]), "".join(chunks["err"])
    # only the kill branches are failures: a child that finished cleanly
    # just past the timeout instant keeps its real rc + result
    if stopped or timed_out:
        return None, stdout
    print(
        f"[campaign] {log_name}: rc={proc.returncode} in {time.time() - t0:.0f}s",
        flush=True,
    )
    if proc.returncode != 0:
        print(stderr[-1500:], flush=True)
    return proc.returncode, stdout


def _last_json_line(stdout: str):
    best = None
    for line in stdout.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            d = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(d, dict) and d.get("value"):
            best = d
    return best


def stage_bench(deadline: int) -> bool:
    out_path = os.path.join(REPO, "BENCH_LOCAL_r05.json")
    if bench_complete():
        print("[campaign] bench: artifact exists, skipping", flush=True)
        return True
    rc, stdout = _run(
        [sys.executable, "-u", "bench.py"],
        env_extra={
            "BENCH_DEADLINE": str(deadline),
            "BENCH_ATTEMPT_TIMEOUT": "1200",
        },
        timeout=deadline + 120,
        log_name="bench-sweep",
    )
    best = _last_json_line(stdout or "")
    if best:
        with open(out_path, "w") as f:
            json.dump(best, f, indent=1)
        print(f"[campaign] bench: LANDED {best['value']} {best.get('unit')}", flush=True)
        return True
    print("[campaign] bench: no nonzero result this pass", flush=True)
    return False


def bench_complete() -> bool:
    return os.path.exists(os.path.join(REPO, "BENCH_LOCAL_r05.json"))


def kernels_complete() -> bool:
    return os.path.exists(os.path.join(REPO, "artifacts", "pallas_microbench_tpu.json"))


def stage_kernels() -> bool:
    if kernels_complete():
        return True
    out_path = os.path.join(REPO, "artifacts", "pallas_microbench_tpu.json")
    rc, _ = _run(
        [sys.executable, "tools/bench_kernels.py", "--out", out_path],
        timeout=2400,
        log_name="kernel-microbench",
    )
    return rc == 0 and kernels_complete()


_MEMSTATS_RUNS = (
    ("sl", "6,12,16,32", "memstats_tpu.json"),
    ("rl", "6,12", "memstats_rl_tpu.json"),
)


def memstats_complete() -> bool:
    return all(
        os.path.exists(os.path.join(REPO, "artifacts", fname))
        for _, _, fname in _MEMSTATS_RUNS
    )


def stage_memstats() -> bool:
    """HBM memory_analysis + flop counts + matmul calibration + 16-step
    re-timing per batch size — the b16/b32 (SL) and b12 (RL) cliff
    diagnosis and the MFU numerator (chip held for compiles + ~16
    steps/config; see tools/memstats.py)."""
    for mode, configs, fname in _MEMSTATS_RUNS:
        out_path = os.path.join(REPO, "artifacts", fname)
        if os.path.exists(out_path):
            continue
        _run(
            [sys.executable, "-u", "tools/memstats.py", "--mode", mode,
             "--configs", configs, "--out", out_path],
            timeout=2400,
            log_name=f"memstats-{mode}",
        )
    return memstats_complete()


_AB_CONFIGS = [
    ("xla", {}),
    ("pallas", {"BENCH_ATTN_IMPL": "pallas", "BENCH_SCATTER_IMPL": "pallas"}),
    # MXU one-hot scatter instead of the serial-row-update loop kernel
    ("pallas_onehot", {"BENCH_ATTN_IMPL": "pallas",
                       "BENCH_SCATTER_IMPL": "pallas_onehot"}),
    # pad-to-bucket entity cap (exact below the cap; PERF.md)
    ("e256", {"BENCH_MAX_ENTITIES": "256"}),
    # fuse 8 timesteps per core-LSTM scan iteration (serial-scan overhead A/B)
    ("unroll8", {"BENCH_LSTM_UNROLL": "8"}),
    # time-major LSTM fallback: attributes the layer-major (hoisted
    # projection) win inside the full step
    ("timemajor", {"BENCH_LSTM_LAYER_MAJOR": "0"}),
]


def _load_ab_configs() -> dict:
    """Landed A/B configs; tolerates a missing/truncated artifact."""
    out_path = os.path.join(REPO, "artifacts", "fullstep_ab_tpu.json")
    try:
        with open(out_path) as f:
            return json.load(f).get("configs", {})
    except (FileNotFoundError, json.JSONDecodeError, OSError):
        return {}


def _fullstep_ab_complete() -> bool:
    have = _load_ab_configs()
    return all(name in have for name, _ in _AB_CONFIGS)


def stage_bench_recheck() -> bool:
    """Cross-examine the landed headline against memstats' independent
    16-step re-timing at the same config (sl b6xt64). If they disagree by
    >2x, the landed artifact is set aside as *_suspect.json and the bench
    re-runs — bench.py now re-times physically-impossible points over a
    longer window itself, so the re-land is trustworthy."""
    bench_path = os.path.join(REPO, "BENCH_LOCAL_r05.json")
    mem_path = os.path.join(REPO, "artifacts", "memstats_tpu.json")
    try:
        with open(bench_path) as f:
            bench = json.load(f)
        with open(mem_path) as f:
            mem = json.load(f)
    except (FileNotFoundError, json.JSONDecodeError, OSError):
        return True  # nothing to cross-examine
    sl = bench.get("sl") or {}
    mem_row = next(
        (r for r in mem.get("rows", [])
         if r.get("batch") == sl.get("batch") and r.get("unroll") == sl.get("unroll")
         and "step_time_s" in r),
        None,
    )
    if not mem_row or not sl.get("step_time_s"):
        return True
    ratio = mem_row["step_time_s"] / sl["step_time_s"]
    if 0.5 <= ratio <= 2.0:
        print(f"[campaign] bench-recheck: headline confirmed "
              f"(memstats/bench step-time ratio {ratio:.2f})", flush=True)
        return True
    print(f"[campaign] bench-recheck: DISAGREEMENT x{ratio:.1f} — setting the "
          f"landed artifact aside and re-running the sweep", flush=True)
    os.replace(bench_path, bench_path.replace(".json", "_suspect.json"))
    return stage_bench(int(os.environ.get("BENCH_RECHECK_DEADLINE", "3600")))


def stage_fullstep_ab() -> bool:
    """A/B the attention/scatter impls inside the full SL step (one modest
    config per impl; compile cache makes reruns cheap)."""
    out_path = os.path.join(REPO, "artifacts", "fullstep_ab_tpu.json")
    # resume: keep landed configs, run only the missing ones (a partial
    # artifact must not permanently skip the remaining comparisons)
    results = _load_ab_configs()
    todo = _AB_CONFIGS
    if all(name in results for name, _ in todo):
        return True
    for name, env_extra in todo:
        if name in results:
            continue
        rc, stdout = _run(
            [sys.executable, "-u", "bench.py", "--run"],
            env_extra={
                "BENCH_MODE": "sl",
                "BENCH_BATCH": "6",
                "BENCH_UNROLL": "64",
                **env_extra,
            },
            timeout=1800,
            log_name=f"fullstep-{name}",
        )
        best = _last_json_line(stdout or "")
        if best:
            results[name] = best.get("sl") or best
    if len(results) >= 2:
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {"metric": "full SL step impl A/B (b6xt64)", "configs": results},
                f,
                indent=1,
            )
        os.replace(tmp, out_path)  # atomic: a kill never leaves a torn file
    done = all(name in results for name, _ in todo)
    if not done:
        print(f"[campaign] fullstep-ab incomplete ({sorted(results)}); will retry", flush=True)
    return done


def profile_complete() -> bool:
    # the trace lands under plugins/profile/<run>/*.xplane.pb — the learner's
    # own logs/ dir existing (or a plugins dir left by a kill mid-export)
    # does NOT mean a trace was captured
    import glob

    return bool(glob.glob(os.path.join(
        REPO, "experiments", "profile_sl", "plugins", "profile", "*", "*.xplane.pb")))


def stage_profile() -> bool:
    if profile_complete():
        return True
    code = """
import os, time, json
import jax
from distar_tpu.utils.compile_cache import configure as _cc
_cc(jax, "/tmp/jax_cache_distar_tpu_bench")  # host-keyed by configure()
from distar_tpu.learner import SLLearner
cfg = {
    "common": {"experiment_name": "profile_sl"},
    "learner": {"batch_size": 6, "unroll_len": 64,
                "save_freq": 10 ** 9, "log_freq": 10 ** 9},
    "model": {"dtype": "bfloat16"},
}
learner = SLLearner(cfg)
data = dict(next(learner._dataloader))
data.pop("new_episodes", None); data.pop("traj_lens", None)
batch = jax.tree.map(jax.numpy.asarray, data)
args = (learner.state["params"], learner.state["opt_state"], batch, learner._hidden)
out = learner._train_step(*args); jax.block_until_ready(out)  # compile+warm
prof = os.path.join(os.getcwd(), "experiments", "profile_sl")
jax.profiler.start_trace(prof)
for _ in range(3):
    out = learner._train_step(out[0], out[1], batch, out[2])
jax.block_until_ready(out)
jax.profiler.stop_trace()
print("PROFILE-OK", prof)
"""
    rc, stdout = _run(
        [sys.executable, "-c", code], timeout=2400, log_name="profile-sl"
    )
    return rc == 0 and "PROFILE-OK" in (stdout or "")


STOP_FILE = "/tmp/tpu_campaign_stop"


def probe_chip(timeout: int = 120) -> bool:
    """Cheap claimability check: dial the relay in a subprocess and drop the
    claim immediately. When the chip is contended the dial blocks forever —
    a fast probe failure lets a retry loop come back in minutes instead of
    burning a full stage timeout holding nothing."""
    rc, stdout = _run(
        [sys.executable, "-c",
         "import jax; print('CHIP-OK', jax.devices()[0].platform)"],
        timeout=timeout,
        log_name="chip-probe",
    )
    return rc == 0 and "CHIP-OK" in (stdout or "")


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--deadline", type=int, default=14400,
                   help="bench-sweep chip-claim budget (s)")
    args = p.parse_args()
    if os.path.exists(STOP_FILE):
        # operator kill-switch: exit 0 so retry loops (`... && break`) stop
        # re-claiming the chip (e.g. before the driver's own bench window)
        print("[campaign] stop file present, exiting", flush=True)
        return
    # a fully-landed campaign must report done WITHOUT touching the chip —
    # cheap artifact checks first (the SAME predicates the stage functions
    # short-circuit on), claim probe only when work remains
    pending = [
        not bench_complete(),
        not kernels_complete(),
        not memstats_complete(),
        not _fullstep_ab_complete(),
        not profile_complete(),
    ]
    if not any(pending):
        print("[campaign] done (all stages complete)", flush=True)
        return
    if not probe_chip():
        print("[campaign] chip not claimable (relay contended); exiting for retry",
              flush=True)
        sys.exit(3)
    ok_bench = stage_bench(args.deadline)
    # only proceed to the extras once the headline number exists — they
    # contend for the same chip claim
    if not ok_bench:
        sys.exit(1)
    all_ok = True
    for stage in (stage_kernels, stage_memstats, stage_bench_recheck,
                  stage_fullstep_ab, stage_profile):
        if os.path.exists(STOP_FILE):
            # re-checked between stages: each holds the chip for up to ~40
            # min, and the switch must also halt an in-flight campaign
            print("[campaign] stop file present, halting before "
                  f"{stage.__name__}", flush=True)
            return
        all_ok = stage() and all_ok
    print(f"[campaign] done (all stages {'complete' if all_ok else 'NOT complete'})",
          flush=True)
    if not all_ok:
        sys.exit(2)  # retry loops: rerun until every artifact has landed


if __name__ == "__main__":
    main()
