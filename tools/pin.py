#!/usr/bin/env python
"""pin: the multi-process core-pinning harness CLI (distar_tpu.fleet.pinning).

Fleet perf numbers on a shared host are context-switch arithmetic unless
every member process owns its core. This tool plans, applies and verifies
core pinning, and prints the PROVENANCE BLOCK bench artifacts must embed to
claim ``scaling_valid: true`` (tools/perf_gate.py refuses the claim without
it, or with ``host_cores < 2``). On a host without enough cores the plan
REFUSES — the artifact then keeps ``scaling_valid: false`` with the reason
in-band. Wired into ``tools/loadgen.py --mode fleet``, the ``BENCH_MODE=
replay`` sweeps and the chaos drills.

  python tools/pin.py plan --procs 3 [--reserve-client 1] [--require]
        print the assignment plan (JSON); --require exits 3 when refused
  python tools/pin.py pid --pid 12345 --cores 2,3
        pin a live process (taskset -cp equivalent via sched_setaffinity)
  python tools/pin.py exec --cores 0,1 -- cmd args...
        pin THIS process then exec the command on those cores
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from distar_tpu.fleet import pinning  # noqa: E402


def cmd_plan(args) -> int:
    p = pinning.plan(args.procs, reserve_client=args.reserve_client)
    print(json.dumps(p.provenance(), indent=1))
    if args.require and not p.pinned:
        return 3
    return 0


def cmd_pid(args) -> int:
    cores = [int(c) for c in args.cores.split(",") if c.strip()]
    ok = pinning.pin_pid(args.pid, cores)
    print(json.dumps({"pid": args.pid, "cores": cores, "pinned": ok}))
    return 0 if ok else 1


def cmd_exec(args) -> int:
    cores = [int(c) for c in args.cores.split(",") if c.strip()]
    if not pinning.pin_pid(0, cores):
        print(json.dumps({"error": "could not pin self", "cores": cores}))
        return 1
    os.execvp(args.cmd[0], args.cmd)
    return 1  # unreachable


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = p.add_subparsers(dest="command", required=True)

    pl = sub.add_parser("plan", help="plan one-core-per-process assignments")
    pl.add_argument("--procs", type=int, required=True,
                    help="fleet processes needing their own core")
    pl.add_argument("--reserve-client", type=int, default=1,
                    help="cores reserved for the driving client side")
    pl.add_argument("--require", action="store_true",
                    help="exit 3 when the host cannot honestly pin")

    pd = sub.add_parser("pid", help="pin a live process")
    pd.add_argument("--pid", type=int, required=True)
    pd.add_argument("--cores", required=True, help="comma core list")

    ex = sub.add_parser("exec", help="pin self, then exec a command")
    ex.add_argument("--cores", required=True, help="comma core list")
    ex.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="command to exec (prefix with --)")

    args = p.parse_args()
    if args.command == "exec":
        args.cmd = [c for c in args.cmd if c != "--"]
        if not args.cmd:
            p.error("exec needs a command after --")
    return {"plan": cmd_plan, "pid": cmd_pid, "exec": cmd_exec}[args.command](args)


if __name__ == "__main__":
    raise SystemExit(main())
