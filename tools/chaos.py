#!/usr/bin/env python
"""chaos: seeded fault-injection CLI for resilience drills (docs/resilience.md).

Operates on the same deterministic injector the chaos test suite uses
(distar_tpu/resilience/chaos.py), so a drill run on a live fleet replays the
faults tests already prove survivable:

  python tools/chaos.py corrupt --path exp/checkpoints/iteration_40.ckpt \\
        --mode truncate [--seed 0] [--frac 0.5] [--flips 8]
  python tools/chaos.py kill --pid 12345 [--signal TERM|KILL]
  python tools/chaos.py reset --addr 127.0.0.1:8423 [--count 4]
  python tools/chaos.py latest --dir exp/checkpoints
  python tools/chaos.py replay-drill --dir /tmp/replay_spill [--items 50] \\
        [--no-spill] [--seed 0]
  python tools/chaos.py multichip-drill --dir /tmp/mc_drill \\
        [--mesh dp=4,fsdp=2] [--resume-mesh dp=8] [--kill-after 2] [--iters 5]
  python tools/chaos.py serve-drill --gateways 3 [--sessions 48] [--steps 8]
  python tools/chaos.py shm-drill --dir /tmp/shm_drill [--items 60] [--seed 0]
  python tools/chaos.py dynamics-drill --dir /tmp/dyn_drill \\
        [--module spatial_encoder] [--pre-steps 3] [--post-steps 3]
  python tools/chaos.py elastic-drill --dir /tmp/el_drill [--sessions 14] \\
        [--slots 8] [--items 60]
  python tools/chaos.py arena-drill --dir /tmp/arena_drill [--batches 4] \\
        [--episodes 6] [--kill-after 1]
  python tools/chaos.py coordinator-drill --dir /tmp/coord_drill \\
        [--items 30] [--post-items 15] [--lease-s 8] [--grace-s 1.5] \\
        [--no-ha]

``corrupt`` damages a checkpoint in place (the resume path must fall back);
``kill`` sends a signal to a role process (the supervisor/orchestrator must
restart it); ``reset`` opens connections to an endpoint and aborts them with
RST (read paths must survive hard resets); ``latest`` prints the durable
pointer's generations with per-generation verification status — run it after
a drill to see the fallback the fleet actually took; ``replay-drill`` stands
up a real replay store + clients on loopback, kills the store mid-run
(``ChaosInjector.kill_role`` with the replay role), restarts it from the
spill directory and reports whether every acked insert survived (exit 0
only when nothing was lost — or, with ``--no-spill``, when the expected
loss was demonstrated: the counter-example the durability contract is
measured against); ``multichip-drill`` kills a sharded-training learner
right after a sharded checkpoint save and supervises restarts on a
DIFFERENT mesh shape until the run finishes unassisted (the resharding
restore under fire).
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from distar_tpu.resilience.chaos import ChaosInjector  # noqa: E402
from distar_tpu.utils.checkpoint import CheckpointManager, verify_checkpoint  # noqa: E402


def cmd_corrupt(args) -> int:
    inj = ChaosInjector(seed=args.seed)
    if args.mode == "truncate":
        new_size = inj.truncate(args.path, keep_frac=args.frac)
        print(f"truncated {args.path} -> {new_size} bytes")
    else:
        offsets = inj.bitflip(args.path, flips=args.flips)
        print(f"bit-flipped {args.path} at byte offsets {offsets}")
    print(f"verify_checkpoint: {verify_checkpoint(args.path)}")
    return 0


def cmd_kill(args) -> int:
    sig = getattr(signal, f"SIG{args.signal.upper()}")
    os.kill(args.pid, sig)
    print(f"sent SIG{args.signal.upper()} to pid {args.pid}")
    return 0


def cmd_reset(args) -> int:
    host, _, port = args.addr.rpartition(":")
    inj = ChaosInjector(seed=args.seed)
    n = inj.reset_connection(host or "127.0.0.1", int(port), count=args.count)
    print(f"aborted {n}/{args.count} connections to {args.addr} with RST")
    return 0 if n else 1


def _sharded_replay_drill(args) -> int:
    """Shard-loss drill: N consistent-hash shards, one killed mid-run.

    Proves the fleet contract the single-store drill cannot: (a) the
    learner-side fan-in rides through the kill on the surviving shards
    without assistance, (b) ONLY the killed shard's unsampled tail goes
    missing while it is down (every surviving item is attributable to a
    live shard by the routing function), and (c) restarting the shard over
    its own spill directory restores exactly that tail — zero items lost
    fleet-wide. Consume-once (fifo) tables make the ledger exact: each key
    is sampleable exactly once, so set arithmetic is the whole proof."""
    from distar_tpu.replay import (
        ReplayServer, ReplayStore, ShardMap, ShardedInsertClient,
        ShardedSampleClient, SpillRing, TableConfig,
    )

    def table_cfg(_name):
        return TableConfig(max_size=max(args.items * 2, 8), sampler="fifo",
                           samples_per_insert=None, min_size_to_sample=1)

    def build_store(i):
        spill = None if args.no_spill else SpillRing(
            os.path.join(args.dir, f"s{i}"), max_items=args.items * 2)
        store = ReplayStore(table_factory=table_cfg, spill=spill,
                            shard_id=f"s{i}", recover_encoded=True)
        return store, store.recover()

    inj = ChaosInjector(seed=args.seed)
    servers = [ReplayServer(build_store(i)[0], port=0).start()
               for i in range(args.shards)]
    addrs = [f"{s.host}:{s.port}" for s in servers]
    shard_map = ShardMap(addrs)
    inserter = ShardedInsertClient(shard_map)

    keys = [f"k{i}" for i in range(args.items)]
    owner = {k: inserter.shard_for("drill", k) for k in keys}
    for k in keys:
        inserter.insert("drill", {"k": k}, key=k, timeout_s=10.0)

    sampler = ShardedSampleClient(shard_map)

    def drain(budget_s: float, want=None) -> set:
        """Fan-in sample until ``want`` is fully seen or the budget lapses.
        A timeout is NOT terminal: a restarted shard sits behind an open
        circuit breaker for a few seconds, so the loop keeps offering until
        the budget says the remainder is genuinely unreachable."""
        got, deadline = set(), time.monotonic() + budget_s
        while time.monotonic() < deadline:
            if want is not None and want <= got:
                break
            try:
                items, _info = sampler.sample(
                    "drill", batch_size=1,
                    timeout_s=min(1.0, max(0.1, deadline - time.monotonic())))
            except Exception:
                time.sleep(0.2)
                continue
            got.update(it["k"] for it in items)
        return got

    # phase 1: train a while, then the chaos moment — kill shard 0 with
    # part of its table acked and unsampled
    pre = drain_n(sampler, keys, args.items // 4)
    victim = addrs[0]
    inj.kill_role(servers[0], name=f"replay:{victim}")

    # phase 2: the learner keeps sampling unassisted; everything still
    # reachable must come from surviving shards
    survivors = {k for k in keys if owner[k] != victim}
    mid = drain(15.0, want=survivors - pre)
    assert all(owner[k] != victim for k in mid), \
        "sampled a key from the dead shard?!"
    missing = set(keys) - pre - mid
    wrong = [k for k in missing if owner[k] != victim]

    # phase 3: restart the killed shard over its spill; its tail comes back
    store2, recovered = build_store(0)
    host, port = victim.rsplit(":", 1)
    server2 = ReplayServer(store2, host=host, port=int(port)).start()
    servers[0] = server2
    post = drain(20.0, want=missing)
    lost = set(keys) - pre - mid - post
    for s in servers:
        s.stop()

    verdict = {
        "shards": args.shards, "items": args.items, "killed": victim,
        "sampled_pre_kill": len(pre), "sampled_during_outage": len(mid),
        "unreachable_during_outage": len(missing),
        "unreachable_not_owned_by_victim": len(wrong),
        "recovered_from_spill": recovered,
        "sampled_after_restart": len(post), "lost_fleet_wide": len(lost),
        "spill": not args.no_spill, "events": [e["kind"] for e in inj.events],
    }
    print(json.dumps(verdict))
    if args.no_spill:
        ok = len(lost) == len(missing) and len(missing) > 0
        print("verdict: shard loss demonstrated without spill"
              if ok else "verdict: UNEXPECTED — nothing lost?")
        return 0 if ok else 1
    ok = (not wrong and not lost and recovered == len(missing)
          and len(missing) > 0 and len(mid) > 0)
    print("verdict: learner rode through the shard kill; the killed shard's "
          "tail recovered from spill; zero items lost fleet-wide"
          if ok else "verdict: DRILL FAILED")
    return 0 if ok else 1


def drain_n(sampler, keys, n: int) -> set:
    """Sample until ``n`` unique keys were consumed (pre-kill warmup)."""
    got = set()
    while len(got) < n:
        items, _info = sampler.sample("drill", batch_size=1, timeout_s=10.0)
        got.update(it["k"] for it in items)
    return got


def cmd_replay_drill(args) -> int:
    """Kill-the-store-mid-run drill on a real server + real clients.
    ``--shards N`` (N > 1) runs the shard-loss variant instead; ``--shards
    1`` is the original whole-store kill — the counter-demo that a single
    store loses its entire unsampled tail where the fleet loses 1/N."""
    from distar_tpu.replay import (
        InsertClient, ReplayServer, ReplayStore, SampleClient, SpillRing,
        TableConfig,
    )
    from distar_tpu.resilience import RetryPolicy

    if args.shards > 1:
        return _sharded_replay_drill(args)

    def table_cfg(_name):
        return TableConfig(max_size=max(args.items * 2, 8),
                           samples_per_insert=None, min_size_to_sample=1)

    def build_store():
        spill = None if args.no_spill else SpillRing(args.dir, max_items=args.items * 2)
        store = ReplayStore(table_factory=table_cfg, spill=spill)
        return store, store.recover()

    inj = ChaosInjector(seed=args.seed)
    store, _ = build_store()
    server = ReplayServer(store, port=0).start()
    inserter = InsertClient(server.host, server.port)
    acked = [inserter.insert("drill", {"i": i}) for i in range(args.items)]
    port = server.port
    # the chaos moment: the store dies with every insert acked, none sampled
    inj.kill_role(server, name="replay")
    store2, recovered = build_store()
    server2 = ReplayServer(store2, host=server.host, port=port).start()
    sampler = SampleClient(server2.host, server2.port,
                           retry_policy=RetryPolicy(max_attempts=2, deadline_s=5.0))
    sampled = 0
    try:
        while sampled < len(acked):
            items, _info = sampler.sample("drill", batch_size=1, timeout_s=0.5)
            sampled += len(items)
    except Exception:
        pass  # a drained (or lossy) store times out — that IS the measurement
    server2.stop()
    lost = len(acked) - sampled
    verdict = {
        "acked": len(acked), "recovered_from_spill": recovered,
        "sampled_after_restart": sampled, "lost": lost,
        "spill": not args.no_spill, "events": [e["kind"] for e in inj.events],
    }
    print(json.dumps(verdict))
    if args.no_spill:
        # counter-demo: without the spill, acked data MUST be lost — if it
        # isn't, the drill didn't actually kill anything
        print("verdict: data loss demonstrated without spill"
              if lost == len(acked) else "verdict: UNEXPECTED — nothing lost?")
        return 0 if lost == len(acked) else 1
    print("verdict: every acked insert survived the kill"
          if lost == 0 else f"verdict: LOST {lost} acked trajectories")
    return 0 if lost == 0 else 1


def cmd_multichip_drill(args) -> int:
    """Kill-the-learner-mid-multichip-run drill with a mesh-shape change.

    Phase 1: a child trains on ``--mesh`` (forced host devices) with
    per-iteration SHARDED checkpoints and kills itself (``os._exit``) right
    after the save at ``--kill-after`` — a preempted pod worker. Then the
    drill supervises restarts (PR 4 RestartPolicy semantics, applied
    cross-process) on ``--resume-mesh`` — a DIFFERENT topology — until the
    run reaches ``--iters`` unassisted. Exit 0 only when the resumed run
    (a) restored from the generation the kill left behind (resharding
    restore) and (b) finished without human help."""
    import subprocess
    import time

    exp_dir = os.path.join(args.dir, "exp")
    target = args.iters

    def child(mesh, extra):
        cmd = [
            sys.executable, "-m", "distar_tpu.parallel.executor",
            "--mesh", mesh, "--host-devices", str(args.host_devices),
            "--iters", str(target), "--save-dir", exp_dir,
            "--experiment-name", "chaos_multichip",
        ] + extra
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=args.timeout_s, cwd=_REPO)
        report = None
        for line in proc.stdout.splitlines():
            if line.startswith("REPORT "):
                report = json.loads(line[len("REPORT "):])
        return proc.returncode, report, proc

    print(f"phase 1: train on --mesh {args.mesh}, kill after iter "
          f"{args.kill_after} (post-save)")
    rc, report, proc = child(args.mesh, ["--save-freq", "1",
                                         "--kill-after", str(args.kill_after)])
    if rc != 137:
        print(f"UNEXPECTED: phase-1 child exited {rc} (wanted the 137 kill)\n"
              f"{proc.stderr[-2000:]}")
        return 1

    # phase 2: supervised restarts on the OTHER mesh shape until done
    restarts, resumed_from, final = 0, None, None
    while restarts < args.restart_max:
        restarts += 1
        print(f"phase 2 (attempt {restarts}): resume on --resume-mesh "
              f"{args.resume_mesh}")
        rc, report, proc = child(args.resume_mesh, ["--resume"])
        if rc == 0 and report is not None:
            resumed_from = report.get("resumed_from")
            final = report
            break
        print(f"restart attempt {restarts} died rc={rc}; retrying\n"
              f"{proc.stderr[-500:]}")
        time.sleep(1.0)
    verdict = {
        "target_iters": target,
        "killed_after": args.kill_after,
        "restarts": restarts,
        "resumed_from": resumed_from,
        "final_iters": final and final.get("iters"),
        "resume_start_iter": final and final.get("start_iter"),
        "mesh_killed": args.mesh,
        "mesh_resumed": final and final.get("mesh"),
    }
    print(json.dumps(verdict))
    ok = (
        final is not None
        and final.get("iters") == target
        and final.get("start_iter", 0) >= args.kill_after
        and resumed_from is not None
    )
    print("verdict: resumed on a different mesh and finished unassisted"
          if ok else "verdict: DRILL FAILED")
    return 0 if ok else 1


def cmd_serve_drill(args) -> int:
    """Gateway-loss drill on the serving fleet: N real gateway processes
    behind the session-affinity router, one killed mid-episode under load.

    The contract being proven (docs/serving.md fleet section): (a) every
    session — including every session pinned to the victim — finishes its
    episode; (b) the router re-routes the victim's sessions to survivors
    within one retry budget, and each re-routed session's carry
    re-materializes from zero, counted EXACTLY (migrations ==
    victim-pinned sessions, detected via session_step running backwards);
    (c) callers see ZERO typed-error leakage beyond shed accounting — a
    dead gateway surfaces as transparent failover, never as an error
    return. Exit 0 only when all three hold."""
    import subprocess
    import threading

    import numpy as np

    from distar_tpu.obs import get_registry
    from distar_tpu.serve import ShedError
    from distar_tpu.serve.fleet import FleetClient, GatewayMap

    def spawn():
        cmd = [sys.executable, "-m", "distar_tpu.serve.fleet.gateway_proc",
               "--port", "0", "--http-port", "0", "--slots", str(args.slots)]
        proc = subprocess.Popen(cmd, stdin=subprocess.PIPE,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL, text=True)
        parts = proc.stdout.readline().split()
        if len(parts) < 4 or parts[0] != "SERVE-GATEWAY":
            raise RuntimeError(f"gateway failed to start: {parts}")
        return proc, f"{parts[1]}:{parts[2]}"

    from distar_tpu.fleet import pinning

    inj = ChaosInjector(seed=args.seed)
    spawned = [spawn() for _ in range(args.gateways)]
    procs = [p for p, _ in spawned]
    addrs = [a for _, a in spawned]
    pin_prov = pinning.pin_fleet([p.pid for p in procs], reserve_client=1)
    fc = FleetClient(gateway_map=GatewayMap(addrs), timeout_s=10.0,
                     down_ttl_s=60.0)
    obs = {"x": np.ones((4, 4), dtype=np.float32)}
    sids = [f"drill-{i}" for i in range(args.sessions)]
    completed = {sid: 0 for sid in sids}
    sheds = [0]
    errors = []
    lock = threading.Lock()
    kill_at = max(1, args.steps // 2)
    killed = [None]

    def step_all(step: int) -> None:
        """One fleet cycle: every session steps once (sheds retried within
        the cycle — they are backpressure, not loss)."""
        pending = list(sids)
        deadline = time.monotonic() + 30.0
        while pending and time.monotonic() < deadline:
            results = fc.act_many(
                [{"session_id": s, "obs": obs} for s in pending], timeout_s=10.0)
            nxt = []
            for s, r in zip(pending, results):
                if isinstance(r, ShedError):
                    with lock:
                        sheds[0] += 1
                    nxt.append(s)
                elif isinstance(r, Exception):
                    with lock:
                        errors.append((s, step, repr(r)))
                else:
                    completed[s] += 1
            pending = nxt
            if pending:
                time.sleep(0.05)
        for s in pending:
            with lock:
                errors.append((s, step, "cycle budget exhausted"))

    migrations0 = get_registry().snapshot().get(
        "distar_fleet_session_migrations_total", 0.0)
    for step in range(args.steps):
        step_all(step)
        if step + 1 == kill_at:
            # the chaos moment: kill the gateway holding the most sessions
            pins = fc.router.stats()["pins_per_gateway"]
            victim = max(pins, key=lambda a: pins[a])
            killed[0] = {"addr": victim, "pinned": pins[victim]}
            inj.kill_role(procs[addrs.index(victim)], name=f"serve:{victim}")
            procs[addrs.index(victim)].wait(timeout=10)
    migrations = get_registry().snapshot().get(
        "distar_fleet_session_migrations_total", 0.0) - migrations0

    finished = sum(1 for s in sids if completed[s] == args.steps)
    fc.close()
    for proc in procs:
        try:
            proc.stdin.close()
            proc.wait(timeout=10)
        except Exception:
            proc.kill()
    verdict = {
        "gateways": args.gateways, "sessions": args.sessions,
        "steps": args.steps, "pinning": pin_prov, "killed": killed[0],
        "finished_sessions": finished,
        "migrations": migrations,
        "sheds_retried": sheds[0],
        "error_leaks": len(errors),
        "events": [e["kind"] for e in inj.events],
    }
    print(json.dumps(verdict))
    ok = (
        finished == args.sessions
        and killed[0] is not None
        and migrations == killed[0]["pinned"]
        and not errors
    )
    print("verdict: gateway killed under load; every session re-routed and "
          "finished; migrations counted exactly; zero error leakage"
          if ok else f"verdict: DRILL FAILED {errors[:5]}")
    return 0 if ok else 1


def cmd_shm_drill(args) -> int:
    """Kill the shm-transport peer mid-frame; prove typed detection + TCP
    fallback with zero acked-item loss.

    A real replay shard subprocess serves the drill over negotiated shm
    rings (tiny rings forced via DISTAR_SHM_RING_BYTES, so the writer is
    usually mid-frame, blocked for space). Mid-traffic the shard is
    SIGKILL'd — no close flags, no unlink, only the heartbeat going
    stale: the drill's writer sees its dead ring *reader* typed
    (ShmPeerDeadError within the heartbeat window) and the drill's
    sampler, parked in recv, sees the dead ring *writer* the same way —
    both directions of the failure model. The counted fallback then rides
    the resilience retry policy onto a restarted shard that only speaks
    TCP (same port, same spill directory), and the run completes there:
    every acked insert must be sampleable afterwards (spill recovery for
    the committed tail + idempotent retries for the in-flight one), the
    replay-drill accounting. Exit 0 only when shm was genuinely active
    before the kill, the fallback was typed+counted, the finish leg is
    tcp, and zero acked items are lost."""
    import subprocess
    import threading

    from distar_tpu.obs import get_registry
    from distar_tpu.replay import InsertClient, SampleClient

    def spawn(port: int, transport: str):
        env = dict(os.environ)
        env["DISTAR_SHM_RING_BYTES"] = str(args.ring_bytes)
        cmd = [sys.executable, "-m", "distar_tpu.replay.server",
               "--port", str(port), "--transport", transport,
               "--spill-dir", args.dir, "--sampler", "fifo",
               "--min-size", "1", "--max-size", str(max(args.items * 2, 64)),
               "--spill-max", str(max(args.items * 2, 64))]
        proc = subprocess.Popen(cmd, stdin=subprocess.PIPE,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL, text=True)
        parts = proc.stdout.readline().split()
        if len(parts) < 3 or parts[0] != "REPLAY-SHARD":
            raise RuntimeError(f"shard failed to start: {parts}")
        return proc, parts[1], int(parts[2])

    from distar_tpu.fleet import pinning

    inj = ChaosInjector(seed=args.seed)
    proc, host, port = spawn(0, "shm")
    pin_prov = pinning.pin_fleet([proc.pid], reserve_client=1)
    payload = os.urandom(args.ring_bytes // 2 + 512)  # frames span the ring
    inserter = InsertClient(host, port, timeout_s=10.0)
    acked, dup, lock = set(), [0], threading.Lock()

    def fallbacks() -> float:
        return sum(v for k, v in get_registry().snapshot().items()
                   if k.startswith("distar_shm_fallbacks_total"))

    # phase 1: half the items acked over live rings
    half = args.items // 2
    for i in range(half):
        inserter.insert("drill", {"k": f"k{i}", "b": payload}, timeout_s=10.0)
        with lock:
            acked.add(f"k{i}")
    transport_before = inserter.transport_active
    fallbacks_before = fallbacks()

    # phase 2: continuous traffic from BOTH seats, then the chaos moment.
    # The sampler parks in a blocking sample (its ring *writer* is the
    # server); the inserter streams frames (its ring *reader* is the
    # server) — the SIGKILL is seen typed from both directions.
    sampler = SampleClient(host, port, timeout_s=10.0)
    sampled, stop = set(), threading.Event()

    def insert_rest():
        # paced so the SIGKILL lands mid-stream (an idle writer would
        # finish before the chaos moment and dodge the drill)
        for i in range(half, args.items):
            while True:
                try:
                    inserter.insert("drill", {"k": f"k{i}", "b": payload},
                                    timeout_s=10.0)
                    with lock:
                        acked.add(f"k{i}")
                    break
                except Exception:
                    if stop.is_set():
                        return
                    time.sleep(0.2)
            time.sleep(0.05)

    def sample_some():
        while not stop.is_set():
            try:
                items, _ = sampler.sample("drill", batch_size=1, timeout_s=5.0)
            except Exception:
                time.sleep(0.2)
                continue
            with lock:
                for it in items:
                    if it["k"] in sampled:
                        dup[0] += 1
                    sampled.add(it["k"])

    threads = [threading.Thread(target=insert_rest, daemon=True),
               threading.Thread(target=sample_some, daemon=True)]
    for t in threads:
        t.start()
    time.sleep(0.5)  # traffic in flight on the rings
    inj.kill_role(proc.pid, sig=signal.SIGKILL, name=f"replay-shm:{host}:{port}")
    proc.wait(timeout=10)
    time.sleep(1.0)  # inside the retry budget: clients are detecting/backing off

    # phase 3: restart on the SAME port, TCP-only, over the same spill dir —
    # the fallback leg must complete the run unassisted
    proc2, host, port = spawn(port, "tcp")
    threads[0].join(60.0)
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        with lock:
            if acked <= sampled:
                break
        time.sleep(0.2)
    stop.set()
    threads[1].join(10.0)
    lost = sorted(acked - sampled)
    # read BEFORE close() (close drops the rings and would fake a "tcp")
    transport_after = {"insert": inserter.transport_active,
                       "sample": sampler.transport_active}
    verdict = {
        "items": args.items,
        "pinning": pin_prov,
        "acked": len(acked),
        "sampled_unique": len(sampled),
        "duplicates_after_restart": dup[0],
        "lost_acked": len(lost),
        "transport_before_kill": transport_before,
        "transport_after_fallback": transport_after,
        "typed_fallbacks_counted": fallbacks() - fallbacks_before,
        "events": [e["kind"] for e in inj.events],
    }
    inserter.close()
    sampler.close()
    try:
        proc2.stdin.close()
        proc2.wait(timeout=10)
    except Exception:
        proc2.kill()
    print(json.dumps(verdict))
    ok = (transport_before == "shm"
          and transport_after == {"insert": "tcp", "sample": "tcp"}
          and verdict["typed_fallbacks_counted"] >= 2  # both client seats
          and len(acked) == args.items
          and not lost)
    print("verdict: peer killed mid-frame detected typed on both ring "
          "directions; clients fell back to the TCP leg and finished with "
          "zero acked-item loss"
          if ok else f"verdict: DRILL FAILED {verdict}")
    return 0 if ok else 1


def cmd_elastic_drill(args) -> int:
    """The elastic-fleet acceptance drill (ISSUE 12): load spike ->
    autoscaler scale-up observed LIVE by clients -> graceful cooldown drain
    with exact migration accounting -> SIGKILL a replay member MID-DRAIN
    with zero acked-item loss.

    Phase A (serve): one mock gateway under the coordinator + autoscaler;
    more sessions than its slots arrive (typed capacity sheds = the load
    spike). The gateway-residency policy breaches, the autoscaler spawns a
    second gateway, and the drill's FleetClient — running the live
    membership refresher, never reconstructed — observes the join and its
    shed sessions land on the new member (capacity spill-over). Load then
    drops; after hysteresis + cooldown the autoscaler drains the newest
    gateway gracefully: every session resident there is ended-and-re-pinned
    by the client (DrainingError handoff), counted EXACTLY (migrations ==
    the victim's pinned sessions at decision time), with zero non-shed
    errors, and the victim process exits on its own.

    Phase B (replay): a 3-shard spill-backed fleet under the same
    coordinator; keyed acked inserts spread over the ring. One shard is
    drained (deregister-then-refuse) and — before its resident tail can
    drain — SIGKILL'd mid-drain. Survivors absorb the insert stream (the
    draining overlay + membership refresh re-route keys), a replacement
    over the victim's spill directory on the SAME port recovers exactly its
    tail, and the fan-in sampler accounts for every acked key. Exit 0 only
    when every contract holds. Core pinning is attempted via the
    tools/pin.py harness and reported in-band (refused on small hosts)."""
    import threading

    import numpy as np

    from distar_tpu.comm.coordinator import Coordinator, CoordinatorServer
    from distar_tpu.fleet import (
        Autoscaler, FleetSupervisor, MemberProbe, ScalePolicy, SubprocessFleet,
        gateway_cmd, pinning, replay_cmd,
    )
    from distar_tpu.obs import TimeSeriesStore, get_registry
    from distar_tpu.replay import ShardMap, ShardedInsertClient, ShardedSampleClient
    from distar_tpu.serve import ShedError
    from distar_tpu.serve.fleet import FleetClient

    slots = args.slots
    sessions = args.sessions
    verdict = {"phase_a": {}, "phase_b": {}}
    failures = []

    coordinator = CoordinatorServer(Coordinator(default_lease_s=10.0))
    coordinator.start()
    coord_addr = f"{coordinator.host}:{coordinator.port}"

    supervisor = FleetSupervisor()
    gw_fleet = SubprocessFleet(
        "gateway", "gateway",
        gateway_cmd(slots=slots, coordinator=coord_addr,
                    extra=["--drain-timeout-s", "20"]),
        drain_timeout_s=25.0)
    rp_fleet = SubprocessFleet(
        "replay", "replay",
        replay_cmd(spill_root=args.dir, coordinator=coord_addr,
                   extra=["--drain-timeout-s", "20",
                          "--max-size", str(max(args.items * 2, 64)),
                          "--spill-max", str(max(args.items * 2, 64))]),
        drain_timeout_s=25.0)
    supervisor.add_fleet(gw_fleet).add_fleet(rp_fleet).start()

    store = TimeSeriesStore()
    probe = MemberProbe(store, supervisor)
    scaler = Autoscaler(
        store, supervisor,
        policies=[ScalePolicy(
            name="gateway_residency", fleet="gateway",
            signal="distar_serve_sessions_active",
            divide_by="distar_serve_session_slots",
            up_when=0.85, down_when=0.30, window_s=6.0, for_count=2)],
        limits={"gateway": (1, 2), "replay": (3, 4)},
        cooldown_s=4.0, interval_s=0.5, probe=probe)

    try:
        # ---------------- phase A: spike -> scale-up -> graceful drain
        supervisor.scale_up("gateway", 1)
        for _ in range(3):
            supervisor.scale_up("replay", 1)
        pin_prov = pinning.pin_fleet(gw_fleet.pids() + rp_fleet.pids(),
                                     reserve_client=1)
        verdict["pinning"] = pin_prov
        scaler.start()

        fc = FleetClient(coordinator_addr=(coordinator.host, coordinator.port),
                         timeout_s=10.0, refresh_s=0.5)
        obs = {"x": np.ones((4, 4), dtype=np.float32)}
        sids = [f"el-{i}" for i in range(sessions)]
        errors, live = [], set()

        def step_all(rounds: int, budget_s: float, want_all: bool) -> None:
            deadline = time.monotonic() + budget_s
            for _ in range(rounds):
                pending = [s for s in sids if s in live or want_all]
                while pending and time.monotonic() < deadline:
                    results = fc.act_many(
                        [{"session_id": s, "obs": obs} for s in pending],
                        timeout_s=8.0)
                    nxt = []
                    for s, r in zip(pending, results):
                        if isinstance(r, ShedError):
                            nxt.append(s)  # spike backpressure: retry
                        elif isinstance(r, Exception):
                            errors.append((s, repr(r)))
                        else:
                            live.add(s)
                    pending = nxt
                    if pending:
                        time.sleep(0.2)

        # the spike: more sessions than the 1-gateway fleet can hold; shed
        # lanes keep retrying while residency pins the policy at 1.0
        spike = threading.Thread(target=step_all, args=(60, 60.0, True),
                                 daemon=True)
        spike.start()
        t0 = time.monotonic()
        while time.monotonic() - t0 < 45.0:
            if len(fc.router.map) >= 2 and len(live) == sessions:
                break
            time.sleep(0.5)
        spike.join(30.0)
        scaled_to = len(fc.router.map.addrs)
        verdict["phase_a"]["scaled_to_gateways"] = scaled_to
        verdict["phase_a"]["sessions_live_after_join"] = len(live)
        verdict["phase_a"]["scale_up_decision"] = next(
            (d for d in scaler.status()["decisions"] if d["direction"] == "up"),
            None)
        if scaled_to < 2 or len(live) != sessions:
            failures.append(
                f"scale-up not observed live: {scaled_to} gateways, "
                f"{len(live)}/{sessions} sessions")

        # load drop: end sessions, preferring the OLDEST gateway's, so the
        # newest (the scale-down victim) keeps residents to migrate
        pins = fc.router.stats()["pins_per_gateway"]
        newest = supervisor.fleet("gateway").active_members()[-1].addr
        keep = [s for s in sids if fc.router._pins.get(s) == newest][:4]
        for s in sids:
            if s not in keep:
                try:
                    fc.end(s)
                except Exception:  # noqa: BLE001 - counted via errors below
                    errors.append((s, "end failed"))
                live.discard(s)

        # baseline counters BEFORE the decision can land: the refresher's
        # drain handoff fires within one refresh tick of the drain
        snap0 = get_registry().snapshot()
        mig0 = snap0.get("distar_fleet_session_migrations_total", 0.0)
        hand0 = snap0.get("distar_fleet_drain_handoff_sessions_total", 0.0)
        # wait for the cooldown scale-down decision (stepping paused, so
        # the victim's pin count is exact at decision time — the handoff
        # ends sessions on the victim but never unpins)
        down = None
        t0 = time.monotonic()
        while time.monotonic() - t0 < 45.0 and down is None:
            down = next((d for d in scaler.status()["decisions"]
                         if d["direction"] == "down"), None)
            time.sleep(0.3)
        if down is None:
            failures.append("no scale-down decision within budget")
            victim, victim_pins = None, 0
        else:
            victim = down["members"][0]
            victim_pins = len(fc.router.pins_on(victim))

        # resume stepping the survivors: their next act on the draining
        # gateway hands off (end-there + re-pin), carries re-materialize
        step_all(6, 40.0, False)
        snap1 = get_registry().snapshot()
        migrations = snap1.get("distar_fleet_session_migrations_total", 0.0) - mig0
        handoffs = snap1.get("distar_fleet_drain_handoff_sessions_total", 0.0) - hand0
        # the victim must exit on its own once drained
        t0 = time.monotonic()
        while time.monotonic() - t0 < 30.0 and victim is not None:
            if victim not in [m.addr for m in gw_fleet.members()]:
                break
            time.sleep(0.3)
        victim_gone = victim is not None and \
            victim not in [m.addr for m in gw_fleet.members()]
        verdict["phase_a"].update({
            "pins_before_drain": pins, "drain_victim": victim,
            "victim_resident_at_decision": victim_pins,
            "migrations": migrations, "drain_handoffs": handoffs,
            "victim_exited": victim_gone,
            "non_shed_errors": len(errors),
        })
        if errors:
            failures.append(f"non-shed errors leaked: {errors[:5]}")
        if down is not None and not (
                migrations == handoffs == victim_pins and victim_pins > 0):
            failures.append(
                f"migration accounting inexact: migrations={migrations} "
                f"handoffs={handoffs} resident={victim_pins}")
        if down is not None and not victim_gone:
            failures.append("drained gateway did not exit")
        for s in keep:
            try:
                fc.end(s)
            except Exception:  # noqa: BLE001 - teardown
                pass
        fc.close()

        # ---------------- phase B: SIGKILL a replay member MID-DRAIN
        inj = ChaosInjector(seed=args.seed)
        inserter = ShardedInsertClient(
            ShardMap.discover((coordinator.host, coordinator.port)))
        inserter.start_refresh((coordinator.host, coordinator.port),
                               interval_s=0.5)
        keys = [f"k{i}" for i in range(args.items)]
        owner = {k: inserter.shard_for("drill", k) for k in keys}
        half = args.items // 2
        for k in keys[:half]:
            inserter.insert("drill", {"k": k}, key=k, timeout_s=10.0)

        members = rp_fleet.active_members()
        victim_m = max(members,
                       key=lambda m: sum(1 for k in keys if owner[k] == m.addr))
        victim_addr, victim_pid = victim_m.addr, victim_m.proc.pid
        victim_port = int(victim_addr.rsplit(":", 1)[1])
        victim_dir = os.path.join(args.dir, f"s{victim_m.meta['index']}")
        victim_resident = sum(1 for k in keys[:half] if owner[k] == victim_addr)

        rp_fleet.drain(victim_m)  # deregister-then-refuse; tail stays (no sampler)
        # the insert stream keeps running THROUGH the drain: draining
        # answers re-route each key to a survivor (overlay), and the
        # membership refresh soon drops the victim from the map entirely
        for k in keys[half:]:
            inserter.insert("drill", {"k": k}, timeout_s=10.0, key=k)
        t0 = time.monotonic()
        while time.monotonic() - t0 < 10.0 and \
                victim_addr in inserter.shard_map.addrs:
            time.sleep(0.2)
        map_dropped = victim_addr not in inserter.shard_map.addrs

        # the chaos moment: SIGKILL mid-drain (resident tail NOT drained)
        inj.kill_role(victim_pid, sig=signal.SIGKILL,
                      name=f"replay-mid-drain:{victim_addr}")
        time.sleep(1.0)

        # replacement over the victim's spill on the SAME port (identity =
        # host:port, so its ring segment comes back with it)
        import subprocess
        cmd = replay_cmd(spill_root=args.dir, coordinator=coord_addr,
                         extra=["--max-size", str(max(args.items * 2, 64)),
                                "--spill-max", str(max(args.items * 2, 64))])(
            int(victim_m.meta["index"]))
        cmd[cmd.index("--port") + 1] = str(victim_port)
        proc2 = subprocess.Popen(cmd, stdin=subprocess.PIPE,
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.DEVNULL, text=True)
        parts = proc2.stdout.readline().split()
        recovered = int(dict(t.split("=", 1) for t in parts[3:]
                             if "=" in t).get("recovered", -1))

        sampler = ShardedSampleClient(
            ShardMap.discover((coordinator.host, coordinator.port)))
        sampler.start_refresh((coordinator.host, coordinator.port),
                              interval_s=0.5)
        got = set()
        deadline = time.monotonic() + 45.0
        while time.monotonic() < deadline and len(got) < len(keys):
            try:
                items, _info = sampler.sample("drill", batch_size=1,
                                              timeout_s=1.0)
            except Exception:
                time.sleep(0.2)
                continue
            got.update(it["k"] for it in items)
        lost = sorted(set(keys) - got)
        verdict["phase_b"] = {
            "items": args.items, "killed_mid_drain": victim_addr,
            "victim_resident_at_kill": victim_resident,
            "map_dropped_victim_before_kill": map_dropped,
            "recovered_from_spill": recovered,
            "sampled_unique": len(got), "lost_acked": len(lost),
        }
        if lost:
            failures.append(f"acked items lost: {lost[:10]}")
        if recovered < victim_resident:
            failures.append(
                f"spill recovered {recovered} < victim's resident tail "
                f"{victim_resident}")
        if not map_dropped:
            failures.append("live membership never dropped the draining shard")
        inserter.close()
        sampler.close()
        try:
            proc2.stdin.close()
            proc2.wait(timeout=10)
        except Exception:  # noqa: BLE001 - teardown
            proc2.kill()
    finally:
        scaler.stop()
        supervisor.stop()
        coordinator.stop()

    verdict["failures"] = failures
    print(json.dumps(verdict, default=str))
    print("verdict: load spike scaled the fleet up live, cooldown drained "
          "a member with exact migration accounting, and a mid-drain "
          "SIGKILL lost zero acked items"
          if not failures else f"verdict: DRILL FAILED {failures}")
    return 0 if not failures else 1


def cmd_dynamics_drill(args) -> int:
    """End-to-end drill for the training-dynamics observatory: poison one
    module's params with a NaN mid-run (``ChaosInjector.poison_module`` — a
    real numeric fault, pre-step) and prove the whole forensic chain:

      (a) the dynamics census localizes the fault to EXACTLY the poisoned
          module (provenance origin ``params``, narrowest family wins);
      (b) exactly ONE learner_grad_nonfinite alert fires, carrying a
          ``blackbox:<bundle>`` exemplar (debounce: one anomaly, one alert);
      (c) exactly one black-box bundle lands in the experiment's blackbox/
          directory;
      (d) ``tools/stepreplay.py`` re-executes the step from the bundle
          ALONE (subprocess, fresh interpreter) and reproduces the
          non-finite step deterministically (exit 0).

    Runs the real SL learner (tiny flagship-shaped model) on CPU in-process;
    health evaluation is driven deterministically once per step."""
    import subprocess

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("DISTAR_PERF_AOT", "0")
    os.environ["DISTAR_EXPERIMENTS_ROOT"] = args.dir

    from distar_tpu.learner import SLLearner
    from distar_tpu.obs import FleetHealth, default_rulebook, get_registry
    from distar_tpu.obs.dynamics import list_bundles, load_bundle

    small_model = {
        "encoder": {
            "entity": {"layer_num": 1, "hidden_dim": 32, "output_dim": 16,
                       "head_dim": 8},
            "spatial": {"down_channels": [4, 4, 8], "project_dim": 4,
                        "resblock_num": 1, "fc_dim": 16},
            "scatter": {"output_dim": 4},
            "core_lstm": {"hidden_size": 32, "num_layers": 1},
        },
        "policy": {
            "action_type_head": {"res_dim": 16, "res_num": 1, "gate_dim": 32},
            "delay_head": {"decode_dim": 16},
            "queued_head": {"decode_dim": 16},
            "selected_units_head": {"func_dim": 16},
            "target_unit_head": {"func_dim": 16},
            "location_head": {"res_dim": 8, "res_num": 1,
                              "upsample_dims": [4, 4, 1], "map_skip_dim": 8},
        },
        "value": {"res_dim": 8, "res_num": 1},
    }
    exp = os.path.join(args.dir, "exp")
    learner = SLLearner({
        "common": {"save_path": exp},
        "learner": {
            "batch_size": 2, "unroll_len": 2,
            "save_freq": 10 ** 6, "log_freq": 1,
            "dynamics": {"every_n": 1, "blackbox_cap": 4},
        },
        "model": small_model,
    })
    monitor = learner._dynamics
    fh = FleetHealth(rules=default_rulebook(roles=("learner",)),
                     registry=get_registry())  # driven manually, not started

    inner = learner._state["params"]
    inner = inner.get("params", inner)
    modules = sorted(inner)
    module = args.module or modules[0]
    if module not in modules:
        print(f"module {module!r} not in model (choose from {modules})")
        return 2

    inj = ChaosInjector(seed=args.seed)
    total = args.pre_steps + 1 + args.post_steps

    def step_to(n: int) -> None:
        learner.run(max_iterations=n)
        fh.sampler.sample_once()
        fh.evaluator.evaluate_once()

    for i in range(args.pre_steps):
        step_to(i + 1)  # clean baseline: EMA + census gauges at healthy 0
    import jax
    import jax.numpy as jnp

    # pre-poison snapshot = the "restore from last good checkpoint" the
    # on-call would do; without it the NaN update poisons every later step
    snap_state = jax.device_get(learner._state)
    snap_hidden = jax.device_get(learner._hidden)
    inj.poison_module(learner, module, n=1)
    print(f"poisoned module {module!r} params before step {args.pre_steps}")
    step_to(args.pre_steps + 1)  # the anomalous step
    inj.restore()
    learner._state = learner._place_state(snap_state)
    learner._hidden = jax.tree.map(jnp.asarray, snap_hidden)
    for i in range(args.pre_steps + 1, total):
        step_to(i + 1)  # recovery: debounce must hold at one bundle

    failures = []
    bundles = list_bundles(os.path.join(exp, "blackbox"))
    if len(bundles) != 1:
        failures.append(f"expected exactly 1 black-box bundle, found "
                        f"{[b['id'] for b in bundles]}")
    provenance = None
    if bundles:
        bundle = load_bundle(bundles[0]["path"])
        provenance = bundle.get("provenance")
        if not provenance or provenance.get("origin") != "params" \
                or provenance.get("module") != module:
            failures.append(f"provenance did not name the poisoned module: "
                            f"{provenance}")
    alerts = fh.evaluator.alerts()
    rule = alerts["rules"].get("learner_grad_nonfinite", {})
    if rule.get("fired_count") != 1:
        failures.append(f"learner_grad_nonfinite fired_count="
                        f"{rule.get('fired_count')} (wanted exactly 1)")
    other_fired = [n for n in ("learner_loss_nonfinite",
                               "learner_grad_explosion",
                               "learner_entropy_collapse")
                   if alerts["rules"].get(n, {}).get("fired_count", 0) > 0]
    if other_fired:
        failures.append(f"other anomaly rules fired: {other_fired}")
    firing_events = [e for e in alerts["history"]
                     if e["rule"] == "learner_grad_nonfinite"
                     and e["state"] == "firing"]
    exemplar = firing_events[-1].get("exemplar_trace_id") if firing_events else None
    if not (exemplar or "").startswith("blackbox:"):
        failures.append(f"firing alert carries no blackbox exemplar: {exemplar!r}")
    elif bundles and exemplar != f"blackbox:{bundles[0]['id']}":
        failures.append(f"exemplar {exemplar!r} != bundle {bundles[0]['id']!r}")

    replay_verdict = None
    if bundles:
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "stepreplay.py"),
             "--bundle", bundles[0]["path"], "--json",
             "--workdir", os.path.join(args.dir, "replay")],
            capture_output=True, text=True, timeout=1200, cwd=_REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        try:
            replay_verdict = json.loads(proc.stdout.strip().splitlines()[-1])
        except (ValueError, IndexError):
            replay_verdict = None
        if proc.returncode != 0 or replay_verdict is None:
            failures.append(f"stepreplay exited {proc.returncode}: "
                            f"{proc.stderr[-800:]}")
        else:
            for want in ("nonfinite_reproduced", "deterministic"):
                if not replay_verdict.get(want):
                    failures.append(f"stepreplay verdict lacks {want}: "
                                    f"{replay_verdict}")

    verdict = {
        "module": module, "steps": total,
        "poisoned_at_step": args.pre_steps,
        "bundles": [b["id"] for b in bundles],
        "provenance": provenance,
        "anomaly_rule_fired_count": rule.get("fired_count"),
        "exemplar_trace_id": exemplar,
        "replay": replay_verdict,
        "events": [e["kind"] for e in inj.events],
        "failures": failures,
    }
    print(json.dumps(verdict, default=str))
    print("verdict: NaN localized to the poisoned module, one alert with a "
          "black-box exemplar, and stepreplay reproduced the step from the "
          "bundle alone"
          if not failures else f"verdict: DRILL FAILED {failures}")
    return 0 if not failures else 1


# evaluator child for the arena drill: a REAL subprocess speaking the real
# arena_next/arena_report wire plane, killable with SIGKILL mid-batch.
# Anchors-only roster (the checkpoint dir is empty) so no model compiles.
_ARENA_CHILD = r"""
import os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
repo = sys.argv[1]
if repo not in sys.path:
    sys.path.insert(0, repo)
host, port = sys.argv[2], int(sys.argv[3])
ckpt, batches, episodes = sys.argv[4], int(sys.argv[5]), int(sys.argv[6])
units, ep_len = int(sys.argv[7]), int(sys.argv[8])
from distar_tpu.arena import ArenaEvaluator
from distar_tpu.envs.jaxenv import EnvConfig, ScenarioConfig
ev = ArenaEvaluator(
    ckpt, model_cfg={}, coordinator_addr=(host, port), episodes=episodes,
    env_cfg=EnvConfig(units_per_squad=units),
    scenario_cfg=ScenarioConfig(units_per_squad=units, max_units=units,
                                episode_len=ep_len))
done = 0
while done < batches:
    print("BATCH_START %d" % done, flush=True)
    out = ev.evaluate_once()
    if out is None:
        time.sleep(0.2)
        continue
    ack = out["ack"]
    print("BATCH_DONE %d applied=%d duplicates=%d"
          % (done, ack["applied"], ack["duplicates"]), flush=True)
    done += 1
print("EVAL_EXIT", flush=True)
"""


def cmd_arena_drill(args) -> int:
    """Kill an arena evaluator mid-batch and restart it: zero lost and zero
    double-counted matches by idempotent-key construction.

    Stands up a real coordinator hosting a durable ArenaStore, runs a real
    evaluator subprocess (anchors-only roster: scripted policies, no model
    loads) over the real ``arena_next``/``arena_report`` wire plane, and
    SIGKILLs it shortly after a batch starts — the assignment is taken and
    the scenario is running, but nothing is reported. The restarted
    evaluator must re-receive the identical assignment (scheduling is a
    pure function of *reported* state) and finish the run with EXACT
    accounting:

      (a) applied matches == scheduled matches (zero lost);
      (b) zero idempotent-key duplicates during normal operation, and a
          deliberately replayed ack — the whole last batch re-sent over the
          wire, as a crashed-after-report evaluator would — dedups 100%
          with the match total unchanged (zero double-counted);
      (c) the round counter advanced exactly once per completed batch;
      (d) the journal reloads into a fresh store that STILL dedups the
          replayed batch (idempotency survives a coordinator restart)."""
    import subprocess

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.makedirs(args.dir, exist_ok=True)

    from distar_tpu.arena import (ArenaStore, match_key, set_arena_store)
    from distar_tpu.comm.coordinator import (CoordinatorServer,
                                             coordinator_request)

    journal = os.path.join(args.dir, "arena.journal")
    ckpt_dir = os.path.join(args.dir, "ckpt")  # empty -> anchors-only roster
    os.makedirs(ckpt_dir, exist_ok=True)
    store = ArenaStore(path=journal)
    set_arena_store(store)
    srv = CoordinatorServer()
    srv.start()
    inj = ChaosInjector(seed=args.seed)
    episodes = int(args.episodes)

    def spawn(batches: int):
        return subprocess.Popen(
            [sys.executable, "-c", _ARENA_CHILD, _REPO, srv.host,
             str(srv.port), ckpt_dir, str(batches), str(episodes), "2", "12"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
            bufsize=1, cwd=_REPO, env={**os.environ, "JAX_PLATFORMS": "cpu"})

    failures = []
    proc = proc2 = None
    try:
        kill_after = max(1, int(args.kill_after))
        total = max(kill_after + 1, int(args.batches))
        proc = spawn(total)
        assert proc.stdout is not None
        for line in proc.stdout:
            if line.startswith(f"BATCH_START {kill_after}"):
                break
        time.sleep(args.kill_delay_s)  # land inside the running scenario
        inj.kill_role(proc.pid, sig=signal.SIGKILL, name="arena-evaluator")
        proc.wait(timeout=60)
        matches_at_kill = store.matches_total
        killed_mid_batch = matches_at_kill < (kill_after + 1) * episodes
        # the hole the restarted evaluator must fill: pure re-ask, no state
        hole = store.next_match([], episodes=episodes)
        if hole is None:
            failures.append("store refused to re-issue the lost assignment")

        proc2 = spawn(total - matches_at_kill // episodes)
        out2, _ = proc2.communicate(timeout=args.timeout_s)
        if proc2.returncode != 0:
            failures.append(f"restarted evaluator exited {proc2.returncode}")
        if "duplicates=0" not in out2 or "EVAL_EXIT" not in out2:
            failures.append(f"restarted evaluator log unexpected: {out2!r}")

        expected = total * episodes
        if store.matches_total != expected:
            failures.append(f"lost matches: applied {store.matches_total}, "
                            f"scheduled {expected}")
        if store.duplicates_total != 0:
            failures.append(f"{store.duplicates_total} duplicates during "
                            "normal operation (keys must be unique)")
        if len(store._seen) != expected:
            failures.append(f"seen-key set has {len(store._seen)} entries, "
                            f"wanted {expected} distinct keys")
        pair = tuple(sorted(store.anchors))
        rounds = store._next_round.get(pair)
        if rounds != total:
            failures.append(f"round counter at {rounds}, wanted {total} "
                            "(one advance per completed batch)")
        if hole is not None:
            refilled = [match_key(hole["home"], hole["away"], hole["round"], i)
                        in store._seen for i in range(episodes)]
            if not all(refilled):
                failures.append(f"re-issued assignment {hole} not fully "
                                f"applied after restart: {refilled}")

        # the double-count arm: replay the final batch's ack over the wire,
        # exactly as an evaluator killed AFTER reporting would on restart
        last = total - 1
        home, away = pair if last % 2 == 0 else (pair[1], pair[0])
        replay = [{"key": match_key(home, away, last, i), "home": home,
                   "away": away, "round": last, "winner": "draw",
                   "game_steps": 1, "duration_s": 0.0}
                  for i in range(episodes)]
        resp = coordinator_request(srv.host, srv.port, "arena_report",
                                   {"matches": replay})
        ack = resp.get("info") if resp.get("code") == 0 else None
        if not ack or ack.get("applied") != 0 \
                or ack.get("duplicates") != episodes:
            failures.append(f"replayed ack was not fully deduped: {resp}")
        if store.matches_total != expected:
            failures.append("replayed ack double-counted matches")

        # idempotency must survive a coordinator restart via the journal
        store.save()
        fresh = ArenaStore(path=journal)
        fresh.maybe_load()
        ack2 = fresh.report_batch(replay)
        if fresh.matches_total != expected or ack2["applied"] != 0:
            failures.append(f"journal reload lost idempotency: "
                            f"matches={fresh.matches_total}, ack={ack2}")

        verdict = {
            "batches": total, "episodes": episodes,
            "killed_after_batch": kill_after,
            "killed_mid_batch": killed_mid_batch,
            "matches_at_kill": matches_at_kill,
            "matches_applied": store.matches_total,
            "duplicates": store.duplicates_total,
            "replayed_ack_deduped": bool(ack and ack.get("duplicates") == episodes),
            "events": [e["kind"] for e in inj.events],
            "failures": failures,
        }
        print(json.dumps(verdict, default=str))
        print("verdict: evaluator killed mid-batch and restarted; zero lost, "
              "zero double-counted, replayed ack deduped before and after a "
              "journal reload" if not failures
              else f"verdict: DRILL FAILED {failures}")
        return 0 if not failures else 1
    finally:
        for p_ in (proc, proc2):
            if p_ is not None and p_.poll() is None:
                p_.kill()
        srv.stop()
        set_arena_store(None)


_COORDINATOR_CHILD = r"""
import os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
repo = sys.argv[1]
if repo not in sys.path:
    sys.path.insert(0, repo)
port, journal_dir, role = int(sys.argv[2]), sys.argv[3], sys.argv[4]
peers = [p for p in sys.argv[5].split(",") if p and p != "-"]
grace, lease_s = float(sys.argv[6]), float(sys.argv[7])
from distar_tpu.arena import ArenaStore, set_arena_store
from distar_tpu.comm.coordinator import Coordinator, CoordinatorServer
set_arena_store(ArenaStore())
co = Coordinator(default_lease_s=lease_s)
srv = CoordinatorServer(coordinator=co, port=port)
if role != "none":
    from distar_tpu.comm.ha import HAState
    ha = HAState(co, journal_dir, advertise="127.0.0.1:%d" % srv.port,
                 peers=peers, role=role, takeover_grace_s=grace,
                 snapshot_every=64)
    ha.boot()
    srv.attach_ha(ha)
srv.start()
print("READY %d" % srv.port, flush=True)
while True:
    time.sleep(1)
"""


def cmd_coordinator_drill(args) -> int:
    """SIGKILL the primary coordinator under live fleet load and prove the
    HA contract end to end (the broker was the fleet's last SPOF):

      LEG 1 — failover: primary + warm standby, live load (producers
      registering payload records, an arena reporter, discovery heartbeats,
      a telemetry shipper) → SIGKILL the primary mid-run. The standby must
      be serving within one lease window; draining the queue afterwards
      must surface EVERY acked register exactly once (semi-synchronous
      replication: an ack means the standby has it); re-reporting every
      acked arena batch must dedup 100% (zero double-counted matches);
      heartbeated leases survive, an abandoned lease is cleanly evicted;
      the revived old primary must rejoin as a STANDBY (epoch fencing) and
      the shipper must have counted a resync.

      LEG 2 — cold restart: kill every coordinator, restart one over its
      journal alone — acked items, arena accounting and dedup keys must be
      reconstructed exactly by snapshot + WAL replay.

      LEG 3 (--no-ha or always-on counter-demo) — a journal-less
      coordinator demonstrably LOSES acked items across the same kill: the
      baseline the durability contract is measured against."""
    import itertools
    import socket
    import subprocess
    import threading

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.makedirs(args.dir, exist_ok=True)

    from distar_tpu.comm import ha as ha_mod
    from distar_tpu.comm.coordinator import coordinator_request
    from distar_tpu.comm.discovery import register_endpoint
    from distar_tpu.obs import get_registry
    from distar_tpu.obs.shipper import TelemetryShipper

    def free_port() -> int:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def spawn(port: int, jdir: str, role: str, peers: str):
        proc = subprocess.Popen(
            [sys.executable, "-c", _COORDINATOR_CHILD, _REPO, str(port),
             jdir, role, peers or "-", str(args.grace_s), str(args.lease_s)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
            bufsize=1, cwd=_REPO, env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.stdout is not None
        deadline = time.time() + 30
        while time.time() < deadline:
            line = proc.stdout.readline()
            if line.startswith("READY"):
                return proc
            if proc.poll() is not None:
                break
        raise RuntimeError(f"coordinator child on :{port} never came up")

    inj = ChaosInjector(seed=args.seed)
    failures = []
    children = []
    lease_s = float(args.lease_s)

    if args.no_ha:
        # counter-demo only: journal-less coordinator loses acked items
        port = free_port()
        proc = spawn(port, os.path.join(args.dir, "none"), "none", "-")
        children.append(proc)
        for i in range(10):
            coordinator_request("127.0.0.1", port, "register",
                                {"token": "demo", "ip": f"10.3.0.{i}", "port": 1})
        inj.kill_role(proc.pid, sig=signal.SIGKILL, name="coordinator")
        proc.wait(timeout=30)
        proc = spawn(port, os.path.join(args.dir, "none"), "none", "-")
        children.append(proc)
        depth = coordinator_request("127.0.0.1", port, "depth",
                                    {"token": "demo"}).get("info")
        lost = 10 - int(depth or 0)
        verdict = {"mode": "no-ha counter-demo", "acked": 10, "lost": lost,
                   "failures": [] if lost > 0 else
                   ["journal-less restart did NOT lose state?"]}
        print(json.dumps(verdict))
        print("verdict: journal-less coordinator lost "
              f"{lost}/10 acked items across a SIGKILL — the loss HA exists "
              "to prevent" if lost > 0 else "verdict: DRILL FAILED")
        for p_ in children:
            if p_.poll() is None:
                p_.kill()
        return 0 if lost > 0 else 1

    p1, p2 = free_port(), free_port()
    addr1, addr2 = f"127.0.0.1:{p1}", f"127.0.0.1:{p2}"
    spec = f"{addr1},{addr2}"
    j1, j2 = os.path.join(args.dir, "j1"), os.path.join(args.dir, "j2")
    ha_mod.reset_targets()

    primary = spawn(p1, j1, "primary", "-")
    standby = spawn(p2, j2, "standby", addr1)
    children += [primary, standby]
    shipper = None
    hb_thread = None
    try:
        # ------------------------------------------------------ live load
        stop_load = threading.Event()
        acked_lock = threading.Lock()
        acked_items: list = []     # "ip:port" acked under token "payload"
        ack_times: list = []
        acked_batches: list = []   # arena batches acked (list of records)
        counter = itertools.count()

        def pusher():
            while not stop_load.is_set():
                i = next(counter)
                ip = f"10.1.{i // 250}.{i % 250}"
                try:
                    r = coordinator_request(spec, None, "register",
                                            {"token": "payload", "ip": ip,
                                             "port": 7}, timeout=5.0)
                    if r.get("code") == 0:
                        with acked_lock:
                            acked_items.append(f"{ip}:7")
                            ack_times.append(time.time())
                except Exception:
                    pass
                time.sleep(0.02)

        def reporter():
            b = 0
            while not stop_load.is_set():
                batch = [{"key": f"a|b|r{b}e{i}", "home": "a", "away": "b",
                          "round": b, "winner": "draw", "game_steps": 1,
                          "duration_s": 0.0} for i in range(4)]
                try:
                    r = coordinator_request(spec, None, "arena_report",
                                            {"matches": batch}, timeout=5.0)
                    if r.get("code") == 0:
                        with acked_lock:
                            acked_batches.append(batch)
                        b += 1
                except Exception:
                    pass
                time.sleep(0.05)

        threads = [threading.Thread(target=pusher, daemon=True),
                   threading.Thread(target=reporter, daemon=True)]
        for t in threads:
            t.start()
        # a heartbeated service lease (must survive the failover) and an
        # abandoned one (must be cleanly evicted when its lease lapses)
        hb_stop = threading.Event()
        hb_thread = register_endpoint((spec, None), "svc", "10.9.0.1", 1,
                                      lease_s=lease_s,
                                      heartbeat_interval_s=max(0.5, lease_s / 8),
                                      stop_event=hb_stop)
        coordinator_request(spec, None, "register",
                            {"token": "svc", "ip": "10.9.0.2", "port": 1,
                             "lease_s": lease_s})
        shipper = TelemetryShipper("coordinator-drill",
                                   coordinator_addr=(spec, None),
                                   interval_s=0.5).start()

        deadline = time.time() + args.timeout_s
        while time.time() < deadline:
            with acked_lock:
                if len(acked_items) >= args.items and len(acked_batches) >= 3:
                    break
            time.sleep(0.1)
        st1 = ha_mod.probe_ha_status(addr1)
        epoch_before = int(st1["epoch"]) if st1 else -1

        # ------------------------------------------------- the SIGKILL
        t_kill = time.time()
        inj.kill_role(primary.pid, sig=signal.SIGKILL, name="coordinator-primary")
        primary.wait(timeout=30)

        with acked_lock:
            acked_at_kill = len(acked_items)
        while time.time() < deadline:
            with acked_lock:
                if len(acked_items) >= acked_at_kill + args.post_items:
                    break
            time.sleep(0.1)
        with acked_lock:
            recovery_s = next((t - t_kill for t in ack_times if t > t_kill),
                              None)
        if recovery_s is None:
            failures.append("no register was acked after the primary kill")
        elif recovery_s > lease_s:
            failures.append(f"standby took {recovery_s:.1f}s to serve "
                            f"(> one lease window {lease_s:.0f}s)")
        st2 = ha_mod.probe_ha_status(addr2)
        if not st2 or st2.get("role") != "primary":
            failures.append(f"standby did not take over: {st2}")
        elif int(st2.get("epoch", -1)) <= epoch_before:
            failures.append(f"promotion did not bump the epoch: {st2} "
                            f"vs {epoch_before}")

        # ------------------------------- epoch fencing: revive the victim
        revived = spawn(p1, j1, "auto", addr2)
        children.append(revived)
        revived_role = None
        for _ in range(40):
            st = ha_mod.probe_ha_status(addr1)
            revived_role = st.get("role") if st else None
            if revived_role == "standby":
                break
            time.sleep(0.25)
        if revived_role != "standby":
            failures.append("revived old primary did not rejoin as standby: "
                            f"{revived_role}")
        stop_load.set()
        for t in threads:
            t.join(timeout=10)

        # ------------------------------------- zero lost acked queue items
        popped = []
        empties = 0
        while empties < 5:
            r = coordinator_request(spec, None, "ask", {"token": "payload"},
                                    timeout=5.0)
            info = r.get("info")
            if r.get("code") == 0 and info:
                popped.append(f"{info['ip']}:{info['port']}")
                empties = 0
            else:
                empties += 1
        with acked_lock:
            acked_set = set(acked_items)
        lost = acked_set - set(popped)
        if lost:
            failures.append(f"{len(lost)} acked queue items lost across "
                            f"failover: {sorted(lost)[:5]}...")
        if len(popped) != len(set(popped)):
            failures.append("a queue item was popped twice")
        extras = len(set(popped) - acked_set)  # applied-but-unacked: benign

        # --------------------------------- zero double-counted arena matches
        st2 = ha_mod.probe_ha_status(addr2) or {}
        with acked_lock:
            replay_all = [rec for batch in acked_batches for rec in batch]
        import urllib.request

        with urllib.request.urlopen(f"http://{addr2}/arena/ratings",
                                    timeout=5.0) as resp:
            matches_before = int(json.loads(resp.read())["matches_total"])
        rr = coordinator_request(spec, None, "arena_report",
                                 {"matches": replay_all})
        ack = rr.get("info") or {}
        if rr.get("code") != 0 or ack.get("applied") != 0 \
                or ack.get("duplicates") != len(replay_all):
            failures.append(f"replayed acked arena batches not fully "
                            f"deduped: {rr}")
        with urllib.request.urlopen(f"http://{addr2}/arena/ratings",
                                    timeout=5.0) as resp:
            matches_after = int(json.loads(resp.read())["matches_total"])
        if matches_after != matches_before:
            failures.append(f"arena matches double-counted across failover: "
                            f"{matches_before} -> {matches_after}")

        # ------------------- every lease re-established or cleanly evicted
        svc = {f"{r['ip']}:{r['port']}" for r in
               coordinator_request(spec, None, "peers",
                                   {"token": "svc"}).get("info") or ()}
        if "10.9.0.1:1" not in svc:
            failures.append("heartbeated lease did not survive the failover")
        evict_deadline = time.time() + lease_s + 5
        while "10.9.0.2:1" in svc and time.time() < evict_deadline:
            time.sleep(0.5)
            svc = {f"{r['ip']}:{r['port']}" for r in
                   coordinator_request(spec, None, "peers",
                                       {"token": "svc"}).get("info") or ()}
        if "10.9.0.2:1" in svc:
            failures.append("abandoned lease was never evicted on the "
                            "new primary")

        resyncs = sum(v for k, v in get_registry().snapshot().items()
                      if k.startswith("distar_obs_shipper_resyncs_total"))
        if resyncs < 1:
            failures.append("telemetry shipper never counted a resync "
                            "across the failover")

        # ------------------------------------------ LEG 2: cold restart
        cold_acked = []
        for i in range(5):
            r = coordinator_request(spec, None, "register",
                                    {"token": "cold", "ip": f"10.2.0.{i}",
                                     "port": 9})
            if r.get("code") == 0:
                cold_acked.append(f"10.2.0.{i}:9")
        hb_stop.set()
        shipper.stop()
        for proc in (standby, revived):
            inj.kill_role(proc.pid, sig=signal.SIGKILL, name="coordinator")
            proc.wait(timeout=30)
        cold = spawn(p2, j2, "auto", addr1)
        children.append(cold)
        st_cold = ha_mod.probe_ha_status(addr2)
        if not st_cold or st_cold.get("role") != "primary":
            failures.append(f"cold restart did not take leadership: {st_cold}")
        cold_popped = []
        empties = 0
        while empties < 5:
            r = coordinator_request(spec, None, "ask", {"token": "cold"},
                                    timeout=5.0)
            info = r.get("info")
            if r.get("code") == 0 and info:
                cold_popped.append(f"{info['ip']}:{info['port']}")
                empties = 0
            else:
                empties += 1
        if set(cold_popped) != set(cold_acked):
            failures.append(f"journal replay lost acked items: wanted "
                            f"{cold_acked}, got {cold_popped}")
        rr = coordinator_request(spec, None, "arena_report",
                                 {"matches": replay_all})
        ack = rr.get("info") or {}
        if rr.get("code") != 0 or ack.get("applied") != 0:
            failures.append(f"arena dedup keys did not survive the cold "
                            f"journal replay: {rr}")
        with urllib.request.urlopen(f"http://{addr2}/arena/ratings",
                                    timeout=5.0) as resp:
            matches_cold = int(json.loads(resp.read())["matches_total"])
        if matches_cold != matches_after:
            failures.append(f"cold replay changed arena accounting: "
                            f"{matches_after} -> {matches_cold}")

        verdict = {
            "acked_items": len(acked_set), "popped": len(popped),
            "applied_unacked_extras": extras,
            "acked_arena_matches": len(replay_all),
            "matches_total": matches_after,
            "recovery_s": recovery_s,
            "lease_window_s": lease_s,
            "epoch_before": epoch_before,
            "epoch_after": st2.get("epoch"),
            "revived_old_primary_role": revived_role,
            "shipper_resyncs": resyncs,
            "cold_restart_items": len(cold_popped),
            "events": [e["kind"] for e in inj.events],
            "failures": failures,
        }
        print(json.dumps(verdict, default=str))
        print("verdict: primary SIGKILL'd under live load; standby served "
              f"in {recovery_s:.1f}s, zero acked items lost, zero arena "
              "matches double-counted, fencing demoted the revived primary, "
              "cold journal replay exact" if not failures
              else f"verdict: DRILL FAILED {failures}")
        return 0 if not failures else 1
    finally:
        if shipper is not None:
            shipper.stop()
        if hb_thread is not None:
            hb_thread.stop_event.set()
        for p_ in children:
            if p_.poll() is None:
                p_.kill()
        ha_mod.reset_targets()


_LEAGUE_PLANE_CHILD = r"""
import os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
repo = sys.argv[1]
if repo not in sys.path:
    sys.path.insert(0, repo)
port, journal_dir = int(sys.argv[2]), sys.argv[3]
players = sys.argv[4].split(",")
seed, lease_s, job_ttl_s = int(sys.argv[5]), float(sys.argv[6]), float(sys.argv[7])
from distar_tpu.arena import ArenaStore, set_arena_store
from distar_tpu.comm.coordinator import Coordinator, CoordinatorServer
from distar_tpu.league.runtime import LeagueService, set_league_service
from distar_tpu.league.runtime.runner import league_cfg
store = ArenaStore()
set_arena_store(store)
service = LeagueService(league_cfg(players), seed=seed,
                        lease_s=lease_s, job_ttl_s=job_ttl_s)
set_league_service(service)
co = Coordinator()
srv = CoordinatorServer(coordinator=co, port=port)
if journal_dir != "-":
    from distar_tpu.comm.ha import HAState
    ha = HAState(co, journal_dir, advertise="127.0.0.1:%d" % srv.port,
                 role="primary", snapshot_every=64,
                 arena_store_fn=lambda: store,
                 league_service_fn=lambda: service)
    ha.boot()
    srv.attach_ha(ha)
srv.start()
print("READY %d" % srv.port, flush=True)
while True:
    time.sleep(1)
"""

_LEAGUE_LEARNER_CHILD = r"""
import json, os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
repo = sys.argv[1]
if repo not in sys.path:
    sys.path.insert(0, repo)
addr, player_id, learner_id = sys.argv[2], sys.argv[3], sys.argv[4]
rounds, sleep_s = int(sys.argv[5]), float(sys.argv[6])
from distar_tpu.league.remote import RemoteLeagueService
remote = RemoteLeagueService(addr, timeout=10.0)
reply = remote.register_learner(player_id, learner_id=learner_id)
print("REG " + json.dumps(reply), flush=True)
if not reply.get("registered"):
    sys.exit(3)
base = int(reply.get("train_seq", -1)) + 1
for i in range(rounds):
    job = remote.ask_job(player_id, learner_id=learner_id)
    rec = {"key": "%se0" % job["job_id"], "home": player_id,
           "away": job["player_ids"][1], "round": 0,
           "winner": ("home", "away", "draw")[i % 3],
           "game_steps": 8, "duration_s": 0.1}
    out = remote.report(job["job_id"], [rec], learner_id=learner_id)
    if out.get("applied"):
        print("MATCH " + json.dumps(rec), flush=True)
    seq = base + i
    gen = "/fake/%s_g%d.ckpt" % (player_id, seq)
    ti = remote.train_info(player_id, seq=seq, train_steps=1,
                           checkpoint_path=gen, generation_path=gen,
                           learner_id=learner_id)
    print("SEQ %d minted=%d snap=%s" % (
        seq, 1 if ti.get("minted") else 0, ti.get("snapshot_id", "-")),
        flush=True)
    time.sleep(sleep_s)
print("DONE %d" % rounds, flush=True)
"""


def cmd_league_drill(args) -> int:
    """SIGKILL one league learner mid-league and prove the matchmaking
    control plane's failure model (the self-play economy must degrade to a
    smaller economy, never a corrupted one):

      * the killed learner's player FREEZES (lease-derived, no tombstone)
        instead of vanishing — it stays on the active roster and its
        minted historical snapshots stay matchable;
      * the surviving learners keep drawing and completing jobs;
      * a supervised restart re-registers under the same learner id and
        resumes its train-info lineage past the service's seq watermark;
      * the dead learner's abandoned assignment expires after the job TTL
        (counted as orphaned) and the assignment map drains to empty —
        matchmaking state is uncorrupted;
      * SIGKILL the coordinator afterwards and cold-restart it over its
        HA journal alone: roster, snapshot lineage, branch counters and
        arena dedup keys reconstruct exactly — re-reporting every acked
        match dedups 100% (zero lost, zero double-counted).

      --no-journal is the counter-demo: the same kill against a
      journal-less control plane provably FORGETS the league (mints gone,
      seq watermark reset, acked matches double-count on replay)."""
    import json as _json
    import socket
    import subprocess
    import threading
    import urllib.request

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.makedirs(args.dir, exist_ok=True)

    from distar_tpu.comm import ha as ha_mod
    from distar_tpu.league.remote import RemoteLeagueService

    players = ("MP0", "EP0", "ME0")
    victim = "EP0"
    lease_s = float(args.lease_s)
    job_ttl_s = float(args.job_ttl_s)
    inj = ChaosInjector(seed=args.seed)
    failures = []
    children = []

    def free_port() -> int:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def spawn_plane(port: int, jdir: str):
        proc = subprocess.Popen(
            [sys.executable, "-c", _LEAGUE_PLANE_CHILD, _REPO, str(port),
             jdir, ",".join(players), str(args.seed), str(lease_s),
             str(job_ttl_s)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
            bufsize=1, cwd=_REPO, env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.stdout is not None
        deadline = time.time() + 30
        while time.time() < deadline:
            line = proc.stdout.readline()
            if line.startswith("READY"):
                children.append(proc)
                return proc
            if proc.poll() is not None:
                break
        raise RuntimeError(f"league control plane on :{port} never came up")

    def spawn_learner(addr: str, pid: str, learner_id: str, rounds: int):
        """Learner child + stdout collector: REG reply, acked match
        records, acked train-info seqs and minted snapshot ids."""
        proc = subprocess.Popen(
            [sys.executable, "-c", _LEAGUE_LEARNER_CHILD, _REPO, addr, pid,
             learner_id, str(rounds), str(args.round_sleep_s)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
            bufsize=1, cwd=_REPO, env={**os.environ, "JAX_PLATFORMS": "cpu"})
        children.append(proc)
        acked = {"reg": None, "matches": [], "seqs": [], "snaps": [],
                 "proc": proc}
        lock = threading.Lock()

        def reader():
            assert proc.stdout is not None
            for line in proc.stdout:
                with lock:
                    if line.startswith("REG "):
                        acked["reg"] = _json.loads(line[4:])
                    elif line.startswith("MATCH "):
                        acked["matches"].append(_json.loads(line[6:]))
                    elif line.startswith("SEQ "):
                        parts = line.split()
                        acked["seqs"].append(int(parts[1]))
                        if parts[2] == "minted=1":
                            acked["snaps"].append(parts[3].split("=", 1)[1])

        threading.Thread(target=reader, daemon=True).start()
        acked["lock"] = lock
        return acked

    def league_status(addr: str) -> dict:
        with urllib.request.urlopen(f"http://{addr}/league/status",
                                    timeout=5.0) as resp:
            return _json.loads(resp.read())

    DIGEST_KEYS = ("active_players", "historical_players", "snapshot_mints",
                   "jobs_by_branch", "orphaned_jobs", "minted")

    ha_mod.reset_targets()
    port = free_port()
    try:
        if args.no_journal:
            # ---------------------- counter-demo: the league is forgotten
            plane = spawn_plane(port, "-")
            addr = f"127.0.0.1:{port}"
            lrn = spawn_learner(addr, "MP0", "MP0-learner", rounds=5)
            lrn["proc"].wait(timeout=60)
            with lrn["lock"]:
                acked_seqs = list(lrn["seqs"])
                acked_matches = list(lrn["matches"])
                mints_acked = len(lrn["snaps"])
            inj.kill_role(plane.pid, sig=signal.SIGKILL,
                          name="league-coordinator")
            plane.wait(timeout=30)
            spawn_plane(port, "-")
            st = league_status(addr)
            remote = RemoteLeagueService(addr, timeout=10.0)
            reg = remote.register_learner("MP0", learner_id="MP0-learner")
            resend = remote.report("RESEND", acked_matches)
            lost_mints = mints_acked - int(st["snapshot_mints"])
            watermark_lost = int(reg.get("train_seq", -1)) < max(acked_seqs)
            double_counted = int(resend.get("applied", 0))
            verdict = {
                "mode": "no-journal counter-demo",
                "acked_mints": mints_acked, "mints_after_restart":
                    st["snapshot_mints"], "lost_mints": lost_mints,
                "seq_watermark_lost": watermark_lost,
                "acked_matches_double_counted": double_counted,
                "failures": [] if (lost_mints > 0 and watermark_lost
                                   and double_counted > 0) else
                ["journal-less restart did NOT lose league state?"],
            }
            print(_json.dumps(verdict))
            lost = not verdict["failures"]
            print("verdict: journal-less control plane forgot "
                  f"{lost_mints} minted snapshots, reset the seq watermark "
                  f"and double-counted {double_counted} acked matches "
                  "across a SIGKILL — the loss the journal exists to prevent"
                  if lost else "verdict: DRILL FAILED")
            return 0 if lost else 1

        # ----------------------------------------------- journaled drill
        jdir = os.path.join(args.dir, "journal")
        plane = spawn_plane(port, jdir)
        addr = f"127.0.0.1:{port}"
        remote = RemoteLeagueService(addr, timeout=10.0)

        survivors = {
            pid: spawn_learner(addr, pid, f"{pid}-learner",
                               rounds=args.rounds)
            for pid in players if pid != victim
        }
        vic = spawn_learner(addr, victim, f"{victim}-learner", rounds=100000)

        # -------------------------- SIGKILL the victim mid-league
        deadline = time.time() + args.timeout_s
        while time.time() < deadline:
            with vic["lock"]:
                if len(vic["seqs"]) >= 3:
                    break
            time.sleep(0.05)
        with vic["lock"]:
            vic_seqs, vic_snaps = list(vic["seqs"]), list(vic["snaps"])
            vic_matches = list(vic["matches"])
        if len(vic_seqs) < 3:
            failures.append("victim learner never reached 3 acked rounds")
        t_kill = time.time()
        inj.kill_role(vic["proc"].pid, sig=signal.SIGKILL,
                      name=f"league-learner-{victim}")
        vic["proc"].wait(timeout=30)
        # a dead actor's ask: dispatched, never reported -> must expire
        orphan_job = remote.ask_job(victim, learner_id="dead-actor")

        # ------------------------ freeze (not vanish) within one lease
        frozen_seen = None
        freeze_deadline = time.time() + lease_s * 3 + 5
        while time.time() < freeze_deadline:
            st = league_status(addr)
            if victim in st["frozen_players"]:
                frozen_seen = st
                break
            time.sleep(0.2)
        if frozen_seen is None:
            failures.append(f"{victim} never froze after the kill")
        else:
            if victim not in frozen_seen["active_players"]:
                failures.append(f"{victim} vanished from the active roster")
            missing = [s for s in vic_snaps
                       if s not in frozen_seen["historical_players"]]
            if missing:
                failures.append(f"killed learner's minted snapshots "
                                f"disappeared: {missing}")
        jobs_at_kill = sum((frozen_seen or st)["jobs_by_branch"].values())

        # ------------- supervised restart resumes the train-info lineage
        vic2 = spawn_learner(addr, victim, f"{victim}-learner",
                             rounds=max(3, args.rounds // 4))
        reg_deadline = time.time() + 30
        reg = None
        while time.time() < reg_deadline:
            with vic2["lock"]:
                reg = vic2["reg"]
            if reg is not None:
                break
            time.sleep(0.1)
        if reg is None or not reg.get("registered"):
            failures.append(f"restarted {victim} failed to register: {reg}")
        elif vic_seqs and int(reg.get("train_seq", -1)) < max(vic_seqs):
            failures.append(
                f"restart lost the seq watermark: register returned "
                f"train_seq={reg.get('train_seq')} < acked {max(vic_seqs)}")
        thaw_deadline = time.time() + lease_s + 10
        while time.time() < thaw_deadline:
            if victim not in league_status(addr)["frozen_players"]:
                break
            time.sleep(0.2)
        else:
            failures.append(f"{victim} stayed frozen after restart")

        for pid, col in {**survivors, victim: vic2}.items():
            if col["proc"].wait(timeout=args.timeout_s) != 0:
                failures.append(f"learner {pid} exited nonzero")
        st = league_status(addr)
        if sum(st["jobs_by_branch"].values()) <= jobs_at_kill:
            failures.append("survivors made no matchmaking progress "
                            "after the kill")

        # ------------- the abandoned assignment expires, map drains clean
        time.sleep(max(0.0, job_ttl_s - (time.time() - t_kill)) + 0.5)
        flush_job = remote.ask_job("MP0", learner_id="drill-flush")
        remote.report(flush_job["job_id"], [], learner_id="drill-flush")
        st1 = league_status(addr)
        if st1["assignments_pending"] != 0:
            failures.append(f"assignment map did not drain: "
                            f"{st1['assignments']}")
        if st1["orphaned_jobs"] < 1:
            failures.append("dead actor's assignment was never counted "
                            "as orphaned")
        if orphan_job and orphan_job["job_id"] in st1["assignments"]:
            failures.append("dead actor's assignment never expired")

        # -------------- cold journal replay: the league state is exact
        all_matches = list(vic_matches)
        for col in list(survivors.values()) + [vic2]:
            with col["lock"]:
                all_matches.extend(col["matches"])
        inj.kill_role(plane.pid, sig=signal.SIGKILL,
                      name="league-coordinator")
        plane.wait(timeout=30)
        spawn_plane(port, jdir)
        st2 = league_status(addr)
        for key in DIGEST_KEYS:
            if st1[key] != st2[key]:
                failures.append(f"cold journal replay diverged on {key}: "
                                f"{st1[key]} != {st2[key]}")
        resend = remote.report("RESEND", all_matches)
        if resend.get("applied", 1) != 0 \
                or resend.get("duplicates") != len(all_matches):
            failures.append(f"acked matches not exactly reconstructed by "
                            f"journal replay: {resend}")

        verdict = {
            "acked_matches": len(all_matches),
            "victim_acked_rounds": len(vic_seqs),
            "victim_minted_snapshots": len(vic_snaps),
            "restart_train_seq": reg and reg.get("train_seq"),
            "snapshot_mints": st2["snapshot_mints"],
            "jobs_by_branch": st2["jobs_by_branch"],
            "orphaned_jobs": st2["orphaned_jobs"],
            "events": [e["kind"] for e in inj.events],
            "failures": failures,
        }
        print(_json.dumps(verdict, default=str))
        print("verdict: learner SIGKILL'd mid-league; its player froze "
              "(still matchable), survivors kept training, the supervised "
              "restart resumed the lineage, the abandoned assignment "
              "expired cleanly, and a cold journal replay reconstructed "
              "the league exactly with zero lost / zero double-counted "
              "acked matches" if not failures
              else f"verdict: DRILL FAILED {failures}")
        return 0 if not failures else 1
    finally:
        for p_ in children:
            if p_.poll() is None:
                p_.kill()
        ha_mod.reset_targets()


def cmd_latest(args) -> int:
    mgr = CheckpointManager(args.dir)
    gens = mgr.generations()
    if not gens:
        print(f"no latest pointer under {args.dir}")
        return 1
    for i, gen in enumerate(gens):
        ok = verify_checkpoint(gen["path"])
        marker = "LATEST " if i == 0 else "       "
        print(f"{marker}step={gen.get('step', '?'):>8}  "
              f"{'ok     ' if ok else 'CORRUPT'}  {gen['path']}")
    resolved = mgr.resolve_latest()
    print(json.dumps({"resolves_to": resolved and resolved["path"]}))
    return 0 if resolved else 2


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = p.add_subparsers(dest="command", required=True)

    c = sub.add_parser("corrupt", help="damage a checkpoint in place")
    c.add_argument("--path", required=True)
    c.add_argument("--mode", choices=("truncate", "bitflip"), default="truncate")
    c.add_argument("--seed", type=int, default=0)
    c.add_argument("--frac", type=float, default=0.5, help="truncate: fraction kept")
    c.add_argument("--flips", type=int, default=8, help="bitflip: bits to flip")

    k = sub.add_parser("kill", help="signal a role process")
    k.add_argument("--pid", type=int, required=True)
    k.add_argument("--signal", default="TERM", choices=("TERM", "KILL", "INT"))

    r = sub.add_parser("reset", help="RST-abort connections to an endpoint")
    r.add_argument("--addr", required=True, help="host:port")
    r.add_argument("--count", type=int, default=1)
    r.add_argument("--seed", type=int, default=0)

    l = sub.add_parser("latest", help="inspect a durable latest pointer")
    l.add_argument("--dir", required=True, help="checkpoint directory")

    d = sub.add_parser("replay-drill",
                       help="kill a replay store mid-run; prove spill recovery")
    d.add_argument("--dir", required=True, help="spill directory")
    d.add_argument("--items", type=int, default=50, help="acked inserts before the kill")
    d.add_argument("--shards", type=int, default=1,
                   help="N > 1: shard-loss variant — kill 1 of N "
                        "consistent-hash shards mid-run; the learner must "
                        "ride through on the rest, only the victim's "
                        "unsampled tail may go missing, and its restart "
                        "must spill-recover exactly that tail (--shards 1 "
                        "is the whole-store counter-demo)")
    d.add_argument("--no-spill", action="store_true",
                   help="counter-demo: run without durability and show the loss")
    d.add_argument("--seed", type=int, default=0)

    s = sub.add_parser("serve-drill",
                       help="kill 1 of N serve gateways under load; prove "
                            "router re-route + exact migration accounting")
    s.add_argument("--gateways", type=int, default=3)
    s.add_argument("--sessions", type=int, default=48)
    s.add_argument("--steps", type=int, default=8,
                   help="episode length per session (kill at the midpoint)")
    s.add_argument("--slots", type=int, default=64, help="slots per gateway")
    s.add_argument("--seed", type=int, default=0)

    h = sub.add_parser("shm-drill",
                       help="SIGKILL the shm-ring peer mid-frame; prove "
                            "typed detection + TCP fallback, zero acked loss")
    h.add_argument("--dir", required=True, help="spill directory")
    h.add_argument("--items", type=int, default=60,
                   help="acked inserts across the kill")
    h.add_argument("--ring-bytes", type=int, default=8192,
                   help="forced tiny ring so frames span it (mid-frame kills)")
    h.add_argument("--seed", type=int, default=0)

    e = sub.add_parser("elastic-drill",
                       help="load spike -> autoscaler scale-up observed "
                            "live -> graceful cooldown drain with exact "
                            "migration accounting -> SIGKILL mid-drain with "
                            "zero acked replay loss")
    e.add_argument("--dir", required=True, help="replay spill root")
    e.add_argument("--slots", type=int, default=8, help="slots per gateway")
    e.add_argument("--sessions", type=int, default=14,
                   help="resident sessions offered (pick > --slots so the "
                        "spike actually sheds)")
    e.add_argument("--items", type=int, default=60,
                   help="acked replay inserts across the drain/kill")
    e.add_argument("--seed", type=int, default=0)

    y = sub.add_parser("dynamics-drill",
                       help="poison one module's params with a NaN mid-run; "
                            "prove census localization, a single exemplar-"
                            "carrying alert, a black-box bundle, and a "
                            "deterministic stepreplay reproduction")
    y.add_argument("--dir", required=True, help="scratch experiment directory")
    y.add_argument("--module", default="",
                   help="top-level param module to poison (default: first "
                        "module, sorted)")
    y.add_argument("--pre-steps", type=int, default=3,
                   help="clean steps before the poison (EMA/census baseline)")
    y.add_argument("--post-steps", type=int, default=3,
                   help="clean steps after (debounce must hold at 1 bundle)")
    y.add_argument("--seed", type=int, default=0)

    a = sub.add_parser(
        "arena-drill",
        help="kill an arena evaluator mid-batch, restart it, prove zero "
             "lost / zero double-counted matches by idempotent keys")
    a.add_argument("--dir", required=True, help="scratch directory (journal "
                   "+ empty checkpoint dir live here)")
    a.add_argument("--batches", type=int, default=4,
                   help="total scenario batches the run must complete")
    a.add_argument("--episodes", type=int, default=6,
                   help="episodes per batch (matches per assignment)")
    a.add_argument("--kill-after", type=int, default=1,
                   help="SIGKILL the evaluator when this batch STARTS "
                        "(this many batches already reported)")
    a.add_argument("--kill-delay-s", type=float, default=0.2,
                   help="wait this long after BATCH_START before the kill")
    a.add_argument("--seed", type=int, default=0)
    a.add_argument("--timeout-s", type=float, default=900.0,
                   help="restarted evaluator wall budget")

    o = sub.add_parser(
        "coordinator-drill",
        help="SIGKILL the primary coordinator under live fleet load; prove "
             "warm-standby failover with zero acked-item loss, exact arena "
             "dedup, lease survival/eviction, epoch fencing of the revived "
             "primary, and an exact cold journal-replay restart")
    o.add_argument("--dir", required=True,
                   help="scratch directory (per-coordinator journals)")
    o.add_argument("--items", type=int, default=30,
                   help="acked payload registers before the kill")
    o.add_argument("--post-items", type=int, default=15,
                   help="further acked registers the fleet must land on the "
                        "standby after the kill")
    o.add_argument("--lease-s", type=float, default=8.0,
                   help="endpoint lease TTL; the failover must complete "
                        "within ONE lease window")
    o.add_argument("--grace-s", type=float, default=1.5,
                   help="standby takeover grace (quiet feed -> promotion)")
    o.add_argument("--no-ha", action="store_true",
                   help="counter-demo: journal-less coordinator provably "
                        "loses acked items across the same SIGKILL")
    o.add_argument("--seed", type=int, default=0)
    o.add_argument("--timeout-s", type=float, default=120.0,
                   help="load-phase wall budget")

    g = sub.add_parser(
        "league-drill",
        help="SIGKILL one league learner mid-league; prove the matchmaker "
             "freezes (not forgets) its player, survivors keep training, a "
             "supervised restart resumes the lineage, the abandoned "
             "assignment expires, and a cold journal replay reconstructs "
             "the league exactly")
    g.add_argument("--dir", required=True,
                   help="scratch directory (the control plane's HA journal)")
    g.add_argument("--rounds", type=int, default=40,
                   help="matchmade rounds each SURVIVOR learner completes "
                        "(the victim runs unbounded until the kill)")
    g.add_argument("--round-sleep-s", type=float, default=0.2,
                   help="per-round think time in the toy learner children")
    g.add_argument("--lease-s", type=float, default=2.0,
                   help="learner lease TTL; the victim's player must freeze "
                        "within ~one window of the kill")
    g.add_argument("--job-ttl-s", type=float, default=5.0,
                   help="assignment TTL; the dead actor's job must expire")
    g.add_argument("--no-journal", action="store_true",
                   help="counter-demo: a journal-less control plane "
                        "provably forgets the league across a SIGKILL")
    g.add_argument("--seed", type=int, default=0)
    g.add_argument("--timeout-s", type=float, default=120.0,
                   help="per-phase wall budget")

    m = sub.add_parser("multichip-drill",
                       help="kill a multichip learner after a sharded save; "
                            "prove resume on a DIFFERENT mesh shape")
    m.add_argument("--dir", required=True, help="experiment scratch directory")
    m.add_argument("--mesh", default="dp=4,fsdp=2",
                   help="mesh the run is killed on")
    m.add_argument("--resume-mesh", default="dp=8",
                   help="mesh the run must finish on (resharding restore)")
    m.add_argument("--host-devices", type=int, default=8)
    m.add_argument("--iters", type=int, default=5, help="target iterations")
    m.add_argument("--kill-after", type=int, default=2,
                   help="kill the learner after this iteration's sharded save")
    m.add_argument("--restart-max", type=int, default=3,
                   help="restart budget (PR 4 RestartPolicy semantics)")
    m.add_argument("--timeout-s", type=float, default=900.0,
                   help="per-child wall budget")

    args = p.parse_args()
    return {"corrupt": cmd_corrupt, "kill": cmd_kill,
            "reset": cmd_reset, "latest": cmd_latest,
            "replay-drill": cmd_replay_drill,
            "serve-drill": cmd_serve_drill,
            "shm-drill": cmd_shm_drill,
            "elastic-drill": cmd_elastic_drill,
            "dynamics-drill": cmd_dynamics_drill,
            "arena-drill": cmd_arena_drill,
            "coordinator-drill": cmd_coordinator_drill,
            "league-drill": cmd_league_drill,
            "multichip-drill": cmd_multichip_drill}[args.command](args)


if __name__ == "__main__":
    raise SystemExit(main())
