"""Pallas-vs-XLA kernel microbench: the silicon A/B for the two flagship
kernels (SURVEY.md §2.3 scatter_connection, §5 entity masked attention).

Runs each op at actor-inference and learner-training shapes, forward and
forward+backward, against its XLA reference, and emits a table
(op, shape, impl, us, speedup). On the tunneled TPU the Pallas kernels lower
natively; on CPU they run interpret=True (labelled — interpret numbers are
for correctness only, never perf).

Usage:
  python tools/bench_kernels.py [--platform tpu|cpu] [--out artifacts/...json]

The chosen config defaults (encoder.entity.attention_impl,
encoder.scatter.impl) should follow this table's data on real silicon.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _time(fn, args, iters=30, warmup=3):
    import jax

    out = None
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1e6  # us


def run(platform: str | None = None, iters: int = 30) -> dict:
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distar_tpu.ops.pallas_kernels import (
        masked_attention,
        masked_attention_reference,
        scatter_add_connection,
        scatter_add_onehot,
    )

    backend = jax.default_backend()
    interpret = backend != "tpu"  # pallas interprets off-TPU
    rng = np.random.default_rng(0)
    rows = []

    # flagship entity-transformer geometry (config: head_dim 128, 2 heads,
    # 512 entities); B=8 ~ actor lockstep fleet, B=64 ~ a learner microbatch.
    # interpret mode (off-TPU) runs a python-level emulation — use toy shapes
    # there, the numbers are correctness-only anyway
    if interpret:
        H, N, Dh = 2, 64, 32
        batches = (2,)
    else:
        H, N, Dh = 2, 512, 128
        # B=8 ~ actor lockstep fleet, B=64 ~ a learner microbatch,
        # B=384 = the learner step's actual b6 x t64 flattened batch
        batches = (8, 64, 384)
    for B in batches:
        q, k, v = (
            jnp.asarray(rng.standard_normal((B, H, N, Dh)), jnp.float32)
            for _ in range(3)
        )
        mask = jnp.asarray(rng.random((B, N)) > 0.2).at[:, 0].set(True)

        impls = {
            "pallas": jax.jit(lambda q, k, v, m: masked_attention(q, k, v, m, interpret)),
            "xla": jax.jit(masked_attention_reference),
        }
        ref = None
        fwd_us = {}
        for name, fn in impls.items():
            out = fn(q, k, v, mask)
            ref = out if ref is None else ref
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3, rtol=2e-3)
            fwd_us[name] = _time(fn, (q, k, v, mask), iters)
        for name in impls:
            rows.append({
                "op": "masked_attention", "pass": "fwd", "shape": f"{B}x{H}x{N}x{Dh}",
                "impl": name, "us": round(fwd_us[name], 1),
                "speedup_vs_xla": round(fwd_us["xla"] / fwd_us[name], 3),
            })

        grads = {
            name: jax.jit(jax.grad(lambda q, k, v, fn=fn: jnp.sum(fn(q, k, v, mask) ** 2), argnums=(0, 1, 2)))
            for name, fn in impls.items()
        }
        bwd_us = {name: _time(g, (q, k, v), max(iters // 3, 5)) for name, g in grads.items()}
        for name in impls:
            rows.append({
                "op": "masked_attention", "pass": "fwd+bwd", "shape": f"{B}x{H}x{N}x{Dh}",
                "impl": name, "us": round(bwd_us[name], 1),
                "speedup_vs_xla": round(bwd_us["xla"] / bwd_us[name], 3),
            })

    # scatter-connection geometry: 512 entities x 32-dim onto the 152x160 map
    if interpret:
        Hm, Wm, D = 20, 16, 8
    else:
        Hm, Wm, D = 152, 160, 32
    for B in batches:
        emb = jnp.asarray(rng.standard_normal((B, N, D)), jnp.float32)
        idx = jnp.asarray(rng.integers(0, Hm * Wm, (B, N)), jnp.int32)

        def _xla_scatter(e, i, hw):
            # same math as ops.scatter_connection's XLA add path
            Bn, Nn, Dn = e.shape
            bias = jnp.arange(Bn, dtype=jnp.int32)[:, None] * hw
            buf = jnp.zeros((Bn * hw, Dn), e.dtype)
            return buf.at[(i + bias).reshape(-1)].add(e.reshape(Bn * Nn, Dn)).reshape(Bn, hw, Dn)

        impls = {
            "pallas": jax.jit(lambda e, i: scatter_add_connection(e, i, Hm * Wm, interpret)),
            "pallas_onehot": jax.jit(lambda e, i: scatter_add_onehot(e, i, Hm * Wm, interpret)),
            "xla": jax.jit(lambda e, i: _xla_scatter(e, i, Hm * Wm)),
        }

        ref = None
        fwd_us = {}
        for name, fn in impls.items():
            out = fn(emb, idx)
            ref = out if ref is None else ref
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3, rtol=2e-3)
            fwd_us[name] = _time(fn, (emb, idx), iters)
        for name in impls:
            rows.append({
                "op": "scatter_add_connection", "pass": "fwd", "shape": f"{B}x{N}x{D}->{Hm}x{Wm}",
                "impl": name, "us": round(fwd_us[name], 1),
                "speedup_vs_xla": round(fwd_us["xla"] / fwd_us[name], 3),
            })

        grads = {
            "pallas": jax.jit(jax.grad(lambda e: jnp.sum(scatter_add_connection(e, idx, Hm * Wm, interpret) ** 2))),
            "pallas_onehot": jax.jit(jax.grad(lambda e: jnp.sum(scatter_add_onehot(e, idx, Hm * Wm, interpret) ** 2))),
            "xla": jax.jit(jax.grad(lambda e: jnp.sum(_xla_scatter(e, idx, Hm * Wm) ** 2))),
        }
        bwd_us = {name: _time(g, (emb,), max(iters // 3, 5)) for name, g in grads.items()}
        for name in grads:
            rows.append({
                "op": "scatter_add_connection", "pass": "fwd+bwd", "shape": f"{B}x{N}x{D}->{Hm}x{Wm}",
                "impl": name, "us": round(bwd_us[name], 1),
                "speedup_vs_xla": round(bwd_us["xla"] / bwd_us[name], 3),
            })

    return {
        "metric": "pallas-vs-xla kernel microbench",
        "backend": backend,
        "pallas_mode": "interpret (correctness only)" if interpret else "native",
        "rows": rows,
    }


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--platform", default=None, choices=[None, "cpu", "tpu"])
    p.add_argument("--iters", type=int, default=30)
    p.add_argument("--out", default=None)
    args = p.parse_args()
    report = run(args.platform, args.iters)
    for r in report["rows"]:
        print(f"  {r['op']:24s} {r['pass']:8s} {r['shape']:20s} {r['impl']:7s} "
              f"{r['us']:10.1f} us   x{r['speedup_vs_xla']:.2f}")
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
    print(json.dumps({k: v for k, v in report.items() if k != "rows"}))


if __name__ == "__main__":
    main()
