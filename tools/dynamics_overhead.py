#!/usr/bin/env python
"""dynamics_overhead: paired A/B cost of the training-dynamics diagnostics.

What the observatory costs on the flagship CPU SL config (full model,
batch 2, unroll 8 — the perf_baseline_cpu_r07 shape): two REAL SLLearners
are built once in the same process —

  * **on**  — what production ships: the per-module diagnostics tree
    (grad/param norms, update ratios, non-finite censuses, clip fraction)
    computed INSIDE the donated train step and riding the step's single
    batched device_get; gauge export every ``--every-n`` steps;
  * **off** — ``dynamics.enabled: false``: the step compiles WITHOUT the
    tree (the spec is static), the pre-observatory step.

Arms interleave (ABAB...) and the verdict is the MEDIAN of PAIRED
per-visit ratios — each visit's on/off ran back-to-back, so the ratio
cancels the host's slow load drift (this class of CI box swings ±10%
between minutes; a ratio of medians would launder that drift into the
verdict). Honesty flags ride in-band: how many timed ON steps actually
crossed an export point (usually zero at every_n=10 over a short window),
with the gauge-publish cost measured separately and amortized into the
headline as ``publish_s / (every_n * step_s_off)`` — the export's device
fetch needs no amortization because the tree rides the log fetch EVERY
step by design. Acceptance (ISSUE r16): headline <= 5% step-time.

    python tools/dynamics_overhead.py --artifact DYNAMICS_r16.json
    python tools/dynamics_overhead.py --iterations 2 --small  # smoke
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time
from typing import List

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

SMALL_MODEL = {
    "encoder": {
        "entity": {"layer_num": 1, "hidden_dim": 32, "output_dim": 16,
                   "head_dim": 8},
        "spatial": {"down_channels": [4, 4, 8], "project_dim": 4,
                    "resblock_num": 1, "fc_dim": 16},
        "scatter": {"output_dim": 4},
        "core_lstm": {"hidden_size": 32, "num_layers": 1},
    },
    "policy": {
        "action_type_head": {"res_dim": 16, "res_num": 1, "gate_dim": 32},
        "delay_head": {"decode_dim": 16},
        "queued_head": {"decode_dim": 16},
        "selected_units_head": {"func_dim": 16},
        "target_unit_head": {"func_dim": 16},
        "location_head": {"res_dim": 8, "res_num": 1,
                          "upsample_dims": [4, 4, 1], "map_skip_dim": 8},
    },
    "value": {"res_dim": 8, "res_num": 1},
}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--batch", type=int, default=2)
    p.add_argument("--unroll", type=int, default=8)
    p.add_argument("--every-n", type=int, default=10,
                   help="ON arm's dynamics export frequency (the production "
                        "default; the tree itself runs every step in-jit)")
    p.add_argument("--iterations", type=int, default=3,
                   help="interleaved paired visits (median ratio wins)")
    p.add_argument("--steps-per-visit", type=int, default=1)
    p.add_argument("--envelope-pct", type=float, default=5.0,
                   help="acceptance: headline overhead within this percent")
    p.add_argument("--small", action="store_true",
                   help="tiny model smoke mode (NOT the flagship claim)")
    p.add_argument("--artifact", default="",
                   help="write JSON lines here (last line = summary)")
    args = p.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("DISTAR_PERF_AOT", "0")
    os.environ.setdefault("DISTAR_EXPERIMENTS_ROOT",
                          tempfile.mkdtemp(prefix="dyn_overhead_"))

    from distar_tpu.fleet import pinning
    from distar_tpu.learner import SLLearner

    def build(tag: str, dynamics_cfg: dict) -> "SLLearner":
        cfg = {
            "common": {"experiment_name": f"dyn_overhead_{tag}"},
            "learner": {
                "batch_size": args.batch, "unroll_len": args.unroll,
                "save_freq": 10 ** 9, "log_freq": 10 ** 9,
                "dynamics": dynamics_cfg,
            },
        }
        if args.small:
            cfg["model"] = SMALL_MODEL
        return SLLearner(cfg)

    t0 = time.perf_counter()
    on = build("on", {"enabled": True, "every_n": args.every_n,
                      "blackbox": False})
    off = build("off", {"enabled": False})
    for learner in (on, off):
        # every run() exit writes a checkpoint (SaveCkptHook after_run) —
        # hundreds of MB of serialization INSIDE the timed visit on the
        # full model; this harness times train steps, not checkpointing
        learner.hooks._hooks["after_run"] = [
            h for h in learner.hooks._hooks["after_run"]
            if h.name != "save_ckpt"]
    lines: List[dict] = []
    last_log = {}

    def visit(learner, steps: int) -> float:
        """Time ``steps`` full iterations (data + donated step + the log
        fetch that the diagnostics tree rides); _train device_gets the info
        tree, so the visit is host-synchronous by construction."""
        target = int(learner.last_iter.val) + steps
        t = time.perf_counter()
        learner.run(max_iterations=target)
        return (time.perf_counter() - t) / steps

    # warmup arm-by-arm: compile + first execute never enter the timing
    # (two visits — the second run() entry retraces residual host paths)
    for learner in (on, off):
        visit(learner, 1)
        visit(learner, 1)
    last_log.update(on.log_buffer)
    setup_s = time.perf_counter() - t0

    arms = {"on": [], "off": []}
    for i in range(max(1, args.iterations)):
        for name, learner in (("on", on), ("off", off)):
            step_s = visit(learner, args.steps_per_visit)
            row = {"metric": "dynamics overhead arm",
                   "case": f"dynamics_{name}", "iteration": i,
                   "step_s": round(step_s, 4)}
            arms[name].append(step_s)
            lines.append(row)
            print(json.dumps(row), flush=True)  # lint: allow-print

    # export steps the timed ON window actually crossed (steps_seen gates
    # publish; warmup consumed step 0, which always publishes)
    timed_on = args.iterations * args.steps_per_visit
    export_steps_timed = sum(
        1 for s in range(1, 1 + timed_on) if s % args.every_n == 0)
    # the gauge-publish leg, measured directly on a real host log dict
    # (pure host work: the device fetch already happened inside _train)
    t = time.perf_counter()
    on._dynamics.publish({k: v for k, v in last_log.items()
                          if isinstance(v, (int, float))})
    publish_s = time.perf_counter() - t

    ratios = [a / b for a, b in zip(arms["on"], arms["off"]) if b > 0]
    ratio = statistics.median(ratios) if ratios else 1.0
    step_s_off = statistics.median(arms["off"])
    amortized_publish_pct = (
        publish_s / (args.every_n * step_s_off) * 100.0 if step_s_off else 0.0)
    overhead_pct = (ratio - 1.0) * 100.0 + amortized_publish_pct
    within = overhead_pct <= args.envelope_pct

    summary = {
        "metric": "training-dynamics diagnostics overhead "
                  "(in-jit tree + export, SL "
                  + ("tiny-model SMOKE" if args.small else "flagship")
                  + " CPU config, paired A/B)",
        "value": round(overhead_pct, 3),
        "unit": "% step-time",
        "overhead_pct": round(overhead_pct, 3),
        "tree_overhead_pct": round((ratio - 1.0) * 100.0, 3),
        "publish_s": round(publish_s, 5),
        "publish_amortized_pct": round(amortized_publish_pct, 4),
        "export_steps_timed": export_steps_timed,
        "paired_ratios": [round(r, 4) for r in ratios],
        "step_s_on": round(statistics.median(arms["on"]), 4),
        "step_s_off": round(step_s_off, 4),
        "every_n": args.every_n,
        "batch": args.batch, "unroll": args.unroll,
        "small_model": bool(args.small),
        "iterations": args.iterations,
        "steps_per_visit": args.steps_per_visit,
        "setup_s": round(setup_s, 1),
        "envelope_pct": args.envelope_pct,
        "within_envelope": within,
        "ab_label": "dynamics",
        "device": "cpu",
        "cpu_derived": True,
        "host_cores": pinning.host_cores(),
        # not a scaling claim — one process, both arms interleaved in the
        # SAME interpreter sharing identical host state (that sharing IS
        # the isolation here; there is nothing to pin apart)
        "scaling_valid": False,
        "pinning": {"pinned": False,
                    "reason": "single-process interleaved A/B: both arms "
                              "share one interpreter and host state"},
        "ts": time.time(),
    }
    lines.append(summary)
    print(json.dumps(summary), flush=True)  # lint: allow-print
    if args.artifact:
        with open(args.artifact, "w") as f:
            for line in lines:
                f.write(json.dumps(line) + "\n")
    return 0 if within else 1


if __name__ == "__main__":
    raise SystemExit(main())
