#!/usr/bin/env python
"""Extract the reference's curated Z strategy libraries as data assets.

The Z libraries (reference: distar/agent/default/lib/*.json, consumed at
agent.py:189-206) are *data*, not code: per-map, per-matchup, per-born-
location strategy statistics (building orders, cumulative-stat index sets,
build locations, loop horizons) distilled from high-MMR human replays by the
reference's gen_z pipeline. Like data/game_contract.json they are game-fact
artifacts the framework consumes; the schema is validated and normalised on
the way through, and every output embeds a ``__provenance__`` block naming
the source. Regenerating them from scratch requires decoding thousands of
ladder replays with a live SC2 install (bin/gen_z.py --replays does exactly
that when one is available).

Usage: python tools/extract_z_data.py [--ref /root/reference] [--out distar_tpu/data/z_libraries]
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys

VALID_RACES = {"zerg", "terran", "protoss", "random"}
# races appear standalone (mirrors) or concatenated (e.g. "zergterran")
MIX_RACES = VALID_RACES | {a + b for a in VALID_RACES for b in VALID_RACES}


def validate_and_normalize(lib: dict, name: str) -> dict:
    """Check the map->mix_race->born_location->entries schema and coerce all
    leaves to plain ints (the loader contract, lib/z_library.py)."""
    out = {}
    n_entries = 0
    for map_name, races in lib.items():
        assert isinstance(map_name, str) and isinstance(races, dict), (name, map_name)
        out_races = {}
        for mix_race, locs in races.items():
            assert mix_race in MIX_RACES, (name, map_name, mix_race)
            assert isinstance(locs, dict), (name, map_name, mix_race)
            out_locs = {}
            for born, entries in locs.items():
                int(born)  # born locations are flat spatial indices
                norm = []
                for e in entries:
                    assert len(e) in (4, 5), (name, map_name, mix_race, born)
                    bo, cum, bo_loc, z_loop = e[:4]
                    rec = [
                        [int(x) for x in bo],
                        [int(x) for x in cum],
                        [int(x) for x in bo_loc],
                        int(z_loop),
                    ]
                    if len(e) == 5:
                        rec.append(int(e[4]))
                    norm.append(rec)
                    n_entries += 1
                out_locs[str(int(born))] = norm
            out_races[mix_race] = out_locs
        out[map_name] = out_races
    print(f"  {name}: {len(out)} maps, {n_entries} entries")
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ref", default="/root/reference")
    ap.add_argument(
        "--out",
        default=os.path.join(os.path.dirname(__file__), "..", "distar_tpu", "data", "z_libraries"),
    )
    args = ap.parse_args()

    src_dir = os.path.join(args.ref, "distar", "agent", "default", "lib")
    os.makedirs(args.out, exist_ok=True)
    count = 0
    for fname in sorted(os.listdir(src_dir)):
        if not fname.endswith(".json"):
            continue
        src = os.path.join(src_dir, fname)
        with open(src) as f:
            raw = f.read()
        lib = validate_and_normalize(json.loads(raw), fname)
        lib["__provenance__"] = {
            "source": f"distar/agent/default/lib/{fname}",
            "sha256": hashlib.sha256(raw.encode()).hexdigest(),
            "tool": "tools/extract_z_data.py",
            "note": (
                "Curated strategy statistics distilled from human ladder "
                "replays by the reference's gen_z pipeline; data asset, "
                "regenerable via bin/gen_z.py --replays with an SC2 install."
            ),
        }
        with open(os.path.join(args.out, fname), "w") as f:
            json.dump(lib, f)
        count += 1
    print(f"extracted {count} Z libraries -> {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
