"""HBM footprint + timing report for the SL/RL train step across batch sizes.

AOT-lowers and compiles the flagship step at each config on the current
backend and prints XLA's ``memory_analysis()`` (argument/output/temp/total
bytes), optimized/unoptimized flop counts, compile time, and — unless
``--steps 0`` — a 16-step chained re-timing (so a chip claim is held for
the compiles plus ~16 steps/config). This is the diagnostic for the b16/b32 batch-scaling cliff
seen in BENCH_LOCAL_r05.json (b6: 9.2 ms/step; b16-e256: 645 ms/step;
b32-e256: compile-helper crash): it separates "spills HBM / falls off the
fused path" from "remote-compile-helper resource limit".

Usage: python tools/memstats.py [--configs 6,16,32] [--unroll 64]
       [--cap 256] [--remat] [--out artifacts/memstats_tpu.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--configs", default="6,12,16,32")
    p.add_argument("--unroll", type=int, default=64)
    p.add_argument("--cap", type=int, default=0, help="entity cap (0 = off)")
    p.add_argument("--remat", action="store_true")
    p.add_argument("--steps", type=int, default=16,
                   help="also TIME this many donated-feedback steps of the "
                        "compiled executable (0 = compile-only). An "
                        "independent, longer-window cross-check of bench.py's "
                        "4-iteration timing.")
    p.add_argument("--out", default="")
    p.add_argument("--mode", default="sl", choices=("sl", "rl"))
    p.add_argument("--platform", default="",
                   help="override jax platform (e.g. cpu). The image pins the "
                        "axon TPU backend via jax.config at interpreter start, "
                        "so the env var alone is too late — and dialing the "
                        "relay blocks when the chip is contended.")
    args = p.parse_args()

    from distar_tpu.utils.compile_cache import configure as _cc

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    _cc(jax, "/tmp/jax_cache_distar_tpu_bench")

    from distar_tpu.learner import RLLearner, SLLearner

    # timing/peak calibration (bench.py's anchor: known-FLOP chained matmul,
    # guarded so a calibration failure never costs the sweep)
    from bench import _calibrate_matmul

    calib = _calibrate_matmul(jax)
    print(f"[memstats] calibration {json.dumps(calib)}", flush=True)

    rows = []
    for b in (int(x) for x in args.configs.split(",")):
        cfg = {
            "common": {"experiment_name": "memstats"},
            "learner": {
                "batch_size": b,
                "unroll_len": args.unroll,
                "save_freq": 10 ** 9,
                "log_freq": 10 ** 9,
                "max_entities": args.cap or None,
                **({"value_pretrain_iters": -1} if args.mode == "rl" else {}),
            },
            "model": {"dtype": "bfloat16", **({"remat": True} if args.remat else {})},
        }
        label = args.mode + f"-b{b}xt{args.unroll}" + (
            f"-e{args.cap}" if args.cap else "") + ("-remat" if args.remat else "")
        print(f"[memstats] {label}: init", flush=True)
        row = {"config": label, "batch": b, "unroll": args.unroll}
        try:
            if args.mode == "rl":
                import jax.numpy as jnp

                learner = RLLearner(cfg)
                data = dict(next(learner._dataloader))
                data.pop("model_last_iter", None)
                batch = learner.shard_batch(learner._cap(data))
                fn_args = (
                    learner.state["params"], learner.state["opt_state"],
                    batch, jnp.asarray(False),
                )
            else:
                learner = SLLearner(cfg)
                data = dict(next(learner._dataloader))
                data.pop("new_episodes", None)
                data.pop("traj_lens", None)
                data = learner._cap(data)
                batch = jax.tree.map(jax.numpy.asarray, data)
                fn_args = (
                    learner.state["params"], learner.state["opt_state"],
                    batch, learner._hidden,
                )
            from distar_tpu.obs.perf import (
                flops_of_compiled, flops_of_lowered, memory_report,
            )

            t0 = time.perf_counter()
            # _train_step is the learner's jitted step (donation + out
            # shardings already applied) — lower exactly what training runs
            lowered = learner._train_step.lower(*fn_args)
            row["trace_s"] = round(time.perf_counter() - t0, 1)
            flops = flops_of_lowered(lowered)
            if flops:
                row["flops_unoptimized"] = flops
            t0 = time.perf_counter()
            compiled = lowered.compile()
            row["compile_s"] = round(time.perf_counter() - t0, 1)
            # executable-level count: post-optimization, the honest MFU
            # numerator (the unoptimized-HLO count can overcount); memory
            # fields come through the same obs/perf.py helper bench.py and
            # the live learner gauges use
            flops = flops_of_compiled(compiled)
            if flops:
                row["flops_optimized"] = flops
            row.update(memory_report(compiled))
            if args.steps > 0:
                # chained re-timing at a longer window than bench's 4 iters:
                # each call consumes the previous call's params/opt (+ the
                # carried hidden state in SL; RL's 4th arg is a static bool)
                def _next(out, prev):
                    carry = out[2] if args.mode == "sl" else prev[3]
                    return (out[0], out[1], batch, carry)

                out = compiled(*fn_args)
                jax.block_until_ready(out)
                t0 = time.perf_counter()
                cur = _next(out, fn_args)
                for _ in range(args.steps):
                    out = compiled(*cur)
                    cur = _next(out, cur)
                jax.block_until_ready(out)
                step_s = (time.perf_counter() - t0) / args.steps
                row["step_time_s"] = round(step_s, 4)
                row["frames_per_sec"] = round(b * args.unroll / step_s, 2)
                if row.get("flops_optimized"):
                    row["implied_tflops"] = round(
                        row["flops_optimized"] / step_s / 1e12, 1
                    )
            del learner, compiled, lowered, batch, fn_args
        except Exception as e:  # keep sweeping: the cliff config may not compile
            row["error"] = repr(e)[:300]
        print(f"[memstats] {json.dumps(row)}", flush=True)
        rows.append(row)

    out = {"metric": f"{args.mode.upper()} step HBM memory analysis + timing",
           "backend": jax.default_backend(),
           "calibration": calib, "rows": rows}
    # a run where EVERY config errored carries no diagnostic value — exit
    # nonzero and write nothing, so a campaign retry loop re-attempts it.
    # Timings alone ARE data (memory/cost introspection can be absent on a
    # backend); any of the three marks the run useful.
    if not any(("total_mb" in r or "flops_optimized" in r or "step_time_s" in r)
               for r in rows):
        print("[memstats] no config produced data; not writing artifact", flush=True)
        sys.exit(1)
    if args.out:
        d = os.path.dirname(args.out)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(out, f, indent=1)
        os.replace(tmp, args.out)  # atomic: a kill never leaves a torn file
        print(f"[memstats] wrote {args.out}", flush=True)


if __name__ == "__main__":
    main()
