#!/usr/bin/env python
"""curve_run: committed toy-run learning curves for the perf_gate curve gate.

Runs the REAL SL and RL learners (tiny flagship-shaped model, CPU) for a
few dozen iterations on a FIXED cycle of fake batches — fixing the data
makes the task memorizable, so total_loss descending is a property of the
whole train step (loss tree, grads, optimizer, donation plumbing), not of
the data stream. The per-iteration total_loss curves are committed as
``artifacts/curves_r<N>.json`` and gated round-over-round by
``perf_gate curve`` next to the distill KL curve the DISTILL artifacts
already carry: a PR that silently breaks learning (bad loss merge, wrong
clip, optimizer state corruption) moves these curves even when every unit
test still passes.

Usage:
  python tools/curve_run.py --round 16 [--iters 24] [--cycle 4] [--seed 0]
  python tools/curve_run.py --out artifacts/curves_r16.json
"""
from __future__ import annotations

import argparse
import json
import math
import os
import platform
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

SMALL_MODEL = {
    "encoder": {
        "entity": {"layer_num": 1, "hidden_dim": 32, "output_dim": 16,
                   "head_dim": 8},
        "spatial": {"down_channels": [4, 4, 8], "project_dim": 4,
                    "resblock_num": 1, "fc_dim": 16},
        "scatter": {"output_dim": 4},
        "core_lstm": {"hidden_size": 32, "num_layers": 1},
    },
    "policy": {
        "action_type_head": {"res_dim": 16, "res_num": 1, "gate_dim": 32},
        "delay_head": {"decode_dim": 16},
        "queued_head": {"decode_dim": 16},
        "selected_units_head": {"func_dim": 16},
        "target_unit_head": {"func_dim": 16},
        "location_head": {"res_dim": 8, "res_num": 1,
                          "upsample_dims": [4, 4, 1], "map_skip_dim": 8},
    },
    "value": {"res_dim": 8, "res_num": 1},
}


class _Cycle:
    """Endless cycle over K pre-drawn batches (shallow-copied per yield:
    the learners pop bookkeeping keys like model_last_iter in place)."""

    def __init__(self, source, k: int):
        self._batches = [next(source) for _ in range(k)]
        self._i = 0

    def __iter__(self):
        return self

    def __next__(self):
        b = self._batches[self._i % len(self._batches)]
        self._i += 1
        return {**b}


def _curve(learner, iters: int, cycle: int) -> list:
    """Drive ``learner.run`` recording per-iteration total_loss host-side
    (one extra sync per iteration — this is a toy harness, not a bench),
    reduced to one point per full pass over the batch cycle: per-batch loss
    LEVELS differ by 3x within a cycle, so consecutive raw iterations are
    not comparable — the per-cycle mean is."""
    losses = []
    orig = learner._train

    def recording(batch):
        log = orig(batch)
        losses.append(float(log["total_loss"]))
        return log

    learner._train = recording
    try:
        learner.run(max_iterations=iters)
    finally:
        learner._train = orig
    return [sum(losses[i:i + cycle]) / cycle
            for i in range(0, len(losses) - cycle + 1, cycle)]


def run_curves(iters: int = 24, cycle: int = 4, seed: int = 0,
               workdir: str = "") -> dict:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("DISTAR_PERF_AOT", "0")
    os.environ["DISTAR_EXPERIMENTS_ROOT"] = \
        workdir or tempfile.mkdtemp(prefix="curve_run_")

    from distar_tpu.learner import RLLearner, SLLearner
    from distar_tpu.learner.data import FakeRLDataloader, FakeSLDataloader

    base_learner_cfg = {
        "batch_size": 2, "unroll_len": 2,
        "save_freq": 10 ** 9, "log_freq": 10 ** 9,
        # curves measure learning, not observability overhead
        "dynamics": {"enabled": False},
    }
    curves = {}

    sl = SLLearner({
        "common": {"experiment_name": "curve_run_sl"},
        # the production default (1e-5) barely moves a toy run; the curve
        # wants visible descent in a few dozen iters
        "learner": dict(base_learner_cfg, learning_rate=1e-3),
        "model": SMALL_MODEL,
    })
    sl.set_dataloader(_Cycle(iter(FakeSLDataloader(2, 2, seed=seed)), cycle))
    curves["sl_total_loss"] = _curve(sl, iters, cycle)

    rl = RLLearner({
        "common": {"experiment_name": "curve_run_rl"},
        # value-pretrain regime: the policy is frozen, so the vtrace/td
        # targets are FIXED and total_loss is a true descent objective on
        # the repeated cycle (the full off-policy surrogate is not — ratio
        # clipping makes it climb on memorized data). teacher == random
        # init, so its KL stays off (the skill-run precedent, rl_soak)
        "learner": dict(base_learner_cfg,
                        learning_rate=1e-3,
                        value_pretrain_iters=10 ** 6,
                        loss={"kl_weight": 0.0,
                              "action_type_kl_weight": 0.0,
                              "entropy_weight": 3e-5}),
        "model": SMALL_MODEL,
    })
    rl.set_dataloader(_Cycle(
        iter(FakeRLDataloader(batch_size=2, unroll_len=2, hidden_size=32,
                              hidden_layers=1, seed=seed)), cycle))
    curves["rl_total_loss"] = _curve(rl, iters, cycle)

    doc = {
        "schema": "distar.curves.v1",
        "metric": "toy-run learning curves (fixed-cycle fake batches)",
        "value": float(len(curves)),
        "unit": "families",
        "iters": iters, "cycle": cycle, "seed": seed,
        "points": "per-cycle mean total_loss over the fixed batch cycle",
        "rl_regime": "value_pretrain (frozen policy: fixed targets)",
        "device": "cpu", "host": platform.node(),
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "curves": {},
    }
    for family, values in curves.items():
        doc["curves"][family] = {
            "iters": len(values),
            "values": [round(v, 5) for v in values],
            "first": round(values[0], 5), "last": round(values[-1], 5),
            "descended": bool(values[-1] < values[0]
                              and all(math.isfinite(v) for v in values)),
        }
    return doc


def main() -> int:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--round", type=int, default=0,
                   help="round number; names artifacts/curves_r<N>.json")
    p.add_argument("--out", default="",
                   help="explicit output path (overrides --round)")
    p.add_argument("--iters", type=int, default=24)
    p.add_argument("--cycle", type=int, default=4,
                   help="distinct fake batches in the fixed cycle")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()
    out = args.out or (os.path.join(_REPO, "artifacts",
                                    f"curves_r{args.round:02d}.json")
                       if args.round else "")

    doc = run_curves(iters=args.iters, cycle=args.cycle, seed=args.seed)
    for family, curve in doc["curves"].items():
        print(f"{family}: {curve['first']:g} -> {curve['last']:g} over "
              f"{curve['iters']} iters (descended={curve['descended']})")
    if out:
        os.makedirs(os.path.dirname(out), exist_ok=True)
        with open(out, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        print(f"wrote {out}")
    else:
        print(json.dumps(doc, indent=1))
    return 0 if all(c["descended"] for c in doc["curves"].values()) else 1


if __name__ == "__main__":
    raise SystemExit(main())
