"""Record the REFERENCE obs/action transforms as golden parity fixtures.

Runs the reference ``Features.transform_obs`` and ``reverse_raw_action``
(reference: distar/agent/default/lib/features.py:463,854 — executed, never
copied) on the shared deterministic dummy protos from
``distar_tpu.envs.dummy_obs.build_parity_fixtures`` and saves every output
field to ``obs_transform.npz``. tests/test_obs_golden_parity.py replays the
SAME fixtures through ``envs/features.ProtoFeatures`` and diffs field by
field — the reference's behavior is the spec for the whole obs contract
(spatial planes, effect lists, the 38-field entity rows and their LUT
remaps, scalar stats, value features, and replay action decoding).

Run:  python tools/record_reference_obs_golden.py --out /tmp/golden_ref
"""
import argparse
import os
import sys
from types import SimpleNamespace as NS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF = "/root/reference"


def fixture_fingerprint() -> str:
    """Hash of the fixture-defining sources: a cached golden npz recorded
    from OLDER fixtures must never be diffed against newer ones (the test
    regenerates on mismatch)."""
    import hashlib

    h = hashlib.sha256()
    for path in (
        os.path.join(REPO, "distar_tpu", "envs", "dummy_obs.py"),
        os.path.abspath(__file__),
    ):
        with open(path, "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


class HF:
    """HasField adapter: the reference checks proto submessage presence via
    HasField; the shared fixtures are SimpleNamespace trees using
    None/absence. Wraps attribute access recursively."""

    def __init__(self, ns):
        object.__setattr__(self, "_ns", ns)

    def HasField(self, name):
        return getattr(self._ns, name, None) is not None

    def __getattr__(self, k):
        v = getattr(self._ns, k)
        return HF(v) if isinstance(v, NS) else v


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default="/tmp/golden_ref")
    args = parser.parse_args()
    os.makedirs(args.out, exist_ok=True)

    sys.path.insert(0, REPO)
    sys.path.insert(0, REF)
    from record_reference_golden import install_stub_modules

    install_stub_modules()

    import numpy as np
    import torch

    from distar_tpu.envs.dummy_obs import build_parity_fixtures

    fx = build_parity_fixtures()

    from distar.agent.default.lib.features import Features

    feat = Features(fx["game_info"], fx["first_obs"], cfg={})

    arrays = {
        "meta/fingerprint": np.asarray(fixture_fingerprint()),
        "meta/home_born_location": np.asarray(feat.home_born_location),
        "meta/away_born_location": np.asarray(feat.away_born_location),
    }

    def put(key, value):
        if isinstance(value, torch.Tensor):
            value = value.numpy()
        arrays[key] = np.asarray(value)

    ret = feat.transform_obs(
        fx["obs"], padding_spatial=True, opponent_obs=fx["opponent_obs"]
    )
    for k, v in ret["spatial_info"].items():
        put(f"spatial/{k}", v)
    for k, v in ret["entity_info"].items():
        put(f"entity/{k}", v)
    for k, v in ret["scalar_info"].items():
        put(f"scalar/{k}", v)
    for k, v in ret["value_feature"].items():
        put(f"vf/{k}", v)
    put("entity_num", ret["entity_num"])
    gi = ret["game_info"]
    put("game/tags", np.asarray(gi["tags"], np.int64))
    put("game/game_loop", gi["game_loop"])
    put("game/battle_score", gi["battle_score"])
    put("game/opponent_battle_score", gi["opponent_battle_score"])
    put("game/action_result", np.asarray(gi["action_result"], np.int64))
    arrays["game/map_name"] = np.asarray(gi["map_name"])

    tags = gi["tags"]
    for name, raw_action in fx["actions"]:
        action = HF(NS(action_raw=raw_action))
        (action_ret, action_mask, sun, last_sel_tags, last_target_tag,
         invalid) = feat.reverse_raw_action(action, tags)
        base = f"act/{name}"
        for k, v in action_ret.items():
            put(f"{base}/{k}", v)
        for k, v in action_mask.items():
            put(f"{base}/mask_{k}", v)
        put(f"{base}/selected_units_num", sun)
        put(f"{base}/invalid", np.asarray(bool(invalid)))
        put(f"{base}/last_selected_tags",
            np.asarray(last_sel_tags if last_sel_tags else [], np.int64))
        put(f"{base}/last_target_tag",
            np.asarray(-1 if last_target_tag is None else last_target_tag, np.int64))

    # ---- Z extraction (reference get_z, features.py:419-460) -------------
    traj = [
        {"action_info": {
            "action_type": torch.tensor(s["action_info"]["action_type"]),
            "target_location": torch.tensor(s["action_info"]["target_location"]),
        }}
        for s in fx["z_stream"]
    ]
    beginning_order, cumulative_stat, bo_len, bo_location = feat.get_z(traj)
    put("z/beginning_order", beginning_order)
    put("z/cumulative_stat", cumulative_stat)
    put("z/bo_len", bo_len)
    put("z/bo_location", bo_location)

    path = os.path.join(args.out, "obs_transform.npz")
    np.savez_compressed(path, **arrays)
    print(f"recorded obs_transform: {len(arrays)} arrays -> {path}")


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    main()
