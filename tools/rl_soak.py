"""RL end-to-end soak: ~100 league-RL iterations on the mock env, with
invariant checks the 2-iteration smoke can't see.

Role: the long-horizon proof of the reference rl_train call stack
(SURVEY.md §3.1 — actor rollouts -> adapter data plane -> learner train
step -> weight publication -> league train-info/snapshot), asserting:

  * weight propagation: the actor's received-model high-water mark keeps
    rising and tracks the learner within the publication cadence
  * off-policy staleness: bounded (mean/max) across every batch
  * league lifecycle: train-info advances the player's total_train_steps
    and the one_phase_step snapshot fires (historical player appears)
  * compute-time stability: median train time of the last quarter vs the
    first quarter after warmup — catches leaks/regressions that creep in
    over minutes, the failure mode a 2-iter smoke can't see (wall iter time
    is reported but not asserted: it settles at the actor production rate)

Usage:  python tools/rl_soak.py [--iters 100] [--out artifacts/rl_soak.json]
The JSON report is ALWAYS written (long-run telemetry must survive a failed
bound); invariant violations land in report["invariant_violations"] and
main() exits 1 when any are present.
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

SMALL_MODEL = {
    "encoder": {
        "entity": {"layer_num": 1, "hidden_dim": 32, "output_dim": 16, "head_dim": 8},
        "spatial": {"down_channels": [4, 4, 8], "project_dim": 4, "resblock_num": 1, "fc_dim": 16},
        "scatter": {"output_dim": 4},
        "core_lstm": {"hidden_size": 32, "num_layers": 1},
    },
    "policy": {
        "action_type_head": {"res_dim": 16, "res_num": 1, "gate_dim": 32},
        "delay_head": {"decode_dim": 16},
        "queued_head": {"decode_dim": 16},
        "selected_units_head": {"func_dim": 16},
        "target_unit_head": {"func_dim": 16},
        "location_head": {"res_dim": 8, "res_num": 1, "upsample_dims": [4, 4, 1], "map_skip_dim": 8},
    },
    "value": {"res_dim": 8, "res_num": 1},
}

def _pin_cpu() -> None:
    """The image's sitecustomize pins jax to the tunneled TPU; the soak is a
    host-side correctness run and must not contend for the chip (same recipe
    as __graft_entry__._pin_virtual_cpu_mesh / tests/conftest.py)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from distar_tpu.utils.compile_cache import configure as _cc
    _cc(jax, "/tmp/jax_cache_distar_tpu")


def run_soak(iters: int = 100, batch_size: int = 4, traj_len: int = 2,
             env_num: int = 2, features: bool = False, actor_threads: int = 1,
             win_rule: str = "random", opponent_pipeline: str = "default",
             learn: bool = False, episode_game_loops: int = 300,
             cache_size: int = 64, prefill: int = 0,
             prefill_timeout: float = 1800.0,
             opponent_heavy: bool = False) -> dict:
    """``features=True`` additionally exercises the round-4 knobs in
    combination for the whole soak: actor+learner pad-to-bucket entity
    caps, per-parameter save_grad logging, and periodic ASYNC checkpoint
    saves racing the train loop.

    Round-5 regimes on top:
      * ``actor_threads``/``env_num`` scale trajectory production; on a
        single host the per-frame cost ratio (actor rollout+teacher vs
        learner fwd+bwd) caps how learner-bound the live equilibrium can
        get, so ``prefill`` additionally banks N trajectories BEFORE the
        learner starts — the drain then measures the SATURATED regime (the
        TPU-learner + CPU-fleet shape: data_share ~0, occupancy ~1,
        queue-aged staleness) with the same machinery
      * ``win_rule='battle'`` + ``opponent_pipeline='scripted.random'`` +
        ``learn=True`` is the SKILL regime (VERDICT r4 #4b): the learnable
        mock-world rule, a model-free random opponent, and RL hyperparams
        that let the policy move (teacher-KL off, modest entropy, higher
        lr) — winrate vs the scripted opponent and the ELO gap are recorded
        every iteration so the report carries a curve."""
    _pin_cpu()
    # sized so >=1 one_phase_step snapshot fires inside the soak
    one_phase_step = max(1, int(iters * batch_size * traj_len * 0.6))
    from distar_tpu.actor import Actor
    from distar_tpu.comm import Adapter, Coordinator
    from distar_tpu.envs import MockEnv
    from distar_tpu.league import League
    from distar_tpu.learner import RLLearner
    from distar_tpu.learner.hooks import LambdaHook
    from distar_tpu.learner.rl_dataloader import RLDataLoader

    league_cfg = {
        "league": {
            # opponent-heavy matchmaking fills the vs-HP0 payoff meter from
            # game 1, so a skill run's winrate curve shows the CLIMB (with
            # the default sp-heavy mix the meter only fills after learning
            # has already moved the policy)
            **({"branch_probs": {
                "MainPlayer": {"sp": 0.1, "pfsp": 0.7, "eval": 0.2},
            }} if opponent_heavy else {}),
            "active_players": {
                "player_id": ["MP0"],
                "checkpoint_path": ["mp0.ckpt"],
                "pipeline": ["default"],
                "frac_id": [1],
                "z_path": ["3map.json"],
                "z_prob": [0.0],
                "teacher_id": ["T"],
                "teacher_path": ["t.ckpt"],
                "one_phase_step": [one_phase_step],
                "chosen_weight": [1.0],
            },
            "historical_players": {
                "player_id": ["HP0"],
                "checkpoint_path": ["hp0.ckpt"],
                "pipeline": [opponent_pipeline],
                "frac_id": [1],
                "z_path": ["3map.json"],
                "z_prob": [0.0],
            },
        }
    }
    league = League(league_cfg)
    co = Coordinator()
    learner_adapter = Adapter(coordinator=co)
    actors = []
    for a_i in range(actor_threads):
        actors.append(Actor(
            cfg={"actor": {"env_num": env_num, "traj_len": traj_len,
                           "seed": 7 + a_i,
                           **({"max_entities": 256} if features else {})}},
            league=league,
            adapter=Adapter(coordinator=co),
            model_cfg=SMALL_MODEL,
            env_fn=lambda a_i=a_i: MockEnv(
                episode_game_loops=episode_game_loops, seed=11 + a_i,
                win_rule=win_rule,
            ),
        ))

    stop = threading.Event()
    actor_err: list = []

    def actor_loop(actor):
        while not stop.is_set():
            try:
                actor.run_job(episodes=1)
            except Exception as e:  # pragma: no cover - surfaced in report
                actor_err.append(repr(e))
                return

    threads = [
        threading.Thread(target=actor_loop, args=(a,), daemon=True) for a in actors
    ]
    for t in threads:
        t.start()

    learner = RLLearner(
        {
            "common": {"experiment_name": "rl_soak"},
            # features spread LAST: dict literals resolve duplicates
            # last-wins, so it must override the base save_freq
            "learner": {"batch_size": batch_size, "unroll_len": traj_len,
                        "save_freq": 10 ** 9, "log_freq": 25,
                        **({"max_entities": 256, "save_grad": True,
                            "save_freq": max(iters // 5, 1)} if features else {}),
                        # skill regime: policy must be free to move — the
                        # teacher is the random init, so its KL would pin
                        # the policy to noise (reference turns this dial
                        # through its rl yaml too)
                        **({"learning_rate": 5e-4,
                            "loss": {"kl_weight": 0.0,
                                     "action_type_kl_weight": 0.0,
                                     "entropy_weight": 3e-5}} if learn else {})},
            "model": SMALL_MODEL,
        }
    )
    # the pull cache bounds worst-case staleness when the LEARNER is the
    # bottleneck: every buffered trajectory ages one learner iter per
    # consumed batch, so depth is a freshness/throughput dial (the reference
    # measures-but-never-drops, rl_learner.py:90-101 — same policy here)
    dataloader = RLDataLoader(learner_adapter, "MP0", batch_size,
                              cache_size=cache_size)
    learner.set_dataloader(dataloader)
    learner.attach_comm(learner_adapter, "MP0", league=league,
                        send_model_freq=4, send_train_info_freq=4)

    telemetry = {
        "iter_times": [], "train_times": [], "data_times": [],
        "staleness_mean": [], "staleness_max": [],
        "total_loss": [], "grad_norm": [], "actor_model_iter": [],
        "historical_count": [], "winrate_hp0": [], "elo_gap": [],
        "games": [], "prefetch_occupancy": [], "actor_model_iter_min": [],
        "broker_depth": [],
    }
    last_t = [time.perf_counter()]

    def record(lrn):
        now = time.perf_counter()
        telemetry["iter_times"].append(now - last_t[0])
        last_t[0] = now
        vr = lrn.variable_record
        telemetry["train_times"].append(vr.get("train_time").val)
        telemetry["data_times"].append(vr.get("data_time").val)
        telemetry["staleness_mean"].append(vr.get("staleness/mean").val)
        telemetry["staleness_max"].append(vr.get("staleness/max").val)
        telemetry["total_loss"].append(vr.get("total_loss").val)
        telemetry["grad_norm"].append(vr.get("grad_norm").val)
        per_actor = [
            max(a.model_iter_highwater.values() or [0]) for a in actors
        ]
        telemetry["actor_model_iter"].append(max(per_actor))
        # the LAGGIEST producer drives trajectory staleness; the freshest
        # one would under-credit the accounting bound (multi-actor runs)
        telemetry["actor_model_iter_min"].append(min(per_actor))
        telemetry["historical_count"].append(len(league.historical_players))
        mp0 = league.all_players["MP0"]
        telemetry["winrate_hp0"].append(
            round(mp0.payoff.win_rate_opponent("HP0", use_prior=False), 4)
        )
        ratings = league.elo.ratings()
        telemetry["elo_gap"].append(
            round(ratings.get("MP0", 0.0) - ratings.get("HP0", 0.0), 2)
        )
        telemetry["games"].append(int(mp0.total_game_count))
        telemetry["prefetch_occupancy"].append(round(dataloader.occupancy(), 3))
        # live backlog only: records past the producers' 120s serve window
        # are expired payloads (loss, not aging)
        telemetry["broker_depth"].append(co.depth(dataloader.token, max_age_s=120.0))

    learner.hooks.add(LambdaHook("soak_record", "after_iter", record, freq=1))
    if prefill > cache_size:
        print(f"[soak] prefill {prefill} clamped to cache {cache_size} "
              "(the pull cache caps what can be banked)", flush=True)
    prefill = min(max(prefill, 0), cache_size)
    prefill_s = 0.0
    if prefill:
        t_pf = time.perf_counter()
        while dataloader.buffered() < prefill:
            if time.perf_counter() - t_pf > prefill_timeout:
                break  # run with whatever banked; the report shows how much
            if actor_err:
                # dead actors can't refill: running on would drain the bank
                # then busy-wait forever — abort while there is nothing to lose
                raise RuntimeError(f"actor died during prefill: {actor_err}")
            time.sleep(1.0)
        prefill_s = time.perf_counter() - t_pf
        print(f"[soak] prefill: {dataloader.buffered()} trajectories "
              f"banked in {prefill_s:.0f}s", flush=True)
    t0 = time.perf_counter()
    learner.run(max_iterations=iters)
    wall = time.perf_counter() - t0
    stop.set()
    for t in threads:
        t.join(timeout=120)

    # ---- invariants -----------------------------------------------------
    # collected, not raised: a violated bound must never DISCARD an hour of
    # telemetry — the report carries the violations and main() exits nonzero
    violations = []

    def check(ok: bool, msg: str) -> None:
        if not ok:
            violations.append(msg)

    check(not actor_err, f"actor loop died: {actor_err}")
    check(learner.last_iter.val == iters,
          f"learner stopped at iter {learner.last_iter.val}, wanted {iters}")

    propagated = telemetry["actor_model_iter"]
    check(propagated[-1] > 0, "actor never received published weights")
    check(propagated[-1] >= iters - 24,
          f"actor weights stale at end: iter {propagated[-1]} vs learner {iters}")
    # (no monotonicity assertion on the high-water mark — it is
    # non-decreasing by construction; backwards application of a stale
    # publication is prevented at the source by _refresh_models' iter guard)

    smax = max(telemetry["staleness_max"])
    check(smax <= iters, f"staleness {smax} exceeds total iterations")
    smean_tail = statistics.fmean(telemetry["staleness_mean"][iters // 2:])
    occ_tail = statistics.fmean(telemetry["prefetch_occupancy"][iters // 2:])
    # staleness decomposes EXACTLY into (a) how far the producing actor's
    # weights lagged the learner and (b) how long the trajectory aged in
    # the queue — so the bound is an accounting check built from the
    # measured components (+32 slack), not a flat number: unexplained
    # staleness (e.g. a recycled-trajectory bug) still fails, while a
    # starved-core refresh lag or a deliberately saturated queue doesn't
    # false-alarm. Both components are themselves visible in the report.
    lag_tail = statistics.fmean(
        (i + 1) - p
        for i, p in enumerate(telemetry["actor_model_iter_min"])
        if i >= iters // 2
    )
    # queue aging spans BOTH buffered hops: the learner-side pull cache AND
    # the broker backlog (trajectories registered but not yet fetched, aging
    # in producer serve windows — curve-regime runs bank 40+ there while
    # the client cache reads empty)
    broker_tail = statistics.fmean(telemetry["broker_depth"][iters // 2:])
    queue_tail = (
        (occ_tail * cache_size + broker_tail) / max(batch_size, 1) * 8
    )
    staleness_bound = 32.0 + max(lag_tail, 0.0) + queue_tail
    check(smean_tail < staleness_bound,
          f"tail staleness mean {smean_tail:.1f} exceeds {staleness_bound:.0f} "
          f"(actor lag {lag_tail:.1f} + queue {queue_tail:.1f} + 32 slack)")
    # crediting measured lag must not let the publication path itself rot:
    # refresh lag from a starved core grows with run speed, so the cap
    # scales with iters, but a sustained mid-run propagation stall (lag ~
    # iters/2) still fails even though the endpoint check recovered
    lag_cap = max(48.0, 0.25 * iters)
    check(lag_tail < lag_cap,
          f"tail actor weight lag {lag_tail:.1f} exceeds {lag_cap:.0f} — "
          "publication path stalling mid-run")

    train_steps = league.all_players["MP0"].total_agent_step
    check(train_steps > 0, "league never saw train info")
    snapshots = telemetry["historical_count"][-1] - telemetry["historical_count"][0]
    check(snapshots >= 1,
          f"no league snapshot fired in {iters} iters "
          f"(train_steps={train_steps}, one_phase_step={one_phase_step})")

    # leak check on COMPUTE time only: wall iter time legitimately settles
    # at the actor's production rate once the compile-window trajectory
    # backlog drains (off-policy equilibrium), so data wait is reported, not
    # asserted
    times = telemetry["train_times"][5:]  # drop compile/warmup
    q = max(len(times) // 4, 1)
    head, tail = times[:q], times[-q:]
    ratio = statistics.median(tail) / max(statistics.median(head), 1e-9)
    check(ratio < 2.5, f"train time drifted {ratio:.2f}x over the soak")

    finite = [x for x in telemetry["total_loss"] if x == x and abs(x) != float("inf")]
    check(len(finite) == len(telemetry["total_loss"]), "non-finite loss seen")

    def curve(series, buckets=10):
        """Bucket means over the iteration axis: a compact trend curve."""
        if not series:
            return []
        step = max(len(series) // buckets, 1)
        return [
            round(statistics.fmean(series[i:i + step]), 4)
            for i in range(0, len(series), step)
        ]

    return {
        "features_on": bool(features),
        "invariant_violations": violations,
        "regime": {
            "actor_threads": actor_threads, "env_num": env_num,
            "batch_size": batch_size, "traj_len": traj_len,
            "win_rule": win_rule, "opponent_pipeline": opponent_pipeline,
            "learn": bool(learn), "episode_game_loops": episode_game_loops,
            "cache_size": cache_size, "prefill": prefill,
            "prefill_s": round(prefill_s, 1),
            "opponent_heavy": bool(opponent_heavy),
        },
        "skill": {
            # read winrate points against games_curve: buckets before the
            # first finished game show the meter's empty default, not play
            "winrate_vs_HP0_curve": curve(telemetry["winrate_hp0"]),
            "elo_gap_curve": curve(telemetry["elo_gap"]),
            "games_curve": curve(telemetry["games"]),
            "final_winrate_vs_HP0": telemetry["winrate_hp0"][-1] if telemetry["winrate_hp0"] else None,
            "final_elo_gap": telemetry["elo_gap"][-1] if telemetry["elo_gap"] else None,
            "games_played": telemetry["games"][-1] if telemetry["games"] else 0,
        },
        "iters": iters,
        "wall_s": round(wall, 1),
        "train_time_s": {
            "median": round(statistics.median(times), 3),
            "p90": round(sorted(times)[int(len(times) * 0.9)], 3),
            "head_median": round(statistics.median(head), 3),
            "tail_median": round(statistics.median(tail), 3),
            "drift_ratio": round(ratio, 3),
        },
        "wall_iter_s": {
            "median": round(statistics.median(telemetry["iter_times"][5:]), 3),
            # the reference bar: 0.67 learner steps/s (BASELINE.md, derived)
            "steps_per_sec": round(
                1.0 / max(statistics.median(telemetry["iter_times"][5:]), 1e-9), 3
            ),
            "data_share": round(
                sum(telemetry["data_times"]) /
                max(sum(telemetry["data_times"]) + sum(telemetry["train_times"]), 1e-9),
                3,
            ),
            "prefetch_occupancy_tail_mean": round(
                statistics.fmean(telemetry["prefetch_occupancy"][iters // 2:]), 3
            ) if telemetry["prefetch_occupancy"] else None,
        },
        "staleness": {
            "mean_tail": round(smean_tail, 2),
            "max": int(smax),
            "actor_lag_tail": round(lag_tail, 2),
            "queue_age_tail": round(queue_tail, 2),
            "broker_depth_tail": round(broker_tail, 2),
        },
        "weights": {
            "actor_final_iter": int(propagated[-1]),
        },
        "league": {
            "train_steps": int(train_steps),
            "snapshots": int(snapshots),
            "games": int(league.all_players["MP0"].total_game_count),
            "elo_games": int(league.elo.game_count),
        },
        "loss": {
            "first10_mean": round(statistics.fmean(telemetry["total_loss"][:10]), 4),
            "last10_mean": round(statistics.fmean(telemetry["total_loss"][-10:]), 4),
        },
    }


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=100)
    p.add_argument("--out", default="artifacts/rl_soak.json")
    p.add_argument("--features", action="store_true",
                   help="soak with entity caps + save_grad + async saves on")
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--traj-len", type=int, default=2)
    p.add_argument("--env-num", type=int, default=2)
    p.add_argument("--actor-threads", type=int, default=1)
    p.add_argument("--win-rule", default="random",
                   choices=("random", "first", "battle"))
    p.add_argument("--opponent-pipeline", default="default",
                   help="HP0 pipeline, e.g. scripted.random")
    p.add_argument("--learn", action="store_true",
                   help="skill regime: teacher-KL off, higher lr")
    p.add_argument("--episode-loops", type=int, default=300)
    p.add_argument("--cache", type=int, default=64,
                   help="pull-cache depth (trajectories); staleness dial")
    p.add_argument("--prefill", type=int, default=0,
                   help="bank N trajectories before the learner starts "
                        "(saturated-regime measurement)")
    p.add_argument("--vs-opponent-heavy", action="store_true",
                   help="matchmaking mix weighted toward HP0 so the "
                        "winrate curve fills from game 1")
    args = p.parse_args()
    if args.cache < 1:
        p.error("--cache must be >= 1 (a zero-depth pull cache deadlocks)")
    if args.prefill < 0:
        p.error("--prefill must be >= 0")
    if args.prefill > args.cache:
        p.error(f"--prefill {args.prefill} exceeds --cache {args.cache}; "
                "the pull cache caps what can be banked")
    report = run_soak(
        args.iters, batch_size=args.batch, traj_len=args.traj_len,
        env_num=args.env_num, features=args.features,
        actor_threads=args.actor_threads, win_rule=args.win_rule,
        opponent_pipeline=args.opponent_pipeline, learn=args.learn,
        episode_game_loops=args.episode_loops, cache_size=args.cache,
        prefill=args.prefill, opponent_heavy=args.vs_opponent_heavy,
    )
    report["invariants"] = [
        "actor weights propagate and end within 24 iters of the learner",
        "staleness max <= total iters; tail staleness mean < "
        "measured actor lag + queue aging + 32 (accounting bound)",
        "league train-info advances and >=1 one_phase_step snapshot fires",
        "median TRAIN time drifts < 2.5x from first to last quarter (wall iter time reported, not asserted)",
        "every loss value finite",
    ]
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))
    if report["invariant_violations"]:
        print("INVARIANT VIOLATIONS:", report["invariant_violations"],
              file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
