#!/usr/bin/env bash
# Regenerate the vendored SC2 proto subset (distar_tpu/envs/sc2/_proto_gen)
# from distar_tpu/envs/sc2/protos/*.proto using the system protoc.
set -euo pipefail
cd "$(dirname "$0")/.."
SRC=distar_tpu/envs/sc2/protos
OUT=distar_tpu/envs/sc2/_proto_gen
mkdir -p "$OUT"
protoc --proto_path="$SRC" --python_out="$OUT" "$SRC"/*.proto
# protoc emits absolute sibling imports; make them package-relative
sed -i -E 's/^import ([a-z0-9_]+_pb2) as/from . import \1 as/' "$OUT"/*_pb2.py
touch "$OUT/__init__.py"
echo "generated: $(ls "$OUT")"
