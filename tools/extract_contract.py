"""Extract the game-data contract from the reference repo into JSON.

The 327-entry action table and the unit/buff/upgrade/ability id vocabularies
are *game data*, not code — the new framework must agree with the reference on
them bit-for-bit or nothing (replays, Z files, pretrained ckpts) interops.
This tool AST-parses the reference sources (never imports them, no torch
needed) and emits ``distar_tpu/data/game_contract.json``.

Sources (reference):
  distar/agent/default/lib/actions.py   — ACTIONS table literal
  distar/pysc2/lib/static_data.py       — id vocabularies + ability remaps

Run:  python tools/extract_contract.py
"""
import ast
import json
import os

REF = "/root/reference"
OUT = os.path.join(os.path.dirname(__file__), "..", "distar_tpu", "data", "game_contract.json")


def literal_assignments(path, names):
    """Return {name: literal_value} for top-level assignments in ``path``."""
    with open(path) as f:
        tree = ast.parse(f.read())
    found = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name) and t.id in names:
                try:
                    found[t.id] = ast.literal_eval(node.value)
                except (ValueError, TypeError):
                    pass
    missing = set(names) - set(found)
    if missing:
        raise SystemExit(f"missing literals in {path}: {missing}")
    return found


def stat_tables(path):
    """Extract unit_dict / cum_dict / action_result_dict literals and the
    ACTION_RACE_MASK (a dict of torch.tensor([...bool...]) calls) from the
    reference stat module."""
    with open(path) as f:
        tree = ast.parse(f.read())
    out = {}
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        name = getattr(node.targets[0], "id", None)
        if name in ("unit_dict", "cum_dict", "action_result_dict"):
            out[name] = ast.literal_eval(node.value)
        elif name == "ACTION_RACE_MASK":
            mask = {}
            for key_node, val_node in zip(node.value.keys, node.value.values):
                race = ast.literal_eval(key_node)
                assert isinstance(val_node, ast.Call)  # torch.tensor([...])
                mask[race] = [bool(x) for x in ast.literal_eval(val_node.args[0])]
            out["action_race_mask"] = mask
    return out


def main():
    actions = literal_assignments(
        os.path.join(REF, "distar/agent/default/lib/actions.py"), ["ACTIONS"]
    )["ACTIONS"]
    stat = stat_tables(os.path.join(REF, "distar/agent/default/lib/stat.py"))
    static = literal_assignments(
        os.path.join(REF, "distar/pysc2/lib/static_data.py"),
        [
            "ABILITIES",
            "UNIT_TYPES",
            "BUFFS",
            "UPGRADES",
            "ADDON",
            "UNIT_SPECIFIC_ABILITIES",
            "UNIT_GENERAL_ABILITIES",
            "UNIT_MIX_ABILITIES",
            "ORDER_ACTIONS",
        ],
    )

    contract = {
        "_provenance": {
            "reference": "opendilab/DI-star @ /root/reference",
            "actions_source": "distar/agent/default/lib/actions.py (ACTIONS literal)",
            "static_source": "distar/pysc2/lib/static_data.py (id vocabularies)",
        },
        "actions": actions,
        **{k.lower(): v for k, v in static.items()},
        **stat,
    }
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "w") as f:
        json.dump(contract, f, separators=(",", ":"))
    sizes = {k: (len(v) if isinstance(v, list) else "-") for k, v in contract.items()}
    print(json.dumps(sizes, indent=2))


if __name__ == "__main__":
    main()
