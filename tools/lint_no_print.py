"""No-print lint: library code must not write raw stdout.

Rejects bare ``print(`` calls in ``distar_tpu/`` outside ``bin/`` (CLI
entrypoints own their stdout; library code must route output through the
TextLogger / metrics registry so large-scale runs stay greppable and
scrapeable). Token-based, so strings, comments and ``pprint``-style names
never false-positive. A line may opt out with ``# lint: allow-print``
(none currently do).

Invoked from the test suite (tests/test_no_print_lint.py) and runnable
standalone: ``python tools/lint_no_print.py``.
"""
from __future__ import annotations

import io
import os
import sys
import tokenize
from typing import List, Tuple

ALLOW_MARKER = "# lint: allow-print"


def find_bare_prints(root: str) -> List[Tuple[str, int, str]]:
    """Scan ``root``/**.py (excluding bin/) for bare print( calls; returns
    (relpath, lineno, line-text) per offence."""
    offences = []
    for dirpath, dirnames, filenames in os.walk(root):
        rel_dir = os.path.relpath(dirpath, root)
        parts = rel_dir.split(os.sep)
        if "bin" in parts or "_proto_gen" in parts or "__pycache__" in parts:
            dirnames[:] = []
            continue
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            offences.extend(_scan_file(path, os.path.relpath(path, root)))
    return offences


def _scan_file(path: str, relpath: str) -> List[Tuple[str, int, str]]:
    with open(path, "rb") as f:
        source = f.read()
    lines = source.decode("utf-8", errors="replace").splitlines()
    out = []
    try:
        tokens = list(tokenize.tokenize(io.BytesIO(source).readline))
    except tokenize.TokenizeError:
        return out
    for i, tok in enumerate(tokens):
        if tok.type != tokenize.NAME or tok.string != "print":
            continue
        # attribute access (x.print) or def print(...) is not the builtin
        prev = tokens[i - 1] if i > 0 else None
        if prev is not None and prev.type == tokenize.OP and prev.string == ".":
            continue
        if prev is not None and prev.type == tokenize.NAME and prev.string in ("def", "class"):
            continue
        nxt = tokens[i + 1] if i + 1 < len(tokens) else None
        if nxt is None or nxt.type != tokenize.OP or nxt.string != "(":
            continue
        lineno = tok.start[0]
        line = lines[lineno - 1] if lineno <= len(lines) else ""
        if ALLOW_MARKER in line:
            continue
        out.append((relpath, lineno, line.strip()))
    return out


def main() -> int:
    pkg_root = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                            "distar_tpu")
    offences = find_bare_prints(pkg_root)
    for relpath, lineno, line in offences:
        sys.stderr.write(f"{relpath}:{lineno}: bare print() in library code: {line}\n")
    if offences:
        sys.stderr.write(
            f"{len(offences)} offence(s); route output through TextLogger or the "
            "metrics registry (see docs/observability.md)\n"
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
