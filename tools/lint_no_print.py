"""No-print lint — thin shim over the analysis framework's ``no-print`` rule.

Library code must not write raw stdout: ``distar_tpu/`` outside ``bin/``
routes output through the TextLogger / metrics registry so large-scale runs
stay greppable and scrapeable. The actual checker lives in
``distar_tpu/analysis/hygiene.py`` (one parse pass shared with every other
rule); this CLI and ``find_bare_prints`` keep the original surface so
existing test invocations and docs keep working. A line may opt out with
``# lint: allow-print`` (legacy marker) or an
``# analysis: allow(no-print) — <why>`` pragma.

Invoked from the test suite (tests/test_obs_metrics.py) and runnable
standalone: ``python tools/lint_no_print.py``. The full analyzer is
``python tools/analyze.py`` (docs/analysis.md).
"""
from __future__ import annotations

import os
import sys
from typing import List, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

ALLOW_MARKER = "# lint: allow-print"


def find_bare_prints(root: str) -> List[Tuple[str, int, str]]:
    """Scan ``root``/**.py (excluding bin/) for bare print( calls; returns
    (relpath, lineno, line-text) per offence — the pre-framework shape."""
    from distar_tpu.analysis import ParsedModule, collect_files
    from distar_tpu.analysis.hygiene import HygieneChecker

    checker = HygieneChecker()
    offences = []
    for path in collect_files([root]):
        mod = ParsedModule(path, os.path.relpath(path, root).replace(os.sep, "/"))
        if mod.syntax_error is not None:
            continue
        for f in checker.check_module(mod):
            if f.rule != "no-print" or mod.pragma_for(f.line, f.rule) is not None:
                continue
            offences.append(
                (os.path.relpath(path, root), f.line, mod.line_text(f.line).strip())
            )
    return offences


def main() -> int:
    pkg_root = os.path.join(_REPO, "distar_tpu")
    offences = find_bare_prints(pkg_root)
    for relpath, lineno, line in offences:
        sys.stderr.write(f"{relpath}:{lineno}: bare print() in library code: {line}\n")
    if offences:
        sys.stderr.write(
            f"{len(offences)} offence(s); route output through TextLogger or the "
            "metrics registry (see docs/observability.md)\n"
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
