"""SL learning curve with a HELD-OUT eval set (SURVEY §7 milestone 4,
VERDICT r4 #4a).

Builds a family of scripted fake-server replays sharing one behavioral rule
(a build -> train -> attack command cycle; per-replay seeds vary unit
choices, build positions, pacing and length), two-pass-decodes them through
the PRODUCTION client stack (websocket + protos + RemoteController +
ReplayDecoder), trains the SL learner on the train split, and evaluates
action_type_acc on decoded replays the learner NEVER saw. The rule is
recoverable from the decoded features (last_action_type drives the cycle),
so held-out accuracy rising past chance and plateauing demonstrates
GENERALIZED imitation, not memorization — the game-free analogue of the
reference's SL milestone (replays -> sl_train -> accuracy climbing).

Usage:  python tools/sl_curve.py [--rounds 12] [--iters-per-round 40]
        [--out artifacts/sl_curve_r05.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

SMALL_MODEL = {
    "encoder": {
        "entity": {"layer_num": 1, "hidden_dim": 32, "output_dim": 16, "head_dim": 8},
        "spatial": {"down_channels": [4, 4, 8], "project_dim": 4, "resblock_num": 1, "fc_dim": 16},
        "scatter": {"output_dim": 4},
        "core_lstm": {"hidden_size": 32, "num_layers": 1},
    },
    "policy": {
        "action_type_head": {"res_dim": 16, "res_num": 1, "gate_dim": 32},
        "delay_head": {"decode_dim": 16},
        "queued_head": {"decode_dim": 16},
        "selected_units_head": {"func_dim": 16},
        "target_unit_head": {"func_dim": 16},
        "location_head": {"res_dim": 8, "res_num": 1, "upsample_dims": [4, 4, 1], "map_skip_dim": 8},
    },
    "value": {"res_dim": 8, "res_num": 1},
}


def _pin_cpu() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    from distar_tpu.utils.compile_cache import configure as _cc

    _cc(jax, "/tmp/jax_cache_distar_tpu")


def make_scripted_replay(seed: int, n_actions: int = 30):
    """One replay from the shared behavioral rule, seed-varied in every
    non-rule dimension (acting units, build sites, pacing, length)."""
    from distar_tpu.lib import actions as ACT

    def gab(name):
        return next(a["general_ability_id"] for a in ACT.ACTIONS if a["name"] == name)

    rng = np.random.default_rng(seed)
    build = gab("Build_Hatchery_pt")
    train = gab("Train_Drone_quick")
    attack = gab("Attack_unit")
    actions = []
    loop = int(rng.integers(8, 14))
    n = n_actions + int(rng.integers(-4, 5))
    for i in range(n):
        tag = [10000 + int(rng.integers(0, 8))]
        kind = i % 3  # THE rule: build -> train -> attack, forever
        if kind == 0:
            site = (18.0 + float(rng.integers(0, 12)), 28.0 + float(rng.integers(0, 8)))
            actions.append((loop, build, tag, site))
        elif kind == 1:
            actions.append((loop, train, tag, None))
        else:
            actions.append((loop, attack, tag, 20001))
        loop += int(rng.integers(22, 40))
    return {
        "base_build": 75689,
        "game_version": "4.10.0",
        "data_version": "FAKE",
        "map_name": "KairosJunction",
        "game_duration_loops": loop + 50,
        "players": [
            {"player_id": 1, "race": 2, "mmr": 4800, "apm": 160, "result": 1},
            {"player_id": 2, "race": 2, "mmr": 4600, "apm": 140, "result": 2},
        ],
        "actions": actions,
    }


def decode_family(root: str, seeds) -> int:
    """Decode one replay per seed into ``root`` (ReplayDataset layout)."""
    from distar_tpu.envs.replay_decoder import ReplayDecoder
    from distar_tpu.envs.sc2.fake_sc2 import FakeGameCore, FakeSC2Server
    from distar_tpu.envs.sc2.remote_controller import RemoteController
    from distar_tpu.learner.sl_dataloader import ReplayDataset

    decoded = 0
    for seed in seeds:
        server = FakeSC2Server(game=FakeGameCore(end_at=100_000))
        server.game.replay_library["r.SC2Replay"] = make_scripted_replay(seed)
        dec = ReplayDecoder(
            cfg={"minimum_action_length": 2, "parse_race": "Z"},
            controller_provider=lambda v, port=server.port: RemoteController(
                "127.0.0.1", port, timeout_seconds=5
            ),
        )
        try:
            traj = dec.run("r.SC2Replay", player_index=0)
        finally:
            dec.close()
            server.stop()
        if traj:
            ReplayDataset.save(root, f"s{seed:04d}", traj)
            decoded += 1
    return decoded


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--rounds", type=int, default=12)
    p.add_argument("--iters-per-round", type=int, default=40)
    p.add_argument("--train-replays", type=int, default=8)
    p.add_argument("--eval-replays", type=int, default=4)
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--unroll", type=int, default=4)
    p.add_argument("--out", default="artifacts/sl_curve_r05.json")
    args = p.parse_args()
    _pin_cpu()

    import tempfile

    from distar_tpu.learner import SLLearner
    from distar_tpu.learner.sl_dataloader import ReplayDataset, SLDataloader

    work = tempfile.mkdtemp(prefix="sl_curve_")
    train_root = os.path.join(work, "train")
    eval_root = os.path.join(work, "eval")
    t0 = time.perf_counter()
    n_train = decode_family(train_root, range(100, 100 + args.train_replays))
    n_eval = decode_family(eval_root, range(900, 900 + args.eval_replays))
    decode_s = time.perf_counter() - t0
    assert n_train and n_eval, (n_train, n_eval)

    learner = SLLearner(
        {
            "common": {"experiment_name": "sl_curve"},
            "learner": {
                "batch_size": args.batch, "unroll_len": args.unroll,
                "save_freq": 10 ** 9, "log_freq": 10 ** 9,
                "learning_rate": 3e-4,
            },
            "model": SMALL_MODEL,
        }
    )
    learner.set_dataloader(
        SLDataloader(ReplayDataset(train_root), args.batch, args.unroll, seed=1)
    )

    curve = []
    total_iters = 0
    for _ in range(args.rounds):
        learner.run(max_iterations=total_iters + args.iters_per_round)
        total_iters += args.iters_per_round
        train_acc = float(learner.variable_record.get("action_type_acc").avg)
        ev = learner.evaluate(
            SLDataloader(ReplayDataset(eval_root), args.batch, args.unroll, seed=2),
            max_batches=10,
        )
        curve.append(
            {
                "iter": total_iters,
                "train_action_type_acc": round(train_acc, 4),
                "eval_action_type_acc": round(ev["action_type_acc"], 4),
                "eval_total_loss": round(ev["total_loss"], 2),
            }
        )
        print(json.dumps(curve[-1]), flush=True)

    accs = [c["eval_action_type_acc"] for c in curve]
    chance = 1.0 / 3.0  # the rule cycles three action types
    report = {
        "metric": "held-out action_type_acc (scripted-rule replay family)",
        "decode": {"train_replays": n_train, "eval_replays": n_eval,
                   "decode_s": round(decode_s, 1)},
        "config": {"batch": args.batch, "unroll": args.unroll,
                   "iters_per_round": args.iters_per_round,
                   "rounds": args.rounds, "model": "small"},
        "curve": curve,
        "summary": {
            "first_eval_acc": accs[0],
            "best_eval_acc": max(accs),
            "final_eval_acc": accs[-1],
            "chance_level": round(chance, 4),
            "rises_past_chance": max(accs) > chance + 0.1,
            # plateau: the last quarter moves < 5 points
            "plateaued": (max(accs[-max(len(accs) // 4, 2):])
                          - min(accs[-max(len(accs) // 4, 2):])) < 0.05,
        },
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report["summary"]))


if __name__ == "__main__":
    main()
