"""Data-plane micro-bench: serializer + socket shuttle + Adapter throughput.

The reference's feed sustains 300 actors pushing traj-16 windows through its
Adapter TCP plane with lz4-compressed pickle payloads (reference:
distar/ctools/worker/coordinator/adapter.py:66-246,
distar/ctools/utils/file_helper.py:21). This tool quantifies ours:

  * serializer: pickle+zlib-1 vs raw pickle, dumps and loads MB/s, on a
    REAL trajectory payload (fake_rl_batch — the actual wire shape actors
    push);
  * socket plane: serve+fetch round trip over loopback, C++ shuttle vs the
    pure-Python fallback, at trajectory-sized payloads;
  * end-to-end Adapter push/pull through an in-process Coordinator.

Prints a human table and one JSON line. CPU-only (no jax import).
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402


def _mb(n_bytes: int) -> float:
    return n_bytes / 1e6


def bench_serializer(payload, iters: int = 5):
    from distar_tpu.comm.serializer import MAGIC_LZ, MAGIC_ZLIB, dumps, loads

    out = {}
    for compress in (True, False):
        blob = dumps(payload, compress=compress)
        t0 = time.perf_counter()
        for _ in range(iters):
            blob = dumps(payload, compress=compress)
        dt_d = (time.perf_counter() - t0) / iters
        t0 = time.perf_counter()
        for _ in range(iters):
            loads(blob)
        dt_l = (time.perf_counter() - t0) / iters
        # label by the codec that actually ran (the blob magic), not by
        # assumption: with g++ present dumps(compress=True) emits LZ4
        if not compress:
            key = "raw"
        else:
            key = {MAGIC_LZ: "lz4", MAGIC_ZLIB: "zlib1"}.get(blob[:4], "compressed")
        out[key] = {
            "blob_mb": round(_mb(len(blob)), 2),
            "dumps_mb_s": round(_mb(len(blob)) / dt_d, 1),
            "loads_mb_s": round(_mb(len(blob)) / dt_l, 1),
        }
    return out


def bench_shuttle(blob: bytes, iters: int = 10):
    """serve+fetch round trip MB/s over loopback, native vs python."""
    from distar_tpu.comm import shuttle

    results = {}
    impls = {}
    if shuttle.native_available():
        impls["cpp"] = (shuttle.serve, shuttle.fetch)
    impls["python"] = (shuttle._py_serve, shuttle._py_fetch)
    for name, (serve, fetch) in impls.items():
        # warmup
        port = serve(blob, 1, 10_000)
        got = fetch("127.0.0.1", port, 10_000)
        assert got == blob, f"{name} shuttle corrupted the payload"
        t0 = time.perf_counter()
        for _ in range(iters):
            port = serve(blob, 1, 10_000)
            fetch("127.0.0.1", port, 10_000)
        dt = (time.perf_counter() - t0) / iters
        results[name] = {
            "payload_mb": round(_mb(len(blob)), 2),
            "round_trip_ms": round(dt * 1000, 2),
            "mb_s": round(_mb(len(blob)) / dt, 1),
        }
    return results


def bench_adapter(payload, iters: int = 8, compress: bool = True):
    """End-to-end push/pull through an in-process Coordinator (the full
    production path: serialize -> shuttle serve -> coordinator register ->
    ask -> shuttle fetch -> deserialize)."""
    from distar_tpu.comm.adapter import Adapter
    from distar_tpu.comm.coordinator import Coordinator
    from distar_tpu.comm.serializer import dumps

    size = _mb(len(dumps(payload, compress=compress)))
    co = Coordinator()
    push_side = Adapter(coordinator=co, compress=compress)
    pull_side = Adapter(coordinator=co, compress=compress)
    push_side.push("bench", payload)
    pull_side.pull("bench")
    t0 = time.perf_counter()
    for _ in range(iters):
        push_side.push("bench", payload)
        pull_side.pull("bench")
    dt = (time.perf_counter() - t0) / iters
    return {
        "payload_mb": round(size, 2),
        "round_trip_ms": round(dt * 1000, 2),
        "mb_s": round(size / dt, 1),
    }


def main():
    from distar_tpu.comm import shuttle
    from distar_tpu.comm.serializer import dumps
    from distar_tpu.learner.data import fake_rl_batch

    traj_len = int(os.environ.get("DP_BENCH_TRAJ", 16))
    payload = fake_rl_batch(1, traj_len, rng=np.random.default_rng(0))
    raw = dumps(payload, compress=False)
    print(f"payload: 1 actor trajectory window (traj_len={traj_len}), "
          f"{_mb(len(raw)):.1f} MB raw pickle")
    print(f"native shuttle available: {shuttle.native_available()}")

    ser = bench_serializer(payload)
    shut = bench_shuttle(raw)
    compressed_label = next((k for k in ser if k != "raw"), "compressed")
    adap = {
        compressed_label: bench_adapter(payload, compress=True),
        "raw": bench_adapter(payload, compress=False),
    }

    print("\nserializer (pickle):")
    for k, v in ser.items():
        print(f"  {k:6s} blob={v['blob_mb']:7.2f} MB  dumps={v['dumps_mb_s']:8.1f} MB/s  "
              f"loads={v['loads_mb_s']:8.1f} MB/s")
    print("shuttle serve+fetch round trip (loopback):")
    for k, v in shut.items():
        print(f"  {k:6s} {v['payload_mb']:7.2f} MB  {v['round_trip_ms']:8.2f} ms  "
              f"{v['mb_s']:8.1f} MB/s")
    print("adapter end-to-end push+pull (in-process coordinator):")
    for k, v in adap.items():
        print(f"  {k:6s} {v['payload_mb']:7.2f} MB  {v['round_trip_ms']:8.2f} ms  "
              f"{v['mb_s']:8.1f} MB/s")

    print(json.dumps({
        "metric": "data-plane MB/s",
        "serializer": ser,
        "shuttle": shut,
        "adapter": adap,
    }))


if __name__ == "__main__":
    main()
