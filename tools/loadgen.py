"""Serve-plane load generator: closed/open loop, throughput + latency tails.

Drives an ``InferenceGateway`` through any of three targets:

  * in-process (default) — a mock-engine gateway built right here; measures
    the batching/session machinery itself with zero network
  * ``--tcp host:port``  — the framed-TCP data plane of a running
    ``bin/serve.py``
  * ``--http host:port`` — the JSON frontend (expect float-inflation
    overhead; this is the showmatch path, not the actor path)

Modes (the canonical load-test shapes):
  * closed   — ``--clients N`` workers each issue the next request the
    moment the previous returns (think-time 0): measures saturated
    throughput and the batch coalescing under full load.
  * open     — requests arrive at ``--rate R`` per second on a fixed
    schedule regardless of completions: measures latency at a given offered
    load and shed behaviour past saturation.
  * sessions — the eval-farm/ladder shape: SESSIONS arrive at ``--rate R``
    per second, each plays ``--requests-per-session`` sequential steps on
    its own sticky session and then ends it (freeing the slot), so
    thousands of distinct sessions can be sustained on one gateway whose
    slot table is far smaller. Arrivals past live capacity shed typed
    (``CapacityError``) — the summary reports the shed RATE, which is the
    eval-farm sizing number.

  * fleet    — the multi-gateway capacity harness (``--mode fleet``):
    spawns ``--gateways`` real gateway SUBPROCESSES (the jax-free
    ``serve.fleet.gateway_proc``, ``--slots`` lanes each — or drives an
    external fleet via ``--tcp a:p,b:p``), mounts the session-affinity
    ``FleetClient`` router over them, and sweeps ``--fleet-levels``
    CONCURRENT resident sessions: each level allocates that many sticky
    sessions fleet-wide (worker threads interleave many live sessions
    each, so concurrency is server-side slot residency, not thread
    count), steps every session ``--requests-per-session`` times and
    ends it. Reports the sessions/gateway distribution and the
    shed-rate curve as levels sweep past fleet slot capacity — the
    numbers a 10k+ session deployment is sized against.

Output: bench.py-style JSON result lines on stdout (the LAST line is the
summary), optionally mirrored to ``--artifact <path>``. A mid-run hot swap
(``--swap-at <frac>``) exercises the registry under load and reports swap
duration + any in-flight disruption (there must be none).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import List, Optional

import numpy as np

sys.path.insert(0, ".")  # runnable as `python tools/loadgen.py` from repo root

from distar_tpu.obs import get_registry  # noqa: E402
from distar_tpu.serve import (  # noqa: E402
    InferenceGateway,
    MockModelEngine,
    ServeClient,
    ShedError,
)


class _TraceTap:
    """Per-run trace bookkeeping (``--trace``): mints a root span per
    request, finishes it with the outcome, and remembers the trace_ids of
    the slowest and shedded requests so the summary links straight to
    retrievable waterfalls (``opsctl trace --id <id>``)."""

    def __init__(self, enabled: bool):
        self.enabled = bool(enabled)
        # per-thread buckets, merged at summary time: the tap must not add
        # a contended lock to every request of the very bench that measures
        # tracing overhead
        self._local = threading.local()
        self._buckets: List[dict] = []
        self._buckets_lock = threading.Lock()

    def _bucket(self) -> dict:
        b = getattr(self._local, "b", None)
        if b is None:
            b = self._local.b = {"ok": [], "shed": []}
            with self._buckets_lock:
                self._buckets.append(b)
        return b

    def mint(self, session: str):
        if not self.enabled:
            return None
        from distar_tpu.obs import start_trace

        return start_trace("loadgen_request", session=session)

    def done(self, ctx, dt: Optional[float] = None, outcome: str = "ok") -> None:
        if ctx is None:
            return
        from distar_tpu.obs import finish_trace

        finish_trace(ctx, "loadgen_done", outcome=outcome)
        b = self._bucket()
        if outcome == "ok" and dt is not None:
            ok = b["ok"]
            ok.append((dt, ctx["trace_id"]))
            if len(ok) > 4096:  # keep the tail bounded mid-run
                ok.sort(key=lambda p: -p[0])
                del ok[256:]
        elif outcome == "shed":
            shed = b["shed"]
            shed.append(ctx["trace_id"])
            del shed[:-16]

    def summary(self) -> dict:
        if not self.enabled:
            return {}
        with self._buckets_lock:
            buckets = list(self._buckets)
        ok = [p for b in buckets for p in b["ok"]]
        shed = [t for b in buckets for t in b["shed"]]
        top = sorted(ok, key=lambda p: -p[0])[:5]
        return {"slowest_traces": [
            {"trace_id": t, "latency_s": round(d, 6)} for d, t in top],
            "shed_traces": shed[-5:]}


class _Stats:
    def __init__(self):
        self.lat: List[float] = []
        self.ok = 0
        self.shed = 0
        self.errors = 0
        self._lock = threading.Lock()

    def record(self, dt: Optional[float], kind: str) -> None:
        with self._lock:
            if kind == "ok":
                self.ok += 1
                self.lat.append(dt)
            elif kind == "shed":
                self.shed += 1
            else:
                self.errors += 1

    def quantile(self, q: float) -> float:
        with self._lock:
            if not self.lat:
                return 0.0
            return float(np.quantile(np.asarray(self.lat), q))


def _make_obs(i: int) -> dict:
    return {"x": np.full((4, 4), float(i % 7), dtype=np.float32)}


class _InprocTarget:
    def __init__(self, slots: int, delay_s: float, max_delay_s: float, capacity: int,
                 idle_ttl_s: float = 300.0):
        self.engine = MockModelEngine(slots, params={"version": "v1", "bias": 0.0},
                                      delay_s=delay_s)
        self.gateway = InferenceGateway(
            self.engine, max_delay_s=max_delay_s, queue_capacity=capacity,
            idle_ttl_s=idle_ttl_s,
        ).start()
        self.gateway.load_version("v1", params={"version": "v1", "bias": 0.0},
                                  activate=True)

    def act(self, session: str, obs, timeout_s: float, trace=None):
        from distar_tpu.obs import wire_ctx

        return self.gateway.act(session, obs, timeout_s,
                                trace=wire_ctx(trace) if trace else None)

    def end(self, session: str) -> None:
        self.gateway.end_session(session)

    def swap(self) -> None:
        self.gateway.load_version("v2", params={"version": "v2", "bias": 1.0},
                                  activate=True)

    def close(self) -> None:
        self.gateway.drain_and_stop()


class _TcpTarget:
    def __init__(self, addr: str):
        host, port = addr.rsplit(":", 1)
        self._mk = lambda: ServeClient(host, int(port))
        self._local = threading.local()

    def _client(self) -> ServeClient:
        c = getattr(self._local, "c", None)
        if c is None:
            c = self._local.c = self._mk()
        return c

    def act(self, session: str, obs, timeout_s: float, trace=None):
        return self._client().act(session, obs, timeout_s, trace=trace)

    def end(self, session: str) -> None:
        self._client().end(session)

    def swap(self) -> None:
        self._client().load("loadgen-swap", params={"version": "loadgen-swap"},
                            activate=True)

    def close(self) -> None:
        pass


class _HttpTarget:
    def __init__(self, addr: str):
        self._base = f"http://{addr}/serve"

    def _post(self, route: str, body: dict, headers: Optional[dict] = None) -> dict:
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            f"{self._base}/{route}", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json", **(headers or {})},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                out = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            # the drain contract answers 503 with the typed wire body
            out = json.loads(e.read() or b"{}")
        if out.get("code") != 0:
            if out.get("shed"):
                raise ShedError(out.get("error", ""))
            raise RuntimeError(out.get("error") or out.get("info"))
        return out["info"]

    def act(self, session: str, obs, timeout_s: float, trace=None):
        headers = {}
        if trace is not None:
            from distar_tpu.obs import format_traceparent

            tp = format_traceparent(trace)
            if tp:
                headers["traceparent"] = tp
        return self._post("act", {
            "session_id": session,
            "obs": {k: np.asarray(v).tolist() for k, v in obs.items()},
            "timeout_s": timeout_s,
        }, headers=headers)

    def end(self, session: str) -> None:
        self._post("end", {"session_id": session})

    def swap(self) -> None:
        raise RuntimeError("hot swap over HTTP needs a checkpoint source; use --tcp")

    def close(self) -> None:
        pass


def emit(line: dict, artifact_lines: List[dict]) -> None:
    print(json.dumps(line), flush=True)
    artifact_lines.append(line)


# --------------------------------------------------------------- fleet mode
def _spawn_gateway_fleet(n: int, slots: int, delay_s: float):
    """``n`` real mock-gateway subprocesses (jax-free gateway_proc — own
    GIL, real sockets). Returns ``(procs, addrs)``; closing a proc's stdin
    reaps it (the replay bench fleet idiom)."""
    import subprocess

    procs, addrs = [], []
    for _ in range(n):
        cmd = [sys.executable, "-m", "distar_tpu.serve.fleet.gateway_proc",
               "--port", "0", "--http-port", "0", "--slots", str(slots),
               "--mock-delay-s", str(delay_s)]
        proc = subprocess.Popen(cmd, stdin=subprocess.PIPE,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL, text=True)
        parts = proc.stdout.readline().split()
        if len(parts) < 4 or parts[0] != "SERVE-GATEWAY":
            raise RuntimeError(f"gateway failed to start: {parts}")
        addrs.append(f"{parts[1]}:{parts[2]}")
        procs.append(proc)
    return procs, addrs


def _reap_gateway_fleet(procs) -> None:
    for proc in procs:
        try:
            proc.stdin.close()
            proc.wait(timeout=10)
        except Exception:
            proc.kill()


def run_fleet_loadgen(
    gateways: int = 3,
    slots: int = 512,
    fleet_levels: str = "",
    fleet_workers: int = 32,
    requests_per_session: int = 4,
    mock_delay_s: float = 0.0,
    timeout_s: float = 10.0,
    tcp: Optional[str] = None,
    artifact: Optional[str] = None,
    trace: bool = False,
) -> dict:
    """The multi-gateway capacity harness (``--mode fleet``); importable —
    the fleet smoke test and the FLEET_r* artifact runs call this. Returns
    the summary dict (= last stdout JSON line), which carries the in-band
    honesty flags (``host_cores``, ``scaling_valid``): on a small CI host
    the whole fleet time-shares the cores, so the curve proves the routed
    fleet EXECUTES at each level, not that it scales."""
    from distar_tpu.fleet import pinning
    from distar_tpu.serve.fleet import FleetClient, GatewayMap

    host_cores = pinning.host_cores()
    if tcp:
        procs, addrs = [], [a.strip() for a in tcp.split(",") if a.strip()]
        # an external fleet's pids are unknown — pinning cannot be claimed
        pin_prov = pinning.PinPlan(
            pinned=False, host_cores=host_cores,
            refused_reason="external --tcp fleet: member pids unknown to "
                           "the harness").provenance()
    else:
        procs, addrs = _spawn_gateway_fleet(gateways, slots, mock_delay_s)
        # the core-pinning harness: each gateway on its own core, the
        # driving client on the reserved remainder — or an explicit refusal
        # that keeps scaling_valid false in-band on small hosts
        pin_prov = pinning.pin_fleet([p.pid for p in procs], reserve_client=1)
    capacity = slots * len(addrs)
    if fleet_levels:
        levels = [int(x) for x in fleet_levels.split(",") if x.strip()]
    else:
        # sweep up THROUGH fleet capacity and past it: the shed knee is
        # the measurement
        levels = sorted({max(1, capacity // 6), max(1, capacity // 2),
                         capacity, capacity + max(1, capacity // 4)})
    artifact_lines: List[dict] = []
    tap = _TraceTap(trace)
    from distar_tpu.serve.fleet import FleetRouter

    # ONE router (pins, migration accounting, down-list) shared by
    # per-worker FleetClients: a ServeClient holds one connection with one
    # request in flight, so per-worker clients are what lets W requests
    # ride the wire concurrently while affinity state stays coherent
    router = FleetRouter(GatewayMap(addrs))
    clients = [FleetClient(router=router, timeout_s=timeout_s)
               for _ in range(fleet_workers)]
    obs = _make_obs(0)
    curve: List[dict] = []
    try:
        for level in levels:
            stats = _Stats()
            shed_arrival = [0]
            live_sessions: List[List[str]] = [[] for _ in range(fleet_workers)]
            lock = threading.Lock()
            # workers interleave their share of the level's sessions so all
            # admitted sessions are RESIDENT (slot held, carry live) at once
            arrived = threading.Barrier(fleet_workers + 1)
            sampled = threading.Barrier(fleet_workers + 1)

            def traced_act(fc, sid: str) -> str:
                ctx = tap.mint(sid)
                t0 = time.perf_counter()
                try:
                    fc.act(sid, obs, timeout_s, trace=ctx)
                    dt = time.perf_counter() - t0
                    stats.record(dt, "ok")
                    tap.done(ctx, dt, "ok")
                    return "ok"
                except ShedError:
                    stats.record(None, "shed")
                    tap.done(ctx, outcome="shed")
                    return "shed"
                except Exception:
                    stats.record(None, "error")
                    tap.done(ctx, outcome="error")
                    return "error"

            def worker(w: int, sids: List[str]) -> None:
                fc = clients[w]
                mine = live_sessions[w]
                for sid in sids:  # arrival pass: allocate the sticky slot
                    kind = traced_act(fc, sid)
                    if kind == "ok":
                        mine.append(sid)
                    elif kind == "shed":
                        with lock:
                            shed_arrival[0] += 1
                arrived.wait()
                sampled.wait()  # main thread reads live residency here
                for _step in range(max(requests_per_session - 1, 0)):
                    for sid in mine:
                        traced_act(fc, sid)
                for sid in mine:
                    try:
                        fc.end(sid)
                    except Exception:
                        pass

            sids = [f"fleet-{level}-{i}" for i in range(level)]
            shares = [sids[w::fleet_workers] for w in range(fleet_workers)]
            t_start = time.perf_counter()
            threads = [threading.Thread(target=worker, args=(w, shares[w]))
                       for w in range(fleet_workers)]
            for t in threads:
                t.start()
            arrived.wait()
            # every admitted session now holds a slot somewhere: measure
            # true server-side residency + the per-gateway distribution
            per_gateway = dict(router.stats()["pins_per_gateway"])
            resident = sum(len(m) for m in live_sessions)
            sampled.wait()
            for t in threads:
                t.join()
            elapsed = time.perf_counter() - t_start
            total = stats.ok + stats.shed + stats.errors
            row = {
                "level": level,
                "concurrent_resident": resident,
                "sessions_per_gateway": per_gateway,
                "shed_at_arrival": shed_arrival[0],
                "session_shed_rate": round(shed_arrival[0] / max(level, 1), 4),
                "shed_rate": round(stats.shed / max(total, 1), 4),
                "errors": stats.errors,
                "req_per_s": round(stats.ok / max(elapsed, 1e-9), 2),
                "latency_p50_s": round(stats.quantile(0.5), 6),
                "latency_p99_s": round(stats.quantile(0.99), 6),
                "elapsed_s": round(elapsed, 3),
            }
            curve.append(row)
            emit({"metric": "fleet level", **row}, artifact_lines)
    finally:
        for fc in clients:
            fc.close()
        _reap_gateway_fleet(procs)
    best = max((r["concurrent_resident"] for r in curve), default=0)
    snap = get_registry().snapshot()
    summary = {
        "metric": "serve fleet concurrent resident sessions "
                  "(mock gateways, loopback)",
        "value": best,
        "unit": "sessions",
        "mode": "fleet",
        "device": "cpu",
        "cpu_derived": True,
        "host_cores": host_cores,
        # scaling_valid is now a PROVEN claim: true only when the pin
        # harness actually gave every gateway its own core (provenance
        # below, verified by perf_gate's scaling gate); on a smaller host
        # the curve still proves routed capacity executes, flagged false
        "scaling_valid": pinning.scaling_valid(pin_prov,
                                               min_cores=len(addrs) + 1),
        "pinning": pin_prov,
        "gateways": len(addrs),
        "slots_per_gateway": slots,
        "fleet_slot_capacity": capacity,
        "requests_per_session": requests_per_session,
        "fleet_curve": curve,
        "migrations": snap.get("distar_fleet_session_migrations_total", 0.0),
        "errors_total": sum(r["errors"] for r in curve),
        # --trace: the bench artifact links straight to retrievable
        # waterfalls (opsctl trace --id <trace_id>)
        **tap.summary(),
    }
    emit(summary, artifact_lines)
    if artifact:
        with open(artifact, "w") as f:
            for line in artifact_lines:
                f.write(json.dumps(line) + "\n")
    return summary


def run_loadgen(
    mode: str = "closed",
    clients: int = 8,
    rate: float = 200.0,
    duration_s: float = 5.0,
    requests_per_client: int = 0,
    requests_per_session: int = 8,
    slots: int = 8,
    mock_delay_s: float = 0.002,
    max_delay_s: float = 0.005,
    queue_capacity: int = 256,
    idle_ttl_s: float = 300.0,
    timeout_s: float = 5.0,
    swap_at: float = 0.0,
    tcp: Optional[str] = None,
    http: Optional[str] = None,
    artifact: Optional[str] = None,
    gateways: int = 3,
    fleet_levels: str = "",
    fleet_workers: int = 32,
    trace: bool = False,
) -> dict:
    """Importable driver (the slow soak test calls this). Returns the
    summary dict that is also the last stdout JSON line."""
    assert mode in ("closed", "open", "sessions", "fleet")
    if mode == "fleet":
        return run_fleet_loadgen(
            gateways=gateways, slots=slots, fleet_levels=fleet_levels,
            fleet_workers=fleet_workers,
            requests_per_session=requests_per_session,
            mock_delay_s=mock_delay_s, timeout_s=timeout_s, tcp=tcp,
            artifact=artifact, trace=trace)
    if tcp:
        target = _TcpTarget(tcp)
    elif http:
        target = _HttpTarget(http)
    else:
        target = _InprocTarget(slots, mock_delay_s, max_delay_s, queue_capacity,
                               idle_ttl_s=idle_ttl_s)
    stats = _Stats()
    tap = _TraceTap(trace)
    artifact_lines: List[dict] = []
    stop_at = time.perf_counter() + duration_s
    swapped = threading.Event()

    def one(session: str, i: int) -> None:
        ctx = tap.mint(session)
        t0 = time.perf_counter()
        try:
            target.act(session, _make_obs(i), timeout_s, trace=ctx)
            dt = time.perf_counter() - t0
            stats.record(dt, "ok")
            tap.done(ctx, dt, "ok")
        except ShedError:
            stats.record(None, "shed")
            tap.done(ctx, outcome="shed")
        except Exception:
            stats.record(None, "error")
            tap.done(ctx, outcome="error")

    def maybe_swap(done_frac: float) -> None:
        if swap_at and done_frac >= swap_at and not swapped.is_set():
            swapped.set()
            t0 = time.perf_counter()
            target.swap()
            emit({"metric": "serve_swap_issue", "value": time.perf_counter() - t0,
                  "unit": "s"}, artifact_lines)

    sessions_started = [0]
    sessions_completed = [0]
    sessions_shed = [0]
    sess_lock = threading.Lock()

    def session_life(n: int) -> None:
        """One eval-farm session: arrive, play ``requests_per_session``
        sequential steps on a sticky session, end it (freeing the slot). A
        shed at ARRIVAL (capacity) abandons the session — that's the number
        the farm sizes against; a shed mid-session retries briefly."""
        sid = f"farm-{n}"
        with sess_lock:
            sessions_started[0] += 1
        i = 0
        while i < requests_per_session:
            ctx = tap.mint(sid)
            t0 = time.perf_counter()
            try:
                target.act(sid, _make_obs(i), timeout_s, trace=ctx)
                dt = time.perf_counter() - t0
                stats.record(dt, "ok")
                tap.done(ctx, dt, "ok")
                i += 1
            except ShedError:
                stats.record(None, "shed")
                tap.done(ctx, outcome="shed")
                if i == 0:  # no slot for this session: the farm is full
                    with sess_lock:
                        sessions_shed[0] += 1
                    return
                time.sleep(0.01)
            except Exception:
                stats.record(None, "error")
                tap.done(ctx, outcome="error")
                return
        try:
            target.end(sid)
        except Exception:
            pass
        with sess_lock:
            sessions_completed[0] += 1

    t_start = time.perf_counter()
    if mode == "closed":
        def worker(w: int) -> None:
            session = f"loadgen-{w}"
            i = 0
            while time.perf_counter() < stop_at or (
                requests_per_client and i < requests_per_client
            ):
                if requests_per_client and i >= requests_per_client:
                    break
                one(session, i)
                i += 1
                maybe_swap((time.perf_counter() - t_start) / duration_s)
                if not requests_per_client and time.perf_counter() >= stop_at:
                    break

        threads = [threading.Thread(target=worker, args=(w,)) for w in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    else:  # open / sessions: fixed arrival schedule, unbounded worker threads
        period = 1.0 / max(rate, 1e-9)
        threads = []
        i = 0
        next_fire = time.perf_counter()
        while time.perf_counter() < stop_at:
            now = time.perf_counter()
            if now < next_fire:
                time.sleep(min(next_fire - now, 0.01))
                continue
            if mode == "sessions":
                t = threading.Thread(target=session_life, args=(i,))
            else:
                session = f"loadgen-{i % max(slots, 1)}"
                t = threading.Thread(target=one, args=(session, i))
            t.start()
            threads.append(t)
            i += 1
            next_fire += period
            maybe_swap((now - t_start) / duration_s)
        for t in threads:
            t.join(timeout_s * (requests_per_session if mode == "sessions" else 1) + 1.0)
    elapsed = time.perf_counter() - t_start
    target.close()

    total = stats.ok + stats.shed + stats.errors
    summary = {
        "metric": "serve_throughput",
        "value": round(stats.ok / max(elapsed, 1e-9), 2),
        "unit": "req/s",
        "mode": mode,
        "ok": stats.ok,
        "shed": stats.shed,
        "errors": stats.errors,
        "total": total,
        "elapsed_s": round(elapsed, 3),
        "latency_p50_s": round(stats.quantile(0.5), 6),
        "latency_p99_s": round(stats.quantile(0.99), 6),
        # the eval-farm sizing number: what fraction of offered work the
        # gateway refused (typed sheds / everything offered)
        "shed_rate": round(stats.shed / max(total, 1), 4),
        # --trace: trace_ids of the slowest/shedded requests, retrievable
        # as waterfalls via opsctl trace --id <id>
        **tap.summary(),
    }
    if mode == "sessions":
        summary["sessions"] = {
            "started": sessions_started[0],
            "completed": sessions_completed[0],
            "shed_at_arrival": sessions_shed[0],
            "requests_per_session": requests_per_session,
            "session_shed_rate": round(
                sessions_shed[0] / max(sessions_started[0], 1), 4),
        }
    if tcp is None and http is None:
        # in-process: the serve metrics live in OUR registry — report the
        # coalescing the acceptance criteria care about
        snap = get_registry().snapshot()
        occ_count = snap.get("distar_serve_batch_occupancy_count", 0.0)
        occ_sum = snap.get("distar_serve_batch_occupancy_sum", 0.0)
        summary["mean_batch_occupancy"] = round(occ_sum / occ_count, 3) if occ_count else 0.0
        summary["swap_p99_s"] = snap.get("distar_serve_swap_duration_seconds_p99", 0.0)
    for q, name in ((0.5, "serve_latency_p50"), (0.99, "serve_latency_p99")):
        emit({"metric": name, "value": stats.quantile(q), "unit": "s"}, artifact_lines)
    emit(summary, artifact_lines)
    if artifact:
        with open(artifact, "w") as f:
            for line in artifact_lines:
                f.write(json.dumps(line) + "\n")
    return summary


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--mode", choices=("closed", "open", "sessions", "fleet"),
                   default="closed")
    p.add_argument("--gateways", type=int, default=3,
                   help="fleet mode: gateway subprocesses to spawn (ignored "
                        "with --tcp, which may name an external fleet "
                        "'a:p,b:p')")
    p.add_argument("--fleet-levels", default="",
                   help="fleet mode: comma list of concurrent-resident-"
                        "session levels to sweep (default: auto up through "
                        "fleet slot capacity and past it)")
    p.add_argument("--fleet-workers", type=int, default=32,
                   help="fleet mode: driver threads (each interleaves many "
                        "live sessions; concurrency = resident slots, not "
                        "threads)")
    p.add_argument("--clients", type=int, default=8, help="closed-loop workers")
    p.add_argument("--rate", type=float, default=200.0,
                   help="open-loop request arrivals/s; sessions mode: "
                        "session arrivals/s")
    p.add_argument("--duration-s", type=float, default=5.0)
    p.add_argument("--requests-per-client", type=int, default=0,
                   help="closed loop: stop after N requests instead of duration")
    p.add_argument("--requests-per-session", type=int, default=8,
                   help="sessions mode: steps each arriving session plays "
                        "before ending (eval-farm episode length)")
    p.add_argument("--slots", type=int, default=8, help="in-process mock slots")
    p.add_argument("--mock-delay-s", type=float, default=0.002)
    p.add_argument("--max-delay-s", type=float, default=0.005)
    p.add_argument("--queue-capacity", type=int, default=256)
    p.add_argument("--idle-ttl-s", type=float, default=300.0,
                   help="in-process gateway session idle eviction")
    p.add_argument("--timeout-s", type=float, default=5.0)
    p.add_argument("--swap-at", type=float, default=0.0,
                   help="hot-swap when this fraction of the run has elapsed (0=off)")
    p.add_argument("--tcp", help="host:port of a running serve TCP frontend")
    p.add_argument("--http", help="host:port of a running serve HTTP frontend")
    p.add_argument("--artifact", help="also write the JSON lines to this path")
    p.add_argument("--trace", action="store_true",
                   help="mint a distributed-trace span per request; the "
                        "summary then names the trace_ids of the slowest "
                        "and shedded requests (opsctl trace --id <id>)")
    p.add_argument("--coordinator", default="",
                   help="with --trace: ship this process's tail-sampled "
                        "client spans (and telemetry) to the coordinator at "
                        "host:port, so the summary's trace_ids resolve to "
                        "FULL waterfalls — client span joined with the "
                        "gateway spans the fleet ships — via opsctl trace")
    p.add_argument("--no-trace-minting", action="store_true",
                   help="force span minting OFF process-wide (the overhead "
                        "A/B posture — also disables server-side joins in "
                        "the in-process gateway)")
    args = p.parse_args()
    if args.no_trace_minting:
        from distar_tpu.obs import set_tracing

        set_tracing(False)
    shipper = None
    if args.coordinator and args.trace:
        from distar_tpu.obs import TelemetryShipper

        chost, _, cport = args.coordinator.rpartition(":")
        shipper = TelemetryShipper(
            source=f"loadgen:{os.getpid()}",
            coordinator_addr=(chost or "127.0.0.1", int(cport)),
            interval_s=1.0).start()
    kwargs = {k.replace("-", "_"): v for k, v in vars(args).items()}
    kwargs.pop("no_trace_minting", None)
    kwargs.pop("coordinator", None)
    try:
        run_loadgen(**kwargs)
    finally:
        if shipper is not None:
            shipper.stop()
            try:
                # final flush: the tail kept since the last tick must reach
                # the broker before this short-lived process exits
                shipper.ship_once()
            except Exception:
                pass


if __name__ == "__main__":
    main()
