"""Metric-name lint — thin shim over the analysis framework's metric rules.

Every registered metric must follow ``distar_<subsystem>_<name>[_<unit>]``
AND appear in the docs/observability.md metric table (an undocumented metric
is invisible to operators). Dynamically named registrations must be declared
in ``DYNAMIC_ALLOW`` (now canonical in ``distar_tpu/analysis/hygiene.py``,
re-exported here). The framework additionally checks counter-vs-gauge misuse
and label cardinality — run ``python tools/analyze.py`` for the full set;
this CLI and ``lint``/``registered_names`` keep the original surface.

Invoked from the test suite (tests/test_obs_metrics.py) and runnable
standalone: ``python tools/lint_metric_names.py`` (``--list`` prints every
statically-known metric name).
"""
from __future__ import annotations

import os
import sys
from typing import List, Set

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from distar_tpu.analysis.hygiene import (  # noqa: E402,F401 — legacy surface
    DYNAMIC_ALLOW,
    METRIC_NAME_RE as NAME_RE,
    REGISTER_METHODS,
)

_LEGACY_RULES = ("metric-name", "metric-undocumented", "metric-dynamic-name")


def lint(pkg_root: str, docs_path: str) -> List[str]:
    """Problem strings for the legacy rule set (naming/documentation/dynamic
    declarations) — the two v2 rules (kind misuse, label cardinality) are
    analyze.py's, so this shim stays behavior-compatible."""
    from distar_tpu.analysis import ParsedModule, collect_files
    from distar_tpu.analysis.hygiene import MetricChecker

    checker = MetricChecker(_REPO, docs_path=docs_path)
    problems: List[str] = []
    for path in collect_files([pkg_root]):
        mod = ParsedModule(path, os.path.relpath(path, pkg_root).replace(os.sep, "/"))
        if mod.syntax_error is not None:
            continue
        for f in checker.check_module(mod):
            if f.rule not in _LEGACY_RULES or mod.pragma_for(f.line, f.rule) is not None:
                continue
            problems.append(f"{mod.relpath}:{f.line}: {f.message}")
    return problems


def registered_names(pkg_root: str) -> Set[str]:
    """Every statically-known metric name in the tree (for doc generation)."""
    import ast

    from distar_tpu.analysis import ParsedModule, collect_files

    names: Set[str] = set()
    for path in collect_files([pkg_root]):
        mod = ParsedModule(path, os.path.relpath(path, pkg_root))
        if mod.syntax_error is not None:
            continue
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
                    and node.func.attr in REGISTER_METHODS and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                names.add(node.args[0].value)
    for extra in DYNAMIC_ALLOW.values():
        names.update(extra)
    return names


def main() -> int:
    pkg_root = os.path.join(_REPO, "distar_tpu")
    docs_path = os.path.join(_REPO, "docs", "observability.md")
    problems = lint(pkg_root, docs_path)
    for p in problems:
        sys.stderr.write(p + "\n")
    if problems:
        sys.stderr.write(
            f"{len(problems)} offence(s); metric names must match "
            "distar_<subsystem>_<name> and appear in docs/observability.md\n"
        )
        return 1
    if "--list" in sys.argv:
        for name in sorted(registered_names(pkg_root)):
            sys.stdout.write(name + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
