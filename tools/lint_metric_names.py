"""Metric-name lint: every registered metric follows the naming convention
and is documented.

Walks ``distar_tpu/**.py`` for ``.counter( / .gauge( / .histogram(`` calls
and checks every string-literal metric name against the
``distar_<subsystem>_<name>[_<unit>]`` convention (docs/observability.md)
AND against the metric table in docs/observability.md — an undocumented
metric is invisible to operators, which defeats the registry. Dynamically
named registrations (f-strings) must be declared in ``DYNAMIC_ALLOW`` with
the names they can produce, so new dynamic families can't dodge the lint.

Invoked from the test suite (tests/test_obs_metrics.py) and runnable
standalone: ``python tools/lint_metric_names.py``.
"""
from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Set, Tuple

NAME_RE = re.compile(r"^distar_[a-z][a-z0-9_]*$")
REGISTER_METHODS = ("counter", "gauge", "histogram")

# files allowed to register dynamically-built names, with every name their
# dynamic path can produce (which must itself be documented)
DYNAMIC_ALLOW: Dict[str, List[str]] = {
    os.path.join("utils", "timing.py"): ["distar_stopwatch_seconds"],
}

SKIP_DIRS = {"__pycache__", "_proto_gen"}


def _doc_metric_names(docs_path: str) -> Set[str]:
    """Backticked metric names in docs/observability.md (the metric table +
    prose both count — operators read the whole page)."""
    with open(docs_path) as f:
        text = f.read()
    names = set()
    for token in re.findall(r"`([^`\n]+)`", text):
        m = re.match(r"(distar_[a-z0-9_]+)", token)
        if m:
            names.add(m.group(1))
    return names


def find_registrations(pkg_root: str) -> Tuple[List[tuple], List[tuple]]:
    """Returns (literal, dynamic) registration sites:
    literal: (relpath, lineno, name); dynamic: (relpath, lineno)."""
    literal, dynamic = [], []
    for dirpath, dirnames, filenames in os.walk(pkg_root):
        dirnames[:] = [d for d in dirnames if d not in SKIP_DIRS]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            relpath = os.path.relpath(path, pkg_root)
            with open(path, "rb") as f:
                try:
                    tree = ast.parse(f.read())
                except SyntaxError:
                    continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                if not (isinstance(func, ast.Attribute) and func.attr in REGISTER_METHODS):
                    continue
                if not node.args:
                    continue  # registry-internal plumbing, not a registration
                first = node.args[0]
                if isinstance(first, ast.Constant) and isinstance(first.value, str):
                    literal.append((relpath, node.lineno, first.value))
                else:
                    dynamic.append((relpath, node.lineno))
    return literal, dynamic


def lint(pkg_root: str, docs_path: str) -> List[str]:
    problems: List[str] = []
    documented = _doc_metric_names(docs_path)
    literal, dynamic = find_registrations(pkg_root)
    for relpath, lineno, name in literal:
        if not NAME_RE.match(name):
            problems.append(
                f"{relpath}:{lineno}: metric {name!r} violates the "
                f"distar_<subsystem>_<name> convention"
            )
        elif name not in documented:
            problems.append(
                f"{relpath}:{lineno}: metric {name!r} missing from the "
                f"docs/observability.md metric table"
            )
    for relpath, lineno in dynamic:
        allowed = DYNAMIC_ALLOW.get(relpath)
        if allowed is None:
            problems.append(
                f"{relpath}:{lineno}: dynamically-named metric registration — "
                f"declare its names in tools/lint_metric_names.py DYNAMIC_ALLOW"
            )
            continue
        for name in allowed:
            if name not in documented:
                problems.append(
                    f"{relpath}:{lineno}: dynamic metric {name!r} missing from "
                    f"the docs/observability.md metric table"
                )
    return problems


def registered_names(pkg_root: str) -> Set[str]:
    """Every statically-known metric name in the tree (for doc generation)."""
    literal, _dynamic = find_registrations(pkg_root)
    names = {name for (_p, _l, name) in literal}
    for extra in DYNAMIC_ALLOW.values():
        names.update(extra)
    return names


def main() -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pkg_root = os.path.join(repo, "distar_tpu")
    docs_path = os.path.join(repo, "docs", "observability.md")
    problems = lint(pkg_root, docs_path)
    for p in problems:
        sys.stderr.write(p + "\n")
    if problems:
        sys.stderr.write(
            f"{len(problems)} offence(s); metric names must match "
            "distar_<subsystem>_<name> and appear in docs/observability.md\n"
        )
        return 1
    if "--list" in sys.argv:
        for name in sorted(registered_names(pkg_root)):
            sys.stdout.write(name + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
