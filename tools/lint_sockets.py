"""Socket-discipline lint — thin shim over the analysis framework's
``socket-bare-except`` / ``socket-no-timeout`` rules.

Rejects bare ``except:`` handlers (they swallow ``KeyboardInterrupt``/
``SystemExit`` and hide the typed error taxonomy the resilience layer
depends on) and ``urlopen(...)``/``create_connection(...)`` without an
explicit ``timeout=`` (a hung peer must never park a fleet role forever —
the week-long-run lesson behind the shuttle deadline fix). The actual
checker lives in ``distar_tpu/analysis/hygiene.py``; this CLI and
``find_offences`` keep the original surface. Opt-outs:
``# lint: allow-bare-except`` / ``# lint: allow-no-timeout`` (legacy) or
``# analysis: allow(socket-bare-except) — <why>`` pragmas.

Invoked from the test suite (tests/test_resilience.py) and runnable
standalone: ``python tools/lint_sockets.py``. The full analyzer is
``python tools/analyze.py`` (docs/analysis.md).
"""
from __future__ import annotations

import os
import sys
from typing import List, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

ALLOW_BARE = "# lint: allow-bare-except"
ALLOW_NO_TIMEOUT = "# lint: allow-no-timeout"

_RULES = ("socket-bare-except", "socket-no-timeout")


def find_offences(root: str) -> List[Tuple[str, int, str]]:
    """(relpath, lineno, message) per offence — the pre-framework shape."""
    from distar_tpu.analysis import ParsedModule, collect_files
    from distar_tpu.analysis.hygiene import HygieneChecker

    checker = HygieneChecker()
    offences = []
    for path in collect_files([root]):
        mod = ParsedModule(path, os.path.relpath(path, root).replace(os.sep, "/"))
        if mod.syntax_error is not None:
            continue
        for f in checker.check_module(mod):
            if f.rule not in _RULES or mod.pragma_for(f.line, f.rule) is not None:
                continue
            offences.append((os.path.relpath(path, root), f.line, f.message))
    return offences


def main() -> int:
    pkg_root = os.path.join(_REPO, "distar_tpu")
    offences = find_offences(pkg_root)
    for relpath, lineno, msg in offences:
        sys.stderr.write(f"{relpath}:{lineno}: {msg}\n")
    if offences:
        sys.stderr.write(
            f"{len(offences)} offence(s); see docs/resilience.md for the "
            "socket-discipline rules\n"
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
