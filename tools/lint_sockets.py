"""Socket-discipline lint: no bare ``except:`` and no unbounded network waits.

Walks ``distar_tpu/**.py`` (AST) and rejects:

* bare ``except:`` handlers — they swallow ``KeyboardInterrupt``/``SystemExit``
  and hide the typed error taxonomy the resilience layer depends on
  (``except Exception:`` is the acceptable broad form);
* ``urlopen(...)`` / ``create_connection(...)`` calls without an explicit
  ``timeout`` keyword — a hung peer must never park a fleet role forever
  (the week-long-run lesson behind the shuttle deadline fix).

A line may opt out with ``# lint: allow-bare-except`` or
``# lint: allow-no-timeout`` (none currently do). Invoked from the test
suite (tests/test_resilience.py) next to lint_no_print/lint_metric_names,
and runnable standalone: ``python tools/lint_sockets.py``.
"""
from __future__ import annotations

import ast
import os
import sys
from typing import List, Tuple

TIMEOUT_REQUIRED = ("urlopen", "create_connection")
ALLOW_BARE = "# lint: allow-bare-except"
ALLOW_NO_TIMEOUT = "# lint: allow-no-timeout"
SKIP_DIRS = {"__pycache__", "_proto_gen"}


def _call_name(node: ast.Call) -> str:
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _scan_file(path: str, relpath: str) -> List[Tuple[str, int, str]]:
    with open(path, "rb") as f:
        source = f.read()
    lines = source.decode("utf-8", errors="replace").splitlines()

    def line(no: int) -> str:
        return lines[no - 1] if 0 < no <= len(lines) else ""

    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            if ALLOW_BARE not in line(node.lineno):
                out.append((relpath, node.lineno,
                            "bare 'except:' — catch a typed error "
                            "(resilience taxonomy) or 'Exception'"))
        elif isinstance(node, ast.Call) and _call_name(node) in TIMEOUT_REQUIRED:
            has_timeout = any(kw.arg == "timeout" for kw in node.keywords)
            if not has_timeout and ALLOW_NO_TIMEOUT not in line(node.lineno):
                out.append((relpath, node.lineno,
                            f"{_call_name(node)}() without an explicit "
                            "timeout= — unbounded network wait"))
    return out


def find_offences(root: str) -> List[Tuple[str, int, str]]:
    offences = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d not in SKIP_DIRS]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            offences.extend(_scan_file(path, os.path.relpath(path, root)))
    return offences


def main() -> int:
    pkg_root = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                            "distar_tpu")
    offences = find_offences(pkg_root)
    for relpath, lineno, msg in offences:
        sys.stderr.write(f"{relpath}:{lineno}: {msg}\n")
    if offences:
        sys.stderr.write(
            f"{len(offences)} offence(s); see docs/resilience.md for the "
            "socket-discipline rules\n"
        )
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
