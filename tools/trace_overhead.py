"""Tracing-overhead A/B: what always-on span minting + tail sampling costs.

The distributed-tracing acceptance gate: the serve mock bench runs against a
REAL gateway subprocess over the framed-TCP data plane (the production
serve path — wire serialization and all), twice per iteration:

  * **on**  — what production ships: client span minted per request
    (``loadgen --trace``), wire trace field on every frame, gateway joins a
    server span with queue/service attribution, tail-sampled buffer
    retention;
  * **off** — span minting disabled in BOTH processes (``gateway_proc
    --no-trace`` + ``loadgen --no-trace-minting``): the pre-tracing wire.

Arms interleave (ABAB...) with a FRESH gateway per arm to damp scheduler
noise and state bleed; per-arm numbers are medians. The artifact carries
the PR 12 honesty provenance in-band (``host_cores`` + ``pinning`` block —
on a 1-core host the pin plan REFUSES and says so; the two processes then
time-share one core, which *overstates* tracing cost, so the committed
number is a ceiling, not a flattery). Acceptance: traced throughput within
``--envelope-pct`` (single digits) of untraced; exit 0 inside, 1 outside —
the committed ``TRACE_r*.json`` records the verdict either way.

An in-process arm pair (``--inproc``) is also available: no sockets, the
cheapest possible baseline, i.e. the WORST case for a percentage overhead —
reported for transparency, never the headline.

    python tools/trace_overhead.py --artifact TRACE_r13.json
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import time
from typing import List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from distar_tpu.fleet import pinning  # noqa: E402


def _spawn_gateway(slots: int, mock_delay_s: float, traced: bool,
                   pin_cores: Optional[List[int]]):
    cmd = [sys.executable, "-m", "distar_tpu.serve.fleet.gateway_proc",
           "--port", "0", "--http-port", "0", "--slots", str(slots),
           "--mock-delay-s", str(mock_delay_s), "--max-delay-ms", "2"]
    if not traced:
        cmd.append("--no-trace")
    proc = subprocess.Popen(cmd, stdin=subprocess.PIPE,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, text=True)
    parts = proc.stdout.readline().split()
    if len(parts) < 4 or parts[0] != "SERVE-GATEWAY":
        proc.kill()
        raise RuntimeError(f"gateway failed to start: {parts}")
    if pin_cores:
        pinning.pin_pid(proc.pid, pin_cores)
    return proc, f"{parts[1]}:{parts[2]}"


def _run_arm(traced: bool, clients: int, duration_s: float, slots: int,
             mock_delay_s: float, gw_cores: Optional[List[int]],
             lg_cores: Optional[List[int]], inproc: bool) -> dict:
    """One interleaved arm: fresh gateway subprocess (unless ``inproc``) +
    fresh loadgen subprocess; returns loadgen's summary line."""
    gw_proc = None
    cmd = [sys.executable, os.path.join(_REPO, "tools", "loadgen.py"),
           "--mode", "closed", "--clients", str(clients),
           "--duration-s", str(duration_s), "--slots", str(slots),
           "--mock-delay-s", str(mock_delay_s)]
    if not inproc:
        gw_proc, addr = _spawn_gateway(slots, mock_delay_s, traced, gw_cores)
        cmd += ["--tcp", addr]
    cmd.append("--trace" if traced else "--no-trace-minting")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL, text=True, env=env)
        if lg_cores:
            pinning.pin_pid(proc.pid, lg_cores)
        out, _ = proc.communicate(timeout=duration_s * 4 + 120)
    finally:
        if gw_proc is not None:
            try:
                gw_proc.stdin.close()
                gw_proc.wait(timeout=10)
            except Exception:
                gw_proc.kill()
    lines = [ln for ln in out.strip().splitlines() if ln.startswith("{")]
    if proc.returncode != 0 or not lines:
        raise RuntimeError(f"loadgen arm failed (rc={proc.returncode})")
    return json.loads(lines[-1])


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--clients", type=int, default=8)
    p.add_argument("--duration-s", type=float, default=4.0)
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--mock-delay-s", type=float, default=0.002)
    p.add_argument("--iterations", type=int, default=3,
                   help="interleaved repeats per arm (median wins)")
    p.add_argument("--envelope-pct", type=float, default=9.0,
                   help="acceptance: traced throughput within this percent "
                        "of untraced")
    p.add_argument("--inproc", action="store_true",
                   help="ALSO run the in-process (no-socket) arm pair — the "
                        "cheapest baseline, worst-case percentage")
    p.add_argument("--artifact", default="",
                   help="write the JSON lines here (last line = summary)")
    args = p.parse_args(argv)

    host_cores = pinning.host_cores()
    # gateway on its own core, loadgen on the reserved remainder — or an
    # in-band refusal on hosts that cannot separate them
    pin_plan = pinning.plan(1, reserve_client=1)
    gw_cores = pin_plan.assignments[0] if pin_plan.pinned else None
    lg_cores = list(pin_plan.client_cores) if pin_plan.pinned else None
    pin_prov = pin_plan.provenance(
        {"gateway": list(gw_cores), "loadgen": list(lg_cores)}
        if pin_plan.pinned else None)

    lines: List[dict] = []

    def sweep(inproc: bool) -> dict:
        arms = {"on": [], "off": []}
        tag = "inproc" if inproc else "tcp"
        for i in range(max(1, args.iterations)):
            for name, traced in (("on", True), ("off", False)):
                summary = _run_arm(traced, args.clients, args.duration_s,
                                   args.slots, args.mock_delay_s,
                                   gw_cores, lg_cores, inproc)
                row = {
                    "metric": "trace overhead arm",
                    "path": tag,
                    "case": f"trace_{name}",
                    "iteration": i,
                    "req_per_s": summary["value"],
                    "latency_p50_s": summary["latency_p50_s"],
                    "latency_p99_s": summary["latency_p99_s"],
                    "ok": summary["ok"],
                    "errors": summary["errors"],
                }
                if name == "on" and summary.get("slowest_traces"):
                    # proof the traced arm retained waterfall-linkable
                    # traces (the ids resolve via opsctl trace --id)
                    row["slowest_traces"] = summary["slowest_traces"]
                arms[name].append(row)
                lines.append(row)
                print(json.dumps(row), flush=True)  # lint: allow-print
        on = statistics.median(r["req_per_s"] for r in arms["on"])
        off = statistics.median(r["req_per_s"] for r in arms["off"])
        # PAIRED ratios: each iteration's on/off ran back-to-back, so the
        # per-iteration ratio cancels the host's slow load drift (this CI
        # box swings ±10%+ between minutes — ratio-of-medians would launder
        # that drift into the verdict); the headline is the median ratio
        ratios = [a["req_per_s"] / b["req_per_s"]
                  for a, b in zip(arms["on"], arms["off"]) if b["req_per_s"]]
        ratio = statistics.median(ratios) if ratios else 1.0
        return {
            "path": tag,
            "req_per_s_traced": round(on, 2),
            "req_per_s_untraced": round(off, 2),
            "overhead_pct": round((1.0 - ratio) * 100.0, 2),
            "paired_ratios": [round(r, 4) for r in ratios],
            "latency_p99_s_traced": round(statistics.median(
                r["latency_p99_s"] for r in arms["on"]), 6),
            "latency_p99_s_untraced": round(statistics.median(
                r["latency_p99_s"] for r in arms["off"]), 6),
        }

    tcp = sweep(inproc=False)
    extra = {}
    if args.inproc:
        extra["inproc"] = sweep(inproc=True)

    within = tcp["overhead_pct"] <= args.envelope_pct
    summary = {
        "metric": "serve tracing overhead (mock gateway subprocess, "
                  "framed TCP, closed loop, A/B)",
        "value": tcp["overhead_pct"],
        "unit": "% throughput",
        **tcp,
        **extra,
        "iterations": args.iterations,
        "envelope_pct": args.envelope_pct,
        "within_envelope": within,
        "device": "cpu",
        "cpu_derived": True,
        "host_cores": host_cores,
        # not a scaling claim (one gateway, one client, both arms
        # identical) — the provenance records HOW the comparison was
        # isolated, honestly including the refusal on hosts that cannot
        # pin; unpinned 1-core runs time-share and OVERSTATE the overhead
        "scaling_valid": False,
        "pinning": pin_prov,
        "ts": time.time(),
    }
    lines.append(summary)
    print(json.dumps(summary), flush=True)  # lint: allow-print
    if args.artifact:
        with open(args.artifact, "w") as f:
            for line in lines:
                f.write(json.dumps(line) + "\n")
    return 0 if within else 1


if __name__ == "__main__":
    raise SystemExit(main())
