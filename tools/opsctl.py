#!/usr/bin/env python
"""opsctl: fleet-health CLI over the /healthz, /alerts, /timeseries routes.

Any process serving the obs health surfaces answers (coordinator broker,
serve HTTP frontend, or a training role started with --metrics-port):

  python tools/opsctl.py status       --addr 127.0.0.1:8423
  python tools/opsctl.py tail-alerts  --addr 127.0.0.1:8423 [--interval 2]
  python tools/opsctl.py query        --addr 127.0.0.1:8423 \\
        --name distar_learner_step_seconds_p50 [--window 300] [--source local]
  python tools/opsctl.py profile      --addr <learner-admin host:port> \\
        [--steps 2] [--timeout 600]
  python tools/opsctl.py trace        --addr 127.0.0.1:8423 \\
        [--name serve_request] [--min-ms 50] [--outcome shed] [--limit 20]
  python tools/opsctl.py trace        --addr 127.0.0.1:8423 --id <trace_id>
  python tools/opsctl.py dynamics     --dir exp/blackbox [--inspect <id>]

``status`` exits 0 when healthy, 1 when any rule is warning, 2 when firing —
scriptable for cron probes; it also prints a per-role step-time/MFU digest
from the ``distar_perf_*`` series when any are in the probed TSDB, and an
actor-throughput digest (env-steps/s, rollout-plane backend, plane sample
rates, serve shed rate) from the ``distar_actor_*``/``distar_rollout_*``/
``distar_serve_*`` series.
``tail-alerts`` follows the transition history (one line per
ok/warning/firing edge, deduped by event sequence). When the probed address
is a replay admin surface (``--type replay`` with ``--metrics-port``),
``status`` additionally prints per-table occupancy and rate-limiter state
from GET ``/replay/stats`` — and for a SHARD FLEET it aggregates: pass the
admin surfaces via ``--replay-addrs a:p,b:p,...`` or probe the coordinator,
whose ``replay_shard`` registrations are auto-discovered; the digest then
shows every shard's tables plus a fleet-aggregate line (total residency,
summed limiter block time, staleness span). Probing a coordinator also
prints the SERVING-FLEET digest: every ``serve_gateway`` registration's
gateway block (sessions/slots occupancy, shed rate, model generation +
served version, read off each gateway's own ``/serve/status``) plus an
aggregate line whose served-version spread says whether a fleet rollout has
converged. A coordinator hosting an ``Autoscaler`` (GET /autoscaler) adds
the AUTOSCALER digest: per-fleet target vs actual membership with
in-progress drains, per-policy value/threshold/hysteresis state, and the
last scaling decision with its reason. When a ``--distill`` learner ships
telemetry to the probed TSDB, ``status`` adds the DISTILLATION digest:
student vs teacher generation (and lag), the live divergence gauge (total
+ per head), the FLOPs-derived step-cost ratio, and the current canary
split state from the ``serve_canary`` record — student drift at a glance
without reading raw metrics. ``profile`` talks to a LEARNER ADMIN surface
(``rl_train --admin-port``): captures --steps iterations of jax.profiler
trace on the live learner and prints the ranked per-bucket attribution
table (obs/traceview.py).
``trace`` is the distributed-tracing consumer: without ``--id`` it lists
the retained traces (coordinator trace store + the probed process's own
tail-sampled buffer; filter by ``--name/--min-ms/--outcome`` — sheds and
errors are always retained by the tail sampler, so ``--outcome shed``
answers "show me a request we refused"); with ``--id`` it fetches one
trace's spans and renders the waterfall + ranked critical-path table
(obs/waterfall.py) — client/router/gateway spans joined under one
trace_id, with queue-wait vs service-time decomposition per process.
``league`` probes a coordinator hosting the league runtime
(``rl_train --type league-run``): learner leases, roster freeze state,
jobs dispatched per matchmaking branch, outstanding assignments, snapshot
mints and elastic reassignments (GET /league/status, docs/league.md).
``arena`` prints the ladder the matchmaker feeds on.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.error
import urllib.parse
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from distar_tpu.resilience import CommError  # noqa: E402


def _fetch(url: str, timeout: float) -> dict:
    """One probe; transport faults surface as the typed ``CommError`` (the
    same taxonomy every fleet call site speaks), never a raw URLError."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read())
    except urllib.error.HTTPError:
        raise  # status codes are handled by the caller (503 carries a body)
    except (urllib.error.URLError, ConnectionError, TimeoutError, OSError,
            ValueError) as e:
        raise CommError(f"GET {url} failed: {e!r}", op="opsctl:get", cause=e) from e


def _get(addr: str, path: str, timeout: float = 10.0) -> dict:
    url = f"http://{addr}{path}"
    try:
        return _fetch(url, timeout)
    except urllib.error.HTTPError as e:
        # /healthz answers 503 while firing — that body is still the payload
        try:
            return json.loads(e.read())
        except Exception:
            raise SystemExit(f"GET {url} -> HTTP {e.code}")
    except CommError as e:
        raise SystemExit(str(e))


def _fmt_ts(ts) -> str:
    try:
        return time.strftime("%H:%M:%S", time.localtime(float(ts)))
    except (TypeError, ValueError):
        return "--:--:--"


def _try_get(addr: str, path: str, timeout: float = 5.0):
    """Optional-surface probe: None when the route isn't served here (404)
    or the peer is unreachable — never exits."""
    try:
        return _fetch(f"http://{addr}{path}", timeout)
    except (urllib.error.HTTPError, CommError, ValueError):
        return None


def _discover_replay_admins(addr: str, timeout: float = 5.0) -> list:
    """Shard-fleet discovery for ``status``: when the probed address is a
    coordinator, its ``replay_shard`` registrations (POST /coordinator/peers)
    name every live shard and the admin port each put in its meta. Returns
    admin addresses; [] when the address isn't a coordinator (or no shard
    registered) — never exits."""
    try:
        req = urllib.request.Request(
            f"http://{addr}/coordinator/peers",
            data=json.dumps({"token": "replay_shard"}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            body = json.loads(resp.read())
    except (urllib.error.URLError, ConnectionError, TimeoutError, OSError,
            ValueError):
        return []
    admins = []
    for rec in (body.get("info") or []):
        admin_port = (rec.get("meta") or {}).get("admin_port")
        if admin_port:
            admins.append(f"{rec['ip']}:{admin_port}")
    return sorted(set(admins))


def _try_post(addr: str, path: str, body: dict, timeout: float = 5.0):
    """Optional POST probe (serve frontends answer /serve/status on POST):
    None on unreachable/unserved — never exits."""
    try:
        req = urllib.request.Request(
            f"http://{addr}{path}", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read())
    except (urllib.error.URLError, ConnectionError, TimeoutError, OSError,
            ValueError):
        return None


def _discover_serve_gateways(addr: str) -> list:
    """Serving-fleet discovery for ``status``: when the probed address is a
    coordinator, its ``serve_gateway`` registrations name every live
    gateway plus the HTTP port each advertised. Returns
    ``[(tcp_addr, http_addr, meta)]``; [] when the address isn't a
    coordinator or no gateway registered — never exits."""
    body = _try_post(addr, "/coordinator/peers", {"token": "serve_gateway"})
    seen = {}
    for rec in ((body or {}).get("info") or []):
        meta = rec.get("meta") or {}
        tcp_addr = f"{rec['ip']}:{rec['port']}"
        http_port = meta.get("http_port")
        http_addr = f"{rec['ip']}:{http_port}" if http_port else None
        seen[tcp_addr] = (tcp_addr, http_addr, meta)
    return [seen[k] for k in sorted(seen)]


def _print_serve_fleet(gateways: list) -> None:
    """Serving-fleet digest for ``status``: one block per discovered
    gateway (sessions/occupancy, shed rate, model generation + served
    version — read off its own /serve/status) and a fleet aggregate line
    (total sessions/slots, weighted shed rate, the served-version spread that
    says whether a rollout has converged)."""
    if not gateways:
        return
    print("serving fleet:")
    agg = {"sessions": 0, "slots": 0, "shed_num": 0.0, "shed_den": 0.0,
           "versions": set(), "unreachable": 0}
    for tcp_addr, http_addr, meta in gateways:
        players = ",".join(meta.get("players") or []) or "-"
        st = _try_post(http_addr, "/serve/status", {}) if http_addr else None
        info = (st or {}).get("info") if isinstance(st, dict) else None
        if not info or (st or {}).get("code") != 0:
            agg["unreachable"] += 1
            print(f"  [{tcp_addr}] players={players} UNREACHABLE "
                  f"(http={http_addr})")
            continue
        sess = info.get("sessions") or {}
        active = sess.get("active", 0)
        slots = sess.get("num_slots", 0)
        occ = active / slots if slots else 0.0
        reqs = info.get("requests") or {}
        total = sum(reqs.values())
        gen = info.get("generation", (info.get("registry") or {}).get("generation"))
        # convergence is about the VERSION each gateway is on — generation
        # numbers are per-gateway monotonic counters (a canaried gateway
        # legitimately runs one ahead after promote)
        version = (info.get("registry") or {}).get("current") \
            or info.get("served_version")
        # per-connection transport split (shm rings vs framed TCP): which
        # leg each colocated client actually negotiated on this gateway
        tr = info.get("transports") or {}
        tr_s = f" transports=shm:{tr.get('shm', 0)}/tcp:{tr.get('tcp', 0)}" \
            if tr else ""
        print(f"  [{tcp_addr}] players={players} sessions={active}/{slots} "
              f"occ={occ:5.2f} shed_rate={info.get('shed_rate', 0.0):.4f} "
              f"gen={gen} serving={version} "
              f"q={info.get('queue_depth', 0)}{tr_s}"
              + (" DRAINING" if info.get("draining") else ""))
        agg["sessions"] += active
        agg["slots"] += slots
        agg["shed_num"] += reqs.get("shed", 0.0)
        agg["shed_den"] += total
        agg["versions"].add(version)
    occ = agg["sessions"] / agg["slots"] if agg["slots"] else 0.0
    shed = agg["shed_num"] / agg["shed_den"] if agg["shed_den"] else 0.0
    versions = sorted(str(v) for v in agg["versions"])
    converged = "converged" if len(versions) <= 1 else f"SPLIT {versions}"
    print(f"  aggregate: {len(gateways)} gateways  "
          f"{agg['sessions']}/{agg['slots']} sessions (occ={occ:.2f})  "
          f"shed_rate={shed:.4f}  versions={converged}"
          + (f"  unreachable={agg['unreachable']}" if agg["unreachable"] else ""))


def _print_replay(per_shard: dict) -> None:
    """Replay digest for ``status``, fleet-aware: one block per shard
    (occupancy + rate-limiter state per table — the numbers that say which
    side of the fleet is behind and WHICH shard), then the fleet aggregate
    (total residency, summed limiter block time, staleness span). A
    single-store probe is just a one-shard fleet."""
    per_shard = {k: v for k, v in per_shard.items() if v}
    if not per_shard:
        return
    fleet = len(per_shard) > 1
    agg = {"size": 0, "max": 0, "ins": 0, "smp": 0,
           "block_ins": 0.0, "block_smp": 0.0,
           "stale_min": None, "stale_max": None, "spill_live": 0}
    print("replay fleet:" if fleet else "replay tables:")
    for shard_addr in sorted(per_shard):
        stats = per_shard[shard_addr]
        tag = f"[{stats.get('shard') or shard_addr}] " if fleet else ""
        if "error" in stats:
            print(f"  {tag}UNREACHABLE: {stats['error']}")
            continue
        for name in sorted(stats.get("tables", {})):
            t = stats["tables"][name]
            lim = t.get("limiter", {})
            spi = lim.get("samples_per_insert")
            blocked = ("insert" if not lim.get("can_insert", True) else
                       "sample" if not lim.get("can_sample", True) else "-")
            print(f"  {tag}{name:<16} {t.get('size', 0):>6}/{t.get('max_size', 0):<6} "
                  f"occ={t.get('occupancy', 0.0):5.2f}  sampler={t.get('sampler', '?'):<11} "
                  f"spi={spi if spi is not None else 'off':<5} "
                  f"ins={lim.get('inserts', 0)} smp={lim.get('samples', 0)} "
                  f"blocked={blocked} "
                  f"block_s=ins:{lim.get('block_insert_s', 0.0)}/smp:{lim.get('block_sample_s', 0.0)}")
            agg["size"] += t.get("size", 0)
            agg["max"] += t.get("max_size", 0)
            agg["ins"] += lim.get("inserts", 0)
            agg["smp"] += lim.get("samples", 0)
            agg["block_ins"] += lim.get("block_insert_s", 0.0)
            agg["block_smp"] += lim.get("block_sample_s", 0.0)
            for key, side in (("newest_item_s", "stale_min"),
                              ("oldest_item_s", "stale_max")):
                v = t.get(key)
                if v is not None:
                    cur = agg[side]
                    pick = min if side == "stale_min" else max
                    agg[side] = v if cur is None else pick(cur, v)
        spill = stats.get("spill")
        if spill:
            agg["spill_live"] += spill.get("live", 0) or 0
            print(f"  {tag}spill: {spill.get('live')}/{spill.get('max_items')} live "
                  f"({spill.get('root')})")
        tr = stats.get("transports")
        if tr:
            # the active transport per data-plane connection: colocated
            # clients negotiate shm rings, remote ones stay framed TCP
            print(f"  {tag}transports: shm:{tr.get('shm', 0)} "
                  f"tcp:{tr.get('tcp', 0)}")
    if fleet:
        occ = agg["size"] / agg["max"] if agg["max"] else 0.0
        stale = (f"staleness={agg['stale_min']}..{agg['stale_max']}s "
                 if agg["stale_min"] is not None else "")
        print(f"  aggregate: {len(per_shard)} shards  {agg['size']}/{agg['max']} "
              f"items (occ={occ:.2f})  ins={agg['ins']} smp={agg['smp']}  "
              f"block_s=ins:{round(agg['block_ins'], 3)}/smp:{round(agg['block_smp'], 3)}  "
              f"{stale}spill_live={agg['spill_live']}")


def _print_autoscaler(addr: str) -> None:
    """Autoscaler digest for ``status``: per-fleet target-vs-actual
    membership + in-progress drains, per-policy state (current value vs
    thresholds and hysteresis streaks), and the last scaling decision with
    its reason — read off the coordinator's GET /autoscaler route (absent
    when no autoscaler runs there)."""
    body = _try_get(addr, "/autoscaler")
    if not body:
        return
    print("autoscaler:")
    for fleet in sorted(body.get("fleets") or {}):
        f = body["fleets"][fleet]
        drains = ",".join(f.get("draining") or []) or "-"
        cd = f.get("cooldown_remaining_s", 0.0)
        print(f"  [{fleet}] actual={f.get('actual')} "
              f"bounds={f.get('min')}..{f.get('max')} draining={drains} "
              f"cooldown={cd}s"
              + ("  GAVE-UP (respawn budget exhausted)" if f.get("gave_up")
                 else ""))
    for name in sorted(body.get("policies") or {}):
        p = body["policies"][name]
        value = p.get("value")
        value_s = f"{value:.4g}" if isinstance(value, (int, float)) else "no-data"
        bounds = []
        if p.get("up_when") is not None:
            bounds.append(f"up>{p['up_when']:g}")
        if p.get("down_when") is not None:
            bounds.append(f"down<{p['down_when']:g}")
        print(f"  policy {name:<24} fleet={p.get('fleet'):<8} "
              f"value={value_s:<10} {' '.join(bounds):<20} "
              f"streaks={p.get('up_streak')}/{p.get('down_streak')} "
              f"(need {p.get('for_count')})")
    last = body.get("last_decision")
    if last:
        print(f"  last decision: scale_{last.get('direction')} "
              f"{last.get('fleet')} {last.get('from')}->{last.get('to')} "
              f"at {_fmt_ts(last.get('ts'))}  ({last.get('reason')})")


# the per-role perf series worth a one-line digest (flattened TSDB keys;
# token = learner class name, sources = fleet processes)
_PERF_DIGEST_NAMES = tuple(
    f"{name}{{token={token}}}"
    for name in ("distar_perf_step_seconds", "distar_perf_frames_per_s",
                 "distar_perf_mfu", "distar_perf_implied_tflops")
    for token in ("rllearner", "sllearner")
)


def _print_distill_digest(addr: str) -> None:
    """Distillation digest for ``status``: student vs teacher generation,
    the live divergence gauge (total + per head), the step-cost ratio when
    a learner published one, and the canary split state — everything an
    operator needs to see student drift without reading raw metrics. All
    from the probed TSDB (shipped by any ``--distill`` learner) plus the
    coordinator's ``serve_canary`` record; silent when no distill learner
    ever shipped."""
    def last_of(name, window=600):
        body = _try_get(addr,
                        f"/timeseries?name={urllib.parse.quote(name)}"
                        f"&window_s={window}")
        best = None
        for source, st in ((body or {}).get("stats") or {}).items():
            if st and st.get("last") is not None:
                ts = st.get("last_ts", 0.0)
                if best is None or ts > best[0]:
                    best = (ts, source, st["last"])
        return best  # (ts, source, value) or None

    kl = last_of("distar_distill_kl")
    if kl is None:
        return
    print("distillation:")
    student = last_of("distar_distill_student_generation")
    teacher = last_of("distar_distill_teacher_generation")
    s_gen = int(student[2]) if student else "-"
    t_gen = int(teacher[2]) if teacher else "-"
    lag = (f" (lag {int(teacher[2]) - int(student[2])})"
           if student and teacher else "")
    print(f"  [{kl[1]}] student_gen={s_gen} teacher_gen={t_gen}{lag}  "
          f"divergence={kl[2]:.6g}")
    heads = []
    for head in ("action_type", "delay", "queued", "selected_units",
                 "target_unit", "target_location"):
        row = last_of(f"distar_distill_head_kl{{head={head}}}")
        if row:
            heads.append(f"{head}={row[2]:.4g}")
    if heads:
        print(f"  per-head KL: {' '.join(heads)}")
    ratio = last_of("distar_distill_step_cost_ratio")
    if ratio:
        print(f"  step-cost ratio: {ratio[2]:.4g}x teacher (FLOPs-derived)")
    canary = _try_post(addr, "/coordinator/peers", {"token": "serve_canary"})
    recs = (canary or {}).get("info") or []
    if recs:
        latest = max(recs, key=lambda r: r.get("ts", 0.0))
        meta = latest.get("meta") or {}
        if meta.get("pct"):
            print(f"  canary split: {meta.get('pct')}% -> "
                  f"{','.join(meta.get('addrs') or [])} "
                  f"(version {meta.get('version') or '?'})")
        else:
            print("  canary split: none (pct=0)")


def _print_arena_digest(addr: str) -> None:
    """Arena digest for ``status``: match accounting + the current top of
    the ladder — read off the coordinator's GET /arena/ratings route
    (absent when no arena store is hosted there)."""
    body = _try_get(addr, "/arena/ratings")
    if not body:
        return
    players = body.get("players") or {}
    top = max(players.items(), key=lambda kv: kv[1].get("elo", 0.0))[0] \
        if players else "-"
    print(f"arena: {body.get('matches_total', 0)} matches "
          f"({body.get('duplicates_total', 0)} deduped) "
          f"players={len(players)} top={top}")


def _print_coordinator_ha_digest(addr: str) -> None:
    """Coordinator HA digest for ``status``: leadership role, fencing epoch
    and journal position per coordinator, plus the standby's replication lag
    (records + seconds behind the primary) with a freshness warning when the
    standby has drifted far enough that a failover would replay stale state.
    ``addr`` may be a comma list (the same spec clients pass as
    ``--coordinator-addr``); a single probed coordinator also reveals its
    peers, which are folded in. Silent when nothing at ``addr`` speaks HA
    (GET /coordinator/ha is 404 on a journal-less coordinator)."""
    probed = {}
    pending = [a.strip() for a in addr.split(",") if a.strip()]
    while pending:
        a = pending.pop(0)
        if a in probed:
            continue
        body = _try_get(a, "/coordinator/ha", timeout=3.0)
        probed[a] = body
        for peer in (body or {}).get("peers") or []:
            if peer not in probed:
                pending.append(peer)
    rows = {a: b for a, b in probed.items() if b}
    if not rows:
        return
    print("coordinator HA:")
    for a in sorted(rows):
        b = rows[a]
        role = b.get("role", "?")
        line = (f"  {a:<24} role={role:<8} epoch={b.get('epoch', 0)} "
                f"seq={b.get('seq', 0)}")
        if role == "standby":
            lag_r = int(b.get("journal_lag_records", 0))
            lag_s = float(b.get("journal_lag_seconds", 0.0))
            line += f" lag={lag_r} records / {lag_s:.1f}s behind"
            if lag_r > 256 or lag_s > 30.0:
                line += "  STALE STANDBY (failover would lose recent state)"
        print(line)
    roles = [b.get("role") for b in rows.values()]
    if "primary" not in roles:
        print("  WARNING: no primary answering (fleet is between leaders)")
    elif roles.count("primary") > 1:
        print("  WARNING: multiple primaries answering (epoch fencing will "
              "demote the loser; check again shortly)")
    elif "standby" not in roles and len(rows) == 1:
        n = int(next(iter(rows.values())).get("followers", 0))
        if n:
            print(f"  note: {n} follower(s) tailing the journal feed "
                  "(probe the comma list for their lag)")
        else:
            print("  note: single HA coordinator probed, no standby attached "
                  "(a failover here would wait on a cold journal replay)")


def cmd_arena(args) -> int:
    """The arena scoreboard: rating ladder, payoff matrix with Wilson
    intervals, PFSP preview weights, and rating-over-time trajectories from
    the shipped ``distar_arena_*`` TSDB series."""
    ratings = _get(args.addr, "/arena/ratings")
    payoff = _get(args.addr, "/arena/payoff")
    if args.json:
        print(json.dumps({"ratings": ratings, "payoff": payoff}, indent=1))
        return 0
    players = ratings.get("players") or {}
    print(f"arena scoreboard  ({ratings.get('matches_total', 0)} matches, "
          f"{ratings.get('duplicates_total', 0)} duplicates deduped)")
    print(f"  {'player':<24} {'elo':>8} {'trueskill':>10} {'games':>6}")
    ordered = sorted(players.items(), key=lambda kv: -kv[1].get("elo", 0.0))
    for pid, row in ordered:
        tag = "  (anchor)" if row.get("anchor") else ""
        print(f"  {pid:<24} {row.get('elo', 0.0):>8.1f} "
              f"{row.get('trueskill_exposed', 0.0):>10.2f} "
              f"{row.get('games', 0):>6}{tag}")
    cells = payoff.get("cells") or []
    if cells:
        print("payoff matrix (a-perspective, draws count half):")
        for c in cells:
            if not c.get("games"):
                continue
            print(f"  {c['a']:<20} vs {c['b']:<20} "
                  f"wr={c['win_rate']:.3f} "
                  f"ci=[{c['wilson_low']:.3f},{c['wilson_high']:.3f}] "
                  f"n={c['games']}")
    preview = payoff.get("pfsp_preview") or {}
    if preview:
        print(f"pfsp preview ({payoff.get('pfsp_weighting', 'variance')} "
              f"weighting):")
        for pid in sorted(preview):
            row = " ".join(f"{o}={w:.3f}"
                           for o, w in sorted(preview[pid].items()))
            print(f"  {pid:<24} {row}")
    # rating-over-time from the shipped TSDB series: the coordinator's
    # registry sampler turns every distar_arena_rating_elo gauge into a
    # series per player — the learning-curve view of the ladder
    shown = False
    for pid, _ in ordered:
        name = urllib.parse.quote(f"distar_arena_rating_elo{{player={pid}}}")
        body = _try_get(args.addr,
                        f"/timeseries?name={name}&window_s={args.window}")
        for source, pts in ((body or {}).get("points") or {}).items():
            if not pts:
                continue
            if not shown:
                print("rating trajectories (TSDB):")
                shown = True
            first, last = pts[0][1], pts[-1][1]
            print(f"  {pid:<24} [{source}] {len(pts)} pts  "
                  f"{first:.1f} -> {last:.1f}  "
                  f"({'+' if last >= first else ''}{last - first:.1f})")
    return 0


def cmd_league(args) -> int:
    """The self-play economy digest: roster (active/frozen/historical),
    learner leases, jobs dispatched per matchmaking branch, outstanding
    assignments, snapshot mints and elastic reassignments — the
    ``GET /league/status`` surface of the coordinator-hosted
    ``LeagueService`` (docs/league.md)."""
    st = _get(args.addr, "/league/status")
    if args.json:
        print(json.dumps(st, indent=1))
        return 0
    print(f"league  ({st.get('active_learners', 0)}/"
          f"{st.get('registered_learners', 0)} learners fresh, "
          f"lease={st.get('lease_s', 0):.0f}s "
          f"job_ttl={st.get('job_ttl_s', 0):.0f}s)")
    frozen = set(st.get("frozen_players") or [])
    learners_by_player = {}
    for lid, e in (st.get("learners") or {}).items():
        learners_by_player.setdefault(e.get("player_id", "?"), []).append(
            (lid, e))
    print(f"  {'player':<12} {'state':<8} learners")
    for pid in st.get("active_players") or []:
        rows = learners_by_player.get(pid, [])
        detail = ", ".join(
            f"{lid}(fresh)" if e.get("fresh")
            else f"{lid}(stale {e.get('age_s', 0.0):.0f}s)"
            for lid, e in sorted(rows)) or "-"
        state = "FROZEN" if pid in frozen else "active"
        print(f"  {pid:<12} {state:<8} {detail}")
    hist = st.get("historical_players") or []
    print(f"historical players: {len(hist)}"
          + (f"  ({', '.join(hist[:8])}{', ...' if len(hist) > 8 else ''})"
             if hist else ""))
    jobs = st.get("jobs_by_branch") or {}
    total = sum(jobs.values())
    dist = "  ".join(f"{b}={jobs.get(b, 0)}"
                     for b in ("sp", "pfsp", "vs_main", "eval"))
    print(f"jobs dispatched: {total}  ({dist})")
    pending = st.get("assignments") or {}
    print(f"assignments pending: {len(pending)}"
          f"  orphaned(ttl-expired): {st.get('orphaned_jobs', 0)}")
    for jid, a in sorted(pending.items()):
        print(f"  {jid:<8} {a.get('branch', '?'):<8} "
              f"{' vs '.join(a.get('player_ids') or [])}  "
              f"learner={a.get('learner_id') or '?'}")
    print(f"snapshot mints: {st.get('snapshot_mints', 0)}"
          f"  reassignments: {st.get('reassignments', 0)}")
    return 0


def _print_actor_digest(addr: str) -> None:
    """Actor-throughput digest from the probed TSDB: env-steps/s, the
    rollout-plane backend serving the fleet, plane sample rates per
    backend, and the serve-plane shed rate — the four numbers that say
    whether the rollout plane is keeping the fleet fed (docs/serving.md)."""
    rows = []
    body = _try_get(addr, "/timeseries?name=distar_actor_env_step_rate&window_s=600")
    for source, st in ((body or {}).get("stats") or {}).items():
        if st and st.get("last") is not None:
            rows.append((source, "env_steps_per_s", f"{st['last']:.6g}"))
    backends = []
    for backend in ("inline", "local", "remote", "anakin"):
        name = urllib.parse.quote(
            f"distar_rollout_plane_backend{{backend={backend}}}")
        body = _try_get(addr, f"/timeseries?name={name}&window_s=600")
        for source, st in ((body or {}).get("stats") or {}).items():
            if st and st.get("last") == 1.0:
                backends.append((source, backend))
        name = urllib.parse.quote(
            f"distar_rollout_samples_total{{backend={backend}}}")
        body = _try_get(addr, f"/timeseries?name={name}&window_s=600")
        for source, st in ((body or {}).get("stats") or {}).items():
            if st and st.get("rate"):
                rows.append((source, f"plane_samples_per_s[{backend}]",
                             f"{st['rate']:.6g}"))
    # the fused-rollout tier has no plane samples: its feed-rate signal is
    # the per-window env-steps/s gauge
    body = _try_get(addr, "/timeseries?name=distar_anakin_env_steps_per_s&window_s=600")
    for source, st in ((body or {}).get("stats") or {}).items():
        if st and st.get("last") is not None:
            rows.append((source, "anakin_env_steps_per_s",
                         f"{st['last']:.6g}"))
    shed = 0.0
    for reason in ("shed_queue_full", "shed_deadline", "shed_capacity", "draining"):
        name = urllib.parse.quote(f"distar_serve_shed_total{{reason={reason}}}")
        body = _try_get(addr, f"/timeseries?name={name}&window_s=600")
        for _source, st in ((body or {}).get("stats") or {}).items():
            shed += st.get("rate") or 0.0
    if not rows and not backends:
        return
    print("actor:")
    for source, backend in sorted(backends):
        print(f"  {source:<24} plane_backend={backend}")
    for source, name, value in sorted(rows):
        print(f"  {source:<24} {name:<28} {value}")
    if shed:
        print(f"  serve shed rate: {shed:.4g}/s")


def _print_perf_digest(addr: str) -> None:
    """Per-role step-time/MFU digest from the probed TSDB: one line per
    (series, source) with the last value — the 10-second answer to "how
    fast is each learner stepping and at what MFU"."""
    rows = []
    for name in _PERF_DIGEST_NAMES:
        body = _try_get(addr, f"/timeseries?name={urllib.parse.quote(name)}&window_s=600")
        if not body or not body.get("points"):
            continue
        for source, st in (body.get("stats") or {}).items():
            if st and st.get("last") is not None:
                rows.append((source, name, st["last"], st.get("mean")))
    if not rows:
        return
    print("perf:")
    for source, name, last, mean in sorted(rows):
        short = name.replace("distar_perf_", "")
        mean_s = f"{mean:.6g}" if isinstance(mean, (int, float)) else "—"
        print(f"  {source:<24} {short:<40} last={last:<12.6g} mean={mean_s}")


_DYN_HEADS = ("action_type", "delay", "queued", "selected_units",
              "target_unit", "target_location")


def _print_dynamics_digest(addr: str) -> None:
    """Training-dynamics digest for ``status``: per-learner total grad
    norm / EMA, update-to-weight ratio, clip fraction, the top-3 loss
    heads by magnitude, and the last anomaly (step + bundle count) — the
    10-second answer to "are the gradients healthy and what dominates the
    loss". All from the probed TSDB (shipped by any learner running the
    dynamics monitor); silent when no learner ever shipped the tree."""
    def stats_of(name, window=600):
        body = _try_get(addr,
                        f"/timeseries?name={urllib.parse.quote(name)}"
                        f"&window_s={window}")
        out = {}
        for source, st in ((body or {}).get("stats") or {}).items():
            if st and st.get("last") is not None:
                out[source] = st["last"]
        return out  # {source: last}

    grad = stats_of("distar_train_grad_norm{module=total}")
    if not grad:
        return
    ema = stats_of("distar_train_grad_norm_ema")
    ratio = stats_of("distar_train_update_ratio{module=total}")
    clip = stats_of("distar_train_grad_clip_fraction")
    print("training dynamics:")
    for source in sorted(grad):
        parts = [f"grad_norm={grad[source]:.6g}"]
        if source in ema:
            parts.append(f"ema={ema[source]:.6g}")
        if source in ratio:
            parts.append(f"update_ratio={ratio[source]:.4g}")
        if source in clip:
            parts.append(f"clip_fraction={clip[source]:.4g}")
        print(f"  {source:<24} {'  '.join(parts)}")
    # top-3 loss heads by |last| across the bounded term x head grid
    heads = []
    for term in ("sl", "pg", "upgo", "entropy", "kl", "dapo"):
        for head in _DYN_HEADS:
            rows = stats_of(
                f"distar_train_loss_head{{head={head},term={term}}}")
            if not rows:
                rows = stats_of(
                    f"distar_train_loss_head{{term={term},head={head}}}")
            for _source, last in rows.items():
                heads.append((abs(last), f"{term}/{head}", last))
    if heads:
        top = sorted(heads, reverse=True)[:3]
        print("  top loss heads: "
              + "  ".join(f"{name}={last:.6g}" for _m, name, last in top))
    anomaly = stats_of("distar_train_last_anomaly_step")
    bundles = stats_of("distar_train_blackbox_bundles_total")
    for source in sorted(anomaly):
        n = bundles.get(source)
        extra = f" ({int(n)} black-box bundle(s) — opsctl dynamics)" \
            if n else ""
        print(f"  {source:<24} last_anomaly_step={int(anomaly[source])}{extra}")


def cmd_dynamics(args) -> int:
    """Black-box bundle browser (local filesystem — bundles are forensic
    artifacts, not telemetry): list a directory's bundles, or inspect one
    (summary, provenance, the worst diagnostics) and print the stepreplay
    invocation that reproduces it."""
    from distar_tpu.obs.dynamics import (bundle_summary, list_bundles,
                                         load_bundle)

    dirpath = args.dir
    if os.path.isdir(os.path.join(dirpath, "blackbox")):
        dirpath = os.path.join(dirpath, "blackbox")  # experiment root given
    bundles = list_bundles(dirpath)
    if args.inspect:
        match = [b for b in bundles if args.inspect in b["id"]]
        if not match:
            print(f"no bundle matching {args.inspect!r} under {dirpath}")
            return 1
        bundle = load_bundle(match[0]["path"])
        if args.json:
            print(json.dumps(bundle_summary(bundle), indent=1, default=str))
        else:
            for k, v in bundle_summary(bundle).items():
                print(f"  {k}: {v}")
            diag = bundle.get("diagnostics") or {}
            worst = sorted(
                ((v, k) for k, v in diag.items()
                 if k.startswith("dyn/nonfinite_") and not k.endswith("/total")
                 and v and v == v),
                reverse=True)[:5]
            if worst:
                print("  non-finite census: "
                      + "  ".join(f"{k}={int(v)}" for v, k in worst))
            print(f"  replay: python tools/stepreplay.py --bundle "
                  f"{match[0]['path']}")
        return 0
    if not bundles:
        print(f"no black-box bundles under {dirpath}")
        return 1
    if args.json:
        print(json.dumps(bundles, indent=1))
        return 0
    for b in bundles:
        print(f"  {b['id']}  step={b['step']}  reason={b['reason']}")
    return 0


def cmd_status(args) -> int:
    body = _get(args.addr, "/healthz")
    status = body.get("status", "unknown")
    print(f"status: {status}   (started={body.get('started')})")
    rules = body.get("rules", {})
    if rules:
        width = max(len(n) for n in rules)
        for name in sorted(rules):
            print(f"  {name:<{width}}  {rules[name]}")
    sources = body.get("sources", {})
    if sources:
        print("sources:")
        for name in sorted(sources):
            s = sources[name]
            stale = "  STALE" if s.get("stale") else ""
            print(f"  {name:<24} age={s.get('age_s', 0):7.1f}s "
                  f"series={s.get('series', 0)}{stale}")
    tsdb = body.get("tsdb", {})
    if tsdb:
        print(f"tsdb: {tsdb.get('series')} series "
              f"(cap {tsdb.get('max_series')} x {tsdb.get('points_per_series')} pts, "
              f"{tsdb.get('dropped_series')} dropped)")
    # replay digest: explicit --replay-addrs fleet, else shards discovered
    # from a probed coordinator's replay_shard registrations, else the
    # probed address itself (a single replay admin surface)
    admin_addrs = ([a.strip() for a in args.replay_addrs.split(",") if a.strip()]
                   if args.replay_addrs else _discover_replay_admins(args.addr))
    if admin_addrs:
        per_shard = {}
        for admin in admin_addrs:
            stats = _try_get(admin, "/replay/stats")
            per_shard[admin] = stats if stats else {"error": "unreachable"}
        _print_replay(per_shard)
    else:
        replay = _try_get(args.addr, "/replay/stats")
        if replay:
            _print_replay({args.addr: replay})
    # serving-fleet digest: gateways auto-discovered from a probed
    # coordinator's serve_gateway registrations (each block read off the
    # gateway's own /serve/status)
    _print_serve_fleet(_discover_serve_gateways(args.addr))
    # elastic-control-plane digest (present when the probed coordinator
    # hosts an autoscaler): policy state, target vs actual, live drains
    _print_autoscaler(args.addr)
    # distillation-tier digest (present when a --distill learner ships
    # telemetry here): student/teacher generation drift, live divergence,
    # canary split state
    _print_distill_digest(args.addr)
    # training-dynamics digest (present when a learner ships the dynamics
    # tree): per-learner grad norm / update ratio / clip fraction, top
    # loss heads, last anomaly + bundle count
    _print_dynamics_digest(args.addr)
    # coordinator-HA digest (present when the probed coordinator journals):
    # role/epoch/journal position per coordinator, standby replication lag
    _print_coordinator_ha_digest(args.addr)
    # skill-ledger digest (present when the probed coordinator hosts the
    # arena store): match accounting + the ladder's current top
    _print_arena_digest(args.addr)
    _print_perf_digest(args.addr)
    _print_actor_digest(args.addr)
    return {"ok": 0, "warning": 1}.get(status, 2)


def _print_event(e: dict) -> None:
    print(f"{_fmt_ts(e.get('ts'))}  {e.get('state', '?'):<8} {e.get('rule', '?')}  "
          f"value={e.get('value')}  series={e.get('series')}  "
          f"[{e.get('severity', '')}] {e.get('summary', '')}")


def cmd_tail_alerts(args) -> int:
    seen = -1
    try:
        while True:
            body = _get(args.addr, "/alerts")
            history = body.get("history", [])
            # the evaluator doesn't stamp seq; dedupe on (ts, rule, state)
            fresh = [e for i, e in enumerate(history) if i > seen or args.once]
            if seen < 0 and not args.once:
                # first poll: show current context, then follow
                for e in history[-10:]:
                    _print_event(e)
            else:
                for e in fresh:
                    _print_event(e)
            seen = len(history) - 1
            firing = body.get("firing", [])
            if args.once:
                if firing:
                    print(f"firing: {', '.join(firing)}")
                return 2 if firing else 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def cmd_query(args) -> int:
    path = f"/timeseries?name={urllib.parse.quote(args.name)}&window_s={args.window}"
    if args.source:
        path += f"&source={urllib.parse.quote(args.source)}"
    body = _get(args.addr, path)
    stats = body.get("stats") or {}
    points = body.get("points") or {}
    if not points:
        print(f"no data for {args.name!r} in the last {args.window}s")
        return 1
    if args.json:
        print(json.dumps(body, indent=1))
        return 0
    for source in sorted(points):
        st = stats.get(source) or {}
        print(f"{args.name} @ {source}: n={st.get('count')} last={st.get('last')} "
              f"mean={st.get('mean')} min={st.get('min')} max={st.get('max')} "
              f"rate={st.get('rate')}")
        for ts, v in points[source][-args.tail:]:
            print(f"  {_fmt_ts(ts)}  {v}")
    return 0


def cmd_trace(args) -> int:
    """Distributed-trace consumer: list retained traces, or render one
    trace's waterfall + ranked critical path. Exit 0 on success, 1 when
    nothing matched (scriptable: a bench can assert its slow request is
    retrievable)."""
    from distar_tpu.obs.waterfall import build_waterfall, render_listing, render_waterfall

    if args.id:
        body = _try_get(args.addr, f"/trace/{args.id}", timeout=10.0)
        if not body or not body.get("spans"):
            print(f"no spans for trace {args.id!r} at {args.addr}")
            return 1
        if args.json:
            print(json.dumps(body, indent=1))
            return 0
        report = body.get("waterfall") or build_waterfall(body["spans"])
        print(render_waterfall(report))
        return 0
    qs = [f"limit={args.limit}"]
    if args.name:
        qs.append(f"name={urllib.parse.quote(args.name)}")
    if args.min_ms:
        qs.append(f"min_ms={args.min_ms}")
    if args.outcome:
        qs.append(f"outcome={urllib.parse.quote(args.outcome)}")
    body = _try_get(args.addr, "/traces?" + "&".join(qs), timeout=10.0)
    if body is None:
        raise SystemExit(f"GET /traces failed at {args.addr} (no trace surface?)")
    rows = body.get("traces") or []
    if args.json:
        print(json.dumps(body, indent=1))
        return 0 if rows else 1
    print(render_listing(rows), end="")
    ing = body.get("ingest") or {}
    buf = body.get("buffer") or {}
    print(f"(ingest: {ing.get('records', 0)} records / "
          f"{ing.get('sources', 0)} sources; local buffer: "
          f"{buf.get('resident', 0)}/{buf.get('maxlen', 0)})")
    return 0 if rows else 1


def cmd_profile(args) -> int:
    """On-demand fleet profiling: POST /learner/profile?steps=N on a live
    learner's admin surface, print the ranked bucket table. Blocks while
    the learner captures + analyzes (bounded by --timeout)."""
    url = (f"http://{args.addr}/learner/profile?steps={args.steps}"
           f"&timeout_s={args.timeout}")
    req = urllib.request.Request(url, data=b"{}", method="POST",
                                 headers={"Content-Type": "application/json"})
    try:
        # +30s transport grace over the learner-side capture budget
        with urllib.request.urlopen(req, timeout=args.timeout + 30.0) as resp:
            body = json.loads(resp.read())
    except (urllib.error.URLError, ConnectionError, TimeoutError, OSError,
            ValueError) as e:
        raise SystemExit(f"POST {url} failed: {e!r}")
    if body.get("code") != 0:
        raise SystemExit(f"profile failed: {body.get('info')}")
    report = body["info"]
    if args.json:
        print(json.dumps(report, indent=1))
        return 0
    print(report.get("markdown", ""))
    perf = report.get("perf") or {}
    if perf:
        parts = [f"{k}={v:.6g}" for k, v in sorted(perf.items())
                 if isinstance(v, (int, float))]
        print("live perf: " + " ".join(parts))
    return 0


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("command", choices=("status", "tail-alerts", "query",
                                       "profile", "trace", "dynamics",
                                       "arena", "league"))
    p.add_argument("--addr", default="127.0.0.1:8423", help="host:port of a health surface")
    p.add_argument("--interval", type=float, default=2.0, help="tail-alerts poll cadence")
    p.add_argument("--once", action="store_true",
                   help="tail-alerts: print the history once and exit "
                        "(exit 2 when anything is firing)")
    p.add_argument("--replay-addrs", default="",
                   help="status: comma-separated replay ADMIN surfaces to "
                        "aggregate the shard-fleet digest across (default: "
                        "auto-discover from the probed coordinator's "
                        "replay_shard registrations, else probe --addr)")
    p.add_argument("--name", default="", help="query: flattened series name")
    p.add_argument("--window", type=float, default=300.0, help="query window seconds")
    p.add_argument("--source", default="", help="query: restrict to one source")
    p.add_argument("--tail", type=int, default=10, help="query: points to print per source")
    p.add_argument("--json", action="store_true",
                   help="query/profile: raw JSON output")
    p.add_argument("--steps", type=int, default=2,
                   help="profile: iterations of device trace to capture")
    p.add_argument("--timeout", type=float, default=600.0,
                   help="profile: learner-side capture+analysis budget (s)")
    p.add_argument("--id", default="",
                   help="trace: render this trace_id's waterfall instead of "
                        "listing")
    p.add_argument("--min-ms", type=float, default=0.0,
                   help="trace: list only traces at least this slow")
    p.add_argument("--outcome", default="",
                   help="trace: filter by outcome (ok/shed/error)")
    p.add_argument("--limit", type=int, default=20,
                   help="trace: max listings")
    p.add_argument("--dir", default="",
                   help="dynamics: blackbox directory (or an experiment "
                        "root containing blackbox/)")
    p.add_argument("--inspect", default="",
                   help="dynamics: inspect the bundle whose id contains "
                        "this substring instead of listing")
    args = p.parse_args()
    if args.command == "status":
        return cmd_status(args)
    if args.command == "dynamics":
        if not args.dir:
            p.error("dynamics requires --dir")
        return cmd_dynamics(args)
    if args.command == "tail-alerts":
        return cmd_tail_alerts(args)
    if args.command == "profile":
        return cmd_profile(args)
    if args.command == "trace":
        return cmd_trace(args)
    if args.command == "arena":
        return cmd_arena(args)
    if args.command == "league":
        return cmd_league(args)
    if not args.name:
        p.error("query requires --name")
    return cmd_query(args)


if __name__ == "__main__":
    raise SystemExit(main())
