"""Serve subsystem smoke: in-process gateway + mock engine, concurrent
clients over threads. The tier-1 acceptance surface: coalesced batches
(mean occupancy > 1), zero-loss hot swap under load, typed shed responses
from admission control, serve metrics visible in the obs registry.

The mock engine's ``delay_s`` sleep releases the GIL like a device
dispatch, so client threads genuinely pile up behind a flush — batching
happens for the same reason it does on a TPU, not by test rigging.
"""
import threading
import time

import numpy as np
import pytest

from distar_tpu.obs import MetricsRegistry, get_registry, set_registry
from distar_tpu.serve import (
    CapacityError,
    DeadlineExceededError,
    DrainingError,
    InferenceGateway,
    MicroBatcher,
    MockModelEngine,
    ModelRegistry,
    PendingRequest,
    QueueFullError,
    ServeClient,
    ServeError,
    ServeHTTPServer,
    ServeTCPServer,
    SessionTable,
    error_from_wire,
)


@pytest.fixture(autouse=True)
def fresh_registry():
    prev = set_registry(MetricsRegistry())
    yield
    set_registry(prev)


def obs_of(v: float) -> dict:
    return {"x": np.full((2, 3), v, dtype=np.float32)}


def make_gateway(slots=8, delay_s=0.003, max_delay_s=0.01, capacity=64, **kw):
    engine = MockModelEngine(slots, params={"version": "v1", "bias": 0.0}, delay_s=delay_s)
    gw = InferenceGateway(
        engine, max_delay_s=max_delay_s, queue_capacity=capacity, **kw
    ).start()
    gw.load_version("v1", params={"version": "v1", "bias": 0.0}, activate=True)
    return engine, gw


# --------------------------------------------------------------- tier-1 smoke
def test_concurrent_clients_are_batched_and_metrics_visible():
    engine, gw = make_gateway(slots=8, delay_s=0.005, max_delay_s=0.02)
    n_clients, n_req = 8, 12
    errors = []

    def client(c):
        sid = f"client-{c}"
        try:
            for i in range(n_req):
                out = gw.act(sid, obs_of(c), timeout_s=10.0)
                # correctness of the decollation: this slot's obs, this
                # session's step counter
                assert out["action"] == pytest.approx(c * 6.0)
                assert out["step"] == i + 1
                assert out["model_version"] == "v1"
        except Exception as e:  # pragma: no cover - surfaced via errors list
            errors.append(e)

    threads = [threading.Thread(target=client, args=(c,)) for c in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    gw.drain_and_stop()
    assert not errors
    snap = get_registry().snapshot()
    # every request served through a coalesced flush; occupancy must beat 1
    occ_count = snap["distar_serve_batch_occupancy_count"]
    occ_sum = snap["distar_serve_batch_occupancy_sum"]
    assert occ_sum == n_clients * n_req  # nothing lost, nothing double-served
    assert occ_sum / occ_count > 1.0, "no batching observed"
    assert engine.forward_calls == occ_count
    # acceptance: serve metric families all present in the obs registry
    for fam in (
        "distar_serve_queue_depth",
        "distar_serve_batch_occupancy_count",
        "distar_serve_request_latency_seconds_count",
        "distar_serve_model_generation",
    ):
        assert any(k.startswith(fam) for k in snap), fam
    assert snap["distar_serve_requests_total{outcome=ok}"] == n_clients * n_req


def test_hot_swap_under_load_loses_no_inflight_requests():
    engine, gw = make_gateway(slots=4, delay_s=0.004, max_delay_s=0.01)
    per_client = [[] for _ in range(4)]
    errors = []
    stop = threading.Event()

    def client(c):
        sid = f"swap-client-{c}"
        while not stop.is_set():
            try:
                per_client[c].append(
                    gw.act(sid, obs_of(1.0), timeout_s=10.0)["model_version"]
                )
            except Exception as e:  # pragma: no cover
                errors.append(e)
                return

    threads = [threading.Thread(target=client, args=(c,)) for c in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.15)
    # load + warm + activate while traffic flows
    gw.load_version("v2", params={"version": "v2", "bias": 1.0}, activate=True)
    time.sleep(0.15)
    stop.set()
    for t in threads:
        t.join()
    gw.drain_and_stop()
    assert not errors, errors[:3]
    results = [v for seq in per_client for v in seq]
    assert set(results) == {"v1", "v2"}  # traffic flowed on both sides of the swap
    for seq in per_client:
        # zero dropped in-flight: each client's stream is a clean v1* v2*
        # boundary — the swap applied atomically between flushes
        assert seq == sorted(seq), seq
    snap = get_registry().snapshot()
    assert snap["distar_serve_swaps_total"] == 2  # v1 boot + v2 swap
    assert snap["distar_serve_swap_duration_seconds_count"] >= 1
    assert snap["distar_serve_requests_total{outcome=ok}"] == len(results)


def test_queue_full_sheds_typed_without_blocking():
    # capacity 2, one slow slot: the third concurrent submit must shed fast
    engine = MockModelEngine(1, delay_s=0.2)
    gw = InferenceGateway(engine, max_delay_s=0.001, queue_capacity=2).start()
    outcomes = []

    def client():
        try:
            gw.act("same-session", obs_of(1.0), timeout_s=5.0)
            outcomes.append("ok")
        except QueueFullError:
            outcomes.append("shed")

    threads = [threading.Thread(target=client) for _ in range(6)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    gw.drain_and_stop()
    assert "shed" in outcomes, outcomes
    assert elapsed < 5.0  # sheds answered immediately, not serialized behind the queue
    snap = get_registry().snapshot()
    assert snap["distar_serve_shed_total{reason=shed_queue_full}"] == outcomes.count("shed")


def test_request_deadline_sheds_typed():
    engine = MockModelEngine(2, delay_s=0.15)
    gw = InferenceGateway(engine, max_delay_s=0.001, queue_capacity=8).start()
    # first request occupies the engine; the second's deadline lapses queued
    t1 = threading.Thread(target=lambda: gw.act("s1", obs_of(1.0), timeout_s=5.0))
    t1.start()
    time.sleep(0.02)  # flush 1 departed (1ms deadline) and is in the forward
    with pytest.raises(DeadlineExceededError):
        gw.act("s2", obs_of(1.0), timeout_s=0.05)
    t1.join()
    gw.drain_and_stop()
    assert get_registry().snapshot()["distar_serve_shed_total{reason=shed_deadline}"] >= 1


# ------------------------------------------------------------------- sessions
def test_sticky_sessions_keep_separate_recurrent_state():
    engine, gw = make_gateway(slots=4, delay_s=0.0, max_delay_s=0.002)
    for i in range(3):
        assert gw.act("a", obs_of(0.0))["step"] == i + 1
    assert gw.act("b", obs_of(0.0))["step"] == 1  # b's slot, not a's
    assert gw.reset_session("a") is True  # episode boundary: carry zeroed
    assert gw.act("a", obs_of(0.0))["step"] == 1
    assert gw.act("b", obs_of(0.0))["step"] == 2  # b untouched by a's reset
    assert gw.end_session("a") is True
    assert gw.reset_session("a") is False  # gone
    gw.drain_and_stop()


def test_session_capacity_shed_and_idle_eviction():
    engine, gw = make_gateway(slots=2, delay_s=0.0, max_delay_s=0.001, idle_ttl_s=0.2)
    assert gw.act("s1", obs_of(1.0))["step"] == 1
    assert gw.act("s2", obs_of(1.0))["step"] == 1
    with pytest.raises(CapacityError):
        gw.act("s3", obs_of(1.0))
    time.sleep(0.25)  # s1/s2 idle past ttl -> evictable
    assert gw.act("s3", obs_of(1.0))["step"] == 1  # fresh slot, zeroed carry
    gw.drain_and_stop()
    assert get_registry().snapshot()["distar_serve_session_evictions_total"] == 1


def test_slot_zeroed_on_recycle_not_leaked():
    engine = MockModelEngine(1, delay_s=0.0)
    gw = InferenceGateway(engine, max_delay_s=0.001, idle_ttl_s=0.05).start()
    for _ in range(3):
        gw.act("first", obs_of(1.0))
    time.sleep(0.1)
    # second session takes the recycled slot: must start from zero carry
    assert gw.act("second", obs_of(1.0))["step"] == 1
    gw.drain_and_stop()


# ----------------------------------------------------------------- shutdown
def test_drain_then_stop_completes_admitted_sheds_new():
    # 3 clients on a 4-lane engine with a long flush deadline: requests sit
    # admitted-but-unflushed until the drain takes them
    engine, gw = make_gateway(slots=4, delay_s=0.0, max_delay_s=0.5)
    results = []
    threads = [
        threading.Thread(
            target=lambda c=c: results.append(gw.act(f"d{c}", obs_of(1.0), timeout_s=5.0))
        )
        for c in range(3)
    ]
    for t in threads:
        t.start()
    t0 = time.perf_counter()
    while gw.batcher.depth < 3 and time.perf_counter() - t0 < 2.0:
        time.sleep(0.002)
    assert gw.batcher.depth == 3  # all admitted, none flushed yet
    gw.drain_and_stop(timeout=10.0)
    for t in threads:
        t.join()
    assert len(results) == 3  # everything admitted was served by the drain flush
    with pytest.raises(DrainingError):
        gw.act("late", obs_of(1.0))
    snap = get_registry().snapshot()
    assert snap["distar_serve_flush_total{reason=drain}"] >= 1


# ------------------------------------------------------------------ registry
def test_registry_warmup_runs_off_serving_path_and_unknown_version():
    engine, gw = make_gateway(slots=2, delay_s=0.0)
    assert gw.act("s", obs_of(1.0))["model_version"] == "v1"  # template learned
    calls_before = engine.forward_calls
    gw.load_version("v9", params={"version": "v9", "bias": 0.0})  # no activate
    assert engine.warmup_calls >= 1  # warm-up happened...
    assert engine.forward_calls == calls_before  # ...but not through serving flushes
    assert gw.act("s", obs_of(1.0))["model_version"] == "v1"  # still v1 until swap
    gw.activate_version("v9")
    assert gw.act("s", obs_of(1.0))["model_version"] == "v9"
    from distar_tpu.serve import UnknownVersionError

    with pytest.raises(UnknownVersionError):
        gw.activate_version("never-loaded")
    status = gw.status()
    assert status["registry"]["current"] == "v9"
    assert set(status["registry"]["versions"]) == {"v1", "v9"}
    gw.drain_and_stop()


def test_registry_loads_checkpoint_through_storage_urls(tmp_path):
    """End-to-end version load via utils.checkpoint + mem:// storage."""
    from distar_tpu.utils.checkpoint import save_checkpoint

    state = {"params": {"w": np.ones((3,), np.float32)}, "opt_state": {"m": np.zeros(3)}}
    url = "mem://serve-test/ckpt-1"
    save_checkpoint(url, state)
    reg = ModelRegistry()
    reg.load("ck1", source=url, activate=True)
    gen, version, params = reg.current()
    assert version == "ck1" and gen == 1
    np.testing.assert_allclose(params["w"], np.ones(3))  # opt_state stripped
    assert "opt_state" not in params


# -------------------------------------------------------------------- errors
def test_error_wire_round_trip():
    for err in (QueueFullError("q"), DeadlineExceededError("d"), CapacityError("c"),
                DrainingError("x"), ServeError("e")):
        back = error_from_wire(err.to_wire())
        assert type(back) is type(err)
        assert back.shed == err.shed
    # unknown code degrades to base ServeError
    assert type(error_from_wire({"code": "from-the-future"})) is ServeError


# ------------------------------------------------------------------ frontends
def test_tcp_frontend_round_trip_and_swap():
    engine, gw = make_gateway(slots=4, delay_s=0.0, max_delay_s=0.002)
    srv = ServeTCPServer(gw, host="127.0.0.1").start()
    try:
        with ServeClient(srv.host, srv.port) as c:
            assert c.ping()
            out = c.act("tcp-1", obs_of(2.0))
            assert out["step"] == 1 and out["action"] == pytest.approx(12.0)
            assert isinstance(out["action"], np.ndarray)  # real numpy on the wire
            c.load("v2", params={"version": "v2", "bias": 1.0})
            c.swap("v2")
            assert c.act("tcp-1", obs_of(2.0))["model_version"] == "v2"
            assert c.reset("tcp-1") is True
            assert c.act("tcp-1", obs_of(2.0))["step"] == 1
            assert c.status()["registry"]["current"] == "v2"
            assert c.end("tcp-1") is True
    finally:
        srv.stop()
        gw.drain_and_stop()


def test_tcp_frontend_typed_shed_over_wire():
    engine = MockModelEngine(1, delay_s=0.0)
    gw = InferenceGateway(engine, max_delay_s=0.001, idle_ttl_s=300.0).start()
    srv = ServeTCPServer(gw, host="127.0.0.1").start()
    try:
        with ServeClient(srv.host, srv.port) as c:
            c.act("tcp-a", obs_of(1.0))
            with pytest.raises(CapacityError):  # rehydrated typed shed
                c.act("tcp-b", obs_of(1.0))
    finally:
        srv.stop()
        gw.drain_and_stop()


def test_http_frontend_act_status_metrics():
    import json
    import urllib.request

    engine, gw = make_gateway(slots=4, delay_s=0.0, max_delay_s=0.002)
    srv = ServeHTTPServer(gw, host="127.0.0.1").start()
    try:
        def post(route, body):
            req = urllib.request.Request(
                f"http://{srv.host}:{srv.port}/serve/{route}",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"}, method="POST",
            )
            return json.loads(urllib.request.urlopen(req, timeout=10).read())

        out = post("act", {"session_id": "h1", "obs": {"x": [[1.0, 2.0]]}})
        assert out["code"] == 0 and out["info"]["step"] == 1
        assert out["info"]["action"] == pytest.approx(3.0)
        assert post("status", {})["info"]["registry"]["current"] == "v1"
        assert post("bogus", {})["code"] == 404
        with urllib.request.urlopen(
            f"http://{srv.host}:{srv.port}/metrics", timeout=10
        ) as resp:
            text = resp.read().decode()
        assert "distar_serve_batch_occupancy" in text
        assert "distar_serve_requests_total" in text
    finally:
        srv.stop()
        gw.drain_and_stop()


# -------------------------------------------------------- component details
def test_batcher_flush_reasons():
    flushed = []
    b = MicroBatcher(lambda reqs, reason: flushed.append((len(reqs), reason)),
                     max_batch=2, max_delay_s=0.02, capacity=8)
    b.start()
    # distinct slots reach max_batch -> "full" without waiting the deadline
    r1, r2 = PendingRequest("a", 0, {}, None), PendingRequest("b", 1, {}, None)
    b.submit(r1)
    b.submit(r2)
    t0 = time.perf_counter()
    while len(flushed) < 1 and time.perf_counter() - t0 < 2.0:
        time.sleep(0.005)
    assert flushed and flushed[0] == (2, "full")
    # single request -> deadline flush
    b.submit(PendingRequest("c", 0, {}, None))
    t0 = time.perf_counter()
    while len(flushed) < 2 and time.perf_counter() - t0 < 2.0:
        time.sleep(0.005)
    assert flushed[1] == (1, "deadline")
    b.drain_and_stop()


def test_batcher_same_slot_requests_serialize_across_flushes():
    flushed = []
    b = MicroBatcher(lambda reqs, reason: flushed.append([r.session_id for r in reqs]),
                     max_batch=4, max_delay_s=0.005, capacity=8)
    # submit BEFORE start: the flush split is then deterministic
    b.submit(PendingRequest("one", 0, {}, None))
    b.submit(PendingRequest("one", 0, {}, None))  # same slot: next flush
    b.submit(PendingRequest("two", 1, {}, None))
    b.start()
    b.drain_and_stop()
    assert flushed == [["one", "two"], ["one"]]


def test_session_table_inflight_blocks_eviction():
    table = SessionTable(1, idle_ttl_s=0.0)  # everything instantly idle-expired
    table.acquire("busy")  # inflight=1, never released
    with pytest.raises(CapacityError):
        table.acquire("other")  # in-flight sessions are not evictable
    table.release("busy")
    assert table.acquire("other") == 0  # now evicted and recycled


# --------------------------------------------------- real-model integration
@pytest.mark.slow
def test_real_model_engine_serves_and_hot_swaps():
    """BatchedInferenceEngine end-to-end: the gateway serves the actual
    jitted ``sample_action`` (conftest SMALL_MODEL shapes) and a hot swap of
    same-shaped params reuses the compiled forward."""
    import jax
    import jax.numpy as jnp

    from conftest import SMALL_MODEL
    from distar_tpu.actor.inference import BatchedInference
    from distar_tpu.lib import features as F
    from distar_tpu.model import Model, default_model_config
    from distar_tpu.serve import BatchedInferenceEngine
    from distar_tpu.utils import deep_merge_dicts

    cfg = deep_merge_dicts(default_model_config(), SMALL_MODEL)
    model = Model(cfg)
    obs = F.fake_step_data(train=False, rng=np.random.default_rng(0))
    batched = jax.tree.map(jnp.asarray, F.batch_tree([obs] * 2))
    H = cfg.encoder.core_lstm.hidden_size
    z = jnp.zeros((2, H))
    hidden = tuple((z, z) for _ in range(cfg.encoder.core_lstm.num_layers))
    params = model.init(
        jax.random.PRNGKey(0),
        batched["spatial_info"], batched["entity_info"], batched["scalar_info"],
        batched["entity_num"], hidden, jax.random.PRNGKey(1),
        method=model.sample_action,
    )
    engine = BatchedInferenceEngine(BatchedInference(model, params, num_slots=2))
    gw = InferenceGateway(engine, max_delay_s=0.01).start()
    gw.load_version("v1", params=params, activate=True)
    out = gw.act("real-a", obs, timeout_s=120.0)  # first flush compiles
    assert out["model_version"] == "v1"
    assert out["action_info"]["action_type"].shape == ()
    # hot swap: perturbed same-shaped params; warmup runs the compiled
    # forward off-path (template known by now), swap serves v2
    p2 = jax.tree.map(lambda x: x * 1.01 if hasattr(x, "dtype") else x, params)
    gw.load_version("v2", params=p2, activate=True)
    out2 = gw.act("real-a", obs, timeout_s=120.0)
    assert out2["model_version"] == "v2"
    assert out2["action_info"]["delay"].shape == ()
    gw.drain_and_stop()


# ------------------------------------------------------------------ soak
@pytest.mark.slow
def test_loadgen_soak_closed_loop_with_swap(tmp_path):
    import sys

    sys.path.insert(0, str(__import__("pathlib").Path(__file__).resolve().parents[1]))
    from tools.loadgen import run_loadgen

    artifact = tmp_path / "loadgen.jsonl"
    summary = run_loadgen(
        mode="closed", clients=8, duration_s=3.0, slots=8,
        mock_delay_s=0.002, max_delay_s=0.005, swap_at=0.5,
        artifact=str(artifact),
    )
    assert summary["errors"] == 0
    assert summary["ok"] > 100
    assert summary["mean_batch_occupancy"] > 1.0
    assert summary["latency_p99_s"] > 0
    lines = [l for l in artifact.read_text().splitlines() if l.strip()]
    import json as _json

    parsed = [_json.loads(l) for l in lines]
    assert parsed[-1]["metric"] == "serve_throughput"  # bench.py tail convention
