"""Anakin fused rollout loop (envs/jaxenv/anakin.py): batch contract parity
with the learner's collate layout, device purity of the fused program, the
window metrics, and a tier-1 SMALL_MODEL training smoke on a vmap'd
scenario batch.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import SMALL_MODEL  # shared tiny model config

from distar_tpu.envs.jaxenv import (
    AnakinDataLoader,
    AnakinRunner,
    EnvConfig,
    ScenarioConfig,
)
from distar_tpu.learner.data import fake_rl_batch
from distar_tpu.obs import get_registry

TINY_B, TINY_T = 2, 3
TINY_ENV = EnvConfig(units_per_squad=2)
TINY_SCN = ScenarioConfig(units_per_squad=2, min_units=1, max_units=2,
                          episode_len=8, spawn_margin=30.0, spawn_spread=6.0)


@pytest.fixture(scope="module")
def learner(tmp_path_factory):
    from distar_tpu.learner import RLLearner

    tmp = tmp_path_factory.mktemp("anakin_rl")
    cfg = {
        "common": {"experiment_name": "anakin_t", "save_path": str(tmp)},
        "learner": {
            "batch_size": TINY_B,
            "unroll_len": TINY_T,
            "save_freq": 100000,
            "log_freq": 1,
        },
        "model": SMALL_MODEL,
    }
    return RLLearner(cfg)


@pytest.fixture(scope="module")
def runner(learner):
    return AnakinRunner(learner.model, batch_size=TINY_B, unroll_len=TINY_T,
                        env_cfg=TINY_ENV, scenario_cfg=TINY_SCN, seed=0)


@pytest.fixture(scope="module")
def loader(learner, runner):
    return AnakinDataLoader(
        runner, params_provider=lambda: learner._state["params"])


@pytest.fixture(scope="module")
def batch(loader):
    return next(loader)


def _shapes(tree):
    return jax.tree.map(lambda x: tuple(np.shape(x)), tree)


def test_batch_layout_matches_collate_contract(batch):
    """Leaf-by-leaf structural parity with fake_rl_batch — the same layout
    collate_trajectories hands the learner, so RLLearner trains on fused
    batches with zero adapter code."""
    lstm = SMALL_MODEL["encoder"]["core_lstm"]
    fake = fake_rl_batch(TINY_B, TINY_T, hidden_size=lstm["hidden_size"],
                         hidden_layers=lstm["num_layers"])
    fake_shapes = _shapes(fake)
    got_shapes = _shapes(batch)
    assert jax.tree.structure(got_shapes) == jax.tree.structure(fake_shapes)
    flat_got = jax.tree_util.tree_flatten_with_path(got_shapes)[0]
    flat_fake = jax.tree.leaves(fake_shapes)
    bad = [(jax.tree_util.keystr(p), g, f)
           for (p, g), f in zip(flat_got, flat_fake) if g != f]
    assert not bad, f"shape mismatches vs collate contract: {bad[:8]}"
    # every leaf already lives on device — the learner's shard_batch
    # (jnp.asarray) must not trigger a host round-trip
    assert all(isinstance(x, jax.Array) for x in jax.tree.leaves(batch))
    # time-major windows: done/step are [T, B], obs leaves [T+1, B, ...]
    assert batch["done"].shape == (TINY_T, TINY_B)
    assert batch["entity_num"].shape == (TINY_T + 1, TINY_B)


def test_fused_rollout_is_device_pure(runner, loader):
    """Acceptance witness: the jitted scan contains no callback / infeed /
    outfeed / host primitives anywhere in its jaxpr (recursively), and a
    transfer guard sees no host transfer during a whole fused window."""
    report = runner.purity_report(loader._params(), runner.init_carry())
    assert report["pure"] is True, report
    assert report["offending"] == []
    # steady state: carry built and first window compiled outside the guard
    # (compile-time constant uploads are one-off), then a whole fused window
    # must execute with the guard up — no per-step host traffic
    params = loader._params()
    carry, _ = runner.rollout(params, runner.init_carry())
    with jax.transfer_guard("disallow"):
        carry, out = runner.rollout(params, carry)
    assert out["done"].shape == (TINY_T, TINY_B)


def test_window_metrics_and_progression(loader, batch):
    snap = get_registry().snapshot()
    assert snap["distar_rollout_plane_backend{backend=anakin}"] == 1.0
    assert snap["distar_anakin_batches_total"] >= 1.0
    assert snap["distar_anakin_env_steps_per_s"] > 0.0
    assert snap["distar_anakin_window_seconds_count"] >= 1.0
    # the next window continues the same lanes: env step counters advance
    batch2 = next(loader)
    assert float(batch2["step"].min()) > float(batch["step"].min()) or (
        float(batch2["done"].sum()) > 0.0)


def test_small_model_trains_on_fused_batches(learner, loader):
    """Satellite 3 tier-1 smoke: SMALL_MODEL runs a real optimizer step on a
    vmap'd-scenario Anakin batch (self-teacher => KL leg is exactly 0)."""
    learner.set_dataloader(iter(loader))
    learner.run(max_iterations=1)
    assert learner.last_iter.val >= 1
    total = learner.variable_record.get("total_loss").avg
    assert np.isfinite(total)
