"""Anakin fused rollout loop (envs/jaxenv/anakin.py): batch contract parity
with the learner's collate layout, device purity of the fused program, the
window metrics, and a tier-1 SMALL_MODEL training smoke on a vmap'd
scenario batch.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import SMALL_MODEL  # shared tiny model config

from distar_tpu.envs.jaxenv import (
    AnakinDataLoader,
    AnakinRunner,
    EnvConfig,
    ScenarioConfig,
)
from distar_tpu.learner.data import fake_rl_batch
from distar_tpu.obs import get_registry

TINY_B, TINY_T = 2, 3
TINY_ENV = EnvConfig(units_per_squad=2)
TINY_SCN = ScenarioConfig(units_per_squad=2, min_units=1, max_units=2,
                          episode_len=8, spawn_margin=30.0, spawn_spread=6.0)


@pytest.fixture(scope="module")
def learner(tmp_path_factory):
    from distar_tpu.learner import RLLearner

    tmp = tmp_path_factory.mktemp("anakin_rl")
    cfg = {
        "common": {"experiment_name": "anakin_t", "save_path": str(tmp)},
        "learner": {
            "batch_size": TINY_B,
            "unroll_len": TINY_T,
            "save_freq": 100000,
            "log_freq": 1,
        },
        "model": SMALL_MODEL,
    }
    return RLLearner(cfg)


@pytest.fixture(scope="module")
def runner(learner):
    return AnakinRunner(learner.model, batch_size=TINY_B, unroll_len=TINY_T,
                        env_cfg=TINY_ENV, scenario_cfg=TINY_SCN, seed=0)


@pytest.fixture(scope="module")
def loader(learner, runner):
    return AnakinDataLoader(
        runner, params_provider=lambda: learner._state["params"])


@pytest.fixture(scope="module")
def batch(loader):
    return next(loader)


def _shapes(tree):
    return jax.tree.map(lambda x: tuple(np.shape(x)), tree)


def test_batch_layout_matches_collate_contract(batch):
    """Leaf-by-leaf structural parity with fake_rl_batch — the same layout
    collate_trajectories hands the learner, so RLLearner trains on fused
    batches with zero adapter code."""
    lstm = SMALL_MODEL["encoder"]["core_lstm"]
    fake = fake_rl_batch(TINY_B, TINY_T, hidden_size=lstm["hidden_size"],
                         hidden_layers=lstm["num_layers"])
    fake_shapes = _shapes(fake)
    got_shapes = _shapes(batch)
    assert jax.tree.structure(got_shapes) == jax.tree.structure(fake_shapes)
    flat_got = jax.tree_util.tree_flatten_with_path(got_shapes)[0]
    flat_fake = jax.tree.leaves(fake_shapes)
    bad = [(jax.tree_util.keystr(p), g, f)
           for (p, g), f in zip(flat_got, flat_fake) if g != f]
    assert not bad, f"shape mismatches vs collate contract: {bad[:8]}"
    # every leaf already lives on device — the learner's shard_batch
    # (jnp.asarray) must not trigger a host round-trip
    assert all(isinstance(x, jax.Array) for x in jax.tree.leaves(batch))
    # time-major windows: done/step are [T, B], obs leaves [T+1, B, ...]
    assert batch["done"].shape == (TINY_T, TINY_B)
    assert batch["entity_num"].shape == (TINY_T + 1, TINY_B)


def test_fused_rollout_is_device_pure(runner, loader):
    """Acceptance witness: the jitted scan contains no callback / infeed /
    outfeed / host primitives anywhere in its jaxpr (recursively), and a
    transfer guard sees no host transfer during a whole fused window."""
    report = runner.purity_report(loader._params(), runner.init_carry())
    assert report["pure"] is True, report
    assert report["offending"] == []
    # steady state: carry built and first window compiled outside the guard
    # (compile-time constant uploads are one-off), then a whole fused window
    # must execute with the guard up — no per-step host traffic
    params = loader._params()
    carry, _ = runner.rollout(params, runner.init_carry())
    with jax.transfer_guard("disallow"):
        carry, out = runner.rollout(params, carry)
    assert out["done"].shape == (TINY_T, TINY_B)


def test_window_metrics_and_progression(loader, batch):
    snap = get_registry().snapshot()
    assert snap["distar_rollout_plane_backend{backend=anakin}"] == 1.0
    assert snap["distar_anakin_batches_total"] >= 1.0
    assert snap["distar_anakin_env_steps_per_s"] > 0.0
    assert snap["distar_anakin_window_seconds_count"] >= 1.0
    # the next window continues the same lanes: env step counters advance
    batch2 = next(loader)
    assert float(batch2["step"].min()) > float(batch["step"].min()) or (
        float(batch2["done"].sum()) > 0.0)


def test_small_model_trains_on_fused_batches(learner, loader):
    """Satellite 3 tier-1 smoke: SMALL_MODEL runs a real optimizer step on a
    vmap'd-scenario Anakin batch (self-teacher => KL leg is exactly 0)."""
    learner.set_dataloader(iter(loader))
    learner.run(max_iterations=1)
    assert learner.last_iter.val >= 1
    total = learner.variable_record.get("total_loss").avg
    assert np.isfinite(total)


# ---------------------------------------------------------------- away seat


@pytest.fixture(scope="module")
def opp_runner(learner):
    return AnakinRunner(learner.model, batch_size=TINY_B, unroll_len=TINY_T,
                        env_cfg=TINY_ENV, scenario_cfg=TINY_SCN, seed=0,
                        opponent_seat=True)


@pytest.fixture(scope="module")
def opp_loader(learner, opp_runner):
    return AnakinDataLoader(
        opp_runner, params_provider=lambda: learner._state["params"])


def test_away_seat_batch_layout_matches_single_policy(batch, opp_loader):
    """A league exploiter trains against a frozen opponent with zero learner
    changes: the opponent-seat batch is structurally identical to the
    single-policy batch (the match_result leaf is stripped host-side)."""
    opp_batch = next(opp_loader)
    assert "match_result" not in opp_batch
    got = _shapes(opp_batch)
    ref = _shapes(batch)
    assert jax.tree.structure(got) == jax.tree.structure(ref)
    assert jax.tree.leaves(got) == jax.tree.leaves(ref)
    assert all(isinstance(x, jax.Array) for x in jax.tree.leaves(opp_batch))


def test_away_seat_match_results_drain(opp_loader):
    """Finished episodes surface exactly once through drain_results() with a
    home/away/draw verdict — the feed LeagueService.report consumes."""
    for _ in range(6):  # 6 windows x 3 steps > episode_len=8: episodes finish
        next(opp_loader)
    results = opp_loader.drain_results()
    assert results, "no episodes finished across 6 windows"
    assert {r["winner"] for r in results} <= {"home", "away", "draw"}
    assert all(r["steps"] >= 1 for r in results)
    # drained means drained — the buffer does not replay old outcomes
    assert opp_loader.drain_results() == []


def test_away_seat_rollout_is_device_pure(opp_runner, opp_loader):
    """The two-policy fused program stays callback/infeed/outfeed-free: the
    frozen opponent runs in-scan, not via host ping-pong."""
    report = opp_runner.purity_report(
        opp_loader._params(), opp_runner.init_carry(),
        opp_loader._opponent_params())
    assert report["pure"] is True, report
    assert report["offending"] == []


def test_away_seat_requires_opponent_params(opp_runner, runner, opp_loader):
    """The seat is explicit: an opponent-seat runner demands opponent params
    and a single-policy runner rejects them — no silent self-play fallback."""
    params = opp_loader._params()
    with pytest.raises(AssertionError):
        opp_runner.rollout(params, opp_runner.init_carry())
    with pytest.raises(AssertionError):
        runner.rollout(params, runner.init_carry(),
                       opponent_params=opp_loader._opponent_params())


def test_away_seat_trains_exploiter(learner, opp_loader):
    """End-to-end: the learner takes a real optimizer step on an away-seat
    batch — the exploiter training loop a league learner runs."""
    learner.set_dataloader(iter(opp_loader))
    learner.run(max_iterations=1)
    total = learner.variable_record.get("total_loss").avg
    assert np.isfinite(total)


def test_failed_window_drops_poisoned_carry():
    """The fused call donates the carry; if a window raises, the loader must
    drop its carry reference so a supervised retry re-initialises instead of
    re-passing deleted buffers (the league learner's restart path)."""
    from types import SimpleNamespace

    calls = {"init": 0}

    def init_carry(key=None):
        calls["init"] += 1
        return ("carry", calls["init"])

    def rollout(params, carry, opponent_params=None):
        raise RuntimeError("window failed mid-donation")

    stub = SimpleNamespace(opponent_seat=False, init_carry=init_carry,
                           rollout=rollout, B=1, T=1, _seed=0)
    dl = AnakinDataLoader(stub, params_provider=lambda: {"w": 1})
    with pytest.raises(RuntimeError):
        next(dl)
    assert dl._carry is None
    assert calls["init"] == 1
    with pytest.raises(RuntimeError):
        next(dl)
    assert calls["init"] == 2
