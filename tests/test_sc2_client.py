"""SC2 client layer tests: websocket protocol + RemoteController status
machine against the in-process fake SC2 server (real websocket handshake,
real proto wire format), multiplayer create/join port plumbing through the
launcher, process launch/teardown, version routing, map registry.

Strategy per VERDICT round-1 #2: where the retail binary is absent, the
client stack runs byte-identically against a recorded-protocol fake
(fake_sc2.FakeSC2Server) — only the simulation behind /sc2api differs.
"""
import os
import stat
import sys

import numpy as np
import pytest

from distar_tpu.envs.sc2 import maps as map_registry
from distar_tpu.envs.sc2 import run_configs
from distar_tpu.envs.sc2.fake_sc2 import FakeGameCore, FakeSC2Server
from distar_tpu.envs.sc2.launcher import (
    Bot,
    Player,
    RealSC2Env,
    SC2GameLauncher,
    crop_and_deduplicate_names,
)
from distar_tpu.envs.sc2.proto import Status, sc_pb
from distar_tpu.envs.sc2.protocol import ProtocolError
from distar_tpu.envs.sc2.remote_controller import RemoteController
from distar_tpu.lib import actions as ACT
from distar_tpu.lib import features as F


@pytest.fixture
def server():
    s = FakeSC2Server(game=FakeGameCore(end_at=300))
    yield s
    s.stop()


def connect(server):
    return RemoteController("127.0.0.1", server.port, timeout_seconds=5)


# ------------------------------------------------------------------ protocol
def test_controller_ping_and_status(server):
    c = connect(server)
    res = c.ping()
    assert res.base_build == server.game.base_build
    assert c.status == Status.launched
    c.quit()
    assert c.status == Status.quit


def test_valid_status_gating(server):
    c = connect(server)
    with pytest.raises(ProtocolError):
        c.observe()  # only legal in_game/in_replay/ended
    with pytest.raises(ProtocolError):
        c.step()
    c.quit()


def test_create_join_observe_step_act(server):
    c = connect(server)
    create = sc_pb.RequestCreateGame()
    create.local_map.map_path = "FakeMap.SC2Map"
    create.player_setup.add(type=sc_pb.Participant)
    create.player_setup.add(type=sc_pb.Computer, race=2, difficulty=7)
    c.create_game(create)
    assert c.status == Status.init_game

    join = sc_pb.RequestJoinGame(options=sc_pb.InterfaceOptions(raw=True, score=True))
    join.race = 2
    res = c.join_game(join)
    assert res.player_id == 1
    assert c.status == Status.in_game

    gi = c.game_info()
    assert gi.start_raw.map_size.x > 0

    obs = c.observe(target_game_loop=0)
    assert obs.observation.game_loop == 0
    assert len(obs.observation.raw_data.units) > 0

    c.step(10)
    obs = c.observe(target_game_loop=10)
    assert obs.observation.game_loop == 10

    # batched acts with the ProtoFeatures raw-command dict contract
    result = c.acts([
        {"ability_id": 3674, "queue_command": False, "unit_tags": [10000, 10001]}
    ])
    assert result == [1]
    assert server.game.action_log

    # run to the scripted end: player_result appears, status -> ended
    c.step(400)
    obs = c.observe(target_game_loop=400)
    assert list(obs.player_result)
    assert c.status == Status.ended
    assert c.status_ended

    c.restart()
    assert c.status == Status.in_game
    c.quit()


def test_observe_regurgitates_stub_observation(server):
    """The 2^32-1 stub obs is replaced by the previous obs + new results
    (reference remote_controller.py:247-264)."""
    c = connect(server)
    create = sc_pb.RequestCreateGame()
    create.player_setup.add(type=sc_pb.Participant)
    c.create_game(create)
    c.join_game(sc_pb.RequestJoinGame(options=sc_pb.InterfaceOptions(raw=True)))
    first = c.observe()
    assert first.observation.game_loop == 0
    # craft a stub observation response through the controller's own path
    stub = sc_pb.ResponseObservation()
    stub.observation.game_loop = 2 ** 32 - 1
    pr = stub.player_result.add()
    pr.player_id = 1
    pr.result = sc_pb.Victory

    orig_send = c._client.send

    def fake_send(**kwargs):
        if "observation" in kwargs:
            return stub
        return orig_send(**kwargs)

    c._client.send = fake_send
    obs = c.observe()
    assert obs.observation.game_loop == first.observation.game_loop
    assert obs.player_result[0].result == sc_pb.Victory
    c._client.send = orig_send
    c.quit()


# ------------------------------------------------------- multiplayer launcher
def two_player_env(server, **env_kwargs):
    launcher = SC2GameLauncher(
        map_name="KairosJunction",
        players=[Player("zerg"), Player("zerg")],
        controller_factory=lambda i: connect(server),
        relaunch_every_episodes=0,
    )
    return RealSC2Env(launcher, **env_kwargs)


def act_dict(action_type: int, delay: int = 4, n_tags: int = 16):
    sel = np.zeros(F.MAX_SELECTED_UNITS_NUM, np.int64)
    sel[0] = 0
    sel[1] = n_tags  # end token
    return {
        "action_type": np.asarray([action_type]),
        "delay": np.asarray([delay]),
        "queued": np.asarray([0]),
        "selected_units": sel,
        "target_unit": np.asarray([0]),
        "target_location": np.asarray([500]),
        "selected_units_num": np.asarray([2]),
    }


def test_multiplayer_create_join_and_episode(server):
    env = two_player_env(server)
    obs = env.reset()
    assert set(obs.keys()) == {0, 1}
    for i in (0, 1):
        assert obs[i]["entity_num"] > 0
        assert obs[i]["spatial_info"]["height_map"].shape == tuple(F.SPATIAL_SIZE)
        assert "value_feature" in obs[i]  # both_obs default

    # an action with selected_units, stepping until the scripted end
    at = next(
        i for i, a in enumerate(ACT.ACTIONS)
        if a["selected_units"] and not a["target_unit"] and not a["target_location"]
    )
    done = False
    for _ in range(100):
        actions = {i: act_dict(at) for i in obs}
        obs, rewards, done, info = env.step(actions)
        if done:
            break
    assert done
    # fake scripts player 1 as the winner; which env index IS player 1
    # depends on join order (parallel joins race, as with real SC2), so map
    # the outcome through the reported player id
    assert sorted(rewards.values()) == [-1.0, 1.0]
    win_idx = max(rewards, key=rewards.get)
    pid = env._raw_obs[win_idx].observation.player_common.player_id
    assert pid == 1
    # both fake connections saw create/join from the plumbing
    assert server.game.started
    env.close()


def test_launcher_bot_game_single_agent(server):
    launcher = SC2GameLauncher(
        map_name="KairosJunction",
        players=[Player("zerg"), Bot("zerg", 7)],
        controller_factory=lambda i: connect(server),
    )
    env = RealSC2Env(launcher)
    assert launcher.num_agents == 1
    obs = env.reset()
    assert set(obs.keys()) == {0}
    env.close()


def test_crop_and_deduplicate_names():
    names = crop_and_deduplicate_names(["a" * 40, "a" * 40, "short"])
    assert len(set(names)) == 3
    assert all(len(n) <= 32 for n in names)


# ------------------------------------------------------------ process launch
def test_sc_process_launch_and_connect(tmp_path):
    """StarcraftProcess launches the fake binary, retries the websocket until
    it serves, pings, and tears down (reference sc_process.py:49-234)."""
    script = tmp_path / "SC2_fake"
    script.write_text(
        "#!/bin/sh\n"
        f'exec {sys.executable} -m distar_tpu.envs.sc2.fake_sc2 "$@"\n'
    )
    script.chmod(script.stat().st_mode | stat.S_IEXEC)

    class StubRunConfig:
        data_dir = str(tmp_path)
        tmp_dir = str(tmp_path)
        cwd = None
        env = {**os.environ, "PYTHONPATH": os.path.dirname(os.path.dirname(__file__))}

    from distar_tpu.envs.sc2.sc_process import StarcraftProcess

    proc = StarcraftProcess(
        StubRunConfig(), exec_path=str(script), version=None, timeout_seconds=30
    )
    try:
        assert proc.running
        assert proc.controller.ping().game_version
    finally:
        proc.close()
    assert not proc.running


# ------------------------------------------------------------ version routing
def test_version_routing():
    v = run_configs.VERSIONS["4.10.0"]
    assert v.build_version == 75689
    # decoder pins (reference replay_decoder.py:37-41)
    assert run_configs.BUILD2VERSION[81009] == "5.0.0"
    assert run_configs.BUILD2VERSION[80188] == "4.12.1"
    assert run_configs.version_for_build(75689).game_version == "4.10.0"
    # unknown build falls back to closest at-or-below
    assert run_configs.version_for_build(75690).game_version == "4.10.0"

    rc = run_configs.RunConfig(
        replay_dir="/tmp", data_dir="/tmp", tmp_dir=None, version="4.10"
    )
    assert rc.version.game_version == "4.10.0"
    with pytest.raises(ValueError):
        run_configs.RunConfig(
            replay_dir="/tmp", data_dir="/tmp", tmp_dir=None, version="9.9.9"
        )


# -------------------------------------------------------------------- maps
def test_map_registry():
    assert map_registry.get_map_size("KairosJunction") == (120, 140)
    assert map_registry.get_map_size("KairosJunction", cropped=False) == (152, 168)
    # localized / battle.net spellings route to the canonical name
    assert map_registry.LOCALIZED_BNET_NAME_TO_NAME_LUT["Kairos Junction LE"] == "KairosJunction"
    m = map_registry.get("Kairos Junction LE")
    assert m.name == "KairosJunction"
    assert m.filename.endswith("KairosJunctionLE.SC2Map")
    with pytest.raises(KeyError):
        map_registry.get("NoSuchMap")


# ------------------------------------------------------------------ replays
def make_fake_replay(base_build=75689, loops=200):
    return {
        "base_build": base_build,
        "game_version": "4.10.0",
        "data_version": "FAKE",
        "map_name": "KairosJunction",
        "game_duration_loops": loops,
        "players": [
            {"player_id": 1, "race": 2, "mmr": 4800, "apm": 160, "result": 1},
            {"player_id": 2, "race": 2, "mmr": 4600, "apm": 140, "result": 2},
        ],
        "actions": [
            (10, 3674, [10000], None),
            (60, 1183, [10001], (20.0, 30.0)),
            (120, 3674, [10002], 20000),
        ],
    }


def test_replay_info_and_action_stream(server):
    import pickle

    rep = make_fake_replay()
    server.game.replay_library["test.SC2Replay"] = rep

    c = connect(server)
    info = c.replay_info(replay_path="test.SC2Replay")
    assert info.base_build == 75689
    assert info.player_info[0].player_mmr == 4800
    assert info.game_duration_loops == 200

    req = sc_pb.RequestStartReplay(replay_path="test.SC2Replay", observed_player_id=1)
    req.options.raw = True
    c.start_replay(req)
    assert c.status == Status.in_replay

    # harvest the action stream at 50-loop strides (the decoder's pass 1)
    harvested = []
    while not c.status_ended:
        c.step(50)
        obs = c.observe()
        harvested.extend(obs.actions)
        if obs.player_result:
            break
    assert [a.action_raw.unit_command.ability_id for a in harvested] == [3674, 1183, 3674]
    assert harvested[1].action_raw.unit_command.target_world_space_pos.x == 20.0
    assert harvested[2].action_raw.unit_command.target_unit_tag == 20000

    # replay_data path (bytes) works too
    c2 = connect(server)
    info2 = c2.replay_info(replay_data=pickle.dumps(rep))
    assert info2.base_build == 75689
    c2.quit()
    c.quit()


def test_sc2_tools_cli_over_fake_server(server, capsys):
    """The developer-tool subcommands (replay-info / map-list /
    benchmark-observe / benchmark-replay) drive the production client stack
    against the fake server (reference pysc2/bin tool scripts)."""
    import sys

    from distar_tpu.bin.sc2_tools import main as tools_main
    from tests.test_replay_decoder import make_replay

    server.game.replay_library["bench.SC2Replay"] = make_replay()
    ep = f"127.0.0.1:{server.port}"

    argv = sys.argv
    try:
        sys.argv = ["sc2_tools", "replay-info", "bench.SC2Replay", "--endpoint", ep]
        tools_main()
        out = capsys.readouterr().out
        assert "KairosJunction" in out and "build 75689" in out

        sys.argv = ["sc2_tools", "map-list"]
        tools_main()
        out = capsys.readouterr().out
        assert "KairosJunction" in out

        sys.argv = ["sc2_tools", "benchmark-observe", "--steps", "5",
                    "--endpoint", ep]
        tools_main()
        out = capsys.readouterr().out
        assert "obs/s" in out

        sys.argv = ["sc2_tools", "benchmark-replay", "bench.SC2Replay",
                    "--endpoint", ep]
        tools_main()
        out = capsys.readouterr().out
        assert "steps/s" in out
    finally:
        sys.argv = argv


def test_bundled_maps_manifest_and_fallback(tmp_path):
    """The shipped Ladder2019Season2 bundle: sha256 manifest verifies, the
    training maps are present, install_maps defaults to the bundle, and
    RunConfig.map_data falls back to it when the install has no Maps dir
    (offline-host story; reference bundles distar/envs/maps/...)."""
    assert map_registry.verify_bundled_maps() == []
    bundled = set(os.listdir(map_registry.bundled_maps_dir()))
    for stem in ("KairosJunctionLE", "KingsCoveLE", "NewRepugnancyLE", "CyberForestLE"):
        assert f"{stem}.SC2Map" in bundled
    # install defaults to the bundle
    n = map_registry.install_maps(sc2_dir=str(tmp_path))
    assert n == len([f for f in bundled if f.endswith(".SC2Map")])
    assert (tmp_path / "Maps" / "Ladder2019Season2" / "KairosJunctionLE.SC2Map").exists()
    assert map_registry.install_maps(sc2_dir=str(tmp_path)) == 0  # idempotent
    # map_data falls back to the bundle for a bare install dir, including
    # punctuation-normalized names (TurboCruise84 -> TurboCruise'84LE)
    rc = run_configs.RunConfig(
        replay_dir="/tmp", data_dir=str(tmp_path / "no_such_install"),
        tmp_dir=None, version="4.10",
    )
    data = rc.map_data("Ladder2019Season2/KairosJunctionLE.SC2Map")
    assert data[:4] == b"MPQ\x1a"
    assert rc.map_data("Ladder2019Season2/TurboCruise84LE.SC2Map")[:4] == b"MPQ\x1a"
    with pytest.raises(ValueError):
        rc.map_data("Ladder2019Season2/NoSuchLE.SC2Map")



def test_headless_observer_renders_live_game(tmp_path, server):
    """bin/observe (role of the reference renderer_human for headless
    debugging): a SECOND connection attaches to a live game (real SC2 status
    is process-global — fake now mirrors that) and renders ASCII + PPM."""
    import distar_tpu.bin.observe as OB

    c = connect(server)
    create = sc_pb.RequestCreateGame()
    create.local_map.map_path = "FakeMap.SC2Map"
    create.player_setup.add(type=sc_pb.Participant)
    create.player_setup.add(type=sc_pb.Computer, race=2, difficulty=7)
    c.create_game(create)
    c.join_game(sc_pb.RequestJoinGame(options=sc_pb.InterfaceOptions(raw=True, score=True), race=2))

    d = tmp_path / "frames"
    OB.main(["--endpoint", f"127.0.0.1:{server.port}", "--count", "2",
             "--interval", "0.01", "--frames", str(d)])
    frames = sorted(os.listdir(d))
    assert len(frames) == 2
    head = (d / frames[0]).read_bytes()[:20]
    assert head.startswith(b"P6 ")

    obs = c.observe()
    gi = c.game_info()
    size = (gi.start_raw.map_size.x, gi.start_raw.map_size.y)
    art = OB.render_ascii(OB.obs_to_grid(obs.observation.raw_data, size, 1))
    assert "o" in art and "x" in art  # both sides visible
