"""Elastic fleet control plane (ISSUE 12): autoscaler, graceful drain,
live membership, TSDB series eviction, and the core-pinning honesty gate.

Covers the `distar_tpu/fleet/` contracts plus the drain surfaces grown onto
serve/replay (docs/serving.md + docs/data_plane.md elasticity sections):
deregister-BEFORE-shed ordering against a live coordinator, the HTTP
503-with-typed-body drain mirror, client-side drain handoff with exact
migration accounting, live membership refresh on both fleets, the replay
draining overlay, ScalePolicy hysteresis/cooldown, and perf_gate's refusal
of forged ``scaling_valid`` claims. In-process servers keep tier-1 fast;
the full subprocess drill is ``tools/chaos.py elastic-drill`` (slow test).
"""
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from distar_tpu.comm.coordinator import Coordinator, CoordinatorServer
from distar_tpu.comm.discovery import (
    discover_endpoints,
    start_refresh,
    unregister_endpoint,
)
from distar_tpu.fleet import (
    Autoscaler,
    ScalePolicy,
    SIG_GW_ACTIVE,
    SIG_GW_SLOTS,
    pinning,
    set_autoscaler,
)
from distar_tpu.obs import (
    TelemetryIngest,
    TelemetryShipper,
    TimeSeriesStore,
    get_registry,
)
from distar_tpu.replay import (
    ReplayServer,
    ReplayStore,
    ShardMap,
    ShardedInsertClient,
    StoreDrainingError,
    TableConfig,
)
from distar_tpu.serve import (
    DrainingError,
    GatewayMux,
    InferenceGateway,
    MockModelEngine,
    ServeClient,
    ServeHTTPServer,
    ServeTCPServer,
)
from distar_tpu.serve.fleet import FleetClient, GatewayMap, register_gateway

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import perf_gate  # noqa: E402


def _obs(i: int = 0) -> dict:
    return {"x": np.full((2, 2), float(i), dtype=np.float32)}


def _gateway(slots: int = 8, delay_s: float = 0.0) -> InferenceGateway:
    params = {"version": "v1", "bias": 0.0}
    gw = InferenceGateway(MockModelEngine(slots, params=params, delay_s=delay_s),
                          max_batch=slots, max_delay_s=0.002)
    gw.load_version("v1", params=params, activate=True)
    return gw.start()


def _snap(name: str) -> float:
    return get_registry().snapshot().get(name, 0.0)


# ------------------------------------------------------ coordinator departures
def test_coordinator_unregister_purges_now_and_notifies():
    co = Coordinator()
    seen = []
    co.add_evict_callback(seen.append)
    co.register("t", "10.0.0.1", 9, lease_s=60.0)
    assert co.peers("t")
    assert co.unregister("10.0.0.1", 9) == 1
    assert co.peers("t") == []
    assert seen == ["10.0.0.1:9"]


def test_coordinator_lease_expiry_notifies_evict_callbacks():
    co = Coordinator()
    seen = []
    co.add_evict_callback(seen.append)
    co.register("t", "10.0.0.2", 7, lease_s=0.05)
    time.sleep(0.1)
    co._last_sweep = 0.0  # allow an immediate sweep
    assert co.peers("t") == []
    assert seen == ["10.0.0.2:7"]


# ------------------------------------------------------------ TSDB eviction
def test_tsdb_evict_source_frees_series_cap():
    store = TimeSeriesStore(points_per_series=8, max_series=3)
    for i in range(3):
        assert store.record(f"m{i}", 1.0, source="old")
    assert not store.record("m_new", 1.0, source="new")  # cap refuses
    before = _snap("distar_obs_series_evicted_total")
    assert store.evict_source("old") == 3
    assert _snap("distar_obs_series_evicted_total") - before == 3
    assert store.record("m_new", 1.0, source="new")  # room again
    st = store.stats()
    assert st["evicted_series"] == 3 and st["series"] == 1
    assert "old" not in store.sources()


def test_ingest_evicts_by_endpoint_and_shipper_stamps_it():
    store = TimeSeriesStore()
    ingest = TelemetryIngest(store)
    shipper = TelemetryShipper("gw-7", ingest=ingest, endpoint="10.0.0.3:88")
    get_registry().counter("distar_tsdb_samples_total", "x").inc()  # something to ship
    assert shipper.ship_once() > 0
    assert "gw-7" in store.sources()
    assert ingest.evict_endpoint("10.0.0.3:88") > 0
    assert "gw-7" not in store.sources()
    assert ingest.evict_endpoint("10.0.0.3:88") == 0  # idempotent


# ------------------------------------------------------------- serve drain
def test_gateway_drain_deregisters_before_shedding_live_coordinator():
    """Satellite regression: a draining gateway must leave discovery FIRST
    (it used to keep heartbeating, so routers kept pinning new sessions to
    it until the lease died)."""
    co = CoordinatorServer(Coordinator(default_lease_s=30.0))
    co.start()
    gw = _gateway(slots=4, delay_s=0.2)
    tcp = ServeTCPServer(gw, port=0).start()
    try:
        beat = register_gateway((co.host, co.port), tcp.host, tcp.port,
                                meta={"slots": 4}, lease_s=30.0)
        order = []

        def dereg():
            order.append(("dereg", gw._draining))
            beat.stop_event.set()
            unregister_endpoint((co.host, co.port), tcp.host, tcp.port)

        gw.deregister = dereg
        assert discover_endpoints((co.host, co.port), "serve_gateway")

        # an in-flight request admitted before the drain must finish
        inflight = {}

        def act():
            inflight["out"] = gw.act("pre", _obs())

        t = threading.Thread(target=act)
        t.start()
        time.sleep(0.05)  # admitted, engine sleeping
        info = gw.begin_drain()
        assert info["draining"]
        # ordering: deregister ran BEFORE the draining flag flipped
        assert order == [("dereg", False)]
        # left discovery immediately, not a lease TTL later
        assert discover_endpoints((co.host, co.port), "serve_gateway") == []
        t.join(5.0)
        assert inflight["out"]["model_version"] == "v1"  # in-flight finished
        with pytest.raises(DrainingError):
            gw.act("post", _obs())
        with pytest.raises(DrainingError):
            gw.reserve_sessions(["post2"])
        assert gw.begin_drain()["draining"]  # idempotent
    finally:
        tcp.stop()
        gw.drain_and_stop(2.0)
        co.stop()


def test_mux_drain_deregisters_once_and_drains_every_player():
    mux = GatewayMux({"MP0": _gateway(2), "MP1": _gateway(2)})
    calls = []
    mux.deregister = lambda: calls.append(1)
    mux.begin_drain()
    mux.begin_drain()
    assert calls == [1]
    assert mux.draining
    with pytest.raises(DrainingError):
        mux.act("s", _obs())
    mux.drain_and_stop(2.0)


def test_http_drain_route_503_with_typed_body():
    """Satellite: the HTTP frontend mirror of the TCP drain contract."""
    gw = _gateway(slots=4, delay_s=0.2)
    http = ServeHTTPServer(gw, port=0).start()

    def post(route, body):
        req = urllib.request.Request(
            f"http://{http.host}:{http.port}/serve/{route}",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.loads(resp.read())

    try:
        # in-flight request admitted pre-drain, finishing post-drain
        inflight = {}

        def act():
            inflight["resp"] = post("act", {"session_id": "pre",
                                            "obs": {"x": [[1.0, 1.0]]}})

        t = threading.Thread(target=act)
        t.start()
        time.sleep(0.05)
        status, body = post("drain", {})
        assert status == 200 and body["code"] == 0 and body["info"]["draining"]
        t.join(5.0)
        assert inflight["resp"][0] == 200 and inflight["resp"][1]["code"] == 0
        # a NEW request while draining: HTTP 503 with the typed wire body
        with pytest.raises(urllib.error.HTTPError) as ei:
            post("act", {"session_id": "post", "obs": {"x": [[1.0, 1.0]]}})
        assert ei.value.code == 503
        wire = json.loads(ei.value.read())
        assert wire["code"] == "draining" and wire["shed"] is True
        # control surfaces stay answerable while draining
        status, body = post("status", {})
        assert status == 200 and body["info"]["draining"] is True
    finally:
        http.stop()
        gw.drain_and_stop(2.0)


def test_tcp_drain_op_and_typed_shed():
    gw = _gateway(slots=4)
    tcp = ServeTCPServer(gw, port=0).start()
    client = ServeClient(tcp.host, tcp.port, timeout_s=5.0)
    try:
        out = client.drain()
        assert out["draining"] is True
        with pytest.raises(DrainingError):
            client.act("s", _obs())
    finally:
        client.close()
        tcp.stop()
        gw.drain_and_stop(2.0)


# --------------------------------------------------- fleet client migration
class _Fleet:
    def __init__(self, n: int, slots: int = 8):
        self.gateways = [_gateway(slots) for _ in range(n)]
        self.servers = [ServeTCPServer(gw, port=0).start() for gw in self.gateways]
        self.addrs = [f"{s.host}:{s.port}" for s in self.servers]

    def close(self):
        for s in self.servers:
            s.stop()
        for gw in self.gateways:
            gw.drain_and_stop(2.0)


def test_fleet_client_drain_handoff_exact_accounting():
    """A draining gateway's resident sessions migrate to survivors with
    zero caller-visible errors: DrainingError never surfaces, the sessions
    are ENDED on the victim (its residency reaches zero), and the
    migration counter moves EXACTLY once per resident session."""
    fleet = _Fleet(2, slots=12)  # the survivor must hold EVERY session
    fc = FleetClient(gateway_map=GatewayMap(fleet.addrs), timeout_s=5.0)
    sids = [f"m-{i}" for i in range(10)]
    try:
        for _ in range(2):  # materialize carries everywhere
            results = fc.act_many([{"session_id": s, "obs": _obs()} for s in sids])
            assert all(isinstance(r, dict) for r in results), results
        victim_idx = max(
            range(2), key=lambda i: len(fc.router.pins_on(fleet.addrs[i])))
        victim = fleet.addrs[victim_idx]
        resident = len(fc.router.pins_on(victim))
        assert resident > 0
        mig0 = _snap("distar_fleet_session_migrations_total")
        hand0 = _snap("distar_fleet_drain_handoff_sessions_total")
        fleet.gateways[victim_idx].begin_drain()
        results = fc.act_many([{"session_id": s, "obs": _obs()} for s in sids])
        assert all(isinstance(r, dict) for r in results), results
        assert _snap("distar_fleet_session_migrations_total") - mig0 == resident
        assert _snap("distar_fleet_drain_handoff_sessions_total") - hand0 == resident
        # the victim's slots were freed by the handoff ends
        assert fleet.gateways[victim_idx].resident_sessions() == 0
        assert len(fc.router.pins_on(victim)) == 0
    finally:
        fc.close()
        fleet.close()


def test_fleet_client_capacity_spillover_fills_the_fleet():
    """Arrival admission is a FLEET property: a fresh session shed for
    capacity at its ring pick spills to the next live gateway; only a
    fleet-wide-full arrival sheds through typed."""
    fleet = _Fleet(2, slots=2)
    fc = FleetClient(gateway_map=GatewayMap(fleet.addrs), timeout_s=5.0)
    try:
        results = fc.act_many(
            [{"session_id": f"c-{i}", "obs": _obs()} for i in range(4)])
        assert all(isinstance(r, dict) for r in results), results
        pins = fc.router.stats()["pins_per_gateway"]
        assert sorted(pins.values()) == [2, 2]  # both gateways full
        res = fc.act_many([{"session_id": "c-full", "obs": _obs()}])
        from distar_tpu.serve.errors import CapacityError
        assert isinstance(res[0], CapacityError)  # fleet full: typed shed
    finally:
        fc.close()
        fleet.close()


def test_fleet_client_live_membership_join_without_restart():
    """The comm.discovery refresh idiom: a gateway joining AFTER the client
    was built becomes routable with no client reconstruction."""
    co = CoordinatorServer(Coordinator(default_lease_s=30.0))
    co.start()
    fleet = _Fleet(2, slots=4)
    beats = []
    host0, port0 = fleet.addrs[0].rsplit(":", 1)
    beats.append(register_gateway((co.host, co.port), host0, int(port0),
                                  meta={"slots": 4}, lease_s=30.0))
    fc = FleetClient(coordinator_addr=(co.host, co.port), timeout_s=5.0,
                     refresh_s=0.2)
    try:
        assert len(fc.router.map) == 1
        host1, port1 = fleet.addrs[1].rsplit(":", 1)
        beats.append(register_gateway((co.host, co.port), host1, int(port1),
                                      meta={"slots": 4}, lease_s=30.0))
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and len(fc.router.map) < 2:
            time.sleep(0.1)
        assert sorted(fc.router.map.addrs) == sorted(fleet.addrs)
    finally:
        for b in beats:
            b.stop_event.set()
        fc.close()
        fleet.close()
        co.stop()


# ------------------------------------------------------------- replay drain
def _fifo_cfg(_name):
    return TableConfig(max_size=64, sampler="fifo", samples_per_insert=None,
                       min_size_to_sample=1)


def test_replay_store_drain_refuses_new_keeps_idem_and_drains_tail():
    store = ReplayStore(table_factory=_fifo_cfg)
    seq = store.insert("t", {"i": 0}, idem="k0", timeout_s=5.0)
    store.insert("t", {"i": 1}, timeout_s=5.0)
    info = store.begin_drain()
    assert info["draining"] and info["resident"] == 2
    with pytest.raises(StoreDrainingError):
        store.insert("t", {"i": 2}, timeout_s=5.0)
    # an idem retry of an ALREADY-acked insert still answers across the edge
    assert store.insert("t", {"i": 0}, idem="k0", timeout_s=5.0) == seq
    # the resident tail keeps draining to samplers
    got = [s.data["i"] for s in store.sample("t", batch_size=2, timeout_s=5.0)]
    assert sorted(got) == [0, 1]
    assert store.resident_items() == 0
    assert store.stats()["draining"] is True


def test_replay_store_drain_releases_spi_pacing():
    """A paced table must NOT park its last samplers forever once inserts
    stop: drain releases the samples-per-insert gate so the tail drains."""
    cfg = TableConfig(max_size=64, sampler="fifo", samples_per_insert=1.0,
                      min_size_to_sample=4, error_buffer=1.0)
    store = ReplayStore(table_factory=lambda n: cfg)
    for i in range(3):  # below min_size: samples would block forever
        store.insert("t", {"i": i}, timeout_s=5.0)
    store.begin_drain()
    got = {s.data["i"] for s in store.sample("t", batch_size=1, timeout_s=2.0)}
    got |= {s.data["i"] for s in store.sample("t", batch_size=2, timeout_s=2.0)}
    assert got == {0, 1, 2}


def test_sharded_insert_reroutes_around_draining_shard():
    """The typed draining answer moves routing to a survivor immediately
    (overlay ring), before any membership refresh happens."""
    stores = [ReplayStore(table_factory=_fifo_cfg, shard_id=f"s{i}")
              for i in range(2)]
    servers = [ReplayServer(s, port=0).start() for s in stores]
    addrs = [f"{s.host}:{s.port}" for s in servers]
    client = ShardedInsertClient(ShardMap(addrs), timeout_s=5.0)
    try:
        keys = [f"k{i}" for i in range(12)]
        owner = {k: client.shard_for("t", k) for k in keys}
        assert len(set(owner.values())) == 2  # both shards owned keys
        victim_idx = 0
        stores[victim_idx].begin_drain()
        before = _snap("distar_replay_drains_observed_total"
                       f"{{shard={addrs[victim_idx]}}}")
        for k in keys:
            client.insert("t", {"k": k}, key=k, timeout_s=5.0)
        # every key landed on the survivor (the draining shard kept none)
        assert stores[victim_idx].resident_items() == 0
        assert stores[1 - victim_idx].resident_items() == len(keys)
        assert _snap("distar_replay_drains_observed_total"
                     f"{{shard={addrs[victim_idx]}}}") - before >= 1
        # the overlay re-routes FUTURE keys too, without another error
        assert client.shard_for("t", "later") == addrs[1 - victim_idx]
    finally:
        client.close()
        for s in servers:
            s.stop()


def test_sharded_client_live_refresh_swaps_map():
    co = CoordinatorServer(Coordinator(default_lease_s=30.0))
    co.start()
    stores = [ReplayStore(table_factory=_fifo_cfg) for _ in range(2)]
    servers = [ReplayServer(s, port=0).start() for s in stores]
    from distar_tpu.replay import register_shard

    beats = [register_shard((co.host, co.port), servers[0].host,
                            servers[0].port, lease_s=30.0)]
    client = ShardedInsertClient(
        ShardMap.discover((co.host, co.port)), timeout_s=5.0)
    client.start_refresh((co.host, co.port), interval_s=0.2)
    try:
        assert len(client.shard_map) == 1
        beats.append(register_shard((co.host, co.port), servers[1].host,
                                    servers[1].port, lease_s=30.0))
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and len(client.shard_map) < 2:
            time.sleep(0.1)
        assert len(client.shard_map) == 2
        # drop one: unregister + refresh shrinks the map back
        unregister_endpoint((co.host, co.port), servers[1].host, servers[1].port)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and len(client.shard_map) > 1:
            time.sleep(0.1)
        assert len(client.shard_map) == 1
    finally:
        for b in beats:
            b.stop_event.set()
        client.close()
        for s in servers:
            s.stop()
        co.stop()


# ------------------------------------------------------------- autoscaler
class _StubFleet:
    def draining_addrs(self):
        return []

    gave_up = False


class _StubSupervisor:
    def __init__(self, fleets):
        self._fleets = dict(fleets)
        self.calls = []

    def fleets(self):
        return sorted(self._fleets)

    def fleet(self, name):
        return _StubFleet()

    def actual(self, name):
        return self._fleets[name]

    def scale_up(self, name, n=1):
        self._fleets[name] += n
        self.calls.append(("up", name, n))
        return [f"new{i}" for i in range(n)]

    def scale_down(self, name, n=1):
        self._fleets[name] -= n
        self.calls.append(("down", name, n))
        return [f"old{i}" for i in range(n)]


def _feed(store, active, slots, source="gateway:a"):
    store.record(SIG_GW_ACTIVE, float(active), source=source)
    store.record(SIG_GW_SLOTS, float(slots), source=source)


def test_autoscaler_hysteresis_cooldown_and_limits():
    store = TimeSeriesStore()
    sup = _StubSupervisor({"gateway": 1})
    scaler = Autoscaler(
        store, sup,
        policies=[ScalePolicy(name="res", fleet="gateway",
                              signal=SIG_GW_ACTIVE, divide_by=SIG_GW_SLOTS,
                              up_when=0.85, down_when=0.30, for_count=2)],
        limits={"gateway": (1, 2)}, cooldown_s=50.0)
    _feed(store, 8, 8)
    t = 1000.0
    # hysteresis: one breached evaluation is NOT enough
    assert scaler.evaluate_once(now=t) == []
    decisions = scaler.evaluate_once(now=t + 1)
    assert [d["direction"] for d in decisions] == ["up"]
    assert sup.calls == [("up", "gateway", 1)]
    assert "res=" in decisions[0]["reason"]
    # cooldown: still breached, no second action inside the window
    _feed(store, 16, 16)
    assert scaler.evaluate_once(now=t + 2) == []
    assert scaler.evaluate_once(now=t + 3) == []
    # max limit: past cooldown, at the cap, no action either
    assert scaler.evaluate_once(now=t + 60) == []
    assert scaler.evaluate_once(now=t + 61) == []
    assert sup.actual("gateway") == 2
    # load drop: down needs its own streak, then acts once, floor-clamped
    _feed(store, 2, 16)
    assert scaler.evaluate_once(now=t + 120) == []
    down = scaler.evaluate_once(now=t + 121)
    assert [d["direction"] for d in down] == ["down"]
    assert sup.actual("gateway") == 1
    # at the floor: even a sustained down-breach cannot go below min
    assert scaler.evaluate_once(now=t + 200) == []
    assert scaler.evaluate_once(now=t + 201) == []
    assert sup.actual("gateway") == 1
    st = scaler.status()
    assert st["last_decision"]["direction"] == "down"
    assert st["policies"]["res"]["value"] == pytest.approx(2 / 16)


def test_autoscaler_no_data_is_no_action():
    store = TimeSeriesStore()
    sup = _StubSupervisor({"gateway": 1})
    scaler = Autoscaler(store, sup, policies=[
        ScalePolicy(name="res", fleet="gateway", signal=SIG_GW_ACTIVE,
                    divide_by=SIG_GW_SLOTS, up_when=0.85, down_when=0.30,
                    for_count=1)])
    assert scaler.evaluate_once(now=0.0) == []
    assert sup.calls == []


def test_coordinator_autoscaler_route_and_opsctl_digest(capsys):
    store = TimeSeriesStore()
    sup = _StubSupervisor({"gateway": 2})
    scaler = Autoscaler(store, sup, policies=[
        ScalePolicy(name="res", fleet="gateway", signal=SIG_GW_ACTIVE,
                    divide_by=SIG_GW_SLOTS, up_when=0.85, down_when=0.30)])
    prev = set_autoscaler(scaler)
    co = CoordinatorServer(Coordinator())
    co.start()
    try:
        with urllib.request.urlopen(
                f"http://{co.host}:{co.port}/autoscaler", timeout=5) as resp:
            body = json.loads(resp.read())
        assert body["fleets"]["gateway"]["actual"] == 2
        assert "res" in body["policies"]
        import opsctl

        opsctl._print_autoscaler(f"{co.host}:{co.port}")
        out = capsys.readouterr().out
        assert "autoscaler:" in out and "[gateway]" in out and "res" in out
    finally:
        set_autoscaler(prev)
        co.stop()
    # with no autoscaler installed the route 404s
    co2 = CoordinatorServer(Coordinator())
    co2.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"http://{co2.host}:{co2.port}/autoscaler",
                                   timeout=5)
        assert ei.value.code == 404
    finally:
        co2.stop()


# ---------------------------------------------------------------- pinning
def test_pinning_refuses_honestly_on_small_hosts(monkeypatch):
    monkeypatch.setattr(os, "sched_getaffinity", lambda pid: {0}, raising=False)
    p = pinning.plan(2)
    assert not p.pinned and "time-share" in p.refused_reason
    prov = p.provenance()
    assert prov["pinned"] is False and prov["host_cores"] == 1
    assert prov["tool"] == "tools/pin.py"
    assert not pinning.scaling_valid(prov)


def test_pinning_plans_disjoint_cores_on_multicore(monkeypatch):
    monkeypatch.setattr(os, "sched_getaffinity",
                        lambda pid: {0, 1, 2, 3}, raising=False)
    p = pinning.plan(3, reserve_client=1)
    assert p.pinned and p.host_cores == 4
    flat = [c for cores in p.assignments for c in cores]
    assert len(flat) == len(set(flat)) == 3  # one core each, disjoint
    assert p.client_cores and not (set(p.client_cores) & set(flat))
    prov = p.provenance({"pid1": [0], "pid2": [1], "pid3": [2]})
    assert pinning.scaling_valid(prov)
    assert pinning.scaling_valid(prov, min_cores=4)
    assert not pinning.scaling_valid(prov, min_cores=5)


def test_pin_pid_self_roundtrip():
    if not pinning.can_pin():
        pytest.skip("no sched_setaffinity on this platform")
    cores = sorted(os.sched_getaffinity(0))
    assert pinning.pin_pid(0, cores)  # pin to the full current mask: no-op
    assert sorted(os.sched_getaffinity(0)) == cores


def test_pin_fleet_refusal_is_inband_on_this_host(monkeypatch):
    monkeypatch.setattr(os, "sched_getaffinity", lambda pid: {0}, raising=False)
    prov = pinning.pin_fleet([os.getpid()])
    assert prov["pinned"] is False and "refused_reason" in prov


# ----------------------------------------------------- perf_gate scaling gate
def test_perf_gate_refuses_forged_scaling_claims(tmp_path):
    forged = {"metric": "x", "value": 1.0, "scaling_valid": True,
              "host_cores": 1}
    assert perf_gate.scaling_offences(forged)
    path = tmp_path / "forged.json"
    path.write_text(json.dumps(forged))
    rc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "perf_gate.py"),
         "scaling", "--artifact", str(path)],
        capture_output=True, text=True).returncode
    assert rc == 2
    # multi-core but NO provenance block: still forged
    assert perf_gate.scaling_offences(
        {"scaling_valid": True, "host_cores": 4})
    # provenance that refused: forged
    assert perf_gate.scaling_offences(
        {"scaling_valid": True, "host_cores": 4,
         "pinning": {"pinned": False, "host_cores": 4,
                     "refused_reason": "x"}})
    # the honest true claim passes
    clean = {"scaling_valid": True, "host_cores": 4,
             "pinning": {"tool": "tools/pin.py", "pinned": True,
                         "host_cores": 4,
                         "assignments": {"pid1": [0], "pid2": [1]},
                         "client_cores": [2, 3]}}
    assert perf_gate.scaling_offences(clean) == []
    # ...and the honest false claim always passes
    assert perf_gate.scaling_offences(
        {"scaling_valid": False, "host_cores": 1}) == []


def test_perf_gate_scaling_sweep_of_committed_artifacts_is_clean():
    """Tier-1 acceptance: no committed artifact carries a forged scaling
    claim (every committed scaling_valid:true must have pinning provenance)."""
    hits = perf_gate.scaling_sweep(REPO)
    assert hits == [], f"forged scaling claims committed: {hits}"


def test_perf_gate_check_hard_fails_on_scaling_precondition(tmp_path):
    base = {"metric": "x", "value": 1.0}
    cand = {"metric": "x", "value": 1.0, "scaling_valid": True,
            "host_cores": 1}
    bp, cp = tmp_path / "b.json", tmp_path / "c.json"
    bp.write_text(json.dumps(base))
    cp.write_text(json.dumps(cand))
    rc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "perf_gate.py"),
         "check", "--baseline", str(bp), "--candidate", str(cp)],
        capture_output=True, text=True).returncode
    assert rc == 2


# -------------------------------------------------------- discovery refresh
def test_start_refresh_applies_records_and_survives_errors():
    co = CoordinatorServer(Coordinator())
    co.start()
    co.coordinator.register("tok", "10.0.0.9", 1, lease_s=60.0)
    seen = []
    boom = [True]

    def apply(records):
        if boom[0]:
            boom[0] = False
            raise RuntimeError("first application fails")
        seen.append([f"{r['ip']}:{r['port']}" for r in records])

    t = start_refresh((co.host, co.port), "tok", apply, interval_s=0.1)
    try:
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and not seen:
            time.sleep(0.05)
        assert seen and seen[0] == ["10.0.0.9:1"]
    finally:
        t.stop_event.set()
        co.stop()


# ------------------------------------------------------------ slow: drill
@pytest.mark.slow
def test_elastic_drill_exits_zero(tmp_path):
    """The full acceptance drill: spike -> live scale-up -> graceful drain
    with exact accounting -> SIGKILL mid-drain -> zero acked loss."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos.py"),
         "elastic-drill", "--dir", str(tmp_path / "spill"),
         "--items", "40", "--sessions", "12"],
        capture_output=True, text=True, timeout=420, cwd=REPO)
    assert out.returncode == 0, out.stdout[-3000:] + out.stderr[-2000:]
    verdict = json.loads(out.stdout.strip().splitlines()[-2])
    assert verdict["failures"] == []
    assert verdict["phase_b"]["lost_acked"] == 0
    assert verdict["pinning"]["pinned"] in (True, False)  # in-band either way
