"""CI wrapper for the two-process jax.distributed smoke (multihost_smoke.py)."""
import os
import subprocess
import sys

import pytest


@pytest.mark.slow
def test_two_process_distributed_smoke():
    script = os.path.join(os.path.dirname(__file__), "multihost_smoke.py")
    proc = subprocess.run(
        [sys.executable, script], capture_output=True, text=True, timeout=1800,
    )
    assert proc.returncode == 0, proc.stderr[-2000:] + proc.stdout[-500:]
    assert "multihost smoke ok" in proc.stdout
    assert "multihost fsdp smoke ok" in proc.stdout  # cross-process shards ran
