"""Harness tests for bench.py's sweep logic (the driver-facing surface).

Three rounds of BENCH_r{N} artifacts died to harness bugs, not model bugs —
so the sweep/retry/emit logic gets direct coverage: the _bench_* measurement
functions are monkeypatched and run_child exercised in-process on the CPU
backend (fast), plus one slow-marked subprocess test that builds the real
model to prove the parent never kills a compiling child (the livelock).
"""
import json
import os
import sys

import pytest

# repo root (bench.py lives outside the package)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402


def _fake_point(b, t, fps=100.0, remat=False):
    point = {
        "frames_per_sec": fps,
        "step_time_s": round(b * t / fps, 4),
        "trace_s": 0.1,
        "compile_s": 0.1,
        "batch": b,
        "unroll": t,
    }
    if remat:
        point["remat"] = True
    return point


def _final_json(capsys):
    out = [l for l in capsys.readouterr().out.splitlines() if l.startswith("{")]
    assert out, "run_child printed no JSON line"
    return json.loads(out[-1])


@pytest.fixture()
def sl_only_env(monkeypatch):
    # single-config plan: BENCH_BATCH/UNROLL pins plan = [(sl, 4, 16)]
    monkeypatch.setenv("BENCH_MODE", "sl")
    monkeypatch.setenv("BENCH_BATCH", "4")
    monkeypatch.setenv("BENCH_UNROLL", "16")
    monkeypatch.delenv("BENCH_REMAT", raising=False)
    monkeypatch.delenv("BENCH_PLATFORM", raising=False)


def test_oom_retries_with_remat(sl_only_env, monkeypatch, capsys):
    """A RESOURCE_EXHAUSTED SL config must be retried once rematerialized,
    and the sweep must record both the failure and the retried point."""
    calls = []

    def fake_sl(b, t, peak, iters=4, remat=False, cap=None):
        calls.append(remat)
        if not remat:
            raise RuntimeError("RESOURCE_EXHAUSTED: HBM OOM allocating 1.9G")
        return _fake_point(b, t, fps=50.0, remat=True)

    monkeypatch.setattr(bench, "_bench_sl", fake_sl)
    bench.run_child()

    assert calls == [False, True]
    final = _final_json(capsys)
    assert final["value"] == 50.0
    assert final["sl"]["remat"] is True
    # sweep keeps the diagnostic error record AND the successful retry
    assert any("error" in p for p in final["sl_sweep"])
    assert any(p.get("remat") for p in final["sl_sweep"] if "error" not in p)


def test_non_oom_error_is_not_retried(sl_only_env, monkeypatch, capsys):
    calls = []

    def fake_sl(b, t, peak, iters=4, remat=False, cap=None):
        calls.append(remat)
        raise ValueError("shape mismatch")

    monkeypatch.setattr(bench, "_bench_sl", fake_sl)
    # nothing completed -> run_child raises so the parent's retry loop fires
    with pytest.raises(RuntimeError, match="no config completed"):
        bench.run_child()
    assert calls == [False]  # no remat retry for non-OOM failures


def test_env_remat_run_skips_oom_retry(sl_only_env, monkeypatch, capsys):
    """BENCH_REMAT=1 runs already built the remat model: an OOM there must
    NOT rebuild the identical config."""
    monkeypatch.setenv("BENCH_REMAT", "1")
    calls = []

    def fake_sl(b, t, peak, iters=4, remat=False, cap=None):
        calls.append(remat)
        raise RuntimeError("RESOURCE_EXHAUSTED")

    monkeypatch.setattr(bench, "_bench_sl", fake_sl)
    with pytest.raises(RuntimeError, match="no config completed"):
        bench.run_child()
    assert calls == [False]


def test_full_plan_budget_break(monkeypatch, capsys):
    """Once any best exists and the budget is spent, the sweep stops —
    partial results must still produce a valid headline line."""
    monkeypatch.delenv("BENCH_BATCH", raising=False)
    monkeypatch.delenv("BENCH_UNROLL", raising=False)
    monkeypatch.delenv("BENCH_REMAT", raising=False)
    monkeypatch.setenv("BENCH_MODE", "both")
    monkeypatch.setenv("BENCH_TIME_BUDGET", "0")  # expire after first point

    seen = []

    def fake_sl(b, t, peak, iters=4, remat=False, cap=None):
        seen.append((b, t))
        return _fake_point(b, t)

    monkeypatch.setattr(bench, "_bench_sl", fake_sl)
    monkeypatch.setattr(bench, "_bench_rl", fake_sl)
    monkeypatch.setattr(bench, "_bench_sl_real", fake_sl)
    bench.run_child()

    assert seen == [(2, 8)]  # probe landed, then the budget gate fired
    final = _final_json(capsys)
    assert final["value"] == 100.0
    assert final["vs_baseline"] == round(100.0 / bench.SL_BASELINE_FRAMES, 3)


def test_headline_modes(monkeypatch, capsys):
    """rl-only and sl_real-only runs headline their own number, never a
    misleading 0.0 SL metric."""
    monkeypatch.setenv("BENCH_MODE", "rl")
    monkeypatch.setenv("BENCH_BATCH", "4")
    monkeypatch.setenv("BENCH_UNROLL", "16")
    monkeypatch.delenv("BENCH_REMAT", raising=False)

    def fake_rl(b, t, peak, iters=4, remat=False, cap=None):
        point = _fake_point(b, t, fps=64.0)
        point["steps_per_sec"] = 1.0
        return point

    monkeypatch.setattr(bench, "_bench_rl", fake_rl)
    bench.run_child()
    final = _final_json(capsys)
    assert "RL learner" in final["metric"]
    assert final["value"] == 64.0
    assert final["rl"]["vs_baseline_frames"] == round(64.0 / bench.RL_BASELINE_FRAMES, 3)


def test_default_plan_routes_entity_caps(monkeypatch, capsys):
    """4-tuple plan entries carry their bucket into the measurement fns;
    the capped baseline regime runs immediately after the probe so the
    strongest number lands earliest in the driver's window."""
    monkeypatch.delenv("BENCH_BATCH", raising=False)
    monkeypatch.delenv("BENCH_UNROLL", raising=False)
    monkeypatch.delenv("BENCH_REMAT", raising=False)
    monkeypatch.setenv("BENCH_MODE", "both")
    monkeypatch.setenv("BENCH_TIME_BUDGET", str(10 ** 9))

    calls = []

    def fake(kind):
        def fn(b, t, peak, iters=4, remat=False, cap=None):
            calls.append((kind, b, t, cap))
            point = _fake_point(b, t)
            if kind == "rl":
                point["steps_per_sec"] = 1.0
            return point

        return fn

    monkeypatch.setattr(bench, "_bench_sl", fake("sl"))
    monkeypatch.setattr(bench, "_bench_rl", fake("rl"))
    monkeypatch.setattr(bench, "_bench_sl_real", fake("sl_real"))
    bench.run_child()

    assert calls[0] == ("sl", 2, 8, None)          # probe first
    assert calls[1] == ("sl", 6, 64, 256)          # capped baseline next
    assert ("rl", 6, 64, 256) in calls             # capped RL regime
    assert ("sl", 32, 64, 256) in calls            # HBM edge bucketed
    assert ("sl_real", 6, 64, None) in calls       # real-data path uncapped
    _final_json(capsys)  # a valid headline line printed


def _run_parent(tmp_path, simulate, attempt_timeout, deadline, timeout=120):
    """Run bench.py's PARENT with a scripted simulated child (no jax, no
    compile — the round-4 version of these tests cold-compiled the real
    model and was flaky under -n 4 oversubscription)."""
    import subprocess
    import sys as _sys

    state = tmp_path / "attempts"
    env = dict(
        os.environ,
        BENCH_SIMULATE=simulate,
        BENCH_SIMULATE_STATE=str(state),
        BENCH_ATTEMPT_TIMEOUT=str(attempt_timeout),
        BENCH_DEADLINE=str(deadline),
    )
    out = subprocess.run(
        [_sys.executable, "-u",
         os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                      "bench.py")],
        env=env, capture_output=True, text=True, timeout=timeout,
    )
    lines = [l for l in out.stdout.splitlines() if l.startswith("{")]
    assert lines, out.stderr[-500:]
    attempts = int(state.read_text() or 0) if state.exists() else 0
    return json.loads(lines[-1]), attempts


def test_parent_extends_attempt_past_compile(tmp_path):
    """A child past backend-init must not be killed at BENCH_ATTEMPT_TIMEOUT:
    killing mid-compile caches nothing and the retry repeats the same
    compile forever (the BENCH_r01-r03 livelock). The simulated child holds
    the compile stage for >2x the attempt timeout, then lands its number.
    Under the livelock bug no attempt EVER lands (each child dies
    mid-compile), so the landed value is the whole assertion — exact
    attempt counts are load-dependent (a python start slower than the
    attempt timeout adds a legitimate pre-stage retry under -n 4
    oversubscription) and deliberately not pinned."""
    final, attempts = _run_parent(
        tmp_path,
        # margins are sleeps, not compiles: load-independent
        "stage:backend-init (chip claim):0,stage:sl-compile b2xt4:20,result:123.0",
        attempt_timeout=8, deadline=300, timeout=360,
    )
    assert final["value"] == 123.0, final
    assert attempts <= 4, f"{attempts} attempts: extend logic not engaging"


def test_parent_kills_stuck_claim_and_retries(tmp_path):
    """A child that never gets past the chip claim IS killed at the attempt
    timeout, and the fresh claim of a later attempt can land (the
    contended-relay regime PERF.md documents)."""
    final, attempts = _run_parent(
        tmp_path,
        # attempt 1: stuck in backend-init far past the attempt timeout;
        # later attempts claim instantly and land
        "stage:backend-init (chip claim):90;"
        "stage:backend-init (chip claim):0,stage:devices-ok cpu:0,result:55.5",
        attempt_timeout=8, deadline=300, timeout=360,
    )
    assert final["value"] == 55.5, final
    assert attempts >= 2, "stuck first attempt was never killed"


def test_env_cap_governs_whole_sweep(monkeypatch, capsys):
    """BENCH_MAX_ENTITIES overrides the plan's own buckets — no entry runs
    at a different bucket and no duplicate configs pay a second compile."""
    monkeypatch.delenv("BENCH_BATCH", raising=False)
    monkeypatch.delenv("BENCH_UNROLL", raising=False)
    monkeypatch.delenv("BENCH_REMAT", raising=False)
    monkeypatch.setenv("BENCH_MODE", "both")
    monkeypatch.setenv("BENCH_TIME_BUDGET", str(10 ** 9))
    monkeypatch.setenv("BENCH_MAX_ENTITIES", "384")

    calls = []

    def fake(kind):
        def fn(b, t, peak, iters=4, remat=False, cap=None):
            calls.append((kind, b, t, cap, remat))
            point = _fake_point(b, t)
            if kind == "rl":
                point["steps_per_sec"] = 1.0
            return point

        return fn

    monkeypatch.setattr(bench, "_bench_sl", fake("sl"))
    monkeypatch.setattr(bench, "_bench_rl", fake("rl"))
    monkeypatch.setattr(bench, "_bench_sl_real", fake("sl_real"))
    bench.run_child()

    assert all(cap is None for _, _, _, cap, _ in calls)  # env governs via fns
    # remat is part of a config's identity: the b16-remat A/B entry is NOT a
    # duplicate of plain b16 (their compiles differ)
    configs = [(k, b, t, remat) for k, b, t, _, remat in calls]
    assert len(configs) == len(set(configs))  # duplicates deduped
    assert ("sl", 6, 64, False) in configs and ("rl", 6, 64, False) in configs
    assert ("sl", 16, 64, True) in configs  # the remat A/B point survives
