"""Test harness: force an 8-device virtual CPU platform.

This is the TPU analogue of the reference's FakeLink fake distributed backend
(distar/ctools/utils/fake_linklink.py) — multi-device collective code paths
run single-process on virtual devices.

The image's sitecustomize registers the 'axon' TPU tunnel backend at
interpreter start and pins the jax platform to axon *via jax.config* (so
setting JAX_PLATFORMS here is too late). We override the config back to cpu
before any backend is initialised. The real-TPU path is exercised by
bench.py / __graft_entry__.py, not by tests — the single tunneled chip
admits one client at a time and tests must not hold it.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# implicit request-span minting OFF suite-wide (the DISTAR_PERF_AOT=0
# precedent): hundreds of serve/replay tests would otherwise each pay the
# tracing hot path for zero test value on a 1-core CI host. Explicit
# ``start_trace``/``finish_trace`` calls (the PR 1 trajectory pipeline)
# are unaffected; tracing tests opt back in via ``obs.set_tracing(True)``
# (tests/test_trace_fleet.py) and its subprocesses via DISTAR_TRACE=1.
# Must be set BEFORE distar_tpu.obs imports (the flag is read at import).
os.environ.setdefault("DISTAR_TRACE", "0")

import jax

jax.config.update("jax_platforms", "cpu")
# persistent compile cache: identical small-model jits recur across test
# modules; cached XLA executables cut warm suite time drastically. The dir
# is keyed by the HOST's cpu flags: this container migrates between hosts,
# and XLA:CPU AOT entries compiled elsewhere can SIGILL when loaded here
# (utils/compile_cache.py; the round-4 full-suite segfaults)
from distar_tpu.utils.compile_cache import configure as _configure_cache  # noqa: E402

_configure_cache(jax, "/tmp/jax_cache_distar_tpu")

import numpy as np
import pytest

# --------------------------------------------------------------- lockwatch
# DISTAR_LOCKWATCH=1: wrap threading.Lock/RLock creation (distar_tpu code
# only) + blocking primitives for the whole session, then report the
# per-thread lock-order graph (ABBA inversions) and held-while-blocking
# pairs at session end — the dynamic witness for the static lock rules
# (docs/analysis.md). Must install BEFORE distar_tpu modules construct
# their locks, i.e. at conftest import.
_LOCKWATCH = os.environ.get("DISTAR_LOCKWATCH") == "1"
if _LOCKWATCH:
    from distar_tpu.analysis import lockwatch as _lockwatch

    _lockwatch.install()


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    if not _LOCKWATCH:
        return
    rep = _lockwatch.report()
    baseline = _lockwatch.load_baseline(
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "tools", "lockwatch_baseline.json"))
    bad = _lockwatch.unbaselined(rep, baseline)
    out = os.environ.get("DISTAR_LOCKWATCH_OUT")
    if out:
        import json as _json

        with open(out, "w") as f:
            _json.dump({"report": rep, "unbaselined": bad}, f, indent=1)
    terminalreporter.section("lockwatch")
    terminalreporter.write_line(_lockwatch.render_report(rep, bad))


@pytest.fixture(autouse=True, scope="session")
def _scoped_experiments_root(tmp_path_factory):
    """Scope every default experiment dir to a fresh tmp root.

    Learners resolve ``experiments/<name>`` relative to
    ``DISTAR_EXPERIMENTS_ROOT`` (base_learner.experiments_root). Without
    this, a test that doesn't pass ``save_path`` writes checkpoints into
    the repo's ``experiments/`` — and a LATER run's auto-resume silently
    restores that stale state (the PR 5 tier-1 poisoning: sl_train resumed
    a previous invocation's checkpoint and ran 0 fresh iterations).
    Subprocesses spawned by tests inherit the env var, so CLI-level tests
    are scoped too."""
    root = tmp_path_factory.mktemp("experiments")
    prev = os.environ.get("DISTAR_EXPERIMENTS_ROOT")
    os.environ["DISTAR_EXPERIMENTS_ROOT"] = str(root)
    yield
    if prev is None:
        os.environ.pop("DISTAR_EXPERIMENTS_ROOT", None)
    else:
        os.environ["DISTAR_EXPERIMENTS_ROOT"] = prev


@pytest.fixture(autouse=True, scope="session")
def _no_background_perf_aot():
    """Disable the perf monitor's background AOT flop extraction suite-wide.

    Every learner that trains would otherwise spawn one background
    lower()/cost_analysis() thread (obs/perf.py) — dozens of concurrent
    re-traces of small models on an oversubscribed CPU host slow the suite
    for zero test value. Tests that exercise the AOT path opt back in per
    learner via ``learner.perf.aot=True``."""
    prev = os.environ.get("DISTAR_PERF_AOT")
    os.environ["DISTAR_PERF_AOT"] = "0"
    yield
    if prev is None:
        os.environ.pop("DISTAR_PERF_AOT", None)
    else:
        os.environ["DISTAR_PERF_AOT"] = prev


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture
def chaos():
    """Seeded fault injector (distar_tpu/resilience/chaos.py); any patches it
    installed are restored on teardown so faults never leak across tests."""
    from distar_tpu.resilience.chaos import ChaosInjector

    inj = ChaosInjector(seed=0)
    yield inj
    inj.restore()


@pytest.fixture(autouse=True, scope="module")
def _bound_compiled_program_accumulation():
    """Drop compiled-executable caches at each module boundary.

    A single long-lived process accumulating a few hundred XLA-CPU
    executables segfaulted inside backend_compile_and_load (deterministic
    at the same test, twice, near the end of a serial full-suite run).
    Clearing per-module bounds native accumulation; the persistent disk
    cache keeps cross-module recompiles cheap."""
    yield
    jax.clear_caches()


# shared tiny flagship-shaped model config for learner/actor tests (several
# older test files still carry local copies; new tests should import this)
SMALL_MODEL = {
    "encoder": {
        "entity": {"layer_num": 1, "hidden_dim": 32, "output_dim": 16, "head_dim": 8},
        "spatial": {"down_channels": [4, 4, 8], "project_dim": 4, "resblock_num": 1, "fc_dim": 16},
        "scatter": {"output_dim": 4},
        "core_lstm": {"hidden_size": 32, "num_layers": 1},
    },
    "policy": {
        "action_type_head": {"res_dim": 16, "res_num": 1, "gate_dim": 32},
        "delay_head": {"decode_dim": 16},
        "queued_head": {"decode_dim": 16},
        "selected_units_head": {"func_dim": 16},
        "target_unit_head": {"func_dim": 16},
        "location_head": {"res_dim": 8, "res_num": 1, "upsample_dims": [4, 4, 1], "map_skip_dim": 8},
    },
    "value": {"res_dim": 8, "res_num": 1},
}
