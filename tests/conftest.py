"""Test harness: force an 8-device virtual CPU platform before JAX import.

This is the TPU analogue of the reference's FakeLink fake distributed backend
(distar/ctools/utils/fake_linklink.py) — multi-device collective code paths
run single-process on virtual devices.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)
