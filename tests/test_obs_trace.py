"""Trace spans: context minting, hop recording, and span-id propagation
actor→adapter→(mock shuttle)→dataloader→learner fields."""
import threading
import time

import numpy as np
import pytest

from distar_tpu.comm import Adapter, Coordinator
from distar_tpu.comm import shuttle as shuttle_mod
from distar_tpu.obs import (
    MetricsRegistry,
    Span,
    finish_trace,
    hop_names,
    is_trace,
    mark_hop,
    mint_span_id,
    set_registry,
    start_trace,
    unwrap_payload,
    wrap_payload,
)


@pytest.fixture
def registry():
    reg = MetricsRegistry()
    prev = set_registry(reg)
    yield reg
    set_registry(prev)


@pytest.fixture
def mock_shuttle(monkeypatch):
    """In-memory shuttle: serve banks the blob under a fake port, fetch pops
    it — the adapter's real serialize/envelope path minus the sockets."""
    store = {}
    ports = iter(range(40_000, 50_000))

    def serve(payload, accept_count=1, timeout_ms=0):
        port = next(ports)
        store[port] = bytes(payload)
        return port

    def fetch(host, port, timeout_ms=0):
        if port not in store:
            raise ConnectionError(f"no payload at {host}:{port}")
        return store.pop(port)

    monkeypatch.setattr(shuttle_mod, "serve", serve)
    monkeypatch.setattr(shuttle_mod, "fetch", fetch)
    return store


# ----------------------------------------------------------------- context
def test_span_ids_unique_and_hex():
    ids = {mint_span_id() for _ in range(100)}
    assert len(ids) == 100
    assert all(len(i) == 16 and int(i, 16) >= 0 for i in ids)


def test_trace_lifecycle_records_hops(registry):
    ctx = start_trace("trajectory", player="MP0")
    assert is_trace(ctx)
    assert ctx["attrs"] == {"player": "MP0"}
    dt = mark_hop(ctx, "adapter_push", registry=registry)
    assert dt >= 0
    age = finish_trace(ctx, hop="learner_collate", registry=registry)
    assert age >= dt
    assert hop_names(ctx) == ["start", "adapter_push", "learner_collate"]
    assert registry.histogram("distar_trace_hop_seconds", hop="adapter_push").count == 1
    assert registry.histogram("distar_trace_e2e_seconds", span="trajectory").count == 1


def test_non_trace_inputs_are_noops(registry):
    assert mark_hop({"random": 1}, "x", registry=registry) == 0.0
    assert finish_trace(None, registry=registry) == 0.0
    assert hop_names("nope") == []


def test_wrap_unwrap_envelope():
    ctx = start_trace("t")
    data = {"payload": [1, 2]}  # consumer data that itself has a 'payload' key
    assert unwrap_payload(data) == (data, None)  # no envelope, returned as-is
    wrapped = wrap_payload(data, ctx)
    payload, got = unwrap_payload(wrapped)
    assert payload is data and got is ctx
    assert wrap_payload(data, None) is data  # no ctx -> no envelope


def test_span_context_manager_publishes(registry):
    with Span("collate", registry=registry) as sp:
        time.sleep(0.002)
    assert sp.elapsed >= 0.002
    assert registry.histogram("distar_span_seconds", span="collate").count == 1


# ------------------------------------------- mock shuttle round-trip
def test_span_id_rides_mock_shuttle_roundtrip(registry, mock_shuttle):
    co = Coordinator()
    adapter = Adapter(coordinator=co)
    ctx = start_trace("trajectory", player="MP0")
    traj = [{"step": 0, "trace": ctx}, {"step": 1}]
    adapter.push("MP0traj", traj, trace=ctx)
    payload, trace = adapter.pull("MP0traj", with_trace=True, timeout=5)
    assert trace["span_id"] == ctx["span_id"]
    assert trace["trace_id"] == ctx["trace_id"]
    assert hop_names(trace) == ["start", "adapter_push", "adapter_pull"]
    # the envelope ctx and the ctx stamped into the trajectory are the SAME
    # object after unpickling (pickle preserves identity within a payload),
    # so downstream consumers see the full hop history either way
    assert payload[0]["trace"] is trace
    assert registry.histogram("distar_trace_hop_seconds", hop="adapter_pull").count == 1


def test_plain_pull_terminates_span(registry, mock_shuttle):
    co = Coordinator()
    adapter = Adapter(coordinator=co)
    ctx = start_trace("model")
    adapter.push("m", {"w": 1}, trace=ctx)
    out = adapter.pull("m", timeout=5)
    assert out == {"w": 1}  # envelope stripped transparently
    assert registry.histogram("distar_trace_e2e_seconds", span="model").count == 1


def test_untraced_push_unchanged(registry, mock_shuttle):
    co = Coordinator()
    adapter = Adapter(coordinator=co)
    adapter.push("m", {"w": 2})
    payload, trace = adapter.pull("m", with_trace=True, timeout=5)
    assert payload == {"w": 2} and trace is None


def test_pull_loop_keep_trace_hands_tuple(registry, mock_shuttle):
    co = Coordinator()
    adapter = Adapter(coordinator=co)
    cache = adapter.start_pull_loop("MP0traj", maxlen=4, keep_trace=True)
    ctx = start_trace("trajectory")
    adapter.push("MP0traj", [{"trace": ctx}], trace=ctx)
    deadline = time.time() + 10
    while not cache and time.time() < deadline:
        time.sleep(0.01)
    adapter.stop()
    assert cache, "pull loop never delivered"
    payload, trace = cache.popleft()
    assert trace["span_id"] == ctx["span_id"]
    # span left open for the consumer: no e2e recorded yet
    assert registry.histogram("distar_trace_e2e_seconds", span="trajectory").count == 0


# ------------------------------------ dataloader -> learner propagation
def test_rl_dataloader_closes_spans_into_batch_fields(registry, mock_shuttle, monkeypatch):
    from distar_tpu.learner import rl_dataloader

    # stub the (schema-heavy) collation: trace handling happens around it
    monkeypatch.setattr(
        rl_dataloader,
        "collate_trajectories",
        lambda trajs: {"model_last_iter": np.zeros(len(trajs), np.float32)},
    )
    co = Coordinator()
    adapter = Adapter(coordinator=co)
    loader = rl_dataloader.RLDataLoader(adapter, "MP0", batch_size=2)
    ids = []
    for i in range(2):
        ctx = start_trace("trajectory", player="MP0")
        ids.append(ctx["span_id"])
        traj = [{"step": i, "trace": ctx}]
        adapter.push("MP0traj", traj, trace=ctx)
    batch = next(loader)
    adapter.stop()
    assert batch["trace_span_ids"] == ids  # FIFO order, ids intact end to end
    assert batch["trace_age_s"].shape == (2,)
    assert (batch["trace_age_s"] >= 0).all()
    e2e = registry.histogram("distar_trace_e2e_seconds", span="trajectory")
    assert e2e.count == 2  # exactly once per trajectory: no double-finish
    assert registry.histogram(
        "distar_trace_hop_seconds", hop="learner_collate"
    ).count == 2
