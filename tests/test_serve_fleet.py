"""Serving fleet: discovery, session-affinity routing, canary rollout.

Covers the `distar_tpu/serve/fleet/` contracts (docs/serving.md, fleet
section): cross-process-deterministic affinity, re-route on gateway death
with zero-carry re-materialization counted exactly, all-or-nothing atomic
rollout with per-gateway ack/rollback, canary percent routing, coordinator
discovery round-trip, player multiplexing over one address, the zstd codec
negotiation, and the loadgen fleet-mode capacity harness.

In-process gateways (mock engine + real TCP servers on loopback) keep the
tier-1 tests fast; the full multi-process chaos drill
(``tools/chaos.py serve-drill``) and the subprocess harnesses are
slow-marked.
"""
import json
import os
import subprocess
import sys
import textwrap
import zlib

import numpy as np
import pytest

from distar_tpu.comm import serializer
from distar_tpu.comm.coordinator import CoordinatorServer
from distar_tpu.serve import (
    GatewayMux,
    InferenceGateway,
    MockModelEngine,
    ServeClient,
    ServeError,
    ServeTCPServer,
    UnknownPlayerError,
)
from distar_tpu.serve.fleet import (
    FleetClient,
    FleetRollout,
    FleetRouter,
    GatewayMap,
    fetch_canary,
    publish_canary,
    register_gateway,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _obs(i: int = 0) -> dict:
    return {"x": np.full((2, 2), float(i), dtype=np.float32)}


def _gateway(slots: int = 8, version: str = "v1", bias: float = 0.0):
    params = {"version": version, "bias": bias}
    gw = InferenceGateway(
        MockModelEngine(slots, params=params), max_batch=slots,
        max_delay_s=0.002, idle_ttl_s=300.0,
    )
    gw.load_version(version, params=params, activate=True)
    return gw.start()


class _Fleet:
    """N in-process gateways behind real TCP servers on loopback."""

    def __init__(self, n: int, slots: int = 8, version: str = "v1"):
        self.gateways = [_gateway(slots, version=version) for _ in range(n)]
        self.servers = [ServeTCPServer(gw, port=0).start() for gw in self.gateways]
        self.addrs = [f"{s.host}:{s.port}" for s in self.servers]

    def stop(self, idx=None):
        for i, s in enumerate(self.servers):
            if idx is None or i == idx:
                s.stop()

    def close(self):
        self.stop()
        for gw in self.gateways:
            gw.drain_and_stop(2.0)


# ----------------------------------------------------------------- affinity
def test_affinity_stable_within_and_across_router_instances():
    gm = GatewayMap(["10.0.0.1:1", "10.0.0.2:2", "10.0.0.3:3"])
    r1, r2 = FleetRouter(gm), FleetRouter(GatewayMap(list(gm.addrs)))
    for i in range(50):
        sid = f"sess-{i}"
        a = r1.gateway_for(sid)
        assert r1.gateway_for(sid) == a  # pin is stable
        assert r2.gateway_for(sid) == a  # two routers agree with no talk


def test_affinity_deterministic_across_processes():
    """A router in a fresh interpreter (different PYTHONHASHSEED) routes the
    same sessions to the same gateways — md5, not hash()."""
    addrs = ["10.0.0.1:1", "10.0.0.2:2", "10.0.0.3:3"]
    sids = [f"sess-{i}" for i in range(30)]
    script = textwrap.dedent("""
        import json, sys
        sys.path.insert(0, %r)
        from distar_tpu.serve.fleet import FleetRouter, GatewayMap
        r = FleetRouter(GatewayMap(%r))
        print(json.dumps({s: r.gateway_for(s) for s in %r}))
    """) % (_REPO, addrs, sids)
    out = subprocess.run([sys.executable, "-c", script], capture_output=True,
                         text=True, env={**os.environ, "PYTHONHASHSEED": "77"})
    assert out.returncode == 0, out.stderr
    theirs = json.loads(out.stdout.strip().splitlines()[-1])
    ours = FleetRouter(GatewayMap(addrs))
    assert theirs == {s: ours.gateway_for(s) for s in sids}


def test_canary_split_is_deterministic_and_percentish():
    gm = GatewayMap(["10.0.0.1:1", "10.0.0.2:2", "10.0.0.3:3"])
    router = FleetRouter(gm)
    router.set_canary(["10.0.0.1:1"], 33.0)
    routed = {f"c-{i}": router.gateway_for(f"c-{i}") for i in range(600)}
    frac = sum(1 for a in routed.values() if a == "10.0.0.1:1") / len(routed)
    assert 0.23 < frac < 0.43  # ~33% with binomial slack
    # a second router agrees on every session's pool membership
    r2 = FleetRouter(GatewayMap(list(gm.addrs)))
    r2.set_canary(["10.0.0.1:1"], 33.0)
    assert routed == {s: r2.gateway_for(s) for s in routed}
    # canary off: fresh sessions never pick the canary-only pool split
    router.clear_canary()
    assert router.canary_config() == ([], 0.0)


# ------------------------------------------------------- re-route on death
def test_reroute_on_gateway_death_counts_migrations_and_zero_carry():
    from distar_tpu.obs import get_registry

    fleet = _Fleet(2, slots=16)
    fc = FleetClient(gateway_map=GatewayMap(fleet.addrs), timeout_s=5.0,
                     down_ttl_s=60.0)
    try:
        sids = [f"d-{i}" for i in range(10)]
        for _ in range(3):  # three steps: every session at session_step 3
            res = fc.act_many([{"session_id": s, "obs": _obs()} for s in sids],
                              timeout_s=5.0)
            assert all(isinstance(r, dict) for r in res)
        pins = fc.router.stats()["pins_per_gateway"]
        victim_idx = 0 if pins[fleet.addrs[0]] >= pins[fleet.addrs[1]] else 1
        victim = fleet.addrs[victim_idx]
        victims = set(fc.router.pins_on(victim))
        assert victims  # the hash spread must put someone on the victim
        before = get_registry().snapshot().get(
            "distar_fleet_session_migrations_total", 0.0)
        fleet.stop(victim_idx)

        res = fc.act_many([{"session_id": s, "obs": _obs()} for s in sids],
                          timeout_s=10.0)
        assert all(isinstance(r, dict) for r in res), res
        snap = get_registry().snapshot()
        # every victim-pinned session migrated, exactly once
        assert snap["distar_fleet_session_migrations_total"] - before == len(victims)
        # zero-carry re-materialization: migrated sessions restarted at
        # step 1 on the survivor; unaffected sessions kept advancing
        for s, r in zip(sids, res):
            assert r["session_step"] == (1 if s in victims else 4)
        # ...and the counter does not double-fire on the next healthy step
        fc.act_many([{"session_id": s, "obs": _obs()} for s in sids],
                    timeout_s=5.0)
        assert get_registry().snapshot()[
            "distar_fleet_session_migrations_total"] - before == len(victims)
        assert victim in fc.router.stats()["down"]
    finally:
        fc.close()
        fleet.close()


def test_typed_sheds_pass_through_without_reroute():
    """Backpressure is an application answer: a CapacityError must not mark
    the gateway down or move pins."""
    fleet = _Fleet(1, slots=2)
    fc = FleetClient(gateway_map=GatewayMap(fleet.addrs), timeout_s=2.0)
    try:
        fc.act("a", _obs())
        fc.act("b", _obs())
        with pytest.raises(ServeError) as ei:
            fc.act("c", _obs())  # no slot, nothing evictable
        assert getattr(ei.value, "shed", False)
        assert fc.router.stats()["down"] == []
    finally:
        fc.close()
        fleet.close()


# ------------------------------------------------------------------ rollout
class _SwapNack:
    """Client wrapper that NACKs activation (simulates a wedged gateway)."""

    def __init__(self, inner):
        self._inner = inner

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def swap(self, version, player=None):
        raise ServeError("injected swap NACK")


class _LoadNack(_SwapNack):
    def swap(self, version, player=None):
        return self._inner.swap(version, player=player)

    def load(self, version, source=None, params=None, activate=False,
             player=None):
        raise ServeError("injected load NACK")


def _fleet_ctl(fleet, nack_cls=None, nack_idx=None):
    def factory(addr):
        host, _, port = addr.rpartition(":")
        client = ServeClient(host, int(port), timeout_s=5.0)
        if nack_cls is not None and addr == fleet.addrs[nack_idx]:
            return nack_cls(client)
        return client

    return FleetRollout(GatewayMap(fleet.addrs), timeout_s=5.0,
                        client_factory=factory)


def test_rollout_atomic_ok_and_load_nack_leaves_fleet_untouched():
    fleet = _Fleet(3)
    try:
        ctl = _fleet_ctl(fleet)
        verdict = ctl.rollout("v2", params={"version": "v2", "bias": 1.0})
        assert verdict["ok"] and len(verdict["generations"]) == 3

        # load-phase NACK on one gateway: nothing anywhere activates v3
        ctl2 = _fleet_ctl(fleet, _LoadNack, 1)
        verdict = ctl2.rollout("v3", params={"version": "v3", "bias": 2.0})
        assert not verdict["ok"] and verdict["outcome"] == "load_nack"
        for st in ctl.fleet_status().values():
            assert st["registry"]["current"] == "v2"
        ctl.close()
        ctl2.close()
    finally:
        fleet.close()


def test_rollout_swap_nack_rolls_swapped_prefix_back():
    fleet = _Fleet(3)
    try:
        ctl = _fleet_ctl(fleet)
        assert ctl.rollout("v2", params={"version": "v2", "bias": 1.0})["ok"]
        ctl2 = _fleet_ctl(fleet, _SwapNack, 2)
        verdict = ctl2.rollout("v3", params={"version": "v3", "bias": 2.0})
        assert not verdict["ok"] and verdict["outcome"] == "rolled_back"
        assert verdict["failed_gateway"] == fleet.addrs[2]
        assert set(verdict["rollback"]) == set(fleet.addrs[:2])
        # the whole fleet still serves v2 — never split-brained
        for st in ctl.fleet_status().values():
            assert st["registry"]["current"] == "v2"
        ctl.close()
        ctl2.close()
    finally:
        fleet.close()


def test_canary_rollout_e2e_split_then_promote_with_version_streams():
    """Acceptance: 3 gateways at v1, canary 1 to v2 with ~33% of new
    sessions routed there, then atomic fleet-wide promote. Per-client
    version streams must be monotone v1* -> v2* (zero in-flight loss — the
    PR 2 hot-swap contract held fleet-wide)."""
    fleet = _Fleet(3, slots=64)
    fc = FleetClient(gateway_map=GatewayMap(fleet.addrs), timeout_s=5.0)
    try:
        ctl = _fleet_ctl(fleet)
        canary_addr = fleet.addrs[0]
        verdict = ctl.canary_start("v2", [canary_addr], 33.0,
                                   params={"version": "v2", "bias": 1.0},
                                   router=fc.router)
        assert verdict["ok"]

        streams = {f"cs-{i}": [] for i in range(60)}
        for _ in range(3):  # canary window traffic
            res = fc.act_many([{"session_id": s, "obs": _obs()} for s in streams])
            for s, r in zip(streams, res):
                assert isinstance(r, dict), r
                streams[s].append(r["version"])
        on_canary = {s for s in streams
                     if fc.router.gateway_for(s) == canary_addr}
        frac = len(on_canary) / len(streams)
        assert 0.15 < frac < 0.55  # ~33% of 60 sessions, binomial slack
        for s, versions in streams.items():
            assert set(versions) == ({"v2"} if s in on_canary else {"v1"})

        compare = ctl.compare([canary_addr])
        assert compare["canary"]["gateways"] == 1
        assert compare["stable"]["gateways"] == 2
        assert compare["canary"]["requests"].get("ok", 0) > 0

        assert ctl.promote("v2", params={"version": "v2", "bias": 1.0},
                           router=fc.router)["ok"]
        assert fc.router.canary_config() == ([], 0.0)
        for _ in range(2):  # post-promote traffic
            res = fc.act_many([{"session_id": s, "obs": _obs()} for s in streams])
            for s, r in zip(streams, res):
                streams[s].append(r["version"])
        for versions in streams.values():
            # monotone version stream: v1* then v2*, never interleaved —
            # the zero-in-flight-loss hot-swap contract, fleet-wide
            first_v2 = versions.index("v2") if "v2" in versions else len(versions)
            assert all(v == "v1" for v in versions[:first_v2])
            assert all(v == "v2" for v in versions[first_v2:])
        ctl.close()
    finally:
        fc.close()
        fleet.close()


# ---------------------------------------------------------------- discovery
def test_gateway_discovery_round_trip_and_lease_eviction():
    server = CoordinatorServer(port=0)
    server.coordinator._default_lease_s = None
    server.start()
    try:
        coord = (server.host, server.port)
        t1 = register_gateway(coord, "127.0.0.1", 7001,
                              meta={"players": ["MP0"], "slots": 32,
                                    "http_port": 8001}, lease_s=60.0)
        t2 = register_gateway(coord, "127.0.0.1", 7002,
                              meta={"players": ["MP1"], "slots": 16,
                                    "http_port": 8002}, lease_s=0.2,
                              heartbeat_interval_s=30.0)
        gm = GatewayMap.discover(coord)
        assert set(gm.addrs) == {"127.0.0.1:7001", "127.0.0.1:7002"}
        assert gm.meta["127.0.0.1:7001"]["slots"] == 32
        assert gm.http_addr("127.0.0.1:7002") == "127.0.0.1:8002"
        assert set(gm.players()) == {"MP0", "MP1"}
        # the non-popping peers read: discovery did not consume the fleet
        assert len(GatewayMap.discover(coord)) == 2
        # gateway 2's lease lapses (no heartbeat inside 0.2s) -> evicted
        # (sleep past the broker's once-per-second lease-sweep cooldown)
        import time as _time

        _time.sleep(1.2)
        gm = GatewayMap.discover(coord)
        assert gm.addrs == ["127.0.0.1:7001"]
        # canary config publish/fetch rides the same broker
        publish_canary(coord, ["127.0.0.1:7001"], 25.0, "v9")
        cfg = fetch_canary(coord)
        assert cfg == {"addrs": ["127.0.0.1:7001"], "pct": 25.0, "version": "v9"}
        publish_canary(coord, [], 0.0, "v9")
        assert fetch_canary(coord)["pct"] == 0.0
        t1.stop_event.set()
        t2.stop_event.set()
    finally:
        server.stop()


def test_gateway_map_parse_and_validation():
    gm = GatewayMap.parse("a:1,b:2,a:1")
    assert gm.addrs == ["a:1", "b:2"]
    with pytest.raises(ValueError):
        GatewayMap([])


# ------------------------------------------------------------ multiplexing
def test_mux_serves_two_players_over_one_address_legacy_unchanged():
    gw0 = _gateway(4, version="mp0-v1")
    gw1 = _gateway(4, version="mp1-v1")
    mux = GatewayMux({"MP0": gw0, "MP1": gw1})
    server = ServeTCPServer(mux, port=0).start()
    try:
        legacy = ServeClient(server.host, server.port)
        mp0 = ServeClient(server.host, server.port, player="MP0")
        mp1 = ServeClient(server.host, server.port, player="MP1")
        # legacy (no player field) resolves to the default player (MP0)
        assert legacy.act("s", _obs())["version"] == "mp0-v1"
        # the SAME session id under each player is an independent session
        assert mp0.act("s", _obs())["session_step"] == 2
        assert mp1.act("s", _obs())["session_step"] == 1
        assert mp1.act("s", _obs())["version"] == "mp1-v1"
        # per-player hot swap: MP1 swaps, MP0 undisturbed
        mp1.load("mp1-v2", params={"version": "mp1-v2", "bias": 1.0},
                 activate=True)
        assert mp1.act("s", _obs())["version"] == "mp1-v2"
        assert mp0.act("s", _obs())["version"] == "mp0-v1"
        with pytest.raises(UnknownPlayerError):
            ServeClient(server.host, server.port, player="MP9").act("x", _obs())
        st = legacy.status()
        assert set(st["players"]) == {"MP0", "MP1"}
        assert st["default_player"] == "MP0"
        legacy.close(), mp0.close(), mp1.close()
    finally:
        server.stop()
        mux.drain_and_stop(2.0)


def test_remote_plane_rides_fleet_and_multiplexed_players():
    """The rollout plane's remote backend over a multi-address fleet list:
    GatewayPolicyClient sessions reserve/step/reset through the router."""
    from distar_tpu.actor.rollout_plane import RolloutPlane

    fleet = _Fleet(2, slots=32)
    plane = RolloutPlane(backend="remote", addr=",".join(fleet.addrs),
                         timeout_s=5.0)
    try:
        client = plane.client_for("MP0", num_slots=6,
                                  params={"version": "v1", "bias": 0.0})
        prepared = [_obs(i) for i in range(6)]
        outs = client.sample(prepared)
        assert all(o is not None and o["version"] == "v1" for o in outs)
        outs = client.sample(prepared)
        assert [o["session_step"] for o in outs] == [2] * 6
        client.reset_slot(3)
        outs = client.sample(prepared)
        assert outs[3]["session_step"] == 1 and outs[0]["session_step"] == 3
        # sessions actually spread over both gateways via the ring
        pins = client.target.router.stats()["pins_per_gateway"]
        assert sum(bool(v) for v in pins.values()) >= 1
        client.close()
    finally:
        fleet.close()


def test_plane_addr_validation():
    from distar_tpu.actor.rollout_plane import RolloutPlane

    with pytest.raises(ValueError):
        RolloutPlane(backend="remote", addr="not-an-addr")
    with pytest.raises(ValueError):
        RolloutPlane(backend="remote", addr="discover")  # no coordinator
    # fleet shapes construct without dialing
    RolloutPlane(backend="remote", addr="a:1,b:2")
    RolloutPlane(backend="remote", addr="discover",
                 coordinator_addr="127.0.0.1:9")


# ------------------------------------------------------------- zstd codec
def test_zstd_codec_negotiation_falls_back_without_binding(monkeypatch):
    if serializer.zstd_available():
        pytest.skip("host has a real zstd binding; fallback path untestable")
    assert serializer.negotiate_codec(["zstd", "lz4"]) == "lz4"
    assert serializer.negotiate_codec(None) == "lz4"
    with pytest.raises(ValueError):
        serializer.dumps({"a": 1}, codec="zstd")


class _FakeZstd:
    class ZstdCompressor:
        def __init__(self, level=3):
            pass

        def compress(self, payload):
            return zlib.compress(payload, 6)

    class ZstdDecompressor:
        def decompress(self, body, max_output_size=0):
            return zlib.decompress(body)


def test_zstd_negotiated_end_to_end_with_injected_binding(monkeypatch):
    """Hello-frame codec negotiation over a real replay server: a
    zstd-preferring client gets zstd when the server speaks it, lz4 when
    the server restricts codecs — and frames round-trip either way."""
    from distar_tpu.replay import (
        InsertClient,
        ReplayServer,
        ReplayStore,
        SampleClient,
        TableConfig,
    )

    monkeypatch.setattr(serializer, "_zstd", _FakeZstd)
    assert "zstd" in serializer.supported_codecs()
    blob, raw = serializer.dumps_sized({"z": b"\0" * 512}, codec="zstd")
    assert blob[:4] == serializer.MAGIC_ZSTD
    assert serializer.loads(blob) == {"z": b"\0" * 512}

    cfg = TableConfig(max_size=16, sampler="fifo", samples_per_insert=None,
                      min_size_to_sample=1)
    server = ReplayServer(ReplayStore(table_factory=lambda n: cfg), port=0).start()
    try:
        ins = InsertClient(server.host, server.port, codec="zstd")
        ins.insert("t", {"k": 1})
        assert ins._neg_codec == "zstd"
        smp = SampleClient(server.host, server.port)  # lz4 legacy default
        items, _ = smp.sample("t", timeout_s=5.0)
        assert smp._neg_codec == "lz4" and items[0]["k"] == 1
        ins.close(), smp.close()
    finally:
        server.stop()
    # server restricted to lz4: the zstd ask degrades in the hello
    server = ReplayServer(ReplayStore(table_factory=lambda n: cfg), port=0,
                          codecs=("lz4",)).start()
    try:
        ins = InsertClient(server.host, server.port, codec="zstd")
        ins.insert("t", {"k": 2})
        assert ins._neg_codec == "lz4"
        ins.close()
    finally:
        server.stop()


def test_zstd_hostile_header_rejected(monkeypatch):
    monkeypatch.setattr(serializer, "_zstd", _FakeZstd)
    evil = serializer.MAGIC_ZSTD + (2 ** 60).to_bytes(8, "little") + b"xx"
    with pytest.raises(ValueError, match="implausible"):
        serializer.loads(evil)


# ----------------------------------------------------- standalone router
def test_standalone_router_process_fronts_fleet():
    fleet = _Fleet(2, slots=16)
    proc = subprocess.Popen(
        [sys.executable, "-m", "distar_tpu.serve.fleet.router",
         "--port", "0", "--http-port", "0",
         "--gateways", ",".join(fleet.addrs)],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, cwd=_REPO)
    try:
        parts = proc.stdout.readline().split()
        assert parts and parts[0] == "SERVE-ROUTER", (parts, proc.stderr.read())
        host, port = parts[1], int(parts[2])
        client = ServeClient(host, port, timeout_s=10.0)
        out = client.act("via-router", _obs())
        assert out["version"] == "v1" and out["session_step"] == 1
        out = client.act("via-router", _obs())
        assert out["session_step"] == 2  # sticky through the proxy
        st = client.status()
        assert set(st["router"]["gateways"]) == set(fleet.addrs)
        assert client.end("via-router") is True
        client.close()
    finally:
        proc.stdin.close()
        proc.wait(timeout=10)
        fleet.close()


def test_opsctl_status_prints_serving_fleet_digest():
    """opsctl against a coordinator auto-discovers serve_gateway
    registrations and prints the per-gateway + aggregate serving digest
    (session counts summed over multiplexed players)."""
    import time

    server = CoordinatorServer(port=0)
    server.start()
    coord = f"{server.host}:{server.port}"
    proc = subprocess.Popen(
        [sys.executable, "-m", "distar_tpu.serve.fleet.gateway_proc",
         "--port", "0", "--http-port", "0", "--slots", "16",
         "--players", "MP0,MP1", "--coordinator", coord],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, cwd=_REPO)
    try:
        parts = proc.stdout.readline().split()
        assert parts and parts[0] == "SERVE-GATEWAY", (parts, proc.stderr.read())
        tcp_addr = f"{parts[1]}:{parts[2]}"
        # put one session on MP1 so the digest shows live occupancy
        client = ServeClient(parts[1], int(parts[2]), player="MP1")
        client.act("digest-sess", _obs())
        client.close()
        time.sleep(0.2)
        out = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "opsctl.py"),
             "status", "--addr", coord],
            capture_output=True, text=True, timeout=60, cwd=_REPO)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "serving fleet:" in out.stdout
        assert f"[{tcp_addr}] players=MP0,MP1 sessions=1/32" in out.stdout
        assert "aggregate: 1 gateways  1/32 sessions" in out.stdout
        assert "versions=converged" in out.stdout
    finally:
        proc.stdin.close()
        proc.wait(timeout=10)
        server.stop()


# --------------------------------------------------------------- harnesses
def test_loadgen_fleet_mode_smoke():
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        from loadgen import run_loadgen
    finally:
        sys.path.pop(0)
    summary = run_loadgen(mode="fleet", gateways=2, slots=8,
                          fleet_levels="4,16,20", fleet_workers=4,
                          requests_per_session=2, timeout_s=15.0)
    assert summary["unit"] == "sessions" and summary["gateways"] == 2
    assert {"host_cores", "scaling_valid", "cpu_derived", "pinning"} <= set(summary)
    # scaling_valid is now a PROVEN claim: it must agree with the pinning
    # provenance block (perf_gate's scaling gate enforces the same)
    assert summary["scaling_valid"] == (
        summary["pinning"]["pinned"]
        and summary["pinning"]["host_cores"] >= summary["gateways"] + 1)
    curve = summary["fleet_curve"]
    assert [r["level"] for r in curve] == [4, 16, 20]
    # the over-capacity level sheds; resident sessions never exceed slots
    assert curve[-1]["shed_at_arrival"] > 0
    assert all(r["concurrent_resident"] <= 16 for r in curve)
    assert summary["errors_total"] == 0


@pytest.mark.slow
def test_chaos_serve_drill_exit_zero():
    """Acceptance: 3 real gateway processes under live load, one killed
    mid-run -> every session re-routes and finishes, migrations counted,
    no non-shed error leaks (tools/chaos.py serve-drill exits 0)."""
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "chaos.py"),
         "serve-drill", "--gateways", "3", "--sessions", "24",
         "--steps", "6", "--slots", "32"],
        capture_output=True, text=True, timeout=300, cwd=_REPO)
    assert out.returncode == 0, f"stdout={out.stdout}\nstderr={out.stderr}"
    verdict = json.loads(out.stdout.strip().splitlines()[-2])
    assert verdict["finished_sessions"] == 24
    assert verdict["migrations"] == verdict["killed"]["pinned"] > 0
    assert verdict["error_leaks"] == 0


@pytest.mark.slow
def test_fleet_artifact_is_current():
    """The committed FLEET_r10.json parses, carries the in-band honesty
    flags and a capacity curve (impossible-timing policy: no unflagged
    throughput claim)."""
    path = os.path.join(_REPO, "FLEET_r10.json")
    with open(path) as f:
        doc = json.load(f)
    assert doc["unit"] == "sessions"
    assert isinstance(doc["host_cores"], int)
    assert isinstance(doc["scaling_valid"], bool)
    assert doc["cpu_derived"] is True
    assert len(doc["fleet_curve"]) >= 2
    assert doc["value"] >= 10000  # the 10k+ concurrent-session regime
