"""jaxenv: the pure-JAX micro-battle world (ISSUE 17 tentpole).

Covers the Features contract parity (leaf-by-leaf against the mock-env /
fake_step_data schema), the determinism golden (committed fingerprint from
a fresh process — any drift in scenario generation, dynamics, or
observation packing flips the sha), env dynamics (combat resolves, states
freeze after done), the scripted-policy win-rate evaluator, and the
``FleetRollout.compare()`` win-rate verdict fed by real jaxenv episodes.
"""
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distar_tpu.envs.jaxenv import (
    EnvConfig,
    JaxMicroBattleEnv,
    ScenarioConfig,
    ScenarioGenerator,
    attack_nearest_policy,
    episode_digest,
    head_to_head,
    idle_policy,
    micro_legal_mask,
    observe,
    reset,
    step,
)
from distar_tpu.lib import actions as ACT
from distar_tpu.lib import features as F

DATA = os.path.join(os.path.dirname(__file__), "data")

TINY_ENV = EnvConfig(units_per_squad=2)
TINY_SCN = ScenarioConfig(units_per_squad=2, min_units=1, max_units=2,
                          episode_len=24, spawn_margin=30.0, spawn_spread=6.0)


def _no_op(batch=None):
    shape = () if batch is None else (batch,)
    return {
        "action_type": jnp.zeros(shape, jnp.int32),
        "delay": jnp.ones(shape, jnp.int32),
        "queued": jnp.zeros(shape, jnp.int32),
        "selected_units": jnp.zeros(shape + (F.MAX_SELECTED_UNITS_NUM,), jnp.int32),
        "target_unit": jnp.zeros(shape, jnp.int32),
        "target_location": jnp.zeros(shape, jnp.int32),
    }


# ------------------------------------------------------------------ contract
def test_host_observation_contract_parity_leaf_by_leaf():
    """The host adapter's obs match the mock-env/fake_step_data contract
    exactly: same keys, shapes, AND dtypes (including int64 entity_num)."""
    env = JaxMicroBattleEnv(TINY_ENV, TINY_SCN, seed=1)
    obs = env.reset()
    ref = F.fake_step_data(train=False, rng=np.random.default_rng(0))
    for agent in (0, 1):
        o = obs[agent]
        for section in ("spatial_info", "scalar_info", "entity_info"):
            assert sorted(o[section]) == sorted(ref[section])
            for k, rv in ref[section].items():
                v = o[section][k]
                assert v.shape == rv.shape, (section, k, v.shape, rv.shape)
                assert v.dtype == rv.dtype, (section, k, v.dtype, rv.dtype)
        assert o["entity_num"].dtype == np.int64
        assert int(o["entity_num"]) >= 1
        # the aux keys the actor's reward machinery reads (MockEnv parity)
        for k in ("game_loop", "action_result", "battle_score",
                  "opponent_battle_score"):
            assert k in o, k


def test_device_observation_schema():
    """On-device observe() emits the schema dtypes directly (entity_num is
    the one documented divergence: int32 without x64)."""
    gen = ScenarioGenerator(TINY_SCN)
    state = reset(TINY_ENV, gen.generate(jax.random.PRNGKey(0)))
    obs = observe(TINY_ENV, state, 0)
    for k, dt in F.SPATIAL_INFO.items():
        assert obs["spatial_info"][k].dtype == dt, k
        expected = (F.EFFECT_LENGTH,) if k.startswith("effect_") else F.SPATIAL_SIZE
        assert obs["spatial_info"][k].shape == expected, k
    for k, (dt, shape) in F.SCALAR_INFO.items():
        assert obs["scalar_info"][k].dtype == dt, k
        assert obs["scalar_info"][k].shape == tuple(shape), k
    for k, dt in F.ENTITY_INFO.items():
        assert obs["entity_info"][k].dtype == dt, k
        assert obs["entity_info"][k].shape == (F.MAX_ENTITY_NUM,), k
    assert obs["entity_num"].dtype == jnp.int32


def test_entity_packing_alliance_blocks():
    """Packed entities: own alive first (alliance 1), then enemies (4),
    zero padding after entity_num — the pointer-action slot contract."""
    gen = ScenarioGenerator(TINY_SCN)
    state = reset(TINY_ENV, gen.generate(jax.random.PRNGKey(2)))
    for team in (0, 1):
        obs = observe(TINY_ENV, state, team)
        n = int(obs["entity_num"])
        alliance = np.asarray(obs["entity_info"]["alliance"])
        valid = alliance[:n]
        assert set(np.unique(valid)) <= {1, 4}
        # own block strictly before enemy block
        if (valid == 1).any() and (valid == 4).any():
            assert valid.argmax() == 0 or valid[0] == 1
            first_enemy = int(np.argmax(valid == 4))
            assert (valid[first_enemy:] == 4).all()
        assert (alliance[n:] == 0).all()


def test_micro_legal_mask_covers_micro_vocabulary():
    mask = micro_legal_mask()
    assert mask.shape == (ACT.NUM_ACTIONS,)
    assert mask[0]          # no_op
    assert mask[3]          # Attack_unit
    assert mask[197]        # Move_pt
    assert mask.sum() < 16  # micro vocabulary only


# --------------------------------------------------------------- determinism
def test_determinism_golden_tiny():
    """Tier-1 drift witness: the committed golden was generated in a fresh
    process; any change to scenario generation, dynamics, or observation
    bytes flips the sha256."""
    with open(os.path.join(DATA, "jaxenv_golden_tiny.json")) as f:
        golden = json.load(f)
    c = golden["config"]
    got = episode_digest(
        seed=c["seed"],
        env_cfg=EnvConfig(units_per_squad=c["units_per_squad"]),
        scenario_cfg=ScenarioConfig(
            units_per_squad=c["units_per_squad"], min_units=c["min_units"],
            max_units=c["max_units"], episode_len=c["episode_len"],
            spawn_margin=c["spawn_margin"], spawn_spread=c["spawn_spread"]),
        max_steps=c["max_steps"])
    assert got == golden["digest"], (
        "jaxenv episode drifted from the committed golden — if the change "
        "is intentional, regenerate tests/data/jaxenv_golden_tiny.json")


@pytest.mark.slow
def test_determinism_across_two_fresh_processes():
    """Same scenario key + params => bit-identical episode in two separate
    interpreter processes (fresh jit caches, fresh PRNG plumbing)."""
    prog = (
        "import json; from distar_tpu.envs.jaxenv import episode_digest, "
        "EnvConfig, ScenarioConfig; "
        "print(json.dumps(episode_digest(seed=17, "
        "env_cfg=EnvConfig(units_per_squad=2), "
        "scenario_cfg=ScenarioConfig(units_per_squad=2, min_units=1, "
        "max_units=2, episode_len=24, spawn_margin=30.0, spawn_spread=6.0), "
        "max_steps=24)))"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.pathsep.join(
                   [os.path.dirname(os.path.dirname(__file__))]
                   + sys.path))
    runs = [subprocess.run([sys.executable, "-c", prog], env=env,
                           capture_output=True, text=True, timeout=300)
            for _ in range(2)]
    for r in runs:
        assert r.returncode == 0, r.stderr
    d1, d2 = (json.loads(r.stdout.strip().splitlines()[-1]) for r in runs)
    assert d1 == d2


def test_scenario_generator_key_determinism_and_batch():
    gen = ScenarioGenerator(TINY_SCN)
    a = gen.generate(jax.random.PRNGKey(5))
    b = gen.generate(jax.random.PRNGKey(5))
    c = gen.generate(jax.random.PRNGKey(6))
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        assert (np.asarray(la) == np.asarray(lb)).all()
    assert any((np.asarray(la) != np.asarray(lc)).any()
               for la, lc in zip(jax.tree.leaves(a), jax.tree.leaves(c)))
    batch = gen.batch(jax.random.PRNGKey(7), 5)
    assert batch.pos_home.shape == (5, TINY_SCN.units_per_squad, 2)
    assert batch.terrain.shape[0] == 5


# ------------------------------------------------------------------ dynamics
def test_episode_resolves_and_freezes_after_done():
    """Scripted-vs-scripted combat terminates; after done the state freezes
    and further steps yield zero reward (window-padding semantics)."""
    cfg = EnvConfig(units_per_squad=2)
    gen = ScenarioGenerator(ScenarioConfig(
        units_per_squad=2, min_units=2, max_units=2, episode_len=64,
        spawn_margin=50.0, spawn_spread=4.0))
    state = reset(cfg, gen.generate(jax.random.PRNGKey(1)))
    no_op = _no_op()
    stepf = jax.jit(lambda s: step(cfg, s, no_op, jnp.asarray(1)))
    done = False
    for _ in range(64):
        state, rew, done, winner = stepf(state)
        if bool(done):
            break
    assert bool(done)
    assert int(winner) in (0, 1, 2)
    frozen = jax.tree.map(np.asarray, state)
    state2, rew2, done2, _ = stepf(state)
    for a, b in zip(jax.tree.leaves(frozen), jax.tree.leaves(
            jax.tree.map(np.asarray, state2))):
        assert (a == b).all()
    assert float(np.abs(np.asarray(rew2["battle"])).sum()) == 0.0
    assert float(np.abs(np.asarray(rew2["winloss"])).sum()) == 0.0


def test_winloss_fires_exactly_once():
    cfg = EnvConfig(units_per_squad=2)
    gen = ScenarioGenerator(ScenarioConfig(
        units_per_squad=2, min_units=2, max_units=2, episode_len=48,
        spawn_margin=50.0, spawn_spread=4.0))
    state = reset(cfg, gen.generate(jax.random.PRNGKey(4)))
    no_op = _no_op()
    stepf = jax.jit(lambda s: step(cfg, s, no_op, jnp.asarray(1)))
    total = np.zeros(2)
    for _ in range(60):
        state, rew, done, winner = stepf(state)
        total += np.abs(np.asarray(rew["winloss"]))
    assert bool(state.done)
    # one +-1 pair at the terminal step (or 0 on a health-fraction draw)
    assert float(total.sum()) in (0.0, 2.0)


# -------------------------------------------------------------- host adapter
def test_host_env_round_trip_with_actions():
    env = JaxMicroBattleEnv(TINY_ENV, TINY_SCN, seed=3)
    obs = env.reset()
    n0 = int(obs[0]["entity_num"])
    su = np.zeros(F.MAX_SELECTED_UNITS_NUM, np.int64)
    su[0] = 0
    su[1] = n0  # end token
    attack = {
        "action_type": np.asarray(3, np.int64),  # Attack_unit
        "delay": np.asarray(1, np.int64),
        "queued": np.asarray(0, np.int64),
        "selected_units": su,
        "target_unit": np.asarray(max(n0 - 1, 0), np.int64),
        "target_location": np.asarray(0, np.int64),
    }
    for t in range(TINY_SCN.episode_len):
        obs, rewards, done, info = env.step({0: attack})
        assert set(rewards) == {0, 1}
        if done:
            assert "winner" in info
            break
    assert done
    # rewards are zero-sum at termination (or a draw)
    assert rewards[0] == -rewards[1]


# ------------------------------------------------------------------ win rate
def test_head_to_head_separates_scripted_policies():
    """The win-rate leg's mock engines: attack-nearest must beat idle on the
    SAME fixed scenario keys from both the home and the away side.

    Composition-fair (mirror_types), open terrain, and a timeout long enough
    to let engagements resolve — the evaluation is bit-deterministic per
    seed, so the margins asserted here are pinned, not statistical."""
    ec = EnvConfig(units_per_squad=2)
    sc = ScenarioConfig(units_per_squad=2, min_units=2, max_units=2,
                        episode_len=160, spawn_margin=50.0, spawn_spread=4.0,
                        mirror_types=True, blocked_frac=0.0)
    atk_home = head_to_head(attack_nearest_policy(), idle_policy(),
                            episodes=8, seed=5, env_cfg=ec, scenario_cfg=sc)
    atk_away = head_to_head(idle_policy(), attack_nearest_policy(),
                            episodes=8, seed=5, env_cfg=ec, scenario_cfg=sc)
    assert atk_home["episodes"] == 8
    assert atk_home["wins"] + atk_home["losses"] + atk_home["draws"] == 8
    # attacker advantage from both sides of the same scenario set
    assert atk_home["win_rate"] > 0.5
    assert atk_away["win_rate"] < 0.5
    # determinism: the evaluation is a pure function of the key set —
    # every field but the wall-clock duration_s is bit-identical
    again = head_to_head(attack_nearest_policy(), idle_policy(),
                         episodes=8, seed=5, env_cfg=ec, scenario_cfg=sc)

    def outcome(res):
        return {k: v for k, v in res.items() if k != "duration_s"}

    assert outcome(again) == outcome(atk_home)


def test_fleet_compare_win_rate_verdict_from_real_episodes():
    """Satellite 1 acceptance: ``FleetRollout.compare()`` carries a win_rate
    column computed from REAL jaxenv episodes (mock engines = the scripted
    policies; mock gateways = a patched fleet_status), and ``min_win_rate``
    gates the promote verdict."""
    from distar_tpu.serve.fleet import FleetRollout, GatewayMap

    ctl = FleetRollout(GatewayMap(["127.0.0.1:9001", "127.0.0.1:9002"]),
                       timeout_s=1.0)
    healthy = {"requests": {"ok": 10.0}, "shed_rate": 0.0,
               "latency_s": {"p99": 0.01}, "sessions": {"num_slots": 4}}
    ctl.fleet_status = lambda: {"127.0.0.1:9001": dict(healthy),
                                "127.0.0.1:9002": dict(healthy)}
    ec = EnvConfig(units_per_squad=2)
    sc = ScenarioConfig(units_per_squad=2, min_units=2, max_units=2,
                        episode_len=160, spawn_margin=50.0, spawn_spread=4.0,
                        mirror_types=True, blocked_frac=0.0)

    def strong_canary():
        return head_to_head(attack_nearest_policy(), idle_policy(),
                            episodes=8, seed=5, env_cfg=ec, scenario_cfg=sc)

    def weak_canary():
        return head_to_head(idle_policy(), attack_nearest_policy(),
                            episodes=8, seed=5, env_cfg=ec, scenario_cfg=sc)

    good = ctl.compare(["127.0.0.1:9001"], win_rate_fn=strong_canary,
                       min_win_rate=0.5)
    assert good["win_rate"]["episodes"] == 8
    assert good["win_rate"]["win_rate"] > 0.5
    assert good["verdict"]["promote"] is True, good["verdict"]

    bad = ctl.compare(["127.0.0.1:9001"], win_rate_fn=weak_canary,
                      min_win_rate=0.5)
    assert bad["verdict"]["promote"] is False
    assert any("win_rate" in r for r in bad["verdict"]["reasons"])
    # a failing win-rate verdict gates promote without touching the fleet
    gated = ctl.promote("v2", verdict=bad)
    assert gated["ok"] is False and gated["outcome"] == "compare_gated"

    # no head-to-head supplied but the gate requested -> explicit reason
    missing = ctl.compare(["127.0.0.1:9001"], min_win_rate=0.5)
    assert any("no head-to-head" in r for r in missing["verdict"]["reasons"])
