"""Observer rendering (bin/observe.py): the curses-free CameraView behind
--interactive (camera pan/zoom clamping, viewport glyph rendering, the
unit-inspect overlay — role of the reference renderer_human.py camera and
select/overlay panels), plus the headless ascii/PPM paths and the
decode_terrain dimension guard (ADVICE r4)."""
import numpy as np

from distar_tpu.bin.observe import (
    CameraView, decode_terrain, hud_line, obs_to_grid, render_ascii, render_ppm,
)
from distar_tpu.envs.dummy_obs import NS, build_dummy_obs, make_unit


def _grid(map_x=120, map_y=120):
    units = [
        make_unit(1, 86, x=30.0, y=40.0),
        make_unit(2, 48, alliance=4, x=90.0, y=100.0),
        make_unit(3, 341, alliance=3, x=10.0, y=10.0),
    ]
    obs = build_dummy_obs(units=units, map_y=map_y, map_x=map_x)
    grid = obs_to_grid(obs.observation.raw_data, (map_x, map_y), 1)
    return obs, grid


def test_camera_starts_fit_and_pan_clamps():
    view = CameraView((120, 120), cols=60, rows=20)
    x0, y0, x1, y1 = view.world_rect()
    assert x0 <= 0 and y0 <= 0 and x1 >= 120 and y1 >= 120  # whole map visible
    for _ in range(100):
        view.pan(10, 0)
    assert view.cx == 120  # clamped at the map edge
    for _ in range(100):
        view.pan(0, 10)
    assert view.cy == 0  # pan down = toward smaller world y


def test_zoom_bounds():
    view = CameraView((120, 120), cols=60, rows=20)
    fit_scale = view.scale
    view.zoom(100.0)
    assert view.scale == fit_scale  # cannot zoom out past whole-map fit
    for _ in range(10):
        view.zoom(0.5)
    assert view.scale == CameraView.MIN_SCALE


def test_render_marks_units_and_cursor():
    obs, grid = _grid()
    view = CameraView((120, 120), cols=60, rows=20)
    rows = view.render(grid)
    assert len(rows) == 20 and all(len(r) == 60 for r in rows)
    joined = "\n".join(rows)
    assert "o" in joined and "x" in joined and "'" in joined
    assert joined.count("+") == 1  # exactly one cursor glyph


def test_zoomed_camera_sees_only_its_rect():
    obs, grid = _grid()
    view = CameraView((120, 120), cols=60, rows=20)
    view.scale = CameraView.MIN_SCALE  # tight zoom ...
    view.cx, view.cy = 30.0, 40.0      # ... on the own hatchery
    joined = "\n".join(view.render(grid))
    assert "o" in joined
    assert "x" not in joined  # the enemy at (90,100) is outside the rect


def test_inspect_returns_units_under_cursor():
    obs, _ = _grid()
    view = CameraView((120, 120), cols=60, rows=20)
    view.scale = 1.0
    # center the view so the cursor's half-open char cell [30,31)x[39,41)
    # covers the hatchery at (30,40)
    view.cx, view.cy = 30.0, 41.0
    hits = view.inspect(obs.observation.raw_data)
    assert hits and hits[0]["unit_type"] == 86 and hits[0]["alliance"] == 1
    assert hits[0]["health"] == 50.0
    # move the cursor to a corner: empty ground there
    view.cur_col, view.cur_row = 0, 0
    assert view.inspect(obs.observation.raw_data) == []


def test_hud_line_contents():
    obs, grid = _grid()
    view = CameraView((120, 120))
    line = hud_line(view, 777, grid, paused=True)
    assert "loop 777" in line and "[PAUSED]" in line and "own 1" in line


def test_ascii_and_ppm_roundtrip(tmp_path):
    obs, grid = _grid()
    art = render_ascii(grid)
    assert "o" in art and "x" in art
    path = str(tmp_path / "f.ppm")
    render_ppm(grid, path)
    blob = open(path, "rb").read()
    assert blob.startswith(b"P6 120 120 255\n")
    assert len(blob) == len(b"P6 120 120 255\n") + 120 * 120 * 3


def test_decode_terrain_dimension_guard():
    # rows >= H but cols < W must fall back to zeros, not a ragged slice
    W, H = 64, 32
    img = NS(size=NS(x=48, y=40), bits_per_pixel=8,
             data=bytes(np.zeros(48 * 40, np.uint8)))
    gi = NS(start_raw=NS(terrain_height=img))
    out = decode_terrain(gi, (W, H))
    assert out.shape == (H, W)
    assert not out.any()
