"""Async env worker pool tests: correctness (epoch-stale drops, error
surfacing) and the round-2 acceptance criterion — throughput under +-3x
step-time jitter within ~15% of the uniform-latency case (a lockstep fleet
would stall on the slowest env every cycle; reference behavior at
distar/actor/actor.py:268-299).
"""
import time

import numpy as np
import pytest

from distar_tpu.actor.env_pool import RESET, STEP, EnvWorkerPool

from conftest import SMALL_MODEL



class SleepEnv:
    """Contract-shaped env whose step blocks like a real SC2 process."""

    def __init__(self, delays):
        self._delays = delays
        self._i = 0
        self.steps = 0

    def reset(self):
        return {0: {"t": 0}, 1: {"t": 0}}

    def step(self, actions):
        time.sleep(self._delays[self._i % len(self._delays)])
        self._i += 1
        self.steps += 1
        return {0: {"t": self._i}, 1: {"t": self._i}}, {0: 0.0, 1: 0.0}, False, {}

    def close(self):
        pass


def drive(pool: EnvWorkerPool, seconds: float) -> int:
    """Actor-shaped loop: act on whatever is ready, resubmit immediately."""
    for e in range(pool.num):
        pool.reset(e)
    deadline = time.monotonic() + seconds
    steps = 0
    while time.monotonic() < deadline:
        for e, kind, payload in pool.ready(timeout=0.2):
            if kind == STEP:
                steps += 1
            pool.submit(e, {})
    return steps


def test_jitter_throughput_matches_uniform():
    n_env, mean = 4, 0.02
    rng = np.random.default_rng(0)
    uniform_pool = EnvWorkerPool([lambda: SleepEnv([mean])] * n_env)
    # +-3x jitter around the same mean service time
    jitter = list(rng.uniform(mean / 3, 3 * mean, 64))
    jitter = [d * mean / np.mean(jitter) for d in jitter]
    jitter_pool = EnvWorkerPool(
        [lambda j=i: SleepEnv(jitter[j * 16:] + jitter[: j * 16]) for i in range(n_env)]
    )
    try:
        uniform_steps = drive(uniform_pool, 2.0)
        jitter_steps = drive(jitter_pool, 2.0)
    finally:
        uniform_pool.close()
        jitter_pool.close()
    assert uniform_steps > 0
    # each env streams independently: same mean latency => same throughput
    assert jitter_steps >= 0.85 * uniform_steps, (jitter_steps, uniform_steps)


def test_epoch_reset_drops_stale_results():
    class SlowEnv(SleepEnv):
        def __init__(self):
            super().__init__([0.2])

    pool = EnvWorkerPool([SlowEnv])
    try:
        pool.reset(0)
        out = pool.ready(timeout=2.0)
        assert out and out[0][1] == RESET
        pool.submit(0, {})  # slow step in flight...
        time.sleep(0.01)
        pool.reset(0)  # ...abandoned by a league reset
        kinds = []
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            for _, kind, _ in pool.ready(timeout=0.2):
                kinds.append(kind)
            if RESET in kinds:
                break
        # the stale STEP result never surfaces; the fresh RESET does
        assert kinds == [RESET]
    finally:
        pool.close()


def test_worker_errors_surface():
    class BoomEnv:
        def reset(self):
            raise ValueError("boom")

        def close(self):
            pass

    pool = EnvWorkerPool([BoomEnv])
    try:
        pool.reset(0)
        with pytest.raises(RuntimeError, match="env worker 0 failed"):
            pool.ready(timeout=2.0)
    finally:
        pool.close()


def test_actor_samples_z_from_library(tmp_path):
    """The job's z_path routes to a real ZLibrary keyed map/matchup
    (reference agent.py:176-243); missing/unknown libraries fall back to the
    synthetic target."""
    import json

    from distar_tpu.actor import Actor

    lib = {
        "KairosJunction": {
            "zerg": {"22": [[[5, 9, 12], [3, 8], [100, 200, 300], 7000]]}
        }
    }
    path = tmp_path / "z.json"
    path.write_text(json.dumps(lib))

    actor = Actor.__new__(Actor)  # no model init needed for _sample_z
    from distar_tpu.utils import Config

    actor.cfg = Config({"z_dirs": [str(tmp_path)], "fake_reward_prob": 1.0, "seed": 0})
    actor._rng = np.random.default_rng(0)

    job = {
        "z_path": ["z.json", "none"],
        "frac_ids": [1, 1],
        "env_info": {"map_name": "KairosJunction"},
    }
    z0 = actor._sample_z(0, job)
    assert z0["beginning_order"] == [5, 9, 12]
    assert z0["cumulative_stat"] == [3, 8]
    assert z0["bo_norm"] == 3

    # side 1 has no library -> synthetic fallback with the same schema
    z1 = actor._sample_z(1, job)
    assert "beginning_order" in z1 and "cumulative_stat" in z1

    # unknown map falls back to an available key, not a crash
    job2 = dict(job, env_info={"map_name": "NoSuchMap"})
    assert actor._sample_z(0, job2)["beginning_order"] == [5, 9, 12]

    # a known born location pins the exact entry; an unknown one falls back
    z_exact = actor._sample_z(0, job, born_location=22)
    assert z_exact["beginning_order"] == [5, 9, 12]
    assert actor._sample_z(0, job, born_location=999)["beginning_order"] == [5, 9, 12]


def test_extracted_z_libraries_load_and_sample():
    """The shipped Z data (extracted reference strategy statistics,
    tools/extract_z_data.py) loads through ZLibrary and samples exact
    map/matchup/born-location keys."""
    import os

    from distar_tpu.lib import features as F
    from distar_tpu.lib.z_library import ZLibrary

    z_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "distar_tpu", "data", "z_libraries",
    )
    lib = ZLibrary(os.path.join(z_dir, "3map.json"))
    assert "__provenance__" not in lib.data
    maps = lib.keys()
    assert "KingsCove" in maps and "zerg" in maps["KingsCove"]
    born = maps["KingsCove"]["zerg"][0]
    z = lib.sample("KingsCove", "zerg", int(born))
    assert len(z["beginning_order"]) == F.BEGINNING_ORDER_LENGTH
    assert z["z_loop"] > 0
    assert all(isinstance(x, int) for x in z["cumulative_stat"])
    # every shipped library parses and yields a sample
    for fname in os.listdir(z_dir):
        l = ZLibrary(os.path.join(z_dir, fname))
        assert l.sample_any("KingsCove", mix_race="zerg") is not None, fname


def test_agent_entity_cap_slices_obs():
    """actor.max_entities: the agent slices entity arrays in pre_process so
    the model, sampled indices, end-token detection, and stored trajectory
    data all agree on the capped entity set."""
    from distar_tpu.actor.agent import Agent
    from distar_tpu.lib import features as F

    rng = np.random.default_rng(0)
    obs = F.fake_step_data(train=False, rng=rng)
    obs["entity_num"] = np.asarray(400, np.int64)
    ag = Agent("P0", traj_len=2, seed=0, max_entities=256)
    ag.reset()
    ag.pre_process(obs)
    capped = ag._observation
    assert capped["entity_num"] == 256
    for v in capped["entity_info"].values():
        assert v.shape[0] == 256

    # overflow frames: the model's end token (== capped entity_num) would
    # alias a REAL tag index in the env's uncapped list; post_process must
    # strip it from the env action (trajectory output keeps the raw indices)
    out = {"action_info": {
        "action_type": np.asarray(0), "delay": np.asarray(1),
        "queued": np.asarray(0),
        "selected_units": np.asarray([3, 256, 0]),  # unit, END, junk
        "target_unit": np.asarray(0), "target_location": np.asarray(0),
    }}
    act = ag.post_process(out)
    assert act["selected_units"][0] == 3
    assert act["selected_units"][1] > 10 ** 9  # end token out of tag range
    assert (ag._output["action_info"]["selected_units"] == [3, 256, 0]).all()

    # below the cap: untouched values, no end-token remap
    obs2 = F.fake_step_data(train=False, rng=rng)
    obs2["entity_num"] = np.asarray(31, np.int64)
    ag.pre_process(obs2)
    assert ag._observation["entity_num"] == 31
    act2 = ag.post_process(out)
    assert act2["selected_units"][1] == 256  # no aliasing below the cap


def test_actor_job_with_entity_cap():
    """A model-vs-scripted job on the mock env completes with the inference
    obs capped to 256 entities — env_num=2 so inactive-slot FILLER obs mix
    into the batch and must carry the bucket shape too."""
    from distar_tpu.actor import Actor
    from distar_tpu.envs import MockEnv

    actor = Actor(
        cfg={"actor": {"env_num": 2, "traj_len": 2, "seed": 3,
                       "max_entities": 256}},
        model_cfg=SMALL_MODEL,
        env_fn=lambda: MockEnv(episode_game_loops=300, seed=9),
    )
    job = {
        "player_ids": ["MP0", "S"],
        "pipelines": ["default", "scripted.idle"],
        "send_data_players": [],
        "update_players": [],
        "teacher_player_ids": ["T", "none"],
        "branch": "eval_test",
        "env_info": {"map_name": "mock"},
    }
    results = actor.run_job(episodes=1, job=job)
    assert len(results) >= 1 and results[0]["0"]["player_id"] == "MP0"
