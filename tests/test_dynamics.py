"""Training-dynamics observatory (obs/dynamics.py + tools/stepreplay.py).

Host-side units run without building a model: the in-jit tree is checked
against hand-computed norms on toy pytrees, and the monitor (freq gating,
debounce, black-box capture, rulebook wiring) is driven with plain float
dicts — proving the healthy path needs no device access at all. The slow
integration builds ONE real SL learner and reuses its compile for the
grad-clip end-to-end, the single-device_get audit, and the poison ->
bundle -> deterministic replay chain."""
import math
import os
import sys

import numpy as np
import pytest

from distar_tpu.obs import MetricsRegistry
from distar_tpu.obs.dynamics import (
    DYNAMICS_DEFAULTS,
    DynamicsMonitor,
    DynamicsSpec,
    config_digest,
    dynamics_tree,
    first_nonfinite,
    list_bundles,
    load_bundle,
    split_tree,
    tree_spec,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from conftest import SMALL_MODEL  # noqa: E402


# ------------------------------------------------------------- in-jit tree
def test_dynamics_tree_hand_computed():
    import jax.numpy as jnp

    params = {"params": {"dec": {"b": jnp.asarray([0.5])},
                         "enc": {"w": jnp.asarray([3.0, 4.0])}}}
    grads = {"params": {"dec": {"b": jnp.asarray([1.0])},
                        "enc": {"w": jnp.asarray([6.0, 8.0])}}}
    updates = {"params": {"dec": {"b": jnp.asarray([0.25])},
                          "enc": {"w": jnp.asarray([0.3, 0.4])}}}
    batch = {"x": jnp.asarray([1.0, 2.0]), "i": jnp.asarray([1, 2])}
    gn_total = math.sqrt(1.0 + 100.0)
    spec = DynamicsSpec(clip_type="norm", clip_threshold=5.0)

    out = {k: float(v) for k, v in dynamics_tree(
        params, grads, updates=updates, batch=batch, spec=spec).items()}

    assert out["dyn/param_norm/enc"] == pytest.approx(5.0)
    assert out["dyn/param_norm/dec"] == pytest.approx(0.5)
    assert out["dyn/param_norm/total"] == pytest.approx(math.sqrt(25.25))
    assert out["dyn/grad_norm/enc"] == pytest.approx(10.0)
    assert out["dyn/grad_norm/total"] == pytest.approx(gn_total)
    assert out["dyn/update_ratio/enc"] == pytest.approx(0.5 / 5.0)
    assert out["dyn/update_ratio/dec"] == pytest.approx(0.25 / 0.5)
    assert out["dyn/update_ratio/total"] == pytest.approx(
        math.sqrt(0.25 + 0.0625) / math.sqrt(25.25))
    # clean trees: every census is exactly zero
    assert out["dyn/nonfinite_grads/total"] == 0.0
    assert out["dyn/nonfinite_params/total"] == 0.0
    assert out["dyn/nonfinite_batch/total"] == 0.0
    # int-only batch keys can't be non-finite: no row at all
    assert "dyn/nonfinite_batch/i" not in out
    # norm clip vs threshold 5: fraction removed = 1 - 5/||g||
    assert out["dyn/clip_fraction"] == pytest.approx(1.0 - 5.0 / gn_total)
    assert out["dyn/clip_active"] == 1.0

    fams = split_tree(out)
    assert fams["param_norm"]["enc"] == pytest.approx(5.0)
    assert set(fams) >= {"param_norm", "grad_norm", "update_ratio",
                         "nonfinite_grads", "clip_fraction"}


def test_dynamics_tree_census_and_provenance_priority():
    import jax.numpy as jnp

    nan, inf = float("nan"), float("inf")
    params = {"dec": {"b": jnp.asarray([nan])}, "enc": {"w": jnp.asarray([1.0])}}
    grads = {"dec": {"b": jnp.asarray([nan])},
             "enc": {"w": jnp.asarray([nan])}}  # blast radius: both modules
    batch = {"x": jnp.asarray([inf, 1.0])}
    out = {k: float(v) for k, v in
           dynamics_tree(params, grads, batch=batch).items()}
    assert out["dyn/nonfinite_grads/total"] == 2.0
    assert out["dyn/nonfinite_params/dec"] == 1.0
    assert out["dyn/nonfinite_batch/x"] == 1.0

    # narrowest origin wins: batch > params > grads
    assert first_nonfinite(out) == {"origin": "batch", "module": "x",
                                    "all": ["x"]}
    no_batch = {k: v for k, v in out.items()
                if not k.startswith("dyn/nonfinite_batch/")}
    assert first_nonfinite(no_batch)["origin"] == "params"
    assert first_nonfinite(no_batch)["module"] == "dec"
    only_grads = {k: v for k, v in no_batch.items()
                  if not k.startswith("dyn/nonfinite_params/")}
    prov = first_nonfinite(only_grads)
    assert prov["origin"] == "grads" and prov["all"] == ["dec", "enc"]
    assert first_nonfinite({"dyn/nonfinite_grads/enc": 0.0}) is None


def test_tree_spec_static_gating():
    assert tree_spec({"enabled": False}, {"type": "norm"}) is None
    spec = tree_spec({}, {"type": "norm", "threshold": 2.5})
    assert spec == DynamicsSpec(clip_type="norm", clip_threshold=2.5)
    assert tree_spec(None, None).clip_type == "none"


# ----------------------------------------------------------------- monitor
class _FakeIter:
    def __init__(self):
        self.val = 0


class _FakeLearner:
    """The attribute surface DynamicsMonitor touches, no jax anywhere."""

    def __init__(self, cfg=None):
        self.name = "sllearner"
        self.last_iter = _FakeIter()
        self.cfg = cfg or {"learner": {"batch_size": 2}}
        self.init_prng_seed = 7
        self.state = {"params": {"enc": np.ones((2,), np.float32)}}


def _healthy_log(gn=1.0):
    return {"total_loss": 0.5, "dyn/grad_norm/total": gn,
            "dyn/grad_norm/enc": gn, "dyn/nonfinite_grads/total": 0.0,
            "dyn/nonfinite_params/total": 0.0}


def test_monitor_freq_gates_export_not_detection(monkeypatch):
    """every_n gates gauge EXPORT only; anomaly steps force-publish; the
    healthy path performs no device access (jax.device_get trapped)."""
    import jax

    def _trap(*a, **k):  # pragma: no cover - failure path
        raise AssertionError("on_step touched the device on the healthy path")

    monkeypatch.setattr(jax, "device_get", _trap)
    reg = MetricsRegistry()
    mon = DynamicsMonitor({"every_n": 3, "blackbox": False}, registry=reg)
    learner = _FakeLearner()
    gauge = reg.gauge("distar_train_grad_norm",
                      "per-module gradient global-norm", module="total")

    assert mon.on_step(learner, _healthy_log(gn=1.0)) == set()
    assert gauge.value == 1.0  # step 0: sampled
    learner.last_iter.val = 1
    assert mon.on_step(learner, _healthy_log(gn=2.0)) == set()
    assert gauge.value == 1.0  # step 1: gated, export skipped
    learner.last_iter.val = 2
    bad = _healthy_log(gn=3.0)
    bad["total_loss"] = float("nan")
    assert mon.on_step(learner, bad) == {"loss_nonfinite"}
    assert gauge.value == 3.0  # anomaly force-publishes off-sample
    # detection ran on the gated step too: EMA kept moving every step
    assert mon.steps_seen == 3 and mon.ema is not None


def test_monitor_disabled_is_inert():
    reg = MetricsRegistry()
    mon = DynamicsMonitor({"enabled": False}, registry=reg)
    learner = _FakeLearner()
    log = _healthy_log()
    log["total_loss"] = float("nan")
    assert mon.on_step(learner, log) == set()
    assert mon.steps_seen == 0 and "distar_train_grad_norm" not in str(
        reg.snapshot())


def test_monitor_explosion_needs_warmup_and_ema():
    reg = MetricsRegistry()
    mon = DynamicsMonitor({"every_n": 1, "blackbox": False,
                           "explosion_warmup": 5, "explosion_factor": 10.0},
                          registry=reg)
    learner = _FakeLearner()
    for i in range(5):
        learner.last_iter.val = i
        assert mon.on_step(learner, _healthy_log(gn=1.0)) == set()
    learner.last_iter.val = 5
    assert mon.on_step(learner, _healthy_log(gn=50.0)) == {"grad_explosion"}
    assert mon.last_anomaly_step == 5
    snap = reg.snapshot()
    assert snap["distar_train_last_anomaly_step"] == 5.0
    assert snap[
        'distar_train_anomalies_total{reason=grad_explosion}'] == 1.0


def test_monitor_debounce_capture_and_bundle_roundtrip(tmp_path):
    from distar_tpu.parallel.grad_clip import _EMAState

    reg = MetricsRegistry()
    mon = DynamicsMonitor({"every_n": 1, "blackbox_cap": 2, "clear_n": 2},
                          registry=reg, blackbox_dir=str(tmp_path))
    learner = _FakeLearner(cfg={"learner": {"batch_size": 2,
                                            "dynamics": {"every_n": 1}}})
    # a NamedTuple in the state must survive the serializer round-trip
    # (optax opt_states are NamedTuples all the way down)
    learner.state = {"params": {"enc": np.ones((2,), np.float32)},
                     "opt_state": _EMAState(np.zeros(()), np.zeros((), np.int32),
                                            np.zeros(()))}
    batch = {"x": np.asarray([1.0, float("nan")], np.float32),
             "_on_device": True}
    bad = _healthy_log()
    bad.update({"dyn/nonfinite_grads/total": 3.0, "dyn/nonfinite_grads/enc": 3.0,
                "dyn/nonfinite_batch/x": 1.0, "dyn/nonfinite_batch/total": 1.0})

    learner.last_iter.val = 4
    assert mon.on_step(learner, bad, batch) == {"grad_nonfinite"}
    learner.last_iter.val = 5
    mon.on_step(learner, bad, batch)  # same class, still active: debounced
    bundles = list_bundles(str(tmp_path))
    assert len(bundles) == 1 and bundles[0]["step"] == 4
    assert bundles[0]["reason"] == "grad_nonfinite"

    for i in range(6, 8):  # clear_n=2 clean steps re-arm the class
        learner.last_iter.val = i
        assert mon.on_step(learner, _healthy_log(), batch) == set()
    learner.last_iter.val = 8
    mon.on_step(learner, bad, batch)
    assert len(list_bundles(str(tmp_path))) == 2
    learner.last_iter.val = 11
    for i in range(9, 11):
        learner.last_iter.val = i
        mon.on_step(learner, _healthy_log(), batch)
    learner.last_iter.val = 11
    mon.on_step(learner, bad, batch)  # cap=2: third anomaly writes nothing
    assert len(list_bundles(str(tmp_path))) == 2
    assert reg.snapshot()["distar_train_blackbox_bundles_total"] == 2.0

    bundle = load_bundle(list_bundles(str(tmp_path))[0]["path"])
    assert bundle["schema"] == "distar.blackbox.v1"
    assert bundle["step"] == 4 and bundle["reasons"] == ["grad_nonfinite"]
    assert bundle["learner"] == "sllearner" and bundle["prng_seed"] == 7
    # provenance: the batch census outranks the grads blast radius
    assert bundle["provenance"] == {"origin": "batch", "module": "x",
                                    "all": ["x"]}
    np.testing.assert_array_equal(bundle["batch"]["x"], batch["x"])
    assert bundle["batch"]["_on_device"] is True
    assert isinstance(bundle["state"]["opt_state"], _EMAState)
    assert bundle["config_digest"] == config_digest(bundle["config"])
    assert bundle["diagnostics"]["dyn/nonfinite_grads/total"] == 3.0


def test_capture_failure_never_raises(tmp_path):
    """Forensics must not kill the run it studies: an unwritable blackbox
    dir degrades to a logged error, not an exception."""
    blocked = tmp_path / "file"
    blocked.write_text("not a dir")
    mon = DynamicsMonitor({"every_n": 1}, registry=MetricsRegistry(),
                          blackbox_dir=str(blocked))
    bad = _healthy_log()
    bad["total_loss"] = float("nan")
    assert mon.on_step(_FakeLearner(), bad, {"x": np.ones(2)}) == {
        "loss_nonfinite"}
    assert mon.bundles_written == 0 and mon.last_bundle_path is None


def test_rulebook_fires_once_with_bundle_exemplar(tmp_path):
    """The e2e alert chain minus the model: anomaly -> capture (exemplar
    noted under the rule-watched family) -> sampler -> evaluator firing
    exactly once, carrying blackbox:<bundle> in the firing event."""
    from distar_tpu.obs import FleetHealth, default_rulebook

    reg = MetricsRegistry()
    mon = DynamicsMonitor({"every_n": 1}, registry=reg,
                          blackbox_dir=str(tmp_path))
    fh = FleetHealth(rules=default_rulebook(roles=("learner",)),
                     registry=reg)  # driven manually, never started
    learner = _FakeLearner()

    bad = _healthy_log()
    bad.update({"dyn/nonfinite_grads/total": 2.0,
                "dyn/nonfinite_grads/enc": 2.0})
    mon.on_step(learner, bad, {"x": np.ones(2, np.float32)})
    fh.sampler.sample_once()
    fh.evaluator.evaluate_once()
    alerts = fh.evaluator.alerts()
    rule = alerts["rules"]["learner_grad_nonfinite"]
    assert rule["state"] == "firing" and rule["fired_count"] == 1
    firing = [e for e in alerts["history"]
              if e["rule"] == "learner_grad_nonfinite"
              and e["state"] == "firing"]
    bundle_id = list_bundles(str(tmp_path))[0]["id"]
    assert firing[0].get("exemplar_trace_id") == f"blackbox:{bundle_id}"

    # recovery + debounce: clean steps clear the alert, no second firing
    for i in range(1, 5):
        learner.last_iter.val = i
        mon.on_step(learner, _healthy_log())
        fh.sampler.sample_once()
        fh.evaluator.evaluate_once()
    alerts = fh.evaluator.alerts()
    assert alerts["rules"]["learner_grad_nonfinite"]["fired_count"] == 1
    assert "learner_grad_nonfinite" not in alerts["firing"]


def test_defaults_are_registered_in_learner_config():
    from distar_tpu.learner.base_learner import DEFAULT_LEARNER_CONFIG

    dyn = DEFAULT_LEARNER_CONFIG["learner"]["dynamics"]
    assert set(DYNAMICS_DEFAULTS) >= set(dyn)
    assert dyn["every_n"] == DYNAMICS_DEFAULTS["every_n"]


# -------------------------------------------------- slow: real-learner e2e
@pytest.mark.slow
def test_sl_learner_dynamics_end_to_end(tmp_path, monkeypatch):
    """One compile, four claims: (1) grad_clip norm is live end-to-end and
    reports clip activation through the tree; (2) the healthy step performs
    EXACTLY one batched device_get; (3) a poisoned param yields one bundle
    whose provenance names the module; (4) tools/stepreplay reproduces the
    anomalous step bit-identically from the bundle alone."""
    import jax

    import stepreplay
    from distar_tpu.learner import SLLearner
    from distar_tpu.resilience.chaos import ChaosInjector

    monkeypatch.setenv("DISTAR_EXPERIMENTS_ROOT", str(tmp_path))
    learner = SLLearner({
        "common": {"save_path": str(tmp_path / "exp")},
        "learner": {
            "batch_size": 2, "unroll_len": 2,
            "save_freq": 10 ** 6, "log_freq": 1,
            # threshold far below a random-init grad norm: clip ACTIVE
            "grad_clip": {"type": "norm", "threshold": 0.05},
            "dynamics": {"every_n": 1, "blackbox_cap": 2},
        },
        "model": SMALL_MODEL,
    })

    calls = []
    real_device_get = jax.device_get
    monkeypatch.setattr(
        jax, "device_get",
        lambda *a, **k: calls.append(1) or real_device_get(*a, **k))
    learner.run(max_iterations=2)
    monkeypatch.setattr(jax, "device_get", real_device_get)
    # (2): the log fetch is the step's ONLY device_get — the dynamics tree
    # rides it instead of adding per-leaf syncs
    assert len(calls) == 2, f"expected 1 batched fetch/step, saw {calls}"

    # log_buffer is folded into variable_record + cleared each iter by the
    # log-reduce hook; read the per-iter record instead
    log = {k: learner.variable_record.get(k).val
           for k in ("dyn/grad_norm/total", "dyn/clip_active",
                     "dyn/clip_fraction")}
    gn = float(log["dyn/grad_norm/total"])
    assert gn > 0.05  # random init: well past the tiny threshold
    assert float(log["dyn/clip_active"]) == 1.0
    assert float(log["dyn/clip_fraction"]) == pytest.approx(
        1.0 - 0.05 / gn, rel=1e-5)
    from distar_tpu.obs import get_registry
    snap = get_registry().snapshot()
    assert snap["distar_train_grad_clip_fraction"] == pytest.approx(
        1.0 - 0.05 / gn, rel=1e-5)
    assert snap["distar_train_grad_clip_active"] == 1.0

    inj = ChaosInjector()
    inj.poison_module(learner, "core_lstm", n=1)
    learner.run(max_iterations=3)
    inj.restore()
    bundles = list_bundles(str(tmp_path / "exp" / "blackbox"))
    assert len(bundles) == 1
    bundle = load_bundle(bundles[0]["path"])
    assert bundle["provenance"]["origin"] == "params"
    assert bundle["provenance"]["module"] == "core_lstm"

    verdict = stepreplay.replay(bundle, params_from="bundle", runs=2)
    assert verdict["deterministic"] is True
    assert verdict["nonfinite_reproduced"] is True
    assert verdict["provenance_confirmed"] is True
    assert verdict["ok"] is True and verdict["config_digest_drift"] is False
