"""Actor-learner distillation tier (docs/serving.md model tiering).

Covers the distillation contracts end to end: the masked per-head KL loss
against hand-computed values (selected-units mask edges included), the
student learner's training signal + ``distar_distill_*`` gauges,
checkpoint ROLE isolation (teacher resume can never pick a student
generation), the ``distill_divergence_runaway`` health rule's trend
detector, the committed DISTILL artifact's honesty flags, and the first
real consumer of canary compare: a student checkpoint rolled through a
canary split -> ``compare()`` verdict -> gated ``promote()`` over a
player-multiplexed (teacher + student behind one address) gateway fleet
with exact per-client version streams and zero in-flight loss.
"""
import itertools
import json
import math
import os
import time

import numpy as np
import pytest

from distar_tpu.losses import DistillLossConfig, compute_distill_loss

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SMOKE_MODEL = {
    "encoder": {
        "entity": {"layer_num": 1, "hidden_dim": 32, "output_dim": 16, "head_dim": 8},
        "spatial": {"down_channels": [4, 4, 8], "project_dim": 4, "resblock_num": 1, "fc_dim": 16},
        "scatter": {"output_dim": 4},
        "core_lstm": {"hidden_size": 32, "num_layers": 1},
    },
    "policy": {
        "action_type_head": {"res_dim": 16, "res_num": 1, "gate_dim": 32},
        "delay_head": {"decode_dim": 16},
        "queued_head": {"decode_dim": 16},
        "selected_units_head": {"func_dim": 16},
        "target_unit_head": {"func_dim": 16},
        "location_head": {"res_dim": 8, "res_num": 1, "upsample_dims": [4, 4, 1], "map_skip_dim": 8},
    },
    "value": {"res_dim": 8, "res_num": 1},
}


# ----------------------------------------------------------------- the loss
def _loss_inputs(T=1, B=1, S=2, K=3):
    """Minimal schema-complete distill-loss inputs: identical student and
    teacher logits everywhere (KL == 0 baseline) that individual tests
    perturb head by head."""
    shapes = {
        "action_type": (K,), "delay": (K,), "queued": (2,),
        "selected_units": (S, K + 1), "target_unit": (K,),
        "target_location": (K,),
    }
    teacher = {k: np.zeros((T, B) + s, np.float32) for k, s in shapes.items()}
    student = {k: np.zeros((T, B) + s, np.float32) for k, s in shapes.items()}
    masks = {
        "actions_mask": {k: np.ones((T, B), np.float32) for k in shapes},
        "selected_units_mask": np.ones((T, B, S), np.float32),
        "step_mask": np.ones((T, B), np.float32),
    }
    return {"student_logit": student, "teacher_logit": teacher, "mask": masks}


def _kl(p_logits, q_logits):
    """Reference forward KL over the last axis, computed independently."""
    p_logits = np.asarray(p_logits, np.float64)
    q_logits = np.asarray(q_logits, np.float64)
    p = np.exp(p_logits - p_logits.max())
    p /= p.sum()
    q = np.exp(q_logits - q_logits.max())
    q /= q.sum()
    return float((p * (np.log(p) - np.log(q))).sum())


def test_distill_kl_matches_hand_computed_value():
    inputs = _loss_inputs()
    # teacher p = softmax([ln4, ln2, ln1]) = [4/7, 2/7, 1/7]; student uniform
    t = np.log([4.0, 2.0, 1.0]).astype(np.float32)
    inputs["teacher_logit"]["action_type"][0, 0] = t
    expected = (4 / 7) * math.log(12 / 7) + (2 / 7) * math.log(6 / 7) \
        + (1 / 7) * math.log(3 / 7)
    total, info = compute_distill_loss(inputs)
    assert float(info["kl/action_type"]) == pytest.approx(expected, rel=1e-5)
    # every untouched head is exactly zero and action_type's weight is 1.0
    for head in ("delay", "queued", "selected_units", "target_unit",
                 "target_location"):
        assert float(info[f"kl/{head}"]) == pytest.approx(0.0, abs=1e-7)
    assert float(total) == pytest.approx(expected, rel=1e-5)
    assert float(info["divergence"]) == pytest.approx(expected, rel=1e-5)


def test_distill_kl_selected_units_mask_edges_and_zero_active_lane():
    # both lanes diverge; only lane 0 is active -> exactly lane 0's KL
    inputs = _loss_inputs()
    lane_logits = np.array([2.0, 0.0, -1.0, 0.5], np.float32)
    inputs["teacher_logit"]["selected_units"][0, 0, 0] = lane_logits
    inputs["teacher_logit"]["selected_units"][0, 0, 1] = lane_logits
    inputs["mask"]["selected_units_mask"][0, 0] = [1.0, 0.0]
    _, info = compute_distill_loss(inputs)
    assert float(info["kl/selected_units"]) == pytest.approx(
        _kl(lane_logits, np.zeros(4)), rel=1e-5)
    # zero active lanes: the step contributes NOTHING however far the
    # teacher diverges (the pointer decode never ran for this action)
    inputs["mask"]["selected_units_mask"][0, 0] = [0.0, 0.0]
    _, info = compute_distill_loss(inputs)
    assert float(info["kl/selected_units"]) == pytest.approx(0.0, abs=1e-7)


def test_distill_kl_actions_mask_gates_heads_and_step_mask_pads():
    inputs = _loss_inputs()
    inputs["teacher_logit"]["target_unit"][0, 0] = [3.0, 0.0, 0.0]
    inputs["mask"]["actions_mask"]["target_unit"][0, 0] = 0.0
    _, info = compute_distill_loss(inputs)
    # the head diverges but the action type took no target unit
    assert float(info["kl/target_unit"]) == pytest.approx(0.0, abs=1e-7)
    # ALWAYS_ON heads ignore actions_mask but respect step_mask (pad steps)
    inputs = _loss_inputs()
    inputs["teacher_logit"]["action_type"][0, 0] = [3.0, 0.0, 0.0]
    inputs["mask"]["actions_mask"]["action_type"][0, 0] = 0.0
    _, info = compute_distill_loss(inputs)
    assert float(info["kl/action_type"]) > 0.0
    inputs["mask"]["step_mask"][0, 0] = 0.0
    total, info = compute_distill_loss(inputs)
    assert float(total) == pytest.approx(0.0, abs=1e-7)


def test_distill_temperature_softens_both_sides():
    inputs = _loss_inputs()
    inputs["teacher_logit"]["action_type"][0, 0] = [4.0, 0.0, 0.0]
    _, sharp = compute_distill_loss(inputs, DistillLossConfig(temperature=1.0))
    _, soft = compute_distill_loss(inputs, DistillLossConfig(temperature=4.0))
    assert float(soft["kl/action_type"]) == pytest.approx(
        _kl(np.array([1.0, 0.0, 0.0]), np.zeros(3)), rel=1e-5)
    assert float(soft["kl/action_type"]) < float(sharp["kl/action_type"])


# -------------------------------------------------- checkpoint role isolation
def test_checkpoint_manager_role_keys_never_cross(tmp_path):
    from distar_tpu.utils.checkpoint import CheckpointManager, save_checkpoint

    d = str(tmp_path / "checkpoints")
    teacher_path = os.path.join(d, "iteration_5.ckpt")
    student_path = os.path.join(d, "student_iteration_9.ckpt")
    save_checkpoint(teacher_path, {"w": np.ones((2,), np.float32)})
    save_checkpoint(student_path, {"w": np.zeros((3,), np.float32)})

    teacher_mgr = CheckpointManager(d)
    student_mgr = CheckpointManager(d, role="student")
    teacher_mgr.record(teacher_path, step=5)
    student_mgr.record(student_path, step=9)

    # distinct pointer files; each role resolves ONLY its own generations
    assert os.path.exists(os.path.join(d, "latest.json"))
    assert os.path.exists(os.path.join(d, "latest_student.json"))
    assert teacher_mgr.resolve_latest()["path"] == teacher_path
    assert student_mgr.resolve_latest()["path"] == student_path
    assert [g["path"] for g in teacher_mgr.generations()] == [teacher_path]
    assert [g["path"] for g in student_mgr.generations()] == [student_path]

    # even a hand-merged pointer cannot hand the teacher a student
    # generation: the role filter drops foreign entries on read
    merged = {"generations": [
        {"path": student_path, "step": 9, "ts": time.time(), "role": "student"},
        {"path": teacher_path, "step": 5, "ts": time.time()},
    ]}
    with open(os.path.join(d, "latest.json"), "w") as f:
        json.dump(merged, f)
    assert [g["path"] for g in teacher_mgr.generations()] == [teacher_path]
    assert teacher_mgr.resolve_latest()["path"] == teacher_path
    fresh_student = CheckpointManager(d, role="student")
    assert fresh_student.resolve_latest()["path"] == student_path


# ------------------------------------------------------- the student learner
def test_distill_learner_toy_run_decreases_divergence(tmp_path):
    """Tier-1 e2e of the --distill learner role: a toy run through the real
    run loop (hooks, checkpointing, gauges) on a fixed batch must decrease
    the KL divergence monotonically, publish the drift gauges, and leave
    its checkpoint under the STUDENT role key only."""
    from distar_tpu.learner import DistillLearner
    from distar_tpu.learner.data import fake_rl_batch
    from distar_tpu.obs import get_registry
    from distar_tpu.utils.checkpoint import CheckpointManager

    learner = DistillLearner({
        "common": {"experiment_name": "distill_e2e", "save_path": str(tmp_path)},
        "learner": {"batch_size": 2, "unroll_len": 3, "save_freq": 10 ** 9,
                    "log_freq": 1},
        "model": SMOKE_MODEL,
    })
    assert learner.CKPT_ROLE == "student"
    batch = fake_rl_batch(2, 3)
    batch["model_last_iter"] = np.full((2,), 37.0, np.float32)
    learner.set_dataloader(itertools.repeat(batch))
    kls = []
    for _ in range(5):
        kls.append(learner._train(dict(batch))["divergence"])
    assert all(b < a for a, b in zip(kls, kls[1:])), kls

    snap = get_registry().snapshot()
    assert snap["distar_distill_kl"] == pytest.approx(kls[-1], rel=1e-5)
    assert snap["distar_distill_teacher_generation"] == 37.0
    assert "distar_distill_head_kl{head=selected_units}" in snap

    learner.last_iter.update(5)
    learner.save(learner.checkpoint_path(), sync=True)
    assert get_registry().snapshot()["distar_distill_student_generation"] == 5.0
    ckpt_dir = os.path.join(str(tmp_path), "checkpoints")
    assert os.path.exists(os.path.join(ckpt_dir, "latest_student.json"))
    # a teacher manager over the SAME directory sees no resumable
    # generation: student checkpoints are invisible to teacher resume
    assert CheckpointManager(ckpt_dir).resolve_latest() is None
    assert CheckpointManager(ckpt_dir, role="student").resolve_latest()[
        "path"].endswith("student_iteration_5.ckpt")


# -------------------------------------------------------- divergence watchdog
def test_distill_divergence_runaway_rule_fires_on_rising_kl():
    from distar_tpu.obs import HealthEvaluator, TimeSeriesStore, default_rulebook

    rules = default_rulebook(roles=("distill",))
    assert [r.name for r in rules] == ["distill_divergence_runaway"]
    store = TimeSeriesStore()
    ev = HealthEvaluator(store, rules, interval_s=3600.0)
    t0 = time.time()
    # falling KL (healthy convergence): never breaches
    for i in range(6):
        store.record("distar_distill_kl", 5.0 - 0.5 * i, ts=t0 + i,
                     source="distill:MP0:0")
    ev.evaluate_once()
    assert ev.alerts()["rules"]["distill_divergence_runaway"]["state"] == "ok"
    # rising KL (a full window past the falling phase, so the 60s query
    # window holds ONLY the rise): warning immediately, firing after the
    # for_count debounce
    for i in range(6):
        store.record("distar_distill_kl", 2.0 + 0.4 * i, ts=t0 + 100 + i,
                     source="distill:MP0:0")
    ev.evaluate_once()
    assert ev.alerts()["rules"]["distill_divergence_runaway"]["state"] == "warning"
    ev.evaluate_once()
    ev.evaluate_once()
    alerts = ev.alerts()
    assert alerts["rules"]["distill_divergence_runaway"]["state"] == "firing"
    assert alerts["rules"]["distill_divergence_runaway"]["severity"] == "warning"
    # recovery: KL falls again -> clears after clear_count evaluations
    for i in range(6):
        store.record("distar_distill_kl", 4.0 - 0.5 * i, ts=t0 + 200 + i,
                     source="distill:MP0:0")
    ev.evaluate_once()
    ev.evaluate_once()
    assert ev.alerts()["rules"]["distill_divergence_runaway"]["state"] == "ok"


# ----------------------------------------------- canary compare-then-promote
def _obs(i: int = 0) -> dict:
    return {"x": np.full((2, 2), float(i), dtype=np.float32)}


def _tier_gateway(slots, version):
    from distar_tpu.serve import InferenceGateway, MockModelEngine

    params = {"version": version, "bias": 0.0}
    gw = InferenceGateway(MockModelEngine(slots, params=params),
                         max_batch=slots, max_delay_s=0.002)
    gw.load_version(version, params=params, activate=True)
    return gw.start()


class _TierFleet:
    """N player-multiplexed gateways — teacher + student tiers behind ONE
    address each (the wire ``player`` field is the QoS class)."""

    def __init__(self, n, slots=64):
        from distar_tpu.serve import (
            STUDENT_TIER, TEACHER_TIER, GatewayMux, ServeTCPServer,
        )

        self.muxes = [
            GatewayMux({TEACHER_TIER: _tier_gateway(slots, "t1"),
                        STUDENT_TIER: _tier_gateway(slots, "s1")},
                       default_player=TEACHER_TIER)
            for _ in range(n)
        ]
        self.servers = [ServeTCPServer(m, port=0).start() for m in self.muxes]
        self.addrs = [f"{s.host}:{s.port}" for s in self.servers]

    def close(self):
        for s in self.servers:
            s.stop()
        for m in self.muxes:
            m.drain_and_stop(2.0)


def test_student_canary_compare_then_promote_tiered_fleet():
    """Acceptance e2e: a student checkpoint rolls to a live tiered gateway
    fleet through canary split -> compare() -> GATED promote, with zero
    in-flight request loss, exact per-client v(s1)->v(s2) version streams
    on the student tier, and the teacher tier serving untouched throughout
    — both tiers simultaneously behind one address via ``player``."""
    from distar_tpu.serve import STUDENT_TIER, TEACHER_TIER, ServeClient
    from distar_tpu.serve.fleet import FleetClient, FleetRollout, GatewayMap

    fleet = _TierFleet(3)
    student_fc = FleetClient(gateway_map=GatewayMap(fleet.addrs),
                             timeout_s=5.0, player=STUDENT_TIER)
    teacher_fc = FleetClient(gateway_map=GatewayMap(fleet.addrs),
                             timeout_s=5.0, player=TEACHER_TIER)
    ctl = FleetRollout(GatewayMap(fleet.addrs), timeout_s=5.0)
    try:
        canary_addr = fleet.addrs[0]
        verdict = ctl.canary_start(
            "s2", [canary_addr], 40.0,
            params={"version": "s2", "bias": 1.0},
            router=student_fc.router, player=STUDENT_TIER)
        assert verdict["ok"]
        baseline = ctl.compare([canary_addr])

        streams = {f"tier-{i}": [] for i in range(40)}
        teacher_streams = {f"tier-{i}": [] for i in range(40)}
        def traffic(rounds):
            for _ in range(rounds):
                res = student_fc.act_many(
                    [{"session_id": s, "obs": _obs()} for s in streams])
                tres = teacher_fc.act_many(
                    [{"session_id": s, "obs": _obs()} for s in streams])
                for s, r, tr in zip(streams, res, tres):
                    # zero in-flight loss: every answer is a result dict
                    assert isinstance(r, dict), r
                    assert isinstance(tr, dict), tr
                    streams[s].append(r["version"])
                    teacher_streams[s].append(tr["version"])
        traffic(3)
        on_canary = {s for s in streams
                     if student_fc.router.gateway_for(s) == canary_addr}
        assert on_canary  # the deterministic 40% split put someone there
        for s, versions in streams.items():
            assert set(versions) == ({"s2"} if s in on_canary else {"s1"})

        # compare: fps-per-slot measurable against the baseline snapshot,
        # divergence-vs-teacher folded into the verdict
        cmp_bad = ctl.compare([canary_addr], baseline=baseline,
                              divergence=9.9, max_divergence=1.0,
                              min_fps_ratio=0.25)
        assert cmp_bad["canary"]["fps_per_slot"] > 0
        assert cmp_bad["stable"]["fps_per_slot"] > 0
        assert cmp_bad["divergence"] == 9.9
        assert cmp_bad["verdict"]["promote"] is False
        # a failing verdict GATES promote: nothing rolls, the canary split
        # keeps serving (outcome is the typed compare_gated refusal)
        gated = ctl.promote("s2", params={"version": "s2", "bias": 1.0},
                            router=student_fc.router, player=STUDENT_TIER,
                            verdict=cmp_bad)
        assert gated == {"ok": False, "outcome": "compare_gated",
                         "reasons": gated["reasons"]}
        assert any("divergence" in r for r in gated["reasons"])
        host, _, port = fleet.addrs[1].rpartition(":")
        probe = ServeClient(host, int(port), player=STUDENT_TIER)
        assert probe.act("probe-gated", _obs())["version"] == "s1"
        probe.close()

        # healthy verdict -> promote graduates the student fleet-wide
        cmp_ok = ctl.compare([canary_addr], baseline=baseline,
                             divergence=0.2, max_divergence=1.0,
                             min_fps_ratio=0.25)
        assert cmp_ok["verdict"]["promote"] is True, cmp_ok["verdict"]
        assert ctl.promote("s2", params={"version": "s2", "bias": 1.0},
                           router=student_fc.router, player=STUDENT_TIER,
                           verdict=cmp_ok)["ok"]
        assert student_fc.router.canary_config() == ([], 0.0)
        traffic(2)

        for s, versions in streams.items():
            # monotone per-client stream: s1* then s2*, never interleaved —
            # the PR 2 flush-boundary contract held fleet-wide for the
            # student tier
            first_s2 = versions.index("s2") if "s2" in versions else len(versions)
            assert all(v == "s1" for v in versions[:first_s2])
            assert all(v == "s2" for v in versions[first_s2:])
        # the teacher tier never moved: one address served BOTH tiers the
        # whole time, and the student rollout touched only its player
        for versions in teacher_streams.values():
            assert set(versions) == {"t1"}
    finally:
        student_fc.close()
        teacher_fc.close()
        ctl.close()
        fleet.close()


def test_student_swap_nack_rolls_back_to_student_version_not_teachers():
    """Regression: on a tiered (muxed) gateway the rollback target of a
    student rollout must be the STUDENT player's served version, not the
    default (teacher) player's — the top-level registry block belongs to
    the teacher."""
    from distar_tpu.serve import STUDENT_TIER, ServeClient, ServeError
    from distar_tpu.serve.fleet import FleetRollout, GatewayMap

    fleet = _TierFleet(2)

    class _SwapNack:
        def __init__(self, inner):
            self._inner = inner

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def swap(self, version, player=None):
            if version == "s2":
                raise ServeError("injected swap NACK")
            return self._inner.swap(version, player=player)

    def factory(addr):
        host, _, port = addr.rpartition(":")
        client = ServeClient(host, int(port), timeout_s=5.0)
        return _SwapNack(client) if addr == fleet.addrs[1] else client

    ctl = FleetRollout(GatewayMap(fleet.addrs), timeout_s=5.0,
                       client_factory=factory)
    try:
        verdict = ctl.rollout("s2", params={"version": "s2", "bias": 1.0},
                              player=STUDENT_TIER)
        assert not verdict["ok"] and verdict["outcome"] == "rolled_back"
        # the swapped prefix (gateway 0) rolled back to the student's s1 —
        # if the teacher's registry had been read, the target would have
        # been t1 (not loaded under the student player -> rollback_failed)
        st = ctl.fleet_status([fleet.addrs[0]])[fleet.addrs[0]]
        assert st["players"][STUDENT_TIER]["registry"]["current"] == "s1"
        assert st["players"]["teacher"]["registry"]["current"] == "t1"
    finally:
        ctl.close()
        fleet.close()


def test_tier_player_maps_traffic_classes():
    from distar_tpu.serve import STUDENT_TIER, TEACHER_TIER, tier_player

    assert tier_player("eval") == TEACHER_TIER
    assert tier_player("ladder") == TEACHER_TIER
    assert tier_player("rollout") == STUDENT_TIER
    assert tier_player("anything-else") == STUDENT_TIER
    assert tier_player("anything-else", default=TEACHER_TIER) == TEACHER_TIER


# --------------------------------------------------------- artifact + digest
def test_distill_artifact_is_current_and_honest():
    """The committed DISTILL_r15.json parses, carries the in-band honesty
    flags, meets the <=0.5 step-cost bar from real (non-smoke) configs, and
    its toy-run KL curve decreases monotonically."""
    path = os.path.join(_REPO, "DISTILL_r15.json")
    with open(path) as f:
        doc = json.load(f)
    assert doc["cpu_derived"] is True and doc["flops_derived"] is True
    assert isinstance(doc["host_cores"], int)
    assert doc["scaling_valid"] is False  # 1-core CI box: honest refusal
    assert doc["smoke_model"] is False
    assert doc["value"] <= 0.5 and doc["meets_target"] is True
    d = doc["distill"]
    assert d["student_flops_per_step"] < d["teacher_flops_per_step"]
    curve = d["toy_run"]["kl_curve"]
    assert d["toy_run"]["monotone_decrease"] is True
    assert all(b < a for a, b in zip(curve, curve[1:]))


def test_perf_gate_trajectory_ingests_distill_artifact():
    import sys

    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        from perf_gate import collect_trajectory
    finally:
        sys.path.pop(0)
    rows = collect_trajectory()
    arts = {r["artifact"] for r in rows}
    assert "DISTILL_r15.json" in arts
    kl_rows = [r for r in rows if "distill toy-run KL" in r["metric"]]
    assert kl_rows and "monotone=True" in kl_rows[0]["metric"]


def test_opsctl_distill_digest_renders(capsys, monkeypatch):
    import sys

    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        import opsctl
    finally:
        sys.path.pop(0)

    series = {
        "distar_distill_kl": 0.42,
        "distar_distill_student_generation": 128,
        "distar_distill_teacher_generation": 160,
        "distar_distill_head_kl{head=action_type}": 0.11,
        "distar_distill_step_cost_ratio": 0.31,
    }

    def fake_get(addr, path, timeout=5.0):
        import urllib.parse as up

        name = up.parse_qs(up.urlparse(path).query).get("name", [""])[0]
        if name in series:
            return {"stats": {"distill:MP0:0": {"last": series[name],
                                                "last_ts": 100.0}}}
        return None

    def fake_post(addr, path, body, timeout=5.0):
        if body.get("token") == "serve_canary":
            return {"info": [{"ts": 5.0, "meta": {
                "addrs": ["10.0.0.1:1"], "pct": 25.0, "version": "s2"}}]}
        return None

    monkeypatch.setattr(opsctl, "_try_get", fake_get)
    monkeypatch.setattr(opsctl, "_try_post", fake_post)
    opsctl._print_distill_digest("127.0.0.1:1")
    out = capsys.readouterr().out
    assert "distillation:" in out
    assert "student_gen=128 teacher_gen=160 (lag 32)" in out
    assert "divergence=0.42" in out
    assert "action_type=0.11" in out
    assert "step-cost ratio: 0.31x teacher" in out
    assert "canary split: 25.0% -> 10.0.0.1:1 (version s2)" in out


@pytest.mark.slow
def test_bench_distill_smoke(monkeypatch, tmp_path):
    """BENCH_MODE=distill machinery on smoke dims: ratio computed from both
    lowered train steps, toy-run curve monotone, smoke flagged in-band."""
    import bench

    monkeypatch.setenv("BENCH_DISTILL_SMOKE", "1")
    monkeypatch.setenv("BENCH_DISTILL_ITERS", "4")
    monkeypatch.setenv("DISTAR_EXPERIMENTS_ROOT", str(tmp_path))
    out = bench.bench_distill()
    assert out["smoke_model"] is True and out["meets_target"] is False
    assert out["value"] and out["value"] > 0
    assert out["distill"]["toy_run"]["monotone_decrease"] is True
