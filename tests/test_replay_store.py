"""Replay-store unit coverage: sum-tree sampling, rate-limiter semantics,
eviction policies, spill durability, and the framed-TCP server/client pair
(docs/data_plane.md)."""
import os
import threading
import time

import pytest

from distar_tpu.replay import (
    InsertClient,
    InvalidBatchError,
    RateLimitTimeout,
    RateLimiter,
    ReplayAdminServer,
    ReplayServer,
    ReplayStore,
    ReplayTable,
    SampleClient,
    SpillRing,
    SumTree,
    TableConfig,
    UnknownTableError,
)
from distar_tpu.resilience import RetryPolicy


def _cfg(**kw):
    base = dict(max_size=16, sampler="uniform", samples_per_insert=None,
                min_size_to_sample=1)
    base.update(kw)
    return TableConfig(**base)


# ------------------------------------------------------------------ sum tree
def test_sum_tree_find_respects_mass():
    t = SumTree(8)
    t.set(0, 1.0)
    t.set(3, 3.0)
    assert t.total == pytest.approx(4.0)
    assert t.find(0.5) == 0
    assert t.find(1.5) == 3
    assert t.find(3.9) == 3
    t.set(3, 0.0)
    assert t.find(0.9) == 0


def test_prioritized_sampling_favors_high_priority():
    table = ReplayTable("p", _cfg(sampler="prioritized", max_size=8))
    low = table.insert({"k": "low"}, priority=1.0, timeout_s=1.0)
    high = table.insert({"k": "high"}, priority=50.0, timeout_s=1.0)
    counts = {low: 0, high: 0}
    for s in table.sample(batch_size=200, timeout_s=1.0):
        counts[s.seq] += 1
    assert counts[high] > counts[low] * 5  # ~50x expected, 5x is a safe floor


def test_update_priorities_shifts_distribution():
    table = ReplayTable("up", _cfg(sampler="prioritized", max_size=8))
    a = table.insert("a", priority=1.0, timeout_s=1.0)
    b = table.insert("b", priority=1.0, timeout_s=1.0)
    assert table.update_priorities({a: 100.0, 999: 5.0}) == 1  # unknown ignored
    hits = sum(1 for s in table.sample(batch_size=100, timeout_s=1.0) if s.seq == a)
    assert hits > 80
    assert b is not None


# ---------------------------------------------------------------- fifo table
def test_fifo_is_consume_once_in_order():
    table = ReplayTable("f", _cfg(sampler="fifo", max_size=8))
    for i in range(5):
        table.insert(i, timeout_s=1.0)
    out = table.sample(batch_size=3, timeout_s=1.0)
    assert [s.data for s in out] == [0, 1, 2]
    assert all(s.sample_count == 1 for s in out)
    assert table.size() == 2  # consumed items left the table


def test_size_eviction_is_fifo_and_counted():
    table = ReplayTable("e", _cfg(max_size=4))
    for i in range(6):
        table.insert(i, timeout_s=1.0)
    assert table.size() == 4
    datas = {s.data for s in table.sample(batch_size=50, timeout_s=1.0)}
    assert datas <= {2, 3, 4, 5}  # 0 and 1 were evicted oldest-first


def test_staleness_eviction():
    table = ReplayTable("s", _cfg(max_size=8, max_staleness_s=0.05))
    table.insert("old", timeout_s=1.0)
    time.sleep(0.08)
    table.insert("fresh", timeout_s=1.0)  # insert sweeps the stale item
    assert table.size() == 1
    assert table.sample(timeout_s=1.0)[0].data == "fresh"


def test_sampled_item_reports_staleness_and_reuse():
    table = ReplayTable("m", _cfg(max_size=4))
    table.insert("x", timeout_s=1.0)
    time.sleep(0.02)
    first = table.sample(timeout_s=1.0)[0]
    second = table.sample(timeout_s=1.0)[0]
    assert first.staleness_s >= 0.02
    assert (first.sample_count, second.sample_count) == (1, 2)


# -------------------------------------------------------------- rate limiter
def test_limiter_blocks_sampling_below_min_size():
    table = ReplayTable("rl1", _cfg(min_size_to_sample=3))
    table.insert("a", timeout_s=1.0)
    with pytest.raises(RateLimitTimeout) as e:
        table.sample(timeout_s=0.05)
    assert e.value.side == "sample"


def test_limiter_enforces_samples_per_insert_both_ways():
    lim = RateLimiter(samples_per_insert=2.0, min_size_to_sample=1,
                      error_buffer=2.0, table="t")
    assert lim.can_insert()
    lim.commit_insert()            # inserts=1 (the free min_size insert)
    assert lim.can_insert()        # adj=1 -> 2*1 <= 0+2
    lim.commit_insert()            # inserts=2
    assert not lim.can_insert()    # adj=2 -> 4 > 0+2: inserter too far ahead
    assert lim.can_sample()
    lim.commit_sample(2)           # samples=2
    assert lim.can_insert()        # 4 <= 2+2 again
    # sampler side: samples bounded by spi*adj + eb = 2*1 + 2
    assert lim.can_sample(2)
    assert not lim.can_sample(3)


def test_limiter_disabled_with_none_spi():
    lim = RateLimiter(samples_per_insert=None, min_size_to_sample=2)
    for _ in range(100):
        assert lim.can_insert()
        lim.commit_insert()
    assert lim.can_sample(50)


def test_limiter_unblocks_waiters_on_commit():
    table = ReplayTable("rl2", _cfg(samples_per_insert=1.0, min_size_to_sample=1,
                                    error_buffer=1.0, sampler="fifo"))
    got = []

    def sampler():
        got.append(table.sample(timeout_s=5.0)[0].data)

    t = threading.Thread(target=sampler, daemon=True)
    t.start()
    time.sleep(0.05)  # sampler parks in the limiter
    table.insert("wake", timeout_s=1.0)
    t.join(5.0)
    assert got == ["wake"]
    # block time was recorded on the sample side
    assert table.limiter.state()["block_sample_s"] > 0.0


def test_reuse_ratio_converges_to_spi():
    """The acceptance knob: measured reuse ratio within +/-10% of the
    configured samples-per-insert once min_size is netted out."""
    spi, min_size = 2.0, 4
    table = ReplayTable("ratio", TableConfig(
        max_size=64, sampler="uniform", samples_per_insert=spi,
        min_size_to_sample=min_size, error_buffer=2.0))
    stop = threading.Event()

    def producer():
        i = 0
        while not stop.is_set():
            try:
                table.insert({"i": i}, timeout_s=0.2)
                i += 1
            except RateLimitTimeout:
                continue

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    sampled = 0
    while sampled < 120:
        sampled += len(table.sample(batch_size=4, timeout_s=5.0))
    stop.set()
    t.join(2.0)
    st = table.limiter.state()
    ratio = st["samples"] / max(st["inserts"] - min_size, 1)
    assert abs(ratio - spi) <= 0.1 * spi, st


def test_fifo_rejects_reuse_ratio_above_one():
    with pytest.raises(ValueError, match="consume-once"):
        TableConfig(sampler="fifo", samples_per_insert=2.0)


def test_limiter_max_sample_batch():
    assert RateLimiter(1.0, 1, error_buffer=1.0).max_sample_batch() == 2.0
    assert RateLimiter(2.0, 1, error_buffer=2.0).max_sample_batch() == 4.0
    assert RateLimiter(1.0, 1, error_buffer=4.0).max_sample_batch() == 8.0
    assert RateLimiter(None, 1).max_sample_batch() == float("inf")


def test_inadmissible_batch_raises_config_error_not_timeout():
    """Regression: spi=1 + error_buffer=1 + batch=4 (the old launcher
    defaults) deadlocks — can_sample(4) needs inserts the limiter will never
    admit, so sampler AND inserter block trading timeouts forever. The
    store must answer with a non-retryable config error instead."""
    table = ReplayTable("dead", TableConfig(
        max_size=64, sampler="uniform", samples_per_insert=1.0,
        min_size_to_sample=4, error_buffer=1.0))
    table.insert("a", timeout_s=1.0)
    with pytest.raises(InvalidBatchError, match="error_buffer"):
        table.sample(batch_size=4, timeout_s=0.2)
    # an admissible batch on the same table still behaves normally
    with pytest.raises(RateLimitTimeout):  # below min_size: pacing, retryable
        table.sample(batch_size=1, timeout_s=0.05)


def test_launcher_default_error_buffer_admits_the_learner_batch():
    """rl_train._table_config sizes the default error_buffer to
    max(1, spi) * batch_size so `--type replay` + rl_train's default batch
    can never build a deadlocked table."""
    import argparse

    from distar_tpu.bin.rl_train import _table_config

    args = argparse.Namespace(
        replay_spi=1.0, replay_max_size=1024, replay_sampler="uniform",
        replay_min_size=0, replay_error_buffer=None,
        replay_max_staleness_s=0.0, batch_size=4)
    cfg = _table_config(args)
    assert cfg.error_buffer == 4.0
    lim = RateLimiter(cfg.samples_per_insert, cfg.min_size_to_sample,
                      error_buffer=cfg.error_buffer)
    assert lim.max_sample_batch() >= 4
    # an explicit CLI value still wins
    args.replay_error_buffer = 2.5
    assert _table_config(args).error_buffer == 2.5


# --------------------------------------------------------------------- spill
def test_spill_roundtrip_and_release(tmp_path):
    spill = SpillRing(str(tmp_path), max_items=8)
    store = ReplayStore(table_factory=lambda n: _cfg(), spill=spill)
    for i in range(4):
        store.insert("MP0", {"i": i})
    assert spill.live_count() == 4
    store.sample("MP0", timeout_s=1.0)  # first sample releases one blob
    assert spill.live_count() == 3

    fresh = ReplayStore(table_factory=lambda n: _cfg(),
                        spill=SpillRing(str(tmp_path), max_items=8))
    assert fresh.recover() == 3
    assert fresh.table("MP0").size() == 3


def test_spill_ring_bound_drops_oldest(tmp_path):
    spill = SpillRing(str(tmp_path), max_items=3)
    store = ReplayStore(table_factory=lambda n: _cfg(), spill=spill)
    for i in range(5):
        store.insert("T", i)
    assert spill.live_count() == 3
    fresh = ReplayStore(table_factory=lambda n: _cfg(),
                        spill=SpillRing(str(tmp_path), max_items=3))
    assert fresh.recover() == 3  # only the newest 3 kept their blobs


def test_spill_skips_corrupt_blobs(tmp_path, chaos):
    spill = SpillRing(str(tmp_path), max_items=8)
    store = ReplayStore(table_factory=lambda n: _cfg(), spill=spill)
    for i in range(3):
        store.insert("T", {"i": i})
    blobs = sorted(p for p in os.listdir(tmp_path) if p.endswith(".spill"))
    chaos.bitflip(str(tmp_path / blobs[0]), flips=16)
    fresh = ReplayStore(table_factory=lambda n: _cfg(),
                        spill=SpillRing(str(tmp_path), max_items=8))
    assert fresh.recover() == 2  # the flipped blob failed CRC and was skipped


def test_insert_spills_blob_before_ack_and_releases_on_timeout(tmp_path):
    """Regression: the blob must be on disk BEFORE the item goes live (a
    concurrent release must find it), and a rate-limited insert must not
    leak its reserved blob as a forever-recovered orphan."""
    spill = SpillRing(str(tmp_path), max_items=8)
    cfg = TableConfig(max_size=16, sampler="uniform", samples_per_insert=1.0,
                      min_size_to_sample=1, error_buffer=1.0)
    store = ReplayStore(table_factory=lambda n: cfg, spill=spill)
    store.insert("T", 0)
    store.insert("T", 1)
    # limiter now blocks inserts (2 inserts ahead, 0 samples, buffer 1)
    with pytest.raises(RateLimitTimeout):
        store.insert("T", 2, timeout_s=0.05)
    assert spill.live_count() == 2  # the timed-out blob was released
    fresh = ReplayStore(table_factory=lambda n: cfg,
                        spill=SpillRing(str(tmp_path), max_items=8))
    assert fresh.recover() == 2  # no orphan comes back as a duplicate


def test_spill_bootstrap_lists_resolved_root_for_schemed_backend():
    """Regression: _bootstrap_seq listed the unresolved root, so a scheme'd
    spill (mem://, gs://) restarted its key sequence at 0 and silently
    overwrote live blobs."""
    root = "mem://spill-bootstrap-regression"
    first = SpillRing(root, max_items=8)
    first.append(first.reserve_key("T"), "T", {"i": 1}, 1.0)
    restarted = SpillRing(root, max_items=8)
    key = restarted.reserve_key("T")
    assert int(key.rsplit("-", 1)[-1]) >= 1  # never reuses the live key


def test_spill_key_sequence_survives_restart(tmp_path):
    spill = SpillRing(str(tmp_path), max_items=8)
    store = ReplayStore(table_factory=lambda n: _cfg(), spill=spill)
    store.insert("T", 1)
    spill2 = SpillRing(str(tmp_path), max_items=8)
    k = spill2.reserve_key("T")
    # a restarted ring must never reuse (and overwrite) a live key
    assert int(k.rsplit("-", 1)[-1]) >= 1


# ----------------------------------------------------------- server / client
def test_server_roundtrip_acked_insert_and_sample():
    store = ReplayStore(table_factory=lambda n: _cfg())
    server = ReplayServer(store, port=0).start()
    try:
        with InsertClient(server.host, server.port) as ic, \
                SampleClient(server.host, server.port) as sc:
            assert ic.ping()
            seq = ic.insert("MP0", {"traj": [1, 2]}, priority=3.0)
            assert seq == 0
            items, info = sc.sample("MP0", batch_size=2, timeout_s=5.0)
            assert items == [{"traj": [1, 2]}] * 2  # with replacement
            assert info[0]["seq"] == 0 and info[1]["sample_count"] == 2
            stats = sc.stats()
            assert stats["tables"]["MP0"]["limiter"]["inserts"] == 1
            assert sc.tables() == ["MP0"]
    finally:
        server.stop()


def test_server_typed_errors():
    store = ReplayStore(table_factory=None)  # no auto-create
    server = ReplayServer(store, port=0).start()
    try:
        sc = SampleClient(server.host, server.port,
                          retry_policy=RetryPolicy(max_attempts=1))
        with pytest.raises(UnknownTableError):
            sc.sample("nope", timeout_s=1.0)
        sc.close()
    finally:
        server.stop()


def test_server_rate_limit_timeout_is_retryable_wire_error():
    store = ReplayStore(table_factory=lambda n: _cfg(min_size_to_sample=5))
    server = ReplayServer(store, port=0).start()
    try:
        sc = SampleClient(server.host, server.port,
                          retry_policy=RetryPolicy(max_attempts=2,
                                                   backoff_base_s=0.01,
                                                   jitter=0.0))
        with pytest.raises(RateLimitTimeout) as e:
            sc.sample("MP0", timeout_s=0.05)
        assert e.value.side == "sample"
        sc.close()
    finally:
        server.stop()


def test_server_invalid_batch_is_nonretryable_wire_error():
    """An inadmissible batch must surface immediately as the typed
    invalid_batch error — not burn the client's whole retry/deadline budget
    the way the (retryable) rate_limited answer does."""
    store = ReplayStore(
        table_factory=lambda n: _cfg(samples_per_insert=1.0, error_buffer=1.0))
    server = ReplayServer(store, port=0).start()
    try:
        sc = SampleClient(server.host, server.port)
        t0 = time.monotonic()
        with pytest.raises(InvalidBatchError):
            sc.sample("MP0", batch_size=8, timeout_s=5.0)
        assert time.monotonic() - t0 < 2.0  # no retry loop, no server-side park
        sc.close()
    finally:
        server.stop()


def test_client_rides_through_server_restart(chaos):
    """Kill the store between requests; the client's retry policy dials the
    restarted server on the same port invisibly (the resilience contract)."""
    store = ReplayStore(table_factory=lambda n: _cfg())
    server = ReplayServer(store, port=0).start()
    host, port = server.host, server.port
    ic = InsertClient(host, port)
    assert ic.insert("MP0", {"i": 0}) == 0
    chaos.kill_role(server, name="replay")
    server2 = ReplayServer(ReplayStore(table_factory=lambda n: _cfg()),
                           host=host, port=port).start()
    try:
        assert ic.insert("MP0", {"i": 1}) == 0  # fresh store, fresh seqs
    finally:
        ic.close()
        server2.stop()


def test_admin_surface_serves_stats_and_metrics():
    import json
    import urllib.request

    store = ReplayStore(table_factory=lambda n: _cfg())
    store.insert("MP0", {"x": 1})
    admin = ReplayAdminServer(store, port=0).start()
    try:
        base = f"http://{admin.host}:{admin.port}"
        body = json.load(urllib.request.urlopen(base + "/replay/stats", timeout=5))
        assert body["tables"]["MP0"]["size"] == 1
        text = urllib.request.urlopen(base + "/metrics", timeout=5).read().decode()
        assert "distar_replay_inserts_total" in text
    finally:
        admin.stop()


def test_bench_replay_emits_standard_json(monkeypatch, capsys):
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import bench

    monkeypatch.setenv("BENCH_REPLAY_SECONDS", "0.4")
    monkeypatch.setenv("BENCH_REPLAY_PAYLOAD_KB", "4")
    monkeypatch.setenv("BENCH_REPLAY_WRITERS", "1")
    monkeypatch.setenv("BENCH_REPLAY_READERS", "1")
    monkeypatch.setenv("BENCH_REPLAY_SHARDS", "1,2")
    point = bench.bench_replay()
    assert {"metric", "value", "unit", "vs_baseline"} <= set(point)
    assert point["replay"]["insert_items_per_s"] > 0
    # in-band honesty flags + the r09 cases: sharded sweep over real shard
    # subprocesses, negotiated-compression A/B, zero-copy fast path
    assert point["cpu_derived"] is True and point["device"] == "cpu"
    assert isinstance(point["scaling_valid"], bool) and point["host_cores"] >= 1
    # pinning provenance (tools/pin.py harness): the scaling_valid flag must
    # agree with it — perf_gate's scaling gate enforces the same contract
    assert point["pinning"]["pinned"] in (True, False)
    assert point["scaling_valid"] == (
        point["pinning"]["pinned"] and point["pinning"]["host_cores"] >= 3)
    assert [r["shards"] for r in point["replay_shard_sweep"]] == [1, 2]
    assert all(r["aggregate_items_per_s"] > 0 for r in point["replay_shard_sweep"])
    comp = point["replay_compression"]
    assert comp["on"]["wire_ratio"] < 0.9 < comp["off"]["wire_ratio"]
    assert point["replay_fast_path"]["vs_tcp_loopback"] > 1.0
    out = capsys.readouterr().out.strip().splitlines()
    import json

    parsed = json.loads(out[-1])
    assert parsed["unit"] == "items/s"
