"""Sharded replay fleet coverage: consistent-hash stability, fleet routing,
fan-in isolation, wire-compression negotiation, the zero-copy colocated
fast path, and the insert idempotency contract (docs/data_plane.md
sharding section)."""
import os
import subprocess
import sys
import threading
import time

import pytest

from distar_tpu.comm.serializer import Opaque, dumps
from distar_tpu.obs import get_registry
from distar_tpu.replay import (
    HashRing,
    InsertClient,
    LocalReplayClient,
    RateLimitTimeout,
    ReplayServer,
    ReplayStore,
    SampleClient,
    ShardMap,
    ShardedInsertClient,
    ShardedSampleClient,
    SpillRing,
    TableConfig,
    UnknownTableError,
    set_local_store,
    stable_hash,
)
from distar_tpu.resilience import RetryPolicy


def _cfg(**kw):
    base = dict(max_size=256, sampler="uniform", samples_per_insert=None,
                min_size_to_sample=1)
    base.update(kw)
    return TableConfig(**base)


def _fleet(n, table_cfg=None, spill_dirs=None, **server_kw):
    """n in-process shard servers + their ShardMap."""
    servers = []
    for i in range(n):
        spill = SpillRing(spill_dirs[i], max_items=1024) if spill_dirs else None
        store = ReplayStore(table_factory=table_cfg or (lambda name: _cfg()),
                            spill=spill, shard_id=f"s{i}",
                            recover_encoded=True)
        store.recover()
        servers.append(ReplayServer(store, port=0, **server_kw).start())
    return servers, ShardMap([f"{s.host}:{s.port}" for s in servers])


def _registry_sum(prefix):
    return sum(v for k, v in get_registry().snapshot().items()
               if k.startswith(prefix))


# ---------------------------------------------------------------- hash ring
def test_ring_deterministic_within_process():
    a = ShardMap(["h1:1", "h2:2", "h3:3"])
    b = ShardMap(["h1:1", "h2:2", "h3:3"])
    keys = [f"k{i}" for i in range(200)]
    assert [a.shard_for("T", k) for k in keys] == [b.shard_for("T", k) for k in keys]


def test_ring_deterministic_across_processes():
    """The routing function must agree between an actor process and a
    learner process: PYTHONHASHSEED-salted ``hash()`` would not, md5 does."""
    keys = [f"key-{i}" for i in range(64)]
    local = [ShardMap(["a:1", "b:2", "c:3"]).shard_for("MP0", k) for k in keys]
    code = (
        "from distar_tpu.replay import ShardMap\n"
        "m = ShardMap(['a:1', 'b:2', 'c:3'])\n"
        f"print('\\n'.join(m.shard_for('MP0', f'key-{{i}}') for i in range({len(keys)})))\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, check=True,
        env={**os.environ, "PYTHONHASHSEED": "12345", "JAX_PLATFORMS": "cpu"},
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert out.stdout.strip().splitlines() == local


def test_ring_growth_remaps_bounded_fraction():
    """Consistent hashing's point: N -> N+1 moves only ~1/(N+1) of keys
    (naive mod-N routing moves ~N/(N+1) — almost everything)."""
    n = 4
    addrs = [f"h{i}:{i}" for i in range(n)]
    keys = [f"k{i}" for i in range(2000)]
    before = {k: ShardMap(addrs).shard_for("T", k) for k in keys}
    after = {k: ShardMap(addrs + [f"h{n}:{n}"]).shard_for("T", k) for k in keys}
    moved = sum(1 for k in keys if before[k] != after[k])
    ideal = 1.0 / (n + 1)
    assert moved / len(keys) < 1.6 * ideal, (moved, len(keys))
    # and every move went TO the new shard (nothing shuffles between
    # survivors — the property mod-N lacks)
    assert all(after[k] == f"h{n}:{n}" for k in keys if before[k] != after[k])


def test_ring_spreads_keys_reasonably():
    addrs = ["h1:1", "h2:2", "h3:3"]
    m = ShardMap(addrs)
    from collections import Counter

    counts = Counter(m.shard_for("T", f"k{i}") for i in range(3000))
    assert set(counts) == set(addrs)  # every shard owns some keys
    assert max(counts.values()) < 2 * min(counts.values())


def test_stable_hash_is_not_pyhash():
    assert stable_hash("x") == stable_hash("x")
    assert stable_hash("x") != hash("x")  # astronomically unlikely to collide


def test_shard_map_parse_and_validation():
    m = ShardMap.parse("a:1, b:2 ,a:1")
    assert m.addrs == ["a:1", "b:2"]  # order-preserving dedupe
    assert len(m) == 2
    with pytest.raises(ValueError):
        ShardMap([])


# ----------------------------------------------------------- sharded clients
def test_insert_routes_by_key_and_sample_pair_lands_same_shard():
    servers, shard_map = _fleet(3)
    try:
        ic = ShardedInsertClient(shard_map)
        keys = [f"ep{i}" for i in range(30)]
        for k in keys:
            ic.insert("MP0", {"k": k}, key=k, timeout_s=5.0)
        # the item physically lives on the shard the routing function names
        by_addr = {
            f"{s.host}:{s.port}": (
                {it.data["k"] for it in s.store.table("MP0")._items.values()}
                if "MP0" in s.store.tables() else set())
            for s in servers
        }
        for k in keys:
            owner = ic.shard_for("MP0", k)
            assert k in by_addr[owner]
            # insert/sample pair: the sample side's routing agrees
            assert ShardedSampleClient(shard_map).shard_map.shard_for("MP0", k) == owner
        ic.close()
    finally:
        for s in servers:
            s.stop()


def test_fanin_serves_all_shards_and_tags_info():
    servers, shard_map = _fleet(3)
    try:
        ic = ShardedInsertClient(shard_map)
        for i in range(30):
            ic.insert("MP0", i, timeout_s=5.0)
        sc = ShardedSampleClient(shard_map)
        seen = set()
        for _ in range(20):
            _items, info = sc.sample("MP0", batch_size=2, timeout_s=5.0)
            seen.update(d["shard"] for d in info)
            assert all("seq" in d for d in info)
        assert seen == set(shard_map.addrs)  # round-robin touched everyone
        ic.close()
        sc.close()
    finally:
        for s in servers:
            s.stop()


def test_stalled_shard_blocks_only_itself():
    """Per-shard limiter invariant: one shard whose spi limiter cannot admit
    a sample (no inserts ever landed there) must not park the fan-in — the
    rotation skips it and serves from the fed shards within the timeout."""
    servers, shard_map = _fleet(
        2, table_cfg=lambda name: _cfg(samples_per_insert=1.0, error_buffer=8.0))
    try:
        # feed ONLY shard 0, directly (bypassing the ring on purpose);
        # 6 inserts stay inside the limiter's insert-ahead window (eb=8)
        direct = InsertClient(servers[0].host, servers[0].port)
        for i in range(6):
            direct.insert("MP0", i, timeout_s=5.0)
        sc = ShardedSampleClient(shard_map)
        t0 = time.monotonic()
        items, info = sc.sample("MP0", batch_size=2, timeout_s=10.0)
        assert time.monotonic() - t0 < 8.0  # did not burn the whole budget
        fed = f"{servers[0].host}:{servers[0].port}"
        assert {d["shard"] for d in info} == {fed}
        assert _registry_sum("distar_replay_fanin_skips_total") >= 0
        direct.close()
        sc.close()
    finally:
        for s in servers:
            s.stop()


def test_fanin_rides_through_shard_kill_and_restart_recovers(tmp_path):
    """The test-sized shard-loss drill: kill 1 of 3, the learner keeps
    sampling from survivors; restart over the same spill brings the
    victim's unsampled tail back (tools/chaos.py replay-drill --shards is
    the CLI-scale version)."""
    spill_dirs = [str(tmp_path / f"s{i}") for i in range(3)]
    servers, shard_map = _fleet(
        3, table_cfg=lambda name: _cfg(sampler="fifo"), spill_dirs=spill_dirs)
    try:
        ic = ShardedInsertClient(shard_map)
        keys = [f"k{i}" for i in range(24)]
        owner = {k: ic.shard_for("MP0", k) for k in keys}
        for k in keys:
            ic.insert("MP0", {"k": k}, key=k, timeout_s=5.0)
        victim_addr = f"{servers[0].host}:{servers[0].port}"
        victim_port = servers[0].port
        victim_keys = {k for k in keys if owner[k] == victim_addr}
        assert victim_keys, "hash ring gave shard 0 nothing — widen the key set"
        servers[0].stop()

        sc = ShardedSampleClient(shard_map)
        got = set()
        deadline = time.monotonic() + 20.0
        while len(got) < len(keys) - len(victim_keys) and time.monotonic() < deadline:
            try:
                items, info = sc.sample("MP0", batch_size=1, timeout_s=2.0)
            except RateLimitTimeout:
                continue
            got.update(it["k"] for it in items)
            assert all(d["shard"] != victim_addr for d in info)
        assert got == set(keys) - victim_keys  # survivors fully served

        # restart the victim over its spill, same address
        store = ReplayStore(table_factory=lambda name: _cfg(sampler="fifo"),
                            spill=SpillRing(spill_dirs[0], max_items=1024),
                            shard_id="s0", recover_encoded=True)
        recovered = store.recover()
        assert recovered == len(victim_keys)
        servers[0] = ReplayServer(store, host=servers[0].host,
                                  port=victim_port).start()
        deadline = time.monotonic() + 20.0
        while len(got) < len(keys) and time.monotonic() < deadline:
            try:
                items, _info = sc.sample("MP0", batch_size=1, timeout_s=2.0)
            except RateLimitTimeout:
                continue
            got.update(it["k"] for it in items)
        assert got == set(keys)  # zero items lost fleet-wide
        ic.close()
        sc.close()
    finally:
        for s in servers:
            s.stop()


def test_fanin_unknown_table_raises_only_when_no_shard_has_it():
    servers, shard_map = _fleet(2, table_cfg=None)
    # no factory: tables must pre-exist
    for s in servers:
        s.store._factory = None
    try:
        sc = ShardedSampleClient(shard_map)
        with pytest.raises(UnknownTableError):
            sc.sample("nope", timeout_s=2.0)
        # one shard grows the table -> fan-in finds it
        servers[1].store.create_table("late", _cfg())
        servers[1].store.insert("late", {"v": 1})
        items, _ = sc.sample("late", timeout_s=5.0)
        assert items == [{"v": 1}]
        sc.close()
    finally:
        for s in servers:
            s.stop()


def test_sharded_update_priorities_routes_by_info():
    servers, shard_map = _fleet(
        2, table_cfg=lambda name: _cfg(sampler="prioritized"))
    try:
        ic = ShardedInsertClient(shard_map)
        for i in range(16):
            ic.insert("MP0", i, timeout_s=5.0)
        sc = ShardedSampleClient(shard_map)
        _items, info = sc.sample("MP0", batch_size=4, timeout_s=5.0)
        updates = {d["seq"]: 50.0 for d in info}
        applied = sc.update_priorities("MP0", updates, info=info)
        assert applied == len({d["seq"] for d in info})
        ic.close()
        sc.close()
    finally:
        for s in servers:
            s.stop()


def test_fleet_stats_reports_dead_shards_without_raising():
    servers, shard_map = _fleet(2)
    try:
        servers[1].stop()
        sc = ShardedSampleClient(shard_map)
        stats = sc.fleet_stats()
        assert set(stats) == set(shard_map.addrs)
        dead = f"{servers[1].host}:{servers[1].port}"
        assert "error" in stats[dead]
        alive = next(a for a in shard_map.addrs if a != dead)
        assert "tables" in stats[alive]
        sc.close()
    finally:
        servers[0].stop()


# -------------------------------------------------------- wire compression
def test_compression_negotiation_and_byte_metrics():
    store = ReplayStore(table_factory=lambda name: _cfg())
    server = ReplayServer(store, port=0).start()
    payload = b"\x00" * 100_000  # maximally compressible
    try:
        # pin the TCP leg: this test measures the WIRE codec's byte
        # accounting, which shm frames (negotiated by default when
        # colocated) deliberately bypass
        on = InsertClient(server.host, server.port, compress=True,
                          transport="tcp")
        before_w = _registry_sum("distar_replay_rx_bytes_wire_total")
        before_r = _registry_sum("distar_replay_rx_bytes_raw_total")
        on.insert("T", payload, timeout_s=5.0)
        wire_on = _registry_sum("distar_replay_rx_bytes_wire_total") - before_w
        raw_on = _registry_sum("distar_replay_rx_bytes_raw_total") - before_r
        assert on._neg_compress is True
        assert raw_on > 100_000
        assert wire_on < raw_on / 10  # compression actually engaged

        off = InsertClient(server.host, server.port, compress=False,
                           transport="tcp")
        before_w = _registry_sum("distar_replay_rx_bytes_wire_total")
        off.insert("T", payload, timeout_s=5.0)
        wire_off = _registry_sum("distar_replay_rx_bytes_wire_total") - before_w
        assert off._neg_compress is False
        assert wire_off > 100_000  # sent raw, as negotiated
        on.close()
        off.close()
    finally:
        server.stop()


def test_server_side_compress_disable_wins_negotiation():
    store = ReplayStore(table_factory=lambda name: _cfg())
    server = ReplayServer(store, port=0, compress=False).start()
    try:
        client = InsertClient(server.host, server.port, compress=True)
        client.insert("T", b"\x00" * 1000, timeout_s=5.0)
        assert client._neg_compress is False  # server's refusal is ANDed in
        client.close()
    finally:
        server.stop()


def test_spill_reserve_skips_recompression(tmp_path):
    """A store that recovered with ``recover_encoded`` holds Opaque blobs
    and re-serves them WITHOUT a recompression pass (uncompressed frame
    around already-compressed payload); the client decodes transparently."""
    spill = SpillRing(str(tmp_path), max_items=64)
    store = ReplayStore(table_factory=lambda name: _cfg(), spill=spill)
    original = {"traj": list(range(100)), "pad": b"\x00" * 10_000}
    store.insert("MP0", original)

    fresh = ReplayStore(table_factory=lambda name: _cfg(),
                        spill=SpillRing(str(tmp_path), max_items=64),
                        recover_encoded=True)
    assert fresh.recover() == 1
    item = next(iter(fresh.table("MP0")._items.values()))
    assert isinstance(item.data, Opaque)  # resident as the encoded blob
    server = ReplayServer(fresh, port=0).start()
    try:
        before = _registry_sum("distar_replay_tx_bytes_raw_total")
        before_wire = _registry_sum("distar_replay_tx_bytes_wire_total")
        sc = SampleClient(server.host, server.port)
        items, _info = sc.sample("MP0", timeout_s=5.0)
        assert items[0] == original  # client decoded the Opaque transparently
        raw = _registry_sum("distar_replay_tx_bytes_raw_total") - before
        wire = _registry_sum("distar_replay_tx_bytes_wire_total") - before_wire
        # the frame went out UNcompressed (raw==wire up to the magic): had
        # the server recompressed, wire would be well below raw
        assert wire == pytest.approx(raw, abs=16)
        sc.close()
    finally:
        server.stop()


# ------------------------------------------------------ colocated fast path
def test_local_client_is_zero_copy():
    store = ReplayStore(table_factory=lambda name: _cfg())
    client = LocalReplayClient(store)
    obj = {"arr": bytearray(1000)}
    client.insert("T", obj)
    items, info = client.sample("T", batch_size=1)
    assert items[0] is obj  # the object itself — no serialization happened
    assert info[0]["seq"] == 0


def test_local_client_decodes_recovered_opaque(tmp_path):
    spill = SpillRing(str(tmp_path), max_items=64)
    ReplayStore(table_factory=lambda name: _cfg(), spill=spill).insert(
        "T", {"v": 7})
    fresh = ReplayStore(table_factory=lambda name: _cfg(),
                        spill=SpillRing(str(tmp_path), max_items=64),
                        recover_encoded=True)
    fresh.recover()
    items, _ = LocalReplayClient(fresh).sample("T", timeout_s=5.0)
    assert items[0] == {"v": 7}


def test_local_store_registry_required_for_inproc_addr():
    set_local_store(None)
    with pytest.raises(RuntimeError):
        LocalReplayClient()
    store = ReplayStore(table_factory=lambda name: _cfg())
    set_local_store(store)
    try:
        client = LocalReplayClient()
        client.insert("T", 1)
        assert client.sample("T")[0] == [1]
    finally:
        set_local_store(None)


def test_actor_replay_target_accepts_fleet_and_inproc():
    from distar_tpu.actor import Actor

    actor = Actor(cfg={"actor": {"replay": {
        "enabled": True, "addr": "h1:7000,h2:7001"}}})
    assert actor._replay_target() == [("h1", 7000), ("h2", 7001)]
    actor = Actor(cfg={"actor": {"replay": {"enabled": True, "addr": "inproc"}}})
    assert actor._replay_target() == "inproc"
    with pytest.raises(ValueError):
        Actor(cfg={"actor": {"replay": {"enabled": True, "addr": "h1:x,h2:y"}}})


# ------------------------------------------------------- insert idempotency
def test_retried_insert_after_lost_ack_does_not_double_apply(tmp_path):
    """The ambiguous-failure regression: server commits the insert (table +
    spill), then the connection dies before the ack. The client's retry
    must be answered from the idem cache — one item, one spill blob, the
    ORIGINAL seq."""
    spill = SpillRing(str(tmp_path), max_items=64)
    store = ReplayStore(table_factory=lambda name: _cfg(), spill=spill)
    server = ReplayServer(store, port=0).start()
    original_send = server._send_counted
    dropped = []

    def drop_first_ack(conn, obj, compress, codec="lz4"):
        if not dropped and isinstance(obj, dict) and "seq" in obj:
            dropped.append(obj["seq"])
            conn.close()  # post-commit reset: the ack dies on the wire
            raise ConnectionError("chaos: ack dropped after commit")
        return original_send(conn, obj, compress, codec)

    server._send_counted = drop_first_ack
    try:
        # pin the TCP leg: the chaos hook patches the TCP send path, which
        # a colocated client would otherwise bypass over shm rings
        client = InsertClient(server.host, server.port, transport="tcp",
                              retry_policy=RetryPolicy(max_attempts=4,
                                                       backoff_base_s=0.01,
                                                       deadline_s=10.0))
        seq = client.insert("T", {"v": 1}, timeout_s=5.0)
        assert dropped, "the chaos hook never fired"
        assert seq == dropped[0]  # the retry got the ORIGINAL seq
        assert store.table("T").size() == 1  # not double-applied
        assert spill.live_count() == 1  # no duplicate blob either
        assert _registry_sum("distar_replay_insert_dedup_total") >= 1
        client.close()
    finally:
        server._send_counted = original_send
        server.stop()


def test_distinct_inserts_never_dedup():
    store = ReplayStore(table_factory=lambda name: _cfg())
    server = ReplayServer(store, port=0).start()
    try:
        client = InsertClient(server.host, server.port)
        seqs = [client.insert("T", i, timeout_s=5.0) for i in range(10)]
        assert len(set(seqs)) == 10
        assert store.table("T").size() == 10
        client.close()
    finally:
        server.stop()


def test_idem_cache_is_bounded():
    store = ReplayStore(table_factory=lambda name: _cfg(max_size=16))
    store.IDEM_CACHE = 4
    for i in range(10):
        store.insert("T", i, idem=f"id{i}")
    assert len(store._idem) == 4
    assert "id9" in store._idem and "id0" not in store._idem


# ---------------------------------------------------------- coordinator map
def test_shard_map_discovery_via_coordinator_peers():
    from distar_tpu.comm import Coordinator, CoordinatorServer
    from distar_tpu.replay import register_shard

    co = CoordinatorServer(coordinator=Coordinator())
    co.start()
    try:
        hb1 = register_shard((co.host, co.port), "10.0.0.1", 7000,
                             meta={"admin_port": 9000}, lease_s=30.0)
        hb2 = register_shard((co.host, co.port), "10.0.0.2", 7000, lease_s=30.0)
        m = ShardMap.discover((co.host, co.port))
        assert m.addrs == ["10.0.0.1:7000", "10.0.0.2:7000"]
        # peers is non-destructive: a second discovery sees the same fleet
        assert ShardMap.discover((co.host, co.port)).addrs == m.addrs
        hb1.stop_event.set()
        hb2.stop_event.set()
    finally:
        co.stop()


def test_shard_map_discovery_empty_fleet_raises():
    from distar_tpu.comm import Coordinator, CoordinatorServer

    co = CoordinatorServer(coordinator=Coordinator())
    co.start()
    try:
        with pytest.raises(ValueError):
            ShardMap.discover((co.host, co.port))
    finally:
        co.stop()


def test_hash_ring_single_node_owns_everything():
    ring = HashRing(["only:1"])
    assert all(ring.lookup(f"k{i}") == "only:1" for i in range(50))


def test_opaque_roundtrip():
    blob = dumps({"x": 1})
    o = Opaque(blob)
    assert o.decode() == {"x": 1}
    import pickle

    assert pickle.loads(pickle.dumps(o)).decode() == {"x": 1}
