"""Sharded training executor: feeder assembly, distributed checkpoints with
resharding restore, typed mesh config errors, and the tier-1 multichip smoke
(executed GSPMD train step on the forced 8-device CPU mesh — see conftest)."""
import glob
import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from distar_tpu.parallel import (
    MeshConfigError,
    MeshSpec,
    ShardFeeder,
    assemble_global,
    batch_sharding,
    make_mesh,
    param_sharding,
)
from distar_tpu.parallel import ckpt as shck
from distar_tpu.utils.checkpoint import (
    CheckpointManager,
    CheckpointCorruptError,
    CheckpointMismatchError,
    load_checkpoint,
    verify_checkpoint,
)

from conftest import SMALL_MODEL  # shared tiny model config


# ------------------------------------------------------------- mesh satellite

def test_mesh_spec_parse():
    spec = MeshSpec.parse("dp=4,fsdp=2")
    assert (spec.dp, spec.fsdp, spec.tp, spec.sp) == (4, 2, 1, 1)
    assert MeshSpec.parse("dp=4, fsdp=2, tp=1, sp=1").sizes(8) == (4, 2, 1, 1)
    assert MeshSpec.parse("").sizes(8) == (8, 1, 1, 1)  # dp absorbs


def test_mesh_spec_parse_typed_errors():
    with pytest.raises(MeshConfigError, match="unknown mesh axis"):
        MeshSpec.parse("dq=4")
    with pytest.raises(MeshConfigError, match="integer size"):
        MeshSpec.parse("dp=four")


def test_mesh_sizes_typed_error_when_devices_dont_factor():
    with pytest.raises(MeshConfigError, match="does not factor"):
        MeshSpec.parse("dp=3").sizes(8)
    with pytest.raises(MeshConfigError, match="must be positive"):
        MeshSpec(dp=0).sizes(8)


def test_batch_sharding_rejects_indivisible_batch():
    mesh = make_mesh(MeshSpec(dp=4, fsdp=2))
    with pytest.raises(MeshConfigError, match="not divisible"):
        batch_sharding(mesh, batch_size=6)
    # divisible passes and still shards over (dp, fsdp)
    sh = batch_sharding(mesh, batch_size=16)
    assert "dp" in str(sh.spec)


def test_assemble_global_rejects_indivisible_dim():
    mesh = make_mesh(MeshSpec(dp=8))
    sh = batch_sharding(mesh)
    with pytest.raises(MeshConfigError, match="cannot shard"):
        assemble_global(np.zeros((6, 3), np.float32), sh)


# ------------------------------------------------------------------- feeder

def test_feeder_shard_assembly_round_trip():
    """Host batches -> global device arrays on a dp=4,fsdp=2 mesh of the 8
    forced host devices; every yielded leaf is sharded (8 distinct shards
    over the batch axis) and round-trips bit-identically to the host."""
    mesh = make_mesh(MeshSpec(dp=4, fsdp=2))
    sh = batch_sharding(mesh)
    rng = np.random.default_rng(0)
    batches = [
        {"x": rng.standard_normal((8, 5)).astype(np.float32),
         "y": np.full((8,), i, np.float32)}
        for i in range(4)
    ]

    def place(b):
        return {k: assemble_global(v, sh) for k, v in b.items()}

    feeder = ShardFeeder(iter(list(batches)), place, depth=2, token="test")
    out = list(feeder)
    assert len(out) == 4
    for i, b in enumerate(out):
        assert len(b["x"].addressable_shards) == 8
        # each device holds a distinct 1-row batch shard
        assert b["x"].addressable_shards[0].data.shape == (1, 5)
        np.testing.assert_array_equal(np.asarray(b["x"]), batches[i]["x"])
        np.testing.assert_array_equal(np.asarray(b["y"]), batches[i]["y"])
    stats = feeder.stats()
    assert stats["batches"] == 4 and stats["place_s_mean"] >= 0.0


def test_feeder_propagates_producer_error():
    def boom():
        yield {"x": np.zeros(8)}
        raise RuntimeError("collate died")

    mesh = make_mesh(MeshSpec(dp=8))
    sh = batch_sharding(mesh)
    feeder = ShardFeeder(boom(), lambda b: {k: assemble_global(v, sh) for k, v in b.items()})
    next(feeder)
    with pytest.raises(RuntimeError, match="collate died"):
        next(feeder)


# --------------------------------------------------- sharded ckpt + reshard

def _param_tree(mesh, seed=0):
    rng = np.random.default_rng(seed)
    host = {
        "params": {
            "dense": {"kernel": rng.standard_normal((16, 8)).astype(np.float32),
                      "bias": rng.standard_normal((8,)).astype(np.float32)},
            "scale": np.float32(rng.standard_normal()),
        },
        "opt": (rng.standard_normal((16, 8)).astype(np.float32),
                np.int32(7)),
    }
    sh = param_sharding(mesh, host)
    return jax.tree.map(lambda x, s: jax.device_put(x, s), host, sh), host


def test_sharded_ckpt_save_mesh_a_restore_mesh_b_bit_identical(tmp_path):
    mesh_a = make_mesh(MeshSpec(dp=4, fsdp=2))
    tree, host = _param_tree(mesh_a)
    path = str(tmp_path / "it1.ckpt")
    shck.save_sharded(path, tree, metadata={"last_iter": 1})
    assert shck.is_sharded_checkpoint(path)
    assert verify_checkpoint(path)

    out = load_checkpoint(path)  # routes through utils.checkpoint
    assert out["metadata"]["last_iter"] == 1
    restored = out["state"]
    np.testing.assert_array_equal(
        restored["params"]["dense"]["kernel"], host["params"]["dense"]["kernel"]
    )
    # restore onto a DIFFERENT mesh (dp=8) — bit-identical after re-place
    mesh_b = make_mesh(MeshSpec(dp=8))
    placed = jax.device_put(
        restored["params"]["dense"]["kernel"],
        param_sharding(mesh_b, host["params"]["dense"]["kernel"]),
    )
    np.testing.assert_array_equal(np.asarray(placed), host["params"]["dense"]["kernel"])
    # ... and onto a single chip (serve/eval)
    single = make_mesh(MeshSpec(dp=1), jax.devices()[:1])
    placed1 = jax.device_put(
        restored["params"]["dense"]["kernel"],
        param_sharding(single, host["params"]["dense"]["kernel"]),
    )
    np.testing.assert_array_equal(np.asarray(placed1), host["params"]["dense"]["kernel"])
    # layout manifest recorded the save-side mesh for the reshard counter
    assert shck.saved_mesh_shape(path) == {"dp": 4, "fsdp": 2, "tp": 1, "sp": 1}


def test_sharded_ckpt_restores_into_target_structure(tmp_path):
    mesh = make_mesh(MeshSpec(dp=4, fsdp=2))
    tree, host = _param_tree(mesh)
    path = str(tmp_path / "it2.ckpt")
    shck.save_sharded(path, tree)
    target = jax.tree.map(np.zeros_like, host)
    out = load_checkpoint(path, target=target)
    # tuples stay tuples through the target overlay (optax state shapes)
    assert isinstance(out["state"]["opt"], tuple)
    np.testing.assert_array_equal(out["state"]["opt"][0], host["opt"][0])
    assert int(out["state"]["opt"][1]) == 7


def test_corrupt_one_shard_fails_typed_and_falls_back(tmp_path):
    """One flipped bit in ONE parameter shard fails the whole generation
    (CheckpointCorruptError) and the manager falls back to the previous
    generation — PR 4's durability contract extended to the sharded layout."""
    mesh = make_mesh(MeshSpec(dp=4, fsdp=2))
    mgr = CheckpointManager(str(tmp_path))
    tree1, host1 = _param_tree(mesh, seed=1)
    tree2, _ = _param_tree(mesh, seed=2)
    p1, p2 = str(tmp_path / "it1.ckpt"), str(tmp_path / "it2.ckpt")
    shck.save_sharded(p1, tree1, metadata={"last_iter": 1})
    mgr.record(p1, step=1)
    shck.save_sharded(p2, tree2, metadata={"last_iter": 2})
    mgr.record(p2, step=2)

    # newest generation: flip one bit in one shard blob
    shard = sorted(glob.glob(os.path.join(p2, "*.shard")))[0]
    blob = bytearray(open(shard, "rb").read())
    blob[-1] ^= 0x01
    open(shard, "wb").write(bytes(blob))

    assert not verify_checkpoint(p2)
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(p2)
    resolved = mgr.resolve_latest()
    assert resolved is not None and resolved["path"] == p1
    out = mgr.load_latest()
    assert out["path"] == p1
    np.testing.assert_array_equal(
        out["state"]["params"]["dense"]["kernel"],
        host1["params"]["dense"]["kernel"],
    )


def test_missing_shard_fails_typed(tmp_path):
    mesh = make_mesh(MeshSpec(dp=8))
    tree, _ = _param_tree(mesh)
    path = str(tmp_path / "it3.ckpt")
    shck.save_sharded(path, tree)
    os.unlink(sorted(glob.glob(os.path.join(path, "*.shard")))[0])
    assert not verify_checkpoint(path)
    with pytest.raises(CheckpointCorruptError, match="missing shard"):
        load_checkpoint(path)


# ------------------------------------------------ stale-resume poisoning fix

def test_experiments_root_env_scopes_default_dirs(monkeypatch, tmp_path):
    from distar_tpu.learner.base_learner import experiments_root

    monkeypatch.setenv("DISTAR_EXPERIMENTS_ROOT", str(tmp_path / "scoped"))
    assert experiments_root() == str(tmp_path / "scoped")
    monkeypatch.delenv("DISTAR_EXPERIMENTS_ROOT")
    assert experiments_root() == os.path.join(os.getcwd(), "experiments")


def test_resume_rejects_mismatched_checkpoint(tmp_path):
    """Auto-resume validation: a latest-pointer generation whose leaves
    don't fit this learner (stale experiment dir from a different model
    config) raises CheckpointMismatchError on direct restore, and
    resume_latest skips it — falling back to an OLDER generation that DOES
    fit instead of silently training on foreign weights."""
    from distar_tpu.learner import RLLearner
    from distar_tpu.utils.checkpoint import save_checkpoint

    learner = RLLearner({
        "common": {"experiment_name": "mismatch", "save_path": str(tmp_path)},
        "learner": {"batch_size": 2, "unroll_len": 2,
                    "save_freq": 10 ** 9, "log_freq": 10 ** 9},
        "model": SMALL_MODEL,
    })
    ckpt_dir = os.path.join(str(tmp_path), "checkpoints")
    # generation 1: a GOOD checkpoint of this very learner
    good = os.path.join(ckpt_dir, "iteration_1.ckpt")
    save_checkpoint(good, learner.state, metadata={"last_iter": 1})
    learner.checkpoint_manager.record(good, step=1)
    # generation 2 (newest): same tree paths, param leaves reshaped — the
    # stale foreign-run poison (a different model config under the same
    # experiment name)
    host = jax.tree.map(np.asarray, learner.state)
    poisoned_state = dict(host, params=jax.tree.map(
        lambda x: np.zeros(x.shape + (2,), x.dtype), host["params"]))
    bad = os.path.join(ckpt_dir, "iteration_2.ckpt")
    save_checkpoint(bad, poisoned_state, metadata={"last_iter": 2})
    learner.checkpoint_manager.record(bad, step=2)

    with pytest.raises(CheckpointMismatchError, match="does not fit"):
        learner.restore(bad)
    resumed = learner.resume_latest()
    assert resumed == good
    assert learner.last_iter.val == 1


# --------------------------------------------------- tier-1 multichip smoke

def test_multichip_smoke_executed_train_step(tmp_path):
    """The acceptance smoke: a 2-step --mesh dp=2 train on the forced host
    devices runs the EXECUTED (non-dryrun) GSPMD path — live-mesh jitted
    step, ShardFeeder double-buffered sharded feeding, sharded checkpoint
    on exit — and the prefetch overlap contract holds (feeder wait < step
    time)."""
    from distar_tpu.parallel.executor import run_sharded_training

    rep = run_sharded_training(
        "dp=2", iters=2, batch_size=2, unroll_len=2,
        model_cfg=SMALL_MODEL, experiment_name="mc_smoke",
        save_dir=str(tmp_path / "exp"), save_freq=1, sharded_ckpt=True,
        max_devices=2,
    )
    assert rep["iters"] == 2
    assert rep["mesh"]["dp"] == 2
    assert np.isfinite(rep["loss"])
    # batches actually flowed through the feeder and steps consumed them
    assert rep["feeder"]["batches"] >= 2
    # prefetch overlap: the learner's wait on the feeder must be below the
    # device step time (host collate of fake batches is cheap; the double
    # buffer hides it behind the step)
    assert rep["feeder"]["wait_s_mean"] < max(rep["step_time_s"], 1e-3)
    # the run-exit save produced a SHARDED checkpoint that verifies and
    # reloads bit-identically
    gens = CheckpointManager(os.path.join(str(tmp_path / "exp"), "checkpoints")).generations()
    assert gens, "no generation recorded"
    assert shck.is_sharded_checkpoint(gens[0]["path"])
    assert verify_checkpoint(gens[0]["path"])
    out = load_checkpoint(gens[0]["path"])
    assert out["metadata"]["last_iter"] == 2


def test_rl_train_cli_mesh_wiring():
    """--mesh reaches the learner constructor and flips sharded_ckpt on by
    default (no training here — parse/wiring only)."""
    import argparse

    from distar_tpu.bin.rl_train import _learner_cfg, _mesh_from_args

    args = argparse.Namespace(
        mesh="dp=4,fsdp=2", sharded_ckpt=None, experiment_name="t",
        save_path="", batch_size=8, traj_len=2, iters=4,
    )
    mesh = _mesh_from_args(args)
    assert dict(mesh.shape) == {"dp": 4, "fsdp": 2, "tp": 1, "sp": 1}
    cfg = _learner_cfg(args, {})
    assert cfg["learner"]["sharded_ckpt"] is True
    args.sharded_ckpt = False
    assert _learner_cfg(args, {})["learner"]["sharded_ckpt"] is False
    args.mesh = ""
    args.sharded_ckpt = None
    assert _mesh_from_args(args) is None
    assert _learner_cfg(args, {})["learner"]["sharded_ckpt"] is False


# ------------------------------------------------------------ slow coverage

@pytest.mark.slow
def test_bench_multichip_case(tmp_path):
    """BENCH_MODE=multichip emits a SUSPECT-gated scaling artifact with
    dp=1/2/4 step times (CPU-derived, structural only)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, BENCH_MODE="multichip", BENCH_MULTICHIP_ITERS="2",
               BENCH_COMPILE_CACHE="/tmp/jax_cache_distar_tpu")
    env.pop("JAX_PLATFORMS", None)
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--run"],
        env=env, capture_output=True, text=True, timeout=1500, cwd=repo,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    lines = [json.loads(l) for l in out.stdout.splitlines() if l.startswith("{")]
    result = [l for l in lines if "multichip" in l][-1]
    assert result["suspect"] is True
    assert set(result["multichip"]["points"]) == {"1", "2", "4"} or set(
        result["multichip"]["points"]) == {1, 2, 4}
    for p in result["multichip"]["points"].values():
        assert p["step_time_s"] > 0


@pytest.mark.slow
def test_chaos_multichip_drill(tmp_path):
    """The chaos acceptance: learner killed after a sharded save on
    dp=4,fsdp=2 resumes on dp=8 and finishes unassisted."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "chaos.py"),
         "multichip-drill", "--dir", str(tmp_path), "--iters", "4",
         "--kill-after", "2"],
        capture_output=True, text=True, timeout=1800, cwd=repo,
        env={**os.environ, "DISTAR_EXPERIMENTS_ROOT": str(tmp_path / "expr")},
    )
    assert out.returncode == 0, out.stdout[-3000:] + out.stderr[-2000:]
    assert "finished unassisted" in out.stdout
