"""obs/perf.py: shared flop/memory helpers, collective estimate, PerfMonitor
gauges (the live ``distar_perf_*`` surface the BaseLearner run loop feeds)."""
import threading
import time

import jax
import jax.numpy as jnp
import pytest

from distar_tpu.obs import MetricsRegistry
from distar_tpu.obs.perf import (
    PerfMonitor,
    estimate_collective_bytes,
    flops_of_compiled,
    flops_of_lowered,
    memory_report,
    peak_flops,
)


def test_peak_flops_table():
    assert peak_flops("TPU v5 lite") == 197e12
    assert peak_flops("TPU v5p") == 459e12  # longest match wins over "v5"
    assert peak_flops("cpu") is None
    assert peak_flops("") is None


def test_flops_and_memory_helpers_on_real_lowering():
    @jax.jit
    def f(x, w):
        return jnp.dot(x, w)

    x = jnp.ones((64, 64), jnp.float32)
    lowered = f.lower(x, x)
    flops = flops_of_lowered(lowered)
    # 2*N^3 for a square matmul; cost analysis may add elementwise epsilon
    assert flops >= 2 * 64 ** 3
    compiled = lowered.compile()
    # CPU may or may not report optimized counts/memory — the helpers must
    # degrade to 0.0/{} rather than raise
    assert flops_of_compiled(compiled) >= 0.0
    mem = memory_report(compiled)
    assert isinstance(mem, dict)
    if mem:
        assert "total_mb" in mem


def test_flops_helpers_swallow_backend_errors():
    class Broken:
        def cost_analysis(self):
            raise RuntimeError("no analysis on this backend")

        def memory_analysis(self):
            raise RuntimeError("nope")

    assert flops_of_lowered(Broken()) == 0.0
    assert flops_of_compiled(Broken()) == 0.0
    assert memory_report(Broken()) == {}


def test_estimate_collective_bytes_dp_and_fsdp():
    from distar_tpu.parallel import MeshSpec, make_mesh

    params = {"w": jnp.ones((1000,), jnp.float32)}  # 4000 bytes
    mesh = make_mesh(MeshSpec(dp=4), jax.devices()[:4])
    est = estimate_collective_bytes(mesh, params)
    assert est["param_bytes"] == 4000.0
    assert est["grad_allreduce"] == pytest.approx(2 * 3 / 4 * 4000)
    assert "fsdp_allgather" not in est
    mesh2 = make_mesh(MeshSpec(dp=2, fsdp=2), jax.devices()[:4])
    est2 = estimate_collective_bytes(mesh2, params)
    assert est2["grad_allreduce"] == pytest.approx(2 * 1 / 2 * 4000)
    assert est2["fsdp_allgather"] == pytest.approx(2 * 1 / 2 * 4000)
    assert est2["fsdp_reducescatter"] == pytest.approx(1 / 2 * 4000)
    assert est2["total"] == pytest.approx(
        est2["grad_allreduce"] + est2["fsdp_allgather"] + est2["fsdp_reducescatter"])


def _snapshot(reg):
    return reg.snapshot()


def test_perf_monitor_on_step_gauges():
    reg = MetricsRegistry()
    mon = PerfMonitor("t", registry=reg, mem_sample_every=10 ** 9)
    mon.on_step(0.5, frames=100.0)
    snap = _snapshot(reg)
    assert snap["distar_perf_frames_per_s{token=t}"] == pytest.approx(200.0)
    assert snap["distar_perf_step_seconds{token=t}"] == pytest.approx(0.5)
    # no flops yet -> tflops/mfu gauges stay at their registered zero
    assert snap["distar_perf_implied_tflops{token=t}"] == 0.0
    assert snap["distar_perf_mfu{token=t}"] == 0.0
    mon.flops_per_step = 1e12
    mon.peak = 2e12
    mon.on_step(1.0, frames=100.0)
    snap = _snapshot(reg)
    assert snap["distar_perf_implied_tflops{token=t}"] == pytest.approx(1.0)
    assert snap["distar_perf_mfu{token=t}"] == pytest.approx(0.5)
    assert mon.snapshot()["mfu"] == pytest.approx(0.5)
    # zero/negative step time is ignored, never a ZeroDivisionError
    mon.on_step(0.0, frames=100.0)


def test_perf_monitor_background_analysis_extracts_flops():
    reg = MetricsRegistry()
    mon = PerfMonitor("t", registry=reg)

    @jax.jit
    def step(x, w):
        return jnp.dot(x, w)

    x = jnp.ones((32, 32), jnp.float32)
    mon.note_step_args(step, x, x)
    mon.note_step_args(step, x, x)  # idempotent: one analysis thread only
    deadline = time.time() + 30.0
    while time.time() < deadline and not mon.flops_per_step:
        time.sleep(0.05)
    assert mon.flops_per_step >= 2 * 32 ** 3
    assert _snapshot(reg)["distar_perf_flops_per_step{token=t}"] == mon.flops_per_step


def test_perf_monitor_analysis_failure_counted_not_raised():
    reg = MetricsRegistry()
    mon = PerfMonitor("t", registry=reg)

    class Unlowerable:
        def lower(self, *a):
            raise RuntimeError("boom")

    mon.note_step_args(Unlowerable(), jnp.ones((2,)))
    deadline = time.time() + 10.0
    while time.time() < deadline:
        if _snapshot(reg).get(
                "distar_perf_analysis_failures_total{token=t}", 0.0):
            break
        time.sleep(0.05)
    assert _snapshot(reg)["distar_perf_analysis_failures_total{token=t}"] == 1.0


def test_perf_monitor_set_collectives_publishes_gauges():
    from distar_tpu.parallel import MeshSpec, make_mesh

    reg = MetricsRegistry()
    mon = PerfMonitor("t", registry=reg)
    mesh = make_mesh(MeshSpec(dp=2, fsdp=2), jax.devices()[:4])
    mon.set_collectives(mesh, {"w": jnp.ones((100,), jnp.float32)})
    snap = _snapshot(reg)
    keys = [k for k in snap if k.startswith("distar_perf_collective_bytes_per_step")]
    assert len(keys) == 3  # grad_allreduce + fsdp_allgather + fsdp_reducescatter


def test_perf_monitor_thread_safety_of_note():
    # concurrent first-iteration calls from racing threads: exactly one wins
    reg = MetricsRegistry()
    mon = PerfMonitor("t", registry=reg)
    started = []

    class Probe:
        def lower(self, *a):
            started.append(1)
            raise RuntimeError("stop here")

    threads = [threading.Thread(target=mon.note_step_args, args=(Probe(), 1))
               for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    time.sleep(0.3)
    assert len(started) <= 1
