"""Direct unit tests for PFSP weighting math and the league race-meter
grids (previously covered only through the full-league pipeline tests)."""
import numpy as np
import pytest

from distar_tpu.league.algorithms import pfsp
from distar_tpu.league.stat_meters import CumStat, DistStat, RaceMeterGrid, UnitNumStat


# ------------------------------------------------------------------- pfsp
def test_pfsp_distributions_sum_to_one():
    w = np.array([0.1, 0.5, 0.9])
    for weighting in ("squared", "variance", "normal"):
        p = pfsp(w, weighting)
        assert p.shape == w.shape
        assert abs(p.sum() - 1.0) < 1e-12
        assert (p >= 0).all()


def test_pfsp_squared_favours_losing_matchups():
    # (1-w)^2: the opponent we lose to (w=0.1) dominates
    p = pfsp(np.array([0.1, 0.9]), "squared")
    assert p[0] > 0.9


def test_pfsp_variance_favours_even_matchups():
    p = pfsp(np.array([0.05, 0.5, 0.95]), "variance")
    assert p[1] == p.max()
    # symmetric around 0.5
    assert abs(p[0] - p[2]) < 1e-12


def test_pfsp_normal_caps_at_half():
    # min(0.5, 1-w): every w <= 0.5 contributes identically
    p = pfsp(np.array([0.0, 0.3, 0.5]), "normal")
    assert abs(p[0] - p[1]) < 1e-12 and abs(p[1] - p[2]) < 1e-12


def test_pfsp_degenerate_cases():
    # all-zero win rates -> uniform (cold-start payoff)
    p = pfsp(np.array([0.0, 0.0, 0.0]), "variance")
    assert np.allclose(p, 1 / 3)
    # all-won (w=1) zeroes every weighting -> uniform fallback
    p = pfsp(np.array([1.0, 1.0]), "squared")
    assert np.allclose(p, 0.5)
    with pytest.raises(KeyError):
        pfsp(np.array([0.5]), "bogus")


# ------------------------------------------------------------ stat meters
def test_race_meter_grid_update_and_render():
    g = RaceMeterGrid(decay=0.9, warm_up_size=1)
    g.update("zerg", {"a": 1.0, "bad": "not-a-number"})
    g.update("zerg", {"a": 3.0})
    g.update("terran", {"a": 2.0})
    assert g.game_count == {"zerg": 2, "terran": 1}
    info = g.stat_info_dict
    # warm_up_size=1: second update applies the EMA decay
    assert info["zerg"]["a"] == pytest.approx(0.9 * 1.0 + 0.1 * 3.0)
    assert info["terran"]["a"] == 2.0
    text = g.get_text()
    assert "zerg" in text and "terran" in text
    assert RaceMeterGrid().get_text() == "(empty)"


def test_dist_stat_consumes_known_keys_only():
    d = DistStat(warm_up_size=1)
    d.update_from_result("zerg", {
        "bo_distance": 4.0, "cum_distance": 2.0, "winloss": 1.0,
    })
    info = d.stat_info_dict["zerg"]
    assert info["bo_distance"] == 4.0 and info["cum_distance"] == 2.0
    assert "winloss" not in info  # not a DistStat key


def test_cum_stat_names_active_slots():
    from distar_tpu.lib.stat import CUM_DICT

    c = CumStat(warm_up_size=1)
    cum = [0] * len(CUM_DICT)
    cum[0] = 1
    cum[2] = 1
    c.update_from_result("zerg", {"cumulative_stat": cum})
    info = c.stat_info_dict["zerg"]
    assert str(CUM_DICT[0]) in info and str(CUM_DICT[2]) in info
    assert str(CUM_DICT[1]) not in info
    c.update_from_result("zerg", {})  # no cumulative_stat: no-op
    assert c.game_count["zerg"] == 1


def test_unit_num_stat_prefixes_unit_names():
    u = UnitNumStat(warm_up_size=1)
    u.update_from_result("zerg", {"unit_num": {"zergling": 30, "drone": 12}})
    info = u.stat_info_dict["zerg"]
    assert info["unit_num/zergling"] == 30.0
    assert info["unit_num/drone"] == 12.0
