"""Trace analyzer (obs/traceview.py): bucket classification, share math,
malformed-event tolerance — against the checked-in miniature trace fixture
(tests/data/mini.trace.json: 2 steps of a synthetic train module covering
every bucket, plus loop-body repeats, a foreign module, python noise and
malformed entries)."""
import gzip
import json
import os

import pytest

from distar_tpu.obs.traceview import (
    BUCKETS,
    analyze_events,
    analyze_trace,
    classify,
    device_op_events,
    find_trace_files,
    render_markdown,
)

FIXTURE = os.path.join(os.path.dirname(__file__), "data", "mini.trace.json")


def _fixture_events():
    with open(FIXTURE) as f:
        return json.load(f)["traceEvents"]


# ------------------------------------------------------------ classification
@pytest.mark.parametrize("name,scope,bucket", [
    ("dot.3", "", "matmul/MXU"),
    ("convolution.12", "", "matmul/MXU"),
    ("custom-call.1", "jit(train)/EntityEncoder/attention/softmax", "entity-attention"),
    ("fusion.7", "flash_attention_fwd", "entity-attention"),
    ("scatter.2", "", "scatter"),
    ("dynamic-update-slice.4", "", "scatter"),
    ("while.9", "", "lstm-scan"),
    ("fusion.1", "core_lstm/scan/body", "lstm-scan"),
    ("all-reduce.5", "", "collectives"),
    ("all_gather.2", "", "collectives"),
    ("collective-permute.1", "", "collectives"),
    ("infeed.1", "", "host/infeed"),
    ("copy-start.3", "", "host/infeed"),
    ("broadcast.8", "", "other"),
    ("transpose.2", "", "other"),
])
def test_classify(name, scope, bucket):
    assert classify(name, scope) == bucket


def test_collectives_outrank_matmul_in_scoped_fusions():
    # an all-reduce fused around a dot is collective time, not MXU time
    assert classify("all-reduce.3", "jit(train)/dot_general") == "collectives"


# ----------------------------------------------------------------- filtering
def test_device_op_filter_counts_malformed_and_drops_noise():
    ops, malformed = device_op_events(_fixture_events())
    # python noise (no hlo args) excluded silently; junk dur + negative dur
    # + non-dict counted as malformed
    assert malformed == 3
    assert all(op["dur_us"] >= 0 for op in ops)
    assert not any("isinstance" in op["name"] for op in ops)


# ------------------------------------------------------------------ analysis
def test_analyze_shares_sum_to_one_and_rank():
    report = analyze_events(_fixture_events())
    assert report["malformed_events"] == 3
    shares = [b["share"] for b in report["buckets"]]
    assert abs(sum(shares) - 1.0) < 1e-6
    # ranked most-expensive first
    times = [b["time_us"] for b in report["buckets"]]
    assert times == sorted(times, reverse=True)
    by_name = {b["bucket"]: b for b in report["buckets"]}
    # fixture arithmetic: matmul = 2*(400+100) + 30 (foreign module)
    assert by_name["matmul/MXU"]["time_us"] == pytest.approx(1030.0)
    # lstm-scan = 2*150 (while) + 6*10 (loop-body fusions under core_lstm)
    assert by_name["lstm-scan"]["time_us"] == pytest.approx(360.0)
    assert by_name["entity-attention"]["time_us"] == pytest.approx(400.0)
    assert set(by_name) <= set(BUCKETS)


def test_analyze_infers_steps_from_dominant_module():
    report = analyze_events(_fixture_events())
    assert report["dominant_module"] == "jit_train_step"
    # loop-body fusions appear 6x but every per-step op appears exactly 2x:
    # the min-count heuristic must land on 2
    assert report["steps_inferred"] == 2
    assert report["steps"] == 2
    assert report["step_time_device_us"] == pytest.approx(
        report["total_device_us"] / 2)


def test_analyze_explicit_steps_override():
    report = analyze_events(_fixture_events(), steps=4)
    assert report["steps"] == 4
    by_name = {b["bucket"]: b for b in report["buckets"]}
    assert by_name["scatter"]["per_step_us"] == pytest.approx(240.0 / 4)


def test_analyze_empty_trace_degrades():
    report = analyze_events([])
    assert report["total_device_us"] == 0.0
    assert report["buckets"] == []
    assert report["steps"] == 1  # divisor never 0


# ---------------------------------------------------------------- file layer
def test_find_and_analyze_logdir_layout(tmp_path):
    # the jax.profiler on-disk layout: logdir/plugins/profile/<stamp>/*.gz
    old = tmp_path / "plugins" / "profile" / "2026_01_01" / "host.trace.json.gz"
    new = tmp_path / "plugins" / "profile" / "2026_01_02" / "host.trace.json.gz"
    for i, p in enumerate((old, new)):
        p.parent.mkdir(parents=True)
        with gzip.open(p, "wt") as f:
            json.dump({"traceEvents": [
                {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 10.0 + i,
                 "name": "dot.1", "args": {"hlo_op": "dot.1", "hlo_module": "m"}},
            ]}, f)
    os.utime(old, (1, 1))  # force mtime ordering
    files = find_trace_files(str(tmp_path))
    assert files[0] == str(new)
    report = analyze_trace(str(tmp_path))
    assert report["trace_path"] == str(new)
    assert report["total_device_us"] == pytest.approx(11.0)


def test_analyze_trace_missing_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        analyze_trace(str(tmp_path))


def test_render_markdown_table():
    report = analyze_events(_fixture_events())
    md = render_markdown(report)
    assert md.startswith("| bucket |")
    assert "matmul/MXU" in md and "%" in md
    # every reported bucket appears as a row
    assert md.count("\n|") >= len(report["buckets"]) + 1
