"""Pipeline plugin registry: resolution + a custom external agent playing
through the Actor (role of the reference's agent plugin system,
distar/agent/import_helper.py + distar/agent/template/)."""
import os
import sys
import textwrap

import pytest

from distar_tpu import plugins


CUSTOM_PIPELINE_SRC = textwrap.dedent(
    """
    \"\"\"A minimal external pipeline module (docs/agent_contract.md).\"\"\"
    from distar_tpu.actor.scripted import ScriptedAgent
    from distar_tpu.learner import SLLearner as _SL


    class Agent(ScriptedAgent):
        HAS_MODEL = False

        def __init__(self, player_id="custom", seed=0, race=None, **kwargs):
            super().__init__(player_id=player_id, seed=seed)
            self.race = race
            self.acted = 0

        def act(self, obs):
            self.acted += 1
            # always no-op: action_type 0 is structurally valid everywhere
            return {
                "action_type": 0, "delay": 4, "queued": 0,
                "selected_units": [], "target_unit": 0,
                "target_location": 0,
            }


    class SLLearner(_SL):
        pass
    """
)


@pytest.fixture()
def custom_pipeline(tmp_path, monkeypatch):
    (tmp_path / "my_custom_pipeline.py").write_text(CUSTOM_PIPELINE_SRC)
    monkeypatch.syspath_prepend(str(tmp_path))
    yield "my_custom_pipeline"
    sys.modules.pop("my_custom_pipeline", None)


def test_default_resolution():
    from distar_tpu.actor.agent import Agent
    from distar_tpu.envs.replay_decoder import ReplayDecoder
    from distar_tpu.learner import RLLearner, SLLearner

    assert plugins.load_component("default", "Agent") is Agent
    assert plugins.load_component("", "RLLearner") is RLLearner
    assert plugins.load_component(None, "SLLearner") is SLLearner
    assert plugins.load_component("default", "ReplayDecoder") is ReplayDecoder


def test_scripted_resolution():
    from distar_tpu.actor.scripted import RandomAgent

    assert plugins.load_component("scripted.random", "Agent") is RandomAgent
    with pytest.raises(ValueError, match="only Agent"):
        plugins.load_component("scripted.random", "RLLearner")


def test_error_messages():
    with pytest.raises(ValueError, match="unknown component"):
        plugins.load_component("default", "Frobnicator")
    with pytest.raises(ValueError, match="bot"):
        plugins.load_component("bot", "Agent")
    with pytest.raises(ImportError, match="not importable"):
        plugins.load_component("definitely_not_a_module_xyz", "Agent")


def test_external_resolution(custom_pipeline):
    agent_cls = plugins.load_component(custom_pipeline, "Agent")
    ag = plugins.build_agent(custom_pipeline, "P9", seed=3, race="zerg")
    assert isinstance(ag, agent_cls)
    assert ag.player_id == "P9" and ag.race == "zerg"
    # the module exposes SLLearner but no RLLearner
    assert plugins.load_component(custom_pipeline, "SLLearner") is not None
    with pytest.raises(AttributeError, match="defines no 'RLLearner'"):
        plugins.load_component(custom_pipeline, "RLLearner")
    assert plugins.is_external(custom_pipeline)
    assert plugins.is_model_free(custom_pipeline)
    assert not plugins.is_external("scripted.random")
    assert not plugins.is_model_free("default")


def test_custom_agent_vs_model_job(custom_pipeline):
    """An external pipeline plays side 1 against the model side 0 on the
    mock env: no inference slot, no trajectories, episodes complete."""
    from distar_tpu.actor import Actor
    from distar_tpu.envs import MockEnv

    small_model = {
        "encoder": {
            "entity": {"layer_num": 1, "hidden_dim": 32, "output_dim": 16,
                       "head_dim": 8},
            "spatial": {"down_channels": [4, 4, 8], "project_dim": 4,
                        "resblock_num": 1, "fc_dim": 16},
            "scatter": {"output_dim": 4},
            "core_lstm": {"hidden_size": 32, "num_layers": 1},
        },
        "policy": {
            "action_type_head": {"res_dim": 16, "res_num": 1, "gate_dim": 32},
            "delay_head": {"decode_dim": 16},
            "queued_head": {"decode_dim": 16},
            "selected_units_head": {"func_dim": 16},
            "target_unit_head": {"func_dim": 16},
            "location_head": {"res_dim": 8, "res_num": 1,
                              "upsample_dims": [4, 4, 1], "map_skip_dim": 8},
        },
        "value": {"res_dim": 8, "res_num": 1},
    }
    actor = Actor(
        cfg={"actor": {"env_num": 1, "traj_len": 2, "seed": 11}},
        model_cfg=small_model,
        env_fn=lambda: MockEnv(episode_game_loops=300, seed=4),
    )
    job = {
        "player_ids": ["MP0", "EXT"],
        "pipelines": ["default", custom_pipeline],
        "send_data_players": [],
        "update_players": [],
        "teacher_player_ids": ["T", "none"],
        "branch": "eval_test",
        "env_info": {"map_name": "mock"},
    }
    results = actor.run_job(episodes=1, job=job)
    assert len(results) >= 1
    for r in results:
        assert r["0"]["player_id"] == "MP0"
        assert r["1"]["player_id"] == "EXT"


def test_shipped_example_pipeline(monkeypatch):
    """examples/custom_pipeline.py must stay loadable through the registry
    (it is the user-facing template) and act within the contract."""
    import os

    examples = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"
    )
    monkeypatch.syspath_prepend(examples)
    import numpy as np

    from distar_tpu.lib import features as F
    from distar_tpu.lib.actions import ACTIONS

    for comp in ("Agent", "SLLearner", "RLLearner"):
        assert plugins.load_component("custom_pipeline", comp) is not None
    ag = plugins.build_agent("custom_pipeline", "EX", seed=0, race="zerg")
    ag.reset()
    obs = F.fake_step_data(train=False, rng=np.random.default_rng(1))
    for _ in range(4):
        act = ag.step(obs)
        assert 0 <= int(np.asarray(act["action_type"])) < len(ACTIONS)
    sys.modules.pop("custom_pipeline", None)
