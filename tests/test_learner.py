"""Learner integration tests on the 8-device CPU mesh with a shrunken model.

This is the multi-host collective analogue of the reference's FakeLink tests:
the pjit train step runs dp=8 over virtual devices.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distar_tpu.parallel import GradClipConfig, MeshSpec, build_grad_clip, build_optimizer, make_mesh


from conftest import SMALL_MODEL  # shared tiny model config


def test_mesh_axes():
    mesh = make_mesh(MeshSpec(dp=-1))
    assert mesh.shape["dp"] == 8 and mesh.shape["tp"] == 1
    mesh2 = make_mesh(MeshSpec(dp=4, sp=2))
    assert mesh2.shape["dp"] == 4 and mesh2.shape["sp"] == 2


def test_shrink_dp_respects_fsdp():
    """shrink_dp must leave a mesh whose dp x fsdp divides the batch — the
    batch shards over BOTH axes when fsdp > 1 (mesh.dp_axes)."""
    from distar_tpu.parallel.mesh import shrink_dp

    mesh = make_mesh(MeshSpec(dp=2, fsdp=2), jax.devices()[:4])
    assert shrink_dp(mesh, 8) is mesh  # 8 % (2*2) == 0: no-op
    m6 = shrink_dp(mesh, 6)  # 6 % 4 != 0 -> must shrink
    assert 6 % (m6.shape["dp"] * m6.shape["fsdp"]) == 0
    m3 = shrink_dp(mesh, 3)
    assert 3 % (m3.shape["dp"] * m3.shape["fsdp"]) == 0


def test_grad_clip_modes():
    params = {"w": jnp.ones((3,)), "b": jnp.ones((2,))}
    grads = {"w": jnp.full((3,), 10.0), "b": jnp.full((2,), 10.0)}
    for kind in ("none", "value", "norm", "max_norm", "momentum_norm"):
        tx = build_grad_clip(GradClipConfig(type=kind, threshold=1.0))
        state = tx.init(params)
        out, _ = tx.update(grads, state, params)
        n = float(jax.tree.leaves(jax.tree.map(lambda g: jnp.abs(g).max(), out))[0])
        if kind != "none":
            assert n <= 10.0


def test_grad_clip_norm_is_exact_and_reports_activation():
    """The norm clip the flagship config ships: post-clip global norm is
    exactly min(||g||, threshold), and clip_activation (the dynamics
    tree's clip gauges) reports the removed fraction to match."""
    from distar_tpu.parallel.grad_clip import clip_activation

    params = {"w": jnp.zeros((3,)), "b": jnp.zeros((2,))}
    grads = {"w": jnp.asarray([3.0, 0.0, 0.0]), "b": jnp.asarray([0.0, 4.0])}
    gnorm = 5.0
    for threshold, expect in ((2.0, 2.0), (7.0, gnorm)):
        tx = build_grad_clip(GradClipConfig(type="norm", threshold=threshold))
        out, _ = tx.update(grads, tx.init(params), params)
        clipped_norm = float(jnp.sqrt(sum(
            jnp.sum(g * g) for g in jax.tree.leaves(out))))
        assert clipped_norm == pytest.approx(expect, rel=1e-6)
        # direction preserved: clip rescales, never rotates
        assert float(out["w"][0]) / float(out["b"][1]) == pytest.approx(3.0 / 4.0)
        frac, active = clip_activation(grads, jnp.asarray(gnorm), "norm", threshold)
        assert float(frac) == pytest.approx(max(0.0, 1.0 - threshold / gnorm))
        assert float(active) == (1.0 if gnorm > threshold else 0.0)
    # value mode: per-element census
    frac, active = clip_activation(grads, jnp.asarray(gnorm), "value", 3.5)
    assert float(frac) == pytest.approx(1.0 / 5.0)  # only b[1]=4 exceeds
    assert float(active) == 1.0
    frac, active = clip_activation(grads, jnp.asarray(gnorm), "none", 1.0)
    assert float(frac) == 0.0 and float(active) == 0.0


def test_optimizer_adam_zero_beta1():
    opt = build_optimizer(learning_rate=1e-3, betas=(0.0, 0.99), eps=1e-5,
                          clip=GradClipConfig(type="norm", threshold=1.0))
    params = {"w": jnp.zeros((4,))}
    state = opt.init(params)
    g = {"w": jnp.ones((4,))}
    updates, state = opt.update(g, state, params)
    assert jnp.all(jnp.isfinite(updates["w"]))


@pytest.fixture(scope="module")
def rl_learner(tmp_path_factory):
    from distar_tpu.learner import RLLearner

    tmp = tmp_path_factory.mktemp("rl")
    cfg = {
        "common": {"experiment_name": "t", "save_path": str(tmp)},
        "learner": {
            "batch_size": 8,
            "unroll_len": 2,
            "save_freq": 100000,
            "log_freq": 1,
        },
        "model": SMALL_MODEL,
    }
    return RLLearner(cfg)


@pytest.mark.slow
def test_rl_learner_steps_and_checkpoint(rl_learner, tmp_path):
    learner = rl_learner
    learner.run(max_iterations=2)
    assert learner.last_iter.val == 2
    assert np.isfinite(learner.variable_record.get("total_loss").avg)
    assert learner.variable_record.get("grad_norm").avg > 0
    # checkpoint roundtrip on the same (already-compiled) learner
    p = str(tmp_path / "ck.ckpt")
    learner.save(p)
    w0 = np.asarray(jax.tree.leaves(learner.state["params"])[0]).copy()
    learner.run(max_iterations=4)
    w1 = jax.tree.leaves(learner.state["params"])[0]
    assert not np.allclose(w0, np.asarray(w1))
    learner.restore(p)
    w2 = jax.tree.leaves(learner.state["params"])[0]
    np.testing.assert_allclose(w0, np.asarray(w2))
    assert learner.last_iter.val == 2


@pytest.mark.slow
def test_rl_learner_fsdp_mesh(tmp_path):
    """A mesh with a REAL second axis: params + Adam moments sharded over
    fsdp (ZeRO-style), batch sharded over dp x fsdp. Verifies the train step
    compiles and executes with non-replicated parameter shardings and that
    a checkpoint still round-trips (device_get gathers the shards)."""
    from distar_tpu.learner import RLLearner

    mesh = make_mesh(MeshSpec(dp=2, fsdp=2), jax.devices()[:4])
    cfg = {
        "common": {"experiment_name": "fsdp", "save_path": str(tmp_path)},
        "learner": {"batch_size": 8, "unroll_len": 2, "save_freq": 100000, "log_freq": 1},
        "model": SMALL_MODEL,
    }
    learner = RLLearner(cfg, mesh=mesh)
    # at least one large param leaf must actually shard over fsdp
    specs = [
        x.sharding.spec
        for x in jax.tree.leaves(learner.state["params"])
        if hasattr(x, "sharding")
    ]
    assert any("fsdp" in str(s) for s in specs), specs
    # and the Adam moments follow (1/fsdp-sized opt state per device)
    mom_specs = [x.sharding.spec for x in jax.tree.leaves(learner.state["opt_state"])]
    assert any("fsdp" in str(s) for s in mom_specs), mom_specs
    learner.run(max_iterations=2)
    assert learner.last_iter.val == 2
    assert np.isfinite(learner.variable_record.get("total_loss").avg)
    p = str(tmp_path / "fsdp.ckpt")
    learner.save(p)
    w0 = np.asarray(jax.tree.leaves(learner.state["params"])[0]).copy()
    learner.restore(p)
    np.testing.assert_allclose(w0, np.asarray(jax.tree.leaves(learner.state["params"])[0]))


@pytest.mark.slow
def test_sl_learner_steps(tmp_path):
    from distar_tpu.learner import SLLearner

    cfg = {
        "common": {"experiment_name": "t", "save_path": str(tmp_path)},
        "learner": {"batch_size": 8, "unroll_len": 2, "save_freq": 100000, "log_freq": 1},
        "model": SMALL_MODEL,
    }
    learner = SLLearner(cfg)
    learner.run(max_iterations=2)
    assert learner.last_iter.val == 2
    assert np.isfinite(learner.variable_record.get("total_loss").avg)
    assert np.isfinite(learner.variable_record.get("action_type_acc").avg)


def test_sl_learner_save_grad_logs_leaf_norms(tmp_path):
    """learner.save_grad folds per-parameter grad/param L2 norms into the
    log (role of the reference's save_grad TB dumps,
    rl_learner.py:35-47,118-130)."""
    from distar_tpu.learner import SLLearner

    cfg = {
        "common": {"experiment_name": "sg", "save_path": str(tmp_path)},
        "learner": {"batch_size": 4, "unroll_len": 2, "save_freq": 100000,
                    "log_freq": 1, "save_grad": True},
        "model": SMALL_MODEL,
    }
    learner = SLLearner(cfg)
    learner.run(max_iterations=1)
    names = set(learner.variable_record.vars())
    per_param_grad = [n for n in names if n.startswith("grad_norm/")]
    per_param_w = [n for n in names if n.startswith("param_norm/")]
    assert len(per_param_grad) > 10 and len(per_param_grad) == len(per_param_w)
    for n in per_param_grad[:5] + per_param_w[:5]:
        assert np.isfinite(learner.variable_record.get(n).avg)


@pytest.mark.slow
def test_rl_learner_save_grad_logs_leaf_norms(tmp_path):
    """RL wiring of learner.save_grad (both the init jit and the admin
    config-patch rebuild thread the same kwarg into make_rl_train_step)."""
    from distar_tpu.learner import RLLearner

    cfg = {
        "common": {"experiment_name": "sg_rl", "save_path": str(tmp_path)},
        "learner": {"batch_size": 2, "unroll_len": 2, "save_freq": 100000,
                    "log_freq": 1, "save_grad": True},
        "model": SMALL_MODEL,
    }
    learner = RLLearner(cfg)
    learner.run(max_iterations=1)
    names = set(learner.variable_record.vars())
    grads = [n for n in names if n.startswith("grad_norm/")]
    assert len(grads) > 10
    assert len(grads) == len([n for n in names if n.startswith("param_norm/")])


def test_sl_loss_spike_guard_snapshots(tmp_path):
    """debug_loss_spike: a loss term jumping past factor x its EMA (or going
    non-finite) after warmup dumps the step's exact inputs + a checkpoint
    (reference SL debug mode, sl_learner.py:55-60)."""
    import glob
    import os

    from distar_tpu.comm.serializer import loads
    from distar_tpu.learner import SLLearner

    cfg = {
        "common": {"experiment_name": "spike", "save_path": str(tmp_path)},
        "learner": {"batch_size": 4, "unroll_len": 2, "save_freq": 100000,
                    "log_freq": 10, "debug_loss_spike": True,
                    "debug_spike_factor": 10.0, "debug_spike_warmup": 0},
        "model": SMALL_MODEL,
    }
    learner = SLLearner(cfg)
    learner.run(max_iterations=1)  # primes the EMA from real values

    def spike_files():
        return glob.glob(os.path.join(str(tmp_path), "debug", "*.spike"))

    pre_step = {"batch": {"x": np.zeros(2)}, "hidden_state": learner._hidden,
                "new_episodes": np.zeros(4, bool), "traj_lens": None}

    # drive the guard directly with a synthetic 20x spike
    base = dict(learner._debug_ema)
    spiked_key = next(k for k in base if "loss" in k and base[k] > 0.01)
    log = dict(base)
    log[spiked_key] = base[spiked_key] * 20 + 1.0
    learner.last_iter.update(5)
    learner._loss_spike_guard(log, pre_step)

    dumps = spike_files()
    assert len(dumps) == 1
    snap = loads(open(dumps[0], "rb").read())
    assert snap["key"] == spiked_key
    # the step's exact inputs travel with the snapshot
    assert "batch" in snap and "hidden_state" in snap and "new_episodes" in snap
    assert "note" in snap  # params-offset caveat recorded
    assert os.path.exists(learner.checkpoint_path())
    # the dump folded the spike into the EMA (0.95/0.05)
    assert learner._debug_ema[spiked_key] == pytest.approx(
        base[spiked_key] * 0.95 + log[spiked_key] * 0.05
    )

    # near-zero EMA (masked heads) must NOT trigger on normal growth
    learner._debug_ema[spiked_key] = 1e-6
    learner._loss_spike_guard({spiked_key: 0.5}, pre_step)
    assert len(spike_files()) == 1

    # a finite -> non-finite transition MUST trigger and not poison the EMA
    learner._debug_ema[spiked_key] = 2.0
    learner._loss_spike_guard({spiked_key: float("nan")}, pre_step)
    assert len(spike_files()) == 2
    assert learner._debug_ema[spiked_key] == 2.0

    # non-finite from the FIRST iteration (no EMA ever seeded) also dumps —
    # a run that diverges immediately is the headline event
    learner._debug_ema.pop("fresh_loss", None)
    learner._loss_spike_guard({"fresh_loss": float("inf")}, pre_step)
    assert len(spike_files()) == 3

    # the dump cap bounds disk usage
    learner._debug_dumps = learner._DEBUG_DUMP_CAP
    learner._loss_spike_guard({spiked_key: 1e9}, pre_step)
    assert len(spike_files()) == 3

def test_rl_learner_with_value_feature(tmp_path):
    """Centralized-critic path: use_value_feature routes opponent features
    through the ValueEncoder into every baseline tower."""
    from distar_tpu.learner import RLLearner

    model = dict(SMALL_MODEL)
    model = {**model, "use_value_feature": True}
    cfg = {
        "common": {"experiment_name": "vf", "save_path": str(tmp_path)},
        "learner": {"batch_size": 8, "unroll_len": 2, "save_freq": 100000, "log_freq": 1},
        "model": model,
    }
    learner = RLLearner(cfg)
    learner.run(max_iterations=1)
    assert learner.last_iter.val == 1
    assert np.isfinite(learner.variable_record.get("total_loss").avg)


@pytest.mark.slow
def test_learner_admin_api(rl_learner):
    """Live admin surface: status, value reset, config patch between iters."""
    import urllib.request, json as _json

    learner = rl_learner
    learner.run(max_iterations=max(learner.last_iter.val + 1, 1))
    admin = learner.start_admin()

    def post(route, body=None):
        req = urllib.request.Request(
            f"http://{admin.host}:{admin.port}/learner/{route}",
            data=_json.dumps(body or {}).encode(), method="POST",
            headers={"Content-Type": "application/json"},
        )
        return _json.loads(urllib.request.urlopen(req, timeout=10).read())

    try:
        status = post("status")
        assert status["code"] == 0 and status["info"]["last_iter"] >= 1
        # queue a value reset + lr patch; both apply on the next iteration
        w_before = np.asarray(
            jax.tree.leaves(learner.state["params"]["params"]["value_winloss"])[0]
        ).copy()
        assert post("reset_value")["code"] == 0
        assert post("update_config", {"config": {"learner": {"learning_rate": 5e-6}}})["code"] == 0
        learner.run(max_iterations=learner.last_iter.val + 1)
        w_after = np.asarray(
            jax.tree.leaves(learner.state["params"]["params"]["value_winloss"])[0]
        )
        assert not np.allclose(w_before, w_after)
        assert float(learner.cfg.learner.learning_rate) == 5e-6
        assert post("bogus")["code"] == 404
    finally:
        admin.stop()


def test_admin_profile_route_e2e(tmp_path):
    """Tier-1 perf-attribution acceptance: POST /profile?steps=2 on a LIVE
    learner captures a real jax.profiler trace at iteration boundaries and
    returns a ranked bucket report whose shares sum to 100%+-1 of measured
    device time (obs/traceview.py through learner/admin.py)."""
    import json as _json
    import threading
    import urllib.request

    from distar_tpu.learner import SLLearner

    cfg = {
        "common": {"experiment_name": "prof", "save_path": str(tmp_path)},
        # same step signature as test_sl_learner_save_grad_logs_leaf_norms,
        # so the persistent compile cache serves the executable
        "learner": {"batch_size": 4, "unroll_len": 2, "save_freq": 100000,
                    "log_freq": 100000, "save_grad": True},
        "model": SMALL_MODEL,
    }
    learner = SLLearner(cfg)
    learner.run(max_iterations=1)  # compile OUTSIDE the capture window
    admin = learner.start_admin()
    runner_err = []

    def runner():
        try:
            # generous ceiling; request_stop ends the loop once profiled
            learner.run(max_iterations=learner.last_iter.val + 10_000)
        except Exception as e:  # pragma: no cover - surfaced via assert
            runner_err.append(e)

    thread = threading.Thread(target=runner, daemon=True)
    thread.start()
    try:
        req = urllib.request.Request(
            f"http://{admin.host}:{admin.port}/learner/profile"
            f"?steps=2&timeout_s=240",
            data=b"{}", method="POST",
            headers={"Content-Type": "application/json"},
        )
        body = _json.loads(urllib.request.urlopen(req, timeout=300).read())
    finally:
        learner.request_stop()
        thread.join(timeout=300)
        admin.stop()
    assert not runner_err, runner_err
    assert not thread.is_alive()
    assert body["code"] == 0, body
    report = body["info"]
    assert report["captured_steps"] == 2
    assert report["total_device_us"] > 0
    buckets = report["buckets"]
    assert buckets, report
    # shares partition measured device time: sum to 100% +- 1
    assert abs(sum(b["share"] for b in buckets) - 1.0) < 0.01
    # ranked most-expensive first
    times = [b["time_us"] for b in buckets]
    assert times == sorted(times, reverse=True)
    # a real train step must show MXU work and a rendered table
    assert any(b["bucket"] == "matmul/MXU" for b in buckets)
    assert "| bucket |" in report["markdown"]
    # the capture wrote a real trace under the experiment dir
    assert str(tmp_path) in report["trace_path"]
    # profile requests after the loop stopped fail typed, not hang
    with pytest.raises(Exception):
        learner.request_profile(steps=1, timeout_s=0.5)


def test_device_prefetcher_order_and_errors():
    from distar_tpu.learner.prefetch import DevicePrefetcher

    batches = [{"i": i} for i in range(5)]
    pf = DevicePrefetcher(iter(batches), lambda b: {**b, "placed": True}, depth=2)
    out = list(pf)
    assert [b["i"] for b in out] == list(range(5))
    assert all(b["placed"] for b in out)

    def boom():
        yield {"i": 0}
        raise RuntimeError("producer failed")

    pf = DevicePrefetcher(boom(), lambda b: b, depth=2)
    assert next(pf)["i"] == 0
    import pytest as _pytest

    with _pytest.raises(RuntimeError, match="producer failed"):
        next(pf)


def test_rl_cap_entities_exact_below_cap(tmp_path):
    """cap_entities_rl (learner.max_entities on the RL learner) is
    numerically exact within the cap: same batch trained at the 512 pad and
    sliced to 256 yields the same loss grid. (A real teacher's logits carry
    ~zero mass beyond its masked candidates; the fake teacher's off-label
    tails are e^-40 relative — negligible.)"""
    from distar_tpu.learner import RLLearner
    from distar_tpu.learner.data import fake_rl_batch

    rng = np.random.default_rng(11)
    batch = fake_rl_batch(4, 2, rng=rng, hidden_size=32, hidden_layers=1)
    batch["entity_num"] = np.minimum(batch["entity_num"], 250)
    batch["model_last_iter"] = np.zeros(4)
    # re-pin end tokens to the clamped entity_num (fake labels put the end
    # flag at the ORIGINAL entity_num)
    su = batch["action_info"]["selected_units"]
    sun = batch["selected_units_num"]
    for t in range(su.shape[0]):
        for b in range(su.shape[1]):
            su[t, b, sun[t, b] - 1] = batch["entity_num"][t, b]
    onehot = np.eye(513, dtype=np.float32)[su]
    batch["teacher_logit"]["selected_units"] = (40.0 * onehot - 20.0).astype(np.float32)

    logs = {}
    for name, cap in (("full", None), ("capped", 256)):
        cfg = {
            "common": {"experiment_name": f"rlcap_{name}", "save_path": str(tmp_path)},
            "learner": {"batch_size": 4, "unroll_len": 2, "save_freq": 100000,
                        "log_freq": 10 ** 9, "max_entities": cap},
            "model": SMALL_MODEL,
        }
        learner = RLLearner(cfg)
        logs[name] = learner._train(dict(batch))
    for k in logs["full"]:
        if k.startswith("staleness"):
            continue
        np.testing.assert_allclose(
            logs["full"][k], logs["capped"][k], rtol=2e-4, atol=2e-4,
            err_msg=f"RL loss term {k} diverged under the entity cap",
        )


def test_rl_cap_entities_overflow_semantics():
    """Above-cap RL steps: every out-of-range selected_units lane clamps
    into range (post-end junk lanes would gather OOB in the sliced decode)
    and the su/tu masks zero for overflow steps (a truncated teacher
    distribution would bias the KL)."""
    from distar_tpu.learner.data import cap_entities_rl, fake_rl_batch

    batch = fake_rl_batch(2, 1, rng=np.random.default_rng(5))
    batch["entity_num"][:] = 100
    batch["entity_num"][0, 0] = 300  # step 0, sample 0 overflows cap 256
    su = batch["action_info"]["selected_units"]
    su[0, 0, :] = 280  # junk + labels beyond the cap
    out = cap_entities_rl(batch, 256)
    assert out["entity_num"].max() == 256
    assert out["action_info"]["selected_units"].max() <= 256  # all in range
    am = out["mask"]["actions_mask"]
    assert am["selected_units"][0, 0] == 0.0 and am["target_unit"][0, 0] == 0.0
    assert am["selected_units"][0, 1] == 1.0  # non-overflow sample untouched
    assert out["teacher_logit"]["selected_units"].shape[-1] == 257
    assert out["teacher_logit"]["target_unit"].shape[-1] == 256


@pytest.mark.slow
def test_rl_learner_resume_latest_with_corrupt_fallback(rl_learner, chaos):
    """The real learner's crash-resume path: save() publishes the durable
    latest pointer, resume_latest() restores from it, and a truncated
    newest checkpoint falls back to the previous generation."""
    learner = rl_learner
    learner.run(max_iterations=max(learner.last_iter.val, 2))
    p1 = learner.checkpoint_path()
    learner.save(p1, sync=True)
    iter1 = learner.last_iter.val
    w1 = np.asarray(jax.tree.leaves(learner.state["params"])[0]).copy()
    learner.run(max_iterations=iter1 + 2)
    p2 = learner.checkpoint_path()
    learner.save(p2, sync=True)
    assert learner.checkpoint_manager.resolve_latest()["path"] == p2
    chaos.truncate(p2)  # torn newest checkpoint
    assert learner.resume_latest() == p1  # fell back a generation
    assert learner.last_iter.val == iter1
    np.testing.assert_allclose(
        w1, np.asarray(jax.tree.leaves(learner.state["params"])[0])
    )
