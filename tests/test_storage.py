"""Pluggable storage backends (utils/storage.py): scheme routing, the
atomic local write discipline, the mem:// blob store, checkpoint and
payload IO riding the seam, and the gs:// stub's guidance error (role of
the reference file_helper's ceph/memcached dispatch, file_helper.py:30-32).
"""
import os

import numpy as np
import pytest

from distar_tpu.comm.serializer import load_payload, save_payload
from distar_tpu.utils import storage
from distar_tpu.utils.checkpoint import load_checkpoint, save_checkpoint


def test_local_roundtrip_atomic(tmp_path):
    path = str(tmp_path / "sub" / "blob.bin")
    storage.write_bytes(path, b"abc")  # creates parent dirs
    assert storage.read_bytes(path) == b"abc"
    assert storage.exists(path)
    assert not [f for f in os.listdir(tmp_path / "sub") if ".tmp." in f]
    storage.write_bytes(path, b"xyz")  # overwrite is atomic replace
    assert storage.read_bytes(path) == b"xyz"
    storage.delete(path)
    assert not storage.exists(path)


def test_file_scheme_is_local(tmp_path):
    path = str(tmp_path / "x.bin")
    storage.write_bytes(f"file://{path}", b"1")
    assert storage.read_bytes(path) == b"1"


def test_mem_backend_roundtrip():
    storage.write_bytes("mem://bucket/a", b"payload")
    assert storage.exists("mem://bucket/a")
    assert storage.read_bytes("mem://bucket/a") == b"payload"
    backend, _ = storage.resolve("mem://bucket/a")
    assert list(backend.list("bucket/")) == ["bucket/a"]
    storage.delete("mem://bucket/a")
    assert not storage.exists("mem://bucket/a")
    with pytest.raises(FileNotFoundError):
        storage.read_bytes("mem://bucket/a")


def test_unknown_scheme_and_custom_registration():
    with pytest.raises(ValueError, match="no storage backend"):
        storage.read_bytes("s3://bucket/key")
    storage.register_backend("s3", storage.MemBackend())
    try:
        storage.write_bytes("s3://bucket/key", b"ok")
        assert storage.read_bytes("s3://bucket/key") == b"ok"
    finally:
        del storage._BACKENDS["s3"]


def test_gcs_stub_raises_with_guidance():
    with pytest.raises(RuntimeError, match="google-cloud-storage"):
        storage.read_bytes("gs://bucket/ckpt")


def test_checkpoint_rides_backends():
    state = {"w": np.arange(6, dtype=np.float32).reshape(2, 3), "step": 7}
    save_checkpoint("mem://ckpts/it1", state, metadata={"iter": 1})
    out = load_checkpoint("mem://ckpts/it1")
    np.testing.assert_array_equal(out["state"]["w"], state["w"])
    assert out["metadata"]["iter"] == 1


def test_payload_rides_backends():
    obj = {"traj": np.ones((4, 5), np.float16), "meta": [1, 2, 3]}
    save_payload("mem://payloads/t0", obj)
    back = load_payload("mem://payloads/t0")
    np.testing.assert_array_equal(back["traj"], obj["traj"])
    assert back["meta"] == [1, 2, 3]
