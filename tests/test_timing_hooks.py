"""Direct unit tests for StopWatch and the learner hook registry
(previously exercised only through full learner runs; EasyTimer has a
basic check in test_utils.py — here it gets the reuse semantics)."""
import time
import types

import pytest

from distar_tpu.learner.hooks import (
    Hook,
    HookRegistry,
    LambdaHook,
    LoadCkptHook,
    ProfilerHook,
    SaveCkptHook,
)
from distar_tpu.utils.timing import EasyTimer, StopWatch


# ------------------------------------------------------------------ timing
def test_easy_timer_measures_block():
    t = EasyTimer()
    with t:
        time.sleep(0.02)
    first = t.value
    assert first > 0.015
    with t:  # reusable; value overwritten
        pass
    assert t.value < first  # empty block must re-measure, not accumulate


def test_stopwatch_disabled_records_nothing():
    sw = StopWatch(enabled=False)
    with sw("phase"):
        time.sleep(0.005)
    assert sw.times == {} and sw.summary() == {}


def test_stopwatch_enabled_accumulates_and_summarises():
    sw = StopWatch(enabled=True)
    for _ in range(3):
        with sw("step"):
            time.sleep(0.003)

    @sw.decorate("fn")
    def work(x):
        return x + 1

    assert work(1) == 2
    s = sw.summary()
    assert s["step"]["num"] == 3
    assert s["step"]["sum"] >= 0.009
    assert s["step"]["avg"] == pytest.approx(s["step"]["sum"] / 3)
    assert s["fn"]["num"] == 1


# ------------------------------------------------------------------- hooks
def _fake_learner(iter_val=0):
    learner = types.SimpleNamespace()
    learner.last_iter = types.SimpleNamespace(val=iter_val)
    learner.calls = []
    return learner


def test_registry_orders_by_priority_and_respects_freq():
    reg = HookRegistry()
    order = []
    reg.add(LambdaHook("b", "after_iter", lambda l: order.append("b"), priority=60))
    reg.add(LambdaHook("a", "after_iter", lambda l: order.append("a"), priority=10))
    reg.add(LambdaHook("c", "after_iter", lambda l: order.append("c"),
                       priority=30, freq=2))
    learner = _fake_learner(iter_val=1)
    reg.call("after_iter", learner)
    assert order == ["a", "b"]  # freq=2 hook skipped on odd iter
    order.clear()
    learner.last_iter.val = 2
    reg.call("after_iter", learner)
    assert order == ["a", "c", "b"]  # priority order, freq hook included


def test_registry_freq_only_gates_iter_positions():
    reg = HookRegistry()
    ran = []
    reg.add(LambdaHook("r", "before_run", lambda l: ran.append(1), freq=1000))
    reg.call("before_run", _fake_learner(iter_val=1))
    assert ran == [1]  # run-positions ignore freq


def test_hook_position_validated():
    with pytest.raises(AssertionError):
        Hook("x", "mid_iter")


def test_save_hook_rank_gated(tmp_path):
    learner = _fake_learner(iter_val=5)
    learner.rank = 1
    saved = []
    learner.save = lambda p: saved.append(p)
    learner.checkpoint_path = lambda: str(tmp_path / "c.ckpt")
    SaveCkptHook()(learner)
    assert saved == []  # only rank 0 writes
    learner.rank = 0
    learner.logger = types.SimpleNamespace(info=lambda *a, **k: None)
    SaveCkptHook()(learner)
    assert saved == [str(tmp_path / "c.ckpt")]


def test_load_hook_ignores_missing_path(tmp_path):
    learner = _fake_learner()
    learner.cfg = types.SimpleNamespace(
        learner={"load_path": str(tmp_path / "nope.ckpt")}
    )
    learner.restore = lambda p: (_ for _ in ()).throw(AssertionError("called"))
    LoadCkptHook()(learner)  # missing file: no restore attempt


# ---------------------------------------------------------------- profiler
class _FakeProfiler:
    """jax.profiler stand-in recording start/stop edges."""

    def __init__(self, fail=False):
        self.events = []
        self.fail = fail

    def start_trace(self, logdir):
        if self.fail:
            raise RuntimeError("no profiler backend")
        self.events.append(("start", logdir))

    def stop_trace(self):
        self.events.append(("stop",))


def _profiled_learner():
    learner = _fake_learner()
    learner.rank = 0
    learner.logger = types.SimpleNamespace(info=lambda *a, **k: None)
    return learner


def test_profiler_hook_freq_gated_capture_window(tmp_path):
    """Every ``freq`` iterations the hook opens a trace and closes it
    ``duration`` iterations later — one bounded capture per gate point."""
    prof = _FakeProfiler()
    hook = ProfilerHook(str(tmp_path), freq=4, duration=2, profiler=prof)
    learner = _profiled_learner()
    for it in range(1, 11):
        learner.last_iter.val = it
        hook(learner)
    # gates at 4 and 8; stops at 6 and 10
    assert prof.events == [
        ("start", str(tmp_path)), ("stop",),
        ("start", str(tmp_path)), ("stop",),
    ]
    assert not hook.session.active


def test_profiler_hook_rank_gated(tmp_path):
    prof = _FakeProfiler()
    hook = ProfilerHook(str(tmp_path), freq=1, duration=1, profiler=prof)
    learner = _profiled_learner()
    learner.rank = 1
    for it in range(1, 5):
        learner.last_iter.val = it
        hook(learner)
    assert prof.events == []  # only rank 0 profiles


def test_profiler_hook_survives_broken_profiler(tmp_path):
    """A missing/broken profiler backend must never take down training."""
    prof = _FakeProfiler(fail=True)
    hook = ProfilerHook(str(tmp_path), freq=2, duration=1, profiler=prof)
    learner = _profiled_learner()
    for it in range(1, 7):
        learner.last_iter.val = it
        hook(learner)  # no raise
    assert not hook.session.active


def test_profiler_sessions_counted_in_registry(tmp_path):
    from distar_tpu.obs import MetricsRegistry, set_registry

    reg = MetricsRegistry()
    prev = set_registry(reg)
    try:
        prof = _FakeProfiler()
        hook = ProfilerHook(str(tmp_path), freq=3, duration=1, profiler=prof)
        learner = _profiled_learner()
        for it in range(1, 8):
            learner.last_iter.val = it
            hook(learner)
        assert reg.counter("distar_profiler_sessions_total").value == 2  # it=3, it=6
    finally:
        set_registry(prev)


def test_profiler_failures_counted_and_hook_self_disables(tmp_path):
    """start/stop failures are no longer silent warnings: each one counts
    distar_profiler_failures_total{stage=...}, and after 3 consecutive
    start failures (unwritable logdir) the hook retires itself instead of
    re-failing at every gate."""
    from distar_tpu.obs import MetricsRegistry, set_registry

    reg = MetricsRegistry()
    prev = set_registry(reg)
    try:
        prof = _FakeProfiler(fail=True)
        hook = ProfilerHook(str(tmp_path), freq=1, duration=1, profiler=prof)
        learner = _profiled_learner()
        for it in range(1, 10):
            learner.last_iter.val = it
            hook(learner)
        assert hook.disabled
        # exactly MAX_CONSECUTIVE_FAILURES attempts, then silence
        assert reg.counter(
            "distar_profiler_failures_total", stage="start"
        ).value == ProfilerHook.MAX_CONSECUTIVE_FAILURES
    finally:
        set_registry(prev)


def test_profiler_session_records_last_profile_path(tmp_path):
    """A successful stop resolves the newest capture dir under the logdir
    (the jax.profiler plugins/profile/<stamp>/ layout) — what the admin
    /profile route hands to the analyzer."""
    import os

    from distar_tpu.obs import MetricsRegistry, ProfilerSession

    stamp = tmp_path / "plugins" / "profile" / "2026_01_02"

    class WritingProfiler(_FakeProfiler):
        def stop_trace(self):
            os.makedirs(stamp)
            super().stop_trace()

    sess = ProfilerSession(str(tmp_path), profiler=WritingProfiler(),
                           registry=MetricsRegistry())
    assert sess.start()
    assert sess.stop()
    assert sess.last_profile_path == str(stamp)
    # failure paths count into the session's registry, typed by stage
    failing = ProfilerSession(str(tmp_path), profiler=_FakeProfiler(fail=True),
                              registry=MetricsRegistry())
    assert not failing.start()
    assert failing.failures == 1
