"""tools/perf_gate.py — the tier-1 perf regression gate.

Runs against the COMMITTED baseline artifact (skips when absent): the gate
must pass on the baseline vs itself, fail on an injected 2x step-time
regression, and hard-fail the impossible-timing precondition regardless of
how favourable the comparison looks."""
import copy
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import perf_gate  # noqa: E402

BASELINE = os.path.join(REPO, "artifacts", "perf_baseline_cpu_r07.json")

pytestmark = pytest.mark.skipif(
    not os.path.exists(BASELINE),
    reason="no committed perf baseline artifact",
)


@pytest.fixture(scope="module")
def baseline():
    with open(BASELINE) as f:
        return json.load(f)


def test_gate_passes_on_committed_baseline(baseline):
    assert perf_gate.impossible_timing(baseline) == []
    regressions, _notes = perf_gate.compare(baseline, baseline, tolerance=0.5)
    assert regressions == []


def test_gate_fails_on_injected_2x_regression(baseline, tmp_path):
    candidate = copy.deepcopy(baseline)
    for p in candidate["sl_sweep"]:
        p["step_time_s"] *= 2.0
        p["frames_per_sec"] /= 2.0
    regressions, _ = perf_gate.compare(baseline, candidate, tolerance=0.5)
    assert regressions, "2x slower must breach a 50% tolerance"
    # and through the CLI, end to end (exit code contract: 1 = regression)
    cand_path = tmp_path / "cand.json"
    cand_path.write_text(json.dumps(candidate))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "perf_gate.py"), "check",
         "--baseline", BASELINE, "--candidate", str(cand_path)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "REGRESSION" in proc.stdout


def test_gate_tolerance_absorbs_noise(baseline):
    candidate = copy.deepcopy(baseline)
    for p in candidate["sl_sweep"]:
        p["step_time_s"] *= 1.3  # 30% drift < 50% tolerance
    regressions, _ = perf_gate.compare(baseline, candidate, tolerance=0.5)
    assert regressions == []


def test_impossible_timing_is_a_hard_precondition(baseline, tmp_path):
    # a candidate claiming a TPU whose own flop count says the step cannot
    # run that fast must fail with exit 2 even though it "improved"
    candidate = copy.deepcopy(baseline)
    candidate["device"] = "TPU v5 lite"
    for p in candidate["sl_sweep"]:
        flops = max(p.get("flops_unoptimized", 0), p.get("flops_optimized", 0))
        assert flops > 0, "baseline must carry flop counts"
        p["step_time_s"] = flops / (200 * 197e12)  # 200x peak: impossible
        p["frames_per_sec"] = 10 ** 9
    offences = perf_gate.impossible_timing(candidate)
    assert offences
    cand_path = tmp_path / "impossible.json"
    cand_path.write_text(json.dumps(candidate))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "perf_gate.py"), "check",
         "--baseline", BASELINE, "--candidate", str(cand_path)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "PRECONDITION" in proc.stdout


def test_suspect_flag_is_a_hard_precondition(baseline):
    candidate = copy.deepcopy(baseline)
    candidate["suspect"] = True
    candidate["suspect_reason"] = "CPU-derived scaling numbers"
    assert perf_gate.impossible_timing(candidate)


def test_missing_candidate_points_note_not_fail(baseline):
    candidate = copy.deepcopy(baseline)
    candidate["sl_sweep"] = []
    candidate.pop("value", None)
    regressions, notes = perf_gate.compare(baseline, candidate, tolerance=0.5)
    # nothing comparable IS a failure; a truncated (but nonempty) sweep is not
    assert any("no comparable points" in r for r in regressions) or notes


def test_trajectory_collects_rounds_and_flags_suspects():
    rows = perf_gate.collect_trajectory()
    assert rows, "repo carries BENCH_*/MULTICHIP_* artifacts"
    by_artifact = {r["artifact"]: r for r in rows}
    assert "perf_baseline_cpu_r07.json" in by_artifact
    # the physically-incoherent 109x rows stay flagged forever
    if "BENCH_LOCAL_r05.json" in by_artifact:
        assert "SUSPECT" in by_artifact["BENCH_LOCAL_r05.json"]["status"]
    # the r06 multichip artifact flags itself in-band
    if "multichip_scaling_cpu_r06.json" in by_artifact:
        assert "SUSPECT" in by_artifact["multichip_scaling_cpu_r06.json"]["status"]


def test_trajectory_write_round_trips_markers(tmp_path):
    target = tmp_path / "PERF.md"
    target.write_text("# perf\n\nintro text\n")
    ns = type("A", (), {"write": str(target)})
    perf_gate.cmd_trajectory(ns)
    first = target.read_text()
    assert perf_gate.TRAJ_BEGIN in first and perf_gate.TRAJ_END in first
    assert "intro text" in first
    perf_gate.cmd_trajectory(ns)  # idempotent: replaces between markers
    second = target.read_text()
    assert second.count(perf_gate.TRAJ_BEGIN) == 1
    assert second == first


def test_perf_md_trajectory_block_is_current():
    """PERF.md's committed trajectory table matches what the artifacts
    derive — the block can't silently rot as artifacts accumulate."""
    with open(os.path.join(REPO, "PERF.md")) as f:
        text = f.read()
    assert perf_gate.TRAJ_BEGIN in text
    committed = text.split(perf_gate.TRAJ_BEGIN, 1)[1].split(perf_gate.TRAJ_END, 1)[0]
    fresh = perf_gate.render_trajectory(perf_gate.collect_trajectory())
    assert committed.strip() == fresh.strip()


# ------------------------------------------------------------- curve gate
def _fam(*entries):
    return [{"round": r, "artifact": f"curves_r{r}.json", "values": list(v)}
            for r, v in entries]


def test_curve_gate_passes_on_descending_rounds():
    fams = {"sl_total_loss": _fam(("15", [10.0, 8.0, 6.0]),
                                  ("16", [10.0, 7.0, 5.9]))}
    verdicts, failures = perf_gate.curve_verdicts(fams, tolerance=0.10)
    assert failures == []
    assert verdicts[0]["regressed"] is False
    assert verdicts[0]["candidate_last"] == 5.9


def test_curve_gate_fails_past_tolerance_and_absorbs_within():
    fams = {"rl_total_loss": _fam(("15", [10.0, 5.0]), ("16", [10.0, 5.4]))}
    # 5.4 <= 5.0 * 1.10: inside the band
    _, failures = perf_gate.curve_verdicts(fams, tolerance=0.10)
    assert failures == []
    # 5.4 > 5.0 * 1.05: regression
    _, failures = perf_gate.curve_verdicts(fams, tolerance=0.05)
    assert len(failures) == 1 and "regressed past" in failures[0]


def test_curve_gate_rejects_nondescent_and_nonfinite():
    fams = {
        "flat": _fam(("16", [5.0, 5.0])),
        "nan": _fam(("16", [5.0, float("nan"), 4.0])),
    }
    _, failures = perf_gate.curve_verdicts(fams, tolerance=0.10)
    assert any("does not descend" in f for f in failures)
    assert any("non-finite" in f for f in failures)


def test_curve_gate_single_round_is_baseline_pass():
    verdicts, failures = perf_gate.curve_verdicts(
        {"distill_kl": _fam(("15", [30.0, 26.0]))}, tolerance=0.10)
    assert failures == [] and verdicts[0]["regressed"] is False
    assert "single round" in verdicts[0]["note"]


def test_curve_gate_sign_safe_for_negative_losses():
    # RL total_loss can be negative; the band must widen, not flip
    fams = {"rl": _fam(("15", [1.0, -2.0]), ("16", [1.0, -1.9]))}
    _, failures = perf_gate.curve_verdicts(fams, tolerance=0.10)
    assert failures == []  # -1.9 <= -2.0 + 0.10*2.0
    _, failures = perf_gate.curve_verdicts(fams, tolerance=0.01)
    assert len(failures) == 1


def test_curve_gate_runs_green_on_committed_artifacts():
    """The repo's own committed toy-run curves must satisfy the gate (the
    chain perf_gate curve walks in CI)."""
    fams = perf_gate.collect_curves()
    assert {"sl_total_loss", "rl_total_loss", "distill_kl"} <= set(fams)
    _, failures = perf_gate.curve_verdicts(fams, tolerance=0.10)
    assert failures == []


ARENA_ARTIFACT = os.path.join(REPO, "ARENA_r18.json")


def _arena_doc(anchor_relative, player="main:300", matches=12):
    return {"bench": "arena", "metric": "arena match throughput",
            "value": 0.5, "unit": "matches/s", "host_cores": 1,
            "scaling_valid": False,
            "arena": {"player": player, "matches": matches,
                      "anchor": "mean(attack_nearest,idle)",
                      "anchor_relative": anchor_relative}}


@pytest.mark.skipif(not os.path.exists(ARENA_ARTIFACT),
                    reason="no committed arena skill artifact")
def test_skill_gate_passes_on_committed_artifact():
    entries = perf_gate.collect_skill()
    assert any(e["artifact"] == "ARENA_r18.json" for e in entries)
    assert entries[-1]["player"].startswith("main:")
    verdicts, failures = perf_gate.skill_verdicts(entries, tolerance=50.0)
    assert failures == []
    assert verdicts and verdicts[0]["regressed"] is False
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "perf_gate.py"),
         "skill"], capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "skill gate: PASS" in proc.stdout


def test_skill_gate_fails_on_injected_regression(tmp_path):
    (tmp_path / "ARENA_r18.json").write_text(json.dumps(_arena_doc(-100.0)))
    (tmp_path / "ARENA_r19.json").write_text(
        json.dumps(_arena_doc(-200.0, player="main:400")))
    entries = perf_gate.collect_skill(repo=str(tmp_path))
    assert [e["round"] for e in entries] == ["18", "19"]
    verdicts, failures = perf_gate.skill_verdicts(entries, tolerance=50.0)
    assert len(failures) == 1 and "regressed past" in failures[0]
    assert verdicts[0]["regressed"] is True
    # a 100-point drop inside a 150-point tolerance is absorbed
    _, failures = perf_gate.skill_verdicts(entries, tolerance=150.0)
    assert failures == []
    # and through the CLI, end to end (exit code contract: 1 = regression)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "perf_gate.py"),
         "skill", "--repo", str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "REGRESSED" in proc.stdout


def test_skill_gate_single_round_is_baseline_pass(tmp_path):
    (tmp_path / "ARENA_r18.json").write_text(json.dumps(_arena_doc(-250.0)))
    verdicts, failures = perf_gate.skill_verdicts(
        perf_gate.collect_skill(repo=str(tmp_path)), tolerance=50.0)
    assert failures == [] and verdicts[0]["note"] == "single round: baseline PASS"


def test_skill_gate_rejects_nonfinite(tmp_path):
    (tmp_path / "ARENA_r18.json").write_text(
        json.dumps(_arena_doc(float("nan"))))
    _, failures = perf_gate.skill_verdicts(
        perf_gate.collect_skill(repo=str(tmp_path)), tolerance=50.0)
    assert any("non-finite" in f for f in failures)


@pytest.mark.skipif(not os.path.exists(ARENA_ARTIFACT),
                    reason="no committed arena skill artifact")
def test_skill_trajectory_rows_present():
    rows = perf_gate.collect_trajectory()
    arena_rows = [r for r in rows if r["artifact"] == "ARENA_r18.json"]
    units = {r["unit"] for r in arena_rows}
    assert "matches/s" in units, "headline throughput row missing"
    assert "elo" in units, "in-band anchor-relative skill row missing"
