"""Tests for the project-invariant analyzer (distar_tpu/analysis/).

Per-rule fixture snippets (positive hit, negative clean, pragma-suppressed),
baseline round-trip with shrink-only enforcement, the lockwatch dynamic
sanitizer (a REAL ABBA order cycle across two threads), and the tier-1 gate:
``test_analysis_repo_clean`` runs the full analyzer over the committed tree
and fails on any non-baselined finding (the lint-from-tests idiom).
"""
import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO) if REPO not in sys.path else None

from distar_tpu.analysis import (  # noqa: E402
    Analyzer,
    apply_baseline,
    collect_files,
    load_baseline,
    render_markdown,
    save_baseline,
)


def run_on(tmp_path, source, filename="distar_tpu/mod.py", rules=None,
           baseline=None, extra_files=()):
    """Analyze one fixture module (plus optional named extras) in a FRESH
    case dir (repeated calls in one test must not rescan prior fixtures);
    returns the AnalysisResult. The default filename puts the fixture inside
    a ``distar_tpu`` dir so package-scoped rules (no-print, metrics) apply."""
    run_on.case = getattr(run_on, "case", 0) + 1
    tmp_path = tmp_path / f"case{run_on.case}"
    path = tmp_path / filename
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    for name, text in extra_files:
        p = tmp_path / name
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    analyzer = Analyzer(repo_root=str(tmp_path), rules=rules)
    return analyzer.run(collect_files([str(tmp_path)]), baseline=baseline)


def rules_of(result):
    return sorted(f.rule for f in result.findings)


# ===================================================================== locks
LOCK_HIT = """
    import threading, time

    class Pump:
        def __init__(self):
            self._lock = threading.Lock()

        def tick(self):
            with self._lock:
                time.sleep(0.1)
"""


def test_lock_held_blocking_hit(tmp_path):
    res = run_on(tmp_path, LOCK_HIT)
    assert "lock-held-blocking" in rules_of(res)


def test_lock_held_blocking_clean_outside_lock(tmp_path):
    res = run_on(tmp_path, """
        import threading, time

        class Pump:
            def __init__(self):
                self._lock = threading.Lock()

            def tick(self):
                with self._lock:
                    n = 1
                time.sleep(0.1)
    """)
    assert "lock-held-blocking" not in rules_of(res)


def test_lock_condition_wait_on_held_lock_is_clean(tmp_path):
    """cond.wait() on the HELD condition releases it — the cv idiom."""
    res = run_on(tmp_path, """
        import threading

        class Q:
            def __init__(self):
                self._cv = threading.Condition()

            def pop(self):
                with self._cv:
                    self._cv.wait(timeout=1.0)
    """)
    assert "lock-held-blocking" not in rules_of(res)


def test_lock_event_wait_under_lock_is_flagged(tmp_path):
    res = run_on(tmp_path, """
        import threading

        class Q:
            def __init__(self):
                self._cv = threading.Condition()
                self._stop = threading.Event()

            def pop(self):
                with self._cv:
                    self._stop.wait(1.0)
    """)
    assert "lock-held-blocking" in rules_of(res)


def test_lock_callback_dispatch_hit_and_snapshot_clean(tmp_path):
    hit = run_on(tmp_path, """
        import threading

        class Emitter:
            def __init__(self):
                self._lock = threading.Lock()
                self._callbacks = []

            def emit(self, event):
                with self._lock:
                    for cb in self._callbacks:
                        cb(event)
    """)
    assert "lock-callback-dispatch" in rules_of(hit)
    clean = run_on(tmp_path, """
        import threading

        class Emitter:
            def __init__(self):
                self._lock = threading.Lock()
                self._callbacks = []

            def emit(self, event):
                with self._lock:
                    cbs = list(self._callbacks)
                for cb in cbs:
                    cb(event)
    """, filename="distar_tpu/mod2.py")
    assert "lock-callback-dispatch" not in rules_of(clean)


def test_lock_order_inversion(tmp_path):
    res = run_on(tmp_path, """
        import threading

        class S:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def one(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def two(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
    """)
    assert "lock-order-inversion" in rules_of(res)


def test_lock_nested_consistent_order_clean(tmp_path):
    res = run_on(tmp_path, """
        import threading

        class S:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def one(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def two(self):
                with self._a_lock:
                    with self._b_lock:
                        pass
    """)
    assert "lock-order-inversion" not in rules_of(res)


def test_closure_under_lock_not_flagged(tmp_path):
    """Code inside a def under a with-lock runs LATER, not under the lock."""
    res = run_on(tmp_path, """
        import threading, time

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def start(self):
                with self._lock:
                    def run():
                        time.sleep(1.0)
                    self._fn = run
    """)
    assert "lock-held-blocking" not in rules_of(res)


# ================================================================= lifecycle
def test_resource_unreleased_hit_and_clean(tmp_path):
    hit = run_on(tmp_path, """
        import socket

        class Server:
            def __init__(self):
                self._sock = socket.socket()
    """)
    assert "resource-unreleased" in rules_of(hit)
    clean = run_on(tmp_path, """
        import socket

        class Server:
            def __init__(self):
                self._sock = socket.socket()

            def stop(self):
                self._sock.close()
    """, filename="distar_tpu/mod2.py")
    assert "resource-unreleased" not in rules_of(clean)


def test_resource_tuple_swap_alias_counts_as_release(tmp_path):
    res = run_on(tmp_path, """
        import socket

        class Client:
            def __init__(self):
                self._sock = socket.socket()

            def close(self):
                sock, self._sock = self._sock, None
                if sock is not None:
                    sock.close()
    """)
    assert "resource-unreleased" not in rules_of(res)


def test_thread_unjoined_nondaemon_error_daemon_with_stop_warning(tmp_path):
    res = run_on(tmp_path, """
        import threading

        class A:
            def __init__(self):
                self._t = threading.Thread(target=self.run)

        class B:
            def __init__(self):
                self._t = threading.Thread(target=self.run, daemon=True)

            def stop(self):
                pass

        class C:
            def __init__(self):
                self._t = threading.Thread(target=self.run, daemon=True)
    """)
    found = {(f.ident, f.severity) for f in res.findings if f.rule == "thread-unjoined"}
    assert ("A._t unjoined", "error") in found
    assert ("B._t unjoined", "warning") in found
    assert not any(i.startswith("C._t") for i, _s in found)  # fire-and-forget daemon


# ====================================================================== wire
ERRORS_MOD = """
    class PlaneError(Exception):
        code = "plane_error"

        def to_wire(self):
            return {"code": self.code, "error": str(self)}

    class LostError(PlaneError):
        code = "lost"

    _WIRE_CODES = {cls.code: cls for cls in (PlaneError,)}

    def error_from_wire(payload):
        return _WIRE_CODES.get(payload.get("code"), PlaneError)(payload.get("error", ""))
"""


def test_wire_code_unregistered(tmp_path):
    res = run_on(tmp_path, ERRORS_MOD, filename="distar_tpu/plane/errors.py")
    hits = [f for f in res.findings if f.rule == "wire-code-unregistered"]
    assert len(hits) == 1 and "LostError" in hits[0].message


def test_wire_code_unknown_literal(tmp_path):
    res = run_on(
        tmp_path, """
        def dispatch(req):
            if not isinstance(req, dict):
                return {"code": "bad_stuff", "error": "nope"}
            return {"code": 0}
        """,
        filename="distar_tpu/plane/server.py",
        extra_files=[("distar_tpu/plane/errors.py", ERRORS_MOD)],
    )
    hits = [f for f in res.findings if f.rule == "wire-code-unknown"]
    assert len(hits) == 1 and "bad_stuff" in hits[0].message


def test_wire_code_registered_literal_clean(tmp_path):
    res = run_on(
        tmp_path, """
        def dispatch(req):
            if req.get("code") == "lost":
                return {"code": "plane_error", "error": "x"}
        """,
        filename="distar_tpu/plane/server.py",
        extra_files=[("distar_tpu/plane/errors.py", ERRORS_MOD)],
    )
    assert not [f for f in res.findings if f.rule == "wire-code-unknown"]


def test_handler_boundary_swallow(tmp_path):
    res = run_on(tmp_path, """
        class Handler:
            def do_POST(self):
                try:
                    self.route()
                except Exception:
                    pass
    """)
    assert "handler-boundary-swallow" in rules_of(res)


def test_handler_boundary_answering_is_clean(tmp_path):
    res = run_on(tmp_path, """
        class Handler:
            def do_POST(self):
                try:
                    payload = self.route()
                except Exception as e:
                    payload = {"code": 1, "info": repr(e)}
                self.send(payload)
    """)
    assert "handler-boundary-swallow" not in rules_of(res)


def test_retryable_swallowed_hit_and_counted_clean(tmp_path):
    hit = run_on(tmp_path, """
        from x import CommError

        def pull(client):
            try:
                client.fetch()
            except CommError:
                pass
    """)
    assert "retryable-swallowed" in rules_of(hit)
    clean = run_on(tmp_path, """
        from x import CommError

        def pull(client, errors):
            try:
                client.fetch()
            except CommError:
                errors.inc()
    """, filename="distar_tpu/mod2.py")
    assert "retryable-swallowed" not in rules_of(clean)


def test_retryable_swallowed_teardown_exempt(tmp_path):
    res = run_on(tmp_path, """
        from x import CommError

        class C:
            def close(self):
                try:
                    self._sock.close()
                except CommError:
                    pass
    """)
    assert "retryable-swallowed" not in rules_of(res)


# ======================================================================= jax
def test_jax_donated_host_leaf(tmp_path):
    res = run_on(tmp_path, """
        import jax
        import numpy as np

        step = jax.jit(lambda s: s, donate_argnums=(0,))

        def train(batch):
            state = np.zeros((4,))
            return step(state)
    """)
    assert "jax-donated-host-leaf" in rules_of(res)


def test_jax_donated_placed_leaf_clean(tmp_path):
    res = run_on(tmp_path, """
        import jax
        import numpy as np

        step = jax.jit(lambda s: s, donate_argnums=(0,))

        def train(batch, sharding):
            state = np.zeros((4,))
            state = jax.device_put(state, sharding)
            return step(state)
    """)
    assert "jax-donated-host-leaf" not in rules_of(res)


def test_jax_device_get_in_loop(tmp_path):
    hit = run_on(tmp_path, """
        import jax

        def decollate(leaves):
            out = []
            for leaf in leaves:
                out.append(jax.device_get(leaf))
            return out
    """)
    assert "jax-device-get-in-loop" in rules_of(hit)
    clean = run_on(tmp_path, """
        import jax

        def decollate(tree):
            host = jax.device_get(tree)
            return [host[k] for k in host]
    """, filename="distar_tpu/mod2.py")
    assert "jax-device-get-in-loop" not in rules_of(clean)


def test_jax_nondeterministic_jit(tmp_path):
    res = run_on(tmp_path, """
        import jax, time

        @jax.jit
        def step(x):
            t = time.time()
            return x + t
    """)
    assert "jax-nondeterministic-jit" in rules_of(res)


def test_jax_nondeterministic_pure_callback_target(tmp_path):
    res = run_on(tmp_path, """
        import jax, time

        def host_fn(x):
            return x * time.time()

        def model(x):
            return jax.pure_callback(host_fn, x, x)
    """)
    assert "jax-nondeterministic-jit" in rules_of(res)


# =================================================================== hygiene
def test_no_print_library_vs_bin(tmp_path):
    res = run_on(tmp_path, "print('hi')\n")
    assert "no-print" in rules_of(res)
    res2 = run_on(tmp_path, "print('hi')\n", filename="distar_tpu/bin/cli.py")
    assert "no-print" not in rules_of(res2)


def test_socket_rules(tmp_path):
    res = run_on(tmp_path, """
        import socket, urllib.request

        def f():
            try:
                urllib.request.urlopen("http://x")
            except:
                pass
            socket.create_connection(("h", 1))
            socket.create_connection(("h", 1), timeout=3)
    """)
    rs = rules_of(res)
    assert rs.count("socket-no-timeout") == 2
    assert "socket-bare-except" in rs


def test_metric_kind_misuse_set_on_counter(tmp_path):
    res = run_on(tmp_path, """
        from .obs import get_registry

        def f(reg):
            reg.counter("distar_x_total", "help").set(3)
    """)
    assert "metric-kind-misuse" in rules_of(res)


def test_metric_kind_misuse_total_gauge(tmp_path):
    res = run_on(tmp_path, """
        def f(reg):
            g = reg.gauge("distar_x_total", "help")
            g.set(1)
    """)
    assert "metric-kind-misuse" in rules_of(res)


def test_metric_inc_only_gauge_flagged_inc_dec_clean(tmp_path):
    hit = run_on(tmp_path, """
        def f(reg):
            g = reg.gauge("distar_x_things", "help")
            g.inc()
    """)
    assert any(f.rule == "metric-kind-misuse" and "inc()ed" in f.message
               for f in hit.findings)
    clean = run_on(tmp_path, """
        def f(reg):
            g = reg.gauge("distar_x_things", "help")
            g.inc()
            g.dec()
    """, filename="distar_tpu/mod2.py")
    assert not any(f.rule == "metric-kind-misuse" for f in clean.findings)


def test_metric_label_cardinality(tmp_path):
    res = run_on(tmp_path, """
        def f(reg, payload):
            reg.counter("distar_x_total", "help", session=payload["session_id"]).inc()
    """)
    assert "metric-label-cardinality" in rules_of(res)


# ================================================================== pragmas
def test_pragma_suppresses_with_reason(tmp_path):
    res = run_on(tmp_path, """
        import threading, time

        class Pump:
            def __init__(self):
                self._lock = threading.Lock()

            def tick(self):
                with self._lock:
                    # analysis: allow(lock-held-blocking) — simulated chip contention is the point here
                    time.sleep(0.1)
    """)
    assert "lock-held-blocking" not in rules_of(res)
    assert any(f.rule == "lock-held-blocking" for f, _why in res.suppressed)


def test_pragma_without_reason_is_itself_a_finding(tmp_path):
    res = run_on(tmp_path, """
        import threading, time

        class Pump:
            def __init__(self):
                self._lock = threading.Lock()

            def tick(self):
                with self._lock:
                    time.sleep(0.1)  # analysis: allow(lock-held-blocking)
    """)
    assert "pragma-no-reason" in rules_of(res)


def test_legacy_marker_still_suppresses(tmp_path):
    res = run_on(tmp_path, "print('x')  # lint: allow-print\n")
    assert "no-print" not in rules_of(res)


# ================================================================== baseline
def test_baseline_round_trip_and_shrink_only(tmp_path):
    src = LOCK_HIT
    res = run_on(tmp_path, src)
    assert res.findings and res.exit_code == 2

    # write the baseline from the findings: same tree is now baselined-only
    bl_path = tmp_path / "baseline.json"
    save_baseline(str(bl_path), res.findings)
    entries = load_baseline(str(bl_path))
    res2 = run_on(tmp_path, src, baseline=entries)
    assert res2.exit_code == 1
    assert not res2.findings and len(res2.baselined) == len(entries)

    # shrink-only: fix the code but keep the baseline entry -> stale = error
    res3 = run_on(tmp_path, """
        import threading

        class Pump:
            def __init__(self):
                self._lock = threading.Lock()
    """, baseline=entries)
    assert res3.stale_baseline and res3.exit_code == 2


def test_apply_baseline_multiset_semantics():
    from distar_tpu.analysis import Finding

    f = Finding(rule="r", severity="error", path="p.py", line=3, message="m")
    g = Finding(rule="r", severity="error", path="p.py", line=9, message="m")
    entries = [{"rule": "r", "path": "p.py", "ident": "m"}]
    new, matched, stale = apply_baseline([f, g], entries)
    assert len(matched) == 1 and len(new) == 1 and not stale


def test_render_markdown_shapes(tmp_path):
    res = run_on(tmp_path, LOCK_HIT)
    md = render_markdown(res)
    assert "lock-held-blocking" in md and "verdict" in md


# =================================================================== driver
def test_analyze_cli_report_and_exit_codes(tmp_path):
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "analyze.py"), "report",
         "distar_tpu/analysis"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert out.returncode in (0, 1), out.stdout + out.stderr
    assert "verdict" in out.stdout


def test_analyze_cli_changed_mode_runs(tmp_path):
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "analyze.py"), "--changed"],
        capture_output=True, text=True, cwd=REPO,
    )
    # whatever git reports changed right now must be analyzable and clean
    # against the committed baseline (or there is nothing changed at all)
    assert out.returncode in (0, 1), out.stdout + out.stderr


# ============================================================ legacy shims
def test_legacy_shim_surfaces(tmp_path):
    """The three legacy lint CLIs keep their import surface and semantics.
    Whole-tree cleanliness is already covered by the pre-existing lint
    tests (test_obs_metrics/test_resilience) + test_analysis_repo_clean, so
    this exercises the shims on a small fixture instead of re-scanning the
    package three times."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import lint_metric_names as lmn
        import lint_no_print as lnp
        import lint_sockets as ls
    finally:
        sys.path.pop(0)
    pkg = tmp_path / "distar_tpu"
    (pkg / "utils").mkdir(parents=True)
    (pkg / "bin").mkdir()
    (pkg / "mod.py").write_text(
        "import socket\n"
        "print('offends')\n"
        "print('allowed')  # lint: allow-print\n"
        "socket.create_connection(('h', 1))\n"
        "try:\n    pass\nexcept:\n    pass\n"
        "def f(reg):\n    reg.counter('wrong_name', 'h').inc()\n"
    )
    (pkg / "bin" / "cli.py").write_text("print('cli stdout is fine')\n")
    prints = lnp.find_bare_prints(str(pkg))
    assert [(p, l) for (p, l, _t) in prints] == [("mod.py", 2)]
    offences = ls.find_offences(str(pkg))
    msgs = [m for (_p, _l, m) in offences]
    assert len(offences) == 2
    assert any("create_connection" in m for m in msgs)
    assert any("bare 'except:'" in m for m in msgs)
    docs = tmp_path / "obs.md"
    docs.write_text("`distar_ok_total` is documented\n")
    problems = lmn.lint(str(pkg), str(docs))
    assert len(problems) == 1 and "wrong_name" in problems[0]
    names = lmn.registered_names(str(pkg))
    assert "wrong_name" in names
    assert "distar_stopwatch_seconds" in names  # DYNAMIC_ALLOW included


# ================================================================= lockwatch
LOCKWATCH_ABBA = """
import sys, threading, time
sys.path.insert(0, %(repo)r)
from distar_tpu.analysis import lockwatch

lockwatch.install(filters=("abba_fixture",))
A = threading.Lock()
B = threading.Lock()
hold_a = threading.Event()
hold_b = threading.Event()

def one():
    with A:
        hold_a.set()
        hold_b.wait(2.0)
        acquired = B.acquire(timeout=0.2)   # real contention, times out
        if acquired:
            B.release()

def two():
    with B:
        hold_b.set()
        hold_a.wait(2.0)
        acquired = A.acquire(timeout=0.2)
        if acquired:
            A.release()

t1 = threading.Thread(target=one)
t2 = threading.Thread(target=two)
t1.start(); t2.start(); t1.join(); t2.join()
rep = lockwatch.report()
import json
print("LOCKWATCH-JSON " + json.dumps(rep))
"""


def test_lockwatch_reports_real_abba_cycle(tmp_path):
    """Two real threads acquire (A then B) and (B then A) concurrently —
    lockwatch must report the inversion and the cycle even though the run
    itself survived (acquire timeouts)."""
    script = tmp_path / "abba_fixture.py"
    script.write_text(LOCKWATCH_ABBA % {"repo": REPO})
    out = subprocess.run([sys.executable, str(script)],
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    line = next(l for l in out.stdout.splitlines() if l.startswith("LOCKWATCH-JSON "))
    rep = json.loads(line[len("LOCKWATCH-JSON "):])
    assert len(rep["inversions"]) == 1, rep["inversions"]
    assert rep["cycles"], "DFS must find the A->B->A cycle"
    inv = rep["inversions"][0]
    assert "abba_fixture.py" in inv["a"] and "abba_fixture.py" in inv["b"]


def test_lockwatch_held_blocking_and_condition_exemption():
    """In-process: a sleep under a watched lock is reported; cond.wait on
    the held condition is NOT (the proxy's _release_save shows it released).
    Installed/uninstalled around the assertions so the suite is unaffected."""
    from distar_tpu.analysis import lockwatch

    if lockwatch.installed():  # DISTAR_LOCKWATCH=1 session: don't fight it
        pytest.skip("lockwatch already active for this session")
    lockwatch.install(filters=("test_analysis",))
    try:
        lock = threading.Lock()
        with lock:
            time.sleep(0.01)
        cv = threading.Condition()
        hit = []

        def waiter():
            with cv:
                cv.wait(timeout=0.3)
                hit.append(1)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        with cv:
            cv.notify()
        t.join()
        rep = lockwatch.report()
    finally:
        lockwatch.uninstall()
        lockwatch.reset()
    assert hit == [1]
    blockers = {(h["blocker"]) for h in rep["held_blocking"]}
    assert "time.sleep" in blockers
    # the condition's own wait never shows as held-while-blocking
    assert not any("Condition" in b for b in blockers)


def test_lockwatch_baseline_matching():
    from distar_tpu.analysis import lockwatch

    rep = {
        "held_blocking": [
            {"lock": "distar_tpu/a.py:10", "blocker": "socket.recv",
             "caller": "distar_tpu/b.py:5", "count": 3},
        ],
        "inversions": [
            {"a": "distar_tpu/a.py:10", "b": "distar_tpu/c.py:7",
             "count_ab": 1, "count_ba": 1},
        ],
    }
    baseline = {
        "held_blocking": [
            {"lock_file": "distar_tpu/a.py", "blocker": "socket.recv",
             "why": "request lock IS the serializer"},
        ],
        "inversions": [],
    }
    bad = lockwatch.unbaselined(rep, baseline)
    assert bad["held_blocking"] == []          # justified
    assert len(bad["inversions"]) == 1         # not justified
    assert not bad["stale"]
    # an entry without a why never matches
    baseline["held_blocking"][0]["why"] = ""
    bad2 = lockwatch.unbaselined(rep, baseline)
    assert len(bad2["held_blocking"]) == 1


# ===================================== regressions for analyzer-found bugs
# Each test pins one genuine bug this PR's analyzer surfaced and fixed
# (docs/analysis.md "incidents" section names them).


def test_wire_bad_request_rehydrates_typed_both_planes():
    """bad_frame/bad_request/shm_error used to cross the wire as raw string
    literals no registry knew — peers degraded them to the base class."""
    from distar_tpu.replay import errors as replay_errors
    from distar_tpu.serve import errors as serve_errors

    e = serve_errors.error_from_wire({"code": "bad_request", "error": "unknown op"})
    assert isinstance(e, serve_errors.BadRequestError)
    e = serve_errors.error_from_wire({"code": "bad_frame", "error": "garbage"})
    assert isinstance(e, serve_errors.BadFrameError)
    e = replay_errors.error_from_wire({"code": "bad_request", "error": "x"})
    assert isinstance(e, replay_errors.BadRequestError)

    # the shm ring pump's dispatch-bug reply is registered on BOTH planes
    from distar_tpu.comm.shm_ring import ShmError

    wire = ShmError("boom", op="pump").to_wire()
    assert wire["code"] == "shm_error"
    assert isinstance(replay_errors.error_from_wire(wire),
                      replay_errors.RingServiceError)
    assert isinstance(serve_errors.error_from_wire(wire),
                      serve_errors.RingServiceError)


def test_serve_tcp_unknown_op_answers_typed():
    from distar_tpu.serve.errors import BadRequestError
    from distar_tpu.serve.tcp_frontend import ServeTCPServer

    class _Gw:
        pass

    srv = ServeTCPServer(_Gw(), port=0)
    wire = srv._dispatch({"op": "definitely_not_an_op"})
    assert wire["code"] == BadRequestError.code
    wire2 = srv._dispatch(["not", "a", "dict"])
    assert wire2["code"] == BadRequestError.code


def test_coordinator_server_stop_joins_serve_thread():
    """stop() used to return while the serve_forever thread could still be
    running (server_close racing the loop)."""
    from distar_tpu.comm.coordinator import CoordinatorServer

    srv = CoordinatorServer()
    srv.start()
    thread = srv._thread
    srv.stop()
    assert srv._thread is None
    assert thread is not None and not thread.is_alive()


def test_replay_admin_stop_joins_and_drain_hook_failure_counted(tmp_path):
    import urllib.request

    from distar_tpu.obs.registry import MetricsRegistry, set_registry
    from distar_tpu.replay.server import ReplayAdminServer
    from distar_tpu.replay.store import ReplayStore, TableConfig

    reg = MetricsRegistry()
    prev = set_registry(reg)
    try:
        store = ReplayStore(table_factory=lambda n: TableConfig())

        def bad_hook():
            raise RuntimeError("deregister exploded")

        admin = ReplayAdminServer(store, port=0, on_drain=bad_hook).start()
        thread = admin._thread
        try:
            req = urllib.request.Request(
                f"http://{admin.host}:{admin.port}/drain", data=b"{}", method="POST")
            body = urllib.request.urlopen(req, timeout=5).read()
            assert b'"code": 0' in body  # drain proceeds; hook is best-effort
            # ... but never silently: the failure is counted now
            assert reg.counter("distar_replay_drain_hook_errors_total").value == 1
        finally:
            admin.stop()
        assert not thread.is_alive()
    finally:
        set_registry(prev)


def test_scalar_sink_close_releases_file(tmp_path):
    from distar_tpu.utils.log import ScalarSink

    sink = ScalarSink(str(tmp_path / "scalars"), force_jsonl=True)
    sink.add_scalar("a", 1.0, 0)
    f = sink._file
    sink.close()
    assert f.closed
    sink.close()  # idempotent


def test_device_prefetcher_close_joins_producer():
    import itertools

    from distar_tpu.learner.prefetch import DevicePrefetcher

    pf = DevicePrefetcher(itertools.count(), place_fn=lambda b: b, depth=2)
    assert next(pf) == 0
    thread = pf._thread
    pf.close()
    assert not thread.is_alive(), "close() must reap the producer thread"


def test_shm_peer_close_joins_beat_thread():
    pytest.importorskip("multiprocessing.shared_memory")
    from distar_tpu.comm import shm_ring

    try:
        peer, _fields = shm_ring.mint_ring_pair(ring_bytes=1 << 16)
    except shm_ring.ShmUnavailableError:
        pytest.skip("no shared memory on this host")
    beat = peer._beat_thread
    peer.close()
    assert not beat.is_alive(), "close() must reap the beat thread before unlink"


# ================================================================ tier-1 gate
def test_analysis_repo_clean():
    """THE gate: the full analyzer over the committed tree must be clean
    (exit 0) or baselined-only (exit 1) against the committed baseline —
    any new finding fails tier-1, mirroring the legacy lint-from-tests
    idiom. Stale baseline entries fail too (shrink-only)."""
    baseline = load_baseline(os.path.join(REPO, "tools", "analysis_baseline.json"))
    analyzer = Analyzer(repo_root=REPO)
    files = collect_files(["distar_tpu", "tools", "bench.py"], repo_root=REPO)
    result = analyzer.run(files, baseline=baseline)
    msg = "\n".join(str(f) for f in result.findings) or "<none>"
    stale = "\n".join(str(e) for e in result.stale_baseline) or "<none>"
    assert result.exit_code in (0, 1), (
        f"new analyzer findings:\n{msg}\nstale baseline entries:\n{stale}\n"
        f"fix the code, add a `# analysis: allow(<rule>) — <why>` pragma, "
        f"or (last resort) baseline via tools/analyze.py --write-baseline"
    )
    # the committed baseline must stay small: grandfathered debt only
    assert len(baseline) <= 25, "baseline may only shrink (ISSUE 14 contract)"
