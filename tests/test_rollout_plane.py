"""Rollout inference plane: PolicyClient backends (inline / local / remote)
over the serve gateway, exact-capacity session reservation, teacher-logits
piggybacking, carry re-materialization through a gateway kill+restart, and
the plane-level weight-refresh dedupe.

Everything fast runs on ``MockModelEngine`` (per-slot step counters make
carry semantics assertable); the slow tests drive the REAL small model
through ``Actor.run_job`` on the local and remote backends.
"""
import threading
import time

import numpy as np
import pytest

from distar_tpu.actor.rollout_plane import (
    GatewayPolicyClient,
    RolloutPlane,
    _LocalTarget,
)
from distar_tpu.obs import MetricsRegistry, get_registry, set_registry
from distar_tpu.serve import (
    CapacityError,
    InferenceGateway,
    MockModelEngine,
    QueueFullError,
    ServeTCPServer,
    SessionTable,
)

from conftest import SMALL_MODEL


@pytest.fixture(autouse=True)
def fresh_registry():
    prev = set_registry(MetricsRegistry())
    yield
    set_registry(prev)


def obs_of(v: float) -> dict:
    return {"x": np.full((2, 3), v, dtype=np.float32)}


def mock_factory(**over):
    def factory(player_id, num_slots, params, teacher_params, model, seed):
        kw = dict(params={"version": "v1", "bias": 0.0},
                  teacher_params=teacher_params)
        kw.update(over)
        return MockModelEngine(num_slots, **kw)

    return factory


# ----------------------------------------------------- SessionTable.reserve
def test_reserve_all_or_nothing_typed():
    table = SessionTable(4, idle_ttl_s=300.0)
    slots = table.reserve(["a", "b", "c"])
    assert sorted(slots.values()) == [0, 1, 2]
    # idempotent for known ids, allocates only the new one
    slots2 = table.reserve(["a", "b", "d"])
    assert slots2["a"] == slots["a"] and slots2["d"] == 3
    # table full, nothing idle-expired: the WHOLE reservation sheds typed
    # and the table is untouched (all-or-nothing)
    with pytest.raises(CapacityError):
        table.reserve(["e", "f"])
    assert table.stats()["active"] == 4
    assert table.slot_of("e") is None and table.slot_of("f") is None


def test_reserve_evicts_idle_expired_only():
    table = SessionTable(2, idle_ttl_s=0.05)
    table.reserve(["old1", "old2"])
    time.sleep(0.1)  # both idle-expired
    slots = table.reserve(["new1", "new2"])
    assert sorted(slots.values()) == [0, 1]
    assert table.slot_of("old1") is None  # evicted


def test_inflight_carries_survive_interleaved_lru_eviction():
    """Satellite acceptance: a session with a request in flight is never an
    LRU victim — its slot (and therefore its carry) survives an interleaved
    eviction pass triggered by reserve() under pressure."""
    engine = MockModelEngine(2, params={"version": "v1"})
    gw = InferenceGateway(engine, max_delay_s=0.001, idle_ttl_s=0.02).start()
    try:
        gw.act("busy", obs_of(1.0))
        gw.act("busy", obs_of(1.0))  # carry advanced to 2
        gw.act("idle", obs_of(1.0))
        time.sleep(0.06)  # both idle-expired by ttl...
        gw.sessions.acquire("busy")  # ...but "busy" now has one in flight
        try:
            # eviction pass must take the idle session, not the in-flight one
            slots = gw.reserve_sessions(["fresh"])
            assert slots["fresh"] == gw.sessions.slot_of("fresh")
            assert gw.sessions.slot_of("idle") is None  # the victim
            assert gw.sessions.slot_of("busy") is not None
        finally:
            gw.sessions.release("busy")
        # the in-flight session's carry is intact: next step continues at 3
        assert gw.act("busy", obs_of(1.0))["step"] == 3
        # and a second reservation now has NO legal victim -> typed shed
        gw.sessions.acquire("busy")
        gw.sessions.acquire("fresh")
        try:
            with pytest.raises(CapacityError):
                gw.reserve_sessions(["overflow"])
        finally:
            gw.sessions.release("busy")
            gw.sessions.release("fresh")
    finally:
        gw.drain_and_stop()


# ------------------------------------------------------------- device fetch
def test_decollate_fetches_once_and_hands_out_views():
    from distar_tpu.actor.inference import decollate

    tree = {"a": np.arange(12).reshape(4, 3), "b": {"c": np.ones((4, 2))}}
    out = decollate(tree, 2)
    np.testing.assert_array_equal(out["a"], [6, 7, 8])
    assert out["b"]["c"].shape == (2,)


# --------------------------------------------------------- local plane client
def test_local_clients_coalesce_in_one_flush():
    plane = RolloutPlane(backend="local", slots=8,
                         engine_factory=mock_factory(delay_s=0.004),
                         max_delay_s=0.02)
    try:
        c1 = plane.client_for("MP0", num_slots=4)
        c2 = plane.client_for("MP0", num_slots=4)
        errs = []

        def cycles(c, n):
            try:
                for _ in range(n):
                    outs = c.sample([obs_of(1.0)] * 4, [True] * 4)
                    assert all(o is not None for o in outs)
            except Exception as e:  # pragma: no cover
                errs.append(e)

        t1 = threading.Thread(target=cycles, args=(c1, 6))
        t2 = threading.Thread(target=cycles, args=(c2, 6))
        t1.start(); t2.start(); t1.join(); t2.join()
        assert not errs
        snap = get_registry().snapshot()
        occ = (snap["distar_serve_batch_occupancy_sum"]
               / snap["distar_serve_batch_occupancy_count"])
        assert occ > 1.0, "two actors' cycles never coalesced"
        assert snap["distar_rollout_samples_total{backend=local}"] == 48
        c1.close(); c2.close()
    finally:
        plane.shutdown()


def test_local_partial_active_and_reset_slot_semantics():
    plane = RolloutPlane(backend="local", engine_factory=mock_factory())
    try:
        c = plane.client_for("MP0", num_slots=2)
        outs = c.sample([obs_of(1.0)] * 2, [True, True])
        assert [o["step"] for o in outs] == [1, 1]
        outs = c.sample([obs_of(1.0)] * 2, [True, False])
        assert outs[0]["step"] == 2 and outs[1] is None  # inactive lane held
        c.reset_slot(0)
        outs = c.sample([obs_of(1.0)] * 2, [True, True])
        # slot 0 restarted from zero carry; slot 1 kept its carry
        assert [o["step"] for o in outs] == [1, 2]
        assert c.hidden_for_slot(0) == {"step": 1}
        assert c.hidden_for_slot(1) == {"step": 2}
        c.close()
    finally:
        plane.shutdown()


def test_teacher_piggybacks_on_same_flush_and_carries_track_active():
    plane = RolloutPlane(backend="local", engine_factory=mock_factory())
    try:
        c = plane.client_for("MP0", num_slots=2,
                             teacher_params={"version": "t1"})
        outs = c.sample([obs_of(1.0)] * 2, [True, True])
        tl = c.teacher_logits([obs_of(1.0)] * 2, outs, [True, True])
        assert [t["teacher_step"] for t in tl] == [1, 1]
        assert tl[0]["teacher_version"] == "t1"
        outs = c.sample([obs_of(1.0)] * 2, [False, True])
        tl = c.teacher_logits([obs_of(1.0)] * 2, outs, [False, True])
        assert tl[0] is None and tl[1]["teacher_step"] == 2
        c.reset_slot(1)  # zeroes policy AND teacher carry
        outs = c.sample([obs_of(1.0)] * 2, [True, True])
        tl = c.teacher_logits([obs_of(1.0)] * 2, outs, [True, True])
        assert tl[1]["teacher_step"] == 1
        # exactly one engine forward + one teacher forward per cycle: the
        # teacher rode the SAME flush, never a second round-trip
        gw = plane._gateways["MP0"]
        assert gw.engine.teacher_calls == gw.engine.forward_calls
        c.close()
    finally:
        plane.shutdown()


def test_exact_capacity_reservation_fails_fast_at_client_creation():
    plane = RolloutPlane(backend="local", slots=2, engine_factory=mock_factory())
    try:
        plane.client_for("MP0", num_slots=2)
        with pytest.raises(CapacityError):
            plane.client_for("MP0", num_slots=2)  # 2 slots already reserved
    finally:
        plane.shutdown()


def test_refresh_dedupes_to_one_registry_swap_per_iteration():
    plane = RolloutPlane(backend="local", slots=4, engine_factory=mock_factory())
    try:
        c1 = plane.client_for("MP0", num_slots=2,
                              params={"version": "v1", "bias": 0.0})
        c2 = plane.client_for("MP0", num_slots=2)
        c1.refresh({"version": "v7", "bias": 7.0}, 7)
        c2.refresh({"version": "v7", "bias": 7.0}, 7)  # same iter: deduped
        c2.refresh({"version": "v5", "bias": 5.0}, 5)  # stale iter: ignored
        out = c2.sample([obs_of(0.0)] * 2)
        assert all(o["model_version"] == "MP0@7" for o in out if o)
        snap = get_registry().snapshot()
        assert snap["distar_rollout_swaps_total"] == 1
        c1.close(); c2.close()
    finally:
        plane.shutdown()


def test_shed_lanes_retry_individually_without_reexecuting_winners():
    """A transient per-lane shed must retry ONLY the shed lane: lanes that
    already advanced their carry are never double-stepped by the retry."""

    class FlakyTarget(_LocalTarget):
        def __init__(self, gw):
            super().__init__(gw)
            self.calls = 0

        def act_many(self, requests, timeout_s=None):
            self.calls += 1
            results = super().act_many(requests, timeout_s)
            if self.calls == 1:  # shed the LAST lane of the first cycle
                results[-1] = QueueFullError("induced")
            return results

    engine = MockModelEngine(2, params={"version": "v1"})
    gw = InferenceGateway(engine, max_delay_s=0.001).start()
    target = FlakyTarget(gw)
    try:
        client = GatewayPolicyClient(target, ["s0", "s1"], player_id="MP0",
                                     timeout_s=5.0)
        outs = client.sample([obs_of(1.0)] * 2, [True, True])
        assert outs[0]["step"] == 1
        # lane 1's first answer was dropped as a shed, so its retry is the
        # visible step... the dropped forward still advanced the carry once
        assert target.calls == 2
        assert get_registry().snapshot()[
            "distar_rollout_shed_total{backend=local}"] == 1
        client.close()
    finally:
        gw.drain_and_stop()


# -------------------------------------------------- remote + chaos restart
def _serve_stack(slots=4, port=0, teacher=True):
    engine = MockModelEngine(
        slots, params={"version": "v1", "bias": 0.0},
        teacher_params={"version": "t1"} if teacher else None,
    )
    gw = InferenceGateway(engine, max_delay_s=0.002, default_timeout_s=5.0).start()
    gw.load_version("v1", params={"version": "v1", "bias": 0.0}, activate=True)
    srv = ServeTCPServer(gw, host="127.0.0.1", port=port).start()
    return engine, gw, srv


def test_remote_backend_round_trip_with_teacher():
    engine, gw, srv = _serve_stack()
    plane = RolloutPlane(backend="remote", addr=f"{srv.host}:{srv.port}",
                         timeout_s=5.0)
    try:
        c = plane.client_for("MP0", num_slots=2,
                             teacher_params={"version": "t2"})
        outs = c.sample([obs_of(2.0)] * 2)
        assert [o["step"] for o in outs] == [1, 1]
        tl = c.teacher_logits([obs_of(2.0)] * 2, outs)
        assert tl[0]["teacher_version"] == "t2"  # set_teacher over the wire
        assert c.hidden_for_slot(0) == {"step": 1}
        c.reset_slot(0)
        assert c.sample([obs_of(2.0)] * 2)[0]["step"] == 1
        c.close()
    finally:
        srv.stop()
        gw.drain_and_stop()


def test_remote_rides_gateway_kill_restart_and_counts_carry_resets(chaos):
    """Satellite acceptance: the gateway dies mid-episode (chaos
    ``kill_role``) and comes back on the same port; the episode FINISHES
    through the client's reconnect/retry, the carry re-materializes from
    zero (server step counter restarts), and the re-materialization is
    counted in ``distar_actor_carry_resets_total``."""
    engine, gw, srv = _serve_stack(teacher=False)
    port = srv.port
    plane = RolloutPlane(backend="remote", addr=f"127.0.0.1:{port}",
                         timeout_s=5.0)
    client = plane.client_for("MP0", num_slots=2)
    new_stack = []
    try:
        episode_steps = []
        for i in range(3):  # first half of the "episode"
            outs = client.sample([obs_of(1.0)] * 2)
            episode_steps.append(outs[0]["step"])
        assert episode_steps == [1, 2, 3]

        # kill the gateway hard (chaos-tagged), restart on the SAME port
        chaos.kill_role(srv, name="serve-gateway")
        gw.drain_and_stop(timeout=2.0)
        new_stack[:] = _serve_stack(port=port, teacher=False)

        for i in range(3):  # second half rides reconnect + fresh carries
            outs = client.sample([obs_of(1.0)] * 2)
            episode_steps.append(outs[0]["step"])
        # the episode finished; the carry restarted from zero at the kill
        assert episode_steps == [1, 2, 3, 1, 2, 3]
        snap = get_registry().snapshot()
        # both lanes' carries were re-materialized exactly once
        assert snap["distar_actor_carry_resets_total{player=MP0}"] == 2
        assert any(e["kind"] == "kill_role" for e in chaos.events)
        client.close()
    finally:
        if new_stack:
            new_stack[2].stop()
            new_stack[1].drain_and_stop(timeout=2.0)
        else:
            srv.stop()
            gw.drain_and_stop(timeout=2.0)


# ------------------------------------------------------------ actor e2e
def _actor(plane_cfg, tmp_path=None, env_num=2):
    from distar_tpu.actor import Actor
    from distar_tpu.envs import MockEnv

    return Actor(
        cfg={"actor": {"env_num": env_num, "traj_len": 2, "seed": 3,
                       "plane": plane_cfg}},
        model_cfg=SMALL_MODEL,
        env_fn=lambda: MockEnv(episode_game_loops=300, seed=1),
    )


@pytest.mark.slow
def test_actor_runs_job_on_local_plane_real_model():
    """The actor's whole hot path — sample, teacher logits, resets, carry
    backup — through the SHARED in-process gateway on the real small model;
    results match the job contract and the coalescing metrics exist."""
    actor = _actor({"backend": "local", "slots": 2, "max_delay_s": 0.002,
                    "timeout_s": 120.0})
    results = actor.run_job(episodes=2)
    assert len(results) >= 2
    assert all("0" in r for r in results)
    snap = get_registry().snapshot()
    assert snap["distar_rollout_samples_total{backend=local}"] > 0
    assert snap["distar_serve_batch_occupancy_count"] > 0
    assert snap["distar_rollout_plane_backend{backend=local}"] == 1.0
    actor.plane.shutdown()


@pytest.mark.slow
def test_actor_runs_job_on_remote_plane_real_model():
    """Remote backend end-to-end on the real model: a bin/serve-shaped
    gateway (BatchedInferenceEngine over TCP) serves an Actor job; teacher
    logits and carries ride the wire."""
    import jax

    from distar_tpu.actor.inference import BatchedInference
    from distar_tpu.lib import features as F
    from distar_tpu.model import Model, default_model_config
    from distar_tpu.serve import BatchedInferenceEngine
    from distar_tpu.utils import deep_merge_dicts

    cfg = deep_merge_dicts(default_model_config(), SMALL_MODEL)
    cfg.use_value_network = False
    model = Model(cfg)
    # boot params exactly as the actor would build them
    probe = _actor({"backend": "inline"})
    params = probe._initial_params()
    # both job sides (MP0 and HP0) reserve env_num sessions on this ONE
    # gateway — size it for the whole job (exact-capacity admission)
    engine = BatchedInferenceEngine(
        BatchedInference(model, jax.tree.map(np.asarray, params), num_slots=4))
    gw = InferenceGateway(engine, max_delay_s=0.002,
                          default_timeout_s=120.0).start()
    gw.load_version("v1", params=params, activate=True)
    srv = ServeTCPServer(gw, host="127.0.0.1").start()
    try:
        actor = _actor({"backend": "remote", "addr": f"{srv.host}:{srv.port}",
                        "timeout_s": 120.0})
        results = actor.run_job(episodes=1)
        assert len(results) >= 1
        snap = get_registry().snapshot()
        assert snap["distar_rollout_samples_total{backend=remote}"] > 0
    finally:
        srv.stop()
        gw.drain_and_stop()


def test_actor_defaults_shared_slots_to_both_job_sides():
    """A self-play job puts TWO clients of the same player on one shared
    gateway (2 x env_num sessions); an unsized plane must default to that,
    or the second side's exact-capacity reserve fails every job."""
    actor = _actor({"backend": "local"}, env_num=3)
    assert actor.plane.slots == 6
    # an explicit size is respected
    actor = _actor({"backend": "local", "slots": 2}, env_num=3)
    assert actor.plane.slots == 2


# ----------------------------------------------------------- tools plumbing
def test_loadgen_sessions_mode_reports_shed_rate():
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    from tools.loadgen import run_loadgen

    summary = run_loadgen(mode="sessions", rate=300.0, duration_s=1.0,
                          requests_per_session=3, slots=8, mock_delay_s=0.001)
    assert summary["mode"] == "sessions"
    assert summary["sessions"]["started"] > 0
    assert summary["sessions"]["completed"] > 0
    assert "shed_rate" in summary and "session_shed_rate" in summary["sessions"]


def test_perf_gate_trajectory_picks_up_rollout_artifacts(tmp_path):
    import json
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parents[1]))
    from tools.perf_gate import collect_trajectory

    (tmp_path / "artifacts").mkdir()
    (tmp_path / "ROLLOUT_r99.json").write_text(json.dumps({
        "metric": "rollout plane env-steps/s, local vs inline @16 actors",
        "value": 4.5, "unit": "x inline", "vs_baseline": 2.0, "device": "cpu",
    }))
    rows = collect_trajectory(repo=str(tmp_path))
    rollout = [r for r in rows if r["artifact"] == "ROLLOUT_r99.json"]
    assert rollout and rollout[0]["round"] == "99"
    assert rollout[0]["status"] == "ok (CPU-derived)"
    assert rollout[0]["value"] == 4.5
