"""Fleet health layer: TSDB ring store + windowed queries, rule state-machine
debounce, telemetry shipping round-trip through the comm serializer, flight
recorder crash bundles, and the /healthz /alerts /timeseries HTTP surfaces
(the acceptance path: one injected learner stall + one injected NaN loss ->
exactly one firing alert each via GET /alerts, then a simulated crash dumps
a bundle carrying the alert history and a registry snapshot)."""
import json
import math
import sys
import time
import urllib.error
import urllib.request

import pytest

from distar_tpu.obs import (
    FleetHealth,
    FlightRecorder,
    HealthEvaluator,
    HealthRule,
    MetricsRegistry,
    TelemetryIngest,
    TelemetryShipper,
    TimeSeriesStore,
    default_rulebook,
    set_flight_recorder,
    set_fleet_health,
    set_registry,
)


@pytest.fixture
def registry():
    reg = MetricsRegistry()
    prev = set_registry(reg)
    yield reg
    set_registry(prev)


@pytest.fixture
def recorder():
    rec = FlightRecorder()
    prev = set_flight_recorder(rec)
    yield rec
    set_flight_recorder(prev)


@pytest.fixture
def fleet(registry, recorder):
    """A process fleet-health handle with fast test cadences (not started —
    tests drive sampling/evaluation deterministically unless they start it)."""
    # short stall window: a stall is "no counter progress for ~window_s", so
    # the test's injected stall becomes visible within a second
    fh = FleetHealth(rules=default_rulebook(stall_window_s=0.6),
                     registry=registry,
                     sample_interval_s=0.05, eval_interval_s=0.05,
                     recorder=recorder)
    prev = set_fleet_health(fh)
    yield fh
    fh.stop()
    set_fleet_health(prev)


# ------------------------------------------------------------------- TSDB
def test_ring_buffer_wraparound_and_windowed_queries():
    store = TimeSeriesStore(points_per_series=4)
    t0 = 1000.0
    for i in range(10):  # 10 points into a 4-slot ring
        store.record("distar_x_total", float(i), ts=t0 + i)
    q = store.query("distar_x_total", window_s=100.0)
    # wraparound: only the last 4 points survive (values 6..9)
    assert q["count"] == 4
    assert q["min"] == 6.0 and q["max"] == 9.0 and q["last"] == 9.0
    assert q["mean"] == pytest.approx(7.5)
    assert q["rate"] == pytest.approx(1.0)  # +1 per second
    # the window filter excludes older points even inside the ring
    q2 = store.query("distar_x_total", window_s=1.5)
    assert q2["count"] == 2 and q2["min"] == 8.0
    # unknown series -> None
    assert store.query("nope") is None


def test_store_window_stats_and_family_matching():
    store = TimeSeriesStore()
    t0 = 2000.0
    for i, v in enumerate([5.0, 1.0, 3.0]):
        store.record("distar_q_depth{token=a}", v, ts=t0 + i)
    store.record("distar_q_depth{token=b}", 7.0, ts=t0)
    store.record("distar_other", 1.0, ts=t0)
    fam = store.matching_names("distar_q_depth")
    assert fam == ["distar_q_depth{token=a}", "distar_q_depth{token=b}"]
    q = store.query("distar_q_depth{token=a}", window_s=100.0)
    assert (q["last"], q["min"], q["max"]) == (3.0, 1.0, 5.0)
    # single-point series: no slope to compute
    assert store.query("distar_q_depth{token=b}", window_s=100.0)["rate"] is None


def test_store_series_cap_refuses_new_series_only():
    store = TimeSeriesStore(points_per_series=8, max_series=2)
    assert store.record("a", 1.0)
    assert store.record("b", 1.0)
    assert not store.record("c", 1.0)  # cap: new series refused
    assert store.record("a", 2.0)  # existing series still accepts
    assert store.stats()["dropped_series"] == 1


# ----------------------------------------------------------- rules engine
def _feed(store, name, values, t0=1000.0, dt=1.0):
    for i, v in enumerate(values):
        store.record(name, v, ts=t0 + i * dt)


def test_rule_state_machine_debounce_nan_loss_and_stall(registry, recorder):
    """Inject a NaN-loss gauge and a stalled step counter; each rule fires
    exactly once (debounced), then recovers back to ok."""
    store = TimeSeriesStore()
    rules = [
        HealthRule(name="loss_nan", metric="distar_learner_loss",
                   op="nonfinite", for_count=2, clear_count=2),
        # short window: a stall is "no progress for ~window_s" — the window
        # must slide past the last advance before the rate can read 0
        HealthRule(name="step_stall", metric="distar_learner_iterations_total",
                   op="stalled", window_s=10.0, for_count=2, clear_count=2),
    ]
    ev = HealthEvaluator(store, rules, recorder=recorder, registry=registry)

    # healthy history: finite loss, advancing counter
    _feed(store, "distar_learner_loss", [0.5, 0.4, 0.3])
    _feed(store, "distar_learner_iterations_total", [1, 2, 3])
    ev.evaluate_once()
    states = ev.alerts()["rules"]
    assert states["loss_nan"]["state"] == "ok"
    assert states["step_stall"]["state"] == "ok"

    # inject: NaN loss + a counter that stopped moving long enough that the
    # stall window holds only flat samples
    _feed(store, "distar_learner_loss", [float("nan")], t0=1103.0)
    _feed(store, "distar_learner_iterations_total", [3, 3, 3], t0=1100.0)
    ev.evaluate_once()  # first breach: warning, debounce holds firing back
    states = ev.alerts()["rules"]
    assert states["loss_nan"]["state"] == "warning"
    assert states["step_stall"]["state"] == "warning"
    ev.evaluate_once()  # second consecutive breach: firing
    ev.evaluate_once()  # still breached: NO second firing event
    alerts = ev.alerts()
    assert set(alerts["firing"]) == {"loss_nan", "step_stall"}
    firing_events = [e for e in alerts["history"] if e["state"] == "firing"]
    assert sorted(e["rule"] for e in firing_events) == ["loss_nan", "step_stall"]
    assert alerts["rules"]["loss_nan"]["fired_count"] == 1
    assert alerts["rules"]["step_stall"]["fired_count"] == 1
    # NaN rule reports the offending value; stall reports the zero rate
    assert math.isnan(alerts["rules"]["loss_nan"]["value"])
    assert alerts["rules"]["step_stall"]["value"] == 0.0

    # recovery: finite loss again, counter advancing again
    _feed(store, "distar_learner_loss", [0.2, 0.2], t0=1110.0)
    _feed(store, "distar_learner_iterations_total", [4, 5, 6], t0=1110.0)
    ev.evaluate_once()
    assert ev.alerts()["rules"]["loss_nan"]["state"] == "firing"  # clear debounce
    ev.evaluate_once()
    states = ev.alerts()["rules"]
    assert states["loss_nan"]["state"] == "ok"
    assert states["step_stall"]["state"] == "ok"
    # alert transitions landed in the flight recorder ring
    kinds = [e["kind"] for e in recorder.events()]
    assert kinds.count("alert") == len(ev.alerts()["history"])


def test_rule_no_data_is_not_a_breach(registry):
    store = TimeSeriesStore()
    ev = HealthEvaluator(store, [HealthRule(
        name="r", metric="distar_never_registered", op="stalled")],
        registry=registry)
    ev.evaluate_once()
    st = ev.alerts()["rules"]["r"]
    assert st["state"] == "ok" and st["no_data"]


def test_threshold_and_family_rules(registry):
    """A labelled family breaches when ANY series breaches (worst wins)."""
    store = TimeSeriesStore()
    _feed(store, "distar_coordinator_queue_depth{token=a}", [10.0, 10.0])
    _feed(store, "distar_coordinator_queue_depth{token=b}", [400.0, 401.0])
    ev = HealthEvaluator(store, [HealthRule(
        name="sat", metric="distar_coordinator_queue_depth",
        agg="last", op=">=", threshold=384.0, for_count=1)],
        registry=registry)
    ev.evaluate_once()
    st = ev.alerts()["rules"]["sat"]
    assert st["state"] == "firing" and st["value"] == 401.0
    assert st["series"].endswith("{token=b}")


# ------------------------------------------------------ telemetry shipping
def test_shipper_roundtrip_in_process(registry):
    store = TimeSeriesStore()
    ingest = TelemetryIngest(store, registry=registry)
    registry.counter("distar_env_steps_total").inc(7)
    ship = TelemetryShipper("actor:1", ingest=ingest, interval_s=99,
                            registry=registry)
    n = ship.ship_once()
    assert n >= 1
    q = store.query("distar_env_steps_total", source="actor:1", window_s=60.0)
    assert q["last"] == 7.0
    assert "actor:1" in store.sources()


def test_shipper_roundtrip_through_serializer_and_coordinator(registry, fleet):
    """The wire path: snapshot -> comm serializer -> POST /coordinator/telemetry
    -> TelemetryIngest -> per-source series with last-seen tracking."""
    from distar_tpu.comm import CoordinatorServer

    registry.gauge("distar_dataloader_occupancy").set(5.0)
    srv = CoordinatorServer()
    srv.start()
    try:
        ship = TelemetryShipper(
            "learner:MP0", coordinator_addr=(srv.host, srv.port),
            interval_s=99, registry=registry,
        )
        n = ship.ship_once()
        assert n >= 1
        q = fleet.store.query("distar_dataloader_occupancy",
                              source="learner:MP0", window_s=60.0)
        assert q["last"] == 5.0
        src = fleet.store.sources()["learner:MP0"]
        assert src["age_s"] < 30.0
        # ship counter ticked on the sender side
        assert registry.snapshot()["distar_telemetry_ships_total"] == 1.0
    finally:
        srv.stop()


# --------------------------------------------------------- flight recorder
def test_flight_recorder_ring_and_bundle_on_exception(tmp_path, registry, recorder):
    registry.counter("distar_env_steps_total").inc(3)
    for i in range(600):  # overflow the default 512-slot ring
        recorder.record("tick", i=i)
    assert len(recorder.events()) == 512
    assert recorder.events()[0]["i"] == 88  # oldest aged out

    recorder.install_crash_hook(str(tmp_path), config={"exp": "t"},
                                registry=registry, handle_sigterm=False)
    try:
        try:
            raise ValueError("injected crash")
        except ValueError:
            # what the interpreter does on the way down for an unhandled
            # exception — invoke the installed hook directly
            hook, prev = sys.excepthook, recorder._prev_excepthook
            recorder._prev_excepthook = lambda *a: None  # silence the chain
            try:
                hook(*sys.exc_info())
            finally:
                recorder._prev_excepthook = prev
    finally:
        recorder.uninstall_crash_hook()

    assert recorder.last_dump_path is not None
    with open(recorder.last_dump_path) as f:
        bundle = json.load(f)
    assert bundle["reason"] == "unhandled:ValueError"
    assert bundle["config"] == {"exp": "t"}
    assert bundle["registry_snapshot"]["distar_env_steps_total"] == 3.0
    assert "python" in bundle["versions"]
    crash = [e for e in bundle["events"] if e["kind"] == "crash"]
    assert len(crash) == 1 and "injected crash" in crash[0]["traceback"]


# ----------------------------------------------- HTTP surfaces (acceptance)
def _get(host, port, path):
    try:
        with urllib.request.urlopen(f"http://{host}:{port}{path}", timeout=10) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_injected_stall_and_nan_fire_exactly_one_alert_each_via_http(
        registry, recorder, fleet, tmp_path):
    """ACCEPTANCE: an injected learner stall and an injected NaN loss each
    produce exactly one firing alert visible via GET /alerts within one
    evaluation interval; a simulated crash then writes a flight-recorder
    bundle containing the alert history and a registry snapshot."""
    from distar_tpu.comm import CoordinatorServer

    srv = CoordinatorServer()
    srv.start()
    try:
        # healthy phase: loss finite, iterations advancing
        loss = registry.gauge("distar_learner_loss")
        iters = registry.counter("distar_learner_iterations_total")
        loss.set(0.5)
        for _ in range(3):
            iters.inc()
            fleet.sampler.sample_once()
            time.sleep(0.02)
        fleet.start()  # background sampling + evaluation from here

        # inject BOTH failures: loss goes NaN, the step counter stops
        loss.set(float("nan"))
        deadline = time.time() + 20
        firing = []
        while time.time() < deadline:
            _status, alerts = _get(srv.host, srv.port, "/alerts")
            firing = alerts["firing"]
            if {"learner_loss_nonfinite", "learner_step_stall"} <= set(firing):
                break
            time.sleep(0.05)
        assert {"learner_loss_nonfinite", "learner_step_stall"} <= set(firing)
        # exactly ONE firing alert each — debounce holds, no re-fire per tick
        time.sleep(0.3)  # several more evaluation intervals pass
        _status, alerts = _get(srv.host, srv.port, "/alerts")
        for rule in ("learner_loss_nonfinite", "learner_step_stall"):
            assert alerts["rules"][rule]["fired_count"] == 1
            events = [e for e in alerts["history"]
                      if e["rule"] == rule and e["state"] == "firing"]
            assert len(events) == 1

        # /healthz: firing -> 503 with the failing rules listed
        status, hz = _get(srv.host, srv.port, "/healthz")
        assert status == 503 and hz["status"] == "firing"

        # /timeseries serves the offending series' window
        status, ts = _get(
            srv.host, srv.port,
            "/timeseries?name=distar_learner_loss&window_s=60")
        assert status == 200 and ts["points"]["local"]

        # simulated crash: the bundle carries alert history + snapshot
        recorder.install_crash_hook(str(tmp_path), registry=registry,
                                    handle_sigterm=False)
        try:
            try:
                raise RuntimeError("simulated crash")
            except RuntimeError:
                prev = recorder._prev_excepthook
                recorder._prev_excepthook = lambda *a: None
                try:
                    sys.excepthook(*sys.exc_info())
                finally:
                    recorder._prev_excepthook = prev
        finally:
            recorder.uninstall_crash_hook()
        with open(recorder.last_dump_path) as f:
            bundle = json.load(f)
        alert_rules = {e.get("rule") for e in bundle["events"]
                       if e["kind"] == "alert" and e.get("state") == "firing"}
        assert {"learner_loss_nonfinite", "learner_step_stall"} <= alert_rules
        assert "distar_learner_iterations_total" in bundle["registry_snapshot"]
    finally:
        srv.stop()


def test_serve_frontend_answers_health_routes(registry, fleet):
    """The serve HTTP frontend shares the same health surface."""
    from distar_tpu.serve import InferenceGateway, MockModelEngine, ServeHTTPServer

    gw = InferenceGateway(MockModelEngine(2), max_delay_s=0.001)
    gw.start()
    http = ServeHTTPServer(gw).start()
    try:
        fleet.sampler.sample_once()
        status, hz = _get(http.host, http.port, "/healthz")
        assert status == 200 and hz["status"] == "ok"
        status, alerts = _get(http.host, http.port, "/alerts")
        assert status == 200 and "rules" in alerts
        status, err = _get(http.host, http.port, "/timeseries")
        assert status == 400  # name is required
    finally:
        http.stop()
        gw.drain_and_stop(5.0)


def test_healthz_sources_staleness(registry, fleet):
    fleet.stale_after_s = 0.05
    fleet.ingest.ingest({"source": "actor:9", "ts": time.time() - 10.0,
                         "snapshot": {"distar_env_steps_total": 1.0}})
    hz = fleet.healthz()
    assert hz["sources"]["actor:9"]["stale"] is True
    fleet.ingest.ingest({"source": "actor:9", "ts": time.time(),
                         "snapshot": {"distar_env_steps_total": 2.0}})
    assert fleet.healthz()["sources"]["actor:9"]["stale"] is False
