"""Pseudo-reward metric tests (oracle values computed by hand)."""
import numpy as np

from distar_tpu.ops.metric import hamming_distance, l2_distance, levenshtein_distance


def test_levenshtein_basic():
    assert levenshtein_distance(np.array([1, 2, 3]), np.array([1, 2, 3])) == 0.0
    assert levenshtein_distance(np.array([1, 2]), np.array([1, 2, 3])) == 1.0
    assert levenshtein_distance(np.array([], dtype=int), np.array([1, 2])) == 2.0
    assert levenshtein_distance(np.array([1, 4, 3]), np.array([1, 2, 3])) == 1.0


def test_levenshtein_location_cost():
    # matching tokens still pay the clamped L2 location cost
    d = levenshtein_distance(
        np.array([5]), np.array([5]),
        np.array([0]), np.array([10]),  # same row, 10 px apart -> 10/5 clamped to 0.8
        lambda a, b: l2_distance(a, b, spatial_x=160),
    )
    assert abs(d - 0.8) < 1e-6


def test_hamming():
    assert hamming_distance(np.array([1, 0, 1]), np.array([1, 1, 0])) == 2.0


def test_l2_distance_clamp():
    assert l2_distance(0, 0) == 0.0
    assert l2_distance(0, 3) == 0.6  # 3px/5 = 0.6
    assert l2_distance(0, 159) == 0.8  # clamped
