import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distar_tpu.ops import (
    AttentionPool,
    FCBlock,
    GLU,
    LayerNormLSTMCell,
    ResBlock,
    ResFCBlock,
    StackedLSTM,
    Transformer,
    binary_encode,
    one_hot,
    scatter_connection,
    sequence_mask,
)


def test_one_hot_clamps():
    x = jnp.array([0, 5, 99])
    out = one_hot(x, 6)
    assert out.shape == (3, 6)
    assert out[2, 5] == 1.0  # out-of-range clamps to last class


def test_binary_encode():
    out = np.asarray(binary_encode(jnp.array([5]), 4))
    np.testing.assert_array_equal(out[0], [0, 1, 0, 1])


def test_sequence_mask():
    m = np.asarray(sequence_mask(jnp.array([0, 2, 4]), 4))
    assert m.sum() == 6
    assert m[1, 1] and not m[1, 2]


def test_fc_res_blocks():
    x = jnp.ones((2, 16))
    for mod in (FCBlock(32), ResFCBlock(16, norm="LN")):
        params = mod.init(jax.random.PRNGKey(0), x)
        y = mod.apply(params, x)
        assert y.shape[0] == 2


def test_conv_res_block():
    x = jnp.ones((2, 8, 8, 4))
    mod = ResBlock(4)
    y = mod.apply(mod.init(jax.random.PRNGKey(0), x), x)
    assert y.shape == (2, 8, 8, 4)


def test_glu():
    x, ctx = jnp.ones((2, 16)), jnp.ones((2, 8))
    mod = GLU(32)
    y = mod.apply(mod.init(jax.random.PRNGKey(0), x, ctx), x, ctx)
    assert y.shape == (2, 32)


def test_transformer_masked_invariance():
    """Padded entity slots must not influence valid entity outputs."""
    B, N, D = 2, 8, 12
    rng = np.random.default_rng(0)
    x = rng.standard_normal((B, N, D)).astype(np.float32)
    lengths = jnp.array([5, 8])
    mask = sequence_mask(lengths, N)
    mod = Transformer(head_dim=8, hidden_dim=16, output_dim=16, layer_num=2)
    params = mod.init(jax.random.PRNGKey(0), jnp.asarray(x), mask)
    y1 = mod.apply(params, jnp.asarray(x), mask)
    # perturb padding slots of batch 0 (idx >= 5)
    x2 = x.copy()
    x2[0, 5:] += 100.0
    y2 = mod.apply(params, jnp.asarray(x2), mask)
    np.testing.assert_allclose(np.asarray(y1[0, :5]), np.asarray(y2[0, :5]), atol=1e-4)
    np.testing.assert_allclose(np.asarray(y1[1]), np.asarray(y2[1]), atol=1e-4)


def test_attention_pool():
    B, N, C = 2, 6, 8
    x = jnp.ones((B, N, C))
    mask = sequence_mask(jnp.array([3, 6]), N)[..., None]
    mod = AttentionPool(head_num=2, output_dim=16, max_num=7)
    params = mod.init(jax.random.PRNGKey(0), x, jnp.array([3, 6]), mask)
    y = mod.apply(params, x, jnp.array([3, 6]), mask)
    assert y.shape == (2, 16)


def test_lstm_cell_and_stack():
    T, B, D, H = 5, 2, 12, 16
    xs = jnp.asarray(np.random.default_rng(0).standard_normal((T, B, D)), dtype=jnp.float32)
    mod = StackedLSTM(hidden_size=H, num_layers=3)
    params = mod.init(jax.random.PRNGKey(0), xs)
    ys, final = mod.apply(params, xs)
    assert ys.shape == (T, B, H)
    assert len(final) == 3 and final[0][0].shape == (B, H)
    # carrying state: running [T] then [T:] from the carried state == running all at once
    ys_a, st = mod.apply(params, xs[:3])
    ys_b, _ = mod.apply(params, xs[3:], st)
    np.testing.assert_allclose(np.asarray(jnp.concatenate([ys_a, ys_b], 0)), np.asarray(ys), atol=1e-5)


def test_lstm_layer_major_matches_time_major():
    """Layer-major execution (hoisted input projection) must be numerically
    identical to the time-major scan on the same params, for both cell
    types, including carried-state restarts."""
    T, B, D, H = 6, 3, 10, 16
    xs = jnp.asarray(np.random.default_rng(2).standard_normal((T, B, D)), dtype=jnp.float32)
    for norm in ("LN", "none"):
        lm = StackedLSTM(hidden_size=H, num_layers=3, norm=norm)  # default layer-major
        tm = StackedLSTM(hidden_size=H, num_layers=3, norm=norm, layer_major=False)
        params = lm.init(jax.random.PRNGKey(0), xs)
        ys_lm, fin_lm = lm.apply(params, xs)
        ys_tm, fin_tm = tm.apply(params, xs)
        np.testing.assert_allclose(np.asarray(ys_lm), np.asarray(ys_tm), atol=1e-5)
        for a, b in zip(fin_lm, fin_tm):
            np.testing.assert_allclose(np.asarray(a[1]), np.asarray(b[1]), atol=1e-5)
        # carried state across a split run
        ys_a, st = lm.apply(params, xs[:2])
        ys_b, _ = lm.apply(params, xs[2:], st)
        np.testing.assert_allclose(
            np.asarray(jnp.concatenate([ys_a, ys_b], 0)), np.asarray(ys_lm), atol=1e-5
        )


def test_lstm_scan_unroll_equivalence():
    """scan_unroll is a pure scheduling knob: same params, same outputs —
    including a T that the unroll factor does not divide."""
    T, B, D, H = 7, 2, 12, 16
    xs = jnp.asarray(np.random.default_rng(1).standard_normal((T, B, D)), dtype=jnp.float32)
    base = StackedLSTM(hidden_size=H, num_layers=2)
    params = base.init(jax.random.PRNGKey(0), xs)
    ys0, fin0 = base.apply(params, xs)
    for u in (4, 8):
        mod = StackedLSTM(hidden_size=H, num_layers=2, scan_unroll=u)
        ys, fin = mod.apply(params, xs)
        np.testing.assert_allclose(np.asarray(ys), np.asarray(ys0), atol=1e-6)
        np.testing.assert_allclose(np.asarray(fin[1][1]), np.asarray(fin0[1][1]), atol=1e-6)


def test_scatter_connection_add():
    B, N, D, H, W = 2, 4, 3, 5, 6
    emb = jnp.ones((B, N, D))
    # two entities share a cell in batch 0 -> embeddings add
    loc = jnp.array(
        [[[1, 2], [1, 2], [0, 0], [5, 4]], [[3, 1], [2, 2], [0, 4], [9, 9]]]
    )
    out = np.asarray(scatter_connection(emb, loc, (H, W), "add"))
    assert out.shape == (B, H, W, D)
    np.testing.assert_array_equal(out[0, 2, 1], [2, 2, 2])  # (x=1,y=2) doubled
    np.testing.assert_array_equal(out[0, 0, 0], [1, 1, 1])
    # out-of-range location clamps into the map
    np.testing.assert_array_equal(out[1, 4, 5], [1, 1, 1])


def test_scatter_connection_cover():
    B, N, D, H, W = 1, 2, 2, 3, 3
    emb = jnp.array([[[1.0, 1.0], [5.0, 5.0]]])
    loc = jnp.array([[[1, 1], [1, 1]]])
    out = np.asarray(scatter_connection(emb, loc, (H, W), "cover"))
    # cover: one of the writes wins (scatter, not add)
    assert out[0, 1, 1, 0] in (1.0, 5.0)
