"""Fault-tolerance layer: retry fabric, supervision, durable checkpoints,
coordinator leases, and the chaos acceptance run (kill the broker + corrupt
the newest checkpoint mid-run; the fleet must finish anyway — and the same
scenario without the resilience layer must demonstrably fail)."""
import os
import socket
import struct
import threading
import time

import numpy as np
import pytest

from distar_tpu.comm import Adapter, Coordinator, CoordinatorServer, coordinator_request
from distar_tpu.obs import (
    FlightRecorder,
    HealthEvaluator,
    HealthRule,
    MetricsRegistry,
    TimeSeriesStore,
    set_flight_recorder,
    set_registry,
)
from distar_tpu.resilience import (
    NO_RETRY,
    AlertRemediator,
    ChaosInjector,
    CircuitBreaker,
    CircuitOpenError,
    CommError,
    FatalError,
    RestartPolicy,
    RetryPolicy,
    Supervisor,
    retry_call,
    supervise_call,
)
from distar_tpu.utils.checkpoint import (
    CheckpointCorruptError,
    CheckpointManager,
    load_checkpoint,
    save_checkpoint,
    verify_checkpoint,
)


@pytest.fixture
def registry():
    reg = MetricsRegistry()
    prev = set_registry(reg)
    yield reg
    set_registry(prev)


@pytest.fixture
def recorder():
    rec = FlightRecorder()
    prev = set_flight_recorder(rec)
    yield rec
    set_flight_recorder(prev)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ===================================================================== policy
def test_retry_policy_backoff_sequence_and_success(registry, recorder):
    sleeps = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 4:
            raise ConnectionError("blip")
        return "ok"

    policy = RetryPolicy(max_attempts=5, backoff_base_s=0.1, backoff_multiplier=2.0,
                         jitter=0.0)
    out = retry_call(flaky, op="t", policy=policy, sleep=sleeps.append)
    assert out == "ok" and calls["n"] == 4
    assert sleeps == [0.1, 0.2, 0.4]  # jitter-free exponential
    snap = registry.snapshot()
    assert snap["distar_resilience_retries_total{op=t}"] == 3
    # every retry is visible in the flight-recorder event ring
    assert len(recorder.events(kind="retry")) == 3


def test_retry_gives_up_and_is_observable(registry, recorder):
    def dead():
        raise ConnectionError("down")

    policy = RetryPolicy(max_attempts=3, backoff_base_s=0.0, jitter=0.0)
    with pytest.raises(ConnectionError):
        retry_call(dead, op="t", policy=policy, sleep=lambda s: None)
    assert registry.snapshot()["distar_resilience_giveups_total{op=t}"] == 1
    assert recorder.events(kind="retry_giveup")


def test_retry_deadline_budget_cuts_attempts_short(registry):
    calls = {"n": 0}

    def dead():
        calls["n"] += 1
        raise ConnectionError("down")

    # 50 attempts allowed but only 0.1s of budget: real sleeps burn it fast
    policy = RetryPolicy(max_attempts=50, backoff_base_s=0.03, jitter=0.0,
                         deadline_s=0.1)
    t0 = time.monotonic()
    with pytest.raises(ConnectionError):
        retry_call(dead, op="t", policy=policy)
    assert time.monotonic() - t0 < 1.0
    assert calls["n"] < 50


def test_fatal_error_never_retried():
    calls = {"n": 0}

    def broken():
        calls["n"] += 1
        raise FatalError("logic bug")

    with pytest.raises(FatalError):
        retry_call(broken, op="t", policy=RetryPolicy(max_attempts=5), sleep=lambda s: None)
    assert calls["n"] == 1


def test_jitter_is_bounded_and_seeded():
    import random

    policy = RetryPolicy(backoff_base_s=1.0, jitter=0.5)
    vals = {policy.backoff_s(0, random.Random(i)) for i in range(32)}
    assert all(0.5 <= v <= 1.5 for v in vals)
    assert len(vals) > 1  # actually jittered
    assert policy.backoff_s(0, random.Random(7)) == policy.backoff_s(0, random.Random(7))


def test_circuit_breaker_open_half_open_close(registry, recorder):
    br = CircuitBreaker(op="peer", failure_threshold=3, reset_after_s=0.05)
    assert br.state == "closed"
    for _ in range(3):
        assert br.allow()
        br.record_failure()
    assert br.state == "open"
    assert not br.allow()  # fail-fast while open
    time.sleep(0.06)
    assert br.allow()  # one probe through: half-open
    assert br.state == "half_open"
    br.record_success()
    assert br.state == "closed"
    assert registry.snapshot()["distar_resilience_breaker_open_total{op=peer}"] == 1
    assert recorder.events(kind="breaker_open")


def test_retry_call_respects_open_breaker():
    br = CircuitBreaker(op="peer", failure_threshold=1, reset_after_s=60.0)
    br.record_failure()
    calls = {"n": 0}

    def fn():
        calls["n"] += 1

    with pytest.raises(CircuitOpenError):
        retry_call(fn, op="peer", policy=RetryPolicy(max_attempts=3), breaker=br)
    assert calls["n"] == 0  # open circuit never even dials


# ================================================================ typed comm
def test_coordinator_request_raises_typed_commerror():
    port = _free_port()  # nothing listening
    with pytest.raises(CommError) as ei:
        coordinator_request("127.0.0.1", port, "ask", {"token": "x"}, policy=NO_RETRY)
    # typed AND backward-compatible: legacy `except OSError` sites still work
    assert isinstance(ei.value, ConnectionError)
    assert ei.value.op == "coordinator:ask"


def test_league_request_raises_typed_commerror():
    from distar_tpu.league import league_request

    with pytest.raises(CommError) as ei:
        league_request("127.0.0.1", _free_port(), "show_players", {}, timeout=2.0)
    assert ei.value.op == "league:show_players"


def test_remote_league_retries_then_raises_commerror():
    from distar_tpu.league.remote import RemoteLeague

    remote = RemoteLeague("127.0.0.1", _free_port(),
                          policy=RetryPolicy(max_attempts=2, backoff_base_s=0.01,
                                             jitter=0.0))
    t0 = time.monotonic()
    with pytest.raises(CommError):
        remote.actor_ask_for_job()
    assert time.monotonic() - t0 < 5.0


# ============================================================ leases/heartbeat
def test_coordinator_lease_eviction_is_counted(registry):
    co = Coordinator(default_lease_s=0.05)
    co.register("t", "10.0.0.1", 7777)
    time.sleep(0.08)
    co._last_sweep = 0.0  # bypass the sweep rate limit for determinism
    assert co.ask("t") is None  # lease expired -> endpoint evicted wholesale
    assert registry.snapshot()["distar_coordinator_evictions_total"] == 1


def test_coordinator_heartbeat_keeps_lease_alive(registry):
    co = Coordinator(default_lease_s=0.1)
    co.register("t", "10.0.0.1", 7777)
    for _ in range(4):
        time.sleep(0.05)
        co._last_sweep = 0.0
        assert co.heartbeat("10.0.0.1", 7777) is True  # records still held
    co._last_sweep = 0.0
    assert co.ask("t") is not None
    # an endpoint the broker lost (restart) answers False: re-register signal
    assert co.heartbeat("10.9.9.9", 1) is False


def test_heartbeat_route_over_http(registry):
    srv = CoordinatorServer(Coordinator(default_lease_s=30.0))
    srv.start()
    try:
        adapter = Adapter(coordinator_addr=(srv.host, srv.port), lease_s=30.0,
                          request_policy=NO_RETRY)
        adapter._register("tok", 4242)
        assert adapter.heartbeat(4242) is True
        assert adapter.heartbeat(9999) is False
    finally:
        srv.stop()


# ==================================================================== shuttle
def test_py_fetch_deadline_applies_mid_read():
    """A peer that sends a partial payload then hangs must not park the
    fetch forever — timeout_ms is a whole-fetch deadline (satellite fix)."""
    from distar_tpu.comm.shuttle import _py_fetch

    listener = socket.socket()
    listener.bind(("127.0.0.1", 0))
    listener.listen(1)
    port = listener.getsockname()[1]
    release = threading.Event()

    def hang_server():
        conn, _ = listener.accept()
        conn.sendall(struct.pack(">Q", 100) + b"x" * 10)  # 10 of promised 100
        release.wait(5.0)
        conn.close()

    t = threading.Thread(target=hang_server, daemon=True)
    t.start()
    t0 = time.monotonic()
    with pytest.raises(OSError):
        _py_fetch("127.0.0.1", port, timeout_ms=300)
    assert time.monotonic() - t0 < 2.0
    release.set()
    listener.close()


def test_py_serve_hung_consumer_does_not_park_forever(registry):
    """A consumer that connects and never reads must not hold the serve
    window open past its timeout (accepted sockets don't inherit the
    listener timeout — the satellite's sendall-hang fix)."""
    from distar_tpu.comm.shuttle import _py_serve

    payload = b"z" * (4 << 20)  # larger than kernel buffers: sendall must block
    port = _py_serve(payload, accept_count=1, timeout_ms=300)
    dead_consumer = socket.create_connection(("127.0.0.1", port), timeout=5.0)
    try:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            snap = registry.snapshot()
            if snap.get("distar_shuttle_drops_total", 0) >= 1 and \
                    snap.get("distar_shuttle_active_serves", 1) == 0:
                break
            time.sleep(0.05)
        snap = registry.snapshot()
        assert snap.get("distar_shuttle_drops_total", 0) >= 1
        assert snap.get("distar_shuttle_active_serves") == 0
    finally:
        dead_consumer.close()


# ================================================================ checkpoints
def _state(v: float):
    return {"params": {"w": np.full((8, 8), v)}, "step": np.asarray(int(v))}


def test_truncated_checkpoint_detected(tmp_path, chaos):
    path = str(tmp_path / "c.ckpt")
    save_checkpoint(path, _state(3.0), metadata={"last_iter": 3})
    assert verify_checkpoint(path)
    chaos.truncate(path, keep_frac=0.4)
    assert not verify_checkpoint(path)
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(path)


def test_bitflipped_checkpoint_detected(tmp_path, chaos):
    path = str(tmp_path / "c.ckpt")
    save_checkpoint(path, _state(3.0))
    chaos.bitflip(path, flips=4)
    assert not verify_checkpoint(path)
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(path)


def test_manager_falls_back_to_previous_generation(tmp_path, chaos, registry, recorder):
    mgr = CheckpointManager(str(tmp_path))
    paths = []
    for i in (1, 2, 3):
        p = str(tmp_path / f"iteration_{i}.ckpt")
        save_checkpoint(p, _state(float(i)), metadata={"last_iter": i})
        mgr.record(p, step=i)
        paths.append(p)
    assert mgr.resolve_latest()["path"] == paths[2]
    chaos.truncate(paths[2])  # corrupt the NEWEST generation
    assert mgr.resolve_latest()["path"] == paths[1]
    out = mgr.load_latest()
    assert out["metadata"]["last_iter"] == 2
    assert registry.snapshot()["distar_resilience_ckpt_fallbacks_total"] >= 1
    assert recorder.events(kind="ckpt_fallback")


def test_manager_pointer_survives_process_boundaries(tmp_path):
    p = str(tmp_path / "a.ckpt")
    save_checkpoint(p, _state(1.0), metadata={"last_iter": 1})
    CheckpointManager(str(tmp_path)).record(p, step=1)
    # a fresh manager (new process after a crash) reads the same pointer
    again = CheckpointManager(str(tmp_path))
    assert again.resolve_latest()["step"] == 1
    assert again.load_latest()["metadata"]["last_iter"] == 1


def test_legacy_checkpoint_without_manifest_still_loads(tmp_path):
    path = str(tmp_path / "legacy.ckpt")
    save_checkpoint(path, _state(2.0), metadata={"last_iter": 2})
    os.unlink(path + ".manifest")  # converted/older checkpoints have none
    assert verify_checkpoint(path)
    assert load_checkpoint(path)["metadata"]["last_iter"] == 2


# ================================================================= supervisor
def test_supervisor_restarts_crashing_task(registry, recorder):
    runs = []
    done = threading.Event()

    def task(ctx):
        runs.append(1)
        if len(runs) < 3:
            raise RuntimeError("injected crash")
        done.set()
        while not ctx.should_exit:
            time.sleep(0.01)

    sup = Supervisor(policy=RestartPolicy(max_restarts=5, backoff_base_s=0.01,
                                          backoff_max_s=0.05))
    sup.add("worker", task)
    sup.start()
    assert done.wait(5.0)
    sup.stop()
    assert len(runs) == 3
    st = sup.status()["worker"]
    assert st["restarts"] == 2 and not st["gave_up"]
    assert registry.snapshot()["distar_resilience_restarts_total{task=worker}"] == 2
    assert len(recorder.events(kind="task_restart")) == 2


def test_supervisor_gives_up_when_budget_exhausted(registry):
    gave = []

    def always_crash(ctx):
        raise RuntimeError("permafail")

    sup = Supervisor(policy=RestartPolicy(max_restarts=2, window_s=60.0,
                                          backoff_base_s=0.01, backoff_max_s=0.02))
    sup.add("worker", always_crash, on_giveup=gave.append)
    sup.start()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and not sup.status()["worker"]["gave_up"]:
        time.sleep(0.02)
    st = sup.status()["worker"]
    assert st["gave_up"] and st["restarts"] == 2
    assert len(gave) == 1
    assert registry.snapshot()[
        "distar_resilience_task_giveups_total{task=worker}"] == 1
    sup.stop()


def test_supervise_call_resumes_foreground_role():
    attempts = []

    def run():
        attempts.append(1)
        if len(attempts) < 3:
            raise RuntimeError("boom")

    resumed = []
    supervise_call(run, op="learner",
                   policy=RestartPolicy(max_restarts=5, backoff_base_s=0.0),
                   on_restart=resumed.append, sleep=lambda s: None)
    assert len(attempts) == 3 and len(resumed) == 2


def test_alert_remediation_restarts_mapped_task(registry, recorder):
    """A firing `stalled` rule (PR 3 health layer) cooperatively bounces the
    mapped supervised task — detect -> remediate, no human."""
    entered = []
    cycle = threading.Event()

    def worker(ctx):
        entered.append(1)
        cycle.set()
        while not ctx.should_exit:
            time.sleep(0.01)

    sup = Supervisor(policy=RestartPolicy(max_restarts=5, backoff_base_s=0.01))
    sup.add("actor", worker)
    sup.start()
    assert cycle.wait(5.0)
    cycle.clear()

    store = TimeSeriesStore()
    # a counter that stopped moving: two in-window points, rate == 0
    store.record_snapshot({"distar_env_steps_total": 100.0}, ts=time.time() - 10,
                          source="actor:1")
    store.record_snapshot({"distar_env_steps_total": 100.0}, ts=time.time(),
                          source="actor:1")
    rule = HealthRule(name="actor_env_starvation", metric="distar_env_steps_total",
                      op="stalled", window_s=60.0, for_count=2)
    ev = HealthEvaluator(store, [rule], registry=registry)
    AlertRemediator(sup, {"actor_env_starvation": "actor"}).attach(ev)
    events = ev.evaluate_once() + ev.evaluate_once()
    assert any(e["state"] == "firing" for e in events)
    assert cycle.wait(5.0)  # the task re-entered: remediation restarted it
    sup.stop()
    assert len(entered) == 2
    assert registry.snapshot()[
        "distar_resilience_remediations_total{rule=actor_env_starvation}"] == 1
    assert recorder.events(kind="remediation")


# ==================================================================== serve
def test_serve_client_reconnects_through_gateway_restart():
    from distar_tpu.serve.tcp_frontend import ServeClient, ServeTCPServer

    srv = ServeTCPServer(gateway=None)  # ping never touches the gateway
    srv.start()
    host, port = srv.host, srv.port
    client = ServeClient(host, port, timeout_s=5.0,
                         retry_policy=RetryPolicy(max_attempts=5,
                                                  backoff_base_s=0.05,
                                                  backoff_max_s=0.2))
    try:
        assert client.ping()
        srv.stop()  # gateway dies...
        srv2 = ServeTCPServer(gateway=None, host=host, port=port)
        srv2.start()  # ...and comes back on the same address
        try:
            assert client.ping()  # transparent reconnect under the policy
        finally:
            srv2.stop()
    finally:
        client.close()


# ===================================================================== league
def test_league_autosave_journal_and_resume(tmp_path):
    from distar_tpu.league import League

    league = League({})
    path = str(tmp_path / "resume.pkl")
    league.start_autosave(path, interval_s=0.05)
    try:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not os.path.exists(path):
            time.sleep(0.02)
        assert os.path.exists(path)
    finally:
        league.stop_autosave()
    fresh = League({})
    fresh.load_resume(path)
    assert set(fresh.active_players) == set(league.active_players)
    assert set(fresh.historical_players) == set(league.historical_players)


# ===================================================================== lints
def _load_tool(name):
    import importlib.util

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(root, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_socket_lint_tree_is_clean():
    lint = _load_tool("lint_sockets")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    offences = lint.find_offences(os.path.join(root, "distar_tpu"))
    assert offences == [], "\n".join(f"{p}:{l}: {m}" for p, l, m in offences)


def test_socket_lint_catches_offences(tmp_path):
    lint = _load_tool("lint_sockets")
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import socket, urllib.request\n"
        "try:\n"
        "    urllib.request.urlopen('http://x')\n"
        "except:\n"
        "    pass\n"
        "socket.create_connection(('h', 1))\n"
        "socket.create_connection(('h', 1), timeout=3)  # ok\n"
    )
    offences = lint.find_offences(str(tmp_path))
    msgs = [m for (_p, _l, m) in offences]
    assert len(offences) == 3
    assert any("bare 'except:'" in m for m in msgs)
    assert any("urlopen" in m for m in msgs)
    assert any("create_connection" in m for m in msgs)


# ========================================================== chaos acceptance
class _ToyLearner:
    """Minimal learner with the real durability contract: pulls batches off
    the real adapter/coordinator data plane, checkpoints through the real
    manifest+latest-pointer machinery. (The full jitted RLLearner rides the
    identical save/resume path — BaseLearner.save/resume_latest — but would
    make this chaos loop minutes-slow.)"""

    def __init__(self, adapter, ckpt_dir: str, target_steps: int, save_every: int = 5):
        self.adapter = adapter
        self.ckpt_dir = ckpt_dir
        self.target = target_steps
        self.save_every = save_every
        self.mgr = CheckpointManager(ckpt_dir)
        self.step = 0
        self.resumed_from = None
        self.hooks = {}  # step -> callable, fired once when the step completes

    def save(self):
        path = os.path.join(self.ckpt_dir, f"step_{self.step}.ckpt")
        save_checkpoint(path, {"w": np.full(4, float(self.step))},
                        metadata={"step": self.step})
        self.mgr.record(path, step=self.step)

    def resume(self):
        out = self.mgr.load_latest()
        if out is not None:
            self.step = int(out["metadata"]["step"])
            self.resumed_from = out["path"]
        return out

    def run(self):
        while self.step < self.target:
            self.adapter.pull("traj", timeout=30.0)
            self.step += 1
            if self.step % self.save_every == 0:
                self.save()
            hook = self.hooks.pop(self.step, None)
            if hook is not None:
                hook()


def _start_producer(supervisor, port, policy):
    def producer(ctx):
        adapter = Adapter(coordinator_addr=("127.0.0.1", port),
                          request_policy=policy)
        while not ctx.should_exit:
            adapter.push("traj", {"x": np.ones(8, np.float32)},
                         accept_count=1, timeout_ms=20_000)
            time.sleep(0.01)

    supervisor.add("producer", producer)


def test_chaos_acceptance_fleet_self_heals(tmp_path, chaos, registry, recorder):
    """THE acceptance scenario: mid-run the broker is killed once (restarted
    with EMPTY state) and the newest checkpoint is truncated right before a
    learner crash-resume. The fleet must reach the target step count with
    zero manual intervention."""
    port = _free_port()
    server_box = [CoordinatorServer(port=port)]
    server_box[0].start()
    TARGET, CRASH_AT, BROKER_KILL_AT = 40, 12, 8

    sup = Supervisor(policy=RestartPolicy(max_restarts=10, backoff_base_s=0.05,
                                          backoff_max_s=0.3))
    _start_producer(sup, port,
                    RetryPolicy(max_attempts=8, backoff_base_s=0.1,
                                backoff_max_s=0.5, deadline_s=20.0))
    sup.start()

    learner = _ToyLearner(
        Adapter(coordinator_addr=("127.0.0.1", port),
                request_policy=RetryPolicy(max_attempts=8, backoff_base_s=0.1,
                                           backoff_max_s=0.5, deadline_s=20.0)),
        str(tmp_path), target_steps=TARGET)

    def kill_and_restart_broker():
        chaos.kill_role(server_box[0])  # all registrations/leases are LOST
        time.sleep(0.3)
        server_box[0] = CoordinatorServer(port=port)  # fresh empty broker
        server_box[0].start()

    def crash_once():
        raise RuntimeError("chaos: learner killed")

    learner.hooks[BROKER_KILL_AT] = kill_and_restart_broker
    learner.hooks[CRASH_AT] = crash_once

    def on_restart(error):
        # corrupt the newest checkpoint BEFORE resume: the fleet must fall
        # back to the previous generation on its own
        gens = learner.mgr.generations()
        if learner.resumed_from is None and gens:
            chaos.truncate(gens[0]["path"])
        learner.resume()

    try:
        supervise_call(learner.run, op="toy_learner",
                       policy=RestartPolicy(max_restarts=5, backoff_base_s=0.05),
                       on_restart=on_restart)
    finally:
        sup.stop()
        server_box[0].stop()

    assert learner.step >= TARGET  # zero manual intervention
    # resumed from the PREVIOUS generation (newest was truncated):
    # crash at 12 with saves at 5/10 -> 10 corrupted -> resume from 5
    assert learner.resumed_from is not None
    assert learner.resumed_from.endswith("step_5.ckpt")
    snap = registry.snapshot()
    assert snap.get("distar_resilience_ckpt_fallbacks_total", 0) >= 1
    # the broker outage was survived by retries (observable), and every
    # retry/restart landed in the flight-recorder ring
    assert any(k.startswith("distar_resilience_retries_total") for k in snap)
    assert recorder.events(kind="retry")
    assert recorder.events(kind="task_restart")
    assert not sup.status()["producer"]["gave_up"]


def test_chaos_without_resilience_fails(tmp_path, chaos):
    """The counter-demonstration: the identical broker-kill scenario with the
    resilience layer OFF (single-attempt RPCs, no supervision, raw loads)
    loses the run — the producer dies on the outage and a truncated
    checkpoint has no fallback."""
    port = _free_port()
    server = CoordinatorServer(port=port)
    server.start()

    producer_error = []

    def fragile_producer():
        adapter = Adapter(coordinator_addr=("127.0.0.1", port),
                          request_policy=NO_RETRY)
        try:
            while True:
                adapter.push("traj", {"x": np.ones(4)}, accept_count=1,
                             timeout_ms=5_000)
                time.sleep(0.01)
        except CommError as e:  # one-shot RPC: first outage is fatal
            producer_error.append(e)

    t = threading.Thread(target=fragile_producer, daemon=True)
    t.start()
    time.sleep(0.3)  # let it stream
    chaos.kill_role(server)  # broker dies; nobody retries, nobody restarts
    t.join(timeout=10.0)
    assert producer_error, "unsupervised producer should die on the outage"
    assert isinstance(producer_error[0], CommError)

    # and the checkpoint half: a truncated newest checkpoint without the
    # manager's generation fallback is an unrecoverable load
    path = str(tmp_path / "only.ckpt")
    save_checkpoint(path, {"w": np.ones(4)}, metadata={"step": 10})
    chaos.truncate(path)
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(path)
