"""Pallas kernel correctness (interpret mode on CPU) vs jnp oracles."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distar_tpu.ops.pallas_kernels import (
    masked_attention,
    masked_attention_reference,
    scatter_add_connection,
)
from distar_tpu.ops import scatter_connection, sequence_mask


def test_masked_attention_matches_reference(rng):
    B, H, N, Dh = 2, 2, 64, 32
    q = jnp.asarray(rng.standard_normal((B, H, N, Dh)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((B, H, N, Dh)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((B, H, N, Dh)).astype(np.float32))
    mask = sequence_mask(jnp.array([10, 64]), N)
    got = masked_attention(q, k, v, mask, interpret=True)
    want = masked_attention_reference(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_masked_attention_padding_invariance(rng):
    """Garbage in masked key slots must not change valid outputs."""
    B, H, N, Dh = 1, 2, 32, 16
    q = jnp.asarray(rng.standard_normal((B, H, N, Dh)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((B, H, N, Dh)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((B, H, N, Dh)).astype(np.float32))
    mask = sequence_mask(jnp.array([7]), N)
    out1 = masked_attention(q, k, v, mask, interpret=True)
    k2 = k.at[:, :, 7:].add(100.0)
    v2 = v.at[:, :, 7:].add(-50.0)
    out2 = masked_attention(q, k2, v2, mask, interpret=True)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), atol=1e-4)


def test_masked_attention_bf16_out_dtype(rng):
    """bf16 inputs produce a bf16 output (matching the XLA path's einsum
    dtype under mixed precision) with f32 accumulation inside."""
    B, H, N, Dh = 1, 2, 16, 8
    q, k, v = (
        jnp.asarray(rng.standard_normal((B, H, N, Dh)), jnp.bfloat16)
        for _ in range(3)
    )
    mask = jnp.ones((B, N), bool)
    out = masked_attention(q, k, v, mask, interpret=True)
    assert out.dtype == jnp.bfloat16
    want = masked_attention_reference(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32), mask
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(want), rtol=0.05, atol=0.05
    )


def test_scatter_add_matches_jnp(rng):
    B, N, D, H, W = 2, 16, 8, 8, 8
    emb = jnp.asarray(rng.standard_normal((B, N, D)).astype(np.float32))
    x = jnp.asarray(rng.integers(0, W, (B, N)))
    y = jnp.asarray(rng.integers(0, H, (B, N)))
    flat = (y * W + x).astype(jnp.int32)
    got = scatter_add_connection(emb, flat, H * W, interpret=True)
    want = scatter_connection(emb, jnp.stack([x, y], -1), (H, W), "add").reshape(B, H * W, D)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_scatter_add_collisions(rng):
    """Multiple entities on one cell must sum."""
    B, N, D = 1, 4, 2
    emb = jnp.ones((B, N, D))
    flat = jnp.zeros((B, N), jnp.int32)  # all collide on cell 0
    out = scatter_add_connection(emb, flat, 9, interpret=True)
    np.testing.assert_allclose(np.asarray(out[0, 0]), [4.0, 4.0])
    assert float(jnp.abs(out[0, 1:]).sum()) == 0.0


def test_scatter_onehot_matches_loop_variant(rng):
    """MXU one-hot formulation == loop formulation (incl. collisions), fwd
    and grad, also at an hw that does NOT divide the cell chunk."""
    from distar_tpu.ops.pallas_kernels import scatter_add_onehot

    B, N, D, H, W = 2, 16, 8, 9, 7  # hw=63: exercises the padded last chunk
    emb = jnp.asarray(rng.standard_normal((B, N, D)).astype(np.float32))
    flat = jnp.asarray(rng.integers(0, H * W, (B, N))).astype(jnp.int32)
    flat = flat.at[0, :4].set(0)  # forced collisions
    want = scatter_add_connection(emb, flat, H * W, interpret=True)
    got = scatter_add_onehot(emb, flat, H * W, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)

    g1 = jax.grad(lambda e: jnp.sum(scatter_add_onehot(e, flat, H * W, True) ** 2))(emb)
    g2 = jax.grad(lambda e: jnp.sum(scatter_add_connection(e, flat, H * W, True) ** 2))(emb)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-5, atol=1e-5)


def test_scatter_oob_clipped_identically_in_both_wrappers(rng):
    """Out-of-range indices are clipped to [0, hw-1] in BOTH public wrappers:
    switching impl strings can never silently change forward or gradient
    semantics (the raw one-hot kernel would drop what the loop kernel
    clamps — the wrappers unify on clamp)."""
    from distar_tpu.ops.pallas_kernels import scatter_add_onehot

    B, N, D, hw = 1, 4, 2, 8
    emb = jnp.asarray(rng.standard_normal((B, N, D)).astype(np.float32))
    flat = jnp.asarray([[0, 3, -2, hw + 5]], jnp.int32)  # last two OOB
    out_loop = scatter_add_connection(emb, flat, hw, interpret=True)
    out_onehot = scatter_add_onehot(emb, flat, hw, interpret=True)
    np.testing.assert_allclose(np.asarray(out_loop), np.asarray(out_onehot),
                               rtol=1e-5, atol=1e-5)
    # clamp semantics: the OOB entities landed on cells 0 and hw-1
    np.testing.assert_allclose(np.asarray(out_loop[0, 0]),
                               np.asarray(emb[0, 0] + emb[0, 2]), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out_loop[0, hw - 1]),
                               np.asarray(emb[0, 3]), rtol=1e-5)
    # gradients agree too, and flow THROUGH the clamped cells (not zeroed)
    g1 = jax.grad(lambda e: jnp.sum(scatter_add_onehot(e, flat, hw, True) ** 2))(emb)
    g2 = jax.grad(lambda e: jnp.sum(scatter_add_connection(e, flat, hw, True) ** 2))(emb)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-5, atol=1e-5)
    assert float(jnp.abs(g1[0, 2:]).sum()) > 0.0  # clamped, so grads flow


def test_scatter_impl_switch_onehot(rng):
    """scatter_connection(impl='pallas_onehot') routes and matches XLA."""
    B, N, D, H, W = 2, 12, 4, 8, 8
    emb = jnp.asarray(rng.standard_normal((B, N, D)).astype(np.float32))
    x = jnp.asarray(rng.integers(0, W, (B, N)))
    y = jnp.asarray(rng.integers(0, H, (B, N)))
    want = scatter_connection(emb, jnp.stack([x, y], -1), (H, W), "add")
    got = scatter_connection(emb, jnp.stack([x, y], -1), (H, W), "add",
                             impl="pallas_onehot")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_masked_attention_vjp_matches_reference(rng):
    """Trainable kernel: pallas forward, XLA-recompute backward — gradients
    must match the dense reference's exactly."""
    B, H, N, Dh = 2, 2, 32, 16
    q = jnp.asarray(rng.standard_normal((B, H, N, Dh)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((B, H, N, Dh)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((B, H, N, Dh)).astype(np.float32))
    mask = sequence_mask(jnp.array([9, 32]), N)
    g1 = jax.grad(
        lambda q, k, v: jnp.sum(masked_attention(q, k, v, mask, True) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    g2 = jax.grad(
        lambda q, k, v: jnp.sum(masked_attention_reference(q, k, v, mask) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


def test_scatter_add_vjp_is_gather(rng):
    B, N, D, HW = 2, 24, 4, 40
    emb = jnp.asarray(rng.standard_normal((B, N, D)).astype(np.float32))
    idx = jnp.asarray(rng.integers(0, HW, (B, N)), jnp.int32)

    def xla_ref(e):
        bias = jnp.arange(B, dtype=jnp.int32)[:, None] * HW
        buf = jnp.zeros((B * HW, D))
        return buf.at[(idx + bias).reshape(-1)].add(e.reshape(-1, D)).reshape(B, HW, D)

    ga = jax.grad(lambda e: jnp.sum(scatter_add_connection(e, idx, HW, True) ** 2))(emb)
    gb = jax.grad(lambda e: jnp.sum(xla_ref(e) ** 2))(emb)
    np.testing.assert_allclose(np.asarray(ga), np.asarray(gb), rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_small_model_trains_with_pallas_ops():
    """Full small-model SL train step with BOTH pallas hot-ops enabled
    (attention_impl='pallas', scatter impl='pallas', interpret on CPU):
    the A/B the bench runs on silicon must be a real training path.

    Runs in a SUBPROCESS: pallas interpret mode at train-step scale leaves
    native state behind that can segfault unrelated later jit compiles in
    the same process (reproduced at suite scale), so its lifetime is scoped
    to a child interpreter."""
    import os
    import subprocess
    import sys

    code = """
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from distar_tpu.learner import SLLearner

model = {
    "encoder": {
        "entity": {"layer_num": 1, "hidden_dim": 32, "output_dim": 16,
                   "head_dim": 8, "attention_impl": "pallas"},
        "spatial": {"down_channels": [4, 4, 8], "project_dim": 4,
                    "resblock_num": 1, "fc_dim": 16},
        "scatter": {"output_dim": 4, "impl": "pallas"},
        "core_lstm": {"hidden_size": 32, "num_layers": 1},
    },
    "policy": {
        "action_type_head": {"res_dim": 16, "res_num": 1, "gate_dim": 32},
        "delay_head": {"decode_dim": 16},
        "queued_head": {"decode_dim": 16},
        "selected_units_head": {"func_dim": 16},
        "target_unit_head": {"func_dim": 16},
        "location_head": {"res_dim": 8, "res_num": 1,
                          "upsample_dims": [4, 4, 1], "map_skip_dim": 8},
    },
    "value": {"res_dim": 8, "res_num": 1},
}
learner = SLLearner(
    {
        "common": {"experiment_name": "pallas_sl_smoke"},
        "learner": {"batch_size": 2, "unroll_len": 2,
                    "save_freq": 10 ** 9, "log_freq": 10 ** 9},
        "model": model,
    }
)
learner.run(max_iterations=2)
assert learner.last_iter.val == 2
assert np.isfinite(learner.variable_record.get("total_loss").avg)
print("PALLAS-TRAIN-OK")
"""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, "-c", code], env=env, cwd=repo,
        capture_output=True, text=True, timeout=1800,
    )
    assert out.returncode == 0, f"child failed:\n{out.stdout[-2000:]}\n{out.stderr[-2000:]}"
    assert "PALLAS-TRAIN-OK" in out.stdout
