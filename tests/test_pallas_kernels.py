"""Pallas kernel correctness (interpret mode on CPU) vs jnp oracles."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distar_tpu.ops.pallas_kernels import (
    masked_attention,
    masked_attention_reference,
    scatter_add_connection,
)
from distar_tpu.ops import scatter_connection, sequence_mask


def test_masked_attention_matches_reference(rng):
    B, H, N, Dh = 2, 2, 64, 32
    q = jnp.asarray(rng.standard_normal((B, H, N, Dh)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((B, H, N, Dh)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((B, H, N, Dh)).astype(np.float32))
    mask = sequence_mask(jnp.array([10, 64]), N)
    got = masked_attention(q, k, v, mask, interpret=True)
    want = masked_attention_reference(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_masked_attention_padding_invariance(rng):
    """Garbage in masked key slots must not change valid outputs."""
    B, H, N, Dh = 1, 2, 32, 16
    q = jnp.asarray(rng.standard_normal((B, H, N, Dh)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((B, H, N, Dh)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((B, H, N, Dh)).astype(np.float32))
    mask = sequence_mask(jnp.array([7]), N)
    out1 = masked_attention(q, k, v, mask, interpret=True)
    k2 = k.at[:, :, 7:].add(100.0)
    v2 = v.at[:, :, 7:].add(-50.0)
    out2 = masked_attention(q, k2, v2, mask, interpret=True)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), atol=1e-4)


def test_scatter_add_matches_jnp(rng):
    B, N, D, H, W = 2, 16, 8, 8, 8
    emb = jnp.asarray(rng.standard_normal((B, N, D)).astype(np.float32))
    x = jnp.asarray(rng.integers(0, W, (B, N)))
    y = jnp.asarray(rng.integers(0, H, (B, N)))
    flat = (y * W + x).astype(jnp.int32)
    got = scatter_add_connection(emb, flat, H * W, interpret=True)
    want = scatter_connection(emb, jnp.stack([x, y], -1), (H, W), "add").reshape(B, H * W, D)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_scatter_add_collisions(rng):
    """Multiple entities on one cell must sum."""
    B, N, D = 1, 4, 2
    emb = jnp.ones((B, N, D))
    flat = jnp.zeros((B, N), jnp.int32)  # all collide on cell 0
    out = scatter_add_connection(emb, flat, 9, interpret=True)
    np.testing.assert_allclose(np.asarray(out[0, 0]), [4.0, 4.0])
    assert float(jnp.abs(out[0, 1:]).sum()) == 0.0
