"""Stat tables, race masks, TrueSkill, and ladder job tests."""
import numpy as np
import pytest

from distar_tpu.league import League
from distar_tpu.league.trueskill import TrueSkill
from distar_tpu.lib.stat import ACTION_RACE_MASK, CUM_DICT, Stat, UNIT_DICT


def test_action_race_mask_shapes():
    for race in ("zerg", "terran", "protoss"):
        assert race in ACTION_RACE_MASK
        assert ACTION_RACE_MASK[race].shape == (327,)
    # per-race legal action counts from the reference data (NB the reference
    # masks even no_op=False in play mode — preserved verbatim)
    assert ACTION_RACE_MASK["zerg"].sum() == 112
    assert ACTION_RACE_MASK["terran"].sum() == 137
    assert ACTION_RACE_MASK["protoss"].sum() == 128


def test_cum_dict_matches_cumulative_slots():
    from distar_tpu.lib.actions import NUM_CUMULATIVE_STAT_ACTIONS

    assert len(CUM_DICT) == NUM_CUMULATIVE_STAT_ACTIONS


def test_stat_tracks_units_and_success():
    from distar_tpu.lib.actions import ACTIONS, FUNC_ID_TO_ACTION_TYPE

    stat = Stat("zerg")
    drone_func = 503  # Train_Drone
    assert drone_func in UNIT_DICT["zerg"]
    at = FUNC_ID_TO_ACTION_TYPE[drone_func]
    obs = {
        "entity_info": {"alliance": np.ones(64, np.int64)},
        "entity_num": np.asarray(64),
    }
    for _ in range(3):
        stat.update(at, 1, obs, game_step=100)
    data = stat.get_stat_data()
    assert data["units/Drone"] == 1.0  # 3/3 == max
    name = ACTIONS[at]["name"]
    assert data[f"rate/{name}/count"] == 3


def test_trueskill_winner_rises():
    ts = TrueSkill()
    for _ in range(20):
        ts.update("A", "B")
    assert ts.exposed("A") > ts.exposed("B")
    lb = ts.leaderboard()
    assert list(lb)[0] == "A"
    # sigma shrinks with games
    assert ts.ratings["A"][1] < 25.0 / 3.0


def test_trueskill_draws_converge_means():
    ts = TrueSkill()
    for _ in range(30):
        ts.update("A", "B", draw=True)
    mu_a, mu_b = ts.ratings["A"][0], ts.ratings["B"][0]
    assert abs(mu_a - mu_b) < 1.0


def test_ladder_job_prefers_underplayed_pairs():
    cfg = {
        "league": {
            "ladder_min_games": 5,
            "active_players": {
                "player_id": ["MP0"],
                "checkpoint_path": ["a.ckpt"],
                "pipeline": ["default"],
                "frac_id": [1],
                "z_path": ["z.json"],
                "z_prob": [0.0],
                "teacher_id": ["T"],
                "teacher_path": ["t.ckpt"],
                "one_phase_step": [10 ** 9],
                "chosen_weight": [1.0],
            },
            "historical_players": {
                "player_id": ["HP0", "HP1"],
                "checkpoint_path": ["h0.ckpt", "h1.ckpt"],
                "pipeline": ["default"] * 2,
                "frac_id": [1] * 2,
                "z_path": ["z.json"] * 2,
                "z_prob": [0.0] * 2,
            },
        }
    }
    lg = League(cfg)
    job = lg.actor_ask_for_job({"job_type": "eval"})
    assert job["branch"] == "ladder"
    assert job["send_data_players"] == []
    assert len(job["player_ids"]) == 2
    # trueskill ingests eval results
    lg.actor_send_result(
        {
            "game_steps": 10, "game_iters": 1, "game_duration": 1.0,
            "0": {"player_id": "HP0", "opponent_id": "HP1", "winloss": 1},
            "1": {"player_id": "HP1", "opponent_id": "HP0", "winloss": -1},
        }
    )
    assert lg.trueskill.game_count == 1


def test_league_race_meters_from_results():
    """Active players accumulate per-race dist/cum/unit meters from results."""
    cfg = {
        "league": {
            "active_players": {
                "player_id": ["MP0"], "checkpoint_path": ["a.ckpt"],
                "pipeline": ["default"], "frac_id": [1], "z_path": ["z.json"],
                "z_prob": [0.0], "teacher_id": ["T"], "teacher_path": ["t.ckpt"],
                "one_phase_step": [10 ** 9], "chosen_weight": [1.0],
            },
            "historical_players": {
                "player_id": ["HP0"], "checkpoint_path": ["h.ckpt"],
                "pipeline": ["default"], "frac_id": [1], "z_path": ["z.json"],
                "z_prob": [0.0],
            },
        }
    }
    lg = League(cfg)
    cum = [0] * 167
    cum[5] = 1
    lg.actor_send_result(
        {
            "game_steps": 100, "game_iters": 1, "game_duration": 5.0,
            "0": {"player_id": "MP0", "opponent_id": "HP0", "winloss": 1,
                   "race": "zerg", "bo_distance": 3.0, "cum_distance": 7.0,
                   "bo_reward_total": -0.2, "cum_reward_total": 0.1,
                   "battle_reward_total": 0.4, "cumulative_stat": cum,
                   "unit_num": {"Drone": 12}},
            "1": {"player_id": "HP0", "opponent_id": "MP0", "winloss": -1},
        }
    )
    mp0 = lg.active_players["MP0"]
    assert mp0.dist_stat.stat_info_dict["zerg"]["bo_distance"] == 3.0
    assert mp0.unit_num_stat.stat_info_dict["zerg"]["unit_num/Drone"] == 12
    from distar_tpu.lib.stat import CUM_DICT

    assert str(CUM_DICT[5]) in mp0.cum_stat.stat_info_dict["zerg"]
    assert "zerg" in mp0.dist_stat.get_text()
