"""Host-keyed persistent-compile-cache paths (utils/compile_cache.py):
the module that stops migrated containers from loading foreign-machine
XLA AOT code (the round-4 segfault root cause)."""
import jax

from distar_tpu.utils import compile_cache as cc


def test_cache_dir_is_host_keyed_and_stable():
    a = cc.cache_dir("/tmp/base")
    b = cc.cache_dir("/tmp/base")
    assert a == b, "key must be deterministic within one host"
    assert a.startswith("/tmp/base-") and len(a.split("-")[-1]) == 8
    assert cc.cache_dir("/tmp/other").split("-")[-1] == a.split("-")[-1]


def test_host_key_never_empty():
    key = cc._host_cpu_key()
    assert isinstance(key, str) and len(key) == 8
    import hashlib

    # the empty-string hash would give distinct hosts the same key
    assert key != hashlib.sha1(b"").hexdigest()[:8]


def test_configure_sets_jax_config(monkeypatch):
    prev = jax.config.jax_compilation_cache_dir
    try:
        cc.configure(jax, "/tmp/cc_test_base")
        assert jax.config.jax_compilation_cache_dir == cc.cache_dir("/tmp/cc_test_base")
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def test_configure_degrades_loudly_not_silently(caplog):
    class BrokenJax:
        class config:
            @staticmethod
            def update(*a, **k):
                raise RuntimeError("no such flag")

    import logging

    with caplog.at_level(logging.WARNING):
        cc.configure(BrokenJax, "/tmp/x")  # must not raise
    assert any("compile cache" in r.message for r in caplog.records)
