"""Version routing + header parsing against the reference's 16 REAL replays.

The reference validates its decode path on recorded games
(distar/pysc2/tests/replay_obs_test.py); without a game binary in this image
we validate what is game-free: the MPQ header parse and the
build->version routing the decoder uses to pick a binary
(distar/agent/default/replay_decoder.py:37-41, :366-377). A full two-pass
decode of one real replay runs when an SC2 install is present (SC2PATH),
and is skip-marked otherwise.
"""
import glob
import os

import pytest

from distar_tpu.envs.sc2.replay_header import (
    CorruptReplayError,
    parse_replay_header,
)
from distar_tpu.envs.sc2.run_configs import BUILD2VERSION, VERSIONS, version_for_build

REPLAY_DIR = "/root/reference/data/replays"
REPLAYS = sorted(glob.glob(os.path.join(REPLAY_DIR, "*.SC2Replay")))

pytestmark = pytest.mark.skipif(
    not REPLAYS, reason="reference replay bundle not present"
)

# filename-embedded version -> expected routed version. Identity everywhere
# except 5.0.1: the reference pins build 81009 -> "5.0.0"
# (replay_decoder.py:37-41), because 5.0.0 and 5.0.1 share data compatibility.
EXPECTED_ROUTE_OVERRIDES = {"5.0.1": "5.0.0"}


def _filename_version(path):
    # "replay_4.10.0.SC2Replay" -> "4.10.0"
    return os.path.basename(path)[len("replay_"):-len(".SC2Replay")]


def test_all_16_headers_parse():
    assert len(REPLAYS) == 16
    for path in REPLAYS:
        h = parse_replay_header(path)
        assert h["signature"].startswith("StarCraft II replay")
        assert h["base_build"] > 70000
        assert h["elapsed_game_loops"] > 0
        assert h["duration_seconds"] > 60


def test_base_build_matches_filename_version():
    """The header's base_build must be the build the filename's version
    names in the public VERSIONS table (the replays are named by the game
    version that recorded them)."""
    for path in REPLAYS:
        h = parse_replay_header(path)
        fname_ver = _filename_version(path)
        assert fname_ver in VERSIONS, f"{fname_ver} missing from VERSIONS"
        assert h["base_build"] == VERSIONS[fname_ver].build_version, (
            f"{os.path.basename(path)}: header base_build {h['base_build']} "
            f"!= VERSIONS[{fname_ver}].build_version "
            f"{VERSIONS[fname_ver].build_version}"
        )


def test_version_routing_on_real_builds():
    """version_for_build must route every real replay's base_build to a
    launchable version — the filename's own version, modulo the reference's
    explicit compatibility pins."""
    for path in REPLAYS:
        h = parse_replay_header(path)
        fname_ver = _filename_version(path)
        expected = EXPECTED_ROUTE_OVERRIDES.get(fname_ver, fname_ver)
        routed = version_for_build(h["base_build"])
        assert routed.game_version == expected, (
            f"{os.path.basename(path)}: build {h['base_build']} routed to "
            f"{routed.game_version}, expected {expected}"
        )
        # the routed version must be fully launchable: a known build dir +
        # data version
        assert routed.build_version in BUILD2VERSION or routed.game_version in VERSIONS
        assert len(routed.data_version) == 32


def test_reference_pins_present():
    """The decoder's three explicit pins (reference replay_decoder.py:37-41)."""
    assert BUILD2VERSION[80188] == "4.12.1"
    assert BUILD2VERSION[81009] == "5.0.0"
    assert BUILD2VERSION[81433] == "5.0.3"


def test_corrupt_input_raises():
    with pytest.raises(CorruptReplayError):
        parse_replay_header(b"not a replay at all" + b"\x00" * 64)
    with pytest.raises(CorruptReplayError):
        # valid magic, truncated/garbage payload
        parse_replay_header(b"MPQ\x1b" + (8).to_bytes(4, "little") * 3 + b"\xff" * 8)


@pytest.mark.slow
@pytest.mark.skipif(
    not os.path.isdir(os.path.expanduser(os.environ.get("SC2PATH", "~/StarCraftII"))),
    reason="no SC2 install (set SC2PATH) — two-pass decode needs the game binary",
)
def test_two_pass_decode_one_real_replay():
    """Full two-pass decode of one bundled replay through a real SC2 client
    (the reference's replay_obs_test analogue). Runs only with an install."""
    from distar_tpu.envs.replay_decoder import ReplayDecoder

    decoder = ReplayDecoder(cfg={"minimum_action_length": 1})
    try:
        steps = decoder.run(REPLAYS[0], 0) or decoder.run(REPLAYS[0], 1)
        assert steps, "decode produced no steps for either player"
        first = steps[0]
        for key in ("spatial_info", "entity_info", "scalar_info", "action_info"):
            assert key in first
    finally:
        decoder.close()
