"""Replay decoder tests: the two-pass decode runs against the fake SC2
server through the production client stack (websocket + protos + controller)
and emits ReplayDataset-contract trajectories that feed the SL dataloader.
"""
import numpy as np
import pytest

from distar_tpu.envs.features import extract_z
from distar_tpu.envs.replay_decoder import FilterActions, ReplayDecoder
from distar_tpu.envs.sc2.fake_sc2 import FakeGameCore, FakeSC2Server
from distar_tpu.envs.sc2.remote_controller import RemoteController
from distar_tpu.learner.sl_dataloader import ReplayDataset, SLDataloader
from distar_tpu.lib import actions as ACT
from distar_tpu.lib import features as F


def gab(name: str) -> int:
    return next(a["general_ability_id"] for a in ACT.ACTIONS if a["name"] == name)


def action_index(name: str) -> int:
    return next(i for i, a in enumerate(ACT.ACTIONS) if a["name"] == name)


@pytest.fixture
def server():
    s = FakeSC2Server(game=FakeGameCore(end_at=100_000))
    yield s
    s.stop()


def make_replay(n_actions: int = 12, loops_between: int = 30):
    """Scripted replay: alternating build-pt / train-quick / attack-unit."""
    actions = []
    loop = 10
    build = gab("Build_Hatchery_pt")
    train = gab("Train_Drone_quick")
    attack = gab("Attack_unit")
    for i in range(n_actions):
        kind = i % 3
        if kind == 0:
            actions.append((loop, build, [10000 + i % 8], (20.0 + i, 30.0)))
        elif kind == 1:
            actions.append((loop, train, [10000 + i % 8], None))
        else:
            actions.append((loop, attack, [10000 + i % 8], 20001))
        loop += loops_between
    return {
        "base_build": 75689,
        "game_version": "4.10.0",
        "data_version": "FAKE",
        "map_name": "KairosJunction",
        "game_duration_loops": loop + 50,
        "players": [
            {"player_id": 1, "race": 2, "mmr": 4800, "apm": 160, "result": 1},
            {"player_id": 2, "race": 2, "mmr": 4600, "apm": 140, "result": 2},
        ],
        "actions": actions,
    }


def test_two_pass_decode_end_to_end(server, tmp_path):
    server.game.replay_library["r.SC2Replay"] = make_replay()

    provider_calls = []

    def provider(version):
        provider_calls.append(version)
        return RemoteController("127.0.0.1", server.port, timeout_seconds=5)

    dec = ReplayDecoder(
        cfg={"minimum_action_length": 2, "parse_race": "Z"},
        controller_provider=provider,
    )
    traj = dec.run("r.SC2Replay", player_index=0)
    assert traj is not None and len(traj) >= 8
    # version routing: bootstrap client (None) then the replay's version
    assert provider_calls[0] is None
    assert "4.10.0" in provider_calls

    step = traj[0]
    # frozen ReplayDataset step contract
    for key in ("spatial_info", "scalar_info", "entity_info", "entity_num",
                "action_info", "action_mask", "selected_units_num"):
        assert key in step, key
    assert "game_info" not in step
    # teacher-forced labels decoded through reverse_raw_action
    at = int(step["action_info"]["action_type"])
    assert ACT.ACTIONS[at]["name"] in (
        "Build_Hatchery_pt", "Train_Drone_quick", "Attack_unit"
    )
    # delays reconstructed from consecutive action loops
    assert int(step["action_info"]["delay"]) == 30
    # Z targets written into every step's scalar_info
    bo = step["scalar_info"]["beginning_order"]
    hatch_bo = ACT.BEGINNING_ORDER_ACTIONS.index(action_index("Build_Hatchery_pt"))
    assert bo[0] == hatch_bo
    cum = step["scalar_info"]["cumulative_stat"]
    assert cum[ACT.CUMULATIVE_STAT_ACTIONS.index(action_index("Build_Hatchery_pt"))] == 1
    # last-action augmentation threads between steps
    assert int(traj[1]["scalar_info"]["last_action_type"]) == at

    # computer / off-race / too-short gates
    dec2 = ReplayDecoder(
        cfg={"minimum_action_length": 500, "parse_race": "Z"},
        controller_provider=provider,
    )
    assert dec2.run("r.SC2Replay", 0) is None  # too short
    dec3 = ReplayDecoder(
        cfg={"minimum_action_length": 2, "parse_race": "T"},
        controller_provider=provider,
    )
    assert dec3.run("r.SC2Replay", 0) is None  # zerg not in parse_race
    dec.close()
    dec2.close()
    dec3.close()

    # ------------------------------ decoded output feeds the SL dataloader
    root = str(tmp_path / "ds")
    ReplayDataset.save(root, "r_p0", traj)
    ds = ReplayDataset(root)
    dl = SLDataloader(ds, batch_size=2, unroll_len=4)
    batch = next(dl)
    assert batch["spatial_info"]["height_map"].shape == (8, *F.SPATIAL_SIZE)
    assert batch["action_info"]["action_type"].shape == (8,)
    assert batch["new_episodes"].all()


def test_sl_dataloader_pads_short_trajectories(tmp_path):
    """Short-game replays are padded with zeroed action masks, not dropped
    (VERDICT round-1 weak #5)."""
    from distar_tpu.learner.sl_dataloader import make_fake_dataset

    root = str(tmp_path / "short")
    make_fake_dataset(root, n_trajectories=2, steps_per_traj=3)
    dl = SLDataloader(ReplayDataset(root), batch_size=1, unroll_len=8)
    batch = next(dl)
    assert batch["action_info"]["action_type"].shape == (8,)
    # steps 3..7 are pads: every head mask zeroed
    for head, m in batch["action_mask"].items():
        assert m[3:].sum() == 0.0, head
        assert m[:3].sum() > 0.0, head


def test_decode_z_builds_library(server, tmp_path):
    """Z-only decode -> build_z_library -> agent-side ZLibrary sampling."""
    from distar_tpu.lib.z_library import ZLibrary, build_z_library, save_z_library

    server.game.replay_library["r.SC2Replay"] = make_replay()

    def provider(version):
        return RemoteController("127.0.0.1", server.port, timeout_seconds=5)

    dec = ReplayDecoder(cfg={"parse_race": "Z"}, controller_provider=provider)
    episodes = [
        ep for pi in (0, 1) if (ep := dec.decode_z("r.SC2Replay", pi)) is not None
    ]
    dec.close()
    assert len(episodes) == 2
    winner = next(e for e in episodes if e["winloss"] == 1)
    assert winner["mix_race"] == "zerg"
    assert winner["mmr"] == 4800
    hatch_bo = ACT.BEGINNING_ORDER_ACTIONS.index(action_index("Build_Hatchery_pt"))
    assert winner["beginning_order"][0] == hatch_bo

    lib = build_z_library(episodes)  # only the winner survives min_winloss
    path = save_z_library(lib, str(tmp_path / "z.json"))
    zlib = ZLibrary(path)
    target = zlib.sample("KairosJunction", "zerg", winner["born_location"])
    assert target["beginning_order"][0] == hatch_bo


def test_filter_actions_dedups_train_spam(server):
    """A burst of identical train commands collapses to the observed order
    delta (reference FilterActions :70-214)."""
    from distar_tpu.envs.sc2.proto import sc_pb

    f = FilterActions(flag=True)
    # a true train ability: Train_Drone_quick is a zerg MORPH (filtered by
    # unit-type change, not order delta)
    train_gab = gab("Train_Queen_quick")

    def act(loop):
        a = sc_pb.Action()
        a.game_loop = loop
        a.action_raw.unit_command.ability_id = train_gab
        a.action_raw.unit_command.unit_tags.extend([42])
        return a

    def obs_with_orders(n_orders, loop):
        ob = sc_pb.ResponseObservation()
        ob.observation.game_loop = loop
        u = ob.observation.raw_data.units.add()
        u.tag = 42
        for _ in range(n_orders):
            u.orders.add(ability_id=train_gab)
        return ob

    # 5 spammed commands, but only 2 new orders appeared
    burst = [act(100 + i) for i in range(5)] + [act(300)]  # gap closes the burst
    pre = obs_with_orders(1, 50)
    post = obs_with_orders(3, 150)
    cached, out = f.run(pre, pre, post, burst)
    assert len(out) == 2
    assert cached == [burst[-1]]
    # the last command of the burst is always kept
    assert out[-1].game_loop == 104

    # morph bursts count units whose type actually changed
    morph_gab = gab("Train_Drone_quick")
    mburst = []
    for i in range(4):
        a = sc_pb.Action()
        a.game_loop = 700 + i
        a.action_raw.unit_command.ability_id = morph_gab
        a.action_raw.unit_command.unit_tags.extend([42, 43])
        mburst.append(a)

    def obs_types(types, loop):
        ob = sc_pb.ResponseObservation()
        ob.observation.game_loop = loop
        for tag, ut in types.items():
            u = ob.observation.raw_data.units.add()
            u.tag = tag
            u.unit_type = ut
        return ob

    pre_m = obs_types({42: 151, 43: 151}, 650)  # larva
    post_m = obs_types({42: 104, 43: 151}, 750)  # one morphed to drone
    cached_m, out_m = f.run(pre_m, pre_m, post_m, mburst + [act(990)])
    assert len(out_m) == 1

    # research bursts collapse to one
    research_gab = gab("Research_ZerglingMetabolicBoost_quick")
    burst2 = []
    for i in range(4):
        a = sc_pb.Action()
        a.game_loop = 500 + i
        a.action_raw.unit_command.ability_id = research_gab
        a.action_raw.unit_command.unit_tags.extend([42])
        burst2.append(a)
    closer = act(900)
    cached2, out2 = f.run(pre, pre, post, burst2 + [closer])
    assert len(out2) == 1 and out2[0].game_loop == 500


def test_extract_z_spine_and_zergling_rules():
    sx = F.SPATIAL_SIZE[1]
    spine = action_index("Build_SpineCrawler_pt")
    zergling = 322
    hatch = action_index("Build_Hatchery_pt")
    home = 10 * sx + 10
    away = 100 * sx + 100

    def info(at, loc=0):
        return {"action_info": {"action_type": np.asarray(at), "target_location": np.asarray(loc)}}

    stream = (
        [info(hatch, 50)]
        + [info(zergling)] * 12  # spam: only 8 zerglings keep BO credit
        + [info(spine, 11 * sx + 11)]   # near home -> dropped
        + [info(spine, 99 * sx + 99)]   # near enemy -> kept
    )
    bo, cum, bo_len, bo_loc = extract_z(stream, home, away)
    names = [ACT.BEGINNING_ORDER_ACTIONS[i] for i in bo[:bo_len]]
    assert names.count(spine) == 1
    assert names.count(zergling) == 8
    assert names[0] == hatch
    assert bo_loc[0] == 50
    assert cum[ACT.CUMULATIVE_STAT_ACTIONS.index(hatch)] == 1


def test_replay_actor_shards_and_feeds_remote_dataloader(server):
    """ReplayActor decodes a sharded replay list through the fake SC2 server
    and pushes trajectories over the Adapter; RemoteSLDataloader assembles
    learner batches from them (reference replay_actor.py + remote SL mode)."""
    from distar_tpu.comm import Adapter, Coordinator
    from distar_tpu.learner.replay_actor import (
        ReplayActor, RemoteSLDataloader, expand_replay_list,
    )

    # sharding math: 2 tasks x epochs over 4 replays
    paths = [f"r{i}.SC2Replay" for i in range(4)]
    shard0 = expand_replay_list(paths, epochs=2, ntasks=2, proc_id=0)
    shard1 = expand_replay_list(paths, epochs=2, ntasks=2, proc_id=1)
    assert len(shard0) == len(shard1) == 4
    assert sorted(shard0 + shard1) == sorted(paths * 2)

    for p in paths[:2]:
        server.game.replay_library[p] = make_replay()

    co = Coordinator()
    push_adapter = Adapter(coordinator=co)
    pull_adapter = Adapter(coordinator=co)

    def decoder_factory():
        return ReplayDecoder(
            cfg={"minimum_action_length": 2, "parse_race": "Z"},
            controller_provider=lambda v: RemoteController(
                "127.0.0.1", server.port, timeout_seconds=5
            ),
        )

    actor = ReplayActor(
        replays=paths[:2],
        adapter_factory=lambda: push_adapter,
        decoder_factory=decoder_factory,
        num_workers=1,
        ntasks=1, proc_id=0,
    )
    actor.run()
    assert actor.pushed >= 2  # both players of both replays that decoded

    loader = RemoteSLDataloader(pull_adapter, batch_size=2, unroll_len=4,
                                pull_timeout=30.0)
    batch = next(loader)
    assert batch["entity_num"].shape == (2 * 4,)
    assert batch["new_episodes"].tolist() == [True, True]
    assert np.isfinite(batch["entity_num"]).all()


def test_replay_fleet_report(tmp_path):
    """The fleet ops CLI (role of reference replay_actions/benchmark_replay/
    mem_leak_check): sharded decode over ReplayActor with a frames/s +
    RSS-slope report; failures counted, not fatal."""
    from distar_tpu.bin.replay_fleet import _FakeDecoder, process_tree_rss_mb, run_fleet

    for i in range(5):
        (tmp_path / f"r{i}.SC2Replay").touch()
    (tmp_path / "corrupt.SC2Replay").touch()
    report = run_fleet(
        str(tmp_path), workers=3,
        decoder_factory=lambda: _FakeDecoder(steps_per_replay=16),
        rss_interval_s=0.2,
    )
    assert report["replays"] == 6
    assert report["trajectories"] == 10  # 5 good replays x 2 players
    assert report["failed_decodes"] == 2
    assert report["frames"] == 160
    assert report["value"] > 0
    assert report["rss"]["peak_mb"] >= report["rss"]["start_mb"] * 0.5
    assert report["decoder"].startswith("fake")
    assert process_tree_rss_mb() > 10  # this test process alone
    # SLURM-style sharding: two tasks split the list without overlap
    r0 = run_fleet(str(tmp_path), workers=1, ntasks=2, proc_id=0,
                   decoder_factory=lambda: _FakeDecoder(4), rss_interval_s=1.0)
    r1 = run_fleet(str(tmp_path), workers=1, ntasks=2, proc_id=1,
                   decoder_factory=lambda: _FakeDecoder(4), rss_interval_s=1.0)
    assert r0["replays"] + r1["replays"] == 6
