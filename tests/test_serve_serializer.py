"""comm/serializer coverage for serve-plane frames: request/response dict
round-trips (real numpy obs, >1 MiB payloads), every codec magic, and the
truncated/garbage-frame error paths both the framing and the socket helpers
must answer typed (ValueError/ConnectionError, never IndexError or a
multi-GiB allocation)."""
import socket
import struct
import threading

import numpy as np
import pytest

from distar_tpu.comm import serializer


def roundtrip(obj, compress=True):
    return serializer.loads(serializer.dumps(obj, compress=compress))


def assert_tree_equal(a, b):
    assert type(a) is type(b)
    if isinstance(a, dict):
        assert a.keys() == b.keys()
        for k in a:
            assert_tree_equal(a[k], b[k])
    elif isinstance(a, np.ndarray):
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(a, b)
    else:
        assert a == b


def serve_act_request(n=16):
    return {
        "op": "act",
        "session_id": "ladder-bot-1",
        "timeout_s": 0.5,
        "obs": {
            "spatial_info": np.random.default_rng(0).random((n, n), np.float32),
            "entity_info": {"flat": np.arange(64, dtype=np.int32)},
            "entity_num": np.int32(7),
        },
    }


def serve_act_response():
    return {
        "code": 0,
        "outputs": {
            "action": np.asarray(3.5, np.float32),
            "logits": np.linspace(0, 1, 327, dtype=np.float32),
            "model_version": "v3",
        },
    }


@pytest.mark.parametrize("compress", [True, False])
def test_serve_frames_round_trip(compress):
    for obj in (serve_act_request(), serve_act_response()):
        assert_tree_equal(roundtrip(obj, compress=compress), obj)


def test_large_payload_round_trip_over_1mib():
    req = serve_act_request()
    # incompressible >1 MiB observation: exercises the lz/zlib fallback and
    # the 8-byte length framing well past small-buffer paths
    req["obs"]["replay_blob"] = np.random.default_rng(1).integers(
        0, 255, size=2_000_000, dtype=np.uint8
    )
    blob = serializer.dumps(req)
    assert len(blob) > 1 << 20
    assert_tree_equal(serializer.loads(blob), req)
    framed = serializer.frame(blob)
    (n,) = struct.unpack(">Q", framed[:8])
    assert n == len(blob)


def test_socket_helpers_round_trip_serve_frames():
    a, b = socket.socketpair()
    try:
        req = serve_act_request()
        req["obs"]["big"] = np.zeros(300_000, np.float32)
        out = {}

        def rx():
            out["msg"] = serializer.recv_msg(b)

        t = threading.Thread(target=rx)
        t.start()
        serializer.send_msg(a, req)
        t.join(10)
        assert not t.is_alive()
        assert_tree_equal(out["msg"], req)
    finally:
        a.close()
        b.close()


def test_truncated_frame_raises_connection_error():
    a, b = socket.socketpair()
    try:
        blob = serializer.dumps(serve_act_response())
        a.sendall(serializer.frame(blob)[: 8 + len(blob) // 2])  # half a frame
        a.close()  # peer dies mid-frame
        with pytest.raises(ConnectionError):
            serializer.recv_msg(b)
    finally:
        b.close()


def test_garbage_frame_header_rejected_before_allocation():
    # 8 bytes of 0xff = an 18-exabyte "length": must fail typed, not OOM
    def recv_exact(n, _data=[b"\xff" * 8]):
        d, _data[0] = _data[0][:n], _data[0][n:]
        return d

    with pytest.raises(ValueError, match="implausible frame length"):
        serializer.read_frame(recv_exact)


def test_garbage_payload_magic_rejected():
    with pytest.raises(ValueError, match="unknown payload magic"):
        serializer.loads(b"NOPE" + b"junk")


def test_truncated_lz_header_rejected():
    with pytest.raises(ValueError, match="truncated lz payload header"):
        serializer.loads(serializer.MAGIC_LZ + b"\x01\x02")


def test_hostile_lz_decompressed_size_rejected():
    # header claims a decompressed size far beyond lz4's possible expansion
    body = struct.pack("<Q", 1 << 40) + b"\x00" * 16
    with pytest.raises(ValueError, match="implausible decompressed size"):
        serializer.loads(serializer.MAGIC_LZ + body)
