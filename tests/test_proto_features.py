"""ProtoFeatures tests over dummy-proto fixtures (the reference's
dummy_observation test strategy, pysc2/tests/dummy_observation_test.py)."""
import numpy as np
import pytest

from distar_tpu.envs.dummy_obs import (
    build_dummy_game_info,
    build_dummy_obs,
    make_effect,
    make_passenger,
    make_raw_action,
    make_unit,
)
from distar_tpu.envs.features import Effects, ProtoFeatures, compute_battle_score
from distar_tpu.lib import actions as ACT
from distar_tpu.lib import features as F


@pytest.fixture
def feat():
    return ProtoFeatures(build_dummy_game_info())


DRONE = ACT.UNIT_TYPES[10]  # some real game unit id from the vocabulary


def test_transform_obs_shapes_and_schema(feat):
    units = [make_unit(100 + i, DRONE, x=5 + i, y=7) for i in range(5)]
    obs = build_dummy_obs(units=units)
    out = feat.transform_obs(obs)
    assert int(out["entity_num"]) == 5
    for k, dtype in F.SPATIAL_INFO.items():
        if k.startswith("effect_"):
            assert out["spatial_info"][k].shape == (F.EFFECT_LENGTH,)
        else:
            assert out["spatial_info"][k].shape == F.SPATIAL_SIZE, k
    for k in F.ENTITY_INFO:
        assert out["entity_info"][k].shape == (F.MAX_ENTITY_NUM,), k
    for k in F.SCALAR_INFO:
        assert k in out["scalar_info"], k


def test_unit_type_remap_and_y_flip(feat):
    u = make_unit(1, DRONE, x=3, y=10)
    out = feat.transform_obs(build_dummy_obs(units=[u]))
    # unit_type remapped into the dense vocabulary (DRONE is index 10)
    assert int(out["entity_info"]["unit_type"][0]) == 10
    # y flipped: map_y(120) - 10 = 110
    assert int(out["entity_info"]["y"][0]) == 110
    assert int(out["entity_info"]["x"][0]) == 3
    # health ratio
    assert out["entity_info"]["health_ratio"][0] == pytest.approx(0.5, abs=1e-3)


def test_bow_vectors_and_upgrades(feat):
    units = [make_unit(i, DRONE) for i in range(3)] + [
        make_unit(50, ACT.UNIT_TYPES[20], alliance=4)
    ]
    up_id = ACT.UPGRADES[5]
    out = feat.transform_obs(build_dummy_obs(units=units, upgrade_ids=[up_id]))
    assert int(out["scalar_info"]["unit_counts_bow"][10]) == 3
    assert int(out["scalar_info"]["unit_type_bool"][10]) == 1
    assert int(out["scalar_info"]["enemy_unit_type_bool"][20]) == 1
    assert int(out["scalar_info"]["upgrades"][5]) == 1
    # log1p stats
    assert out["scalar_info"]["agent_statistics"][0] == pytest.approx(np.log1p(500))


def test_cargo_passengers_become_entities(feat):
    carrier = make_unit(
        1, DRONE, passengers=[make_passenger(2, ACT.UNIT_TYPES[11])]
    )
    out = feat.transform_obs(build_dummy_obs(units=[carrier]))
    assert int(out["entity_num"]) == 2
    assert int(out["entity_info"]["is_in_cargo"][1]) == 1
    assert out["game_info"]["tags"] == [1, 2]


def test_effect_coordinates_flat_flipped(feat):
    eff = make_effect(Effects.PsiStorm, [(4, 20)])
    out = feat.transform_obs(build_dummy_obs(effects=[eff]))
    expected = 4 + (120 - 20) * F.SPATIAL_SIZE[1]
    assert int(out["spatial_info"]["effect_PsiStorm"][0]) == expected
    # own liberator zones are skipped
    own_zone = make_effect(Effects.LiberatorDefenderZone, [(1, 1)], owner=1)
    out2 = feat.transform_obs(build_dummy_obs(effects=[own_zone]))
    assert int(out2["spatial_info"]["effect_LiberatorDefenderZone"][0]) == 0


def test_battle_score(feat):
    obs = build_dummy_obs(killed_minerals=100.0, killed_vespene=40.0)
    assert compute_battle_score(obs) == pytest.approx(100 + 1.5 * 40)


def test_value_feature_from_opponent(feat):
    my_units = [make_unit(1, DRONE, alliance=1)]
    opp_units = [make_unit(9, ACT.UNIT_TYPES[30], alliance=1, x=50, y=60)]
    obs = build_dummy_obs(units=my_units)
    opp = build_dummy_obs(units=opp_units, player_id=2)
    out = feat.transform_obs(obs, opponent_obs=opp)
    vf = out["value_feature"]
    assert int(vf["total_unit_count"]) == 2  # 1 enemy + 1 own
    assert int(vf["enemy_unit_counts_bow"][30]) == 1
    assert vf["own_units_spatial"].shape == F.SPATIAL_SIZE
    assert int(vf["unit_alliance"][0]) == 1 and int(vf["unit_alliance"][1]) == 0


def test_transform_action_roundtrip(feat):
    tags = [111, 222, 333]
    attack_pt = ACT.FUNC_ID_TO_ACTION_TYPE[2]  # Attack_pt: selects + location
    action = {
        "action_type": np.asarray(attack_pt),
        "delay": np.asarray(3),
        "queued": np.asarray(1),
        "selected_units": np.asarray([0, 2] + [3] * 62),  # 3 == entity_num end
        "target_unit": np.asarray(0),
        "target_location": np.asarray(5 + 10 * F.SPATIAL_SIZE[1]),
    }
    cmd = feat.transform_action(action, tags)
    assert cmd["ability_id"] == ACT.ACTIONS[attack_pt]["general_ability_id"]
    assert cmd["unit_tags"] == [111, 333]
    # post-end-token garbage must not produce commands: fill tail with a
    # valid-looking index
    garbage = dict(action, selected_units=np.asarray([0, 3] + [1] * 62))
    assert feat.transform_action(garbage, tags)["unit_tags"] == [111]
    # explicit selected_units_num wins
    assert feat.transform_action(action, tags, selected_units_num=1)["unit_tags"] == [111]
    assert cmd["queue_command"] is True
    x, y = cmd["target_world_space_pos"]
    assert (x, y) == (5.0, 120.0 - 10.0)


def test_reverse_raw_action(feat):
    tags = [111, 222, 333]
    attack_gab = ACT.ACTIONS[ACT.FUNC_ID_TO_ACTION_TYPE[2]]["general_ability_id"]  # 3674
    raw = make_raw_action(attack_gab, unit_tags=[222, 111], target_pos=(5, 110),
                          queue_command=True)
    out = feat.reverse_raw_action(raw, tags)
    a = out["action"]
    assert int(a["action_type"]) == ACT.FUNC_ID_TO_ACTION_TYPE[2]  # Attack_pt
    # selected: indices then end flag (== entity_num == 3)
    assert a["selected_units"][:3].tolist() == [1, 0, 3]
    assert int(out["selected_units_num"]) == 3
    assert int(a["queued"]) == 1
    assert int(a["target_location"]) == (120 - 110) * F.SPATIAL_SIZE[1] + 5
    assert out["mask"]["target_location"] == 1.0 and out["mask"]["target_unit"] == 0.0
    assert not out["invalid"]


def test_reverse_raw_action_unit_variant(feat):
    """Same general ability with a target unit must decode to the _unit
    variant (cmd-kind disambiguation)."""
    tags = [111, 222, 333]
    attack_gab = 3674
    raw = make_raw_action(attack_gab, unit_tags=[111], target_unit_tag=333)
    out = feat.reverse_raw_action(raw, tags)
    assert int(out["action"]["action_type"]) == ACT.FUNC_ID_TO_ACTION_TYPE[3]  # Attack_unit
    assert int(out["action"]["target_unit"]) == 2
    assert out["mask"]["target_unit"] == 1.0 and out["mask"]["target_location"] == 0.0


def test_reverse_raw_action_cancel_slot_and_clamp(feat):
    tags = [111]
    # cancel-slot ability family remaps to the cancel general (3671)
    out = feat.reverse_raw_action(make_raw_action(313, unit_tags=[111]), tags)
    cancel_action = ACT.GAB_KIND_TO_ACTION[(3671, "quick")]
    assert int(out["action"]["action_type"]) == cancel_action
    assert not out["invalid"]
    # y=0 flips past the map edge; label clamps inside
    attack_gab = 3674
    out2 = feat.reverse_raw_action(
        make_raw_action(attack_gab, unit_tags=[111], target_pos=(5, 0)), tags
    )
    assert int(out2["action"]["target_location"]) == (120 - 1) * F.SPATIAL_SIZE[1] + 5


def test_reverse_raw_action_invalid(feat):
    tags = [111]
    # unknown ability -> masked no_op
    unk = feat.reverse_raw_action(make_raw_action(999999, unit_tags=[111]), tags)
    assert int(unk["action"]["action_type"]) == 0
    assert unk["invalid"] and unk["mask"]["action_type"] == 0.0
    # frivolous (Dance) dropped
    assert feat.reverse_raw_action(make_raw_action(6, unit_tags=[111]), tags)["invalid"]


def test_agent_consumes_proto_obs(feat):
    """The proto transform's output feeds Agent.pre_process unchanged."""
    from distar_tpu.actor.agent import Agent

    out = feat.transform_obs(build_dummy_obs(units=[make_unit(1, DRONE)]))
    ag = Agent("MP0", traj_len=4)
    model_in = ag.pre_process(out)
    assert model_in["scalar_info"]["beginning_order"].shape == (20,)
    assert model_in["entity_num"] == out["entity_num"]


def test_fake_server_exercises_rich_obs_paths():
    """The fake's observations cover the transform paths a real client hits
    constantly (VERDICT r3: keep the fake honest): orders with progress,
    buffs, cargo passengers -> is_in_cargo pseudo-entities, addon tags,
    effects -> scatter planes, researched upgrades."""
    from distar_tpu.envs.sc2.fake_sc2 import FakeGameCore

    game = FakeGameCore(end_at=10_000, map_size=(120, 120), n_units=6)
    game.advance(150)  # past the upgrade/effect thresholds
    gi = game.build_game_info()
    feats = ProtoFeatures(gi)
    obs = game.build_observation(1)

    out = feats.transform_obs(obs, padding_spatial=True)
    ent, n = out["entity_info"], int(out["entity_num"])
    assert n == 2 * 6 + 2  # both sides' units + one passenger per transport
    assert (np.asarray(ent["order_length"])[:n] > 0).any()
    assert (np.asarray(ent["order_progress_0"])[:n] > 0).any()
    assert (np.asarray(ent["order_id_1"])[:n] > 0).any()
    assert (np.asarray(ent["buff_id_0"])[:n] > 0).any()
    assert (np.asarray(ent["is_in_cargo"])[:n] > 0).any()
    assert (np.asarray(ent["addon_unit_type"])[:n] > 0).any()
    assert (np.asarray(out["spatial_info"]["effect_CorrosiveBile"]) > 0).any()
    assert np.asarray(out["scalar_info"]["upgrades"]).sum() >= 2
