import os

import pytest

from distar_tpu.utils import (
    AverageMeter,
    Config,
    EMAMeter,
    EasyTimer,
    VariableRecord,
    deep_merge_dicts,
    read_config,
    save_config,
)


def test_config_attribute_access():
    cfg = Config({"model": {"encoder": {"dim": 256}}, "lst": [{"a": 1}]})
    assert cfg.model.encoder.dim == 256
    assert cfg.lst[0].a == 1
    cfg.model.encoder.dim = 128
    assert cfg["model"]["encoder"]["dim"] == 128


def test_deep_merge_semantics():
    base = Config({"a": {"b": 1, "c": 2}, "d": [1, 2]})
    override = {"a": {"c": 3}, "d": [9]}
    merged = deep_merge_dicts(base, override)
    assert merged.a.b == 1 and merged.a.c == 3
    assert merged.d == [9]
    # base untouched
    assert base.a.c == 2


def test_yaml_roundtrip(tmp_path):
    cfg = Config({"learner": {"lr": 1e-4, "betas": [0.0, 0.99]}})
    p = os.path.join(tmp_path, "cfg.yaml")
    save_config(cfg, p)
    loaded = read_config(p)
    assert loaded.learner.lr == pytest.approx(1e-4)
    assert loaded.learner.betas == [0.0, 0.99]


def test_meters():
    m = AverageMeter(length=3)
    for v in [1, 2, 3, 4]:
        m.update(v)
    assert m.val == 4 and m.avg == pytest.approx(3.0)
    e = EMAMeter(alpha=0.5)
    e.update(0.0)
    e.update(1.0)
    assert e.avg == pytest.approx(0.5)


def test_variable_record():
    rec = VariableRecord(length=10)
    rec.update_var({"loss": 1.0, "acc": 0.5})
    rec.update_var({"loss": 3.0})
    assert rec.get("loss").avg == pytest.approx(2.0)
    assert "loss" in rec.get_vars_text()


def test_timer():
    t = EasyTimer()
    with t:
        pass
    assert t.value >= 0.0
