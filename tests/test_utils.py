import os

import pytest

from distar_tpu.utils import (
    AverageMeter,
    Config,
    EMAMeter,
    EasyTimer,
    VariableRecord,
    deep_merge_dicts,
    read_config,
    save_config,
)


def test_config_attribute_access():
    cfg = Config({"model": {"encoder": {"dim": 256}}, "lst": [{"a": 1}]})
    assert cfg.model.encoder.dim == 256
    assert cfg.lst[0].a == 1
    cfg.model.encoder.dim = 128
    assert cfg["model"]["encoder"]["dim"] == 128


def test_deep_merge_semantics():
    base = Config({"a": {"b": 1, "c": 2}, "d": [1, 2]})
    override = {"a": {"c": 3}, "d": [9]}
    merged = deep_merge_dicts(base, override)
    assert merged.a.b == 1 and merged.a.c == 3
    assert merged.d == [9]
    # base untouched
    assert base.a.c == 2


def test_yaml_roundtrip(tmp_path):
    cfg = Config({"learner": {"lr": 1e-4, "betas": [0.0, 0.99]}})
    p = os.path.join(tmp_path, "cfg.yaml")
    save_config(cfg, p)
    loaded = read_config(p)
    assert loaded.learner.lr == pytest.approx(1e-4)
    assert loaded.learner.betas == [0.0, 0.99]


def test_meters():
    m = AverageMeter(length=3)
    for v in [1, 2, 3, 4]:
        m.update(v)
    assert m.val == 4 and m.avg == pytest.approx(3.0)
    e = EMAMeter(alpha=0.5)
    e.update(0.0)
    e.update(1.0)
    # bias-corrected: weighted mean (alpha*0 + 1*1)/(alpha + 1), not the raw
    # EMA 0.5 (debias semantics, tests/test_obs_metrics.py)
    assert e.avg == pytest.approx(2.0 / 3.0)


def test_variable_record():
    rec = VariableRecord(length=10)
    rec.update_var({"loss": 1.0, "acc": 0.5})
    rec.update_var({"loss": 3.0})
    assert rec.get("loss").avg == pytest.approx(2.0)
    assert "loss" in rec.get_vars_text()


def test_timer():
    t = EasyTimer()
    with t:
        pass
    assert t.value >= 0.0


def test_downloader_resumes_with_range(tmp_path):
    """download_model resumes a partial file via HTTP Range (reference
    distar/bin/download_model.py:24-48)."""
    import http.server
    import threading

    payload = bytes(range(256)) * 40  # 10240 bytes

    class RangeHandler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            start = 0
            rng = self.headers.get("Range")
            if rng:
                start = int(rng.split("=")[1].rstrip("-"))
                self.send_response(206)
            else:
                self.send_response(200)
            body = payload[start:]
            self.send_header("Content-Length", str(len(payload) if not rng else len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), RangeHandler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        from distar_tpu.bin.download_model import Downloader

        out = tmp_path / "model.pth"
        out.write_bytes(payload[:3000])  # partial file on disk
        url = f"http://127.0.0.1:{srv.server_address[1]}/model.pth"
        d = Downloader(url, str(out), timeout=5.0)
        assert d.total_size == len(payload)
        d.download()
        assert out.read_bytes() == payload
    finally:
        srv.shutdown()


def test_downloader_restarts_when_server_ignores_range(tmp_path):
    """A 200 response to a Range request must overwrite, not append."""
    import http.server
    import threading

    payload = b"x" * 5000

    class NoRangeHandler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            self.send_response(200)  # ignores Range entirely
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), NoRangeHandler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        from distar_tpu.bin.download_model import Downloader

        out = tmp_path / "model.pth"
        out.write_bytes(b"y" * 1234)  # stale partial file
        d = Downloader(f"http://127.0.0.1:{srv.server_address[1]}/m", str(out))
        d.download()
        assert out.read_bytes() == payload
    finally:
        srv.shutdown()
