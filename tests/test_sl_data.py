"""SL dataset/dataloader tests + Z library round trip."""
import numpy as np
import pytest

from distar_tpu.learner.sl_dataloader import ReplayDataset, SLDataloader, make_fake_dataset
from distar_tpu.lib.z_library import ZLibrary, build_z_library, save_z_library, z_entry_to_target

from conftest import SMALL_MODEL  # shared tiny model config



def test_dataset_roundtrip(tmp_path):
    ds = make_fake_dataset(str(tmp_path), n_trajectories=2, steps_per_traj=6)
    assert len(ds.paths) == 2
    steps = ds.load(0)
    assert len(steps) == 6
    assert steps[0]["spatial_info"]["height_map"].shape == (152, 160)


def test_sl_dataloader_windows_and_new_episodes(tmp_path):
    ds = make_fake_dataset(str(tmp_path), n_trajectories=3, steps_per_traj=8)
    dl = SLDataloader(ds, batch_size=2, unroll_len=4)
    b1 = next(dl)
    assert b1["new_episodes"].all()  # first windows are fresh
    assert b1["entity_num"].shape == (8,)  # B*T flat
    b2 = next(dl)
    assert not b2["new_episodes"].any()  # second window of same trajectories
    b3 = next(dl)
    assert b3["new_episodes"].all()  # trajectories exhausted -> refilled


def test_sl_learner_trains_from_dataset(tmp_path):
    from distar_tpu.learner import SLLearner

    ds = make_fake_dataset(str(tmp_path / "data"), n_trajectories=2, steps_per_traj=4)
    small = {
        "encoder": {
            "entity": {"layer_num": 1, "hidden_dim": 32, "output_dim": 16, "head_dim": 8},
            "spatial": {"down_channels": [4, 4, 8], "project_dim": 4, "resblock_num": 1, "fc_dim": 16},
            "scatter": {"output_dim": 4},
            "core_lstm": {"hidden_size": 32, "num_layers": 1},
        },
        "policy": {
            "action_type_head": {"res_dim": 16, "res_num": 1, "gate_dim": 32},
            "delay_head": {"decode_dim": 16},
            "queued_head": {"decode_dim": 16},
            "selected_units_head": {"func_dim": 16},
            "target_unit_head": {"func_dim": 16},
            "location_head": {"res_dim": 8, "res_num": 1, "upsample_dims": [4, 4, 1], "map_skip_dim": 8},
        },
        "value": {"res_dim": 8, "res_num": 1},
    }
    learner = SLLearner(
        {
            "common": {"experiment_name": "sl_ds", "save_path": str(tmp_path / "exp")},
            "learner": {"batch_size": 2, "unroll_len": 2, "save_freq": 10 ** 9, "log_freq": 1},
            "model": small,
        }
    )
    learner.set_dataloader(SLDataloader(ReplayDataset(str(tmp_path / "data")), 2, 2))
    learner.run(max_iterations=2)
    assert learner.last_iter.val == 2
    assert np.isfinite(learner.variable_record.get("total_loss").avg)

    # held-out metric pass (tools/sl_curve.py rides this): averaged scalar
    # metrics, no state mutation
    import jax

    before = np.array(jax.tree.leaves(learner.state["params"])[0])
    eval_ds = make_fake_dataset(str(tmp_path / "eval"), n_trajectories=2,
                                steps_per_traj=4, seed=9)
    metrics = learner.evaluate(SLDataloader(eval_ds, 2, 2), max_batches=3)
    assert {"action_type_acc", "total_loss"} <= set(metrics)
    assert all(np.isfinite(v) for v in metrics.values())
    assert 0.0 <= metrics["action_type_acc"] <= 1.0
    after = np.array(jax.tree.leaves(learner.state["params"])[0])
    np.testing.assert_array_equal(before, after)


def test_sl_train_cli_holdout_eval(tmp_path, capsys, monkeypatch):
    """bin/sl_train --eval-data runs the no-grad held-out pass on cadence
    and prints parseable EVAL lines (beyond-reference: the reference tracks
    train metrics only)."""
    import json
    import sys as _sys

    from distar_tpu.bin import sl_train

    make_fake_dataset(str(tmp_path / "tr"), n_trajectories=2, steps_per_traj=6)
    make_fake_dataset(str(tmp_path / "ev"), n_trajectories=2, steps_per_traj=6,
                      seed=5)
    monkeypatch.setattr(_sys, "argv", [
        "sl_train", "--type", "learner",
        "--data", str(tmp_path / "tr"), "--eval-data", str(tmp_path / "ev"),
        "--iters", "2", "--eval-freq", "1", "--eval-batches", "2",
        "--batch-size", "2", "--traj-len", "2",
        "--experiment-name", "sl_cli_eval_test",
    ])
    sl_train.main()
    out = capsys.readouterr().out
    evals = [json.loads(l[5:]) for l in out.splitlines() if l.startswith("EVAL ")]
    assert len(evals) == 2  # freq 1 over 2 iters
    assert {"iter", "action_type_acc", "total_loss"} <= set(evals[0])
    assert "sl_train done" in out


@pytest.mark.slow
def test_sl_learns_from_decoded_replay(tmp_path):
    """SURVEY §7 milestone 4's game-free analogue: two-pass-decode a
    scripted fake-server replay through the production client stack, feed
    the decoded trajectory through ReplayDataset -> SLDataloader ->
    SLLearner, and watch action_type_acc RISE (and the CE loss fall) over a
    few hundred steps. Thresholds calibrated on the observed curve
    (acc 0.20 -> 0.33, loss 311 -> 204 by iter 180)."""
    from test_replay_decoder import make_replay

    from distar_tpu.envs.replay_decoder import ReplayDecoder
    from distar_tpu.envs.sc2.fake_sc2 import FakeGameCore, FakeSC2Server
    from distar_tpu.envs.sc2.remote_controller import RemoteController
    from distar_tpu.learner import SLLearner

    server = FakeSC2Server(game=FakeGameCore(end_at=100_000))
    server.game.replay_library["r.SC2Replay"] = make_replay(n_actions=24)
    dec = ReplayDecoder(
        cfg={"minimum_action_length": 2, "parse_race": "Z"},
        controller_provider=lambda v: RemoteController(
            "127.0.0.1", server.port, timeout_seconds=5
        ),
    )
    try:
        traj = dec.run("r.SC2Replay", player_index=0)
    finally:
        dec.close()
        server.stop()
    assert traj is not None and len(traj) >= 16

    root = str(tmp_path / "decoded")
    ReplayDataset.save(root, "r0", traj)

    small = {
        "encoder": {
            "entity": {"layer_num": 1, "hidden_dim": 32, "output_dim": 16, "head_dim": 8},
            "spatial": {"down_channels": [4, 4, 8], "project_dim": 4, "resblock_num": 1, "fc_dim": 16},
            "scatter": {"output_dim": 4},
            "core_lstm": {"hidden_size": 32, "num_layers": 1},
        },
        "policy": {
            "action_type_head": {"res_dim": 16, "res_num": 1, "gate_dim": 32},
            "delay_head": {"decode_dim": 16},
            "queued_head": {"decode_dim": 16},
            "selected_units_head": {"func_dim": 16},
            "target_unit_head": {"func_dim": 16},
            "location_head": {"res_dim": 8, "res_num": 1, "upsample_dims": [4, 4, 1], "map_skip_dim": 8},
        },
        "value": {"res_dim": 8, "res_num": 1},
    }
    learner = SLLearner(
        {
            "common": {"experiment_name": "sl_e2e", "save_path": str(tmp_path / "exp")},
            "learner": {
                "batch_size": 2, "unroll_len": 4,
                "save_freq": 10 ** 9, "log_freq": 10 ** 9,
                "learning_rate": 3e-4,
            },
            "model": small,
        }
    )
    learner.set_dataloader(SLDataloader(ReplayDataset(root), 2, 4))

    learner.run(max_iterations=30)
    acc_early = learner.variable_record.get("action_type_acc").avg
    loss_early = learner.variable_record.get("total_loss").avg
    learner.run(max_iterations=180)
    acc_late = learner.variable_record.get("action_type_acc").avg
    loss_late = learner.variable_record.get("total_loss").avg

    assert np.isfinite(loss_late)
    assert acc_late >= 0.28, f"action_type_acc did not rise: {acc_early} -> {acc_late}"
    assert acc_late >= acc_early + 0.05, f"no learning signal: {acc_early} -> {acc_late}"
    assert loss_late < 0.85 * loss_early, f"loss did not fall: {loss_early} -> {loss_late}"


def test_z_library_roundtrip(tmp_path):
    eps = [
        {
            "map_name": "KJ", "mix_race": "zvz", "born_location": 22, "winloss": 1,
            "beginning_order": [3, 5, 0, 7], "bo_location": [1, 2, 3, 4],
            "cumulative_stat": [4, 9], "game_loop": 9000,
        },
        {  # loser: excluded
            "map_name": "KJ", "mix_race": "zvz", "born_location": 22, "winloss": -1,
            "beginning_order": [1], "bo_location": [0], "cumulative_stat": [1],
            "game_loop": 100,
        },
    ]
    lib = build_z_library(eps)
    assert len(lib["KJ"]["zvz"]["22"]) == 1
    p = str(tmp_path / "z.json")
    save_z_library(lib, p)
    z = ZLibrary(p).sample("KJ", "zvz", 22)
    assert z["beginning_order"] == [3, 5, 7]  # zeros dropped
    assert z["bo_norm"] == 3 and z["cum_norm"] == 2


def test_z_entry_types():
    entry = [[1, 2], [3], [0, 0], 500, 3]  # z_type 3: both rewards off
    z = z_entry_to_target(entry)
    assert not z["use_bo_reward"] and not z["use_cum_reward"]


def test_cap_entities_exact_below_cap(tmp_path):
    """The pad-to-bucket cap (learner.max_entities) is numerically exact for
    samples within the cap: same data trained with 512 padding and with the
    entity axis sliced to 256 yields the same loss grid (padded rows are
    masked out of every reduction; all model shapes derive from inputs)."""
    import jax

    from distar_tpu.learner import SLLearner
    from distar_tpu.learner.data import cap_entities, fake_sl_batch

    rng = np.random.default_rng(7)
    batch = fake_sl_batch(4, 2, rng=rng)
    # keep every sample within the bucket (end tokens land at entity_num)
    batch["entity_num"] = np.minimum(batch["entity_num"], 250)
    su = batch["action_info"]["selected_units"]
    batch["action_info"]["selected_units"] = np.minimum(
        su, batch["entity_num"][..., None]
    )
    batch["action_info"]["target_unit"] = np.minimum(
        batch["action_info"]["target_unit"],
        np.maximum(batch["entity_num"] - 1, 0),
    )
    batch["new_episodes"] = np.zeros(4, bool)

    cfg = {
        "common": {"experiment_name": "cap", "save_path": str(tmp_path)},
        "learner": {"batch_size": 4, "unroll_len": 2, "save_freq": 100000,
                    "log_freq": 10 ** 9},
        "model": SMALL_MODEL,
    }
    logs = {}
    for name, max_e in (("full", None), ("capped", 256)):
        c = dict(cfg, learner=dict(cfg["learner"], max_entities=max_e),
                 common=dict(cfg["common"], experiment_name=f"cap_{name}"))
        learner = SLLearner(c)
        logs[name] = learner._train(dict(batch))
        if max_e:
            shapes = {k: v.shape for k, v in cap_entities(batch, 256)["entity_info"].items()}
            assert all(s[1] == 256 for s in shapes.values())
    for k in logs["full"]:
        np.testing.assert_allclose(
            logs["full"][k], logs["capped"][k], rtol=2e-4, atol=2e-4,
            err_msg=f"loss term {k} diverged under the entity cap",
        )


def test_cap_entities_masks_out_overflow():
    """Samples ABOVE the cap: entity_num clamps, end tokens remap, and any
    label referencing a dropped entity zeroes that head's mask."""
    from distar_tpu.learner.data import cap_entities, fake_sl_batch

    batch = fake_sl_batch(2, 1, rng=np.random.default_rng(3))
    batch["entity_num"] = np.asarray([300, 100], np.int64)
    su = np.zeros_like(batch["action_info"]["selected_units"])
    su[0, 0] = 280   # dropped under cap 256
    su[0, 1] = 300   # old end token
    su[1, :] = 100   # end token, within cap
    batch["action_info"]["selected_units"] = su
    batch["action_info"]["target_unit"] = np.asarray([280, 5])
    batch["action_mask"]["selected_units"] = np.ones(2, np.float32)
    batch["action_mask"]["target_unit"] = np.ones(2, np.float32)

    out = cap_entities(batch, 256)
    assert list(out["entity_num"]) == [256, 100]
    su2 = out["action_info"]["selected_units"]
    assert su2[0, 0] == 256 and su2[0, 1] == 256  # dropped + end -> new end
    assert (su2[1] == 100).all()                  # untouched below the cap
    assert out["action_mask"]["selected_units"][0] == 0.0  # dropped label
    assert out["action_mask"]["selected_units"][1] == 1.0
    assert out["action_info"]["target_unit"][0] == 0
    assert out["action_mask"]["target_unit"][0] == 0.0
    assert out["action_mask"]["target_unit"][1] == 1.0
    for v in out["entity_info"].values():
        assert v.shape[1] == 256
