"""End-to-end RL pipeline smoke: league -> actor (mock env, batched jitted
inference) -> adapter data plane -> dataloader -> pjit learner step -> weight
publication back to the actor. The whole reference rl_train loop
(SURVEY.md §3.1) in one process on the CPU mesh."""
import numpy as np
import pytest

from distar_tpu.actor import Actor
from distar_tpu.comm import Adapter, Coordinator
from distar_tpu.envs import MockEnv
from distar_tpu.league import League
from distar_tpu.learner import RLLearner
from distar_tpu.learner.rl_dataloader import RLDataLoader, collate_trajectories

SMALL_MODEL = {
    "encoder": {
        "entity": {"layer_num": 1, "hidden_dim": 32, "output_dim": 16, "head_dim": 8},
        "spatial": {"down_channels": [4, 4, 8], "project_dim": 4, "resblock_num": 1, "fc_dim": 16},
        "scatter": {"output_dim": 4},
        "core_lstm": {"hidden_size": 32, "num_layers": 1},
    },
    "policy": {
        "action_type_head": {"res_dim": 16, "res_num": 1, "gate_dim": 32},
        "delay_head": {"decode_dim": 16},
        "queued_head": {"decode_dim": 16},
        "selected_units_head": {"func_dim": 16},
        "target_unit_head": {"func_dim": 16},
        "location_head": {"res_dim": 8, "res_num": 1, "upsample_dims": [4, 4, 1], "map_skip_dim": 8},
    },
    "value": {"res_dim": 8, "res_num": 1},
}

LEAGUE_CFG = {
    "league": {
        # force pfsp so jobs pit MP0 against history (sp with a single main
        # would self-match and skip ELO/payoff, which the test asserts on).
        # sp/eval must be EXPLICIT zeros: deep_merge keeps default weights
        # for keys the override omits, which made this test flaky
        "branch_probs": {"MainPlayer": {"sp": 0.0, "pfsp": 1.0, "eval": 0.0}},
        "active_players": {
            "player_id": ["MP0"],
            "checkpoint_path": ["mp0.ckpt"],
            "pipeline": ["default"],
            "frac_id": [1],
            "z_path": ["3map.json"],
            "z_prob": [0.0],
            "teacher_id": ["T"],
            "teacher_path": ["t.ckpt"],
            "one_phase_step": [10 ** 9],
            "chosen_weight": [1.0],
        },
        "historical_players": {
            "player_id": ["HP0"],
            "checkpoint_path": ["hp0.ckpt"],
            "pipeline": ["default"],
            "frac_id": [1],
            "z_path": ["3map.json"],
            "z_prob": [0.0],
        },
    }
}

TRAJ_LEN = 2
N_ENV = 2


@pytest.mark.slow
def test_full_rl_loop(tmp_path):
    """Actor rollout -> data plane -> RLDataLoader -> pjit learner -> weight
    publication + league train-info, all in one process."""
    league = League(LEAGUE_CFG)
    co = Coordinator()
    actor_adapter = Adapter(coordinator=co)
    learner_adapter = Adapter(coordinator=co)

    actor = Actor(
        cfg={"actor": {"env_num": N_ENV, "traj_len": TRAJ_LEN, "seed": 3}},
        league=league,
        adapter=actor_adapter,
        model_cfg=SMALL_MODEL,
        env_fn=lambda: MockEnv(episode_game_loops=300, seed=1),
    )
    dataloader = RLDataLoader(learner_adapter, "MP0", batch_size=4)
    results = actor.run_job(episodes=2)
    assert len(results) >= 2
    # league ingested results (pfsp branch guarantees a real opponent)
    assert league.all_players["MP0"].total_game_count >= 1
    assert league.elo.game_count >= 1

    # the streaming dataloader collates trajectories from the plane
    batch = next(iter(dataloader))
    assert batch["action_info"]["action_type"].shape == (TRAJ_LEN, 4)
    assert batch["spatial_info"]["height_map"].shape[0] == TRAJ_LEN + 1
    assert batch["mask"]["selected_units_mask"].shape == (TRAJ_LEN, 4, 64)
    assert np.isfinite(batch["behaviour_logp"]["action_type"]).all()

    learner = RLLearner(
        {
            "common": {"experiment_name": "e2e", "save_path": str(tmp_path)},
            "learner": {"batch_size": 4, "unroll_len": TRAJ_LEN, "save_freq": 10 ** 9,
                        "log_freq": 1},
            "model": SMALL_MODEL,
        }
    )
    learner.attach_comm(
        learner_adapter, "MP0", league=league, send_model_freq=1, send_train_info_freq=1
    )
    learner.set_dataloader(iter(lambda: batch, None))  # replay the collated batch
    learner.run(max_iterations=2)
    assert learner.last_iter.val == 2
    assert np.isfinite(learner.variable_record.get("total_loss").avg)
    # league saw train info
    assert league.active_players["MP0"].total_agent_step > 0
    # published weights are pullable (actor-side refresh path); the plane is
    # FIFO so drain to the freshest publication
    latest = -1
    while True:
        pub = actor_adapter.pull("MP0model", block=False)
        if pub is None:
            break
        assert "params" in pub
        latest = max(latest, pub["iter"])
    assert latest >= 1


def test_value_feature_flows_through_trajectory(tmp_path):
    """Actor-side value_feature (centralized critic) reaches the collated
    learner batch with [T+1, B, ...] layout."""
    from distar_tpu.actor.agent import Agent, sample_fake_z
    from distar_tpu.envs import MockEnv
    from distar_tpu.lib import features as F
    import jax

    env = MockEnv(episode_game_loops=50, seed=0, include_value_feature=True)
    obs = env.reset()
    ag = Agent("MP0", z=sample_fake_z(), traj_len=2)
    fake_out = {
        "action_info": F.fake_action_info(),
        "action_logp": F.fake_action_logp(),
        "selected_units_num": np.asarray(1),
        "logit": F.fake_action_logits(),
    }
    hidden = tuple((np.zeros(8, np.float32), np.zeros(8, np.float32)) for _ in range(1))
    teacher = F.fake_action_logits()
    trajs = []
    for _ in range(2):
        traj = None
        while traj is None:
            ag.pre_process(obs[0])
            ag.post_process(fake_out)
            next_obs, rewards, done, info = env.step({0: fake_out["action_info"], 1: fake_out["action_info"]})
            traj = ag.collect_data(next_obs[0], rewards[0], done, teacher, hidden)
            obs = next_obs
        trajs.append(traj)
    batch = collate_trajectories(trajs)
    assert "value_feature" in batch
    vf = batch["value_feature"]
    assert vf["own_units_spatial"].shape == (TRAJ_LEN + 1, 2, 152, 160)
    assert vf["enemy_agent_statistics"].shape == (TRAJ_LEN + 1, 2, 10)
    # behaviour Z merged in for the critic
    assert vf["beginning_order"].shape == (TRAJ_LEN + 1, 2, 20)


@pytest.mark.slow
def test_one_sided_eval_vs_bot():
    """play.py's agent_vs_bot shape: a single model-driven side over a
    1-agent env (the built-in bot lives inside the game), pinned matchup via
    the explicit job override — no league, no data push."""
    from distar_tpu.envs.dummy_obs import build_dummy_game_info
    from distar_tpu.envs.features import ProtoFeatures
    from distar_tpu.envs.sc2_env import FakeController, SC2Env

    gi = build_dummy_game_info()

    def env_fn():
        return SC2Env(
            [FakeController(player_id=1, end_at=40, winner_player=1)],
            [ProtoFeatures(gi)],
        )

    actor = Actor(
        cfg={"actor": {"env_num": 1, "traj_len": 10 ** 9, "seed": 5}},
        model_cfg=SMALL_MODEL,
        env_fn=env_fn,
    )
    job = {
        "player_ids": ["model1"],
        "send_data_players": [],
        "update_players": [],
        "teacher_player_ids": ["none"],
        "branch": "eval_test",
        "env_info": {"map_name": "fake"},
        "opponent_id": "bot10",
    }
    results = actor.run_job(episodes=2, job=job)
    assert len(results) >= 2
    for r in results:
        assert r["0"]["winloss"] == 1  # the fake game declares player 1 winner
        assert r["0"]["opponent_id"] == "bot10"
        assert "1" not in r


@pytest.mark.slow
def test_remote_roles_over_http(tmp_path):
    """League + coordinator as HTTP servers; actor and learner connect via
    RemoteLeague/Adapter addresses (the multi-host role path)."""
    from distar_tpu.comm import CoordinatorServer
    from distar_tpu.league import LeagueAPIServer
    from distar_tpu.league.remote import RemoteLeague

    league_server = LeagueAPIServer(League(LEAGUE_CFG))
    league_server.start()
    co_server = CoordinatorServer()
    co_server.start()
    try:
        remote = RemoteLeague(league_server.host, league_server.port)
        info = remote.register_learner("MP0", rank=0, world_size=1)
        assert info["checkpoint_path"] == "mp0.ckpt"

        actor = Actor(
            cfg={"actor": {"env_num": 1, "traj_len": TRAJ_LEN, "seed": 9}},
            league=remote,
            adapter=Adapter(coordinator_addr=(co_server.host, co_server.port)),
            model_cfg=SMALL_MODEL,
            env_fn=lambda: MockEnv(episode_game_loops=120, seed=4),
        )
        actor.run_job(episodes=1)

        learner_adapter = Adapter(coordinator_addr=(co_server.host, co_server.port))
        traj = learner_adapter.pull("MP0traj", timeout=30)
        assert len(traj) == TRAJ_LEN + 1
        reply = remote.learner_send_train_info("MP0", train_steps=10)
        assert isinstance(reply, dict)
    finally:
        league_server.stop()
        co_server.stop()


@pytest.mark.slow
def test_scripted_vs_model_job():
    """A scripted pipeline (no model, no inference slot, no trajectories)
    plays side 1 against the model-driven side 0 on the mock env (role of the
    reference's scripted demo agents, pysc2/agents/)."""
    from distar_tpu.actor.scripted import RandomAgent, build_scripted, is_scripted

    assert is_scripted("scripted.random") and is_scripted("scripted.idle")
    assert isinstance(build_scripted("scripted.random", "X"), RandomAgent)

    actor = Actor(
        cfg={"actor": {"env_num": 2, "traj_len": 2, "seed": 5}},
        model_cfg=SMALL_MODEL,
        env_fn=lambda: MockEnv(episode_game_loops=300, seed=2),
    )
    job = {
        "player_ids": ["MP0", "BOT"],
        "pipelines": ["default", "scripted.random"],
        "send_data_players": [],
        "update_players": [],
        "teacher_player_ids": ["T", "none"],
        "branch": "eval_test",
        "env_info": {"map_name": "mock"},
    }
    results = actor.run_job(episodes=2, job=job)
    assert len(results) >= 2
    for r in results:
        assert r["0"]["player_id"] == "MP0"
        assert r["1"]["player_id"] == "BOT"
        assert r["1"]["bo_reward_total"] == 0.0


def test_scripted_agents_emit_valid_actions():
    """Every scripted action respects the per-head ACTIONS masks and the
    fixed feature shapes."""
    from distar_tpu.actor.scripted import IdleAgent, RandomAgent
    from distar_tpu.lib import features as F
    from distar_tpu.lib.actions import (
        SELECTED_UNITS_MASK, TARGET_LOCATION_MASK, TARGET_UNIT_MASK,
    )

    rng = np.random.default_rng(0)
    obs = F.fake_step_data(train=False, rng=rng)
    for agent in (RandomAgent("r", seed=1, noop_prob=0.1), IdleAgent("i")):
        agent.reset()
        for _ in range(50):
            a = agent.step(obs)
            at = a["action_type"]
            assert 0 <= at < len(SELECTED_UNITS_MASK)
            assert 0 <= a["delay"] <= F.MAX_DELAY
            assert a["selected_units"].shape == (F.MAX_SELECTED_UNITS_NUM,)
            n = int(np.asarray(obs["entity_num"]))
            if a["selected_units_num"]:
                assert SELECTED_UNITS_MASK[at]
                sel = a["selected_units"][: a["selected_units_num"]]
                assert (sel < n).all() and len(set(sel.tolist())) == len(sel)
            if a["target_unit"]:
                assert TARGET_UNIT_MASK[at]
                assert a["target_unit"] < n
            if a["target_location"]:
                assert TARGET_LOCATION_MASK[at]
                assert a["target_location"] < F.SPATIAL_SIZE[0] * F.SPATIAL_SIZE[1]
