"""gen_z CLI end-to-end: --replays through the fake SC2 server (the
DISTAR_SC2_PORT external-endpoint path), --input aggregation, --demo.
(The library/decoder internals are covered in test_replay_decoder.py;
this drives the operator-facing entry, reference distar/bin/gen_z.py.)"""
import json
import pickle

import pytest

from distar_tpu.envs.sc2.fake_sc2 import FakeGameCore, FakeSC2Server
from distar_tpu.lib.z_library import ZLibrary

from test_replay_decoder import make_replay


@pytest.fixture
def server():
    s = FakeSC2Server(game=FakeGameCore(end_at=100_000))
    yield s
    s.stop()


def test_gen_z_replays_via_fake_endpoint(server, tmp_path, monkeypatch):
    replays = tmp_path / "replays"
    replays.mkdir()
    (replays / "r.SC2Replay").write_bytes(pickle.dumps(make_replay()))

    out = str(tmp_path / "z.json")
    monkeypatch.setenv("DISTAR_SC2_PORT", str(server.port))
    from distar_tpu.bin.gen_z import main

    main(["--replays", str(replays), "--output", out, "--min-mmr", "0"])

    zlib = ZLibrary(out)
    target = zlib.sample_any("KairosJunction", mix_race="zerg")
    assert target is not None
    assert len(target["beginning_order"]) > 0


def test_gen_z_input_jsonl(tmp_path):
    from distar_tpu.bin.gen_z import main

    episodes = [
        {
            "map_name": "KairosJunction", "mix_race": "zerg", "born_location": 1,
            "beginning_order": [3, 5], "bo_location": [100, 200],
            "cumulative_stat": [0, 2], "winloss": 1, "mmr": 5000,
        },
        {   # loser: dropped by min_winloss
            "map_name": "KairosJunction", "mix_race": "zerg", "born_location": 2,
            "beginning_order": [4], "bo_location": [150],
            "cumulative_stat": [1], "winloss": -1, "mmr": 4900,
        },
    ]
    src = tmp_path / "eps.jsonl"
    src.write_text("\n".join(json.dumps(e) for e in episodes) + "\n")
    out = str(tmp_path / "z.json")
    main(["--input", str(src), "--output", out])

    zlib = ZLibrary(out)
    target = zlib.sample("KairosJunction", "zerg", 1)
    assert target["beginning_order"][0] == 3


def test_gen_z_demo(tmp_path):
    from distar_tpu.bin.gen_z import main

    out = str(tmp_path / "demo_z.json")
    main(["--demo", "--output", out])
    raw = json.loads(open(out).read())
    assert raw  # non-empty library loads through the agent-side reader
    ZLibrary(out)
